"""Benchmark: text-SFT training throughput on the available chip(s).

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.
Metric: training tokens/sec/chip on a Qwen3-0.6B-class dense model (largest
of the family that fits a single v5e chip with full AdamW state); MFU is
reported alongside. vs_baseline is measured MFU / 40.0 (BASELINE.json north
star: >= 40% MFU for text SFT on TPU; no published TPU numbers exist).

``run_bench()`` is importable so scripts/mfu_sweep.py can ladder over
micro-batch size / attention impl / remat policy in one process.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def _bench_out_dir() -> str:
    """Where this bench process's diagnosis artifacts live (post-mortems,
    per-rank heartbeats): BENCH_OUT, or a per-PID tempdir (rank is 0 for
    every bench, so a shared /tmp would collide two concurrent benches).
    Heartbeats must be written DURING the run (a stall diagnosis needs the
    beats from before the stall), so the dir exists on healthy runs too —
    :func:`_cleanup_default_out` reaps it at a clean exit when it holds
    nothing but heartbeats (a post-mortem is evidence and is kept)."""
    import tempfile

    return os.environ.get("BENCH_OUT") or os.path.join(
        tempfile.gettempdir(), f"veomni-bench-pm-{os.getpid()}"
    )


def _cleanup_default_out() -> None:
    """Reap the per-PID default artifact dir at a CLEAN exit: a healthy
    bench must not leak one /tmp dir per invocation. Only heartbeat files
    are removed, and only when BENCH_OUT is unset (an operator-chosen dir
    is theirs) and nothing else — a post-mortem, a stall artifact — lives
    there. Never raises."""
    if os.environ.get("BENCH_OUT"):
        return
    d = _bench_out_dir()
    try:
        names = os.listdir(d)
    except OSError:
        return
    try:
        from veomni_tpu.observability.fleet import HEARTBEAT_RE

        if all(HEARTBEAT_RE.match(n) for n in names):
            for n in names:
                os.unlink(os.path.join(d, n))
            os.rmdir(d)
    except Exception:
        pass


_BEAT_MIN_INTERVAL_S = 1.0
_LAST_BEAT = {"t": 0.0, "phase": ""}


def _beat(global_step: int = 0, phase: str = "init",
          step_time_s: float = 0.0) -> None:
    """Progress heartbeat (observability/fleet.py): an atomic rewrite of
    heartbeat-<rank>.json recording the last phase/step that made progress.
    When the relay wedges (BENCH_r01–r05: 0 tok/s, no artifact), the stall
    JSON's heartbeat ages say exactly WHERE progress stopped — init, first
    compile, or step N — instead of silence. Same-phase beats are throttled
    to one per second: the per-step call sits inside the bench's TIMED
    window, and an unthrottled write per step (milliseconds each on a
    network filesystem) would deflate the very tokens/sec the bench exists
    to measure — stall diagnosis only needs watchdog-timeout freshness.
    Never raises."""
    now = time.monotonic()
    if phase == _LAST_BEAT["phase"] and \
            now - _LAST_BEAT["t"] < _BEAT_MIN_INTERVAL_S:
        return
    _LAST_BEAT["t"], _LAST_BEAT["phase"] = now, phase
    try:
        from veomni_tpu.observability.fleet import write_heartbeat

        write_heartbeat(_bench_out_dir(), global_step=global_step,
                        phase=phase, step_time_s=step_time_s)
    except Exception:
        pass


def _start_watchdog(timeout_s: float, metric: str = "train_tokens_per_sec_per_chip"):
    """The axon TPU tunnel can wedge its chip claim (a killed process leaves
    the grant held), after which backend init hangs indefinitely. If the
    bench can't produce a measurement in time, emit an honest zero-valued
    record — now including the thread-stack dump showing WHERE it wedged and
    the flight-recorder post-mortem path showing WHAT it was doing — instead
    of hanging the driver (see BENCH_NOTES.md). ``metric`` keeps the zero
    record in the right bench series (train vs serve). Uses the shared
    ``utils.helper.Watchdog`` (same stall detector as the train-loop
    supervisor); caller must ``.stop()`` it before printing the real record
    so the dog never races a measurement out of a block-buffered stdout via
    its os._exit."""
    from veomni_tpu.observability.flight_recorder import (
        configure_flight_recorder,
    )
    from veomni_tpu.utils.helper import Watchdog

    # the bench has no output_dir; without this the dog's post-mortem falls
    # back to the launch CWD (which may be read-only). Default is a
    # per-PROCESS dir (see _bench_out_dir), created lazily by the dump
    # itself so the common no-stall run leaks nothing. The stall JSON below
    # records the exact path either way.
    configure_flight_recorder(dump_dir=_bench_out_dir())

    def on_stall(stack_dump: str):
        # per-rank heartbeat freshness (observability/fleet.py): the beats
        # run_bench/_serve_main drop at each phase/step say where progress
        # stopped — the diagnosis artifact five wedged-relay rounds lacked
        try:
            from veomni_tpu.observability.fleet import heartbeat_ages

            beats = heartbeat_ages(_bench_out_dir(),
                                   stale_after_s=float(timeout_s))
        except Exception:
            beats = []
        print(json.dumps({
            "metric": metric,
            "value": 0,
            "unit": f"tokens/s — no measurement within {int(timeout_s)}s "
                    "(TPU init or run stalled); last good numbers in BENCH_NOTES.md",
            "vs_baseline": 0,
            "watchdog_stack_dump": stack_dump,
            # the dog wrote postmortem-<rank>.json (event ring + metrics +
            # stacks) just before invoking this callback; wd is late-bound
            # and the dog can only fire timeout_s after it is assigned
            "postmortem": wd.last_postmortem_path,
            # heartbeat age + last-progress step/phase per rank: WHICH rank
            # stopped making progress, and at what point
            "heartbeats": beats,
            "last_progress_step": max(
                (b.get("global_step", 0) for b in beats), default=0
            ),
        }), flush=True)

    wd = Watchdog(
        timeout_s, on_stall=on_stall, exit_code=3, description=f"bench ({metric})"
    ).start()
    return wd


def _pctl(vals, q):
    """Percentile over a possibly-empty list (0.0 when empty) — shared by
    the closed-loop and open-loop serve benches."""
    return float(np.percentile(np.asarray(vals), q)) if vals else 0.0


BENCH_PRESETS = {
    # headline metric (largest of the family that fits one v5e with FULL
    # f32 AdamW state)
    "qwen3_0p6b": dict(hidden_size=1024, intermediate_size=3072,
                       num_hidden_layers=28, param_dtype="float32"),
    # MXU-representative point: hidden-2048 matmuls; fits one v5e only with
    # a momentum-only optimizer (Muon) — bf16 params 3.4G + bf16 momentum
    # 3.4G vs AdamW's 9.6G f32 state
    "qwen3_1p7b": dict(hidden_size=2048, intermediate_size=6144,
                       num_hidden_layers=28, param_dtype="bfloat16"),
    # CPU-runnable smoke point (JAX_PLATFORMS=cpu BENCH_SERVE=1 ...): the
    # serve bench's engine/cache accounting is host-side, so prefix-cache
    # hit rates and prefill-step counts measured here transfer to the real
    # presets — only the kernel timings don't
    "qwen3_smoke": dict(hidden_size=256, intermediate_size=512,
                        num_hidden_layers=2, param_dtype="float32"),
}


def bench_config(remat_policy: str = "dots", preset: str = "qwen3_0p6b"):
    import jax.numpy as jnp

    from veomni_tpu.models import TransformerConfig

    dims = dict(BENCH_PRESETS[preset])
    return TransformerConfig(
        model_type="qwen3",
        vocab_size=151936,
        num_attention_heads=16,
        num_key_value_heads=8,
        head_dim=128,
        qk_norm=True,
        tie_word_embeddings=True,
        max_position_embeddings=131072,
        rope_theta=1e6,
        dtype=jnp.bfloat16,
        param_dtype=getattr(jnp, dims.pop("param_dtype")),
        remat_policy=remat_policy,
        **dims,
    )


def _wait_for_backend(retry_s: float = 120.0):
    """The axon relay intermittently refuses the chip claim with
    ``UNAVAILABLE: TPU backend setup/compile error`` (observed for hours at
    a stretch, round-4 notes). Init is cheap to retry and the watchdog
    bounds total time — keep knocking instead of dying on the first
    refusal."""
    import jax

    attempt = 0
    while True:
        try:
            return jax.device_count()
        except RuntimeError as e:
            if "UNAVAILABLE" not in str(e) and "Unable to initialize" not in str(e):
                raise
            attempt += 1
            print(f"# backend init refused (attempt {attempt}): retrying "
                  f"in {int(retry_s)}s", file=sys.stderr, flush=True)
            # jax caches the failed init (error dict + backend map); clear
            # so the next call re-attempts the claim
            try:
                from jax._src import xla_bridge

                with xla_bridge._backend_lock:
                    xla_bridge._backends.clear()
                    xla_bridge._backend_errors.clear()
                    xla_bridge._default_backend = None
            except Exception:
                pass
            time.sleep(retry_s)


# previous integrity-metric readings: each bench record reports the DELTA
# since the last run_bench call (mfu_sweep.py ladders many configs in one
# process — absolute registry values would re-report the first config's
# restore traffic in every later record)
_INTEGRITY_SNAP = {"verify_s": 0.0, "quarantined": 0, "fallbacks": 0}

# previous per-bucket train-step compile times, same delta discipline (a
# sweep re-compiles the same shape bucket per config; the cumulative census
# figure would misattribute earlier configs' compiles to this record)
_CENSUS_SNAP = {}

# drift gate between the analytic FlopsCounter (the MFU denominator) and
# what XLA actually compiled: outside this band the offline MFU number is
# suspect (count_flops.py rotted behind a model change, or XLA compiled
# something structurally different from what the formula assumes). The band
# is sized to catch layer/vocab/doubling-class rot, not to demand equality:
# healthy ratios sit ~0.65-1.0 because the XLA census counts work the
# analytic convention deliberately omits — full masked causal scores (the
# formula credits seq/2), softmax/CE/norm elementwise, tied-embedding
# backward scatters.
FLOPS_RATIO_BAND = (0.6, 1.4)


def census_bench_fields(analytic_flops_per_step: float,
                        census=None, warn=True) -> dict:
    """Per-bucket XLA cost-census readout for the train-step site.

    ``compile_time_s`` is the per-bucket DELTA since the previous
    ``run_bench`` (sweep-proof); ``xla_flops_per_step`` is the latest
    train-step program's whole-mesh FLOPs (census FLOPs are per device);
    ``analytic_vs_xla_flops_ratio`` is the sanity field — a warning fires
    outside ``FLOPS_RATIO_BAND`` so the MFU denominator can no longer
    silently rot as models change. Never raises: a census-blind run (env
    kill switch, analysis-less backend) reports zeros."""
    out = {"compile_time_s": {}, "xla_flops_per_step": 0.0,
           "analytic_vs_xla_flops_ratio": 0.0}
    try:
        if census is None:
            from veomni_tpu.observability.cost import get_cost_census

            census = get_cost_census()
        for rec in census.programs("train_step"):
            prev = _CENSUS_SNAP.get(rec.bucket, 0.0)
            delta = rec.compile_time_s - prev
            _CENSUS_SNAP[rec.bucket] = rec.compile_time_s
            if delta > 0:
                out["compile_time_s"][rec.bucket] = round(delta, 4)
        rec = census.latest("train_step")
        if rec is not None and rec.flops:
            out["xla_flops_per_step"] = rec.flops * rec.num_devices
            ratio = analytic_flops_per_step / out["xla_flops_per_step"]
            out["analytic_vs_xla_flops_ratio"] = round(ratio, 4)
            lo, hi = FLOPS_RATIO_BAND
            if warn and not (lo <= ratio <= hi):
                print(
                    f"# WARNING: analytic FlopsCounter is {ratio:.3f}x the "
                    f"XLA cost census (band {lo}-{hi}): the reported MFU's "
                    "denominator disagrees with what XLA compiled — "
                    "utils/count_flops.py may have rotted behind a model "
                    "change", file=sys.stderr, flush=True,
                )
    except Exception as e:
        print(f"# cost census unavailable for bench record: {e}",
              file=sys.stderr, flush=True)
    return out


def _integrity_delta() -> dict:
    from veomni_tpu.observability.metrics import get_registry

    reg = get_registry()
    cur = {
        "verify_s": reg.histogram_sum("integrity.verify_s"),
        "quarantined": int(reg.counter("integrity.ckpt_quarantined").value),
        "fallbacks": int(reg.counter("integrity.ckpt_fallbacks").value),
    }
    delta = {k: cur[k] - _INTEGRITY_SNAP[k] for k in cur}
    _INTEGRITY_SNAP.update(cur)
    return delta


def run_bench(
    seq_len: int,
    micro_bs: int,
    steps: int,
    *,
    attention_impl: str = None,
    remat_policy: str = "dots",
    donate: bool = True,
    preset: str = "qwen3_0p6b",
    optimizer: str = "adamw",
    ulysses_size: int = 1,
    ulysses_async: bool = False,
    ulysses_async_chunks: int = 4,
) -> dict:
    """One full train-throughput measurement; returns {tok_s_chip, mfu, dt}."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from veomni_tpu.models import build_foundation_model
    from veomni_tpu.optim import build_lr_scheduler, build_optimizer
    from veomni_tpu.parallel import init_parallel_state, use_parallel_state
    from veomni_tpu.train import build_train_state, build_train_step
    from veomni_tpu.train.train_step import resolve_state_shardings
    from veomni_tpu.utils.count_flops import FlopsCounter
    from veomni_tpu.utils.device import get_device_peak_flops

    os.environ["VEOMNI_DONATE_STATE"] = "1" if donate else "0"
    pins = {}
    if attention_impl:
        pins["attention"] = attention_impl
    if ulysses_async:
        # chunked a2a/compute overlap pipeline (parallel/async_ulysses.py)
        pins["ulysses"] = "ulysses_async"
        os.environ["VEOMNI_ULYSSES_ASYNC_CHUNKS"] = str(ulysses_async_chunks)

    # first beat BEFORE the chip claim: a wedge inside _wait_for_backend
    # (the historical relay failure) must read as "stuck at init", not as
    # an empty heartbeat list
    _beat(phase="init")
    n_chips = _wait_for_backend()
    _beat(phase="backend")  # progress marker: chip claim succeeded
    ps = init_parallel_state(ulysses_size=ulysses_size)

    with use_parallel_state(ps):
        cfg = bench_config(remat_policy, preset)
        # pins ride through the builder: build_foundation_model runs
        # apply_ops_config itself, and a bare call would WIPE pins applied
        # beforehand (clear_pins precedes re-pinning)
        model = build_foundation_model(config=cfg, ops_implementation=pins or None)
        plan = model.get_parallel_plan()
        opt = build_optimizer(
            model.abstract(), optimizer=optimizer,
            lr=build_lr_scheduler(lr=1e-4, train_steps=1000),
        )

        def make_state(rng):
            return build_train_state(model.family.init_params(rng, cfg), opt)

        abs_state = jax.eval_shape(make_state, jax.random.PRNGKey(0))
        shardings = resolve_state_shardings(abs_state, plan, ps)
        state = jax.jit(make_state, out_shardings=shardings)(jax.random.PRNGKey(0))

        keys = ("input_ids", "labels", "position_ids", "segment_ids")
        batch_shardings = {
            k: NamedSharding(ps.mesh, P(None, ps.dp_axes, ps.sp_axes)) for k in keys
        }
        step = build_train_step(
            model.loss_fn, opt, ps,
            state_shardings=shardings, batch_shardings=batch_shardings,
        )

        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (1, micro_bs, seq_len))
        batch = {
            "input_ids": jnp.asarray(ids, jnp.int32),
            "labels": jnp.asarray(ids, jnp.int32),
            "position_ids": jnp.asarray(
                np.broadcast_to(np.arange(seq_len), ids.shape).copy(), jnp.int32
            ),
            "segment_ids": jnp.ones(ids.shape, jnp.int32),
        }
        batch = {k: jax.device_put(v, batch_shardings[k]) for k, v in batch.items()}

        # warmup (compile); NOTE: on the axon-tunneled TPU platform
        # block_until_ready has not always waited for remote execution — a
        # host fetch (float()) is the only guaranteed synchronization point.
        state, metrics = step(state, batch)
        _ = float(metrics["loss"])
        _beat(phase="compile")  # progress marker: warmup compile + fetch ran

        # utilization accounting for the timed window: goodput split from
        # host spans + recompile count from the train-step trace counter
        # (a steady-state retrace inside the window voids the measurement)
        from veomni_tpu.observability.goodput import GoodputTracker
        from veomni_tpu.observability.spans import enable_spans, span
        from veomni_tpu.train import train_step as train_step_mod

        enable_spans()
        tracker = GoodputTracker()
        traces0 = train_step_mod.TRACE_COUNTS["train_step"]
        tracker.begin_window()
        t0 = time.perf_counter()
        for i in range(steps):
            with span("step.dispatch"):
                state, metrics = step(state, batch)
            # last-progress marker for the stall JSON: dispatch is async, so
            # this says the HOST kept feeding the device up to step i+1 (the
            # sync fetch below is where a wedged device surfaces)
            _beat(global_step=i + 1, phase="step")
        with span("sync.fetch"):
            _ = float(metrics["loss"])
        dt = time.perf_counter() - t0
        _beat(global_step=steps, phase="done", step_time_s=dt / max(1, steps))
        gp = tracker.end_window()
        recompiles = train_step_mod.TRACE_COUNTS["train_step"] - traces0

        # integrity trajectory: restore-verification time + quarantine and
        # fallback counts for whatever checkpoint traffic this process did
        # (zero for the pure-throughput path; scripts driving resume flows
        # through run_bench see the real numbers)
        _integ = _integrity_delta()
        restore_verify_s = _integ["verify_s"]
        ckpt_quarantined = _integ["quarantined"]
        ckpt_fallbacks = _integ["fallbacks"]

        # numerics-tier cost (observability/numerics.py): time the
        # instrumented sibling step on the same batch and report its
        # per-step overhead over the hot step — the continuously-measured
        # price of a train.observability_numerics_interval step. Never
        # fatal: a failure reports 0.0 and the bench line says why.
        numerics_overhead_frac = 0.0
        try:
            if os.environ.get("BENCH_NUMERICS", "1") in ("0", ""):
                raise RuntimeError("disabled via BENCH_NUMERICS=0")
            from veomni_tpu.observability.numerics import NumericsSpec

            num_step = build_train_step(
                model.loss_fn, opt, ps,
                state_shardings=shardings, batch_shardings=batch_shardings,
                numerics_spec=NumericsSpec(),
            )
            # warmup compile, then a short timed loop (the sibling never
            # donates, so `state` stays live for the delete below)
            _s, _m, _h = num_step(state, batch)
            _ = float(_m["loss"])
            n_num = max(2, min(8, steps))
            tn0 = time.perf_counter()
            for _ in range(n_num):
                _s, _m, _h = num_step(state, batch)
            _ = float(_m["loss"])
            t_num = (time.perf_counter() - tn0) / n_num
            t_plain = dt / max(1, steps)
            numerics_overhead_frac = max(0.0, t_num / t_plain - 1.0)
            del _s, _m, _h
        except Exception as e:
            print(f"# numerics overhead probe unavailable: {e}",
                  file=sys.stderr, flush=True)

        tokens = micro_bs * seq_len * steps
        tok_per_sec_chip = tokens / dt / n_chips
        analytic_per_step = FlopsCounter.from_config(cfg).batch_flops(
            micro_bs * seq_len, seq_len
        )
        flops = analytic_per_step * steps
        mfu = 100.0 * flops / dt / (get_device_peak_flops() * n_chips)
        # XLA cost-census cross-check (observability/cost.py): per-bucket
        # compile time + compiled-program FLOPs, and the drift gate between
        # the analytic formula above and what XLA actually built
        census = census_bench_fields(analytic_per_step)

        # free state before the caller builds the next config
        del batch
        jax.tree.map(lambda x: x.delete(), state)
        return {"tok_s_chip": tok_per_sec_chip, "mfu": mfu, "dt": dt,
                "seq_len": seq_len, "micro_bs": micro_bs, "steps": steps,
                "attention": attention_impl or "auto",
                "remat_policy": remat_policy, "preset": preset,
                "optimizer": optimizer, "ulysses_size": ulysses_size,
                "ulysses_async": ulysses_async,
                "goodput_pct": gp.get("goodput_pct", 0.0),
                "data_wait_frac": gp.get("data_wait_frac", 0.0),
                "recompiles": recompiles,
                "numerics_overhead_frac": numerics_overhead_frac,
                "restore_verify_s": restore_verify_s,
                "ckpt_quarantined": ckpt_quarantined,
                "ckpt_fallbacks": ckpt_fallbacks,
                "compile_time_s": census["compile_time_s"],
                "xla_flops_per_step": census["xla_flops_per_step"],
                "analytic_vs_xla_flops_ratio":
                    census["analytic_vs_xla_flops_ratio"]}


def run_serve_bench(
    *,
    num_slots: int = 4,
    block_size: int = 16,
    n_requests: int = 16,
    prompt_lens=(64, 128, 256),
    max_new_tokens: int = 64,
    preset: str = "qwen3_0p6b",
    remat_policy: str = "dots",
    shared_prefix: int = 0,
    prefill_chunk: int = 0,
    prefix_cache: bool = True,
    spec_ks=(),
    spec_draft: str = "ngram",
    kv_quant: str = "",
    weight_quant: str = "none",
) -> dict:
    """Continuous-batching inference throughput: N requests with a cycled
    prompt-length mix through the serving engine. Returns decode tokens/s
    (steady-state, measured after the first token of the last-admitted
    request wherever possible — here simply total generated / wall) and
    mean TTFT. Single-chip, random weights: measures the engine + kernels,
    not checkpoint IO.

    ``shared_prefix`` > 0 makes every prompt open with the same
    ``shared_prefix``-token system prompt (the millions-of-users-share-a-
    system-prompt workload) and ALSO drives the same timed request set
    through a cache-off engine, so the JSON line carries TTFT p50/p99 and
    prefill step counts with the prefix cache on vs off.

    ``spec_ks`` (BENCH_SERVE_SPEC_K, e.g. ``0,2,4,8``) additionally drives
    the SAME timed request set through a draft-then-verify engine per k:
    the sweep records decode tok/s and the verify acceptance rate at each
    k, with the k=0 run doubling as the ``nospec_*`` baseline — the
    accepted-tokens-per-verify-width tradeoff curve the ROADMAP's
    speculative-decoding item regresses against.

    ``kv_quant`` (BENCH_SERVE_KV_QUANT, e.g. ``int8``; optionally paired
    with ``weight_quant`` via BENCH_SERVE_WEIGHT_QUANT) additionally
    drives the SAME timed request set through a quantized engine
    (mirroring the nocache_*/nospec_* comparisons): the JSON line then
    carries quantized-vs-f32 decode tok/s and TTFT, the measured per-block
    byte sizes (int8 payload + scale sidecar, straight from the pool's
    ``nbytes``), the fixed-pool-bytes capacity ratio, and the fixed-seed
    quality-gate stats — so a capacity win can never be reported without
    its quality cost in the same record."""
    import jax
    import jax.numpy as jnp

    from veomni_tpu.models import build_foundation_model
    from veomni_tpu.serving import (
        EngineConfig,
        InferenceEngine,
        Request,
        SamplingParams,
    )

    _beat(phase="init")  # before the chip claim: see run_bench
    _wait_for_backend()
    _beat(phase="backend")  # progress marker: chip claim succeeded
    cfg = bench_config(remat_policy, preset)
    model = build_foundation_model(config=cfg)
    params = model.family.init_params(jax.random.PRNGKey(0), cfg)
    _beat(phase="params")  # progress marker: weights materialized on device

    max_len = max(prompt_lens) + max_new_tokens
    rng = np.random.default_rng(0)
    prefix = [int(t) for t in rng.integers(1, cfg.vocab_size, shared_prefix)]

    def make_prompts(n, seed):
        r = np.random.default_rng(seed)
        prompts = []
        for i in range(n):
            want = prompt_lens[i % len(prompt_lens)]
            # at least one unique token per request so every prompt still
            # has an uncached suffix to run (and requests stay distinct)
            suffix = max(1, want - shared_prefix)
            prompts.append(prefix[: max(0, want - suffix)] + [
                int(t) for t in r.integers(1, cfg.vocab_size, suffix)
            ])
        return prompts

    def drive(engine_cfg, warm_prompts, timed_prompts):
        eng = InferenceEngine(params, cfg, engine_cfg)
        # warmup through the SAME engine (the decode-step jit cache is
        # per-engine), one length class at a time: a solo run walks that
        # class's whole block-allocation trajectory, so every power-of-two
        # context bucket the timed run can hit (nbb is always pow2 of SOME
        # running seq's allocation) is compiled before t0 — batch-mixed
        # warmup would let the longest prompt mask the smaller buckets.
        # With the prefix cache on this also pre-caches the shared prefix,
        # so the timed window measures the steady state.
        for wi, p in enumerate(warm_prompts):
            eng.run([Request(prompt_ids=p, sampling=SamplingParams(
                max_new_tokens=max_new_tokens))])
            # warmup compiles are where the relay historically wedges
            _beat(global_step=wi + 1, phase="serve_warmup")
        m0 = eng.metrics()  # reset the throughput window

        timed = [Request(prompt_ids=p, sampling=SamplingParams(
            max_new_tokens=max_new_tokens)) for p in timed_prompts]
        t0 = time.perf_counter()
        ids = [eng.submit(r) for r in timed]
        outs = eng.run()
        dt = time.perf_counter() - t0
        _beat(global_step=len(timed), phase="serve_done")
        m1 = eng.metrics(reset_window=False)
        # warmup-proof deltas across the timed window; prompt_tokens counts
        # every (re)admission's recompute prompt, so the token fraction is
        # bounded by 1 even under preemption storms
        delta = {k: m1[k] - m0[k]
                 for k in ("prefill_chunks", "cached_tokens",
                           "prompt_tokens", "spec_proposed",
                           "spec_accepted")}
        return eng, ids, outs, dt, delta

    engine_cfg = EngineConfig(
        num_slots=num_slots, block_size=block_size, max_model_len=max_len,
        prefix_cache=prefix_cache, prefill_chunk=prefill_chunk,
    )
    warm = make_prompts(len(prompt_lens), seed=1)
    timed_prompts = make_prompts(n_requests, seed=2)
    eng, ids, outs, dt, delta = drive(engine_cfg, warm, timed_prompts)
    total = sum(len(outs[rid].token_ids) for rid in ids)
    ttfts = [outs[rid].ttft_s for rid in ids if outs[rid].ttft_s is not None]

    # per-request latency distribution over the TIMED requests only (the
    # outputs carry the request_trace rollup, so warmup traffic in the
    # process-global histograms can't skew these) — the numbers the
    # SLO-scheduling roadmap item regresses against
    waits = [outs[rid].queue_wait_s for rid in ids
             if outs[rid].queue_wait_s is not None]
    tpots = [outs[rid].tpot_s for rid in ids if outs[rid].tpot_s is not None]
    result = {
        "decode_tok_s": total / dt,
        "ttft_mean_s": sum(ttfts) / max(1, len(ttfts)),
        "ttft_p50_s": _pctl(ttfts, 50),
        "ttft_p99_s": _pctl(ttfts, 99),
        "total_tokens": total,
        "dt": dt,
        "num_slots": num_slots,
        "block_size": block_size,
        "n_requests": n_requests,
        "prompt_lens": list(prompt_lens),
        "max_new_tokens": max_new_tokens,
        "preset": preset,
        "shared_prefix": shared_prefix,
        "prefill_chunk": prefill_chunk,
        "prefix_cache": prefix_cache,
        "preemptions": eng.scheduler.preemption_count,
        "queue_wait_p50_s": _pctl(waits, 50),
        "queue_wait_p99_s": _pctl(waits, 99),
        "tpot_p50_s": _pctl(tpots, 50),
        "tpot_p99_s": _pctl(tpots, 99),
        # from the timed outputs, like the percentiles above — the engine-
        # cumulative scheduler counter would fold warmup traffic in
        "preemptions_per_request": sum(
            outs[rid].preemptions for rid in ids) / max(1, n_requests),
        # prefix-cache effectiveness over the timed window. Two distinct
        # views: hit RATE is request-weighted (share of timed requests
        # whose latest admission matched cached blocks), the token FRAC is
        # token-weighted over every (re)admission's recompute prompt
        # (warmup-proof engine-counter delta, bounded by 1 even when
        # preemption re-admissions inflate cached_tokens per request)
        "prefix_hit_rate": sum(
            1 for rid in ids if outs[rid].cached_tokens > 0
        ) / max(1, len(ids)),
        "cached_tokens_frac": (
            delta["cached_tokens"] / max(1.0, delta["prompt_tokens"])
        ),
        "prefill_chunks": delta["prefill_chunks"],
    }
    if shared_prefix > 0 and prefix_cache:
        # the same request set through a cache-off engine: the on-vs-off
        # TTFT/prefill-step comparison the ROADMAP's serving item regresses
        _, ids2, outs2, _, delta_off = drive(
            EngineConfig(num_slots=num_slots, block_size=block_size,
                         max_model_len=max_len, prefix_cache=False,
                         prefill_chunk=prefill_chunk),
            warm, timed_prompts,
        )
        off_ttfts = [outs2[rid].ttft_s for rid in ids2
                     if outs2[rid].ttft_s is not None]
        result["nocache_ttft_p50_s"] = _pctl(off_ttfts, 50)
        result["nocache_ttft_p99_s"] = _pctl(off_ttfts, 99)
        result["nocache_prefill_chunks"] = delta_off["prefill_chunks"]
    if spec_ks:
        # the SAME timed request set per draft length k: the k=0 run is the
        # nospec baseline (mirrors the nocache_* pattern above), the rest
        # trace the accepted-tokens-vs-verify-width curve
        sweep = []
        for k in spec_ks:
            if int(k) == 0:
                # spec_k=0 IS the main (speculation-off) drive above —
                # reuse its measurement instead of re-running warmup + the
                # whole timed set for a byte-identical engine
                entry = {
                    "spec_k": 0,
                    "decode_tok_s": result["decode_tok_s"],
                    "spec_acceptance_rate": 0.0,
                    "spec_accepted_tokens": 0.0,
                    "tpot_p50_s": result["tpot_p50_s"],
                }
            else:
                _, ids_k, outs_k, dt_k, delta_k = drive(
                    EngineConfig(num_slots=num_slots, block_size=block_size,
                                 max_model_len=max_len,
                                 prefix_cache=prefix_cache,
                                 prefill_chunk=prefill_chunk,
                                 spec_k=int(k), spec_draft=spec_draft),
                    warm, timed_prompts,
                )
                total_k = sum(len(outs_k[r].token_ids) for r in ids_k)
                tpots_k = [outs_k[r].tpot_s for r in ids_k
                           if outs_k[r].tpot_s is not None]
                entry = {
                    "spec_k": int(k),
                    "decode_tok_s": total_k / dt_k,
                    "spec_acceptance_rate": (
                        delta_k["spec_accepted"]
                        / max(1.0, delta_k["spec_proposed"])
                    ),
                    "spec_accepted_tokens": delta_k["spec_accepted"],
                    "tpot_p50_s": _pctl(tpots_k, 50),
                }
            sweep.append(entry)
            _beat(global_step=len(sweep), phase="serve_spec_sweep")
            if int(k) == 0:
                result["nospec_decode_tok_s"] = entry["decode_tok_s"]
                result["nospec_tpot_p50_s"] = entry["tpot_p50_s"]
        result["spec_sweep"] = sweep
        result["spec_draft"] = spec_draft
    if kv_quant:
        # the SAME timed request set through a quantized engine (mirrors
        # the nocache_*/nospec_* comparisons above). Byte sizes come from
        # the live pools via kv_capacity() (QuantizedKV.nbytes = int8
        # payload + f32 scale sidecar), and the fixed-seed quality gate
        # rides in the same record: capacity and quality move together.
        from veomni_tpu.serving.quality import fixed_corpus, quality_stats

        eng_q, ids_q, outs_q, dt_q, _ = drive(
            EngineConfig(num_slots=num_slots, block_size=block_size,
                         max_model_len=max_len, prefix_cache=prefix_cache,
                         prefill_chunk=prefill_chunk, kv_quant=kv_quant,
                         weight_quant=weight_quant),
            warm, timed_prompts,
        )
        _beat(phase="serve_kv_quant")
        total_q = sum(len(outs_q[rid].token_ids) for rid in ids_q)
        q_ttfts = [outs_q[rid].ttft_s for rid in ids_q
                   if outs_q[rid].ttft_s is not None]
        cap_f32 = eng.kv_capacity()
        cap_q = eng_q.kv_capacity()
        # fixed-pool-BYTES capacity: max-length sequences the quantized
        # blocks fit inside the f32 pool's byte budget vs what f32 fits —
        # the "2x the users in the same HBM" headline (block 0 stays the
        # reserved null block in both denominators)
        per_seq = max(1.0, cap_f32["blocks_per_max_len_seq"])
        q_blocks_in_f32_bytes = cap_f32["pool_bytes"] // max(
            1.0, cap_q["block_bytes"])
        q_seqs = (q_blocks_in_f32_bytes - 1) // per_seq
        stats = quality_stats(
            params, cfg, fixed_corpus(cfg.vocab_size),
            kv_quant=kv_quant, weight_quant=weight_quant,
            block_size=block_size,
        )
        result.update({
            "kv_quant": kv_quant,
            "weight_quant": weight_quant,
            "kvq_decode_tok_s": total_q / dt_q,
            "kvq_ttft_p50_s": _pctl(q_ttfts, 50),
            "kvq_ttft_p99_s": _pctl(q_ttfts, 99),
            "kv_block_bytes": cap_q["block_bytes"],
            "kv_block_bytes_f32": cap_f32["block_bytes"],
            "kv_capacity_ratio": (
                q_seqs / max(1.0, cap_f32["max_concurrent_seqs"])
            ),
            "quality_ppl_ref": stats["ppl_ref"],
            "quality_ppl_quant": stats["ppl_quant"],
            "quality_ppl_rel_delta": stats["ppl_rel_delta"],
            "quality_topk_overlap": stats["topk_overlap"],
        })
    return result


def run_serve_open_loop_bench(
    *,
    num_slots: int = 4,
    block_size: int = 16,
    n_requests: int = 32,
    prompt_lens=(64, 128, 256),
    max_new_tokens: int = 32,
    preset: str = "qwen3_0p6b",
    remat_policy: str = "dots",
    arrival_rate_mults=(0.5, 1.0, 2.0),
    arrival_rates=(),
    queue_bound: int = 0,
    deadline_s: float = 0.0,
    interactive_frac: float = 0.5,
    classes: str = "interactive:4,batch:1",
    seed: int = 0,
    kv_quant: str = "",
    weight_quant: str = "none",
    shared_prefix: int = 0,
    shared_prefix_groups: int = 1,
    replicas: int = 1,
    replica_kill_at_s: float = 0.0,
    chaos_seed: int = -1,
    chaos_stall_s: float = 2.0,
    chaos_publishes: int = 0,
    publish: bool = False,
    _model=None,
) -> dict:
    """Open-loop Poisson overload bench: arrivals fire on a fixed schedule
    regardless of whether the engine keeps up — the load model a closed
    feedback loop (``run_serve_bench``) structurally cannot produce, and
    the only one that exposes overload behavior: queue growth, shedding,
    deadline misses, p99 blowup.

    A closed-loop calibration run first measures the engine's completion
    capacity (requests/s with every slot busy); ``arrival_rate_mults``
    (default sweeps 0.5x/1x/2x, i.e. *past capacity*) scale it into the
    open-loop arrival rates (``arrival_rates`` in req/s overrides). Each
    rate drives the SAME request set — an interactive/batch mix
    (``interactive_frac``; interactive requests carry ``deadline_s`` when
    set) — against a QoS engine with a bounded queue (``queue_bound``; 0
    defaults to ``4 * num_slots``), reporting per rate: reject rate,
    deadline-miss rate, p50/p99 TTFT (overall + interactive-only), p99
    TPOT, decode tok/s, max observed queue depth, and **goodput** —
    tokens from requests that finished within their deadline per second
    of wall time, the number that keeps honest under overload when raw
    decode tok/s still looks fine.

    ``kv_quant`` (BENCH_SERVE_KV_QUANT) adds a quantized leg at FIXED
    pool bytes: the int8 pool is sized to the f32 pool's exact byte
    budget (more, smaller blocks), the same Poisson arrivals replay at
    the same rates, and each ``kvq_sweep`` entry carries the
    goodput-under-overload and reject-rate deltas vs the f32 leg.

    ``replicas`` (BENCH_SERVE_REPLICAS, N > 1) adds a scale-out leg: the
    SAME Poisson storms replay at the SAME swept rates against the
    prefix-affinity router over N data-parallel engine replicas (compiled
    programs shared — one warmup covers the fleet). Each ``router_sweep``
    entry carries the aggregate and per-replica goodput, the router's
    prefix hit rate vs the single-engine leg's (affinity should keep
    shared-prefix traffic at least as warm as one engine sees), and
    ``goodput_scaling`` — aggregate goodput over the single-engine leg's
    at the identical rate: past single-engine capacity the fleet's extra
    slots/KV/queue convert sheds and deadline misses back into goodput. ``shared_prefix`` prepends that many
    common tokens to every prompt, drawn from ``shared_prefix_groups``
    distinct prefixes (BENCH_SERVE_PREFIX_GROUPS; think N different
    system prompts — the workload affinity routing exists for: each
    group's KV warms exactly one replica instead of cold-missing on all
    of them); ``replica_kill_at_s`` (BENCH_SERVE_REPLICA_KILL_AT_S) kills one
    replica that many seconds into each router rate — the mid-storm
    fault drill (survivors absorb re-dispatched work, the entry reports
    ``redispatched``/``cancelled``).

    ``chaos_seed`` (BENCH_SERVE_CHAOS, >= 0 enables) adds the chaos soak
    leg: a seeded deterministic fault schedule (``resilience/chaos.py`` —
    replica kills + hang/delay/exception across the serve fault points)
    fires over a self-healing fleet (wedge detection at ``chaos_stall_s``,
    respawn + probation enabled) while the same Poisson storm replays
    twice — once fault-free, once under chaos. The entry reports the
    fleet invariants (no lost/duplicated ids, zero leaked blocks per
    survivor, fleet restored to full live count) and ``goodput_ratio``
    (chaos / fault-free; the acceptance floor is 0.7). The PR-17 kill
    drill (``replica_kill_at_s``) deliberately keeps respawns OFF — it
    measures the *degraded* fleet; the chaos leg measures the *healing*
    one. ``chaos_publishes`` (BENCH_SERVE_CHAOS_PUBLISHES) additionally
    schedules that many mid-storm rolling weight publications inside the
    chaos leg (drawn AFTER the seed's faults/kills, so existing seeds
    replay bit-identically) — the soak then also checks the fleet
    converged to exactly one weights version.

    ``publish`` (BENCH_SERVE_PUBLISH=1) adds the rolling-publish leg:
    the same Poisson storm replays twice over the self-healing fleet —
    once publish-free, once with ONE mid-storm ``publish_weights`` of a
    perturbed payload rolling through every replica — reporting the
    publish wall time (fire -> fleet converged on the new version), the
    goodput ratio vs the publish-free replay (acceptance floor 0.7) and
    the zero-new-traces gate across the whole drain -> swap -> rotation
    window (``models/decode.py::TRACE_COUNTS`` delta from the moment of
    publish must be 0).

    ``_model`` injects a prebuilt ``(params, cfg)`` (tier-1 CPU smoke uses
    a tiny model); by default the ``preset`` model is built fresh."""
    import jax

    from veomni_tpu.models import build_foundation_model
    from veomni_tpu.serving import (
        EngineConfig,
        InferenceEngine,
        Request,
        SamplingParams,
    )

    if _model is not None:
        params, cfg = _model
    else:
        _beat(phase="init")
        _wait_for_backend()
        _beat(phase="backend")
        cfg = bench_config(remat_policy, preset)
        model = build_foundation_model(config=cfg)
        params = model.family.init_params(jax.random.PRNGKey(0), cfg)
        _beat(phase="params")

    max_len = max(prompt_lens) + max_new_tokens
    queue_bound = queue_bound or 4 * num_slots
    rng = np.random.default_rng(seed)
    # the interactive/batch roles map onto the CONFIGURED class spec: the
    # first (highest-priority) class plays "interactive" and the last
    # "batch", so a custom BENCH_SERVE_CLASSES sweep doesn't crash on
    # labels the engine never configured
    from veomni_tpu.serving import parse_classes

    class_names = [n for n, _ in parse_classes(classes)]
    hi_class, lo_class = class_names[0], class_names[-1]

    # common leading chunks (think distinct system prompts): the
    # shared-prefix workload the radix cache — and the router's affinity
    # keying on top of it — exists for. 0 keeps fully random prompts.
    prefixes = [
        [int(t) for t in rng.integers(1, cfg.vocab_size, shared_prefix)]
        for _ in range(max(1, shared_prefix_groups))
    ]

    def make_requests(n):
        reqs = []
        for i in range(n):
            want = prompt_lens[i % len(prompt_lens)]
            prefix = prefixes[int(rng.integers(0, len(prefixes)))]
            fresh = max(1, want - len(prefix))
            prompt = prefix + [
                int(t) for t in rng.integers(1, cfg.vocab_size, fresh)
            ]
            interactive = bool(rng.random() < interactive_frac)
            reqs.append(Request(
                prompt_ids=prompt,
                sampling=SamplingParams(max_new_tokens=max_new_tokens),
                priority=hi_class if interactive else lo_class,
                deadline_s=(deadline_s if interactive and deadline_s > 0
                            else None),
            ))
        return reqs

    def clone_requests(protos):
        """Fresh Request objects over the same prompts/classes/deadlines:
        every swept rate replays the IDENTICAL workload (cross-rate deltas
        measure load response, not workload noise), while each engine
        assigns its own request ids."""
        return [Request(prompt_ids=list(r.prompt_ids), sampling=r.sampling,
                        priority=r.priority, deadline_s=r.deadline_s)
                for r in protos]

    def engine_cfg(**kw):
        return EngineConfig(num_slots=num_slots, block_size=block_size,
                            max_model_len=max_len, **kw)

    # ---- closed-loop calibration: completion capacity with full slots
    # (shared warmup: compiles land here, not inside any timed window)
    cal = InferenceEngine(params, cfg, engine_cfg(classes=classes))
    warm = make_requests(len(prompt_lens))
    for r in warm:
        cal.run([r])
    _beat(phase="serve_warmup")
    proto = make_requests(n_requests)  # ONE workload, replayed per rate
    # calibration strips deadlines: an expiry "completing" a request early
    # would inflate the measured service capacity the sweep scales from
    cal_reqs = [Request(prompt_ids=list(r.prompt_ids), sampling=r.sampling,
                        priority=r.priority) for r in proto]
    t0 = time.perf_counter()
    cal.run(cal_reqs)
    cal_dt = time.perf_counter() - t0
    capacity_rps = n_requests / max(cal_dt, 1e-9)
    _beat(phase="serve_capacity")

    rates = [float(r) for r in arrival_rates] or [
        m * capacity_rps for m in arrival_rate_mults
    ]

    def run_rate(rate, **cfg_kw):
        eng = InferenceEngine(params, cfg, engine_cfg(
            queue_bound=queue_bound, classes=classes, **cfg_kw,
        ))
        for r in warm:  # per-engine jit caches: warm each engine
            eng.run([Request(prompt_ids=r.prompt_ids, sampling=r.sampling,
                             priority=r.priority)])
        reqs = clone_requests(proto)
        # per-rate seeded arrivals: the Poisson pattern is reproducible for
        # a given (seed, rate) independent of sweep order
        arng = np.random.default_rng((seed, int(rate * 1e6)))
        arrivals = np.cumsum(arng.exponential(1.0 / rate, size=n_requests))
        m0 = eng.metrics()  # reset the goodput/throughput window
        ids = []
        max_queue = 0
        t0 = time.perf_counter()
        i = 0
        while i < len(reqs) or eng.has_work:
            now = time.perf_counter() - t0
            while i < len(reqs) and arrivals[i] <= now:
                ids.append(eng.submit(reqs[i]))  # open loop: never blocks
                i += 1
            max_queue = max(max_queue, eng.scheduler.queue_depth)
            if eng.has_work:
                eng.step()
            elif i < len(reqs):
                time.sleep(min(max(arrivals[i] - now, 0.0), 0.01))
        dt = time.perf_counter() - t0
        m1 = eng.metrics(reset_window=False)
        outs = {rid: eng._outputs[rid] for rid in ids}
        done = [o for o in outs.values()
                if o.finish_reason in ("eos", "length")]
        inter_ids = [rid for rid, r in zip(ids, reqs)
                     if r.priority == hi_class]
        ttfts = [o.ttft_s for o in done if o.ttft_s is not None]
        inter_ttfts = [outs[rid].ttft_s for rid in inter_ids
                       if outs[rid].ttft_s is not None]
        tpots = [o.tpot_s for o in done if o.tpot_s is not None]
        n_rej = sum(1 for o in outs.values()
                    if o.finish_reason == "rejected")
        n_miss = sum(1 for o in outs.values() if o.deadline_missed)
        return {
            "arrival_rate_rps": rate,
            "rate_vs_capacity": rate / max(capacity_rps, 1e-9),
            "reject_rate": n_rej / max(1, n_requests),
            "deadline_miss_rate": n_miss / max(1, n_requests),
            "completed": len(done),
            "max_queue_depth": max_queue,
            "ttft_p50_s": _pctl(ttfts, 50),
            "ttft_p99_s": _pctl(ttfts, 99),
            "ttft_p99_interactive_s": _pctl(inter_ttfts, 99),
            "tpot_p99_s": _pctl(tpots, 99),
            "decode_tok_s": sum(len(o.token_ids) for o in done) / dt,
            # window deltas are warmup-proof (m0 reset the window); goodput
            # divides by the open-loop wall, not the window elapsed
            "goodput_tok_s": (m1["goodput_tokens"] - m0["goodput_tokens"])
            / dt,
            "shed_tokens": m1["shed_tokens"] - m0["shed_tokens"],
            "prefix_hit_rate": m1["prefix_hit_rate"],
        }

    def run_rate_router(rate, n_replicas):
        """Open-loop replay through the prefix-affinity router: the SAME
        storm (identical protos, identical Poisson arrivals at the same
        rate) that just hit one engine, now absorbed by N replicas — the
        question an operator staring at a shedding single engine actually
        asks. Past single-engine capacity the fleet's extra slots/KV/queue
        convert sheds and deadline misses back into goodput. Optional
        mid-storm replica kill."""
        from veomni_tpu.serving import Router, RouterConfig

        # respawns stay OFF here: this leg measures the DEGRADED fleet
        # (how survivors absorb a kill), not the healing one — the chaos
        # leg below owns resurrection. Pump workers heartbeat per replica
        # so a wedged replica is nameable from the stall JSON.
        router = Router(params, cfg, engine_cfg(
            queue_bound=queue_bound * n_replicas, classes=classes,
        ), RouterConfig(replicas=n_replicas, max_respawns=0,
                        heartbeat_dir=_bench_out_dir()))
        # compiled programs are SHARED across replicas: one warmup pass
        # through the router compiles for the whole fleet
        for r in warm:
            router.run([Request(prompt_ids=r.prompt_ids, sampling=r.sampling,
                                priority=r.priority)])
        reqs = clone_requests(proto)
        arng = np.random.default_rng((seed, int(rate * 1e6)))
        arrivals = np.cumsum(arng.exponential(1.0 / rate, size=len(reqs)))
        m0 = router.metrics()
        ids = []
        killed = ""
        t0 = time.perf_counter()
        i = 0
        while i < len(reqs) or router.has_work:
            now = time.perf_counter() - t0
            if (replica_kill_at_s > 0 and not killed
                    and now >= replica_kill_at_s
                    and len(router.live_replicas()) > 1):
                killed = router.live_replicas()[0].rid
                router.kill_replica(killed, reason="bench kill drill")
            while i < len(reqs) and arrivals[i] <= now:
                ids.append(router.submit(reqs[i]))
                i += 1
            if router.has_work:
                router.step()
            elif i < len(reqs):
                time.sleep(min(max(arrivals[i] - now, 0.0), 0.01))
        dt = time.perf_counter() - t0
        m1 = router.metrics(reset_window=False)
        outs = {rid: router._outputs[rid] for rid in ids}
        done = [o for o in outs.values()
                if o.finish_reason in ("eos", "length")]
        entry = {
            "arrival_rate_rps": rate,
            "replicas": n_replicas,
            "completed": len(done),
            "reject_rate": sum(
                1 for o in outs.values() if o.finish_reason == "rejected"
            ) / max(1, len(reqs)),
            "cancelled": sum(1 for o in outs.values()
                             if o.finish_reason == "cancelled"),
            "redispatched": int(m1["redispatched"]),
            "spills": int(m1["spills"]),
            # aggregate goodput from the OUTPUTS (deadline-met tokens over
            # the open-loop wall): a killed replica's engine totals leave
            # the fleet aggregate mid-run, so the lifetime-delta trick the
            # single-engine leg uses would undercount here
            "goodput_tok_s": sum(
                len(o.token_ids) for o in done if not o.deadline_missed
            ) / dt,
            # per-replica split from engine lifetime deltas (survivors
            # only — a killed replica drops out of the census)
            "per_replica_goodput_tok_s": {
                rid: (m["goodput_tokens"]
                      - m0["per_replica"].get(rid, {}).get(
                          "goodput_tokens", 0.0)) / dt
                for rid, m in m1["per_replica"].items()
            },
            "prefix_hit_rate": m1["prefix_hit_rate"],
        }
        if killed:
            entry["replica_killed"] = killed
            entry["replica_kill_at_s"] = replica_kill_at_s
        return entry

    sweep = []
    for rate in rates:
        sweep.append(run_rate(rate))
        _beat(global_step=len(sweep), phase="serve_open_loop")
    result = {
        "capacity_rps": capacity_rps,
        "num_slots": num_slots,
        "block_size": block_size,
        "n_requests": n_requests,
        "prompt_lens": list(prompt_lens),
        "max_new_tokens": max_new_tokens,
        "preset": preset,
        "queue_bound": queue_bound,
        "deadline_s": deadline_s,
        "interactive_frac": interactive_frac,
        "classes": classes,
        "sweep": sweep,
    }
    if kv_quant:
        # quantized leg at FIXED pool bytes: size the quantized pool to the
        # f32 pool's exact byte budget (int8 blocks are smaller, so more of
        # them fit), then replay the SAME Poisson arrivals at the SAME
        # swept rates — the per-rate goodput/reject deltas isolate what the
        # extra KV capacity buys under overload, at constant HBM spend
        import jax.numpy as jnp

        from veomni_tpu.ops.quantization import kv_block_nbytes

        kb = (cfg.num_hidden_layers, block_size,
              cfg.num_key_value_heads, cfg.head_dim)
        dtype_bytes = jnp.dtype(cfg.dtype).itemsize
        f32_block = kv_block_nbytes(*kb, kv_quant="none",
                                    dtype_bytes=dtype_bytes)
        q_block = kv_block_nbytes(*kb, kv_quant=kv_quant,
                                  dtype_bytes=dtype_bytes)
        f32_blocks = engine_cfg().num_blocks  # the defaulted f32 pool
        q_blocks = max(f32_blocks, (f32_blocks * f32_block) // q_block)
        q_sweep = []
        for rate in rates:
            q_sweep.append(run_rate(
                rate, kv_quant=kv_quant, weight_quant=weight_quant,
                num_blocks=int(q_blocks),
            ))
            _beat(global_step=len(q_sweep), phase="serve_open_loop_kvq")
        for base, q in zip(sweep, q_sweep):
            q["goodput_delta_tok_s"] = (
                q["goodput_tok_s"] - base["goodput_tok_s"]
            )
            q["reject_rate_delta"] = q["reject_rate"] - base["reject_rate"]
        result.update({
            "kv_quant": kv_quant,
            "weight_quant": weight_quant,
            "kv_block_bytes": float(q_block),
            "kv_block_bytes_f32": float(f32_block),
            "kvq_num_blocks": int(q_blocks),
            "f32_num_blocks": int(f32_blocks),
            "kvq_sweep": q_sweep,
        })
    if replicas > 1:
        # scale-out leg: the same storm at the same rate, N replicas;
        # goodput_scaling compares the fleet aggregate against the
        # single-engine leg at the identical arrival rate
        r_sweep = []
        for base, rate in zip(sweep, rates):
            entry = run_rate_router(rate, replicas)
            entry["goodput_scaling"] = (
                entry["goodput_tok_s"] / max(base["goodput_tok_s"], 1e-9)
            )
            entry["prefix_hit_rate_single"] = base["prefix_hit_rate"]
            r_sweep.append(entry)
            _beat(global_step=len(r_sweep), phase="serve_open_loop_router")
        result.update({
            "replicas": replicas,
            "replica_kill_at_s": replica_kill_at_s,
            "shared_prefix": shared_prefix,
            "router_sweep": r_sweep,
        })
    def _perturbed_params(idx: int):
        # deterministic non-trivial payload for publish drills: every
        # float leaf scaled by a per-publish factor (same shapes/dtypes,
        # so the hot-swap is zero-trace by construction)
        import jax
        import jax.numpy as jnp

        scale = 1.0 + 1e-3 * (idx + 1)
        return jax.tree_util.tree_map(
            lambda x: x * scale
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.inexact)
            else x,
            params,
        )

    if chaos_seed >= 0:
        # chaos soak: the same storm replayed fault-free and under a
        # seeded deterministic fault schedule against a SELF-HEALING
        # fleet; the seed in the report replays a failure bit-for-bit
        from veomni_tpu.resilience.chaos import (
            build_chaos_plan,
            run_chaos_soak,
        )
        from veomni_tpu.serving import Router, RouterConfig

        n_rep = replicas if replicas > 1 else 3
        chaos_rate = max(rates)
        arng = np.random.default_rng((seed, 777))
        chaos_arrivals = [float(t) for t in np.cumsum(
            arng.exponential(1.0 / chaos_rate, size=n_requests))]

        def chaos_factory():
            router = Router(params, cfg, engine_cfg(
                queue_bound=queue_bound * n_rep, classes=classes,
            ), RouterConfig(replicas=n_rep, replica_stall_ticks=2,
                            max_respawns=4, respawn_backoff_s=0.05,
                            respawn_backoff_max_s=0.5,
                            probation_requests=2,
                            heartbeat_dir=_bench_out_dir()))
            # warm under the default forgiving stall deadline — compiles
            # must not read as wedges — then run the warm set AGAIN: the
            # prefix-cache hits route through the chunked-prefill program,
            # which otherwise first compiles mid-storm and trips the
            # tightened deadline below
            for _ in range(2):
                router.run([Request(prompt_ids=list(r.prompt_ids),
                                    sampling=r.sampling,
                                    priority=r.priority) for r in warm])
            router.config.replica_stall_s = chaos_stall_s
            return router

        plan = build_chaos_plan(
            chaos_seed, duration_s=chaos_arrivals[-1],
            hang_seconds=2.0 * chaos_stall_s + 1.0,
            expected_ticks=max(50, (n_requests * max_new_tokens) // 8),
            publishes=max(0, chaos_publishes),
        )
        base_soak = run_chaos_soak(
            router_factory=chaos_factory, requests=clone_requests(proto),
            arrivals=chaos_arrivals, plan=None, restore_timeout_s=60.0)
        _beat(phase="serve_chaos_fault_free")
        chaos_soak = run_chaos_soak(
            router_factory=chaos_factory, requests=clone_requests(proto),
            arrivals=chaos_arrivals, plan=plan,
            publish_fn=(
                (lambda router, idx: router.publish_weights(
                    _perturbed_params(idx), f"storm-v{idx + 1}"))
                if chaos_publishes > 0 else None),
            restore_timeout_s=60.0)
        _beat(phase="serve_chaos")
        ratio = (chaos_soak["goodput_tok_s"]
                 / max(base_soak["goodput_tok_s"], 1e-9))

        def _slim(rep):
            return {k: v for k, v in rep.items()
                    if k not in ("outputs", "router")}

        result["chaos"] = {
            "seed": chaos_seed,
            "replicas": n_rep,
            "stall_s": chaos_stall_s,
            "arrival_rate_rps": chaos_rate,
            "plan": plan.to_doc(),
            "fault_free": _slim(base_soak),
            "chaos": _slim(chaos_soak),
            "goodput_ratio": ratio,
            # mid-storm publish coverage (chaos_publishes > 0): the soak's
            # invariants_ok already folds in version convergence
            "publishes": chaos_soak["publishes"],
            "published_versions": chaos_soak["published_versions"],
            "version_converged": chaos_soak["version_converged"],
            "publish_wall_s": chaos_soak["publish_wall_s"],
            "ok": bool(base_soak["invariants_ok"]
                       and chaos_soak["invariants_ok"]
                       and ratio >= 0.7),
        }
    if publish:
        # rolling-publish leg (BENCH_SERVE_PUBLISH=1): the same Poisson
        # storm replayed publish-free and then with ONE mid-storm rolling
        # weight publication over the self-healing fleet — publish wall
        # time, goodput ratio vs the publish-free replay (0.7 floor) and
        # the zero-new-traces gate from the moment of publish
        from veomni_tpu.models import decode as decode_mod
        from veomni_tpu.resilience.chaos import (
            build_chaos_plan,
            run_chaos_soak,
        )
        from veomni_tpu.serving import Router, RouterConfig

        n_rep = replicas if replicas > 1 else 3
        pub_rate = max(rates)
        prng = np.random.default_rng((seed, 778))
        pub_arrivals = [float(t) for t in np.cumsum(
            prng.exponential(1.0 / pub_rate, size=n_requests))]

        def publish_factory():
            router = Router(params, cfg, engine_cfg(
                queue_bound=queue_bound * n_rep, classes=classes,
            ), RouterConfig(replicas=n_rep, probation_requests=2,
                            heartbeat_dir=_bench_out_dir()))
            # warm twice like the chaos factory: the second pass routes
            # prefix-cache hits through the chunked-prefill program so
            # nothing compiles mid-storm
            for _ in range(2):
                router.run([Request(prompt_ids=list(r.prompt_ids),
                                    sampling=r.sampling,
                                    priority=r.priority) for r in warm])
            return router

        pub_plan = build_chaos_plan(
            max(0, chaos_seed) if chaos_seed >= 0 else seed,
            duration_s=pub_arrivals[-1],
            kills=0, hangs=0, delays=0, exceptions=0, publishes=1,
            expected_ticks=max(50, (n_requests * max_new_tokens) // 8),
        )
        base_rep = run_chaos_soak(
            router_factory=publish_factory, requests=clone_requests(proto),
            arrivals=pub_arrivals, plan=None, restore_timeout_s=60.0)
        _beat(phase="serve_publish_baseline")
        trace_mark: dict = {}

        def _publish_payload(router, idx):
            # trace census snapshot at the MOMENT of publish: the gate
            # covers exactly the drain -> swap -> rotation window
            trace_mark.update(decode_mod.TRACE_COUNTS)
            return router.publish_weights(_perturbed_params(idx),
                                          f"publish-v{idx + 1}")

        pub_rep = run_chaos_soak(
            router_factory=publish_factory, requests=clone_requests(proto),
            arrivals=pub_arrivals, plan=pub_plan,
            publish_fn=_publish_payload, restore_timeout_s=60.0)
        _beat(phase="serve_publish")
        trace_delta = (sum(decode_mod.TRACE_COUNTS.values())
                       - sum(trace_mark.values()))
        pratio = (pub_rep["goodput_tok_s"]
                  / max(base_rep["goodput_tok_s"], 1e-9))

        def _slim_pub(rep):
            return {k: v for k, v in rep.items()
                    if k not in ("outputs", "router")}

        result["publish"] = {
            "replicas": n_rep,
            "arrival_rate_rps": pub_rate,
            "plan": pub_plan.to_doc(),
            "baseline": _slim_pub(base_rep),
            "publish": _slim_pub(pub_rep),
            "published_versions": pub_rep["published_versions"],
            "publish_wall_s": pub_rep["publish_wall_s"],
            "version_converged": pub_rep["version_converged"],
            "goodput_ratio": pratio,
            "trace_delta": trace_delta,
            "ok": bool(base_rep["invariants_ok"]
                       and pub_rep["invariants_ok"]
                       and pub_rep["version_converged"]
                       and pratio >= 0.7
                       and trace_delta == 0),
        }
    return result


def _serve_open_loop_main(preset: str, watchdog=None):
    """BENCH_SERVE_OPEN_LOOP=1 entry: one JSON line for the overload
    trajectory (reject rate, p99 TTFT, goodput per arrival rate)."""
    lens = tuple(
        int(x) for x in
        os.environ.get("BENCH_SERVE_PROMPT_LENS", "64,128,256").split(",")
    )
    rates = tuple(
        float(x) for x in
        os.environ.get("BENCH_SERVE_ARRIVAL_RATES", "").split(",")
        if x.strip()
    )
    mults = tuple(
        float(x) for x in
        os.environ.get("BENCH_SERVE_RATE_MULTS", "0.5,1.0,2.0").split(",")
        if x.strip()
    )
    r = run_serve_open_loop_bench(
        num_slots=int(os.environ.get("BENCH_SERVE_SLOTS", 4)),
        block_size=int(os.environ.get("BENCH_SERVE_BLOCK", 16)),
        n_requests=int(os.environ.get("BENCH_SERVE_REQUESTS", 32)),
        prompt_lens=lens,
        max_new_tokens=int(os.environ.get("BENCH_SERVE_NEW_TOKENS", 32)),
        preset=preset,
        arrival_rates=rates,
        arrival_rate_mults=mults,
        queue_bound=int(os.environ.get("BENCH_SERVE_QUEUE_BOUND", 0)),
        deadline_s=float(os.environ.get("BENCH_SERVE_DEADLINE_S", 0.0)),
        interactive_frac=float(
            os.environ.get("BENCH_SERVE_INTERACTIVE_FRAC", 0.5)
        ),
        classes=os.environ.get("BENCH_SERVE_CLASSES",
                               "interactive:4,batch:1"),
        # BENCH_SERVE_KV_QUANT=int8 adds the fixed-pool-bytes quantized
        # leg (optionally BENCH_SERVE_WEIGHT_QUANT=int8 for tier 2 too)
        kv_quant=os.environ.get("BENCH_SERVE_KV_QUANT", ""),
        weight_quant=os.environ.get("BENCH_SERVE_WEIGHT_QUANT", "none"),
        # BENCH_SERVE_REPLICAS=N (N > 1) adds the scale-out router leg:
        # same arrivals at N-scaled rates over N data-parallel replicas;
        # BENCH_SERVE_REPLICA_KILL_AT_S kills one replica mid-storm and
        # BENCH_SERVE_SHARED_PREFIX makes the traffic affinity-routable
        shared_prefix=int(os.environ.get("BENCH_SERVE_SHARED_PREFIX", 0)),
        shared_prefix_groups=int(
            os.environ.get("BENCH_SERVE_PREFIX_GROUPS", 1)
        ),
        replicas=int(os.environ.get("BENCH_SERVE_REPLICAS", 1)),
        replica_kill_at_s=float(
            os.environ.get("BENCH_SERVE_REPLICA_KILL_AT_S", 0.0)
        ),
        # BENCH_SERVE_CHAOS=<seed> adds the chaos soak leg: a seeded
        # deterministic kill/hang/delay/exception schedule over a
        # self-healing fleet (3 replicas unless BENCH_SERVE_REPLICAS
        # says otherwise), reported against a fault-free replay
        chaos_seed=int(os.environ.get("BENCH_SERVE_CHAOS", -1)),
        chaos_stall_s=float(
            os.environ.get("BENCH_SERVE_CHAOS_STALL_S", 2.0)
        ),
        # BENCH_SERVE_CHAOS_PUBLISHES=N fires N mid-storm rolling weight
        # publications inside the chaos leg; BENCH_SERVE_PUBLISH=1 adds
        # the dedicated rolling-publish leg (publish wall time, goodput
        # ratio vs publish-free replay, zero-new-traces gate)
        chaos_publishes=int(
            os.environ.get("BENCH_SERVE_CHAOS_PUBLISHES", 0)
        ),
        publish=os.environ.get("BENCH_SERVE_PUBLISH", "0")
        not in ("0", ""),
    )
    if watchdog is not None:
        watchdog.stop()
    # headline = the HIGHEST swept rate, independent of the order the
    # rates/mults were supplied in (sweep entries keep supplied order)
    worst = max(r["sweep"], key=lambda e: e["arrival_rate_rps"],
                default={})
    print(json.dumps({
        # headline: goodput at the HIGHEST swept rate — the number that
        # stays honest when raw decode tok/s still looks fine past capacity
        "metric": "serve_open_loop_goodput_tok_s",
        "value": round(worst.get("goodput_tok_s", 0.0), 1),
        "unit": (
            f"deadline-met tokens/s ({r['preset']} bf16, "
            f"slots={r['num_slots']}, "
            f"rate={worst.get('arrival_rate_rps', 0.0):.2f}rps "
            f"~{worst.get('rate_vs_capacity', 0.0):.1f}x capacity, "
            f"queue_bound={r['queue_bound']})"
        ),
        "vs_baseline": 0.0,  # no published open-loop TPU baseline
        "capacity_rps": round(r["capacity_rps"], 3),
        "reject_rate": round(worst.get("reject_rate", 0.0), 4),
        "deadline_miss_rate": round(worst.get("deadline_miss_rate", 0.0), 4),
        "ttft_p99_s": round(worst.get("ttft_p99_s", 0.0), 5),
        "ttft_p99_interactive_s": round(
            worst.get("ttft_p99_interactive_s", 0.0), 5),
        "max_queue_depth": worst.get("max_queue_depth", 0),
        "sweep": [
            {k: (round(v, 5) if isinstance(v, float) else v)
             for k, v in entry.items()}
            for entry in r["sweep"]
        ],
        # fixed-pool-bytes quantized leg when BENCH_SERVE_KV_QUANT is set:
        # same arrivals, same byte budget, per-rate goodput/reject deltas
        **({
            "kv_quant": r["kv_quant"],
            "weight_quant": r["weight_quant"],
            "kv_block_bytes": r["kv_block_bytes"],
            "kv_block_bytes_f32": r["kv_block_bytes_f32"],
            "kvq_num_blocks": r["kvq_num_blocks"],
            "f32_num_blocks": r["f32_num_blocks"],
            "kvq_sweep": [
                {k: (round(v, 5) if isinstance(v, float) else v)
                 for k, v in entry.items()}
                for entry in r["kvq_sweep"]
            ],
        } if "kv_quant" in r else {}),
        # scale-out router leg when BENCH_SERVE_REPLICAS > 1: aggregate +
        # per-replica goodput, goodput_scaling vs the single-engine leg,
        # and the router-vs-single prefix hit rates
        **({
            "replicas": r["replicas"],
            "shared_prefix": r["shared_prefix"],
            "replica_kill_at_s": r["replica_kill_at_s"],
            "router_sweep": [
                {k: (round(v, 5) if isinstance(v, float) else
                     {rk: round(rv, 5) for rk, rv in v.items()}
                     if isinstance(v, dict) else v)
                 for k, v in entry.items()}
                for entry in r["router_sweep"]
            ],
        } if "router_sweep" in r else {}),
        # chaos soak leg when BENCH_SERVE_CHAOS is set: the seeded plan,
        # both soak reports (fault-free + chaos), the fleet invariants
        # and the goodput floor verdict
        **({
            "chaos": {
                k: (round(v, 5) if isinstance(v, float) else v)
                for k, v in r["chaos"].items()
                if k not in ("fault_free", "chaos")
            },
            "chaos_invariants_ok": r["chaos"]["chaos"]["invariants_ok"],
            "chaos_wedged": r["chaos"]["chaos"]["wedged"],
            "chaos_respawns": r["chaos"]["chaos"]["respawns"],
        } if "chaos" in r else {}),
        # rolling-publish leg when BENCH_SERVE_PUBLISH=1: publish wall
        # time, goodput ratio vs the publish-free replay and the
        # zero-new-traces verdict
        **({
            "publish": {
                k: (round(v, 5) if isinstance(v, float) else v)
                for k, v in r["publish"].items()
                if k not in ("baseline", "publish", "plan")
            },
            "publish_ok": r["publish"]["ok"],
            "publish_goodput_ratio": round(
                r["publish"]["goodput_ratio"], 5),
            "publish_trace_delta": r["publish"]["trace_delta"],
        } if "publish" in r else {}),
    }), flush=True)
    _cleanup_default_out()  # healthy exit: don't leak the per-PID /tmp dir


def _serve_main(preset: str, watchdog=None):
    """BENCH_SERVE=1 entry: one JSON line for the serving trajectory."""
    lens = tuple(
        int(x) for x in
        os.environ.get("BENCH_SERVE_PROMPT_LENS", "64,128,256").split(",")
    )
    shared_prefix = int(os.environ.get("BENCH_SERVE_SHARED_PREFIX", 0))
    # chunked prefill defaults ON for the shared-prefix workload: without
    # chunks, cache-on and cache-off both run one prefill step per request
    # and the on-vs-off step-count comparison is vacuous
    prefill_chunk = int(os.environ.get(
        "BENCH_SERVE_PREFILL_CHUNK", 64 if shared_prefix > 0 else 0
    ))
    # BENCH_SERVE_SPEC_K="0,2,4,8" sweeps draft-then-verify speculation
    # over the same timed request set (empty/unset skips the sweep)
    spec_ks = tuple(
        int(x) for x in
        os.environ.get("BENCH_SERVE_SPEC_K", "").split(",") if x.strip()
    )
    r = run_serve_bench(
        num_slots=int(os.environ.get("BENCH_SERVE_SLOTS", 4)),
        block_size=int(os.environ.get("BENCH_SERVE_BLOCK", 16)),
        n_requests=int(os.environ.get("BENCH_SERVE_REQUESTS", 16)),
        prompt_lens=lens,
        max_new_tokens=int(os.environ.get("BENCH_SERVE_NEW_TOKENS", 64)),
        preset=preset,
        shared_prefix=shared_prefix,
        prefill_chunk=prefill_chunk,
        prefix_cache=os.environ.get("BENCH_SERVE_PREFIX_CACHE", "1")
        not in ("0", ""),
        spec_ks=spec_ks,
        spec_draft=os.environ.get("BENCH_SERVE_SPEC_DRAFT", "ngram"),
        # BENCH_SERVE_KV_QUANT=int8 adds the quantized-engine comparison
        # leg (optionally BENCH_SERVE_WEIGHT_QUANT=int8 for tier 2 too)
        kv_quant=os.environ.get("BENCH_SERVE_KV_QUANT", ""),
        weight_quant=os.environ.get("BENCH_SERVE_WEIGHT_QUANT", "none"),
    )
    if watchdog is not None:
        watchdog.stop()
    line = {
        "metric": "serve_decode_tokens_per_sec",
        "value": round(r["decode_tok_s"], 1),
        "unit": f"decode tokens/s ({r['preset']} bf16, slots={r['num_slots']}, "
                f"block={r['block_size']}, {r['n_requests']} reqs "
                f"mix{r['prompt_lens']}, ttft={r['ttft_mean_s']*1e3:.0f}ms, "
                f"preempt={r['preemptions']})",
        # nominal serving north star: 1k decode tok/s on one chip (no
        # published single-v5e continuous-batching baseline exists)
        "vs_baseline": round(r["decode_tok_s"] / 1000.0, 4),
        # per-request latency trajectory (observability/request_trace.py):
        # the SLO-scheduling roadmap item tunes priority classes against
        # exactly these percentiles, so BENCH_*.json must carry them
        "queue_wait_p50_s": round(r["queue_wait_p50_s"], 5),
        "queue_wait_p99_s": round(r["queue_wait_p99_s"], 5),
        "tpot_p50_s": round(r["tpot_p50_s"], 5),
        "tpot_p99_s": round(r["tpot_p99_s"], 5),
        "preemptions_per_request": round(r["preemptions_per_request"], 3),
        # prefix-cache effectiveness (serving/prefix_cache.py): timed-window
        # hit rate + prefill step count, with TTFT percentiles on vs off
        # when the shared-prefix workload is active
        "shared_prefix": r["shared_prefix"],
        "prefill_chunk": r["prefill_chunk"],
        "prefix_cache": r["prefix_cache"],
        "prefix_hit_rate": round(r["prefix_hit_rate"], 4),
        "cached_tokens_frac": round(r["cached_tokens_frac"], 4),
        "prefill_chunks": r["prefill_chunks"],
        "ttft_p50_s": round(r["ttft_p50_s"], 5),
        "ttft_p99_s": round(r["ttft_p99_s"], 5),
    }
    if "nocache_ttft_p50_s" in r:
        line["nocache_ttft_p50_s"] = round(r["nocache_ttft_p50_s"], 5)
        line["nocache_ttft_p99_s"] = round(r["nocache_ttft_p99_s"], 5)
        line["nocache_prefill_chunks"] = r["nocache_prefill_chunks"]
    if "spec_sweep" in r:
        # speculative decoding sweep (serving/spec_decode.py): decode tok/s
        # + verify acceptance rate per draft length k, nospec baseline from
        # the k=0 leg — the multi-token-decode tradeoff curve
        line["spec_draft"] = r["spec_draft"]
        line["spec_sweep"] = [
            {"spec_k": e["spec_k"],
             "decode_tok_s": round(e["decode_tok_s"], 1),
             "spec_acceptance_rate": round(e["spec_acceptance_rate"], 4),
             "spec_accepted_tokens": e["spec_accepted_tokens"],
             "tpot_p50_s": round(e["tpot_p50_s"], 5)}
            for e in r["spec_sweep"]
        ]
        if "nospec_decode_tok_s" in r:
            line["nospec_decode_tok_s"] = round(r["nospec_decode_tok_s"], 1)
            line["nospec_tpot_p50_s"] = round(r["nospec_tpot_p50_s"], 5)
    if "kv_quant" in r:
        # quantized serving tier (ops/quantization.py): same timed set
        # through an int8-KV (and optionally int8-weight) engine, with the
        # measured per-block bytes, the fixed-pool-bytes capacity ratio,
        # and the fixed-seed quality-gate stats riding in the same record
        line["kv_quant"] = r["kv_quant"]
        line["weight_quant"] = r["weight_quant"]
        line["kvq_decode_tok_s"] = round(r["kvq_decode_tok_s"], 1)
        line["kvq_ttft_p50_s"] = round(r["kvq_ttft_p50_s"], 5)
        line["kvq_ttft_p99_s"] = round(r["kvq_ttft_p99_s"], 5)
        line["kv_block_bytes"] = r["kv_block_bytes"]
        line["kv_block_bytes_f32"] = r["kv_block_bytes_f32"]
        line["kv_capacity_ratio"] = round(r["kv_capacity_ratio"], 3)
        line["quality_ppl_rel_delta"] = round(
            r["quality_ppl_rel_delta"], 6)
        line["quality_topk_overlap"] = round(r["quality_topk_overlap"], 4)
    print(json.dumps(line), flush=True)
    _cleanup_default_out()  # healthy exit: don't leak the per-PID /tmp dir


def main():
    from veomni_tpu.utils.xla_flags import apply_performance_flags

    apply_performance_flags()
    serve = os.environ.get("BENCH_SERVE", "0") not in ("0", "")
    open_loop = os.environ.get("BENCH_SERVE_OPEN_LOOP", "0") not in ("0", "")
    watchdog = _start_watchdog(
        float(os.environ.get("BENCH_WATCHDOG_S", 900)),
        "serve_open_loop_goodput_tok_s" if open_loop
        else "serve_decode_tokens_per_sec" if serve
        else "train_tokens_per_sec_per_chip",
    )
    preset = os.environ.get("BENCH_PRESET", "qwen3_0p6b")
    if preset not in BENCH_PRESETS:  # fail fast, BEFORE the chip claim
        raise SystemExit(
            f"unknown BENCH_PRESET {preset!r}; choose from {sorted(BENCH_PRESETS)}"
        )
    if open_loop:
        return _serve_open_loop_main(preset, watchdog)
    if serve:
        return _serve_main(preset, watchdog)
    seq_len = int(os.environ.get("BENCH_SEQ_LEN", 4096))
    micro_bs = int(os.environ.get("BENCH_MICRO_BS", 4))
    steps = int(os.environ.get("BENCH_STEPS", 10))
    r = run_bench(
        seq_len, micro_bs, steps,
        # default pinned to the measured-safe impl: on the r5 relay the
        # registry auto-picks pallas_flash (the old axon platform-string
        # gate no longer matches), but Pallas EXECUTION is silicon-unproven
        # there (r1: hangs) — an auto-picked hang would watchdog-zero the
        # round-end bench. scripts/pallas_probe.py decides the flip.
        attention_impl=os.environ.get("BENCH_ATTN_IMPL", "xla_twopass") or None,
        remat_policy=os.environ.get("BENCH_REMAT", "ctx"),
        preset=preset,
        optimizer=os.environ.get("BENCH_OPT", "adamw"),
        # BENCH_ULYSSES_ASYNC=1 selects the chunked async Ulysses pipeline
        # (only meaningful with BENCH_ULYSSES_SIZE > 1 on a multi-chip claim)
        ulysses_size=int(os.environ.get("BENCH_ULYSSES_SIZE", 1)),
        ulysses_async=os.environ.get("BENCH_ULYSSES_ASYNC", "0") not in ("0", ""),
        ulysses_async_chunks=int(os.environ.get("BENCH_ULYSSES_CHUNKS", 4)),
    )
    watchdog.stop()  # before printing: the watchdog must never race the
    # real record out of a block-buffered stdout via os._exit
    print(json.dumps({
        "metric": "train_tokens_per_sec_per_chip",
        "value": round(r["tok_s_chip"], 1),
        "unit": f"tokens/s/chip ({r['preset']} bf16 {r['optimizer']}, "
                f"seq{seq_len}, mfu={r['mfu']:.1f}%)",
        "vs_baseline": round(r["mfu"] / 40.0, 4),
        # utilization trajectory: BENCH_*.json now captures where the wall
        # time went, not just the headline rate (docs/observability.md)
        "goodput_pct": round(r["goodput_pct"], 2),
        "data_wait_frac": round(r["data_wait_frac"], 4),
        "recompiles": r["recompiles"],
        # integrity trajectory (docs/resilience.md "Integrity & quarantine"):
        # nonzero quarantine/fallback counts mean the measurement ran on a
        # run that survived storage rot — worth knowing next to its MFU
        "restore_verify_s": round(r["restore_verify_s"], 4),
        "ckpt_quarantined": r["ckpt_quarantined"],
        "ckpt_fallbacks": r["ckpt_fallbacks"],
        # device cost census (docs/observability.md "Device cost &
        # capacity"): what XLA compiled, how long it took, and whether the
        # analytic MFU denominator still agrees with it (FLOPS_RATIO_BAND)
        "compile_time_s": r["compile_time_s"],
        "xla_flops_per_step": r["xla_flops_per_step"],
        "analytic_vs_xla_flops_ratio": r["analytic_vs_xla_flops_ratio"],
    }), flush=True)
    _cleanup_default_out()  # healthy exit: don't leak the per-PID /tmp dir


if __name__ == "__main__":
    main()
