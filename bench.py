"""Benchmark: text-SFT training throughput on the available chip(s).

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.
Metric: training tokens/sec/chip on a Qwen3-0.6B-class dense model (largest
of the family that fits a single v5e chip with full AdamW state); MFU is
reported alongside. vs_baseline is measured MFU / 40.0 (BASELINE.json north
star: >= 40% MFU for text SFT on TPU; no published TPU numbers exist).
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

_done = threading.Event()


def _watchdog(timeout_s: float):
    """The axon TPU tunnel can wedge its chip claim (a killed process leaves
    the grant held), after which backend init hangs indefinitely. If the
    bench can't produce a measurement in time, emit an honest zero-valued
    record pointing at the last measured numbers instead of hanging the
    driver (see BENCH_NOTES.md)."""
    if _done.wait(timeout_s):
        return
    print(json.dumps({
        "metric": "train_tokens_per_sec_per_chip",
        "value": 0,
        "unit": f"tokens/s/chip — no measurement within {int(timeout_s)}s "
                "(TPU init or run stalled); last good numbers in BENCH_NOTES.md",
        "vs_baseline": 0,
    }), flush=True)
    os._exit(3)


def main():
    threading.Thread(
        target=_watchdog,
        args=(float(os.environ.get("BENCH_WATCHDOG_S", 900)),),
        daemon=True,
    ).start()
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from veomni_tpu.models import TransformerConfig, build_foundation_model
    from veomni_tpu.optim import build_lr_scheduler, build_optimizer
    from veomni_tpu.parallel import init_parallel_state, use_parallel_state
    from veomni_tpu.train import build_train_state, build_train_step
    from veomni_tpu.train.train_step import resolve_state_shardings
    from veomni_tpu.utils.count_flops import FlopsCounter
    from veomni_tpu.utils.device import get_device_peak_flops

    n_chips = jax.device_count()
    ps = init_parallel_state()

    seq_len = int(os.environ.get("BENCH_SEQ_LEN", 4096))
    micro_bs = int(os.environ.get("BENCH_MICRO_BS", 4))
    steps = int(os.environ.get("BENCH_STEPS", 10))

    with use_parallel_state(ps):
        cfg = TransformerConfig(
            model_type="qwen3",
            vocab_size=151936,
            hidden_size=1024,
            intermediate_size=3072,
            num_hidden_layers=28,
            num_attention_heads=16,
            num_key_value_heads=8,
            head_dim=128,
            qk_norm=True,
            tie_word_embeddings=True,
            max_position_embeddings=32768,
            rope_theta=1e6,
            dtype=jnp.bfloat16,
        )
        model = build_foundation_model(config=cfg)
        plan = model.get_parallel_plan()
        opt = build_optimizer(model.abstract(), lr=build_lr_scheduler(lr=1e-4, train_steps=1000))

        def make_state(rng):
            return build_train_state(model.family.init_params(rng, cfg), opt)

        abs_state = jax.eval_shape(make_state, jax.random.PRNGKey(0))
        shardings = resolve_state_shardings(abs_state, plan, ps)
        state = jax.jit(make_state, out_shardings=shardings)(jax.random.PRNGKey(0))

        keys = ("input_ids", "labels", "position_ids", "segment_ids")
        batch_shardings = {
            k: NamedSharding(ps.mesh, P(None, ps.dp_axes, ps.sp_axes)) for k in keys
        }
        step = build_train_step(
            model.loss_fn, opt, ps,
            state_shardings=shardings, batch_shardings=batch_shardings,
        )

        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (1, micro_bs, seq_len))
        batch = {
            "input_ids": jnp.asarray(ids, jnp.int32),
            "labels": jnp.asarray(ids, jnp.int32),
            "position_ids": jnp.asarray(
                np.broadcast_to(np.arange(seq_len), ids.shape).copy(), jnp.int32
            ),
            "segment_ids": jnp.ones(ids.shape, jnp.int32),
        }
        batch = {k: jax.device_put(v, batch_shardings[k]) for k, v in batch.items()}

        # warmup (compile); NOTE: on the axon-tunneled TPU platform
        # block_until_ready does not wait for remote execution — a host
        # fetch (float()) is the only true synchronization point.
        state, metrics = step(state, batch)
        _ = float(metrics["loss"])

        t0 = time.perf_counter()
        for _ in range(steps):
            state, metrics = step(state, batch)
        _ = float(metrics["loss"])
        dt = time.perf_counter() - t0

        tokens = micro_bs * seq_len * steps
        tok_per_sec_chip = tokens / dt / n_chips
        flops = FlopsCounter.from_config(cfg).batch_flops(
            micro_bs * seq_len, seq_len
        ) * steps
        mfu = 100.0 * flops / dt / (get_device_peak_flops() * n_chips)

        _done.set()  # before printing: the watchdog must never race the
        # real record out of a block-buffered stdout via os._exit
        print(json.dumps({
            "metric": "train_tokens_per_sec_per_chip",
            "value": round(tok_per_sec_chip, 1),
            "unit": f"tokens/s/chip (qwen3-0.6B bf16, seq{seq_len}, mfu={mfu:.1f}%)",
            "vs_baseline": round(mfu / 40.0, 4),
        }), flush=True)


if __name__ == "__main__":
    main()
