"""Request/response surface of the continuous-batching inference engine.

Plain dataclasses over token ids — tokenization is the caller's concern
(scripts/serve.py shows the CLI wiring). Sampling semantics mirror
``models/decode.py``: temperature<=0 is greedy; top_k<=0 and top_p>=1 keep
the full distribution.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional


@dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling knobs (HF generate analogue). ``eos_id < 0``
    disables early stopping; ``seed`` makes a sampled request reproducible
    independent of what else shares its decode batch (per-slot PRNG keys)."""

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    max_new_tokens: int = 64
    eos_id: int = -1
    seed: int = 0


@dataclass
class Request:
    prompt_ids: List[int]
    sampling: SamplingParams = field(default_factory=SamplingParams)
    request_id: str = ""  # engine assigns "req-N" when empty
    # ---- QoS / fairness surface (docs/serving.md "QoS, fairness & overload")
    # SLO class name; must name one of the engine's configured classes
    # (EngineConfig.classes). With a single-class engine ANY label is
    # accepted into that one queue — the seed-FIFO configuration.
    priority: str = "interactive"
    # fairness key: admission round-robins across tenants inside each class
    # and per-tenant in-flight caps count against it. "" = the default
    # tenant (single-tenant deployments never need to set it).
    tenant: str = ""
    # optional end-to-end deadline in seconds from submit. A request still
    # WAITING (or still prefilling) past its deadline is cancelled — blocks
    # released, terminal finish_reason "deadline" — instead of burning pool
    # capacity on an answer nobody is waiting for. A request that finishes
    # late keeps its tokens but is marked deadline_missed (and excluded
    # from goodput_tokens_per_sec). None = no deadline.
    deadline_s: Optional[float] = None


@dataclass
class StreamEvent:
    """One generated token, emitted as soon as its decode (or prefill) step
    lands. ``index`` is the token's position in the request's generated
    stream (0 = first token after the prompt)."""

    request_id: str
    token: int
    index: int
    finished: bool = False
    finish_reason: str = ""  # "eos" | "length" when finished (cancellation
    # and rejection produce no token, hence no StreamEvent — read the
    # terminal status off RequestOutput.finish_reason)


@dataclass
class RequestOutput:
    request_id: str
    prompt_ids: List[int]
    token_ids: List[int] = field(default_factory=list)
    finished: bool = False
    # "eos" | "length" for normal completion; terminal QoS statuses:
    # "rejected"  — load-shed at submit (bounded queue / tenant cap; the
    #               429-equivalent: no tokens were ever produced),
    # "deadline"  — cancelled while waiting/prefilling past deadline_s,
    # "cancelled" — explicit InferenceEngine.cancel()
    finish_reason: str = ""
    # finished after its deadline_s elapsed (tokens kept, but the request
    # does not count toward serve.goodput_tokens_per_sec)
    deadline_missed: bool = False
    ttft_s: Optional[float] = None  # wall time submit -> first token
    # per-request lifecycle rollup (observability/request_trace.py): total
    # time spent waiting for a decode slot (initial + every post-preemption
    # re-admission wait), decode time-per-output-token, and how often the
    # scheduler preempted this request — the "why was request X slow" triple
    queue_wait_s: Optional[float] = None
    tpot_s: Optional[float] = None
    preemptions: int = 0
    # prompt positions served from the prefix cache on the LATEST admission
    # (0 with the cache off or on a cold miss) — cached_tokens/len(prompt_ids)
    # is this request's share of the engine's serve.prefix_hit_rate
    cached_tokens: int = 0
    # generated tokens that arrived as ACCEPTED speculative drafts (verify
    # steps, spec_k > 0) rather than one-token decode steps — this
    # request's share of serve.spec_accepted. 0 with speculation off.
    spec_accepted_tokens: int = 0
