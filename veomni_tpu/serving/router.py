"""Scale-out serving: prefix-affinity router over data-parallel replicas.

Everything the serving stack grew through PR 15 lives inside ONE engine
process; millions of users need N of them behind a front door. The router
is that front door: it owns the QoS admission queue (classes, tenant DRR,
queue bound, deadlines — moved UP from the engine) and dispatches over N
in-process :class:`~veomni_tpu.serving.engine.InferenceEngine` replicas
through the existing ``api.py`` Request/RequestOutput surface. Replica
engines run single-class FIFO (``classes="default"``, bounds off), so
per-request semantics on a replica stay token-exact with the bare engine.

Three pillars:

1. **Prefix-affinity routing.** The dispatch target is chosen by
   rendezvous-hashing the prompt's LEADING block-aligned chunk key — the
   same ``tuple(tokens[i*bs:(i+1)*bs])`` chunks the radix prefix cache
   keys its tree on — so shared-prefix traffic lands where its KV already
   lives, multiplying the PR 9 hit rate instead of diluting it N ways.
   Rendezvous (highest-random-weight) keeps the mapping stable when
   replicas come and go: adding or removing one replica only moves the
   keys that hash to it. Affinity yields under load pressure: when the
   target's engine queue depth reaches ``spill_queue_depth`` (or its free
   concurrent-sequence estimate drops below ``spill_min_free_seqs``) the
   request spills to the least-loaded live replica instead; when EVERY
   live replica is past the threshold the request parks at the router —
   which is exactly what makes the router-level QoS pick meaningful under
   overload (back-pressure, not blind fan-out).

2. **Health- and shed-aware dispatch.** The router's pump steps every
   replica; a replica whose ``step()`` raises (a wedged scheduler, a
   device error) is marked DEAD and drained out of rotation the same
   tick. Its stranded requests are triaged exactly-once: nothing
   streamed yet -> re-dispatched (front of the router queue, original
   arrival order) to a survivor; tokens already streamed -> terminal
   ``cancelled`` (re-running would duplicate delivered output); already
   terminal on the dead engine -> captured as-is. Nothing ever hangs.
   ``serve.router.*`` gauges/counters, ``/debug/router`` and
   ``router.*`` flight events expose all of it.

3. **Live add/remove behind versioned weights.** ``add_replica()`` spins
   up an engine that SHARES the compiled-program bundle
   (:class:`~veomni_tpu.serving.engine.SharedPrograms` — zero new
   compiles) and the latest ``publish_weights(params, version)`` payload;
   ``remove_replica()`` drains (no new dispatches, in-flight work
   finishes, outputs captured) then detaches — no lost or duplicated
   request ids. ``publish_weights`` itself performs a ROLLING in-place
   hot-swap of the running fleet (docs/serving.md "Versioned weight
   publication"): one replica at a time enters PUBLISHING — out of the
   dispatch rotation, draining its in-flight work on the OLD version
   (requests never see a mid-stream weight change) — then its engine's
   buffers are swapped in place (zero new traces: the jitted steps take
   params per call), its prefix cache flushed under a bumped cache
   epoch (stale KV from the old weights becomes unreachable, the
   no-leak block identity conserved), and it returns to rotation at the
   new version. The roll never drops the LIVE count below ``min_live``
   while a pending respawn could restore headroom; per-replica
   ``weights_version`` gauges track the mixed-version window. A replica
   that dies or wedges MID-publish is triaged by the normal failure
   path and its respawn attaches at the LATEST published version — the
   same interface the trainer hot-swap loop (ROADMAP item 4) publishes
   into, with ``publish_from_checkpoint`` refusing a corrupt generation
   behind the PR 5 integrity gate before any buffer is touched.

4. **Self-healing fleet** (docs/serving.md "Self-healing fleet"). A
   replica that *raises* dies and sheds; a replica that *hangs* — the
   TPU-relay failure mode, reproducible via the ``hang`` fault at
   ``serve.decode_tick`` — used to wedge the pump's join barrier
   forever. Now every busy replica is pumped on a worker thread behind a
   per-replica deadline (``RouterConfig.replica_stall_s``): a ``step()``
   over deadline for ``replica_stall_ticks`` consecutive router ticks
   marks the handle WEDGED, the stuck worker is abandoned behind a
   generation fence (it may still be inside XLA; its results are never
   read and its labelled metric writes are revoked) and the normal
   death triage runs — healthy replicas' tick latency is never held
   hostage. Dead/wedged replicas then RESPAWN after a deterministic
   ``resilience.retry.RetryPolicy`` backoff, attach to the shared
   program bundle (zero new compiles, same gate as ``add_replica``),
   and serve a PROBATION period — spill traffic only — before rejoining
   the rendezvous rotation; a ``max_respawns`` budget per lineage
   exhausts into loud permanent retirement. ``health()`` surfaces all
   of it for ``/healthz`` (503 below ``min_live`` — recovering, never
   sticky).

Threading contract: the router holds no locks for its own state — ONE
pump thread (the caller's) drives ``submit``/``step``/``generate``/
``run``, and each replica engine is touched by at most one thread at a
time: either the router thread (quiescent) or the single outstanding
pump worker the router started for it (``_PumpTicket``; ``Thread.join``
is the happens-before edge that publishes the worker's result back).
While a ticket is outstanding the router reads only the handle's
``last_*`` snapshots, never the engine. The only other cross-thread
surface is the debug snapshot behind ``_debug_lock`` (the exporter's
HTTP thread reads ``/debug/router`` and ``health()``) plus the
already-thread-safe metrics registry and flight recorder.
"""

from __future__ import annotations

import os
import threading
import time
import zlib
from dataclasses import dataclass, field, replace
from typing import Any, Dict, Iterable, Iterator, List, Optional, Union

from veomni_tpu.observability.fleet import write_heartbeat
from veomni_tpu.observability.flight_recorder import record as _flight_record
from veomni_tpu.observability.metrics import get_registry
from veomni_tpu.resilience.faults import fault_point
from veomni_tpu.resilience.retry import RetryPolicy
from veomni_tpu.serving.api import (
    Request,
    RequestOutput,
    SamplingParams,
    StreamEvent,
)
from veomni_tpu.serving.engine import EngineConfig, InferenceEngine
from veomni_tpu.serving.replica import (
    STATE_DEAD,
    STATE_DETACHED,
    STATE_DRAINING,
    STATE_LIVE,
    STATE_PROBATION,
    STATE_PUBLISHING,
    STATE_WEDGED,
    ReplicaHandle,
)
from veomni_tpu.serving.scheduler import QoSPicker, parse_classes
from veomni_tpu.serving.weights import (
    WeightRecord,
    WeightStore,
    load_published_params,
)
from veomni_tpu.utils.logging import get_logger

logger = get_logger(__name__)

#: numeric encoding for the per-replica ``serve.router.<rid>.state`` gauge
#: (docs/observability.md): forward transitions only ever raise the value
#: until a respawn resets it
STATE_CODES = {
    STATE_LIVE: 0,
    STATE_PROBATION: 1,
    STATE_DRAINING: 2,
    STATE_WEDGED: 3,
    STATE_DEAD: 4,
    STATE_DETACHED: 5,
    STATE_PUBLISHING: 6,  # transient: returns to live/probation post-swap
}


@dataclass
class RouterConfig:
    """Router-level knobs (the engine keeps its own via EngineConfig)."""

    # initial replica count (grow/shrink live via add/remove_replica)
    replicas: int = 2
    # leading FULL blocks of the prompt hashed into the affinity key —
    # mirrors the radix cache's block-aligned chunk keys, so requests
    # sharing a system prompt share a key. Prompts shorter than one block
    # key on the whole prompt.
    affinity_blocks: int = 2
    # affinity yields when the target replica's engine queue depth reaches
    # this; when EVERY live replica is past it, requests park at the
    # router (back-pressure). 0 disables spill AND parking (pure affinity).
    spill_queue_depth: int = 4
    # affinity also yields when the target's free concurrent-sequence
    # estimate (the serve.kv_free_concurrent_seqs signal) drops below
    # this. 0 disables the capacity leg.
    spill_min_free_seqs: int = 0
    # QoS at the front door. None inherits the corresponding EngineConfig
    # field, so an engine-tuned deployment routes identically.
    classes: Optional[str] = None
    queue_bound: Optional[int] = None
    tenant_max_inflight: Optional[int] = None
    # --- self-healing fleet (docs/serving.md "Self-healing fleet") ---
    # per-replica pump deadline: a step() still running after this many
    # seconds counts one stall strike per router tick, and
    # replica_stall_ticks consecutive strikes mark the replica WEDGED
    # (detection latency <= replica_stall_s + one tick). 0 disables wedge
    # detection and keeps the legacy unbounded-join pump.
    replica_stall_s: float = 60.0
    replica_stall_ticks: int = 2
    # respawn budget per replica lineage (0 disables resurrection);
    # attempts are spaced by the deterministic retry.RetryPolicy backoff
    # (base * 2**attempt, capped — no jitter, so recovery timelines are
    # reproducible in tests and chaos replays)
    max_respawns: int = 2
    respawn_backoff_s: float = 0.5
    respawn_backoff_max_s: float = 30.0
    # clean completions (eos/length) a respawned replica must serve —
    # spill traffic only, never an affinity target — before it rejoins
    # the rendezvous rotation. 0 respawns straight to live.
    probation_requests: int = 2
    # health() reports healthy=False (the exporter serves HTTP 503) while
    # fewer than this many replicas are LIVE; recovering, not sticky
    min_live: int = 1
    # pump workers drop throttled heartbeat-<rid>.json files here so a
    # wedged replica is diagnosable from OUTSIDE the process
    # (scripts/fleet.py timeline); "" disables
    heartbeat_dir: str = ""

    def __post_init__(self):
        if self.replicas < 1:
            raise ValueError("need at least one replica")
        if self.affinity_blocks < 1:
            raise ValueError("affinity_blocks must be >= 1")
        if self.spill_queue_depth < 0:
            raise ValueError("spill_queue_depth must be >= 0 (0 disables)")
        if self.spill_min_free_seqs < 0:
            raise ValueError("spill_min_free_seqs must be >= 0 (0 disables)")
        if self.replica_stall_s < 0:
            raise ValueError("replica_stall_s must be >= 0 (0 disables)")
        if self.replica_stall_ticks < 1:
            raise ValueError("replica_stall_ticks must be >= 1")
        if self.max_respawns < 0:
            raise ValueError("max_respawns must be >= 0 (0 disables)")
        if self.respawn_backoff_s < 0 or self.respawn_backoff_max_s < 0:
            raise ValueError("respawn backoff delays must be >= 0")
        if self.probation_requests < 0:
            raise ValueError("probation_requests must be >= 0 (0 skips)")
        if self.min_live < 0:
            raise ValueError("min_live must be >= 0")


@dataclass
class _RouterItem:
    """Router-side bookkeeping for one accepted request."""

    request: Request
    class_idx: int  # QoSPicker duck-type field
    order: int  # arrival sequence number (re-dispatch keeps this order)
    submit_time: float = field(default_factory=time.perf_counter)
    phase: str = "queued"  # queued -> dispatched -> done
    replica: str = ""  # rid while dispatched

    @property
    def tenant(self) -> str:  # QoSPicker duck-type field
        return getattr(self.request, "tenant", "")


class _PumpTicket:
    """One in-flight ``engine.step()`` on a worker thread.

    Created and joined by the router's pump thread; the worker writes
    ``result`` then exits, and ``Thread.join`` is the happens-before edge
    that publishes the result back. While a ticket is outstanding the
    replica's engine belongs to the worker — every router-side read goes
    through the handle's ``last_*`` snapshots instead.

    ``generation`` snapshots the handle's fence at start. If the router
    abandons this ticket (the replica wedged, was killed mid-stall, or
    respawned), the dropped ticket reference means the zombie's result is
    never read, the bumped handle generation invalidates any late match,
    and the engine's revoked metrics view drops its late labelled writes
    — the zombie may still be inside XLA, and none of that matters.
    """

    def __init__(self, handle: ReplicaHandle, heartbeat_dir: str = ""):
        self.handle = handle
        self.generation = handle.generation
        self.heartbeat_dir = heartbeat_dir
        self.started = time.perf_counter()
        self.thread: Optional[threading.Thread] = None
        self.result: Any = ("ok", [])

    def run(self) -> None:
        h = self.handle
        if self.heartbeat_dir:
            # throttled liveness beat BEFORE the step: a wedged step
            # leaves the file aging, which is exactly what makes the
            # wedge diagnosable from outside the process
            # (scripts/fleet.py; docs/observability.md heartbeats)
            now = time.monotonic()
            if now - h.last_beat >= 1.0:
                h.last_beat = now
                write_heartbeat(
                    self.heartbeat_dir, rank=h.rid,
                    global_step=h.pumped_ticks, phase="serve_pump",
                    extra={"replica": h.rid, "state": h.state,
                           "generation": self.generation},
                )
        try:
            self.result = ("ok", h.engine.step())
        except Exception as e:  # noqa: BLE001 — triaged post-join
            self.result = ("dead", e)
        h.pumped_ticks += 1


class Router:
    """Front door over N in-process engine replicas."""

    def __init__(self, params, cfg, engine_config: Optional[EngineConfig] = None,
                 config: Optional[RouterConfig] = None):
        self.engine_config = engine_config or EngineConfig()
        self.config = config or RouterConfig()
        ec, rc = self.engine_config, self.config
        # QoS moves UP to the router: the front door runs the class/tenant
        # pick and the admission bounds; replicas run single-class FIFO
        # with bounds off so their per-request semantics stay token-exact.
        classes_spec = rc.classes if rc.classes is not None else ec.classes
        self.qos = QoSPicker(parse_classes(classes_spec))
        self.queue_bound = (
            rc.queue_bound if rc.queue_bound is not None else ec.queue_bound
        )
        self.tenant_max_inflight = (
            rc.tenant_max_inflight if rc.tenant_max_inflight is not None
            else ec.tenant_max_inflight
        )
        # versioned weights: every spawn (boot, add_replica, respawn)
        # reads the store's LATEST record, so a replica resurrected after
        # a publish attaches at the new version, never the boot payload
        self._weights = WeightStore(params, "v0")
        self._cfg = cfg
        self.replicas: Dict[str, ReplicaHandle] = {}
        self.retired: List[ReplicaHandle] = []
        self._next_rid = 0
        self._programs = None  # SharedPrograms, built by the first replica
        for _ in range(rc.replicas):
            self._spawn_replica()
        # request bookkeeping: arrival-ordered router queue + id -> item
        self._items: Dict[str, _RouterItem] = {}
        self._queue: List[_RouterItem] = []
        self._outputs: Dict[str, RequestOutput] = {}
        self._req_counter = 0
        self._order_counter = 0
        # router-local outcome totals (metrics() mirrors the engine's keys)
        self._rejected_total = 0
        self._shed_tokens_total = 0
        self._deadline_cancelled_total = 0
        self._spill_total = 0
        self._redispatch_total = 0
        self._wedged_total = 0
        self._respawn_total = 0
        self._probation_total = 0
        self._publish_total = 0
        # self-healing scheduler state: pending respawns (due-dated by the
        # deterministic backoff), the per-lineage budget ledger, and the
        # lineages that exhausted it (permanently retired)
        self._respawn_policy = RetryPolicy(
            retries=max(0, rc.max_respawns),
            base_delay_s=rc.respawn_backoff_s,
            max_delay_s=max(rc.respawn_backoff_s, rc.respawn_backoff_max_s),
        )
        self._pending_respawns: List[Dict[str, Any]] = []
        self._lineage_respawns: Dict[str, int] = {}
        self._retired_lineages: set = set()
        # router-level observability (docs/observability.md):
        self._reg = get_registry()
        self._m_requests = self._reg.counter("serve.router.requests")
        self._m_dispatched = self._reg.counter("serve.router.dispatched")
        self._m_redispatched = self._reg.counter("serve.router.redispatched")
        self._m_spills = self._reg.counter("serve.router.spills")
        self._m_rejected = self._reg.counter("serve.router.rejected")
        self._m_deadline = self._reg.counter("serve.router.deadline_cancelled")
        self._m_wedged = self._reg.counter("serve.router.wedged")
        self._m_respawns = self._reg.counter("serve.router.respawns")
        self._m_probation = self._reg.counter("serve.router.probation")
        self._m_publishes = self._reg.counter("serve.router.publishes")
        self._m_publish_gauge = self._reg.gauge(
            "serve.router.publish_in_progress")
        self._m_live = self._reg.gauge("serve.router.replicas_live")
        self._m_queue = self._reg.gauge("serve.router.queue_depth")
        self._m_hit_rate = self._reg.gauge("serve.router.prefix_hit_rate")
        # cross-thread debug snapshot: the exporter's HTTP thread reads
        # /debug/router while the pump writes — the ONLY router state that
        # crosses threads, refreshed at the end of every step()
        self._debug_lock = threading.Lock()
        self._debug_doc: Dict[str, Any] = {}  # guarded-by: _debug_lock
        self._publish_gauges()

    # ------------------------------------------------------------- replicas
    def _spawn_replica(self, rid: Optional[str] = None,
                       state: str = STATE_LIVE, generation: int = 0,
                       lineage: str = "") -> ReplicaHandle:
        if rid is None:
            rid = f"r{self._next_rid}"
            self._next_rid += 1
        # resurrection fault drill (docs/resilience.md ``serve.spawn``): an
        # exception here during a respawn burns one budget attempt
        fault_point("serve.spawn")
        # replicas run single-class FIFO with the bounds off — QoS lives at
        # the router — and carry their rid as the metrics instance label.
        # A respawned replica REUSES its ancestor's rid: the metric series
        # continues, and the generation fence (plus the ancestor's revoked
        # registry view) keeps the zombie's late writes out of it.
        rcfg = replace(
            self.engine_config, classes="default", queue_bound=0,
            tenant_max_inflight=0, metrics_label=rid,
        )
        eng = InferenceEngine(self._params, self._cfg, rcfg,
                              programs=self._programs)
        if self._programs is None:
            self._programs = eng.programs
        h = ReplicaHandle(rid=rid, engine=eng, state=state,
                          generation=generation, lineage=lineage or rid,
                          weights_version=self._weights_version)
        self.replicas[rid] = h
        return h

    def _schedule_respawn(self, *, rid: str, lineage: str, generation: int,
                          fail_reason: str = "") -> None:
        """Book a resurrection attempt for a dead/wedged lineage, spaced
        by the deterministic backoff; a lineage past ``max_respawns`` is
        permanently retired instead — loudly, because from here only an
        operator ``add_replica()`` restores the lost capacity."""
        rc = self.config
        if rc.max_respawns <= 0:
            return
        used = self._lineage_respawns.get(lineage, 0)
        if used >= rc.max_respawns:
            if lineage not in self._retired_lineages:
                self._retired_lineages.add(lineage)
                logger.error(
                    "router: replica %s exhausted its respawn budget "
                    "(%d/%d) and is PERMANENTLY retired — fleet capacity "
                    "stays reduced until an operator adds a replica "
                    "(last failure: %s)",
                    lineage, used, rc.max_respawns, fail_reason or "n/a")
                _flight_record("router.replica_retired", cid=lineage,
                               respawns=used,
                               last_error=fail_reason[:160])
            return
        delay = self._respawn_policy.delay(used)
        self._lineage_respawns[lineage] = used + 1
        self._pending_respawns.append({
            "rid": rid, "lineage": lineage, "generation": generation,
            "attempt": used + 1, "delay_s": delay,
            "due": time.perf_counter() + delay,
        })
        logger.warning(
            "router: replica %s will respawn in %.3gs (attempt %d/%d)",
            rid, delay, used + 1, rc.max_respawns)

    def _maybe_respawn(self) -> None:
        """Land every due respawn: a fresh engine attached to the shared
        program bundle (zero new traces — the same compile-count gate as
        ``add_replica``), same rid, bumped generation, entering PROBATION
        (spill traffic only) unless probation is disabled. A spawn that
        raises (the ``serve.spawn`` fault drill, an allocator error)
        burns the attempt and reschedules."""
        if not self._pending_respawns:
            return
        now = time.perf_counter()
        for p in [p for p in self._pending_respawns if p["due"] <= now]:
            self._pending_respawns.remove(p)
            state = (STATE_PROBATION if self.config.probation_requests > 0
                     else STATE_LIVE)
            try:
                h = self._spawn_replica(rid=p["rid"], state=state,
                                        generation=p["generation"],
                                        lineage=p["lineage"])
            except Exception as e:  # noqa: BLE001 — a failed respawn must
                # not take down the healthy fleet driving this pump
                logger.warning("router: respawn of replica %s failed (%s)",
                               p["rid"], e)
                self._schedule_respawn(rid=p["rid"], lineage=p["lineage"],
                                       generation=p["generation"],
                                       fail_reason=repr(e))
                continue
            self._respawn_total += 1
            self._m_respawns.inc()
            _flight_record("router.replica_respawned", cid=h.rid,
                           generation=h.generation, attempt=p["attempt"],
                           state=h.state)
            logger.warning(
                "router: replica %s respawned (generation %d, %s, "
                "attempt %d/%d)", h.rid, h.generation, h.state,
                p["attempt"], self.config.max_respawns)
            self._publish_gauges()

    def add_replica(self) -> ReplicaHandle:
        """Grow the fleet by one live replica. The new engine shares the
        compiled-program bundle (zero new traces/compiles — pinned by the
        router compile-count gate) and serves the LATEST published
        weights version."""
        h = self._spawn_replica()
        _flight_record("router.replica_added", cid=h.rid,
                       weights_version=h.weights_version)
        self._publish_gauges()
        return h

    def remove_replica(self, rid: str) -> ReplicaHandle:
        """Begin a clean drain: the replica leaves the dispatch rotation
        immediately, finishes everything already dispatched to it, and
        detaches once drained (no lost or duplicated requests). Refuses to
        drain the LAST live replica — a router with work and nowhere to
        send it would stall."""
        h = self.replicas[rid]
        if h.state != STATE_LIVE:
            raise ValueError(f"replica {rid!r} is {h.state}, not live")
        if sum(1 for o in self.replicas.values()
               if o.state == STATE_LIVE) <= 1:
            raise ValueError("cannot remove the last live replica")
        h.state = STATE_DRAINING
        _flight_record("router.replica_draining", cid=rid,
                       assigned=len(h.assigned))
        self._publish_gauges()
        return h

    def kill_replica(self, rid: str, reason: str = "killed") -> None:
        """Simulate a replica crash (tests, the bench's mid-storm kill
        drill): the replica is drained out of rotation exactly as if its
        pump had raised — stranded requests re-dispatched or surfaced
        terminal, never hung."""
        self._on_replica_failure(self.replicas[rid], RuntimeError(reason))

    # ------------------------------------------------------ weight publish
    def publish_weights(self, params, version: str) -> str:
        """Publish a new weights payload under a version tag and roll it
        into the RUNNING fleet (docs/serving.md "Versioned weight
        publication"). The payload lands in the :class:`WeightStore`
        immediately — replicas spawned from now on (``add_replica``,
        respawns) serve it — and ``step()`` then rolls the existing
        fleet one replica at a time: PUBLISHING (out of rotation) ->
        drain in-flight work on the old version -> in-place buffer swap
        + prefix-cache flush under a bumped cache epoch -> back to
        rotation at the new version. Zero new traces across the whole
        drain->swap->rotation window; the LIVE count never drops below
        ``min_live`` while waiting could restore headroom. An idle fleet
        converges on the caller's next ``step()``/``run()`` drive
        (``has_work`` stays True until every serving replica is on the
        latest version). Duplicate version tags are refused — tags are
        immutable once published."""
        rec = self._weights.put(str(version), params)
        self._publish_total += 1
        self._m_publishes.inc()
        _flight_record("router.weights_published", cid=rec.version,
                       seq=rec.seq)
        logger.info(
            "router: weights %s published (seq %d); rolling %d serving "
            "replica(s)", rec.version, rec.seq,
            sum(1 for h in self.replicas.values()
                if h.state in (STATE_LIVE, STATE_PROBATION)))
        self._publish_gauges()
        return rec.version

    def publish_from_checkpoint(self, step_dir: str, loader,
                                *, version: Optional[str] = None,
                                verify_mode: str = "size") -> str:
        """``publish_weights`` from a checkpoint generation, behind the
        PR 5 integrity gate: an uncommitted directory or a manifest that
        fails verification raises ``CheckpointCorruptError`` BEFORE
        ``loader`` materializes a single byte — no replica buffer is
        ever touched by a corrupt generation. ``version`` defaults to
        the generation's directory name (e.g. ``step_000400``)."""
        params = load_published_params(step_dir, loader,
                                       verify_mode=verify_mode)
        if version is None:
            version = os.path.basename(os.path.normpath(step_dir))
        return self.publish_weights(params, version)

    @property
    def _params(self):
        """Latest published params — what every new spawn attaches to."""
        return self._weights.latest.params

    @property
    def _weights_version(self) -> str:
        return self._weights.latest.version

    @property
    def weights_version(self) -> str:
        return self._weights_version

    @property
    def publish_in_progress(self) -> bool:
        """True while any SERVING replica (live/probation/publishing) is
        not yet on the latest published version — the mixed-version
        window. Draining replicas finish on their version and detach;
        they never hold a publish open."""
        latest = self._weights.latest.version
        return any(
            h.state == STATE_PUBLISHING or h.weights_version != latest
            for h in self.replicas.values()
            if h.state in (STATE_LIVE, STATE_PROBATION, STATE_PUBLISHING)
        )

    def _advance_publish(self) -> None:
        """One rolling-publish step, run at the top of every ``step()``:
        complete any PUBLISHING replica that has drained (swap + flush +
        return to rotation), then move at most ONE stale serving replica
        into PUBLISHING — one at a time keeps the out-of-rotation window
        minimal and the live floor honest."""
        latest = self._weights.latest
        publishing = [h for h in self.replicas.values()
                      if h.state == STATE_PUBLISHING]
        for h in publishing:
            if (h.pump is None and not h.engine.has_work
                    and not h.assigned):
                self._swap_replica(h, latest)
        if any(h.state == STATE_PUBLISHING
               for h in self.replicas.values()):
            return  # one replica out of rotation at a time
        stale = [h for h in self.replicas.values()
                 if h.state in (STATE_LIVE, STATE_PROBATION)
                 and h.weights_version != latest.version]
        if not stale:
            return
        # probation replicas first (they are outside the live rotation —
        # no floor impact), then the least-loaded live replica (shortest
        # drain); rid tiebreak keeps the roll deterministic
        stale.sort(key=lambda h: (h.state != STATE_PROBATION,
                                  len(h.assigned) + h.queue_depth(),
                                  h.rid))
        h = stale[0]
        if h.state == STATE_LIVE:
            n_live = sum(1 for o in self.replicas.values()
                         if o.state == STATE_LIVE)
            if (n_live - 1 < self.config.min_live
                    and self._pending_respawns):
                # taking this replica would breach min_live and a pending
                # respawn could still restore headroom: wait for it. With
                # nothing pending, waiting cannot help — the roll
                # proceeds (briefly under the floor) because holding the
                # fleet on stale weights forever is the worse failure.
                return
        h.publish_from_state = h.state
        h.publish_to = latest.version
        h.state = STATE_PUBLISHING
        _flight_record("router.publish_replica", cid=h.rid,
                       prev=h.weights_version, to=latest.version,
                       assigned=len(h.assigned))
        logger.info(
            "router: replica %s PUBLISHING %s -> %s (%d in-flight to "
            "drain)", h.rid, h.weights_version, latest.version,
            len(h.assigned))
        # already drained (idle replica): swap within the same tick — the
        # out-of-rotation window closes before dispatch even runs
        if h.pump is None and not h.engine.has_work and not h.assigned:
            self._swap_replica(h, latest)

    def _swap_replica(self, h: ReplicaHandle, rec: WeightRecord) -> None:
        """In-place hot-swap of a drained PUBLISHING replica's engine:
        the ``serve.publish`` fault point fires first (the deterministic
        kill-mid-publish drill), then the engine swaps buffers and
        flushes its prefix cache under a bumped cache epoch. A swap that
        raises is a replica failure — the normal triage runs and the
        respawn attaches at the LATEST version, so the fleet still
        converges to exactly one version."""
        t0 = time.perf_counter()
        try:
            fault_point("serve.publish", context=h.rid)
            info = h.engine.swap_weights(rec.params)
        except Exception as e:  # noqa: BLE001 — a publish casualty is a
            # replica casualty: triaged, respawned at the new version
            logger.warning(
                "router: replica %s died mid-publish (%s); its respawn "
                "attaches at %s", h.rid, e, rec.version)
            self._on_replica_failure(h, e)
            return
        prev = h.weights_version
        h.weights_version = rec.version
        h.state = h.publish_from_state or STATE_LIVE
        h.publish_from_state = ""
        h.publish_to = ""
        self._reg.gauge(f"serve.router.{h.rid}.weights_version").set(
            self._weights.seq(rec.version))
        _flight_record("router.publish_swapped", cid=h.rid,
                       prev=prev, to=rec.version,
                       flushed_blocks=info["flushed_blocks"],
                       cache_epoch=info["cache_epoch"],
                       wall_s=round(time.perf_counter() - t0, 6))
        logger.info(
            "router: replica %s swapped %s -> %s (%d cached blocks "
            "flushed, cache epoch %d); back in rotation", h.rid, prev,
            rec.version, info["flushed_blocks"], info["cache_epoch"])
        if not self.publish_in_progress:
            _flight_record("router.publish_done", cid=rec.version,
                           replicas=len(self.replicas))
            logger.info("router: fleet converged on weights %s",
                        rec.version)

    def live_replicas(self) -> List[ReplicaHandle]:
        return [h for h in self.replicas.values() if h.state == STATE_LIVE]

    # ---------------------------------------------------------------- intake
    def submit(self, request: Union[Request, Iterable[int]],
               sampling: Optional[SamplingParams] = None) -> str:
        """Enqueue a request at the front door. Validation mirrors
        ``InferenceEngine.submit`` exactly (malformed raises, overloaded
        load-sheds to a terminal ``rejected`` output) so a single-replica
        router is behavior-identical to the bare engine."""
        ec = self.engine_config
        if not isinstance(request, Request):
            request = Request(prompt_ids=[int(t) for t in request],
                              sampling=sampling or SamplingParams())
        if not request.request_id:
            while f"req-{self._req_counter}" in self._items:
                self._req_counter += 1
            request.request_id = f"req-{self._req_counter}"
            self._req_counter += 1
        if request.request_id in self._items:
            raise ValueError(f"duplicate request id {request.request_id!r}")
        if not request.prompt_ids:
            raise ValueError("empty prompt")
        sp = request.sampling
        if sp.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = len(request.prompt_ids) + sp.max_new_tokens
        if total > ec.max_model_len:
            raise ValueError(
                f"prompt+max_new_tokens={total} exceeds max_model_len="
                f"{ec.max_model_len}"
            )
        blocks_needed = -(-total // ec.block_size)
        if blocks_needed > ec.num_blocks - 1:
            raise ValueError(
                f"request needs {blocks_needed} blocks; each replica pool "
                f"has {ec.num_blocks - 1}"
            )
        if request.deadline_s is not None and request.deadline_s < 0:
            raise ValueError("deadline_s must be >= 0 (None disables)")
        # unknown priority class raises BEFORE anything registers —
        # malformed is an error, overloaded is an outcome
        class_idx = self.qos.resolve_class(
            getattr(request, "priority", "interactive")
        )
        item = _RouterItem(request=request, class_idx=class_idx,
                           order=self._order_counter)
        self._order_counter += 1
        self._m_requests.inc()
        # front-door admission control: the waiting population is the
        # router queue PLUS every pumped engine's waiting queue, so with
        # one replica the bound sheds exactly when the bare engine would
        if (self.queue_bound
                and self._total_waiting() >= self.queue_bound) or (
                self.tenant_max_inflight
                and self._tenant_inflight(item.tenant)
                >= self.tenant_max_inflight):
            out = RequestOutput(
                request_id=request.request_id,
                prompt_ids=list(request.prompt_ids),
            )
            out.finished = True
            out.finish_reason = "rejected"
            item.phase = "done"
            self._items[request.request_id] = item
            self._outputs[request.request_id] = out
            self._rejected_total += 1
            self._shed_tokens_total += total
            self._m_rejected.inc()
            _flight_record("router.rejected", cid=request.request_id)
            return request.request_id
        self._items[request.request_id] = item
        self._queue.append(item)
        return request.request_id

    def _total_waiting(self) -> int:
        return len(self._queue) + sum(
            h.queue_depth() for h in self.replicas.values() if h.pumpable
        )

    def _tenant_inflight(self, tenant: str) -> int:
        return sum(1 for it in self._items.values()
                   if it.phase != "done" and it.tenant == tenant)

    # -------------------------------------------------------------- affinity
    def _affinity_key(self, prompt_ids) -> int:
        """crc32 over the prompt's leading block-aligned chunk keys — the
        exact ``tuple(tokens[i*bs:(i+1)*bs])`` chunks the radix cache keys
        its tree on, so two prompts that would share cache blocks share an
        affinity key. Prompts shorter than one block key on the whole
        prompt (they can't share full blocks anyway)."""
        bs = self.engine_config.block_size
        n = min(self.config.affinity_blocks, len(prompt_ids) // bs)
        if n <= 0:
            chunks: Any = tuple(int(t) for t in prompt_ids)
        else:
            chunks = tuple(
                tuple(int(t) for t in prompt_ids[i * bs:(i + 1) * bs])
                for i in range(n)
            )
        return zlib.crc32(repr(chunks).encode())

    def _affinity_target(self, key: int,
                         live: List[ReplicaHandle]) -> ReplicaHandle:
        """Rendezvous (highest-random-weight) hash: stable under replica
        add/remove — only keys owned by a departing replica move."""
        return max(live, key=lambda h: (
            zlib.crc32(f"{key}:{h.rid}".encode()), h.rid,
        ))

    def _past_threshold(self, h: ReplicaHandle) -> bool:
        rc = self.config
        if rc.spill_queue_depth and h.queue_depth() >= rc.spill_queue_depth:
            return True
        if (rc.spill_min_free_seqs
                and h.free_concurrent_seqs() < rc.spill_min_free_seqs):
            return True
        return False

    # ---------------------------------------------------------------- pump
    @property
    def has_work(self) -> bool:
        # an unconverged publish IS work: generate()/run() keep stepping
        # until every serving replica swapped to the latest version, so a
        # publish into an idle fleet still completes on the next drive
        return bool(self._queue) or self.publish_in_progress or any(
            (h.pump is not None or h.engine.has_work or h.assigned)
            for h in self.replicas.values() if h.pumpable
        )

    def step(self) -> List[StreamEvent]:
        """One router tick: land due respawns, expire queued deadlines,
        dispatch under the QoS pick + affinity/spill policy, pump every
        busy replica one engine tick behind the ``replica_stall_s``
        deadline (a raising replica dies, a hanging one WEDGES and is
        abandoned — either way survivors shed, never hang), capture
        finished outputs, detach drained replicas, and refresh gauges +
        the /debug/router snapshot."""
        self._maybe_respawn()
        self._advance_publish()
        self._expire_deadlines()
        self._dispatch()
        events: List[StreamEvent] = []
        stall_s = self.config.replica_stall_s
        pump = [h for h in self.replicas.values() if h.pumpable]
        if stall_s > 0:
            events.extend(self._pump_fenced(pump, stall_s))
        else:
            events.extend(self._pump_legacy(pump))
        for h in pump:
            # skip replicas that died/wedged this tick, and replicas whose
            # pump worker is still running (their engine is untouchable
            # until the ticket resolves)
            if self.replicas.get(h.rid) is h and h.engine_quiescent:
                self._capture_finished(h)
        self._detach_drained()
        self._publish_gauges()
        if (not events and self._pending_respawns
                and not any(h.pump is not None or h.engine.has_work
                            for h in self.replicas.values() if h.pumpable)):
            # idle fleet waiting out a respawn backoff: a generate()/run()
            # caller spins on has_work, so nap toward the next due time
            # instead of burning a core
            wait = (min(p["due"] for p in self._pending_respawns)
                    - time.perf_counter())
            if wait > 0:
                time.sleep(min(wait, 0.005))
        return events

    def _pump_fenced(self, pump: List[ReplicaHandle],
                     stall_s: float) -> List[StreamEvent]:
        """Pump every busy replica on a worker thread behind a
        per-replica join deadline. Each engine is still touched by
        exactly one thread at a time — its single outstanding worker,
        with ``join`` as the read-back barrier — so the engine's
        single-pump-thread contract holds per replica while the jitted
        steps (which release the GIL) overlap; this is where the
        aggregate throughput scaling comes from. A worker that blows the
        deadline leaves its ticket outstanding (the replica is skipped by
        dispatch/capture/gauges until it resolves) and collects one stall
        strike per router tick; ``replica_stall_ticks`` strikes wedge the
        replica and abandon the worker behind the generation fence."""
        events: List[StreamEvent] = []
        tickets: List[ReplicaHandle] = []
        for h in pump:
            if h.pump is not None:
                tickets.append(h)  # outstanding from a previous tick
                continue
            if not h.engine.has_work:
                continue
            t = _PumpTicket(h, self.config.heartbeat_dir)
            h.pump = t
            t.thread = threading.Thread(
                target=t.run, name=f"router-pump-{h.rid}", daemon=True)
            t.thread.start()
            tickets.append(h)
        for h in tickets:
            t = h.pump
            remaining = stall_s - (time.perf_counter() - t.started)
            t.thread.join(max(0.0, remaining))
            if t.thread.is_alive():
                # over its deadline: one strike per router tick, so a
                # wedge is declared within replica_stall_s + one tick
                h.stall_ticks += 1
                if h.stall_ticks >= self.config.replica_stall_ticks:
                    self._on_replica_wedged(h)
                continue
            h.pump = None
            h.stall_ticks = 0
            if t.generation != h.generation:
                continue  # fenced: the handle moved on while this ran
            kind, val = t.result
            if kind == "ok":
                events.extend(val)
            else:
                self._on_replica_failure(h, val)
        return events

    def _pump_legacy(self, pump: List[ReplicaHandle]) -> List[StreamEvent]:
        """The pre-self-healing pump (``replica_stall_s=0`` opts out of
        wedge detection): inline for a single busy replica, concurrent
        workers behind an UNBOUNDED join barrier otherwise."""
        events: List[StreamEvent] = []
        busy = [h for h in pump if h.engine.has_work]
        if len(busy) == 1:
            h = busy[0]
            try:
                events.extend(h.engine.step())
            except Exception as e:  # noqa: BLE001 — a replica failure
                # must shed to survivors, not take the router down
                self._on_replica_failure(h, e)
        elif busy:
            results: Dict[str, Any] = {}

            def _pump_one(handle: ReplicaHandle) -> None:
                try:
                    results[handle.rid] = ("ok", handle.engine.step())
                except Exception as e:  # noqa: BLE001 — triaged post-join
                    results[handle.rid] = ("dead", e)

            threads = [
                threading.Thread(target=_pump_one, args=(h,),
                                 name=f"router-pump-{h.rid}", daemon=True)
                for h in busy
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for h in busy:
                kind, val = results[h.rid]
                if kind == "ok":
                    events.extend(val)
                else:
                    self._on_replica_failure(h, val)
        return events

    def generate(self, requests: Optional[Iterable] = None
                 ) -> Iterator[StreamEvent]:
        """Streaming interface mirroring the engine's: submit, then yield
        token events (from every replica) until all in-flight work
        drains. More requests may be ``submit()``-ed between yields."""
        for r in requests or ():
            self.submit(r)
        while self.has_work:
            yield from self.step()

    def run(self, requests: Optional[Iterable] = None
            ) -> Dict[str, RequestOutput]:
        """Drain ``generate()`` and hand over every terminal output,
        releasing router bookkeeping for them (same ownership contract as
        ``InferenceEngine.run``)."""
        for _ in self.generate(requests):
            pass
        done = dict(self._outputs)
        for rid in done:
            self._outputs.pop(rid, None)
            self._items.pop(rid, None)
        return done

    def pop_output(self, request_id: str) -> Optional[RequestOutput]:
        """Release and return one finished request's output; refuses while
        it is still in flight anywhere in the fleet."""
        item = self._items.get(request_id)
        if item is not None and item.phase != "done":
            raise ValueError(f"request {request_id!r} is still in flight")
        self._items.pop(request_id, None)
        return self._outputs.pop(request_id, None)

    def cancel(self, request_id: str, reason: str = "cancelled") -> bool:
        """Cancel wherever the request currently is: parked at the router
        (terminal output synthesized here) or dispatched (delegated to the
        owning engine, output captured immediately). False for unknown or
        already-finished ids."""
        item = self._items.get(request_id)
        if item is None or item.phase == "done":
            return False
        if item.phase == "queued":
            self._queue.remove(item)
            out = RequestOutput(
                request_id=request_id,
                prompt_ids=list(item.request.prompt_ids),
            )
            out.finished = True
            out.finish_reason = reason
            self._finish_item(item, out)
            return True
        h = self.replicas.get(item.replica)
        # a replica mid-stall (outstanding pump ticket) is untouchable —
        # the cancel would race its worker inside the engine; callers see
        # False and may retry after the ticket resolves or the wedge triage
        # surfaces the request terminally
        if h is None or not h.engine_quiescent:
            return False
        if not h.engine.cancel(request_id, reason):
            return False
        self._capture_finished(h)
        return True

    # ------------------------------------------------------------- internals
    def _expire_deadlines(self) -> None:
        """Expire ROUTER-queued requests past their deadline (terminal
        ``deadline`` status) — the engine expires what was dispatched to
        it, with the clock backdated to router intake so the two waits
        add up to one deadline."""
        now = time.perf_counter()
        for item in [it for it in self._queue
                     if it.request.deadline_s is not None
                     and (now - it.submit_time) > it.request.deadline_s]:
            self._queue.remove(item)
            out = RequestOutput(
                request_id=item.request.request_id,
                prompt_ids=list(item.request.prompt_ids),
            )
            out.finished = True
            out.finish_reason = "deadline"
            out.deadline_missed = True
            self._deadline_cancelled_total += 1
            self._shed_tokens_total += (
                len(item.request.prompt_ids)
                + item.request.sampling.max_new_tokens
            )
            self._m_deadline.inc()
            _flight_record("router.deadline", cid=item.request.request_id)
            self._finish_item(item, out)

    def _dispatch(self) -> None:
        # a replica with an outstanding pump ticket is untouchable until
        # the ticket resolves — its engine belongs to the worker thread
        live = [h for h in self.live_replicas() if h.pump is None]
        probation = [h for h in self.replicas.values()
                     if h.state == STATE_PROBATION and h.pump is None]
        if not live and not probation:
            if (self._queue and not self._pending_respawns
                    and not any(
                        h.pump is not None or h.engine.has_work or h.assigned
                        for h in self.replicas.values() if h.pumpable)):
                # nothing can ever serve the queue again — surface every
                # queued request as a terminal REJECTED output first (a
                # generate()/run() caller must never block forever on a
                # request that can no longer be served), THEN fail loudly,
                # mirroring the engine's scheduler-stall invariant
                self._reject_stranded_queue()
                raise RuntimeError(
                    "router stalled: requests queued but no live replicas"
                )
            # draining replicas may still finish their work, and a pending
            # respawn may restore capacity — the pump waits, never stalls
            return
        # probation replicas receive ONLY spill traffic: the rendezvous
        # target set is the live rotation, and probation capacity shows up
        # as a spill destination / parking headroom. A fleet reduced to
        # probation-only dispatches to it directly — serving on an
        # unproven replica beats stalling the queue.
        targets = live or probation
        pool = live + probation
        while self._queue:
            # park at the router when every live+probation replica is past
            # the spill threshold AND the fleet is actually busy —
            # back-pressure makes the router-level QoS pick decide who
            # goes next. An idle fleet always accepts (a threshold below
            # the idle capacity must never stall an empty router).
            busy = (any(h.engine.has_work for h in pool)
                    or any(h.pump is not None
                           for h in self.replicas.values()))
            if busy and all(self._past_threshold(h) for h in pool):
                break
            item = self.qos.pick(self._queue)
            key = self._affinity_key(item.request.prompt_ids)
            target = self._affinity_target(key, targets)
            if self._past_threshold(target):
                spilled = min(pool, key=lambda h: (h.queue_depth(), h.rid))
                if spilled.rid != target.rid:
                    self._spill_total += 1
                    self._m_spills.inc()
                    _flight_record("router.spill",
                                   cid=item.request.request_id,
                                   affinity=target.rid, to=spilled.rid)
                target = spilled
            self.qos.commit(item)
            self._queue.remove(item)
            self._dispatch_to(item, target)

    def _reject_stranded_queue(self) -> None:
        """Terminal REJECTED outputs for everything still queued when the
        router stalls with no live replicas and no way back — callers
        blocked in ``run()``/``pop_output`` get an answer, not a hang."""
        for item in list(self._queue):
            req = item.request
            out = RequestOutput(request_id=req.request_id,
                                prompt_ids=list(req.prompt_ids))
            out.finished = True
            out.finish_reason = "rejected"
            self._rejected_total += 1
            self._shed_tokens_total += (
                len(req.prompt_ids) + req.sampling.max_new_tokens)
            self._m_rejected.inc()
            _flight_record("router.rejected", cid=req.request_id,
                           reason="no live replicas")
            self._finish_item(item, out)
        self._queue.clear()
        self._publish_gauges()

    def _dispatch_to(self, item: _RouterItem, h: ReplicaHandle) -> None:
        req = item.request
        try:
            h.engine.submit(req)
        except Exception as e:  # noqa: BLE001 — an admission that raises
            # (the serve.admit fault drill, an allocator edge) bounces the
            # REQUEST, not the fleet: terminal rejected, the replica stays
            # in rotation. Malformed requests cannot reach here —
            # Router.submit already ran the same validation the engine
            # does, so whatever raised is environmental.
            out = RequestOutput(request_id=req.request_id,
                                prompt_ids=list(req.prompt_ids))
            out.finished = True
            out.finish_reason = "rejected"
            self._rejected_total += 1
            self._shed_tokens_total += (
                len(req.prompt_ids) + req.sampling.max_new_tokens)
            self._m_rejected.inc()
            _flight_record("router.dispatch_rejected", cid=req.request_id,
                           replica=h.rid, error=repr(e)[:160])
            self._finish_item(item, out)
            return
        # router-side wait counts toward the deadline exactly like engine
        # queue wait: one clock, started at user intake
        h.engine.backdate_submit_time(req.request_id, item.submit_time)
        item.phase = "dispatched"
        item.replica = h.rid
        h.assigned.add(req.request_id)
        h.dispatched += 1
        self._m_dispatched.inc()
        _flight_record("router.dispatch", cid=req.request_id, replica=h.rid)

    def _capture_finished(self, h: ReplicaHandle) -> None:
        """Pull every terminal output off a replica. Runs after each pump
        tick AND on demand (cancel), and covers event-less terminals too
        (deadline/cancel inside the engine emit no StreamEvent). Clean
        completions captured from a PROBATION replica count toward its
        parole: ``probation_requests`` of them rejoin it to the live
        rendezvous rotation."""
        for rid_ in list(h.assigned):
            out = h.engine.get_output(rid_)
            if out is not None and out.finished:
                h.engine.pop_output(rid_)
                h.assigned.discard(rid_)
                self._finish_item(self._items[rid_], out)
                if (h.state == STATE_PROBATION
                        and out.finish_reason in ("eos", "length")):
                    h.probation_done += 1
                    if h.probation_done >= self.config.probation_requests:
                        h.state = STATE_LIVE
                        self._probation_total += 1
                        self._m_probation.inc()
                        _flight_record("router.probation_passed", cid=h.rid,
                                       generation=h.generation,
                                       served=h.probation_done)
                        logger.info(
                            "router: replica %s passed probation after %d "
                            "clean completions; rejoining rotation",
                            h.rid, h.probation_done)

    def _finish_item(self, item: _RouterItem, out: RequestOutput) -> None:
        item.phase = "done"
        item.replica = ""
        self._outputs[out.request_id] = out

    def _on_replica_wedged(self, h: ReplicaHandle) -> None:
        """A pump worker blew ``replica_stall_s`` for
        ``replica_stall_ticks`` consecutive ticks: abandon it behind the
        generation fence and run the normal death triage. The zombie
        thread may still be inside XLA — its ticket is dropped before the
        fence bumps, so its result is never read and its labelled metric
        writes are revoked."""
        t = h.pump
        stalled = time.perf_counter() - t.started if t is not None else 0.0
        self._wedged_total += 1
        self._m_wedged.inc()
        _flight_record("router.replica_wedged", cid=h.rid,
                       generation=h.generation,
                       stalled_s=round(stalled, 3),
                       stall_ticks=h.stall_ticks)
        logger.warning(
            "router: replica %s WEDGED — step() still running after %.3gs "
            "(deadline replica_stall_s=%.3gs, %d strike(s)); abandoning "
            "its pump thread behind the generation fence",
            h.rid, stalled, self.config.replica_stall_s, h.stall_ticks)
        self._on_replica_failure(
            h,
            RuntimeError(
                f"wedged: step() exceeded replica_stall_s="
                f"{self.config.replica_stall_s}s for {h.stall_ticks} "
                f"consecutive tick(s)"
            ),
            state=STATE_WEDGED,
        )

    def _on_replica_failure(self, h: ReplicaHandle, exc: Exception,
                            state: str = STATE_DEAD) -> None:
        """Drain a dead/wedged replica out of rotation, exactly-once per
        stranded request: finished on the dead engine -> captured as-is;
        nothing streamed yet -> re-dispatched at the FRONT of the router
        queue in original arrival order; tokens already streamed ->
        terminal ``cancelled`` keeping what was delivered. Never hung.
        If the lineage still has respawn budget a resurrection is booked
        on the deterministic backoff."""
        if h.state in (STATE_DEAD, STATE_WEDGED):
            return
        if h.pump is not None:
            # abandon the in-flight worker behind the generation fence:
            # the ticket reference is dropped (its result is never read),
            # the generation bump invalidates any late match, and the
            # engine's labelled metrics view is revoked so the zombie's
            # eventual writes are dropped. The triage reads below touch
            # only GIL-atomic dict/list state the worker appends to, so a
            # concurrently-running zombie cannot corrupt them.
            h.pump = None
            h.generation += 1
            h.engine.revoke_metrics()
        h.state = state
        h.fail_reason = repr(exc)
        self.replicas.pop(h.rid, None)
        self.retired.append(h)
        logger.warning("router: replica %s died (%s); %d stranded requests",
                       h.rid, exc, len(h.assigned))
        _flight_record("router.replica_dead", cid=h.rid, error=repr(exc),
                       stranded=len(h.assigned))
        # last state the rid's gauge will show until a respawn resets it
        self._reg.gauge(f"serve.router.{h.rid}.state").set(
            STATE_CODES.get(state, -1))
        requeue: List[_RouterItem] = []
        for rid_ in list(h.assigned):
            item = self._items[rid_]
            out = h.engine.get_output(rid_)
            if out is not None and out.finished:
                self._finish_item(item, out)
            elif out is None or not out.token_ids:
                item.phase = "queued"
                item.replica = ""
                requeue.append(item)
                h.redispatched += 1
                self._redispatch_total += 1
                self._m_redispatched.inc()
                _flight_record("router.redispatch", cid=rid_,
                               from_replica=h.rid)
            else:
                out.finished = True
                out.finish_reason = "cancelled"
                self._shed_tokens_total += (
                    item.request.sampling.max_new_tokens - len(out.token_ids)
                )
                self._finish_item(item, out)
        h.assigned.clear()
        # front of the queue, original arrival order — like a preemption
        # requeue, a victim of infrastructure never loses its place
        self._queue[:0] = sorted(requeue, key=lambda it: it.order)
        # self-healing: book the resurrection (or retire the lineage)
        self._schedule_respawn(rid=h.rid, lineage=h.lineage or h.rid,
                               generation=h.generation + 1,
                               fail_reason=h.fail_reason)
        self._publish_gauges()

    def _detach_drained(self) -> None:
        for h in [h for h in self.replicas.values()
                  if h.state == STATE_DRAINING and h.pump is None
                  and not h.engine.has_work and not h.assigned]:
            h.state = STATE_DETACHED
            self.replicas.pop(h.rid, None)
            self.retired.append(h)
            _flight_record("router.replica_detached", cid=h.rid)

    # ---------------------------------------------------------------- stats
    def _publish_gauges(self) -> None:
        live = [h for h in self.replicas.values() if h.state == STATE_LIVE]
        self._m_live.set(len(live))
        self._m_queue.set(len(getattr(self, "_queue", ())))
        self._m_publish_gauge.set(1 if self.publish_in_progress else 0)
        cached = prompts = 0
        for h in self.replicas.values():
            if not h.pumpable:
                continue
            self._reg.gauge(
                f"serve.router.{h.rid}.queue_depth"
            ).set(h.queue_depth())
            self._reg.gauge(
                f"serve.router.{h.rid}.state"
            ).set(STATE_CODES.get(h.state, -1))
            # mixed-version window: each replica reports the monotonic
            # seq of the version it serves (tags are opaque strings)
            self._reg.gauge(
                f"serve.router.{h.rid}.weights_version"
            ).set(self._weights.seq(h.weights_version))
            if not h.engine_quiescent:
                continue  # engine belongs to its outstanding pump worker
            # lifetime totals; pump-thread-private engine fields are safe
            # to read here — the router thread owns a quiescent engine
            cached += h.engine._cached_tokens_total
            prompts += h.engine._prompt_tokens_total
        self._m_hit_rate.set(cached / max(1, prompts))
        self._refresh_debug()

    def _refresh_debug(self) -> None:
        now = time.perf_counter()
        doc = {
            "replicas": [h.status_doc() for h in self.replicas.values()],
            "retired": [h.status_doc() for h in self.retired],
            "queue_depth": len(self._queue),
            "weights_version": self._weights_version,
            "rejected": self._rejected_total,
            "deadline_cancelled": self._deadline_cancelled_total,
            "spills": self._spill_total,
            "redispatched": self._redispatch_total,
            # self-healing columns (docs/serving.md "Self-healing fleet")
            "replicas_live": sum(1 for h in self.replicas.values()
                                 if h.state == STATE_LIVE),
            "min_live": self.config.min_live,
            "wedged": self._wedged_total,
            "respawns": self._respawn_total,
            "probation_passed": self._probation_total,
            # versioned weight publication (docs/serving.md)
            "publishes": self._publish_total,
            "publish_in_progress": self.publish_in_progress,
            "pending_respawns": [
                {"rid": p["rid"], "attempt": p["attempt"],
                 "delay_s": p["delay_s"],
                 "due_in_s": round(max(0.0, p["due"] - now), 3)}
                for p in self._pending_respawns
            ],
            "retired_lineages": sorted(self._retired_lineages),
        }
        with self._debug_lock:
            self._debug_doc = doc

    def debug_doc(self) -> Dict[str, Any]:
        """Thread-safe snapshot for ``/debug/router`` (exporter HTTP
        thread); refreshed by the pump at the end of every step. The
        ``_debug_doc`` swap is the ONLY cross-thread write, and the
        single-writer is the router's pump thread — an abandoned zombie
        pump worker never touches it (workers only run ``engine.step``),
        so a wedge cannot corrupt the snapshot a scrape is reading."""
        with self._debug_lock:
            return dict(self._debug_doc)

    def health(self) -> Dict[str, Any]:
        """Fleet health for ``/healthz`` — thread-safe (built from the
        locked debug snapshot, so the exporter's HTTP thread calls it
        directly). ``healthy`` is False while fewer than
        ``RouterConfig.min_live`` replicas are LIVE — the exporter maps
        that to HTTP 503 — and it is RECOVERING, not sticky: the moment
        respawn + probation restore the fleet, the next scrape is 200."""
        doc = self.debug_doc()
        rows = doc.get("replicas", [])
        n_live = doc.get(
            "replicas_live",
            sum(1 for r in rows if r.get("state") == STATE_LIVE),
        )
        return {
            "healthy": n_live >= self.config.min_live,
            "replicas_live": n_live,
            "min_live": self.config.min_live,
            "replica_states": {r.get("rid"): r.get("state") for r in rows},
            "queue_depth": doc.get("queue_depth", 0),
            "wedged": doc.get("wedged", 0),
            "respawns": doc.get("respawns", 0),
            "pending_respawns": len(doc.get("pending_respawns", ())),
            "retired_lineages": doc.get("retired_lineages", []),
            # versioned weight publication: the latest tag, whether the
            # mixed-version window is still open, and each replica's
            # served version (probes watch convergence here)
            "weights_version": doc.get("weights_version", ""),
            "publish_in_progress": doc.get("publish_in_progress", False),
            "replica_weights": {r.get("rid"): r.get("weights_version")
                                for r in rows},
        }

    def metrics(self, reset_window: bool = True) -> Dict[str, Any]:
        """Fleet-aggregated metrics, same keys as the engine's plus
        router-level outcomes and a ``per_replica`` breakdown. Rates sum
        across replicas; the hit rate is token-weighted."""
        per: Dict[str, Dict[str, float]] = {}
        for h in self.replicas.values():
            # a replica mid-stall is skipped for one poll rather than
            # racing its worker inside the engine's window bookkeeping
            if h.pumpable and h.engine_quiescent:
                per[h.rid] = h.engine.metrics(reset_window=reset_window)
        agg: Dict[str, Any] = {
            "queue_depth": float(len(self._queue)) + sum(
                m["queue_depth"] for m in per.values()
            ),
            "num_running": sum(m["num_running"] for m in per.values()),
            "generated_tokens": sum(
                m["generated_tokens"] for m in per.values()
            ),
            "decode_tokens_per_sec": sum(
                m["decode_tokens_per_sec"] for m in per.values()
            ),
            "goodput_tokens": sum(m["goodput_tokens"] for m in per.values()),
            "goodput_tokens_per_sec": sum(
                m["goodput_tokens_per_sec"] for m in per.values()
            ),
            "prefix_hit_rate": (
                sum(m["cached_tokens"] for m in per.values())
                / max(1, sum(m["prompt_tokens"] for m in per.values()))
            ),
            "cached_tokens": sum(m["cached_tokens"] for m in per.values()),
            "prompt_tokens": sum(m["prompt_tokens"] for m in per.values()),
            "preemptions": sum(m["preemptions"] for m in per.values()),
            # engine-side rejects are structurally 0 (bounds live here)
            "rejected": float(self._rejected_total),
            "shed_tokens": float(self._shed_tokens_total) + sum(
                m["shed_tokens"] for m in per.values()
            ),
            "deadline_misses": float(self._deadline_cancelled_total) + sum(
                m["deadline_misses"] for m in per.values()
            ),
            "spills": float(self._spill_total),
            "redispatched": float(self._redispatch_total),
            "replicas_live": float(len(self.live_replicas())),
            "wedged": float(self._wedged_total),
            "respawns": float(self._respawn_total),
            "probation_passed": float(self._probation_total),
            "publishes": float(self._publish_total),
            "per_replica": per,
        }
        return agg
