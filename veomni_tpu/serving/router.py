"""Scale-out serving: prefix-affinity router over data-parallel replicas.

Everything the serving stack grew through PR 15 lives inside ONE engine
process; millions of users need N of them behind a front door. The router
is that front door: it owns the QoS admission queue (classes, tenant DRR,
queue bound, deadlines — moved UP from the engine) and dispatches over N
in-process :class:`~veomni_tpu.serving.engine.InferenceEngine` replicas
through the existing ``api.py`` Request/RequestOutput surface. Replica
engines run single-class FIFO (``classes="default"``, bounds off), so
per-request semantics on a replica stay token-exact with the bare engine.

Three pillars:

1. **Prefix-affinity routing.** The dispatch target is chosen by
   rendezvous-hashing the prompt's LEADING block-aligned chunk key — the
   same ``tuple(tokens[i*bs:(i+1)*bs])`` chunks the radix prefix cache
   keys its tree on — so shared-prefix traffic lands where its KV already
   lives, multiplying the PR 9 hit rate instead of diluting it N ways.
   Rendezvous (highest-random-weight) keeps the mapping stable when
   replicas come and go: adding or removing one replica only moves the
   keys that hash to it. Affinity yields under load pressure: when the
   target's engine queue depth reaches ``spill_queue_depth`` (or its free
   concurrent-sequence estimate drops below ``spill_min_free_seqs``) the
   request spills to the least-loaded live replica instead; when EVERY
   live replica is past the threshold the request parks at the router —
   which is exactly what makes the router-level QoS pick meaningful under
   overload (back-pressure, not blind fan-out).

2. **Health- and shed-aware dispatch.** The router's pump steps every
   replica; a replica whose ``step()`` raises (a wedged scheduler, a
   device error) is marked DEAD and drained out of rotation the same
   tick. Its stranded requests are triaged exactly-once: nothing
   streamed yet -> re-dispatched (front of the router queue, original
   arrival order) to a survivor; tokens already streamed -> terminal
   ``cancelled`` (re-running would duplicate delivered output); already
   terminal on the dead engine -> captured as-is. Nothing ever hangs.
   ``serve.router.*`` gauges/counters, ``/debug/router`` and
   ``router.*`` flight events expose all of it.

3. **Live add/remove behind versioned weights.** ``add_replica()`` spins
   up an engine that SHARES the compiled-program bundle
   (:class:`~veomni_tpu.serving.engine.SharedPrograms` — zero new
   compiles) and the latest ``publish_weights(params, version)`` payload;
   ``remove_replica()`` drains (no new dispatches, in-flight work
   finishes, outputs captured) then detaches — no lost or duplicated
   request ids. Old replicas finish on their weights version while new
   ones serve the new tag: the same interface the trainer hot-swap loop
   (ROADMAP item 4) publishes into.

Threading contract: like the engine, the router holds no locks for its
own state — ONE pump thread (the caller's) drives ``submit``/``step``/
``generate``/``run`` and every replica engine. The only cross-thread
surface is the debug snapshot behind ``_debug_lock`` (the exporter's
HTTP thread reads ``/debug/router``) plus the already-thread-safe
metrics registry and flight recorder.
"""

from __future__ import annotations

import threading
import time
import zlib
from dataclasses import dataclass, field, replace
from typing import Any, Dict, Iterable, Iterator, List, Optional, Union

from veomni_tpu.observability.flight_recorder import record as _flight_record
from veomni_tpu.observability.metrics import get_registry
from veomni_tpu.serving.api import (
    Request,
    RequestOutput,
    SamplingParams,
    StreamEvent,
)
from veomni_tpu.serving.engine import EngineConfig, InferenceEngine
from veomni_tpu.serving.replica import (
    STATE_DEAD,
    STATE_DETACHED,
    STATE_DRAINING,
    STATE_LIVE,
    ReplicaHandle,
)
from veomni_tpu.serving.scheduler import QoSPicker, parse_classes
from veomni_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class RouterConfig:
    """Router-level knobs (the engine keeps its own via EngineConfig)."""

    # initial replica count (grow/shrink live via add/remove_replica)
    replicas: int = 2
    # leading FULL blocks of the prompt hashed into the affinity key —
    # mirrors the radix cache's block-aligned chunk keys, so requests
    # sharing a system prompt share a key. Prompts shorter than one block
    # key on the whole prompt.
    affinity_blocks: int = 2
    # affinity yields when the target replica's engine queue depth reaches
    # this; when EVERY live replica is past it, requests park at the
    # router (back-pressure). 0 disables spill AND parking (pure affinity).
    spill_queue_depth: int = 4
    # affinity also yields when the target's free concurrent-sequence
    # estimate (the serve.kv_free_concurrent_seqs signal) drops below
    # this. 0 disables the capacity leg.
    spill_min_free_seqs: int = 0
    # QoS at the front door. None inherits the corresponding EngineConfig
    # field, so an engine-tuned deployment routes identically.
    classes: Optional[str] = None
    queue_bound: Optional[int] = None
    tenant_max_inflight: Optional[int] = None

    def __post_init__(self):
        if self.replicas < 1:
            raise ValueError("need at least one replica")
        if self.affinity_blocks < 1:
            raise ValueError("affinity_blocks must be >= 1")
        if self.spill_queue_depth < 0:
            raise ValueError("spill_queue_depth must be >= 0 (0 disables)")
        if self.spill_min_free_seqs < 0:
            raise ValueError("spill_min_free_seqs must be >= 0 (0 disables)")


@dataclass
class _RouterItem:
    """Router-side bookkeeping for one accepted request."""

    request: Request
    class_idx: int  # QoSPicker duck-type field
    order: int  # arrival sequence number (re-dispatch keeps this order)
    submit_time: float = field(default_factory=time.perf_counter)
    phase: str = "queued"  # queued -> dispatched -> done
    replica: str = ""  # rid while dispatched

    @property
    def tenant(self) -> str:  # QoSPicker duck-type field
        return getattr(self.request, "tenant", "")


class Router:
    """Front door over N in-process engine replicas."""

    def __init__(self, params, cfg, engine_config: Optional[EngineConfig] = None,
                 config: Optional[RouterConfig] = None):
        self.engine_config = engine_config or EngineConfig()
        self.config = config or RouterConfig()
        ec, rc = self.engine_config, self.config
        # QoS moves UP to the router: the front door runs the class/tenant
        # pick and the admission bounds; replicas run single-class FIFO
        # with bounds off so their per-request semantics stay token-exact.
        classes_spec = rc.classes if rc.classes is not None else ec.classes
        self.qos = QoSPicker(parse_classes(classes_spec))
        self.queue_bound = (
            rc.queue_bound if rc.queue_bound is not None else ec.queue_bound
        )
        self.tenant_max_inflight = (
            rc.tenant_max_inflight if rc.tenant_max_inflight is not None
            else ec.tenant_max_inflight
        )
        # versioned weights: replicas added later serve the latest publish
        self._params = params
        self._cfg = cfg
        self._weights_version = "v0"
        self.replicas: Dict[str, ReplicaHandle] = {}
        self.retired: List[ReplicaHandle] = []
        self._next_rid = 0
        self._programs = None  # SharedPrograms, built by the first replica
        for _ in range(rc.replicas):
            self._spawn_replica()
        # request bookkeeping: arrival-ordered router queue + id -> item
        self._items: Dict[str, _RouterItem] = {}
        self._queue: List[_RouterItem] = []
        self._outputs: Dict[str, RequestOutput] = {}
        self._req_counter = 0
        self._order_counter = 0
        # router-local outcome totals (metrics() mirrors the engine's keys)
        self._rejected_total = 0
        self._shed_tokens_total = 0
        self._deadline_cancelled_total = 0
        self._spill_total = 0
        self._redispatch_total = 0
        # router-level observability (docs/observability.md):
        self._reg = get_registry()
        self._m_requests = self._reg.counter("serve.router.requests")
        self._m_dispatched = self._reg.counter("serve.router.dispatched")
        self._m_redispatched = self._reg.counter("serve.router.redispatched")
        self._m_spills = self._reg.counter("serve.router.spills")
        self._m_rejected = self._reg.counter("serve.router.rejected")
        self._m_deadline = self._reg.counter("serve.router.deadline_cancelled")
        self._m_live = self._reg.gauge("serve.router.replicas_live")
        self._m_queue = self._reg.gauge("serve.router.queue_depth")
        self._m_hit_rate = self._reg.gauge("serve.router.prefix_hit_rate")
        # cross-thread debug snapshot: the exporter's HTTP thread reads
        # /debug/router while the pump writes — the ONLY router state that
        # crosses threads, refreshed at the end of every step()
        self._debug_lock = threading.Lock()
        self._debug_doc: Dict[str, Any] = {}  # guarded-by: _debug_lock
        self._publish_gauges()

    # ------------------------------------------------------------- replicas
    def _spawn_replica(self) -> ReplicaHandle:
        rid = f"r{self._next_rid}"
        self._next_rid += 1
        # replicas run single-class FIFO with the bounds off — QoS lives at
        # the router — and carry their rid as the metrics instance label
        rcfg = replace(
            self.engine_config, classes="default", queue_bound=0,
            tenant_max_inflight=0, metrics_label=rid,
        )
        eng = InferenceEngine(self._params, self._cfg, rcfg,
                              programs=self._programs)
        if self._programs is None:
            self._programs = eng.programs
        h = ReplicaHandle(rid=rid, engine=eng,
                          weights_version=self._weights_version)
        self.replicas[rid] = h
        return h

    def add_replica(self) -> ReplicaHandle:
        """Grow the fleet by one live replica. The new engine shares the
        compiled-program bundle (zero new traces/compiles — pinned by the
        router compile-count gate) and serves the LATEST published
        weights version."""
        h = self._spawn_replica()
        _flight_record("router.replica_added", cid=h.rid,
                       weights_version=h.weights_version)
        self._publish_gauges()
        return h

    def remove_replica(self, rid: str) -> ReplicaHandle:
        """Begin a clean drain: the replica leaves the dispatch rotation
        immediately, finishes everything already dispatched to it, and
        detaches once drained (no lost or duplicated requests). Refuses to
        drain the LAST live replica — a router with work and nowhere to
        send it would stall."""
        h = self.replicas[rid]
        if h.state != STATE_LIVE:
            raise ValueError(f"replica {rid!r} is {h.state}, not live")
        if sum(1 for o in self.replicas.values()
               if o.state == STATE_LIVE) <= 1:
            raise ValueError("cannot remove the last live replica")
        h.state = STATE_DRAINING
        _flight_record("router.replica_draining", cid=rid,
                       assigned=len(h.assigned))
        self._publish_gauges()
        return h

    def kill_replica(self, rid: str, reason: str = "killed") -> None:
        """Simulate a replica crash (tests, the bench's mid-storm kill
        drill): the replica is drained out of rotation exactly as if its
        pump had raised — stranded requests re-dispatched or surfaced
        terminal, never hung."""
        self._on_replica_failure(self.replicas[rid], RuntimeError(reason))

    def publish_weights(self, params, version: str) -> str:
        """Publish a new weights payload under a version tag. Replicas
        added from now on serve it; existing replicas finish on the
        version they were built with (in-flight requests never see a
        mid-stream weight change). A full in-place hot-swap of live
        replicas plugs in here later (ROADMAP item 4) — the version tag
        is the interface both sides already agree on."""
        self._params = params
        self._weights_version = str(version)
        _flight_record("router.weights_published", cid=self._weights_version)
        self._refresh_debug()
        return self._weights_version

    @property
    def weights_version(self) -> str:
        return self._weights_version

    def live_replicas(self) -> List[ReplicaHandle]:
        return [h for h in self.replicas.values() if h.state == STATE_LIVE]

    # ---------------------------------------------------------------- intake
    def submit(self, request: Union[Request, Iterable[int]],
               sampling: Optional[SamplingParams] = None) -> str:
        """Enqueue a request at the front door. Validation mirrors
        ``InferenceEngine.submit`` exactly (malformed raises, overloaded
        load-sheds to a terminal ``rejected`` output) so a single-replica
        router is behavior-identical to the bare engine."""
        ec = self.engine_config
        if not isinstance(request, Request):
            request = Request(prompt_ids=[int(t) for t in request],
                              sampling=sampling or SamplingParams())
        if not request.request_id:
            while f"req-{self._req_counter}" in self._items:
                self._req_counter += 1
            request.request_id = f"req-{self._req_counter}"
            self._req_counter += 1
        if request.request_id in self._items:
            raise ValueError(f"duplicate request id {request.request_id!r}")
        if not request.prompt_ids:
            raise ValueError("empty prompt")
        sp = request.sampling
        if sp.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = len(request.prompt_ids) + sp.max_new_tokens
        if total > ec.max_model_len:
            raise ValueError(
                f"prompt+max_new_tokens={total} exceeds max_model_len="
                f"{ec.max_model_len}"
            )
        blocks_needed = -(-total // ec.block_size)
        if blocks_needed > ec.num_blocks - 1:
            raise ValueError(
                f"request needs {blocks_needed} blocks; each replica pool "
                f"has {ec.num_blocks - 1}"
            )
        if request.deadline_s is not None and request.deadline_s < 0:
            raise ValueError("deadline_s must be >= 0 (None disables)")
        # unknown priority class raises BEFORE anything registers —
        # malformed is an error, overloaded is an outcome
        class_idx = self.qos.resolve_class(
            getattr(request, "priority", "interactive")
        )
        item = _RouterItem(request=request, class_idx=class_idx,
                           order=self._order_counter)
        self._order_counter += 1
        self._m_requests.inc()
        # front-door admission control: the waiting population is the
        # router queue PLUS every pumped engine's waiting queue, so with
        # one replica the bound sheds exactly when the bare engine would
        if (self.queue_bound
                and self._total_waiting() >= self.queue_bound) or (
                self.tenant_max_inflight
                and self._tenant_inflight(item.tenant)
                >= self.tenant_max_inflight):
            out = RequestOutput(
                request_id=request.request_id,
                prompt_ids=list(request.prompt_ids),
            )
            out.finished = True
            out.finish_reason = "rejected"
            item.phase = "done"
            self._items[request.request_id] = item
            self._outputs[request.request_id] = out
            self._rejected_total += 1
            self._shed_tokens_total += total
            self._m_rejected.inc()
            _flight_record("router.rejected", cid=request.request_id)
            return request.request_id
        self._items[request.request_id] = item
        self._queue.append(item)
        return request.request_id

    def _total_waiting(self) -> int:
        return len(self._queue) + sum(
            h.queue_depth() for h in self.replicas.values() if h.pumpable
        )

    def _tenant_inflight(self, tenant: str) -> int:
        return sum(1 for it in self._items.values()
                   if it.phase != "done" and it.tenant == tenant)

    # -------------------------------------------------------------- affinity
    def _affinity_key(self, prompt_ids) -> int:
        """crc32 over the prompt's leading block-aligned chunk keys — the
        exact ``tuple(tokens[i*bs:(i+1)*bs])`` chunks the radix cache keys
        its tree on, so two prompts that would share cache blocks share an
        affinity key. Prompts shorter than one block key on the whole
        prompt (they can't share full blocks anyway)."""
        bs = self.engine_config.block_size
        n = min(self.config.affinity_blocks, len(prompt_ids) // bs)
        if n <= 0:
            chunks: Any = tuple(int(t) for t in prompt_ids)
        else:
            chunks = tuple(
                tuple(int(t) for t in prompt_ids[i * bs:(i + 1) * bs])
                for i in range(n)
            )
        return zlib.crc32(repr(chunks).encode())

    def _affinity_target(self, key: int,
                         live: List[ReplicaHandle]) -> ReplicaHandle:
        """Rendezvous (highest-random-weight) hash: stable under replica
        add/remove — only keys owned by a departing replica move."""
        return max(live, key=lambda h: (
            zlib.crc32(f"{key}:{h.rid}".encode()), h.rid,
        ))

    def _past_threshold(self, h: ReplicaHandle) -> bool:
        rc = self.config
        if rc.spill_queue_depth and h.queue_depth() >= rc.spill_queue_depth:
            return True
        if (rc.spill_min_free_seqs
                and h.free_concurrent_seqs() < rc.spill_min_free_seqs):
            return True
        return False

    # ---------------------------------------------------------------- pump
    @property
    def has_work(self) -> bool:
        return bool(self._queue) or any(
            (h.engine.has_work or h.assigned)
            for h in self.replicas.values() if h.pumpable
        )

    def step(self) -> List[StreamEvent]:
        """One router tick: expire queued deadlines, dispatch under the
        QoS pick + affinity/spill policy, pump every live/draining
        replica one engine tick (a raising replica dies and sheds, never
        hangs), capture finished outputs, detach drained replicas, and
        refresh gauges + the /debug/router snapshot."""
        self._expire_deadlines()
        self._dispatch()
        events: List[StreamEvent] = []
        pump = [h for h in self.replicas.values() if h.pumpable]
        busy = [h for h in pump if h.engine.has_work]
        if len(busy) == 1:
            h = busy[0]
            try:
                events.extend(h.engine.step())
            except Exception as e:  # noqa: BLE001 — a replica failure
                # must shed to survivors, not take the router down
                self._on_replica_failure(h, e)
        elif busy:
            # pump replicas CONCURRENTLY: each engine is still touched by
            # exactly one thread at a time (its worker, with a join
            # barrier before any router bookkeeping reads it back), so the
            # engine's single-pump-thread contract holds per replica while
            # the jitted steps — which release the GIL — overlap. This is
            # where the aggregate throughput scaling comes from; a serial
            # pump would serialize N device programs behind one core.
            results: Dict[str, Any] = {}

            def _pump_one(handle: ReplicaHandle) -> None:
                try:
                    results[handle.rid] = ("ok", handle.engine.step())
                except Exception as e:  # noqa: BLE001 — triaged post-join
                    results[handle.rid] = ("dead", e)

            threads = [
                threading.Thread(target=_pump_one, args=(h,),
                                 name=f"router-pump-{h.rid}", daemon=True)
                for h in busy
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for h in busy:
                kind, val = results[h.rid]
                if kind == "ok":
                    events.extend(val)
                else:
                    self._on_replica_failure(h, val)
        for h in pump:
            if h.rid in self.replicas:  # skip replicas that died this tick
                self._capture_finished(h)
        self._detach_drained()
        self._publish_gauges()
        return events

    def generate(self, requests: Optional[Iterable] = None
                 ) -> Iterator[StreamEvent]:
        """Streaming interface mirroring the engine's: submit, then yield
        token events (from every replica) until all in-flight work
        drains. More requests may be ``submit()``-ed between yields."""
        for r in requests or ():
            self.submit(r)
        while self.has_work:
            yield from self.step()

    def run(self, requests: Optional[Iterable] = None
            ) -> Dict[str, RequestOutput]:
        """Drain ``generate()`` and hand over every terminal output,
        releasing router bookkeeping for them (same ownership contract as
        ``InferenceEngine.run``)."""
        for _ in self.generate(requests):
            pass
        done = dict(self._outputs)
        for rid in done:
            self._outputs.pop(rid, None)
            self._items.pop(rid, None)
        return done

    def pop_output(self, request_id: str) -> Optional[RequestOutput]:
        """Release and return one finished request's output; refuses while
        it is still in flight anywhere in the fleet."""
        item = self._items.get(request_id)
        if item is not None and item.phase != "done":
            raise ValueError(f"request {request_id!r} is still in flight")
        self._items.pop(request_id, None)
        return self._outputs.pop(request_id, None)

    def cancel(self, request_id: str, reason: str = "cancelled") -> bool:
        """Cancel wherever the request currently is: parked at the router
        (terminal output synthesized here) or dispatched (delegated to the
        owning engine, output captured immediately). False for unknown or
        already-finished ids."""
        item = self._items.get(request_id)
        if item is None or item.phase == "done":
            return False
        if item.phase == "queued":
            self._queue.remove(item)
            out = RequestOutput(
                request_id=request_id,
                prompt_ids=list(item.request.prompt_ids),
            )
            out.finished = True
            out.finish_reason = reason
            self._finish_item(item, out)
            return True
        h = self.replicas.get(item.replica)
        if h is None or not h.engine.cancel(request_id, reason):
            return False
        self._capture_finished(h)
        return True

    # ------------------------------------------------------------- internals
    def _expire_deadlines(self) -> None:
        """Expire ROUTER-queued requests past their deadline (terminal
        ``deadline`` status) — the engine expires what was dispatched to
        it, with the clock backdated to router intake so the two waits
        add up to one deadline."""
        now = time.perf_counter()
        for item in [it for it in self._queue
                     if it.request.deadline_s is not None
                     and (now - it.submit_time) > it.request.deadline_s]:
            self._queue.remove(item)
            out = RequestOutput(
                request_id=item.request.request_id,
                prompt_ids=list(item.request.prompt_ids),
            )
            out.finished = True
            out.finish_reason = "deadline"
            out.deadline_missed = True
            self._deadline_cancelled_total += 1
            self._shed_tokens_total += (
                len(item.request.prompt_ids)
                + item.request.sampling.max_new_tokens
            )
            self._m_deadline.inc()
            _flight_record("router.deadline", cid=item.request.request_id)
            self._finish_item(item, out)

    def _dispatch(self) -> None:
        live = self.live_replicas()
        if not live:
            if self._queue and not any(
                    h.engine.has_work or h.assigned
                    for h in self.replicas.values() if h.pumpable):
                # nothing can ever serve the queue again — fail loudly,
                # mirroring the engine's scheduler-stall invariant, instead
                # of letting generate() spin on has_work forever
                raise RuntimeError(
                    "router stalled: requests queued but no live replicas"
                )
            return  # draining replicas may still finish their work
        while self._queue:
            # park at the router when every live replica is past the spill
            # threshold AND the fleet is actually busy — back-pressure
            # makes the router-level QoS pick decide who goes next. An
            # idle fleet always accepts (a threshold below the idle
            # capacity must never stall an empty router).
            busy = any(h.engine.has_work for h in live)
            if busy and all(self._past_threshold(h) for h in live):
                break
            item = self.qos.pick(self._queue)
            key = self._affinity_key(item.request.prompt_ids)
            target = self._affinity_target(key, live)
            if self._past_threshold(target):
                spilled = min(live, key=lambda h: (h.queue_depth(), h.rid))
                if spilled.rid != target.rid:
                    self._spill_total += 1
                    self._m_spills.inc()
                    _flight_record("router.spill",
                                   cid=item.request.request_id,
                                   affinity=target.rid, to=spilled.rid)
                target = spilled
            self.qos.commit(item)
            self._queue.remove(item)
            self._dispatch_to(item, target)

    def _dispatch_to(self, item: _RouterItem, h: ReplicaHandle) -> None:
        req = item.request
        h.engine.submit(req)
        # router-side wait counts toward the deadline exactly like engine
        # queue wait: one clock, started at user intake
        h.engine.backdate_submit_time(req.request_id, item.submit_time)
        item.phase = "dispatched"
        item.replica = h.rid
        h.assigned.add(req.request_id)
        h.dispatched += 1
        self._m_dispatched.inc()
        _flight_record("router.dispatch", cid=req.request_id, replica=h.rid)

    def _capture_finished(self, h: ReplicaHandle) -> None:
        """Pull every terminal output off a replica. Runs after each pump
        tick AND on demand (cancel), and covers event-less terminals too
        (deadline/cancel inside the engine emit no StreamEvent)."""
        for rid_ in list(h.assigned):
            out = h.engine.get_output(rid_)
            if out is not None and out.finished:
                h.engine.pop_output(rid_)
                h.assigned.discard(rid_)
                self._finish_item(self._items[rid_], out)

    def _finish_item(self, item: _RouterItem, out: RequestOutput) -> None:
        item.phase = "done"
        item.replica = ""
        self._outputs[out.request_id] = out

    def _on_replica_failure(self, h: ReplicaHandle, exc: Exception) -> None:
        """Drain a dead replica out of rotation, exactly-once per stranded
        request: finished on the dead engine -> captured as-is; nothing
        streamed yet -> re-dispatched at the FRONT of the router queue in
        original arrival order; tokens already streamed -> terminal
        ``cancelled`` keeping what was delivered. Never hung."""
        if h.state == STATE_DEAD:
            return
        h.state = STATE_DEAD
        h.fail_reason = repr(exc)
        self.replicas.pop(h.rid, None)
        self.retired.append(h)
        logger.warning("router: replica %s died (%s); %d stranded requests",
                       h.rid, exc, len(h.assigned))
        _flight_record("router.replica_dead", cid=h.rid, error=repr(exc),
                       stranded=len(h.assigned))
        requeue: List[_RouterItem] = []
        for rid_ in list(h.assigned):
            item = self._items[rid_]
            out = h.engine.get_output(rid_)
            if out is not None and out.finished:
                self._finish_item(item, out)
            elif out is None or not out.token_ids:
                item.phase = "queued"
                item.replica = ""
                requeue.append(item)
                h.redispatched += 1
                self._redispatch_total += 1
                self._m_redispatched.inc()
                _flight_record("router.redispatch", cid=rid_,
                               from_replica=h.rid)
            else:
                out.finished = True
                out.finish_reason = "cancelled"
                self._shed_tokens_total += (
                    item.request.sampling.max_new_tokens - len(out.token_ids)
                )
                self._finish_item(item, out)
        h.assigned.clear()
        # front of the queue, original arrival order — like a preemption
        # requeue, a victim of infrastructure never loses its place
        self._queue[:0] = sorted(requeue, key=lambda it: it.order)
        self._publish_gauges()

    def _detach_drained(self) -> None:
        for h in [h for h in self.replicas.values()
                  if h.state == STATE_DRAINING
                  and not h.engine.has_work and not h.assigned]:
            h.state = STATE_DETACHED
            self.replicas.pop(h.rid, None)
            self.retired.append(h)
            _flight_record("router.replica_detached", cid=h.rid)

    # ---------------------------------------------------------------- stats
    def _publish_gauges(self) -> None:
        live = [h for h in self.replicas.values() if h.state == STATE_LIVE]
        self._m_live.set(len(live))
        self._m_queue.set(len(getattr(self, "_queue", ())))
        cached = prompts = 0
        for h in self.replicas.values():
            if not h.pumpable:
                continue
            # lifetime totals; pump-thread-private engine fields are safe
            # to read here — the router IS the pump thread
            cached += h.engine._cached_tokens_total
            prompts += h.engine._prompt_tokens_total
            self._reg.gauge(
                f"serve.router.{h.rid}.queue_depth"
            ).set(h.queue_depth())
        self._m_hit_rate.set(cached / max(1, prompts))
        self._refresh_debug()

    def _refresh_debug(self) -> None:
        doc = {
            "replicas": [h.status_doc() for h in self.replicas.values()],
            "retired": [h.status_doc() for h in self.retired],
            "queue_depth": len(self._queue),
            "weights_version": self._weights_version,
            "rejected": self._rejected_total,
            "deadline_cancelled": self._deadline_cancelled_total,
            "spills": self._spill_total,
            "redispatched": self._redispatch_total,
        }
        with self._debug_lock:
            self._debug_doc = doc

    def debug_doc(self) -> Dict[str, Any]:
        """Thread-safe snapshot for ``/debug/router`` (exporter HTTP
        thread); refreshed by the pump at the end of every step."""
        with self._debug_lock:
            return dict(self._debug_doc)

    def metrics(self, reset_window: bool = True) -> Dict[str, Any]:
        """Fleet-aggregated metrics, same keys as the engine's plus
        router-level outcomes and a ``per_replica`` breakdown. Rates sum
        across replicas; the hit rate is token-weighted."""
        per: Dict[str, Dict[str, float]] = {}
        for h in self.replicas.values():
            if h.pumpable:
                per[h.rid] = h.engine.metrics(reset_window=reset_window)
        agg: Dict[str, Any] = {
            "queue_depth": float(len(self._queue)) + sum(
                m["queue_depth"] for m in per.values()
            ),
            "num_running": sum(m["num_running"] for m in per.values()),
            "generated_tokens": sum(
                m["generated_tokens"] for m in per.values()
            ),
            "decode_tokens_per_sec": sum(
                m["decode_tokens_per_sec"] for m in per.values()
            ),
            "goodput_tokens": sum(m["goodput_tokens"] for m in per.values()),
            "goodput_tokens_per_sec": sum(
                m["goodput_tokens_per_sec"] for m in per.values()
            ),
            "prefix_hit_rate": (
                sum(m["cached_tokens"] for m in per.values())
                / max(1, sum(m["prompt_tokens"] for m in per.values()))
            ),
            "cached_tokens": sum(m["cached_tokens"] for m in per.values()),
            "prompt_tokens": sum(m["prompt_tokens"] for m in per.values()),
            "preemptions": sum(m["preemptions"] for m in per.values()),
            # engine-side rejects are structurally 0 (bounds live here)
            "rejected": float(self._rejected_total),
            "shed_tokens": float(self._shed_tokens_total) + sum(
                m["shed_tokens"] for m in per.values()
            ),
            "deadline_misses": float(self._deadline_cancelled_total) + sum(
                m["deadline_misses"] for m in per.values()
            ),
            "spills": float(self._spill_total),
            "redispatched": float(self._redispatch_total),
            "replicas_live": float(len(self.live_replicas())),
            "per_replica": per,
        }
        return agg
