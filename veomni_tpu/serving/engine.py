"""In-process continuous-batching inference engine over a paged KV cache.

Execution model (one ``step()`` tick):

0. **Expire**: waiting (and still-prefilling) requests past their
   ``deadline_s`` are cancelled — blocks released, terminal ``deadline``
   status — before they can burn pool capacity nobody is waiting for.
1. **Admit**: free slots are filled from the waiting queue by the QoS pick
   (per-class stride weights, per-tenant round robin — plain FIFO with a
   single configured class); admission matches each prompt against the
   prefix cache (when enabled) and allocates only the uncached suffix's
   blocks — shared prompt blocks are referenced, not recomputed. Intake is
   bounded: past ``queue_bound`` waiting requests (or a tenant's
   ``tenant_max_inflight``), ``submit()`` load-sheds — the request comes
   back as a terminal ``rejected`` output (429-equivalent) instead of
   growing the queue without bound.
2. **Prefill (chunked)**: every admitted-but-unfinished prefill advances by
   ONE chunk per tick, so a long arriving prompt never blocks the running
   requests' next token for more than a chunk's worth of work. The chunk
   attends over the already-cached prefix through the sequence's block
   table (``paged_prefill_step``); the final chunk's logits sample the
   first token. With chunking off the whole uncached suffix is one chunk,
   and with the cache off too the path is the original monolithic prefill
   (``models/decode.py``'s jit + ``scatter_prompt_cache``) — byte-identical
   to the pre-cache engine.
3. **Capacity**: every decoding sequence is grown to cover its next write
   position; when blocks run out, cached (refcount-0) blocks are evicted
   LRU first, and only a truly dry pool preempts LIFO (recompute).
4. **Batched decode**: one jitted ``paged_decode_step`` over the fixed slot
   batch — per-slot positions, block tables, PRNG keys and sampling params.
   The gathered-context width (``nbb * block_size``, ``nbb`` the
   power-of-two bucket of the widest running block table) is the only shape
   that varies, so the compile count is bounded by the bucket count — never
   by request count or arrival pattern (``TRACE_COUNTS["paged_decode"]``).
   Chunked prefill adds one more bucketed program
   (``TRACE_COUNTS["paged_prefill"]``) over (chunk bucket, table bucket).
   With ``spec_k > 0`` the decode tick becomes **draft-then-verify**: a
   host-side drafting op (``spec_draft`` registry dispatch,
   ``serving/spec_decode.py``) proposes up to k tokens per slot, blocks for
   the drafted positions are claimed best-effort (never preempting), and
   ONE jitted ``paged_verify_step`` scores all k+1 positions — emitting
   1..k+1 tokens per slot per tick while staying token-exact with the
   one-token path (greedy AND seeded sampling; the verify step replays the
   same per-token PRNG key schedule). Rejected drafts roll their claimed
   blocks back the same tick. The verify program's compile count is
   bounded by (verify-width bucket x table bucket):
   ``TRACE_COUNTS["paged_verify"]`` is O(log2 k x log2 table-width). A
   tick where no slot drafts anything (or ``spec_k == 0``, the default)
   runs the plain decode step — byte-identical to the non-speculative
   engine.

Shapes the XLA programs see: slot batch ``S`` (static per engine), prompt
and chunk buckets (power-of-two), context buckets (power-of-two blocks).
Everything else — arrivals, lengths, finishes, preemptions, cache hits —
is host bookkeeping.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, Iterable, Iterator, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from veomni_tpu.models import decode as decode_mod
from veomni_tpu.models.config import TransformerConfig
from veomni_tpu.ops.quantization import make_kv_pool, quantize_decode_params
from veomni_tpu.models.decode import supports_cached_decode
from veomni_tpu.observability.metrics import LabelledRegistry, get_registry
from veomni_tpu.observability.request_trace import RequestTracer
from veomni_tpu.observability.spans import span
from veomni_tpu.resilience.faults import fault_point
from veomni_tpu.serving.api import (
    Request,
    RequestOutput,
    SamplingParams,
    StreamEvent,
)
from veomni_tpu.serving.kv_block_manager import KVBlockManager
from veomni_tpu.serving.prefix_cache import PrefixCache
from veomni_tpu.serving.scheduler import (
    Scheduler,
    SequenceState,
    parse_classes,
)
from veomni_tpu.utils.helper import host_floats
from veomni_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class EngineConfig:
    """Static engine shape knobs (all become compile-time constants)."""

    num_slots: int = 4  # decode batch width
    block_size: int = 16  # cache positions per KV block (power of two)
    max_model_len: int = 2048  # prompt + generated ceiling per request
    num_blocks: int = 0  # 0 -> 1 + num_slots * blocks(max_model_len)
    log_every_steps: int = 0  # 0 disables periodic metric logging
    # share full prompt blocks across requests (radix prefix cache over the
    # block pool; refcounted, LRU-evicted under pressure). OFF restores the
    # pre-cache engine exactly: exclusive blocks, monolithic prefill.
    prefix_cache: bool = True
    # prefill at most this many tokens per step() tick (0 = the whole
    # uncached suffix in one go). Bounds how long a newly arrived long
    # prompt can stall every running request's next token.
    prefill_chunk: int = 0
    # speculative decoding (draft-then-verify): propose up to spec_k tokens
    # per running slot per tick via the spec_draft strategy and verify them
    # in ONE batched jitted step — multi-token decode ticks, token-exact
    # with the one-token path. 0 (the default) keeps the seed decode path
    # byte-identical; the `off` strategy disables drafting even with k > 0.
    spec_k: int = 0
    spec_draft: str = "ngram"  # registry impl name (serving/spec_decode.py)
    # QoS classes, "name:weight,..." highest priority first (parsed by
    # scheduler.parse_classes). Two defaults ship: interactive (weight 4)
    # and batch (1). A SINGLE-class spec (e.g. "default") restores the
    # seed FIFO scheduler exactly and admits any priority label; with the
    # default two-class spec, an all-interactive stream (every Request's
    # default) is likewise behavior-identical to the seed.
    classes: str = "interactive:4,batch:1"
    # admission control / load-shedding: max waiting requests before
    # submit() sheds (terminal "rejected" status). 0 = unbounded (seed).
    queue_bound: int = 0
    # per-tenant cap on waiting+running requests. 0 = uncapped (seed).
    tenant_max_inflight: int = 0
    # KV-cache block storage mode: "none" keeps the dense compute-dtype
    # pool (bit-identical to the seed engine); "int8" stores blocks as an
    # int8 payload + per-(layer, block, row, kv-head) f32 scale sidecar —
    # ~4x the concurrent sequences per pool byte at f32, dequantized inside
    # the gathered attend (`paged_attention/xla_gather_q8`). "fp8" is
    # scaffolded behind the same interface but not yet shipped. Non-"none"
    # modes are NOT bit-exact: they ship under the fixed-seed quality gate
    # (serving/quality.py; docs/serving.md "Quantized serving tier").
    kv_quant: str = "none"
    # decode-path weight storage: "int8" stores the dense q/k/v/o and
    # gate/up/down projections as int8 + per-output-channel f32 scales,
    # dequantized in-kernel through the `decode_matmul/xla_q8` registry
    # impl. Embeddings, norms, biases, the lm head, routers and the MoE
    # expert stacks stay full-width.
    weight_quant: str = "none"
    # serving-side recompile detection: after this many step() ticks the
    # decode/prefill TRACE_COUNTS baselines are armed, and any later bucket
    # growth emits the trainer's loud rank-0 RECOMPILE warning + the
    # `recompiles` counter (a serving compile storm was previously
    # invisible — the trainer's detector deliberately watches only
    # train_step). 0 disables. The grace window absorbs the legitimate
    # warmup compiles of the pow2 bucket ladder.
    recompile_warmup_ticks: int = 256
    # metric-instance label: with N in-process engines (the scale-out
    # router) each engine's serve.* instruments get this label inserted
    # after the family prefix (serve.queue_depth -> serve.r0.queue_depth)
    # so replicas stop clobbering each other's process-wide gauges. ""
    # (the default) keeps the single-engine names byte-identical.
    metrics_label: str = ""

    def __post_init__(self):
        if self.block_size < 1 or (self.block_size & (self.block_size - 1)):
            raise ValueError("block_size must be a power of two")
        if self.prefill_chunk < 0:
            raise ValueError("prefill_chunk must be >= 0 (0 disables)")
        if self.spec_k < 0:
            raise ValueError("spec_k must be >= 0 (0 disables)")
        if self.queue_bound < 0:
            raise ValueError("queue_bound must be >= 0 (0 = unbounded)")
        if self.tenant_max_inflight < 0:
            raise ValueError(
                "tenant_max_inflight must be >= 0 (0 = uncapped)"
            )
        if self.kv_quant not in ("none", "int8", "fp8"):
            raise ValueError(
                f"kv_quant must be 'none', 'int8' or 'fp8', got "
                f"{self.kv_quant!r}"
            )
        if self.weight_quant not in ("none", "int8"):
            raise ValueError(
                f"weight_quant must be 'none' or 'int8', got "
                f"{self.weight_quant!r}"
            )
        if self.metrics_label and not all(
            c.isalnum() or c in "_-" for c in self.metrics_label
        ):
            raise ValueError(
                f"metrics_label must be [A-Za-z0-9_-]*, got "
                f"{self.metrics_label!r}"
            )
        # malformed class specs fail at construction, not mid-serve
        parse_classes(self.classes)
        if self.num_blocks <= 0:
            per_seq = -(-self.max_model_len // self.block_size)
            self.num_blocks = 1 + self.num_slots * per_seq


@dataclass
class SharedPrograms:
    """The engine's compiled-program bundle, shareable across replicas.

    Every jitted step the engine builds closes over ``cfg`` ONLY — slot
    count, bucket widths and sampling state all arrive as (bucketed)
    arguments. Data-parallel replicas of the same model therefore trace
    and compile the exact same programs; without sharing, each replica
    re-traces its own copies and an N-replica router multiplies the warmup
    compile bill N ways (``TRACE_COUNTS`` counts traces, so the router's
    compile-count gate would catch it). The first replica builds the
    bundle, later replicas receive it via ``InferenceEngine(programs=...)``
    — adding a replica adds ZERO compiles. Donation is per-call, so a
    shared program donates each caller's own pool buffers safely."""

    cfg: TransformerConfig
    prefill: Any
    scatter: Any
    sample: Any
    decode_step: Any
    prefill_chunk_step: Any
    verify_step: Any
    cow: Any


class InferenceEngine:
    """Continuous-batching generation over a fixed slot batch.

    ``submit()`` enqueues, ``step()`` advances every in-flight request by
    one token (and every in-flight prefill by one chunk), ``generate()``
    streams events, ``run()`` drains to completion. Single-threaded by
    design: callers own the pump loop.

    Threading contract (lock-discipline audit, docs/static-analysis.md):
    the engine holds no locks because only the pump thread touches its
    state. Anything another thread needs — the exporter's HTTP handlers,
    bench readers — goes through the thread-safe surfaces the engine
    *publishes into*: the metrics registry gauges/counters and the
    RequestTracer (both internally locked). Do not hand live engine or
    scheduler attributes to another thread."""

    def __init__(self, params, cfg: TransformerConfig,
                 config: Optional[EngineConfig] = None,
                 programs: Optional[SharedPrograms] = None):
        if not supports_cached_decode(cfg):
            raise ValueError(
                f"config {cfg.model_type!r} has no cached-decode path; the "
                "serving engine requires supports_cached_decode(cfg)"
            )
        self.cfg = cfg
        self.config = config or EngineConfig()
        ec = self.config
        # int8 decode weights are quantized ONCE at construction; the jitted
        # steps receive the QuantizedWeight leaves and dispatch the
        # decode-path matmuls through decode_matmul/xla_q8 (dequantizing
        # in-kernel). weight_quant="none" keeps the params bit-identical.
        self.params = (
            quantize_decode_params(params) if ec.weight_quant == "int8"
            else params
        )

        L = cfg.num_hidden_layers
        shape = (L, ec.num_blocks, ec.block_size, cfg.num_key_value_heads,
                 cfg.head_dim)
        # kv_quant="int8" allocates QuantizedKV pools (int8 payload + f32
        # scale sidecar) behind the same pytree surface; every jitted step,
        # the CoW copy and the prefill scatter thread them unchanged
        self.k_pool = make_kv_pool(shape, ec.kv_quant, cfg.dtype)
        self.v_pool = make_kv_pool(shape, ec.kv_quant, cfg.dtype)
        self.blocks = KVBlockManager(ec.num_blocks, ec.block_size)
        self.prefix_cache = (
            PrefixCache(self.blocks) if ec.prefix_cache else None
        )
        # weight-publication epoch: bumped by swap_weights() even when the
        # prefix cache is disabled, so "which weights produced this
        # engine's KV" is always observable
        self.cache_epoch = 0
        # observability registry view: with a metrics_label every serve.*
        # instrument this engine (and its tracer) creates carries the
        # instance label — N router replicas stop clobbering each other's
        # process-wide gauges; unlabelled stays the plain shared registry
        reg = get_registry()
        if ec.metrics_label:
            reg = LabelledRegistry(reg, ec.metrics_label)
        self._registry = reg
        # per-request lifecycle tracing (request_trace.py): the scheduler
        # reports queued/admitted/preempted, the engine reports prefill/
        # first-token/finished — together they feed serve.queue_wait_s and
        # serve.tpot_s and the /debug/requests timelines
        self.tracer = RequestTracer(ec.num_slots, registry=reg)
        # draft-then-verify speculation: resolve the drafting strategy up
        # front (a typo'd spec_draft fails at construction, not mid-serve)
        # and widen admission headroom for the per-tick k-token growth. An
        # ops-config pin outranks the engine knob — including for the
        # enabled/disabled decision, so a pinned `off` also releases the
        # admission headroom and the per-tick draft calls, and a pinned
        # real strategy can switch speculation ON over a spec_draft="off"
        # engine (spec_k still gates: k=0 never speculates).
        from veomni_tpu.ops.kernel_registry import KERNEL_REGISTRY
        from veomni_tpu.serving.spec_decode import resolve_draft_fn

        effective_draft = (
            KERNEL_REGISTRY.pinned("spec_draft") or ec.spec_draft
        )
        self._spec_enabled = ec.spec_k > 0 and effective_draft != "off"
        self._draft_fn = (
            resolve_draft_fn(ec.spec_draft) if self._spec_enabled else None
        )
        spec_headroom = (
            -(-ec.spec_k // ec.block_size) if self._spec_enabled else 0
        )
        self.scheduler = Scheduler(ec.num_slots, self.blocks,
                                   tracer=self.tracer,
                                   prefix_cache=self.prefix_cache,
                                   spec_headroom_blocks=spec_headroom,
                                   classes=parse_classes(ec.classes),
                                   queue_bound=ec.queue_bound,
                                   tenant_max_inflight=ec.tenant_max_inflight)

        # compiled-program bundle: built once here, or adopted from a peer
        # replica with the same model config (SharedPrograms) so adding a
        # data-parallel replica adds zero traces/compiles
        if programs is not None:
            if programs.cfg != cfg:
                raise ValueError(
                    "SharedPrograms built for a different model config; "
                    "replicas can only share programs for the same model"
                )
            self.programs = programs
        else:
            self.programs = SharedPrograms(
                cfg=cfg,
                # prefill is the SAME jitted program greedy_generate uses
                # (shared prompt buckets, shared TRACE_COUNTS["prefill"])
                prefill=decode_mod._jitted(cfg)[0],
                scatter=jax.jit(
                    decode_mod.scatter_prompt_cache, donate_argnums=(0,)
                ),
                sample=jax.jit(decode_mod.sample_tokens),
                decode_step=self._build_decode_step(),
                prefill_chunk_step=self._build_prefill_chunk_step(),
                # built unconditionally — jit tracing is lazy, so a
                # non-speculative engine never pays for it, and a
                # speculative peer can adopt the bundle
                verify_step=self._build_verify_step(),
                # copy-on-write block duplication: src/dst are traced
                # scalars, so this compiles exactly once per bundle
                cow=jax.jit(
                    lambda k, v, src, dst: decode_mod.copy_block(
                        (k, v), src, dst
                    ),
                    donate_argnums=(0, 1),
                ),
            )
        self._prefill = self.programs.prefill
        self._scatter = self.programs.scatter
        self._sample = self.programs.sample
        self._decode_step = self.programs.decode_step
        self._prefill_chunk_step = self.programs.prefill_chunk_step
        self._verify_step = (
            self.programs.verify_step if self._spec_enabled else None
        )
        self._cow = self.programs.cow

        self._outputs: Dict[str, RequestOutput] = {}
        self._req_counter = 0
        self._step_counter = 0
        # metrics: TTFT accumulators (lifetime + window) + a
        # decode-throughput window + prefix-cache totals.
        #
        # The WINDOW accumulators are the one engine surface read AND
        # reset from outside the pump thread: metrics(reset_window=True)
        # from two concurrent scrapers (router poll + exporter) used to
        # race the reset — scraper A computes rates, scraper B zeroes the
        # window under it, A's reset then re-zeroes a window B already
        # claimed and a whole window of tokens vanishes from both
        # readings. Snapshot+reset is now atomic under _metrics_lock
        # (pump-side increments take it too; it is uncontended off-scrape).
        self._metrics_lock = threading.Lock()
        self._ttft_sum = 0.0
        self._ttft_n = 0
        self._win_ttft_sum = 0.0  # guarded-by: _metrics_lock
        self._win_ttft_n = 0  # guarded-by: _metrics_lock
        self._total_generated = 0
        self._window_tokens = 0  # guarded-by: _metrics_lock
        self._window_t0 = time.perf_counter()  # guarded-by: _metrics_lock
        self._prompt_tokens_total = 0
        self._cached_tokens_total = 0
        self._prefill_chunks_total = 0
        # speculative-decoding accounting: lifetime totals + a window pair
        # for the acceptance-rate gauge (resets with the metrics window)
        self._spec_proposed_total = 0
        self._spec_accepted_total = 0
        self._win_spec_proposed = 0  # guarded-by: _metrics_lock
        self._win_spec_accepted = 0  # guarded-by: _metrics_lock
        # QoS / overload accounting: load-shed + deadline outcomes
        # (lifetime totals) and the goodput window — tokens from requests
        # that finished WITHIN their deadline (deadline-free requests
        # always qualify), attributed to the window their finish lands in
        self._rejected_total = 0
        self._shed_tokens_total = 0
        self._deadline_miss_total = 0
        self._goodput_tokens_total = 0
        self._win_goodput_tokens = 0  # guarded-by: _metrics_lock
        self._m_requests = reg.counter("serve.requests")
        self._m_tokens = reg.counter("serve.generated_tokens")
        self._m_ttft = reg.histogram("serve.ttft_s")
        self._m_queue = reg.gauge("serve.queue_depth")
        self._m_running = reg.gauge("serve.num_running")
        self._m_kv = reg.gauge("serve.kv_utilization")
        self._m_preempt = reg.gauge("serve.preemptions")
        self._m_tps = reg.gauge("serve.decode_tokens_per_sec")
        self._m_hit_rate = reg.gauge("serve.prefix_hit_rate")
        self._m_cached_tokens = reg.counter("serve.cached_tokens")
        self._m_chunks = reg.counter("serve.prefill_chunks")
        # speculative decoding: drafted tokens sent to verification, how
        # many were accepted, and the window acceptance rate — the live
        # "is speculation paying for its verify width" gauges
        self._m_spec_proposed = reg.counter("serve.spec_proposed")
        self._m_spec_accepted = reg.counter("serve.spec_accepted")
        self._m_spec_rate = reg.gauge("serve.spec_acceptance_rate")
        # overload / QoS outcomes: requests load-shed at submit (the
        # 429-equivalent), the offered tokens those sheds turned away,
        # deadline outcomes (cancelled waiting/prefilling + finished-late),
        # and goodput — tokens from requests that met their deadline
        self._m_rejected = reg.counter("serve.rejected")
        self._m_shed_tokens = reg.counter("serve.shed_tokens")
        self._m_deadline_misses = reg.counter("serve.deadline_misses")
        self._m_goodput = reg.gauge("serve.goodput_tokens_per_sec")
        # HBM capacity accounting (observability/devmem.py): pool bytes are
        # static per engine; the concurrent-sequence estimates answer "how
        # many max-length users fit" (total, and with the blocks free now)
        self._m_kv_pool_bytes = reg.gauge("serve.kv_pool_bytes")
        self._m_kv_block_bytes = reg.gauge("serve.kv_block_bytes")
        self._m_kv_max_seqs = reg.gauge("serve.kv_max_concurrent_seqs")
        self._m_kv_free_seqs = reg.gauge("serve.kv_free_concurrent_seqs")
        cap = self.kv_capacity()
        self._m_kv_pool_bytes.set(cap["pool_bytes"])
        self._m_kv_block_bytes.set(cap["block_bytes"])
        self._m_kv_max_seqs.set(cap["max_concurrent_seqs"])
        self._m_kv_free_seqs.set(cap["free_concurrent_seqs"])
        # serving-side recompile detection over the decode-bucket trace
        # counters: armed after the warmup grace window (step()), so a
        # mid-run compile storm gets the same loud RECOMPILE treatment the
        # train step has had since PR 4
        self._recompile_detector = None
        if ec.recompile_warmup_ticks > 0:
            from veomni_tpu.observability.goodput import RecompileDetector

            # the WHOLE trace-count dict is watched (no key filter): every
            # engine-side compile counter — including the chunked-prefill
            # one, TRACE_COUNTS["paged_prefill"], and any counter a future
            # prefill/decode path adds — is storm-detected without anyone
            # remembering to extend a key list. Chunked-prefill coverage is
            # pinned by a regression test (test_fleet_observatory.py).
            self._recompile_detector = RecompileDetector(
                [("serve_decode", decode_mod.TRACE_COUNTS)], registry=reg,
            )

    # ------------------------------------------------------------ jit plumbing
    def _build_decode_step(self):
        cfg = self.cfg

        def impl(params, k_pool, v_pool, tables, positions, tokens, keys,
                 temps, top_ks, top_ps):
            decode_mod.TRACE_COUNTS["paged_decode"] += 1  # trace-time only
            logits, (k_pool, v_pool) = decode_mod.paged_decode_step(
                params, cfg, (k_pool, v_pool), tables, positions, tokens
            )
            # per-slot key split mirrors the scan decode's (carry, sample)
            split = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
            nxt = decode_mod.sample_tokens(
                logits, split[:, 1], temps, top_ks, top_ps
            )
            return nxt, split[:, 0], k_pool, v_pool

        from veomni_tpu.observability.cost import instrument_jit

        return instrument_jit(
            "paged_decode", jax.jit(impl, donate_argnums=(1, 2)),
            # args: (params, k_pool, v_pool, tables, ...) — the table width
            # bucket is the only varying shape
            bucket_fn=lambda a: f"s{a[3].shape[0]}_nbb{a[3].shape[1]}",
        )

    def _build_prefill_chunk_step(self):
        cfg = self.cfg

        def impl(params, k_pool, v_pool, table, start, tokens, chunk_len,
                 chunk_bucket):
            decode_mod.TRACE_COUNTS["paged_prefill"] += 1  # trace-time only
            return decode_mod.paged_prefill_step(
                params, cfg, (k_pool, v_pool), table, start, tokens,
                chunk_len, chunk_bucket,
            )

        from veomni_tpu.observability.cost import instrument_jit

        return instrument_jit(
            "paged_prefill",
            jax.jit(impl, static_argnums=(7,), donate_argnums=(1, 2)),
            static_argnums=(7,),
            # (chunk bucket, table-width bucket) — the two compile axes
            bucket_fn=lambda a: f"cb{a[7]}_nbb{a[3].shape[0]}",
        )

    def _build_verify_step(self):
        cfg = self.cfg

        def impl(params, k_pool, v_pool, tables, positions, tokens, n_input,
                 keys, temps, top_ks, top_ps):
            decode_mod.TRACE_COUNTS["paged_verify"] += 1  # trace-time only
            logits, (k_pool, v_pool) = decode_mod.paged_verify_step(
                params, cfg, (k_pool, v_pool), tables, positions, tokens,
                n_input,
            )
            targets, n_emit, new_keys = decode_mod.verify_accept(
                logits, tokens, n_input, keys, temps, top_ks, top_ps
            )
            return targets, n_emit, new_keys, k_pool, v_pool

        from veomni_tpu.observability.cost import instrument_jit

        return instrument_jit(
            "paged_verify", jax.jit(impl, donate_argnums=(1, 2)),
            # args: (params, k_pool, v_pool, tables, positions, tokens, ...)
            # — (table-width bucket, verify-width bucket) are the two
            # varying shapes, each a power of two: O(log2 x log2) compiles
            bucket_fn=lambda a: (
                f"s{a[3].shape[0]}_nbb{a[3].shape[1]}_kb{a[5].shape[1]}"
            ),
        )

    # ----------------------------------------------------------------- intake
    def submit(self, request: Union[Request, Iterable[int]],
               sampling: Optional[SamplingParams] = None) -> str:
        """Enqueue a request (a ``Request`` or a bare prompt-id iterable).
        Returns the request id; tokens arrive via ``step()`` events.

        Under overload (waiting queue at ``queue_bound`` or the tenant at
        ``tenant_max_inflight``) the request is **load-shed**: the returned
        id's ``RequestOutput`` is already terminal with
        ``finish_reason="rejected"`` (the 429-equivalent; no exception — an
        overloaded server refusing work is an outcome, not an error).
        Malformed requests (empty prompt, over-length, unknown priority
        class) still raise ``ValueError``."""
        fault_point("serve.admit")
        if not isinstance(request, Request):
            request = Request(prompt_ids=[int(t) for t in request],
                              sampling=sampling or SamplingParams())
        if not request.request_id:
            # skip over user-supplied ids that happen to look like ours
            while f"req-{self._req_counter}" in self._outputs:
                self._req_counter += 1
            request.request_id = f"req-{self._req_counter}"
            self._req_counter += 1
        if request.request_id in self._outputs:
            raise ValueError(f"duplicate request id {request.request_id!r}")
        if not request.prompt_ids:
            raise ValueError("empty prompt")
        sp = request.sampling
        if sp.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = len(request.prompt_ids) + sp.max_new_tokens
        if total > self.config.max_model_len:
            raise ValueError(
                f"prompt+max_new_tokens={total} exceeds max_model_len="
                f"{self.config.max_model_len}"
            )
        if self.blocks.blocks_for(total) > self.config.num_blocks - 1:
            raise ValueError(
                f"request needs {self.blocks.blocks_for(total)} blocks; pool "
                f"has {self.config.num_blocks - 1}"
            )
        if request.deadline_s is not None and request.deadline_s < 0:
            raise ValueError("deadline_s must be >= 0 (None disables)")
        seq = SequenceState(
            request=request,
            rng=np.asarray(jax.random.PRNGKey(sp.seed)),
        )
        out = RequestOutput(
            request_id=request.request_id,
            prompt_ids=list(request.prompt_ids),
        )
        # may raise ValueError (unknown priority class) BEFORE the output
        # registers — malformed is an error, overloaded is an outcome
        accepted = self.scheduler.add(seq)
        self._outputs[request.request_id] = out
        self._m_requests.inc()
        if not accepted:
            # load-shed: terminal REJECTED, counted with the offered work
            # (prompt + requested generation) it turned away
            out.finished = True
            out.finish_reason = "rejected"
            shed = len(request.prompt_ids) + sp.max_new_tokens
            self._rejected_total += 1
            self._shed_tokens_total += shed
            self._m_rejected.inc()
            self._m_shed_tokens.inc(shed)
            self.tracer.on_rejected(request.request_id)
            return request.request_id
        self._m_queue.set(self.scheduler.queue_depth)
        return request.request_id

    # ---------------------------------------------------------- weight swap
    def swap_weights(self, params) -> Dict[str, int]:
        """Hot-swap the engine's weights in place, invalidating all cached
        KV. ``params`` is the UNQUANTIZED pytree; the engine re-applies
        its own ``weight_quant`` storage transform exactly as at
        construction, so a quantized tier swaps quantized buffers.

        Contract (docs/serving.md "Versioned weight publication"):

        * the engine must be drained — no waiting or running sequences
          (the router's PUBLISHING state guarantees this; a direct caller
          gets a hard error, never a mid-stream weight change);
        * the payload must be shape/dtype-congruent with the current
          weights — a mismatched payload is a different model, refused
          before any state changes (it would also silently retrace every
          jitted program);
        * the prefix cache is flushed under a bumped ``cache_epoch``
          (stale KV from the old weights becomes unreachable) and the
          block-manager no-leak identity is conserved across the flush;
        * ZERO new traces: the jitted steps take params as per-call
          arguments, so congruent buffers reuse every compiled program.

        Returns ``{"flushed_blocks": n, "cache_epoch": e}``.
        """
        if self.scheduler.has_work:
            raise RuntimeError(
                "swap_weights on a busy engine: drain waiting/running "
                "sequences first (the router's PUBLISHING state does this)"
            )
        new_params = (
            quantize_decode_params(params)
            if self.config.weight_quant == "int8" else params
        )
        old_leaves, old_def = jax.tree_util.tree_flatten(self.params)
        new_leaves, new_def = jax.tree_util.tree_flatten(new_params)
        if old_def != new_def:
            raise ValueError(
                "swap_weights payload tree structure differs from the "
                "serving weights: a publish must carry the same model"
            )
        for i, (o, n) in enumerate(zip(old_leaves, new_leaves)):
            o_sig = (getattr(o, "shape", None), getattr(o, "dtype", None))
            n_sig = (getattr(n, "shape", None), getattr(n, "dtype", None))
            if o_sig != n_sig:
                raise ValueError(
                    f"swap_weights payload leaf {i} is {n_sig}, serving "
                    f"weights have {o_sig}: shape/dtype-incongruent "
                    "payloads are refused (they would retrace)"
                )
        self.params = new_params
        flushed = (
            self.prefix_cache.flush() if self.prefix_cache is not None
            else 0
        )
        self.cache_epoch += 1
        self._registry.counter("serve.weights_swaps").inc()
        self._registry.counter("serve.weights_flushed_blocks").inc(flushed)
        return {"flushed_blocks": flushed, "cache_epoch": self.cache_epoch}

    # ------------------------------------------------------------------ drive
    @property
    def has_work(self) -> bool:
        return self.scheduler.has_work

    def step(self) -> List[StreamEvent]:
        """One engine tick: expire deadlines, admit, advance every in-flight
        prefill by one chunk, secure blocks, batched decode. Returns every
        token event produced this tick (cancellations produce none — their
        terminal status lands on the RequestOutput)."""
        events: List[StreamEvent] = []
        self._expire_deadlines()
        for seq in self.scheduler.admit():
            self._start_prefill(seq)
        # one chunk per prefilling sequence per tick: decode of running
        # requests interleaves between chunks, so a long prompt's TTFT cost
        # to everyone else is bounded by a chunk, not by the prompt
        prefilling = [s for _, s in self.scheduler.running() if s.prefilling]
        for seq in prefilling:
            with span("serve.prefill"):
                events.extend(self._prefill_tick(seq))
        self.scheduler.ensure_decode_capacity()
        decodable = [(i, s) for i, s in self.scheduler.running()
                     if not s.prefilling]
        if decodable:
            with span("serve.decode"):
                events.extend(self._decode_tick(decodable))
        elif not events and not prefilling and self.scheduler.has_work:
            raise RuntimeError(
                "scheduler stalled: waiting requests but nothing running "
                "and nothing admissible (pool misconfigured?)"
            )
        self._step_counter += 1
        self._m_queue.set(self.scheduler.queue_depth)
        self._m_running.set(self.scheduler.num_running)
        self._m_kv.set(self.blocks.utilization())
        self._m_preempt.set(self.scheduler.preemption_count)
        per_seq = max(1, self.blocks.blocks_for(self.config.max_model_len))
        self._m_kv_free_seqs.set(self.blocks.num_free // per_seq)
        det = self._recompile_detector
        if det is not None:
            grace = self.config.recompile_warmup_ticks
            if self._step_counter == grace:
                det.arm()  # warmup bucket compiles absorbed
            elif self._step_counter > grace:
                det.check()
        le = self.config.log_every_steps
        if le and self._step_counter % le == 0:
            # non-resetting read: periodic logging must not clobber the
            # throughput window of an external metrics() consumer
            m = self.metrics(reset_window=False)
            logger.info(
                "serve step %d | %s", self._step_counter,
                " ".join(f"{k}={v:.4g}" for k, v in sorted(m.items())),
            )
        return events

    def generate(self, requests: Optional[Iterable] = None
                 ) -> Iterator[StreamEvent]:
        """Streaming interface: submit ``requests`` (if given), then yield
        token events until all in-flight work drains. More requests may be
        ``submit()``-ed between yields."""
        for r in requests or ():
            self.submit(r)
        while self.has_work:
            yield from self.step()

    def run(self, requests: Optional[Iterable] = None
            ) -> Dict[str, RequestOutput]:
        """Drain ``generate()`` and return {request_id: RequestOutput} for
        every finished request, handing ownership to the caller — retained
        outputs are released so a long-running pump loop doesn't accumulate
        one token list per request ever served."""
        for _ in self.generate(requests):
            pass
        done = {rid: o for rid, o in self._outputs.items() if o.finished}
        for rid in done:
            del self._outputs[rid]
        return done

    def backdate_submit_time(self, request_id: str,
                             submit_time: float) -> None:
        """Rewind a just-submitted request's deadline clock to an upstream
        arrival time. ``deadline_s`` measures from when the USER submitted;
        a front door (the scale-out router) that held the request in its
        own QoS queue forwards the original intake time here so router
        wait counts against the deadline exactly like engine queue wait.
        Only ever moves the clock BACK (min), and only while the request
        is still in flight."""
        seq = self._find_seq(request_id)
        if seq is not None:
            seq.submit_time = min(seq.submit_time, float(submit_time))

    def get_output(self, request_id: str) -> Optional[RequestOutput]:
        """Read-only peek at a request's output, in flight or finished,
        without releasing it. The router's replica-kill path uses this to
        decide each stranded request's fate: no tokens yet -> safe to
        re-dispatch to a survivor; tokens already streamed -> terminal
        ``cancelled`` (re-running it elsewhere would duplicate output)."""
        return self._outputs.get(request_id)

    def pop_output(self, request_id: str) -> Optional[RequestOutput]:
        """Release and return one finished request's output (streaming
        callers pop after seeing its finished event). Refuses while the
        request is in flight — the engine still appends tokens to it."""
        out = self._outputs.get(request_id)
        if out is not None and not out.finished:
            raise ValueError(f"request {request_id!r} is still in flight")
        return self._outputs.pop(request_id, None)

    # ----------------------------------------------------- QoS / cancellation
    def cancel(self, request_id: str, reason: str = "cancelled") -> bool:
        """Cancel an in-flight (waiting, prefilling, or decoding) request:
        its blocks — including partially-claimed chunked-prefill blocks and
        a pinned copy-on-write source — return to the pool, and its output
        turns terminal with ``finish_reason=reason``. Tokens already
        emitted stay on the output. Returns False when the id is unknown
        or already finished."""
        out = self._outputs.get(request_id)
        if out is None or out.finished:
            return False
        seq = self._find_seq(request_id)
        if seq is None:
            return False
        self._cancel_seq(seq, reason)
        return True

    def _find_seq(self, request_id: str) -> Optional[SequenceState]:
        for s in self.scheduler.waiting:
            if s.seq_id == request_id:
                return s
        for _, s in self.scheduler.running():
            if s.seq_id == request_id:
                return s
        return None

    def _expire_deadlines(self) -> None:
        """Cancel waiting/prefilling requests past their deadline (terminal
        ``deadline`` status) so pool capacity goes to requests that can
        still meet theirs. Runs at the top of every tick, BEFORE admission,
        so freed blocks admit someone else the same tick."""
        for seq in self.scheduler.expired():
            self._cancel_seq(seq, "deadline")

    def _cancel_seq(self, seq: SequenceState, reason: str) -> None:
        self.scheduler.cancel(seq)
        out = self._outputs[seq.seq_id]
        out.finished = True
        out.finish_reason = reason
        if reason == "deadline":
            out.deadline_missed = True
            self._deadline_miss_total += 1
            self._m_deadline_misses.inc()
        # offered work the cancellation turned away, symmetric with the
        # submit-time rejection accounting: a cancel that produced NOTHING
        # (expired in the queue / mid-initial-prefill) sheds prompt +
        # requested generation exactly like a reject; one that already
        # emitted tokens sheds only the un-generated remainder (the
        # delivered tokens stay on the output and were counted generated)
        if seq.generated:
            shed = seq.request.sampling.max_new_tokens - len(seq.generated)
        else:
            shed = (len(seq.request.prompt_ids)
                    + seq.request.sampling.max_new_tokens)
        if shed > 0:
            self._shed_tokens_total += shed
            self._m_shed_tokens.inc(shed)
        tl = self.tracer.on_finished(seq.seq_id, reason, len(seq.generated))
        if tl is not None:
            out.queue_wait_s = tl.queue_wait_s
            out.tpot_s = tl.tpot_s
            out.preemptions = tl.preemptions
        self._m_queue.set(self.scheduler.queue_depth)

    # --------------------------------------------------------------- internals
    def _start_prefill(self, seq: SequenceState) -> None:
        """Per-admission bookkeeping: prefix-cache accounting and the
        copy-on-write device copy for a fully-cached prompt's divergence
        block (the copy MUST land before any chunk writes into it)."""
        p = len(seq.recompute_prompt)
        self._prompt_tokens_total += p
        if seq.cached_tokens:
            self._cached_tokens_total += seq.cached_tokens
            self._m_cached_tokens.inc(seq.cached_tokens)
        self._m_hit_rate.set(
            self._cached_tokens_total / max(1, self._prompt_tokens_total)
        )
        out = self._outputs.get(seq.seq_id)
        if out is not None:
            out.cached_tokens = seq.cached_tokens
        if seq.cow_src is not None:
            dst = self.blocks.table(seq.seq_id)[-1]
            self.k_pool, self.v_pool = self._cow(
                self.k_pool, self.v_pool,
                jnp.int32(seq.cow_src), jnp.int32(dst),
            )
            # the source was pinned at admission so claiming fresh blocks
            # could not evict it before this copy; release it now
            self.blocks.release_block(seq.cow_src)
            seq.cow_src = None

    def _prefill_tick(self, seq: SequenceState) -> List[StreamEvent]:
        """Advance one sequence's prefill by one chunk. The legacy
        monolithic path (cache miss + chunking off) is kept verbatim so a
        cache-off engine is byte-identical to the pre-cache one."""
        fault_point("serve.prefill")
        if seq.cached_tokens == 0 and self.config.prefill_chunk <= 0:
            return self._prefill_monolithic(seq)
        return self._prefill_chunk(seq)

    def _prefill_monolithic(self, seq: SequenceState) -> List[StreamEvent]:
        bs = self.config.block_size
        prompt = seq.recompute_prompt
        pt = len(prompt)
        pb = decode_mod._bucket_pow2(pt, floor=max(16, bs))
        tokens = jnp.zeros((1, pb), jnp.int32).at[0, :pt].set(
            jnp.asarray(prompt, jnp.int32)
        )
        logits, caches = self._prefill(
            self.params, tokens, jnp.int32(pt), pb, pb
        )
        # scatter the contiguous prompt cache into this sequence's blocks;
        # tail entries past the real allocation point at the null block
        ids = self.blocks.table(seq.seq_id)
        ids = ids + [KVBlockManager.NULL_BLOCK] * (pb // bs - len(ids))
        self.k_pool, self.v_pool = self._scatter(
            (self.k_pool, self.v_pool), caches,
            jnp.asarray(ids, jnp.int32),
        )
        self._prefill_chunks_total += 1
        self._m_chunks.inc()
        return self._finish_prefill(seq, logits)

    def _prefill_chunk(self, seq: SequenceState) -> List[StreamEvent]:
        bs = self.config.block_size
        prompt = seq.recompute_prompt
        p = len(prompt)
        start = seq.prefill_pos
        budget = self.config.prefill_chunk or (p - start)
        clen = min(budget, p - start)
        cb = decode_mod._bucket_pow2(clen, floor=max(16, bs))
        tokens = jnp.zeros((cb,), jnp.int32).at[:clen].set(
            jnp.asarray(prompt[start:start + clen], jnp.int32)
        )
        ids = self.blocks.table(seq.seq_id)
        nbb = decode_mod._bucket_pow2(len(ids), floor=1)
        table = np.zeros(nbb, np.int32)  # null-block padded
        table[: len(ids)] = ids
        logits, (self.k_pool, self.v_pool) = self._prefill_chunk_step(
            self.params, self.k_pool, self.v_pool, jnp.asarray(table),
            jnp.int32(start), tokens, jnp.int32(clen), cb,
        )
        seq.prefill_pos = start + clen
        self._prefill_chunks_total += 1
        self._m_chunks.inc()
        if seq.prefill_pos < p:
            return []  # more chunks next tick; decode interleaves meanwhile
        return self._finish_prefill(seq, logits)

    def _finish_prefill(self, seq: SequenceState,
                        logits) -> List[StreamEvent]:
        """Shared prefill tail: sample the first token from the last prompt
        row's logits, publish the full prompt blocks to the prefix cache,
        and flip the sequence into the decode batch."""
        sp = seq.request.sampling
        rng, sub = jax.random.split(seq.rng)
        seq.rng = np.asarray(rng)
        first = int(self._sample(
            logits.astype(jnp.float32), sub[None],
            jnp.full((1,), sp.temperature, jnp.float32),
            jnp.full((1,), sp.top_k, jnp.int32),
            jnp.full((1,), sp.top_p, jnp.float32),
        )[0])
        pt = len(seq.recompute_prompt)
        seq.prefill_len = pt
        seq.pos = pt  # the pending token's write position
        seq.prefill_pos = pt
        seq.prefilling = False
        # prompt blocks become shareable the moment they hold real KV: a
        # staggered arrival with the same system prompt hits immediately
        self.scheduler.cache_insert(seq)
        self.tracer.on_prefill_done(seq.seq_id,
                                    cached_tokens=seq.cached_tokens)
        if seq.first_token_time is None:
            seq.first_token_time = time.perf_counter()
            ttft = seq.first_token_time - seq.submit_time
            self._outputs[seq.seq_id].ttft_s = ttft
            self._ttft_sum += ttft
            self._ttft_n += 1
            with self._metrics_lock:
                self._win_ttft_sum += ttft
                self._win_ttft_n += 1
            self._m_ttft.observe(ttft)
            self.tracer.on_first_token(seq.seq_id)
        else:
            # post-preemption re-admission: this prefill's resume token is
            # part of the DECODE phase (it lands after first_token), so it
            # counts toward the tracer's per-tick decode-token tally —
            # serve.tpot_s divides by exactly the tokens inside its wall
            self.tracer.on_decode_tokens(seq.seq_id, 1)
        return [self._emit(seq, first)]

    def _decode_tick(
        self, running: List[Tuple[int, SequenceState]]
    ) -> List[StreamEvent]:
        fault_point("serve.decode_tick")
        if self._spec_enabled:
            return self._spec_decode_tick(running)
        return self._plain_decode_tick(running)

    def _fill_slot_arrays(self, running: List[Tuple[int, SequenceState]]):
        """Per-slot batch rows shared by the plain and verify decode
        ticks: null-padded block tables (width = the power-of-two bucket
        of the widest running table — the step's only varying table
        shape), positions, PRNG keys and per-slot sampling params. Keeping
        ONE assembly path is what keeps the two ticks' batches — and
        therefore their token streams — in lockstep."""
        nbb = decode_mod._bucket_pow2(
            max(self.blocks.num_allocated(s.seq_id) for _, s in running),
            floor=1,
        )
        S = self.config.num_slots
        tables = np.zeros((S, nbb), np.int32)  # null-block padded
        positions = np.zeros(S, np.int32)
        keys = np.zeros((S, 2), np.uint32)
        temps = np.zeros(S, np.float32)
        top_ks = np.zeros(S, np.int32)
        top_ps = np.ones(S, np.float32)
        for slot, seq in running:
            tbl = self.blocks.table(seq.seq_id)
            tables[slot, : len(tbl)] = tbl
            positions[slot] = seq.pos
            keys[slot] = seq.rng
            sp = seq.request.sampling
            temps[slot] = sp.temperature
            top_ks[slot] = sp.top_k
            top_ps[slot] = sp.top_p
        return tables, positions, keys, temps, top_ks, top_ps

    def _plain_decode_tick(
        self, running: List[Tuple[int, SequenceState]]
    ) -> List[StreamEvent]:
        tables, positions, keys, temps, top_ks, top_ps = (
            self._fill_slot_arrays(running)
        )
        tokens = np.zeros(self.config.num_slots, np.int32)
        for slot, seq in running:
            tokens[slot] = seq.last_token

        nxt, new_keys, self.k_pool, self.v_pool = self._decode_step(
            self.params, self.k_pool, self.v_pool,
            jnp.asarray(tables), jnp.asarray(positions), jnp.asarray(tokens),
            jnp.asarray(keys), jnp.asarray(temps), jnp.asarray(top_ks),
            jnp.asarray(top_ps),
        )
        nxt = np.asarray(nxt)
        new_keys = np.asarray(new_keys)

        events = []
        for slot, seq in running:
            seq.rng = new_keys[slot]
            seq.pos += 1  # the freshly sampled token's write position
            # per-tick emitted-token count: keeps serve.tpot_s honest for
            # any multi-token tick (the verify path lands several)
            self.tracer.on_decode_tokens(seq.seq_id, 1)
            events.append(self._emit(seq, int(nxt[slot])))
        return events

    def _spec_decode_tick(
        self, running: List[Tuple[int, SequenceState]]
    ) -> List[StreamEvent]:
        """Draft-then-verify decode tick: host-side drafting per slot,
        best-effort speculative block claims, ONE batched verify step, then
        per-slot accept/rollback. Token-exact with the one-token path: the
        verify step replays the same logits contexts and the same per-token
        PRNG key schedule, and only emits tokens the target model would
        have emitted anyway."""
        ec = self.config
        bs = ec.block_size
        # 1) draft (host, cheap) + claim blocks for the drafted positions.
        # Per-slot k: a slot whose drafter proposes nothing — or whose
        # remaining token budget is 0 — degrades to k=0 (pure decode for
        # that slot) instead of widening everyone's verify step.
        drafts: Dict[int, List[int]] = {}
        pre_lens: Dict[int, int] = {}
        for slot, seq in running:
            sp = seq.request.sampling
            # a verify tick emits up to k+1 tokens; never draft past the
            # request's remaining budget (parity: the one-token path would
            # have stopped at max_new_tokens too)
            budget = sp.max_new_tokens - len(seq.generated) - 1
            k = min(ec.spec_k, max(0, budget))
            d = list(self._draft_fn(seq.recompute_prompt, k))[:k] if k else []
            if d:
                pre = self.blocks.num_allocated(seq.seq_id)
                k_granted, claimed = self.scheduler.claim_speculative(
                    seq, len(d)
                )
                d = d[:max(0, k_granted)]
                if not d and claimed:
                    # pool too dry to cover even one draft: roll the claim
                    # back immediately, this slot decodes plainly
                    self.blocks.shrink(seq.seq_id, pre)
                else:
                    pre_lens[slot] = pre
            drafts[slot] = d
        if not any(drafts.values()):
            # nothing to verify anywhere: the plain decode step (same
            # compiled program as the non-speculative engine) is strictly
            # cheaper than a kb=2 verify
            return self._plain_decode_tick(running)

        # 2) ONE batched verify step over all slots. kb (committed token +
        # widest draft, power-of-two) and the table-width bucket are the
        # only varying shapes — compile count stays O(log2 k x log2 width).
        kb = decode_mod._bucket_pow2(
            1 + max(len(d) for d in drafts.values()), floor=2
        )
        tables, positions, keys, temps, top_ks, top_ps = (
            self._fill_slot_arrays(running)
        )
        S = ec.num_slots
        tokens = np.zeros((S, kb), np.int32)
        n_input = np.ones(S, np.int32)
        for slot, seq in running:
            d = drafts[slot]
            tokens[slot, 0] = seq.last_token
            if d:
                tokens[slot, 1:1 + len(d)] = d
            n_input[slot] = 1 + len(d)

        targets, n_emit, new_keys, self.k_pool, self.v_pool = (
            self._verify_step(
                self.params, self.k_pool, self.v_pool, jnp.asarray(tables),
                jnp.asarray(positions), jnp.asarray(tokens),
                jnp.asarray(n_input), jnp.asarray(keys), jnp.asarray(temps),
                jnp.asarray(top_ks), jnp.asarray(top_ps),
            )
        )
        targets = np.asarray(targets)
        n_emit = np.asarray(n_emit)
        new_keys = np.asarray(new_keys)

        # 3) per-slot accept + emit + rollback
        events: List[StreamEvent] = []
        for slot, seq in running:
            seq.rng = new_keys[slot]
            m = int(n_emit[slot])
            proposed = len(drafts[slot])
            accepted = m - 1  # drafts matching target sampling, in order
            # truncate at eos / budget BEFORE emitting so the tick's token
            # count (and the accepted rollup) reflect what actually lands
            sp = seq.request.sampling
            emit: List[int] = []
            for j in range(m):
                t = int(targets[slot, j])
                emit.append(t)
                if sp.eos_id >= 0 and t == sp.eos_id:
                    break
                if len(seq.generated) + len(emit) >= sp.max_new_tokens:
                    break
            # accepted drafts that actually LANDED as extra tokens: a tick
            # emitting L tokens saves L-1 decode steps, so an eos/budget
            # truncation caps the rollup at len(emit) - 1 (counting the
            # truncated tick's first token too would overstate the win)
            accepted_emitted = min(accepted, len(emit) - 1)
            if proposed:
                self._spec_proposed_total += proposed
                self._m_spec_proposed.inc(proposed)
                self._spec_accepted_total += accepted_emitted
                self._m_spec_accepted.inc(accepted_emitted)
                with self._metrics_lock:
                    self._win_spec_proposed += proposed
                    self._win_spec_accepted += accepted_emitted
                self._outputs[seq.seq_id].spec_accepted_tokens += (
                    accepted_emitted
                )
            self.tracer.on_decode_tokens(seq.seq_id, len(emit),
                                         spec_accepted=accepted_emitted)
            finished = False
            for t in emit:
                seq.pos += 1  # this token's write position
                ev = self._emit(seq, t)
                events.append(ev)
                if ev.finished:
                    finished = True
                    break
            if finished or slot not in pre_lens:
                continue  # finish freed every block / nothing was claimed
            # rollback: release claimed blocks past what the ACCEPTED
            # extent (plus the pending token's write position) needs — a
            # rejected draft's block goes back to the pool this tick, and
            # the refcounted release can never strand a shared/cached block
            keep = max(pre_lens[slot], seq.pos // bs + 1)
            self.blocks.shrink(seq.seq_id, keep)
        return events

    def _emit(self, seq: SequenceState, token: int) -> StreamEvent:
        """Record a sampled token, finishing the request on eos/length."""
        seq.generated.append(token)
        with self._metrics_lock:
            self._window_tokens += 1
        self._total_generated += 1
        self._m_tokens.inc()
        sp = seq.request.sampling
        out = self._outputs[seq.seq_id]
        out.token_ids.append(token)
        finished = False
        reason = ""
        if sp.eos_id >= 0 and token == sp.eos_id:
            finished, reason = True, "eos"
        elif len(seq.generated) >= sp.max_new_tokens:
            finished, reason = True, "length"
        if finished:
            self.scheduler.finish(seq)
            out.finished = True
            out.finish_reason = reason
            # goodput: every token of a request that finished WITHIN its
            # deadline counts (no deadline = trivially met); a late finish
            # keeps its tokens but is a deadline miss and contributes none
            if seq.deadline_expired(time.perf_counter()):
                out.deadline_missed = True
                self._deadline_miss_total += 1
                self._m_deadline_misses.inc()
            else:
                self._goodput_tokens_total += len(seq.generated)
                with self._metrics_lock:
                    self._win_goodput_tokens += len(seq.generated)
            tl = self.tracer.on_finished(seq.seq_id, reason,
                                         len(seq.generated))
            if tl is not None:
                # surface the lifecycle rollup on the output the caller
                # already holds (bench/SLO tooling reads these, not the
                # tracer)
                out.queue_wait_s = tl.queue_wait_s
                out.tpot_s = tl.tpot_s
                out.preemptions = tl.preemptions
        return StreamEvent(
            request_id=seq.seq_id, token=token,
            index=len(seq.generated) - 1, finished=finished,
            finish_reason=reason,
        )

    # ---------------------------------------------------------------- metrics
    def revoke_metrics(self) -> None:
        """Fence off this engine's labelled metric writes (no-op for an
        unlabelled engine). The router calls this when it abandons a
        WEDGED replica's pump thread: that zombie may still be inside XLA
        and will eventually return and try to bump its ``serve.<rid>.*``
        instruments — after revocation those writes are dropped, so the
        respawned successor (a fresh engine, fresh labelled view, same
        rid) never has its window double-counted by its predecessor."""
        revoke = getattr(self._registry, "revoke", None)
        if revoke is not None:
            revoke()

    def kv_capacity(self) -> Dict[str, float]:
        """Block-pool capacity in operator units (pool bytes + estimated
        max-concurrent max-length sequences); the `/debug/memory` pool
        document (``scripts/serve.py`` wires it to the exporter)."""
        from veomni_tpu.observability.devmem import kv_capacity_stats

        return kv_capacity_stats(
            self.blocks, self.k_pool, self.v_pool,
            max_model_len=self.config.max_model_len,
        )

    def metrics(self, reset_window: bool = True) -> Dict[str, float]:
        """Host-float engine metrics; feed them straight into any
        logger/meter sink. ``decode_tokens_per_sec`` and ``ttft_avg_s`` are
        measured over the window since the last resetting call (pass
        ``reset_window=False`` for a peek that leaves another consumer's
        window intact); ``ttft_avg_lifetime_s`` never resets.

        Window snapshot and reset are ATOMIC under ``_metrics_lock``: two
        concurrent resetting scrapers (router poll + exporter) each claim
        a disjoint window instead of racing the reset and losing one
        window's tokens from both readings."""
        now = time.perf_counter()
        with self._metrics_lock:
            dt = max(now - self._window_t0, 1e-9)
            m = {
                "queue_depth": float(self.scheduler.queue_depth),
                "num_running": float(self.scheduler.num_running),
                "block_utilization": self.blocks.utilization(),
                "preemptions": float(self.scheduler.preemption_count),
                "generated_tokens": float(self._total_generated),
                "decode_tokens_per_sec": self._window_tokens / dt,
                "prefix_hit_rate": (
                    self._cached_tokens_total
                    / max(1, self._prompt_tokens_total)
                ),
                "cached_tokens": float(self._cached_tokens_total),
                "prompt_tokens": float(self._prompt_tokens_total),
                "prefill_chunks": float(self._prefill_chunks_total),
                # speculative decoding: lifetime totals (bench deltas) +
                # the window acceptance rate (drafts the verify step kept)
                "spec_proposed": float(self._spec_proposed_total),
                "spec_accepted": float(self._spec_accepted_total),
                "spec_acceptance_rate": (
                    self._win_spec_accepted
                    / max(1, self._win_spec_proposed)
                ),
                # QoS / overload outcomes (lifetime totals; bench takes
                # deltas) + the window goodput rate — tokens from requests
                # that met their deadline, the overload bench's headline
                "rejected": float(self._rejected_total),
                "shed_tokens": float(self._shed_tokens_total),
                "deadline_misses": float(self._deadline_miss_total),
                "goodput_tokens": float(self._goodput_tokens_total),
                "goodput_tokens_per_sec": self._win_goodput_tokens / dt,
            }
            if self._win_ttft_n:
                m["ttft_avg_s"] = self._win_ttft_sum / self._win_ttft_n
            if self._ttft_n:
                m["ttft_avg_lifetime_s"] = self._ttft_sum / self._ttft_n
            if reset_window:
                # the resetting caller owns the throughput window; mirror
                # its reading to the exporter gauge
                self._m_tps.set(m["decode_tokens_per_sec"])
                self._m_spec_rate.set(m["spec_acceptance_rate"])
                self._m_goodput.set(m["goodput_tokens_per_sec"])
                self._window_tokens = 0
                self._win_goodput_tokens = 0
                self._window_t0 = now
                self._win_ttft_sum = 0.0
                self._win_ttft_n = 0
                self._win_spec_proposed = 0
                self._win_spec_accepted = 0
        return host_floats(m)
