"""Paged KV-cache block accounting (host side) with shared, refcounted blocks.

The device arrays — ``[L, num_blocks, block_size, hkv, d]`` pools — live in
the engine; this manager owns the free list, per-block reference counts, and
the per-sequence block tables that index into them (vLLM's BlockSpaceManager
translated to what a single-host recompute-preemption engine needs).

Ownership model (PR 9): blocks are **shared**, not exclusive. A block is in
exactly one of three states:

- **free** — on the free list; content is garbage.
- **active** — refcount >= 1: referenced by one or more sequence tables (a
  prefix-cache hit gives several sequences the same prompt blocks) or
  pinned as a copy-on-write source.
- **cached** — refcount == 0 but registered in the attached
  :class:`~veomni_tpu.serving.prefix_cache.PrefixCache`: content is a valid
  full block of KV, kept warm for future prefix hits and **evictable** LRU
  when the pool runs dry. The effective free set is therefore
  ``free ∪ evictable`` — eviction always reclaims cached blocks before the
  scheduler ever has to preempt a running sequence.

Writes only ever target exclusively-owned blocks: full cached blocks are
immutable, partial tail blocks are never shared, and a sequence that must
write into a cached block (a fully-cached prompt recomputing its final
token) gets a **copy-on-write** replacement via
``allocate_shared(cow_src=...)`` — the engine device-copies the block's rows
before the write lands.

Block 0 is reserved as the **null block**: block tables handed to the
device are padded with it past each sequence's allocation, and inactive
decode slots write their garbage row into it, so every table entry is
always a valid pool index and no program ever branches on table length.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional, Tuple


class KVBlockManager:
    NULL_BLOCK = 0

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is the reserved null block)")
        if block_size < 1 or (block_size & (block_size - 1)):
            raise ValueError(f"block_size must be a power of two, got {block_size}")
        self.num_blocks = num_blocks
        self.block_size = block_size
        # deque: freed blocks are reused FIFO, keeping allocation deterministic
        self._free = deque(range(1, num_blocks))
        self._tables: Dict[str, List[int]] = {}
        self._ref = [0] * num_blocks
        # attached PrefixCache (duck-typed: num_evictable/evict_lru/has_block);
        # None keeps the manager's pre-cache exclusive-ownership behavior
        self._cache = None
        self.cow_count = 0  # copy-on-write allocations (divergence blocks)
        self.evictions = 0  # cached blocks reclaimed to satisfy allocations

    def attach_cache(self, cache) -> None:
        """Attach the prefix cache whose registered refcount-0 blocks extend
        the free list (``free ∪ evictable``). Called by the cache's own
        constructor so the two can never disagree about ownership."""
        self._cache = cache

    # ---------------------------------------------------------------- queries
    @property
    def num_free(self) -> int:
        """Blocks an allocation can claim: truly free plus evictable cached
        (refcount-0) blocks the attached prefix cache would give back."""
        n = len(self._free)
        if self._cache is not None:
            n += self._cache.num_evictable()
        return n

    @property
    def num_free_uncached(self) -> int:
        """Blocks on the raw free list only (no eviction needed)."""
        return len(self._free)

    @property
    def num_used(self) -> int:
        """Blocks actively referenced by sequences (cached refcount-0 blocks
        are reclaimable, so they count as free, not used)."""
        return (self.num_blocks - 1) - self.num_free

    @property
    def num_cached(self) -> int:
        """Blocks registered in the prefix cache with refcount 0 (warm,
        evictable)."""
        return 0 if self._cache is None else self._cache.num_evictable()

    def refcount(self, block: int) -> int:
        return self._ref[block]

    def blocks_for(self, n_positions: int) -> int:
        """Blocks needed to hold ``n_positions`` cache rows (>= 1)."""
        return max(1, -(-int(n_positions) // self.block_size))

    def can_allocate(self, n_blocks: int) -> bool:
        return self.num_free >= n_blocks

    def num_allocated(self, seq_id: str) -> int:
        return len(self._tables.get(seq_id, ()))

    def table(self, seq_id: str) -> List[int]:
        if seq_id not in self._tables:
            raise KeyError(
                f"sequence {seq_id!r} has no block table: table() is only "
                "valid between allocate()/allocate_shared() and free_seq() "
                f"(currently allocated: {sorted(self._tables) or 'none'})"
            )
        return list(self._tables[seq_id])

    def utilization(self) -> float:
        """Fraction of allocatable (non-null) blocks actively in use."""
        return self.num_used / max(1, self.num_blocks - 1)

    # ------------------------------------------------------------- internals
    def _pop_block(self) -> int:
        """Claim one block: free list first, then LRU eviction from the
        prefix cache (cached blocks are reclaimed before any caller has to
        preempt)."""
        if self._free:
            return self._free.popleft()
        if self._cache is not None:
            blk = self._cache.evict_lru()
            if blk is not None:
                self.evictions += 1
                return blk
        raise RuntimeError(
            "out of KV blocks: free list empty and no evictable cached "
            "blocks"
        )

    def _take_ref(self, block: int) -> None:
        self._ref[block] += 1
        if (self._ref[block] == 1 and self._cache is not None
                and self._cache.has_block(block)):
            # a cached block leaving refcount 0 leaves the evictable set
            self._cache.note_referenced(block)

    def _release_ref(self, block: int) -> None:
        self._ref[block] -= 1
        assert self._ref[block] >= 0, f"refcount underflow on block {block}"
        if self._ref[block] == 0:
            if self._cache is not None and self._cache.has_block(block):
                # cached blocks stay warm in the prefix cache (evictable)
                self._cache.note_unreferenced(block)
            else:
                self._free.append(block)

    def reclaim_cached(self, block: int) -> None:
        """Return a refcount-0 block the prefix cache is dropping to the
        free list. Only the cache's flush/invalidation paths call this —
        normal eviction hands the block straight to the claimant via
        ``_pop_block`` and never lands it back on the free list."""
        if self._ref[block] != 0:
            raise RuntimeError(
                f"reclaim_cached(block={block}) with refcount "
                f"{self._ref[block]}: cache dropped a referenced block"
            )
        self._free.append(block)

    # ------------------------------------------------------------ transitions
    def allocate(self, seq_id: str, n_blocks: int) -> List[int]:
        table, _ = self.allocate_shared(seq_id, [], n_blocks)
        return table

    def allocate_shared(
        self,
        seq_id: str,
        shared: List[int],
        n_new: int,
        cow_src: Optional[int] = None,
    ) -> Tuple[List[int], List[int]]:
        """Build ``seq_id``'s table from ``shared`` (prefix-cache hits, one
        reference taken on each) plus ``n_new`` freshly claimed blocks.

        ``cow_src`` marks a copy-on-write divergence: the caller matched a
        cached block it must write into, so the last fresh block is its
        replacement. The source is **pinned** (refcounted) here so claiming
        the fresh blocks can never evict it before the engine's device copy;
        the engine releases the pin via :meth:`release_block` after copying.
        Returns ``(full table, fresh blocks)``."""
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id!r} already has blocks")
        # reference shared + pinned blocks FIRST: they leave the evictable
        # set before _pop_block can consider them
        for b in shared:
            self._take_ref(b)
        if cow_src is not None:
            self._take_ref(cow_src)
        if not self.can_allocate(n_new):
            for b in shared:
                self._release_ref(b)
            if cow_src is not None:
                self._release_ref(cow_src)
            raise RuntimeError(
                f"out of KV blocks: need {n_new}, free {self.num_free}"
            )
        fresh = [self._pop_block() for _ in range(n_new)]
        for b in fresh:
            self._ref[b] = 1
        if cow_src is not None:
            self.cow_count += 1
        self._tables[seq_id] = list(shared) + fresh
        return self.table(seq_id), fresh

    def grow(self, seq_id: str, n_blocks: int = 1) -> List[int]:
        if seq_id not in self._tables:
            raise KeyError(
                f"sequence {seq_id!r} has no block table to grow: grow() is "
                "only valid between allocate()/allocate_shared() and "
                f"free_seq() (currently allocated: "
                f"{sorted(self._tables) or 'none'})"
            )
        if not self.can_allocate(n_blocks):
            raise RuntimeError(
                f"out of KV blocks: need {n_blocks}, free {self.num_free}"
            )
        fresh = [self._pop_block() for _ in range(n_blocks)]
        for b in fresh:
            self._ref[b] = 1
        self._tables[seq_id].extend(fresh)
        return self.table(seq_id)

    def shrink(self, seq_id: str, n_keep: int) -> List[int]:
        """Release the sequence's trailing table entries down to ``n_keep``
        blocks — the speculative-decoding rollback: blocks claimed for
        drafted positions whose drafts were rejected go back to the pool
        the same tick they were claimed.

        Releases go through the shared refcount machinery
        (:meth:`_release_ref`), NOT straight to the free list: if a trailing
        block is somehow still shared (a refcount > 1 cached prefix block
        can never legally be a speculative claim, but the invariant is
        enforced here rather than assumed), shrinking this sequence only
        drops ITS reference — a rollback can never strand a block another
        sequence (or the prefix cache) still holds. Returns the released
        block ids (tail first)."""
        if seq_id not in self._tables:
            raise KeyError(
                f"sequence {seq_id!r} has no block table to shrink: shrink()"
                " is only valid between allocate()/allocate_shared() and "
                f"free_seq() (currently allocated: "
                f"{sorted(self._tables) or 'none'})"
            )
        if n_keep < 1:
            raise ValueError(f"shrink() must keep >= 1 block, got {n_keep}")
        table = self._tables[seq_id]
        released: List[int] = []
        while len(table) > n_keep:
            blk = table.pop()
            self._release_ref(blk)
            released.append(blk)
        return released

    def release_block(self, block: int) -> None:
        """Drop one reference taken outside a table (the copy-on-write
        source pin)."""
        self._release_ref(block)

    def free_seq(self, seq_id: str) -> int:
        """Release a sequence's references. Blocks whose refcount drops to 0
        return to the free list unless the prefix cache holds them (then
        they stay warm as evictable). Returns the number of table entries
        released."""
        blocks = self._tables.pop(seq_id, [])
        for b in blocks:
            self._release_ref(b)
        return len(blocks)
