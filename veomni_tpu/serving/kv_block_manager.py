"""Paged KV-cache block accounting (host side).

The device arrays — ``[L, num_blocks, block_size, hkv, d]`` pools — live in
the engine; this manager owns the free list and the per-sequence block
tables that index into them (vLLM's BlockSpaceManager reduced to what a
single-host, recompute-preemption engine needs: alloc/grow/free plus
utilization accounting; no copy-on-write forking).

Block 0 is reserved as the **null block**: block tables handed to the
device are padded with it past each sequence's allocation, and inactive
decode slots write their garbage row into it, so every table entry is
always a valid pool index and no program ever branches on table length.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List


class KVBlockManager:
    NULL_BLOCK = 0

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is the reserved null block)")
        if block_size < 1 or (block_size & (block_size - 1)):
            raise ValueError(f"block_size must be a power of two, got {block_size}")
        self.num_blocks = num_blocks
        self.block_size = block_size
        # deque: freed blocks are reused FIFO, keeping allocation deterministic
        self._free = deque(range(1, num_blocks))
        self._tables: Dict[str, List[int]] = {}

    # ---------------------------------------------------------------- queries
    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_used(self) -> int:
        return (self.num_blocks - 1) - len(self._free)

    def blocks_for(self, n_positions: int) -> int:
        """Blocks needed to hold ``n_positions`` cache rows (>= 1)."""
        return max(1, -(-int(n_positions) // self.block_size))

    def can_allocate(self, n_blocks: int) -> bool:
        return len(self._free) >= n_blocks

    def num_allocated(self, seq_id: str) -> int:
        return len(self._tables.get(seq_id, ()))

    def table(self, seq_id: str) -> List[int]:
        return list(self._tables[seq_id])

    def utilization(self) -> float:
        """Fraction of allocatable (non-null) blocks in use."""
        return self.num_used / max(1, self.num_blocks - 1)

    # ------------------------------------------------------------- transitions
    def allocate(self, seq_id: str, n_blocks: int) -> List[int]:
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id!r} already has blocks")
        if not self.can_allocate(n_blocks):
            raise RuntimeError(
                f"out of KV blocks: need {n_blocks}, free {self.num_free}"
            )
        self._tables[seq_id] = [self._free.popleft() for _ in range(n_blocks)]
        return self.table(seq_id)

    def grow(self, seq_id: str, n_blocks: int = 1) -> List[int]:
        if not self.can_allocate(n_blocks):
            raise RuntimeError(
                f"out of KV blocks: need {n_blocks}, free {self.num_free}"
            )
        self._tables[seq_id].extend(
            self._free.popleft() for _ in range(n_blocks)
        )
        return self.table(seq_id)

    def free_seq(self, seq_id: str) -> int:
        """Return a sequence's blocks to the free list; count returned."""
        blocks = self._tables.pop(seq_id, [])
        self._free.extend(blocks)
        return len(blocks)
