"""Continuous-batching inference engine with a paged KV cache.

Layering: ``api`` (request/response dataclasses, incl. the per-request QoS
surface: priority class / tenant / deadline) -> ``kv_block_manager``
(host block accounting: shared refcounted blocks) -> ``prefix_cache``
(radix tree sharing prompt KV blocks across requests) -> ``scheduler``
(QoS admission: class weights + tenant fairness + bounded intake /
load-shedding, class-aware preemption, cache-aware) -> ``spec_decode``
(host-side draft strategies for speculative decoding,
registry-dispatched) -> ``engine`` (jitted chunked prefill over cached
prefixes + batched paged decode, one-token or draft-then-verify;
deadline expiry + goodput accounting) -> ``quality`` (fixed-seed
perplexity/top-k gate certifying the non-bit-exact quantized tier) ->
``replica``/``router`` (scale-out front door: QoS admission at the
router, prefix-affinity dispatch over N single-class engine replicas,
health-aware shedding, live add/remove behind versioned weights). See
``docs/serving.md`` for the architecture, the QoS/overload semantics,
the quantized serving tier, scale-out routing, and the compile-count
story.
"""

from veomni_tpu.serving import spec_decode  # registers the spec_draft op
from veomni_tpu.serving.api import (
    Request,
    RequestOutput,
    SamplingParams,
    StreamEvent,
)
from veomni_tpu.serving.engine import (
    EngineConfig,
    InferenceEngine,
    SharedPrograms,
)
from veomni_tpu.serving.kv_block_manager import KVBlockManager
from veomni_tpu.serving.quality import fixed_corpus, quality_stats
from veomni_tpu.serving.prefix_cache import PrefixCache
from veomni_tpu.serving.replica import ReplicaHandle
from veomni_tpu.serving.router import Router, RouterConfig
from veomni_tpu.serving.scheduler import (
    DEFAULT_CLASSES,
    QoSPicker,
    Scheduler,
    SequenceState,
    parse_classes,
)
from veomni_tpu.serving.weights import (
    WeightRecord,
    WeightStore,
    load_published_params,
)

__all__ = [
    "DEFAULT_CLASSES",
    "EngineConfig",
    "fixed_corpus",
    "parse_classes",
    "quality_stats",
    "InferenceEngine",
    "KVBlockManager",
    "PrefixCache",
    "QoSPicker",
    "ReplicaHandle",
    "Request",
    "RequestOutput",
    "Router",
    "RouterConfig",
    "SamplingParams",
    "Scheduler",
    "SequenceState",
    "SharedPrograms",
    "StreamEvent",
    "WeightRecord",
    "WeightStore",
    "load_published_params",
]
