"""Continuous-batching inference engine with a paged KV cache.

Layering: ``api`` (request/response dataclasses) -> ``kv_block_manager``
(host block accounting) -> ``scheduler`` (admission/preemption policy) ->
``engine`` (jitted prefill-into-blocks + batched paged decode). See
``docs/serving.md`` for the architecture and the compile-count story.
"""

from veomni_tpu.serving.api import (
    Request,
    RequestOutput,
    SamplingParams,
    StreamEvent,
)
from veomni_tpu.serving.engine import EngineConfig, InferenceEngine
from veomni_tpu.serving.kv_block_manager import KVBlockManager
from veomni_tpu.serving.scheduler import Scheduler, SequenceState

__all__ = [
    "EngineConfig",
    "InferenceEngine",
    "KVBlockManager",
    "Request",
    "RequestOutput",
    "SamplingParams",
    "Scheduler",
    "SequenceState",
    "StreamEvent",
]
