"""Continuous-batching inference engine with a paged KV cache.

Layering: ``api`` (request/response dataclasses) -> ``kv_block_manager``
(host block accounting: shared refcounted blocks) -> ``prefix_cache``
(radix tree sharing prompt KV blocks across requests) -> ``scheduler``
(admission/preemption policy, cache-aware) -> ``spec_decode`` (host-side
draft strategies for speculative decoding, registry-dispatched) ->
``engine`` (jitted chunked prefill over cached prefixes + batched paged
decode, one-token or draft-then-verify). See ``docs/serving.md`` for the
architecture and the compile-count story.
"""

from veomni_tpu.serving import spec_decode  # registers the spec_draft op
from veomni_tpu.serving.api import (
    Request,
    RequestOutput,
    SamplingParams,
    StreamEvent,
)
from veomni_tpu.serving.engine import EngineConfig, InferenceEngine
from veomni_tpu.serving.kv_block_manager import KVBlockManager
from veomni_tpu.serving.prefix_cache import PrefixCache
from veomni_tpu.serving.scheduler import Scheduler, SequenceState

__all__ = [
    "EngineConfig",
    "InferenceEngine",
    "KVBlockManager",
    "PrefixCache",
    "Request",
    "RequestOutput",
    "SamplingParams",
    "Scheduler",
    "SequenceState",
    "StreamEvent",
]
