"""Versioned weight payloads for live publication into a serving fleet.

The router's rolling publish (docs/serving.md "Versioned weight
publication") needs three things from the weight layer, and this module
is all three:

* ``WeightStore`` — an append-only map of version tag -> params payload.
  Tags are opaque operator-chosen strings ("v1", "step-4000", a ckpt
  path); the store assigns each a monotonic ``seq`` so gauges and the
  mixed-version window can be reasoned about numerically even though
  tags are not ordered. A tag is immutable once published: re-publishing
  under the same tag is refused, because a replica that already swapped
  to "v1" must never disagree with a replica that swaps to "v1" later.
* ``WeightRecord`` — one (version, seq, params) entry. ``params`` is the
  UNQUANTIZED pytree; each engine re-applies its own ``weight_quant``
  storage transform at swap time, exactly as it does at construction.
* ``load_published_params`` — the checkpoint-to-publish gate.
  ``Router.publish_from_checkpoint`` must refuse a corrupt or
  uncommitted generation BEFORE any replica buffer is touched, so the
  PR 5 integrity manifest is verified here, ahead of the loader call
  that would materialize bytes.

The store keeps every published payload alive (host references, not
device copies — engines hold their own, possibly quantized, buffers).
Publishes are operator-rate events; retention is deliberately simple.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, List

from veomni_tpu.resilience.integrity import (
    CheckpointCorruptError,
    is_committed_dir,
    verify_manifest,
)

__all__ = [
    "WeightRecord",
    "WeightStore",
    "load_published_params",
]


@dataclass(frozen=True)
class WeightRecord:
    """One published weight payload: opaque tag, monotonic seq, params."""

    version: str
    seq: int
    params: Any


class WeightStore:
    """Append-only, version-tagged weight payloads with monotonic seqs.

    Not thread-safe by itself — the router publishes and reads under its
    own single-writer discipline (``publish_weights`` and ``step()`` run
    on the caller's thread; pump workers never touch the store).
    """

    def __init__(self, params: Any, version: str = "v0"):
        self._by_version: Dict[str, WeightRecord] = {}
        self._order: List[str] = []
        self.put(version, params)

    # ------------------------------------------------------------- write
    def put(self, version: str, params: Any) -> WeightRecord:
        """Publish ``params`` under ``version``. Tags are immutable: a
        duplicate tag is refused (ValueError) rather than silently
        retagged — two replicas reporting the same version MUST hold the
        same weights."""
        version = str(version)
        if not version:
            raise ValueError("weights version tag must be non-empty")
        if version in self._by_version:
            raise ValueError(
                f"weights version {version!r} already published; version "
                f"tags are immutable (pick a new tag)"
            )
        rec = WeightRecord(version=version, seq=len(self._order),
                           params=params)
        self._by_version[version] = rec
        self._order.append(version)
        return rec

    # -------------------------------------------------------------- read
    @property
    def latest(self) -> WeightRecord:
        return self._by_version[self._order[-1]]

    def get(self, version: str) -> WeightRecord:
        return self._by_version[str(version)]

    def seq(self, version: str) -> int:
        """Monotonic sequence number for ``version`` (-1 if unknown —
        a replica tagged by an older store generation)."""
        rec = self._by_version.get(str(version))
        return rec.seq if rec is not None else -1

    def versions(self) -> List[str]:
        return list(self._order)

    def __len__(self) -> int:
        return len(self._order)

    def __contains__(self, version: object) -> bool:
        return str(version) in self._by_version


def load_published_params(
    step_dir: str,
    loader: Callable[[str], Any],
    *,
    verify_mode: str = "size",
) -> Any:
    """Integrity-gate a checkpoint generation, then load params from it.

    The gate runs BEFORE ``loader`` so a corrupt generation is refused
    without materializing a single byte into host or device memory:

    * an uncommitted directory (no ``train_state/`` — a crashed save's
      temp dir, or a typo) raises ``CheckpointCorruptError``;
    * a manifest that fails ``verify_manifest(mode=verify_mode)``
      (truncated array file, flipped bytes under "full") raises
      ``CheckpointCorruptError`` with the report summary;
    * a generation without a manifest (pre-manifest checkpoints) is
      unverifiable — it loads, matching the restore path's behavior.

    ``verify_mode="off"`` skips manifest verification but still refuses
    uncommitted directories. ``loader`` receives ``step_dir`` and
    returns the params pytree (the caller owns the Orbax/file-format
    specifics — this module owns only the refuse-before-read contract).
    """
    step_dir = os.fspath(step_dir)
    if not is_committed_dir(step_dir):
        raise CheckpointCorruptError(
            f"refusing to publish from {step_dir!r}: not a committed "
            f"checkpoint generation (no train_state/ subtree)"
        )
    if verify_mode != "off":
        report = verify_manifest(step_dir, mode=verify_mode)
        if report is not None and not report.passed:
            raise CheckpointCorruptError(
                f"refusing to publish from {step_dir!r}: integrity "
                f"verification failed — {report.summary()}"
            )
    return loader(step_dir)
