"""Fixed-seed quality gate for non-bit-exact serving features.

The quantized serving tier (int8 KV blocks, int8 decode weights) is the
first serving feature that is deliberately NOT token-exact with the f32
engine. Exact parity drills can't certify it, so it ships behind this
gate: score a fixed-seed corpus **teacher-forced** through the reference
and the quantized decode path and bound two deltas —

- **perplexity delta**: relative change in teacher-forced perplexity
  (``exp(mean NLL)`` of each next token under the previous position's
  logits). Bounds the aggregate likelihood damage.
- **top-k overlap**: mean ``|topk(ref) ∩ topk(quant)| / k`` over
  positions. Bounds per-position ranking damage — a model can hold its
  perplexity while reshuffling the argmax neighborhood, and it is the
  argmax neighborhood that greedy/top-k serving actually samples from.

Scoring runs the REAL paged serving path, not a surrogate: one
:func:`~veomni_tpu.models.decode.paged_verify_step` call per sequence
(S=1, all T tokens as one verify batch) against freshly scattered pools in
the requested storage mode, so the quantize-on-write and
dequantize-in-attend code under test is exactly the code the engine runs.

``tests/tools/quality_gate.py`` wraps this with the pinned repo-wide
bounds; ``bench.py``'s kv-quant sweep records the same stats in its JSON
line so a perf run can never silently trade quality for capacity.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from veomni_tpu.models import decode as decode_mod
from veomni_tpu.models.config import TransformerConfig
from veomni_tpu.ops.quantization import make_kv_pool, quantize_decode_params


def fixed_corpus(vocab_size: int, *, n_seqs: int = 4, length: int = 24,
                 seed: int = 0) -> List[List[int]]:
    """The gate's fixed-seed token corpus: deterministic across runs and
    machines (numpy Philox via default_rng), tokens in [1, vocab)."""
    rng = np.random.default_rng(seed)
    return [
        [int(t) for t in rng.integers(1, vocab_size, size=length)]
        for _ in range(n_seqs)
    ]


def teacher_forced_logits(params, cfg: TransformerConfig,
                          tokens: Sequence[int], *,
                          kv_quant: str = "none",
                          block_size: int = 16) -> np.ndarray:
    """Per-position next-token logits [T, V] f32 for one sequence, scored
    through the paged serving path in the requested KV storage mode.

    One eager ``paged_verify_step`` call with S=1 and all T tokens as the
    verify batch: row j's logits are computed with rows 0..j written to the
    (possibly quantized) pool and attended through the block table — the
    exact cache state the engine would have after token j."""
    t = len(tokens)
    nb = -(-t // block_size)
    L = cfg.num_hidden_layers
    shape = (L, nb + 1, block_size, cfg.num_key_value_heads, cfg.head_dim)
    pools = (
        make_kv_pool(shape, kv_quant, cfg.dtype),
        make_kv_pool(shape, kv_quant, cfg.dtype),
    )
    # block 0 is the null block; the sequence owns blocks 1..nb
    table = jnp.arange(1, nb + 1, dtype=jnp.int32)[None]
    positions = jnp.zeros((1,), jnp.int32)
    toks = jnp.asarray(tokens, jnp.int32)[None]
    n_input = jnp.full((1,), t, jnp.int32)
    logits, _ = decode_mod.paged_verify_step(
        params, cfg, pools, table, positions, toks, n_input
    )
    return np.asarray(logits[0], np.float32)


def _ppl(logits: np.ndarray, tokens: Sequence[int]) -> float:
    """Teacher-forced perplexity: exp(mean NLL of tokens[j+1] under
    logits[j])."""
    lp = jax.nn.log_softmax(jnp.asarray(logits), axis=-1)
    nxt = jnp.asarray(tokens[1:], jnp.int32)
    nll = -jnp.take_along_axis(lp[:-1], nxt[:, None], axis=-1)[:, 0]
    return float(jnp.exp(nll.mean()))


def _topk_overlap(ref: np.ndarray, quant: np.ndarray, k: int) -> float:
    """Mean |topk(ref) ∩ topk(quant)| / k over positions."""
    ri = np.argsort(-ref, axis=-1)[:, :k]
    qi = np.argsort(-quant, axis=-1)[:, :k]
    inter = [
        len(set(r.tolist()) & set(q.tolist())) for r, q in zip(ri, qi)
    ]
    return float(np.mean(inter) / k)


def quality_stats(params, cfg: TransformerConfig,
                  corpus: Sequence[Sequence[int]], *,
                  kv_quant: str = "none", weight_quant: str = "none",
                  top_k: int = 8, block_size: int = 16) -> Dict[str, float]:
    """Score ``corpus`` through the f32 reference path and the quantized
    path; return the gate's statistics.

    Returns ``{ppl_ref, ppl_quant, ppl_rel_delta, topk_overlap}`` where
    ``ppl_rel_delta = |ppl_quant - ppl_ref| / ppl_ref`` (aggregated over
    the whole corpus) and ``topk_overlap`` is the per-position mean. The
    reference is always the unquantized path over the same corpus, so the
    stats isolate the quantization damage from the model itself."""
    qparams = (
        quantize_decode_params(params) if weight_quant == "int8" else params
    )
    nll_ref: List[float] = []
    nll_q: List[float] = []
    overlaps: List[float] = []
    for tokens in corpus:
        ref = teacher_forced_logits(params, cfg, tokens,
                                    kv_quant="none", block_size=block_size)
        qnt = teacher_forced_logits(qparams, cfg, tokens,
                                    kv_quant=kv_quant,
                                    block_size=block_size)
        nll_ref.append(np.log(_ppl(ref, tokens)))
        nll_q.append(np.log(_ppl(qnt, tokens)))
        overlaps.append(_topk_overlap(ref, qnt, top_k))
    ppl_ref = float(np.exp(np.mean(nll_ref)))
    ppl_quant = float(np.exp(np.mean(nll_q)))
    return {
        "ppl_ref": ppl_ref,
        "ppl_quant": ppl_quant,
        "ppl_rel_delta": abs(ppl_quant - ppl_ref) / ppl_ref,
        "topk_overlap": float(np.mean(overlaps)),
    }
