"""Replica handle: the router's per-engine bookkeeping unit.

One :class:`ReplicaHandle` wraps one in-process
:class:`~veomni_tpu.serving.engine.InferenceEngine` behind the scale-out
router (``serving/router.py``). The handle owns everything the router
needs to know about a replica that the engine itself does not track:

* **lifecycle state** — ``live`` (in the dispatch rotation), ``probation``
  (a respawned replica serving only spill traffic until it proves itself),
  ``draining`` (finishing in-flight work before a clean detach; receives
  no new requests), ``publishing`` (receives no new requests while it
  drains in-flight work on the OLD weights version, then the router
  hot-swaps its buffers in place and returns it to its prior rotation
  state at the new version — docs/serving.md "Versioned weight
  publication"), ``wedged`` (its pump thread blew the
  ``replica_stall_s`` deadline and was abandoned behind the generation
  fence) or ``dead`` (pump raised / killed; its stranded requests were
  re-dispatched or surfaced terminal by the router).
* **generation fence** — a monotonically increasing integer bumped every
  time the router abandons the replica's in-flight pump (wedge) or
  respawns its lineage. A zombie pump thread that eventually returns
  carries a stale generation, so its results, metrics-label writes and
  debug rows are all dropped instead of corrupting the successor.
* **assignment set** — the request ids currently dispatched to this
  engine and not yet captured back by the router. On replica death this
  set IS the list of stranded requests to triage; on drain it is the
  work left before detach.
* **weights version** — the version tag the replica's parameters were
  published under (``Router.publish_weights``). Replicas added after a
  publish serve the new version while old replicas finish on theirs —
  the same versioned-weights interface the trainer hot-swap loop
  (ROADMAP item 4) plugs into.
* **dispatch counters** — requests dispatched here, requests that had to
  be re-dispatched AWAY after this replica died, and the probation
  completions a respawned replica has served so far.

The handle is plain host bookkeeping touched only by the router's pump
thread; anything another thread reads goes through the router's locked
debug snapshot (``/debug/router``), never through a live handle. The one
exception is the pump worker the router itself starts for this handle:
while a pump ticket is outstanding (``pump is not None``) the ENGINE
belongs to that worker, so every router-side read here falls back to the
``last_*`` snapshots taken at the previous completed tick.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Set

from veomni_tpu.serving.engine import InferenceEngine

#: lifecycle states a replica moves through (strictly forward:
#: live -> draining -> detached, live/draining -> dead/wedged, and —
#: self-healing, docs/serving.md "Self-healing fleet" — dead/wedged ->
#: respawned successor handle in probation -> live)
STATE_LIVE = "live"
STATE_DRAINING = "draining"
STATE_DEAD = "dead"
STATE_DETACHED = "detached"  # drained clean and out of the replica set
STATE_WEDGED = "wedged"  # pump blew replica_stall_s; thread abandoned
STATE_PROBATION = "probation"  # respawned; spill-only until proven
STATE_PUBLISHING = "publishing"  # draining toward a weight hot-swap


@dataclass
class ReplicaHandle:
    """One engine replica as the router sees it."""

    rid: str  # instance label, e.g. "r0" — also the engine's metrics_label
    engine: InferenceEngine
    state: str = STATE_LIVE
    weights_version: str = "v0"
    # request ids dispatched to this engine, not yet captured back
    assigned: Set[str] = field(default_factory=set)
    dispatched: int = 0  # requests ever routed here
    redispatched: int = 0  # requests re-routed away after this replica died
    # the router's last observed failure for a dead replica (repr'd
    # exception) — lands in the debug doc so a postmortem names the cause
    fail_reason: str = ""
    # generation fence: bumped on wedge-abandon and on respawn. A pump
    # ticket snapshots the generation at start; the router only applies
    # results whose generation still matches.
    generation: int = 0
    # lineage root rid (respawned handles keep their ancestor's rid, so
    # lineage == rid today; kept explicit for the respawn budget ledger)
    lineage: str = ""
    # clean completions served while on probation (router-counted)
    probation_done: int = 0
    # rolling weight publication (state == "publishing"): the version tag
    # this replica is draining toward, and the rotation state to restore
    # after the swap (live replicas return to live, probation replicas
    # resume probation — a publish must not launder a replica past its
    # probation sentence)
    publish_to: str = ""
    publish_from_state: str = ""
    # consecutive router ticks this handle's pump exceeded replica_stall_s
    stall_ticks: int = 0
    # outstanding pump ticket (router._PumpTicket) — None when the engine
    # is quiescent and safe for the router thread to touch directly
    pump: Optional[Any] = field(default=None, repr=False)
    # last engine readings taken while quiescent; served while a pump
    # ticket is outstanding so gauges/spill decisions never race the
    # worker thread into the engine
    last_queue_depth: int = 0
    last_num_running: int = 0
    last_free_seqs: int = 0
    # pump-worker-private: ticks pumped (heartbeat global_step) and the
    # last heartbeat write time (throttle); only ever touched by the one
    # outstanding worker, never by the router thread
    pumped_ticks: int = 0
    last_beat: float = 0.0

    @property
    def in_rotation(self) -> bool:
        """Eligible for NEW affinity dispatches (probation, draining,
        wedged and dead replicas are not)."""
        return self.state == STATE_LIVE

    @property
    def pumpable(self) -> bool:
        """Still stepped by the router (wedged/dead replicas never are).
        A PUBLISHING replica stays pumpable: it must finish its in-flight
        work on the old weights before the swap can happen."""
        return self.state in (STATE_LIVE, STATE_DRAINING, STATE_PROBATION,
                              STATE_PUBLISHING)

    @property
    def engine_quiescent(self) -> bool:
        """True when the router thread may touch ``engine`` directly: the
        replica is pumpable or cleanly detached AND no pump worker is in
        flight. Wedged/dead engines may still be mutated by an abandoned
        zombie thread, so they are never quiescent."""
        return (self.pump is None
                and self.state not in (STATE_DEAD, STATE_WEDGED))

    def queue_depth(self) -> int:
        """Waiting requests at the replica's engine (the spill signal).
        Falls back to the last quiescent snapshot while a pump ticket is
        outstanding."""
        if not self.engine_quiescent:
            return self.last_queue_depth
        self.last_queue_depth = self.engine.scheduler.queue_depth
        return self.last_queue_depth

    def free_concurrent_seqs(self) -> int:
        """Max-length sequences the engine's free blocks could still
        admit — the capacity leg of the spill decision (mirrors the
        engine's ``serve.kv_free_concurrent_seqs`` gauge). Snapshot-backed
        like :meth:`queue_depth`."""
        if not self.engine_quiescent:
            return self.last_free_seqs
        eng = self.engine
        per_seq = max(1, eng.blocks.blocks_for(eng.config.max_model_len))
        self.last_free_seqs = eng.blocks.num_free // per_seq
        return self.last_free_seqs

    def status_doc(self) -> Dict[str, Any]:
        """JSON-ready row for ``/debug/router`` and the CLI census."""
        if self.state in (STATE_DEAD, STATE_WEDGED):
            qd = nr = -1
        elif self.pump is not None:
            qd, nr = self.last_queue_depth, self.last_num_running
        else:
            qd = self.queue_depth()
            nr = self.last_num_running = self.engine.scheduler.num_running
        doc: Dict[str, Any] = {
            "rid": self.rid,
            "state": self.state,
            "generation": self.generation,
            "weights_version": self.weights_version,
            "queue_depth": qd,
            "num_running": nr,
            "assigned": len(self.assigned),
            "dispatched": self.dispatched,
            "redispatched": self.redispatched,
        }
        if self.state == STATE_PROBATION:
            doc["probation_done"] = self.probation_done
        if self.state == STATE_PUBLISHING:
            doc["publish_to"] = self.publish_to
        if self.stall_ticks:
            doc["stall_ticks"] = self.stall_ticks
        if self.fail_reason:
            doc["fail_reason"] = self.fail_reason
        return doc
