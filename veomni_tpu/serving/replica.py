"""Replica handle: the router's per-engine bookkeeping unit.

One :class:`ReplicaHandle` wraps one in-process
:class:`~veomni_tpu.serving.engine.InferenceEngine` behind the scale-out
router (``serving/router.py``). The handle owns everything the router
needs to know about a replica that the engine itself does not track:

* **lifecycle state** — ``live`` (in the dispatch rotation), ``draining``
  (finishing in-flight work before a clean detach; receives no new
  requests) or ``dead`` (pump raised / killed; its stranded requests were
  re-dispatched or surfaced terminal by the router).
* **assignment set** — the request ids currently dispatched to this
  engine and not yet captured back by the router. On replica death this
  set IS the list of stranded requests to triage; on drain it is the
  work left before detach.
* **weights version** — the version tag the replica's parameters were
  published under (``Router.publish_weights``). Replicas added after a
  publish serve the new version while old replicas finish on theirs —
  the same versioned-weights interface the trainer hot-swap loop
  (ROADMAP item 4) plugs into.
* **dispatch counters** — requests dispatched here, and requests that
  had to be re-dispatched AWAY after this replica died.

The handle is plain host bookkeeping touched only by the router's pump
thread; anything another thread reads goes through the router's locked
debug snapshot (``/debug/router``), never through a live handle.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Set

from veomni_tpu.serving.engine import InferenceEngine

#: lifecycle states a replica moves through (strictly forward:
#: live -> draining -> detached, or live/draining -> dead)
STATE_LIVE = "live"
STATE_DRAINING = "draining"
STATE_DEAD = "dead"
STATE_DETACHED = "detached"  # drained clean and out of the replica set


@dataclass
class ReplicaHandle:
    """One engine replica as the router sees it."""

    rid: str  # instance label, e.g. "r0" — also the engine's metrics_label
    engine: InferenceEngine
    state: str = STATE_LIVE
    weights_version: str = "v0"
    # request ids dispatched to this engine, not yet captured back
    assigned: Set[str] = field(default_factory=set)
    dispatched: int = 0  # requests ever routed here
    redispatched: int = 0  # requests re-routed away after this replica died
    # the router's last observed failure for a dead replica (repr'd
    # exception) — lands in the debug doc so a postmortem names the cause
    fail_reason: str = ""

    @property
    def in_rotation(self) -> bool:
        """Eligible for NEW dispatches (draining/dead replicas are not)."""
        return self.state == STATE_LIVE

    @property
    def pumpable(self) -> bool:
        """Still stepped by the router (dead replicas never are)."""
        return self.state in (STATE_LIVE, STATE_DRAINING)

    def queue_depth(self) -> int:
        """Waiting requests at the replica's engine (the spill signal)."""
        return self.engine.scheduler.queue_depth

    def free_concurrent_seqs(self) -> int:
        """Max-length sequences the engine's free blocks could still
        admit — the capacity leg of the spill decision (mirrors the
        engine's ``serve.kv_free_concurrent_seqs`` gauge)."""
        eng = self.engine
        per_seq = max(1, eng.blocks.blocks_for(eng.config.max_model_len))
        return eng.blocks.num_free // per_seq

    def status_doc(self) -> Dict[str, Any]:
        """JSON-ready row for ``/debug/router`` and the CLI census."""
        doc: Dict[str, Any] = {
            "rid": self.rid,
            "state": self.state,
            "weights_version": self.weights_version,
            "queue_depth": (
                self.queue_depth() if self.state != STATE_DEAD else -1
            ),
            "num_running": (
                self.engine.scheduler.num_running
                if self.state != STATE_DEAD else -1
            ),
            "assigned": len(self.assigned),
            "dispatched": self.dispatched,
            "redispatched": self.redispatched,
        }
        if self.fail_reason:
            doc["fail_reason"] = self.fail_reason
        return doc
