"""Speculative-decoding draft strategies (host side, registry dispatched).

Draft-then-verify decoding replaces the engine's one-token decode tick with
a cheap host-side **draft** of up to k continuation tokens per slot plus ONE
batched jitted **verify** step (``models/decode.py::paged_verify_step``)
that scores all k+1 positions at once and accepts the longest prefix
matching target-model sampling. The drafting strategy is the pluggable
half: it runs on the host between device steps (microseconds against a
decode step's milliseconds), so it is dispatched through the kernel
registry like ``ulysses`` and ``paged_attention`` — an ops-config pin or
``EngineConfig.spec_draft`` selects the impl, and a future model-based
drafter (small draft model over the same bucketed jit machinery) slots in
without touching the engine.

Shipped impls:

- ``ngram`` — self-speculative **prompt lookup** (vLLM's
  ngram-prompt-lookup / HF's prompt_lookup_decoding): find the most recent
  earlier occurrence of the longest matching tail n-gram in the sequence's
  own prompt + generated ids and propose the tokens that followed it.
  Needs no second model and wins hardest on the shared-prefix /
  re-summarization traffic the prefix cache (PR 9) optimizes: continuations
  that restate the prompt accept nearly every draft.
- ``off`` — proposes nothing: every tick degrades to the pure decode step.

A draft is only ever a *proposal*: the verify step accepts a token iff it
equals what the target model would have emitted at that position (greedy
argmax or the seeded categorical draw), so a bad drafter can cost
throughput but can never change a single output token.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from veomni_tpu.ops.kernel_registry import KERNEL_REGISTRY

#: tail n-gram sizes the prompt-lookup drafter tries, longest first — a
#: longer match is more specific, so its continuation is accepted more often
NGRAM_MAX = 3

#: lookback cap for the prompt-lookup scan: drafting runs on the host
#: BETWEEN device steps for every slot every tick, so its cost must not
#: grow with sequence length — matches beyond this window are stale enough
#: that the acceptance loss is noise next to the per-tick latency win
NGRAM_WINDOW = 4096


@KERNEL_REGISTRY.register("spec_draft", "off", priority=1)
def draft_off(context: Sequence[int], k: int) -> List[int]:
    """The trivial drafter: never proposes. Auto-resolution picks this
    (highest priority) so speculation is opt-in by NAME, never by accident
    of registration order."""
    return []


@KERNEL_REGISTRY.register("spec_draft", "ngram")
def draft_ngram(context: Sequence[int], k: int) -> List[int]:
    """Prompt-lookup drafting over the sequence's own token history.

    ``context`` is the committed stream (prompt + every generated token,
    the pending last token included — proposals continue AFTER it); ``k``
    caps the proposal length. Tries tail n-grams of size ``NGRAM_MAX``
    down to 1 and, for the longest size that recurs earlier in the
    context, proposes the tokens that followed its MOST RECENT earlier
    occurrence. Returns [] when nothing matches (or the match sits at the
    very end with nothing after it) — the engine degrades that slot to
    k=0, i.e. a pure decode step, instead of wasting verify width."""
    if k <= 0 or len(context) < 2:
        return []
    # vectorized over a bounded lookback window: per-tick cost is O(window)
    # of numpy compares, never O(sequence) of Python-level slicing
    arr = np.asarray(context[-NGRAM_WINDOW:], dtype=np.int64)
    n = arr.shape[0]
    for m in range(min(NGRAM_MAX, n - 1), 0, -1):
        tail = arr[n - m:]
        # candidate start positions of the tail n-gram, excluding its own
        # occurrence at n - m
        hits = arr[: n - m] == tail[0]
        for j in range(1, m):
            # candidates may overlap the tail's own span (repetition runs),
            # so each offset-j compare covers the full candidate range
            hits &= arr[j: j + n - m] == tail[j]
        cand = np.flatnonzero(hits)
        if cand.size == 0:
            continue
        i = int(cand[-1])  # most recent earlier occurrence
        # i <= n - m - 1, so at least one token always follows the match
        return [int(t) for t in arr[i + m: i + m + k]]
    return []


def resolve_draft_fn(name: str):
    """Look up a drafting impl by name (the ``EngineConfig.spec_draft``
    surface), honoring a registry pin when one is set — same precedence as
    the ``ulysses`` dispatcher: ops-config pin > engine knob."""
    pin = KERNEL_REGISTRY.pinned("spec_draft")
    name = pin or name
    impls = KERNEL_REGISTRY.impls("spec_draft")
    if name not in impls:
        raise ValueError(
            f"unknown spec_draft impl {name!r}; registered: {sorted(impls)}"
        )
    return impls[name].fn
