"""Radix prefix cache: share prompt KV blocks across requests.

When millions of users share system prompts, most prefill FLOPs recompute
KV that already sits in the block pool. This module is the vLLM automatic-
prefix-caching idea over our :class:`KVBlockManager`: a radix tree keyed on
**block-aligned token chunks** (one node per full block, key = that block's
exact ``block_size`` token ids) mapping to pool block ids. Admission walks
the tree with the request's prompt and charges only the uncached suffix;
the matched blocks are shared by reference (the manager refcounts them).

Only *full* blocks are ever cached — a partial tail block is exclusively
owned by its sequence and still being written, so sharing it would let one
request corrupt another's context. Full blocks are registered when a
prefill completes and when a sequence releases its blocks (finish or
preemption: a preempted sequence's blocks staying cached is what turns
LIFO-recompute re-admission from a full re-prefill into a near-free hit).

Eviction is **LRU over refcount-0 leaves**: a cached block with no
references and no cached children is reclaimed first, ordered by a
monotonic access clock (deterministic — no wall time). Because a sequence
referencing a block also references every ancestor on its path (tables are
root paths of the tree), a refcount-0 node's descendants are refcount-0
too, so every refcount-0 cached block is transitively reclaimable and
``num_evictable()`` can count them all — the manager's ``free ∪ evictable``
accounting rests on this invariant.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from veomni_tpu.serving.kv_block_manager import KVBlockManager


class _Node:
    __slots__ = ("key", "block", "children", "parent", "last_access")

    def __init__(self, key: Tuple[int, ...], block: int,
                 parent: Optional["_Node"]):
        self.key = key
        self.block = block
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.last_access = 0


class PrefixCache:
    """One instance per engine, attached to its block manager."""

    def __init__(self, manager: KVBlockManager):
        self.manager = manager
        self.block_size = manager.block_size
        self._root = _Node((), KVBlockManager.NULL_BLOCK, None)
        self._by_block: Dict[int, _Node] = {}
        self._clock = 0  # monotonic LRU clock: deterministic, no wall time
        # cached blocks with refcount 0, maintained incrementally on every
        # 0<->1 refcount transition (the manager notifies) — num_evictable()
        # sits on the per-tick hot path (can_allocate/utilization), so an
        # O(cached-blocks) scan there would cost O(slots x blocks) python
        # per generated token batch
        self._evictable = 0
        # weight-publication epoch: bumped by flush(). Cached KV is only
        # valid for the weights that produced it, so a weight swap flushes
        # the whole tree and advances the epoch — a cheap observable for
        # tests/metrics that stale entries cannot have survived
        self.epoch = 0
        manager.attach_cache(self)

    # ---------------------------------------------------------------- queries
    def __len__(self) -> int:
        """Number of cached blocks (any refcount)."""
        return len(self._by_block)

    def has_block(self, block: int) -> bool:
        return block in self._by_block

    def num_evictable(self) -> int:
        """Cached blocks with refcount 0. All are transitively reclaimable
        via repeated leaf eviction (see module docstring invariant)."""
        return self._evictable

    # --------------------------------------------- manager refcount callbacks
    def note_unreferenced(self, block: int) -> None:
        """A cached block's refcount dropped to 0: it is warm + evictable."""
        self._evictable += 1

    def note_referenced(self, block: int) -> None:
        """A cached block's refcount left 0: no longer evictable."""
        self._evictable -= 1
        assert self._evictable >= 0, "evictable count underflow"

    # ------------------------------------------------------------ transitions
    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def match(self, tokens: Sequence[int]) -> List[int]:
        """Longest cached block-aligned prefix of ``tokens``; returns the
        pool block ids in sequence order and bumps their LRU clocks. The
        caller must take references (``allocate_shared``) before claiming
        any new blocks, or the match could be evicted out from under it."""
        bs = self.block_size
        node = self._root
        out: List[int] = []
        t = self._tick()
        for i in range(len(tokens) // bs):
            child = node.children.get(tuple(tokens[i * bs:(i + 1) * bs]))
            if child is None:
                break
            child.last_access = t
            out.append(child.block)
            node = child
        return out

    def insert(self, tokens: Sequence[int], blocks: Sequence[int]) -> int:
        """Register ``blocks`` (full blocks, in sequence order, with
        ``tokens`` covering them exactly) under the radix tree. A chunk
        whose key already exists keeps the **existing** block — the caller's
        duplicate (e.g. a copy-on-write replacement) stays private and is
        freed normally when its references drop. Returns the number of
        blocks newly registered."""
        bs = self.block_size
        assert len(tokens) >= len(blocks) * bs, "tokens must cover blocks"
        node = self._root
        t = self._tick()
        added = 0
        for i, blk in enumerate(blocks):
            key = tuple(tokens[i * bs:(i + 1) * bs])
            child = node.children.get(key)
            if child is None:
                if blk in self._by_block:
                    # already cached under a different path — the engine flow
                    # never produces this; refuse to alias rather than corrupt
                    break
                child = _Node(key, blk, node)
                node.children[key] = child
                self._by_block[blk] = child
                if self.manager.refcount(blk) == 0:
                    # callers normally insert while still holding references
                    # (prefill completion / just before release), but a
                    # direct refcount-0 insert must land in the count too
                    self._evictable += 1
                added += 1
            child.last_access = t
            node = child
        return added

    def flush(self) -> int:
        """Drop EVERY cached block and return them to the manager's free
        list, bumping the cache epoch. This is the weight-publication
        invalidation: KV computed under the old weights must become
        unreachable before the first request runs on the new ones.

        The caller must have drained the engine first — a cached block
        still referenced by a live sequence cannot be invalidated without
        corrupting that sequence, so a referenced block is a hard error,
        not a skip. Blocks return to the free list in sorted order so the
        post-flush allocation sequence is deterministic. Returns the
        number of blocks flushed; the no-leak identity is conserved:
        ``num_cached`` drops to 0 and ``num_free_uncached`` grows by
        exactly the flushed count."""
        rc = self.manager.refcount
        held = sorted(b for b in self._by_block if rc(b) != 0)
        if held:
            raise RuntimeError(
                f"prefix cache flush with {len(held)} referenced cached "
                f"blocks (e.g. block {held[0]}): engine not drained"
            )
        flushed = sorted(self._by_block)
        for b in flushed:
            self.manager.reclaim_cached(b)
        self._root = _Node((), KVBlockManager.NULL_BLOCK, None)
        self._by_block = {}
        self._evictable = 0
        self.epoch += 1
        return len(flushed)

    def evict_lru(self) -> Optional[int]:
        """Remove and return the least-recently-used refcount-0 **leaf**
        block (evicting a parent would orphan cached children). Returns
        None when nothing is evictable. The scan is O(cached blocks) but
        runs only under pool pressure (the free list is already empty) —
        never on the per-tick accounting path."""
        rc = self.manager.refcount
        best: Optional[_Node] = None
        for b, node in self._by_block.items():
            if node.children or rc(b) != 0:
                continue
            if (best is None or node.last_access < best.last_access
                    or (node.last_access == best.last_access
                        and b < best.block)):
                best = node
        if best is None:
            return None
        del best.parent.children[best.key]
        del self._by_block[best.block]
        self._evictable -= 1
        return best.block
