"""Continuous-batching scheduler: admission, slot assignment, preemption.

Policy (vLLM-style, recompute preemption):

- **FIFO admission with head-of-line blocking**: waiting requests are
  admitted in arrival order into free decode slots whenever the block pool
  can hold their (re)compute prompt plus one block of headroom. The head is
  never skipped — out-of-order admission would make greedy outputs depend
  on pool pressure, which would break token-parity guarantees.
- **LIFO recompute preemption**: when a running sequence needs a block and
  the pool is dry, the most recently admitted running sequence is evicted —
  its blocks are freed and it is requeued at the FRONT of the waiting queue
  with ``prompt + generated-so-far`` as its recompute prompt. Greedy
  decoding is deterministic, so recompute resumes the exact token stream;
  already-emitted tokens are never re-emitted.

The scheduler is pure host bookkeeping — it owns no device state and is
unit-testable without building a model. When a
:class:`~veomni_tpu.observability.request_trace.RequestTracer` is attached
(the engine does), the scheduler reports its transitions — queued, admitted
(with slot), preempted — so every request carries a lifecycle timeline; the
engine reports the rest (prefill-done, first token, finished).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, List, Optional, Tuple

from veomni_tpu.serving.api import Request
from veomni_tpu.serving.kv_block_manager import KVBlockManager


@dataclass
class SequenceState:
    """Host-side runtime state of one request (survives preemption)."""

    request: Request
    generated: List[int] = field(default_factory=list)  # ALL emitted tokens
    rng: Any = None  # per-request PRNG key carry [2] uint32
    slot: int = -1
    pos: int = 0  # write position of the pending last token
    prefill_len: int = 0  # positions covered by the latest prefill
    admit_order: int = -1
    preemptions: int = 0
    submit_time: float = field(default_factory=time.perf_counter)
    first_token_time: Optional[float] = None

    @property
    def seq_id(self) -> str:
        return self.request.request_id

    @property
    def recompute_prompt(self) -> List[int]:
        """What a (re)admission must prefill: the original prompt plus every
        token generated before preemption."""
        return list(self.request.prompt_ids) + list(self.generated)

    @property
    def last_token(self) -> int:
        return self.generated[-1]


class Scheduler:
    def __init__(self, num_slots: int, block_manager: KVBlockManager,
                 tracer: Optional[Any] = None):
        if num_slots < 1:
            raise ValueError("need at least one decode slot")
        self.blocks = block_manager
        self.waiting: Deque[SequenceState] = deque()
        self.slots: List[Optional[SequenceState]] = [None] * num_slots
        self.preemption_count = 0
        self._admit_counter = 0
        # optional RequestTracer (duck-typed: anything with on_queued /
        # on_admitted / on_preempted) — None keeps the scheduler trace-free
        self.tracer = tracer

    # ---------------------------------------------------------------- queries
    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    @property
    def num_running(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    @property
    def has_work(self) -> bool:
        return bool(self.waiting) or self.num_running > 0

    def running(self) -> List[Tuple[int, SequenceState]]:
        """(slot, seq) pairs in slot order — the decode batch row order."""
        return [(i, s) for i, s in enumerate(self.slots) if s is not None]

    # ------------------------------------------------------------ transitions
    def add(self, seq: SequenceState) -> None:
        self.waiting.append(seq)
        if self.tracer is not None:
            self.tracer.on_queued(seq.seq_id)

    def admit(self) -> List[SequenceState]:
        """Fill free slots from the waiting queue (FIFO, head-of-line).
        Admission allocates the recompute prompt's blocks and requires one
        extra free block of headroom so a fresh admission isn't preempted on
        its very first decode step just to grow someone else."""
        admitted = []
        for slot in range(len(self.slots)):
            if self.slots[slot] is not None or not self.waiting:
                continue
            head = self.waiting[0]
            n_blocks = self.blocks.blocks_for(len(head.recompute_prompt))
            # no headroom demanded when the engine is idle: an exact-fit
            # request must admit (it can still grow — the engine validates
            # blocks_for(prompt+max_new) <= pool size at submit)
            headroom = 1 if self.num_running else 0
            if not self.blocks.can_allocate(n_blocks + headroom):
                break  # head-of-line: never admit around the queue head
            self.waiting.popleft()
            self.blocks.allocate(head.seq_id, n_blocks)
            head.slot = slot
            head.admit_order = self._admit_counter
            self._admit_counter += 1
            self.slots[slot] = head
            admitted.append(head)
            if self.tracer is not None:
                self.tracer.on_admitted(head.seq_id, slot)
        return admitted

    def ensure_decode_capacity(self) -> List[SequenceState]:
        """Grow each running sequence to cover its next write position,
        preempting (LIFO) when the pool runs dry. Returns the preempted
        sequences (already requeued at the front of the waiting queue)."""
        preempted: List[SequenceState] = []
        for _, seq in self.running():
            if seq.slot < 0:  # already preempted within this pass
                continue
            need = seq.pos // self.blocks.block_size + 1
            while self.blocks.num_allocated(seq.seq_id) < need:
                if self.blocks.can_allocate(1):
                    self.blocks.grow(seq.seq_id, 1)
                    continue
                victim = max(
                    (s for _, s in self.running()), key=lambda s: s.admit_order
                )
                self._preempt(victim)
                preempted.append(victim)
                if victim is seq:
                    break
        return preempted

    def _preempt(self, seq: SequenceState) -> None:
        self.blocks.free_seq(seq.seq_id)
        self.slots[seq.slot] = None
        seq.slot = -1
        seq.preemptions += 1
        self.preemption_count += 1
        self.waiting.appendleft(seq)
        if self.tracer is not None:
            self.tracer.on_preempted(seq.seq_id)

    def finish(self, seq: SequenceState) -> None:
        self.blocks.free_seq(seq.seq_id)
        if seq.slot >= 0:
            self.slots[seq.slot] = None
        seq.slot = -1
