"""Continuous-batching scheduler: QoS admission, slot assignment, preemption.

Policy (vLLM-style recompute preemption, PR 15 QoS layer on top):

- **Per-class weighted admission**: waiting requests live in one arrival-
  ordered queue but are *picked* by QoS class. Each class (e.g.
  ``interactive``/``batch``, ``EngineConfig.classes``) owns a stride-
  scheduling pass value advanced by ``1/weight`` per admission, so a
  4:1-weighted interactive class gets ~4 of every 5 admissions while batch
  still progresses (no starvation in either direction — interactive can't
  starve behind a batch backlog, batch can't be frozen out). With a single
  configured class (or no classes) the pick degenerates to the queue head:
  **behavior-identical to the seed FIFO scheduler**.
- **Per-tenant fairness inside each class**: admission round-robins across
  tenants (deficit round robin with unit quantum: pick the waiting tenant
  with the lowest served count, newly active tenants joining at the current
  level so they can't burst on stale credit). One tenant flooding the queue
  cannot starve another's trickle; a single-tenant stream is plain FIFO.
- **Head-of-line within the pick**: the selected candidate is never admitted
  around — if its blocks don't fit, admission stops for this tick (out-of-
  order admission would make greedy outputs depend on pool pressure and
  break token-parity guarantees). Selection state (stride passes, tenant
  credits) commits only on successful admission.
- **Bounded queue + per-tenant in-flight caps (load-shedding)**: past
  ``queue_bound`` waiting requests (or ``tenant_max_inflight`` waiting+
  running for one tenant), :meth:`add` REFUSES the request (returns False —
  the engine turns that into a terminal ``rejected`` output, the
  429-equivalent) instead of growing the queue without bound. Preemption
  requeues are exempt: admitted work is never shed by its own recompute.
- **Prefix-cache-aware admission**: unchanged from PR 9 — admission matches
  the recompute prompt against the radix tree and charges only the uncached
  suffix; a fully-covered prompt takes its divergence block copy-on-write.
- **Class-aware LIFO recompute preemption**: when a running sequence needs a
  block and the pool is dry (free list AND evictable cached blocks), the
  victim is the most recently admitted sequence of the LOWEST-priority
  class — batch preempts before interactive regardless of admission order;
  within a class, LIFO exactly as before. Victims requeue at the FRONT of
  the waiting order with ``prompt + generated-so-far`` as their recompute
  prompt; greedy decoding resumes the exact token stream.
- **Deadline expiry**: :meth:`expired` names waiting (and still-prefilling)
  sequences past their ``Request.deadline_s``; the engine cancels them via
  :meth:`cancel`, which releases any partially-claimed blocks AND a still-
  pinned copy-on-write source — a shed mid-chunked-prefill request can
  never leak pool blocks.

The scheduler is pure host bookkeeping — it owns no device state and is
unit-testable without building a model. Only the engine's pump thread
touches it (the exporter reads the thread-safe registry gauges the engine
publishes, never live scheduler state — docs/static-analysis.md). When a
:class:`~veomni_tpu.observability.request_trace.RequestTracer` is attached
(the engine does), the scheduler reports its transitions — queued, admitted
(with slot), preempted — so every request carries a lifecycle timeline; the
engine reports the rest (prefill-done, first token, finished/cancelled).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from veomni_tpu.serving.api import Request
from veomni_tpu.serving.kv_block_manager import KVBlockManager

#: default QoS classes, highest priority first: interactive gets 4 of every
#: 5 admission picks under contention, batch the remaining 1
DEFAULT_CLASSES: Tuple[Tuple[str, int], ...] = (("interactive", 4),
                                                ("batch", 1))


def parse_classes(spec: Union[str, Sequence, None]
                  ) -> List[Tuple[str, int]]:
    """``"interactive:4,batch:1"`` (or an already-structured sequence of
    ``(name, weight)``) -> ordered class list, FIRST = highest priority
    (both for admission tie-breaks and for preemption: later classes are
    preempted first). Weights must be positive ints; names unique."""
    if spec is None or spec == "":
        return list(DEFAULT_CLASSES)
    if isinstance(spec, str):
        entries = []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            name, _, w = part.partition(":")
            name = name.strip()
            if not name:
                raise ValueError(f"empty class name in classes spec {spec!r}")
            try:
                weight = int(w) if w.strip() else 1
            except ValueError:
                raise ValueError(
                    f"class weight must be an integer in {part!r} "
                    f"(classes spec {spec!r})"
                ) from None
            entries.append((name, weight))
    else:
        entries = [(str(n), int(w)) for n, w in spec]
    if not entries:
        raise ValueError(f"classes spec {spec!r} defines no classes")
    names = [n for n, _ in entries]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate class name in classes spec {spec!r}")
    for name, weight in entries:
        if weight < 1:
            raise ValueError(
                f"class {name!r} has non-positive weight {weight} "
                "(weights are admission shares, must be >= 1)"
            )
    return entries


class QoSPicker:
    """The QoS admission policy, factored out of :class:`Scheduler` so the
    scale-out router (``serving/router.py``) runs the IDENTICAL discipline
    over its own dispatch queue: per-class stride scheduling (pass values
    advance by ``1/weight`` per pick, a newly active class floored at the
    current virtual time so it can't burst on stale credit) with
    unit-quantum deficit-round-robin across each class's tenants.

    Items are duck-typed: anything carrying ``class_idx`` and ``tenant``
    attributes (the scheduler picks :class:`SequenceState`, the router its
    own queue entries). ``pick`` is pure — selection state commits in
    :meth:`commit` only once the caller actually takes the candidate, so a
    head-of-line wait never burns stride or tenant credit."""

    def __init__(self, classes: Optional[Sequence[Tuple[str, int]]] = None):
        # classes, highest priority first. None (or a single class) is the
        # seed FIFO policy: one queue, any priority label accepted.
        self.classes = list(classes) if classes else None
        self._weights = {i: w for i, (_, w) in enumerate(self.classes or ())}
        self._class_idx = {n: i for i, (n, _) in
                           enumerate(self.classes or ())}
        # stride-scheduling state across classes: pass values advance by
        # 1/weight per admission; _vtime floors a newly active class so an
        # idle class can't burst on stale credit
        self._pass: Dict[int, float] = {}
        self._vtime = 0.0
        # per-(class, tenant) served counts (unit-quantum DRR) + per-class
        # floor a newly active tenant joins at
        self._tenant_served: Dict[Tuple[int, str], int] = {}
        self._tenant_floor: Dict[int, int] = {}

    @property
    def single_class(self) -> bool:
        return self.classes is None or len(self.classes) == 1

    def resolve_class(self, priority: str) -> int:
        """Class index for a request's priority label. A single-class (or
        class-less) picker accepts ANY label into its one queue — the
        seed-FIFO configuration; a multi-class one refuses unknown labels
        loudly (a typo'd priority silently landing in the wrong tier would
        be an SLO bug nobody can see)."""
        if self.single_class:
            return 0
        try:
            return self._class_idx[priority]
        except KeyError:
            raise ValueError(
                f"unknown priority class {priority!r}; configured classes: "
                f"{[n for n, _ in self.classes]}"
            ) from None

    def pick(self, waiting: Sequence[Any]) -> Optional[Any]:
        """Next candidate from ``waiting`` (arrival order, front first)
        under the QoS policy. Pure — commit separately."""
        if not waiting:
            return None
        if self.single_class:
            return waiting[0]  # seed FIFO exactly
        # stride pick across active classes: lowest effective pass wins,
        # ties break toward the higher-priority (earlier) class. An idle
        # class's stale pass is floored at _vtime so it can't burst.
        active = sorted({s.class_idx for s in waiting})
        c = min(active, key=lambda i: (max(self._pass.get(i, 0.0),
                                           self._vtime), i))
        # unit-quantum DRR across the class's active tenants: lowest served
        # count wins, ties break toward the earliest-waiting tenant
        order: List[str] = []
        for s in waiting:
            if s.class_idx == c and s.tenant not in order:
                order.append(s.tenant)
        t = min(order, key=lambda tn: (
            max(self._tenant_served.get((c, tn), 0),
                self._tenant_floor.get(c, 0)),
            order.index(tn),
        ))
        for s in waiting:
            if s.class_idx == c and s.tenant == t:
                return s
        raise AssertionError("picked (class, tenant) has no waiting seq")

    def commit(self, item: Any) -> None:
        """Charge the taken candidate's class stride + tenant credit (the
        caller removes it from its own waiting order)."""
        if self.single_class:
            return
        c = item.class_idx
        base = max(self._pass.get(c, 0.0), self._vtime)
        self._vtime = base
        self._pass[c] = base + 1.0 / self._weights[c]
        served = max(self._tenant_served.get((c, item.tenant), 0),
                     self._tenant_floor.get(c, 0))
        self._tenant_served[(c, item.tenant)] = served + 1
        # newly active tenants join at the level of the last pick: fair
        # from now on, no retroactive catch-up burst
        self._tenant_floor[c] = served

    def prune_tenant(self, tenant: str) -> None:
        """Drop a fully-drained tenant's DRR credit entries: a long-running
        server sees unboundedly many distinct tenant ids, and keeping one
        counter per (class, tenant) forever would leak. Safe for fairness —
        a rejoining tenant is re-floored at the class's current credit
        level (``max(served, _tenant_floor[c])``), exactly as if its stale
        entry had been kept. The caller checks the tenant really is
        drained (no waiting or running work) before calling."""
        for key in [k for k in self._tenant_served if k[1] == tenant]:
            del self._tenant_served[key]


@dataclass
class SequenceState:
    """Host-side runtime state of one request (survives preemption)."""

    request: Request
    generated: List[int] = field(default_factory=list)  # ALL emitted tokens
    rng: Any = None  # per-request PRNG key carry [2] uint32
    slot: int = -1
    pos: int = 0  # write position of the pending last token
    prefill_len: int = 0  # positions covered by the latest prefill
    admit_order: int = -1
    preemptions: int = 0
    submit_time: float = field(default_factory=time.perf_counter)
    first_token_time: Optional[float] = None
    # QoS class index into the scheduler's class list (0 with classes off)
    class_idx: int = 0
    # chunked-prefill / prefix-cache state for the CURRENT admission
    prefilling: bool = False  # admitted, prefill not finished (chunks left)
    prefill_pos: int = 0  # next uncomputed position (rows [0, here) valid)
    cached_tokens: int = 0  # positions served from the prefix cache
    cow_src: Optional[int] = None  # pinned copy-on-write source block

    @property
    def seq_id(self) -> str:
        return self.request.request_id

    @property
    def tenant(self) -> str:
        return getattr(self.request, "tenant", "")

    @property
    def deadline_s(self) -> Optional[float]:
        return getattr(self.request, "deadline_s", None)

    def deadline_expired(self, now: float) -> bool:
        dl = self.deadline_s
        return dl is not None and (now - self.submit_time) > dl

    @property
    def recompute_prompt(self) -> List[int]:
        """What a (re)admission must prefill: the original prompt plus every
        token generated before preemption."""
        return list(self.request.prompt_ids) + list(self.generated)

    @property
    def last_token(self) -> int:
        return self.generated[-1]

    @property
    def kv_valid_len(self) -> int:
        """Cache rows [0, here) hold real KV: up to the write position when
        decoding, up to the last completed chunk while mid-prefill."""
        return self.prefill_pos if self.prefilling else self.pos


class Scheduler:
    def __init__(self, num_slots: int, block_manager: KVBlockManager,
                 tracer: Optional[Any] = None,
                 prefix_cache: Optional[Any] = None,
                 spec_headroom_blocks: int = 0,
                 classes: Optional[Sequence[Tuple[str, int]]] = None,
                 queue_bound: int = 0,
                 tenant_max_inflight: int = 0):
        if num_slots < 1:
            raise ValueError("need at least one decode slot")
        if queue_bound < 0:
            raise ValueError("queue_bound must be >= 0 (0 = unbounded)")
        if tenant_max_inflight < 0:
            raise ValueError("tenant_max_inflight must be >= 0 (0 = uncapped)")
        self.blocks = block_manager
        self.cache = prefix_cache
        # arrival-ordered waiting list (front = next within its class and
        # tenant; preemption requeues at the very front). The QoS pick
        # selects INTO this order — it never reorders it, so within one
        # (class, tenant) stream admission is exactly the seed FIFO.
        self._waiting: List[SequenceState] = []
        self.slots: List[Optional[SequenceState]] = [None] * num_slots
        self.preemption_count = 0
        self._admit_counter = 0
        # optional RequestTracer (duck-typed: anything with on_queued /
        # on_admitted / on_preempted) — None keeps the scheduler trace-free
        self.tracer = tracer
        # QoS classes, highest priority first. None (or a single class) is
        # the seed scheduler: one FIFO queue, any priority label admitted.
        # The stride/DRR policy itself lives in QoSPicker so the scale-out
        # router shares the one implementation.
        self.qos = QoSPicker(classes)
        self.classes = self.qos.classes
        # admission control: 0 disables either bound (seed behavior)
        self.queue_bound = queue_bound
        self.tenant_max_inflight = tenant_max_inflight
        # extra admission headroom when the engine decodes speculatively:
        # a running sequence can grow by ceil(spec_k / block_size) blocks
        # per tick on top of the usual one, so admission keeps that many
        # more blocks free per tick — a fresh admission must not force every
        # speculative claim to degrade to k=0 on its very first step. 0
        # (the default, spec off) keeps the seed admission policy exactly.
        self.spec_headroom_blocks = spec_headroom_blocks

    # ---------------------------------------------------------------- queries
    @property
    def waiting(self) -> List["SequenceState"]:
        """Waiting sequences in arrival order (requeued preemptions at the
        front) — a read-only view for tests/introspection; the QoS pick
        decides the actual admission order."""
        return list(self._waiting)

    @property
    def queue_depth(self) -> int:
        return len(self._waiting)

    @property
    def num_running(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    @property
    def has_work(self) -> bool:
        return bool(self._waiting) or self.num_running > 0

    def running(self) -> List[Tuple[int, SequenceState]]:
        """(slot, seq) pairs in slot order — the decode batch row order."""
        return [(i, s) for i, s in enumerate(self.slots) if s is not None]

    def resolve_class(self, priority: str) -> int:
        """Class index for a request's priority label (delegates to the
        shared :class:`QoSPicker`: single-class accepts any label — the
        seed-FIFO configuration — multi-class refuses unknown ones)."""
        return self.qos.resolve_class(priority)

    def tenant_inflight(self, tenant: str) -> int:
        """Waiting + running sequences charged to one tenant (the in-flight
        cap's accounting unit)."""
        n = sum(1 for s in self._waiting if s.tenant == tenant)
        n += sum(1 for _, s in self.running() if s.tenant == tenant)
        return n

    # ------------------------------------------------------------ transitions
    def add(self, seq: SequenceState) -> bool:
        """Enqueue a fresh request. Returns False — the load-shedding
        refusal, the engine's 429-equivalent — when the waiting queue is at
        ``queue_bound`` or the sequence's tenant is at
        ``tenant_max_inflight``; the caller owns turning that into a
        terminal REJECTED output. Accepted sequences get their class index
        resolved here (unknown labels raise, see :meth:`resolve_class`)."""
        seq.class_idx = self.resolve_class(
            getattr(seq.request, "priority", "interactive")
        )
        if self.queue_bound and len(self._waiting) >= self.queue_bound:
            return False
        if self.tenant_max_inflight and (
                self.tenant_inflight(seq.tenant) >= self.tenant_max_inflight):
            return False
        self._waiting.append(seq)
        if self.tracer is not None:
            self.tracer.on_queued(seq.seq_id)
        return True

    # ------------------------------------------------------------- QoS pick
    def _pick_candidate(self) -> Optional[SequenceState]:
        """Next admission candidate under the QoS policy (the shared
        :class:`QoSPicker`). Pure — selection state commits in
        :meth:`_commit_pick` only after the candidate's blocks actually
        fit, so a head-of-line wait doesn't burn credit."""
        return self.qos.pick(self._waiting)

    def _commit_pick(self, seq: SequenceState) -> None:
        """Remove the admitted candidate from the waiting order and charge
        its class stride + tenant credit."""
        self._waiting.remove(seq)
        self.qos.commit(seq)

    def admit(self) -> List[SequenceState]:
        """Fill free slots from the waiting queue (QoS pick; plain FIFO
        head-of-line with a single class). Admission matches the recompute
        prompt against the prefix cache, shares the matched blocks, and
        allocates only the uncached suffix — plus one extra free block of
        headroom so a fresh admission isn't preempted on its very first
        decode step just to grow someone else. The picked candidate is
        never admitted around: if it doesn't fit, admission stops."""
        admitted = []
        for slot in range(len(self.slots)):
            if self.slots[slot] is not None or not self._waiting:
                continue
            head = self._pick_candidate()
            prompt = head.recompute_prompt
            p = len(prompt)
            n_total = self.blocks.blocks_for(p)
            shared: List[int] = []
            cow_src: Optional[int] = None
            if self.cache is not None:
                shared = self.cache.match(prompt)
                if len(shared) * self.blocks.block_size >= p:
                    # every full block is cached, but the engine still needs
                    # the LAST token's logits to sample the first generated
                    # token: recompute only that token, copy-on-write its
                    # (shared, otherwise-corrupted) divergence block
                    cow_src = shared[-1]
                    shared = shared[:-1]
            n_new = n_total - len(shared)
            # no headroom demanded when the engine is idle: an exact-fit
            # request must admit (it can still grow — the engine validates
            # blocks_for(prompt+max_new) <= pool size at submit). With
            # speculative decoding on, per-tick growth is up to
            # spec_headroom_blocks MORE than the one-token step's single
            # block (k drafted positions per running sequence).
            headroom = ((1 + self.spec_headroom_blocks) if self.num_running
                        else 0)
            # matched blocks currently sitting in the evictable set leave it
            # the moment allocate_shared references them, so they must not
            # double-count as claimable headroom
            pinned = [b for b in shared if self.blocks.refcount(b) == 0]
            if cow_src is not None and self.blocks.refcount(cow_src) == 0:
                pinned.append(cow_src)
            if self.blocks.num_free - len(pinned) < n_new + headroom:
                break  # head-of-line: never admit around the picked head
            self._commit_pick(head)
            self.blocks.allocate_shared(head.seq_id, shared, n_new,
                                        cow_src=cow_src)
            head.cow_src = cow_src
            head.cached_tokens = (
                p - 1 if cow_src is not None
                else len(shared) * self.blocks.block_size
            )
            head.prefill_pos = head.cached_tokens
            head.prefilling = True
            head.slot = slot
            head.admit_order = self._admit_counter
            self._admit_counter += 1
            self.slots[slot] = head
            admitted.append(head)
            if self.tracer is not None:
                self.tracer.on_admitted(head.seq_id, slot)
        return admitted

    def _preempt_victim(self) -> SequenceState:
        """Class-aware LIFO: the newest admission of the LOWEST-priority
        running class — batch is evicted before interactive no matter who
        arrived first; within one class this is exactly the seed LIFO."""
        return max((s for _, s in self.running()),
                   key=lambda s: (s.class_idx, s.admit_order))

    def ensure_decode_capacity(self) -> List[SequenceState]:
        """Grow each decoding sequence to cover its next write position,
        preempting (class-aware LIFO) when the pool — free list plus
        evictable cached blocks — runs dry. Mid-prefill sequences already
        hold their whole prompt allocation and are skipped for growth (but
        stay preemptable). Returns the preempted sequences (already
        requeued at the front of the waiting queue)."""
        preempted: List[SequenceState] = []
        for _, seq in self.running():
            if seq.slot < 0 or seq.prefilling:  # preempted / still prefilling
                continue
            need = seq.pos // self.blocks.block_size + 1
            while self.blocks.num_allocated(seq.seq_id) < need:
                if self.blocks.can_allocate(1):
                    self.blocks.grow(seq.seq_id, 1)
                    continue
                victim = self._preempt_victim()
                self._preempt(victim)
                preempted.append(victim)
                if victim is seq:
                    break
        return preempted

    def claim_speculative(self, seq: SequenceState,
                          k: int) -> Tuple[int, List[int]]:
        """Best-effort block claim for ``k`` drafted positions beyond the
        sequence's pending write position: grow its table until positions
        [pos, pos + k] are covered or the pool runs dry. NEVER preempts —
        speculation is an optimization, so a dry pool degrades k (possibly
        to 0, a pure decode step) instead of evicting a running sequence.
        Cached refcount-0 blocks still count as claimable (the pool's
        ``free ∪ evictable`` accounting), exactly like any other growth.

        Returns ``(k_granted, claimed_blocks)``: the draft length the
        claimed coverage supports, and the freshly claimed block ids — the
        engine rolls the unaccepted suffix back via
        :meth:`KVBlockManager.shrink` after the verify step."""
        bs = self.blocks.block_size
        have = self.blocks.num_allocated(seq.seq_id)
        need = (seq.pos + k) // bs + 1
        claimed: List[int] = []
        while have < need and self.blocks.can_allocate(1):
            claimed.append(self.blocks.grow(seq.seq_id, 1)[-1])
            have += 1
        # coverage reached: positions [0, have*bs) — the last draftable
        # position is have*bs - 1, so k_granted drafts fit after pos (a
        # pool so dry the PENDING position isn't even covered grants 0;
        # the mandatory ensure_decode_capacity pass handles that case)
        return max(0, min(k, have * bs - 1 - seq.pos)), claimed

    def expired(self, now: Optional[float] = None) -> List[SequenceState]:
        """Sequences past their deadline that have produced NOTHING yet:
        still waiting for their first admission, or admitted but still
        mid-initial-prefill. A sequence that has emitted tokens keeps
        running to completion no matter where it sits — including a
        preempted one waiting to re-admit: cancelling a partially-streamed
        request mid-stream would waste the delivered tokens AND make the
        client-visible outcome depend on pool pressure (whether a
        preemption happened to land), exactly the coupling the head-of-line
        admission rule exists to prevent. Late finishers are merely marked
        deadline_missed and excluded from goodput. The engine cancels each
        returned sequence via :meth:`cancel`."""
        if now is None:
            now = time.perf_counter()
        out = [s for s in self._waiting
               if not s.generated and s.deadline_expired(now)]
        out += [s for _, s in self.running()
                if s.prefilling and not s.generated
                and s.deadline_expired(now)]
        return out

    def cache_insert(self, seq: SequenceState) -> int:
        """Register the sequence's full KV blocks in the prefix cache, keyed
        on the tokens they hold. Called at prefill completion (prompt blocks
        become shareable immediately) and before releasing blocks on
        preemption/finish (generated-token blocks stay warm)."""
        if self.cache is None or self.blocks.num_allocated(seq.seq_id) == 0:
            return 0
        n_full = seq.kv_valid_len // self.blocks.block_size
        if n_full <= 0:
            return 0
        tokens = seq.recompute_prompt
        table = self.blocks.table(seq.seq_id)
        return self.cache.insert(tokens[: n_full * self.blocks.block_size],
                                 table[:n_full])

    def _release(self, seq: SequenceState) -> None:
        """Drop the sequence's block references, caching its full blocks
        first so they stay warm for re-admission or other requests. A
        still-pinned copy-on-write source (admission happened but the
        engine's device copy hasn't landed — possible when a sequence is
        cancelled between the two) releases here too: the shed-mid-prefill
        path must leak NOTHING."""
        self.cache_insert(seq)
        self.blocks.free_seq(seq.seq_id)
        if seq.cow_src is not None:
            self.blocks.release_block(seq.cow_src)
            seq.cow_src = None

    def _preempt(self, seq: SequenceState) -> None:
        self._release(seq)
        self.slots[seq.slot] = None
        seq.slot = -1
        seq.preemptions += 1
        self.preemption_count += 1
        # reset per-admission prefill state: the next admit() re-matches the
        # (longer) recompute prompt against the cache from scratch
        seq.prefilling = False
        seq.prefill_pos = 0
        seq.cached_tokens = 0
        seq.pos = 0
        # requeue at the FRONT, bypassing the admission-control bounds:
        # admitted work is never shed by its own recompute
        self._waiting.insert(0, seq)
        if self.tracer is not None:
            self.tracer.on_preempted(seq.seq_id)

    def cancel(self, seq: SequenceState) -> None:
        """Remove a sequence wherever it is — waiting (deadline expiry,
        explicit cancel) or running (shed mid-chunked-prefill) — releasing
        every block reference it holds, including partially-claimed prefill
        blocks and a pinned copy-on-write source. Idempotent."""
        if seq.slot >= 0:
            self._release(seq)
            self.slots[seq.slot] = None
            seq.slot = -1
        else:
            try:
                self._waiting.remove(seq)
            except ValueError:
                pass  # already admitted/cancelled — nothing to remove
        self._prune_tenant(seq.tenant)

    def finish(self, seq: SequenceState) -> None:
        self._release(seq)
        if seq.slot >= 0:
            self.slots[seq.slot] = None
        seq.slot = -1
        self._prune_tenant(seq.tenant)

    def _prune_tenant(self, tenant: str) -> None:
        """Drop a fully-drained tenant's DRR credit entries (the leak
        guard lives in :class:`QoSPicker`; the drained check — no waiting
        or running work left — is the scheduler's)."""
        if any(s.tenant == tenant for s in self._waiting):
            return
        if any(s.tenant == tenant for _, s in self.running()):
            return
        self.qos.prune_tenant(tenant)
