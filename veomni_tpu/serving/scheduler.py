"""Continuous-batching scheduler: admission, slot assignment, preemption.

Policy (vLLM-style, recompute preemption):

- **FIFO admission with head-of-line blocking**: waiting requests are
  admitted in arrival order into free decode slots whenever the block pool
  can hold their (re)compute prompt plus one block of headroom. The head is
  never skipped — out-of-order admission would make greedy outputs depend
  on pool pressure, which would break token-parity guarantees.
- **Prefix-cache-aware admission**: with a
  :class:`~veomni_tpu.serving.prefix_cache.PrefixCache` attached, admission
  matches the recompute prompt against the radix tree first and charges
  only the **uncached suffix** — matched full blocks are shared by
  reference. A prompt whose every full block is cached would have nothing
  left to run (the engine still needs the last token's logits), so its
  divergence block is taken **copy-on-write**: the last matched block is
  pinned as a copy source, a fresh replacement is allocated, and only the
  final token is recomputed.
- **LIFO recompute preemption**: when a running sequence needs a block and
  the pool is dry (free list AND evictable cached blocks — eviction always
  reclaims cached blocks before a preemption fires), the most recently
  admitted running sequence is evicted. Its full blocks are **inserted into
  the prefix cache** before its references drop, so re-admission is a
  near-free cache hit instead of a full re-prefill; it is requeued at the
  FRONT of the waiting queue with ``prompt + generated-so-far`` as its
  recompute prompt. Greedy decoding is deterministic, so recompute resumes
  the exact token stream; already-emitted tokens are never re-emitted.

The scheduler is pure host bookkeeping — it owns no device state and is
unit-testable without building a model. When a
:class:`~veomni_tpu.observability.request_trace.RequestTracer` is attached
(the engine does), the scheduler reports its transitions — queued, admitted
(with slot), preempted — so every request carries a lifecycle timeline; the
engine reports the rest (prefill-done, first token, finished).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, List, Optional, Tuple

from veomni_tpu.serving.api import Request
from veomni_tpu.serving.kv_block_manager import KVBlockManager


@dataclass
class SequenceState:
    """Host-side runtime state of one request (survives preemption)."""

    request: Request
    generated: List[int] = field(default_factory=list)  # ALL emitted tokens
    rng: Any = None  # per-request PRNG key carry [2] uint32
    slot: int = -1
    pos: int = 0  # write position of the pending last token
    prefill_len: int = 0  # positions covered by the latest prefill
    admit_order: int = -1
    preemptions: int = 0
    submit_time: float = field(default_factory=time.perf_counter)
    first_token_time: Optional[float] = None
    # chunked-prefill / prefix-cache state for the CURRENT admission
    prefilling: bool = False  # admitted, prefill not finished (chunks left)
    prefill_pos: int = 0  # next uncomputed position (rows [0, here) valid)
    cached_tokens: int = 0  # positions served from the prefix cache
    cow_src: Optional[int] = None  # pinned copy-on-write source block

    @property
    def seq_id(self) -> str:
        return self.request.request_id

    @property
    def recompute_prompt(self) -> List[int]:
        """What a (re)admission must prefill: the original prompt plus every
        token generated before preemption."""
        return list(self.request.prompt_ids) + list(self.generated)

    @property
    def last_token(self) -> int:
        return self.generated[-1]

    @property
    def kv_valid_len(self) -> int:
        """Cache rows [0, here) hold real KV: up to the write position when
        decoding, up to the last completed chunk while mid-prefill."""
        return self.prefill_pos if self.prefilling else self.pos


class Scheduler:
    def __init__(self, num_slots: int, block_manager: KVBlockManager,
                 tracer: Optional[Any] = None,
                 prefix_cache: Optional[Any] = None,
                 spec_headroom_blocks: int = 0):
        if num_slots < 1:
            raise ValueError("need at least one decode slot")
        self.blocks = block_manager
        self.cache = prefix_cache
        self.waiting: Deque[SequenceState] = deque()
        self.slots: List[Optional[SequenceState]] = [None] * num_slots
        self.preemption_count = 0
        self._admit_counter = 0
        # optional RequestTracer (duck-typed: anything with on_queued /
        # on_admitted / on_preempted) — None keeps the scheduler trace-free
        self.tracer = tracer
        # extra admission headroom when the engine decodes speculatively:
        # a running sequence can grow by ceil(spec_k / block_size) blocks
        # per tick on top of the usual one, so admission keeps that many
        # more blocks free per tick — a fresh admission must not force every
        # speculative claim to degrade to k=0 on its very first step. 0
        # (the default, spec off) keeps the seed admission policy exactly.
        self.spec_headroom_blocks = spec_headroom_blocks

    # ---------------------------------------------------------------- queries
    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    @property
    def num_running(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    @property
    def has_work(self) -> bool:
        return bool(self.waiting) or self.num_running > 0

    def running(self) -> List[Tuple[int, SequenceState]]:
        """(slot, seq) pairs in slot order — the decode batch row order."""
        return [(i, s) for i, s in enumerate(self.slots) if s is not None]

    # ------------------------------------------------------------ transitions
    def add(self, seq: SequenceState) -> None:
        self.waiting.append(seq)
        if self.tracer is not None:
            self.tracer.on_queued(seq.seq_id)

    def admit(self) -> List[SequenceState]:
        """Fill free slots from the waiting queue (FIFO, head-of-line).
        Admission matches the recompute prompt against the prefix cache,
        shares the matched blocks, and allocates only the uncached suffix —
        plus one extra free block of headroom so a fresh admission isn't
        preempted on its very first decode step just to grow someone else."""
        admitted = []
        for slot in range(len(self.slots)):
            if self.slots[slot] is not None or not self.waiting:
                continue
            head = self.waiting[0]
            prompt = head.recompute_prompt
            p = len(prompt)
            n_total = self.blocks.blocks_for(p)
            shared: List[int] = []
            cow_src: Optional[int] = None
            if self.cache is not None:
                shared = self.cache.match(prompt)
                if len(shared) * self.blocks.block_size >= p:
                    # every full block is cached, but the engine still needs
                    # the LAST token's logits to sample the first generated
                    # token: recompute only that token, copy-on-write its
                    # (shared, otherwise-corrupted) divergence block
                    cow_src = shared[-1]
                    shared = shared[:-1]
            n_new = n_total - len(shared)
            # no headroom demanded when the engine is idle: an exact-fit
            # request must admit (it can still grow — the engine validates
            # blocks_for(prompt+max_new) <= pool size at submit). With
            # speculative decoding on, per-tick growth is up to
            # spec_headroom_blocks MORE than the one-token step's single
            # block (k drafted positions per running sequence).
            headroom = ((1 + self.spec_headroom_blocks) if self.num_running
                        else 0)
            # matched blocks currently sitting in the evictable set leave it
            # the moment allocate_shared references them, so they must not
            # double-count as claimable headroom
            pinned = [b for b in shared if self.blocks.refcount(b) == 0]
            if cow_src is not None and self.blocks.refcount(cow_src) == 0:
                pinned.append(cow_src)
            if self.blocks.num_free - len(pinned) < n_new + headroom:
                break  # head-of-line: never admit around the queue head
            self.waiting.popleft()
            self.blocks.allocate_shared(head.seq_id, shared, n_new,
                                        cow_src=cow_src)
            head.cow_src = cow_src
            head.cached_tokens = (
                p - 1 if cow_src is not None
                else len(shared) * self.blocks.block_size
            )
            head.prefill_pos = head.cached_tokens
            head.prefilling = True
            head.slot = slot
            head.admit_order = self._admit_counter
            self._admit_counter += 1
            self.slots[slot] = head
            admitted.append(head)
            if self.tracer is not None:
                self.tracer.on_admitted(head.seq_id, slot)
        return admitted

    def ensure_decode_capacity(self) -> List[SequenceState]:
        """Grow each decoding sequence to cover its next write position,
        preempting (LIFO) when the pool — free list plus evictable cached
        blocks — runs dry. Mid-prefill sequences already hold their whole
        prompt allocation and are skipped for growth (but stay preemptable).
        Returns the preempted sequences (already requeued at the front of
        the waiting queue)."""
        preempted: List[SequenceState] = []
        for _, seq in self.running():
            if seq.slot < 0 or seq.prefilling:  # preempted / still prefilling
                continue
            need = seq.pos // self.blocks.block_size + 1
            while self.blocks.num_allocated(seq.seq_id) < need:
                if self.blocks.can_allocate(1):
                    self.blocks.grow(seq.seq_id, 1)
                    continue
                victim = max(
                    (s for _, s in self.running()), key=lambda s: s.admit_order
                )
                self._preempt(victim)
                preempted.append(victim)
                if victim is seq:
                    break
        return preempted

    def claim_speculative(self, seq: SequenceState,
                          k: int) -> Tuple[int, List[int]]:
        """Best-effort block claim for ``k`` drafted positions beyond the
        sequence's pending write position: grow its table until positions
        [pos, pos + k] are covered or the pool runs dry. NEVER preempts —
        speculation is an optimization, so a dry pool degrades k (possibly
        to 0, a pure decode step) instead of evicting a running sequence.
        Cached refcount-0 blocks still count as claimable (the pool's
        ``free ∪ evictable`` accounting), exactly like any other growth.

        Returns ``(k_granted, claimed_blocks)``: the draft length the
        claimed coverage supports, and the freshly claimed block ids — the
        engine rolls the unaccepted suffix back via
        :meth:`KVBlockManager.shrink` after the verify step."""
        bs = self.blocks.block_size
        have = self.blocks.num_allocated(seq.seq_id)
        need = (seq.pos + k) // bs + 1
        claimed: List[int] = []
        while have < need and self.blocks.can_allocate(1):
            claimed.append(self.blocks.grow(seq.seq_id, 1)[-1])
            have += 1
        # coverage reached: positions [0, have*bs) — the last draftable
        # position is have*bs - 1, so k_granted drafts fit after pos (a
        # pool so dry the PENDING position isn't even covered grants 0;
        # the mandatory ensure_decode_capacity pass handles that case)
        return max(0, min(k, have * bs - 1 - seq.pos)), claimed

    def cache_insert(self, seq: SequenceState) -> int:
        """Register the sequence's full KV blocks in the prefix cache, keyed
        on the tokens they hold. Called at prefill completion (prompt blocks
        become shareable immediately) and before releasing blocks on
        preemption/finish (generated-token blocks stay warm)."""
        if self.cache is None or self.blocks.num_allocated(seq.seq_id) == 0:
            return 0
        n_full = seq.kv_valid_len // self.blocks.block_size
        if n_full <= 0:
            return 0
        tokens = seq.recompute_prompt
        table = self.blocks.table(seq.seq_id)
        return self.cache.insert(tokens[: n_full * self.blocks.block_size],
                                 table[:n_full])

    def _release(self, seq: SequenceState) -> None:
        """Drop the sequence's block references, caching its full blocks
        first so they stay warm for re-admission or other requests."""
        self.cache_insert(seq)
        self.blocks.free_seq(seq.seq_id)

    def _preempt(self, seq: SequenceState) -> None:
        self._release(seq)
        self.slots[seq.slot] = None
        seq.slot = -1
        seq.preemptions += 1
        self.preemption_count += 1
        # reset per-admission prefill state: the next admit() re-matches the
        # (longer) recompute prompt against the cache from scratch
        seq.prefilling = False
        seq.prefill_pos = 0
        seq.cached_tokens = 0
        seq.cow_src = None
        seq.pos = 0
        self.waiting.appendleft(seq)
        if self.tracer is not None:
            self.tracer.on_preempted(seq.seq_id)

    def finish(self, seq: SequenceState) -> None:
        self._release(seq)
        if seq.slot >= 0:
            self.slots[seq.slot] = None
        seq.slot = -1
