"""Compatibility shims across the jax versions this repo meets.

The codebase targets the current jax API surface (``jax.shard_map`` with
``check_vma``, the ``jax_num_cpu_devices`` config); some container images
pin an older jaxlib (0.4.x: ``jax.experimental.shard_map`` with
``check_rep``, virtual CPU devices via
``--xla_force_host_platform_device_count``). All version probing lives here
so the rest of the tree can use one spelling.
"""

from __future__ import annotations

import os

import jax

try:  # jax >= 0.6: top-level export, `check_vma` kwarg
    from jax import shard_map as _shard_map_new  # type: ignore

    _HAS_NEW_SHARD_MAP = True
except ImportError:  # jax 0.4.x: experimental namespace, `check_rep` kwarg
    from jax.experimental.shard_map import shard_map as _shard_map_old

    _HAS_NEW_SHARD_MAP = False


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` with the replication/VMA check knob mapped to
    whatever this jax spells it (``check_vma`` new / ``check_rep`` old)."""
    kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    if _HAS_NEW_SHARD_MAP:
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        return _shard_map_new(f, **kwargs)
    if check_vma is not None:
        kwargs["check_rep"] = check_vma
    return _shard_map_old(f, **kwargs)


def axis_size(axis_name) -> int:
    """``jax.lax.axis_size`` (newer jax) with the classic ``psum(1, axis)``
    idiom as the 0.4.x fallback — both return a static python int inside
    shard_map, which callers rely on for loop bounds."""
    try:
        return jax.lax.axis_size(axis_name)
    except AttributeError:
        return jax.lax.psum(1, axis_name)


def pallas_tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` across its rename (0.4.x:
    ``TPUCompilerParams``)."""
    import jax.experimental.pallas.tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)


def cpu_collective_timeout_flags(
    warn_s: int = 120, terminate_s: int = 600
) -> list:
    """The XLA:CPU collective-rendezvous timeout flags, or ``[]`` on jaxlib
    builds whose XLA predates them — parse_flags_from_env ABORTS the process
    on unknown flags, so these must never reach an old backend's XLA_FLAGS.
    (The flags landed alongside the jax 0.5 line; gate on that.)"""
    if jax.__version_info__ < (0, 5, 0):
        return []
    return [
        f"--xla_cpu_collective_call_warn_stuck_timeout_seconds={warn_s}",
        f"--xla_cpu_collective_call_terminate_timeout_seconds={terminate_s}",
        f"--xla_cpu_collective_timeout_seconds={terminate_s}",
    ]


def apply_cpu_collective_timeout_flags(
    warn_s: int = 120, terminate_s: int = 600
) -> None:
    """Append the (version-gated) rendezvous timeout flags to XLA_FLAGS.
    Must run before first backend init; idempotent."""
    flags = os.environ.get("XLA_FLAGS", "")
    for f in cpu_collective_timeout_flags(warn_s, terminate_s):
        if f.split("=")[0] not in flags:
            flags += " " + f
    os.environ["XLA_FLAGS"] = flags.strip()


def set_virtual_cpu_devices(n: int) -> None:
    """Force the CPU platform with ``n`` virtual devices. Must run before
    the first JAX backend initialization (both mechanisms only apply then).
    """
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        # jax 0.4.x: the device count rides XLA_FLAGS instead of jax.config.
        # Replace (not skip) an inherited value — subprocess harnesses pass
        # the parent's XLA_FLAGS through the environment
        tok = "--xla_force_host_platform_device_count"
        kept = [t for t in os.environ.get("XLA_FLAGS", "").split()
                if not t.startswith(tok)]
        kept.append(f"{tok}={n}")
        os.environ["XLA_FLAGS"] = " ".join(kept)
