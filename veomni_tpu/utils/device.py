"""Device abstraction layer (reference: ``veomni/utils/device.py:28-123``).

On the reference this switches CUDA vs Ascend-NPU; here it abstracts over TPU
generations and the CPU fallback used for tests (virtual multi-device CPU via
``--xla_force_host_platform_device_count``).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax


@functools.lru_cache(maxsize=None)
def get_device_type() -> str:
    """"tpu" | "gpu" | "cpu". The experimental "axon" tunnel platform reports
    TPU devices, so we classify by device kind rather than platform name."""
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "").lower()
    platform = getattr(dev, "platform", "").lower()
    if "tpu" in kind or platform == "tpu" or platform == "axon":
        return "tpu"
    if platform in ("gpu", "cuda", "rocm"):
        return "gpu"
    return "cpu"


def is_tpu_available() -> bool:
    return get_device_type() == "tpu"


@functools.lru_cache(maxsize=None)
def supports_pallas() -> bool:
    """Whether Pallas kernels can actually execute here.

    The experimental "axon" relay platform accepts pallas_call lowering but
    hangs at execution (observed: even a trivial VMEM copy kernel never
    returns), so Pallas is gated off there. CPU supports interpret mode.
    Override with VEOMNI_AXON_PALLAS=1 to re-test on axon.
    """
    import os

    dev = jax.devices()[0]
    try:  # r5 relay: the device reports kind "TPU v5 lite" but the BACKEND
        # platform is still "axon" — check both surfaces
        backend_platform = jax.extend.backend.get_backend().platform
    except Exception:
        backend_platform = ""
    if (
        getattr(dev, "platform", "") == "axon"
        or backend_platform == "axon"
        or "axon" in str(getattr(dev, "client", ""))
    ):
        return os.environ.get("VEOMNI_AXON_PALLAS") == "1"
    return True


def device_count() -> int:
    return jax.device_count()


def local_device_count() -> int:
    return jax.local_device_count()


def synchronize() -> None:
    """Block until all dispatched device work is done (cf. torch.cuda.synchronize)."""
    # A tiny transfer drains the dispatch queue on every local device.
    for d in jax.local_devices():
        jax.device_put(0.0, d).block_until_ready()


@functools.lru_cache(maxsize=None)
def get_device_peak_flops(dtype: str = "bf16") -> float:
    """Peak FLOP/s per chip (cf. reference ``count_flops.py:25`` get_device_flops).

    Values are the published bf16 dense peak numbers per chip.
    """
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    table = {
        # TPU generations (bf16 peak per chip)
        "tpu v2": 45e12,
        "tpu v3": 123e12,
        "tpu v4": 275e12,
        "tpu v5 lite": 197e12,  # v5e
        "tpu v5e": 197e12,
        "tpu v5": 459e12,  # v5p
        "tpu v5p": 459e12,
        "tpu v6 lite": 918e12,  # trillium
        "tpu v6e": 918e12,
        "tpu7x": 4614e12,
    }
    for key in sorted(table, key=len, reverse=True):
        if kind.startswith(key):
            return table[key]
    if get_device_type() == "cpu":
        return 1e12  # nominal, keeps MFU math finite in tests
    return 197e12


@functools.lru_cache(maxsize=None)
def get_device_peak_bandwidth() -> float:
    """Peak HBM bandwidth per chip in bytes/s (published numbers, same
    table discipline as :func:`get_device_peak_flops`).

    Feeds the roofline machine balance (peak FLOP/s ÷ peak bytes/s) the
    cost census classifies compiled programs against, and the
    ``bandwidth_util_pct`` window gauge."""
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    table = {
        "tpu v2": 700e9,
        "tpu v3": 900e9,
        "tpu v4": 1228e9,
        "tpu v5 lite": 819e9,  # v5e
        "tpu v5e": 819e9,
        "tpu v5": 2765e9,  # v5p
        "tpu v5p": 2765e9,
        "tpu v6 lite": 1640e9,  # trillium
        "tpu v6e": 1640e9,
        "tpu7x": 7400e9,
    }
    for key in sorted(table, key=len, reverse=True):
        if kind.startswith(key):
            return table[key]
    if get_device_type() == "cpu":
        return 1e11  # nominal, keeps bandwidth-utilization math finite
    return 819e9


@functools.lru_cache(maxsize=None)
def get_device_peak_interconnect_bandwidth() -> float:
    """Nominal per-chip aggregate ICI bandwidth in bytes/s (same table
    discipline as :func:`get_device_peak_flops`).

    Feeds the comm observatory's predicted-comm-time gauges and the
    ``comm``-bound extension of the roofline verdict
    (``observability/comm.py``). These are order-of-magnitude link-budget
    numbers (links x per-link one-way bandwidth, torus generations assume
    the full link complement), not measured all-reduce goodput — the
    estimate they produce says *where to look*, it is not an SLA."""
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    table = {
        "tpu v2": 100e9,
        "tpu v3": 140e9,
        "tpu v4": 270e9,        # 6 links x 45 GB/s (3D torus)
        "tpu v5 lite": 180e9,   # v5e: 4 links x 45 GB/s (2D torus)
        "tpu v5e": 180e9,
        "tpu v5": 540e9,        # v5p: 6 links x 90 GB/s
        "tpu v5p": 540e9,
        "tpu v6 lite": 360e9,   # trillium: 4 links x 90 GB/s
        "tpu v6e": 360e9,
        "tpu7x": 1200e9,
    }
    for key in sorted(table, key=len, reverse=True):
        if kind.startswith(key):
            return table[key]
    if get_device_type() == "cpu":
        return 1e10  # nominal, keeps comm-time estimates finite in tests
    return 180e9


def mesh_devices_grid(shape: Tuple[int, ...]):
    """Devices reshaped to ``shape`` for building a Mesh; validates count."""
    import numpy as np

    devs = np.array(jax.devices())
    n = int(np.prod(shape))
    if n != devs.size:
        raise ValueError(f"mesh shape {shape} needs {n} devices, have {devs.size}")
    return devs.reshape(shape)
