"""Centralized env-flag system (reference: ``veomni/utils/env.py:23-34``).

All VEOMNI_* environment flags are declared here with defaults so they can be
printed at import and discovered in one place.
"""

from __future__ import annotations

import os
from typing import Any, Dict

ENV_DEFAULTS: Dict[str, Any] = {
    # "native" = our own model zoo; "hf" reserved for torch-free HF-config load.
    "VEOMNI_MODELING_BACKEND": "native",
    # Log level for the framework logger.
    "VEOMNI_LOG_LEVEL": "INFO",
    # Force all kernel-registry ops to the eager XLA impl (skip Pallas).
    "VEOMNI_FORCE_EAGER_OPS": "0",
    # Directory for JAX persistent compilation cache ("" disables).
    "VEOMNI_COMPILE_CACHE": "",
    # Use donated buffers in the train step (disable when debugging).
    "VEOMNI_DONATE_STATE": "1",
    # Seq length above which the default XLA attention switches to the
    # blockwise online-softmax (flash-style) path instead of materializing
    # the [B, H, S, S] score tensor.
    "VEOMNI_ATTN_CHUNK_THRESHOLD": "2048",
    # Route Ulysses SP attention through the chunked async a2a/compute
    # pipeline (parallel/async_ulysses.py) instead of the monolithic a2a.
    "VEOMNI_ULYSSES_ASYNC": "0",
    # Head-chunk count for the async Ulysses pipeline (clamped to the
    # feasible maximum of the model's head layout).
    "VEOMNI_ULYSSES_ASYNC_CHUNKS": "4",
    # Deterministic fault-injection plan (JSON text or @file) arming the
    # resilience fault points — see docs/resilience.md. "" = unarmed.
    "VEOMNI_FAULT_PLAN": "",
}


def get_env(name: str) -> str:
    if name not in ENV_DEFAULTS:
        raise KeyError(f"Unknown env flag {name}; declare it in ENV_DEFAULTS")
    return os.environ.get(name, str(ENV_DEFAULTS[name]))


def env_bool(name: str) -> bool:
    return get_env(name).lower() in ("1", "true", "yes", "on")


def describe_env() -> str:
    lines = []
    for k, default in sorted(ENV_DEFAULTS.items()):
        v = os.environ.get(k)
        lines.append(f"  {k}={v if v is not None else default}{'' if v is None else ' (set)'}")
    return "Environment flags:\n" + "\n".join(lines)
