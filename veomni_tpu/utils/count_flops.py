"""Analytic per-step FLOPs counter.

Reference: ``veomni/utils/count_flops.py:60-988`` (``VeomniFlopsCounter``) —
per-architecture formulas used by the MFU meter. We implement the dense
transformer, GQA attention, MoE, and ViT terms from model config fields.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass
class FlopsCounter:
    """Computes promised forward+backward FLOPs for one batch.

    Counts follow the standard 6*N*T approximation refined per-term:
      - matmul fwd = 2*M*N*K; bwd = 2x fwd (dgrad+wgrad) => total 6*M*N*K
      - attention scores/context scale with seq_len^2 (causal halves it)
    """

    hidden_size: int
    intermediate_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    vocab_size: int
    # MoE (0 => dense)
    num_experts: int = 0
    num_experts_per_tok: int = 0
    moe_intermediate_size: int = 0
    num_shared_experts: int = 0
    # ViT tower (VLM); counted per image token externally
    tie_word_embeddings: bool = False

    def flops_per_token_fwd(self, seq_len: int) -> float:
        h = self.hidden_size
        q_dim = self.num_heads * self.head_dim
        kv_dim = self.num_kv_heads * self.head_dim
        # attention projections (q,k,v,o)
        proj = 2 * h * (q_dim + 2 * kv_dim + q_dim)
        # scores + context (causal => T/2 effective)
        attn = 2 * 2 * q_dim * (seq_len / 2)
        # MLP
        if self.num_experts and self.num_experts_per_tok:
            inter = self.moe_intermediate_size or self.intermediate_size
            mlp = 2 * 3 * h * inter * self.num_experts_per_tok
            mlp += 2 * 3 * h * inter * self.num_shared_experts
            mlp += 2 * h * self.num_experts  # router
        else:
            mlp = 2 * 3 * h * self.intermediate_size
        per_layer = proj + attn + mlp
        lm_head = 2 * h * self.vocab_size
        return self.num_layers * per_layer + lm_head

    def batch_flops(self, total_tokens: int, seq_len: int, include_backward: bool = True) -> float:
        fwd = total_tokens * self.flops_per_token_fwd(seq_len)
        return fwd * 3.0 if include_backward else fwd

    @classmethod
    def from_config(cls, cfg) -> "FlopsCounter":
        """Build from any model config exposing llama-family field names."""
        g = lambda n, d=0: getattr(cfg, n, d)
        head_dim = g("head_dim") or (g("hidden_size") // max(1, g("num_attention_heads", 1)))
        return cls(
            hidden_size=g("hidden_size"),
            intermediate_size=g("intermediate_size"),
            num_layers=g("num_hidden_layers"),
            num_heads=g("num_attention_heads"),
            num_kv_heads=g("num_key_value_heads") or g("num_attention_heads"),
            head_dim=head_dim,
            vocab_size=g("vocab_size"),
            num_experts=g("num_experts", 0) or g("n_routed_experts", 0),
            num_experts_per_tok=g("num_experts_per_tok", 0),
            moe_intermediate_size=g("moe_intermediate_size", 0),
            num_shared_experts=g("n_shared_experts", 0),
            tie_word_embeddings=g("tie_word_embeddings", False),
        )
