"""Analytic per-step FLOPs counter.

Reference: ``veomni/utils/count_flops.py:60-988`` (``VeomniFlopsCounter``) —
per-architecture formulas used by the MFU meter. Implemented terms:

* dense GQA transformer (llama/qwen lineage), incl. partial-rotary and the
  qwen3_next gated-attention q_proj doubling;
* MLA (deepseek q/kv low-rank compression — NOT approximated as plain
  ``nh * head_dim`` projections);
* MoE (top-k routed + shared experts + router);
* qwen3_next GatedDeltaNet linear-attention layers (chunkwise cost model);
* ViT towers (per-patch, window or full attention) and DiT blocks via the
  dedicated helpers, fed to the meter as ``extra_flops``.

Counts follow the standard factorization: matmul fwd = 2*M*N*K, backward =
2x forward (dgrad + wgrad), so total = 3x forward.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass
class FlopsCounter:
    """Promised forward FLOPs per token for the language model."""

    hidden_size: int
    intermediate_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    vocab_size: int
    # MoE (0 => dense)
    num_experts: int = 0
    num_experts_per_tok: int = 0
    moe_intermediate_size: int = 0
    num_shared_experts: int = 0
    shared_expert_intermediate_size: int = 0
    tie_word_embeddings: bool = False
    # MLA (deepseek); kv_lora_rank > 0 switches the attention-projection term
    q_lora_rank: int = 0
    kv_lora_rank: int = 0
    qk_nope_head_dim: int = 0
    qk_rope_head_dim: int = 0
    v_head_dim: int = 0
    # qwen3_next hybrid: every `full_attention_interval`-th layer is full
    # attention, the rest are GatedDeltaNet linear attention
    linear_num_value_heads: int = 0
    linear_num_key_heads: int = 0
    linear_key_head_dim: int = 0
    linear_value_head_dim: int = 0
    linear_conv_kernel_dim: int = 4
    full_attention_interval: int = 0
    attn_output_gate: bool = False
    delta_chunk: int = 64

    # ------------------------------------------------------------- per-term
    def _attn_proj_flops(self) -> float:
        """q/k/v/o projections per token (fwd)."""
        h = self.hidden_size
        if self.kv_lora_rank:
            qk = self.qk_nope_head_dim + self.qk_rope_head_dim
            nh, vd = self.num_heads, self.v_head_dim
            q = (
                2 * h * self.q_lora_rank + 2 * self.q_lora_rank * nh * qk
                if self.q_lora_rank
                else 2 * h * nh * qk
            )
            kv_a = 2 * h * (self.kv_lora_rank + self.qk_rope_head_dim)
            kv_b = 2 * self.kv_lora_rank * nh * (self.qk_nope_head_dim + vd)
            o = 2 * nh * vd * h
            return q + kv_a + kv_b + o
        q_dim = self.num_heads * self.head_dim
        kv_dim = self.num_kv_heads * self.head_dim
        q_mult = 2 if self.attn_output_gate else 1
        return 2 * h * (q_mult * q_dim + 2 * kv_dim + q_dim)

    def _attn_score_flops(self, seq_len: int) -> float:
        """scores + context per token (fwd); causal halves the window."""
        if self.kv_lora_rank:
            qk = self.qk_nope_head_dim + self.qk_rope_head_dim
            per_head = 2 * (qk + self.v_head_dim) * (seq_len / 2)
            return self.num_heads * per_head
        return 2 * 2 * self.num_heads * self.head_dim * (seq_len / 2)

    def _mlp_flops(self) -> float:
        h = self.hidden_size
        if self.num_experts and self.num_experts_per_tok:
            inter = self.moe_intermediate_size or self.intermediate_size
            mlp = 2 * 3 * h * inter * self.num_experts_per_tok
            shared = self.shared_expert_intermediate_size or (
                inter * self.num_shared_experts
            )
            if shared:
                mlp += 2 * 3 * h * shared + (2 * h if self.shared_expert_intermediate_size else 0)
            mlp += 2 * h * self.num_experts  # router
            return mlp
        return 2 * 3 * h * self.intermediate_size

    def _linear_attn_flops(self) -> float:
        """GatedDeltaNet per-token fwd cost: projections + conv + chunkwise
        delta rule (in-chunk attn/UT-transform + state update)."""
        h = self.hidden_size
        nk, nv = self.linear_num_key_heads, self.linear_num_value_heads
        dk, dv = self.linear_key_head_dim, self.linear_value_head_dim
        key_dim, value_dim = nk * dk, nv * dv
        conv_dim = 2 * key_dim + value_dim
        proj = 2 * h * (2 * key_dim + 2 * value_dim)      # in_proj_qkvz
        proj += 2 * h * 2 * nv                             # in_proj_ba
        proj += 2 * value_dim * h                          # out_proj
        conv = 2 * conv_dim * self.linear_conv_kernel_dim
        c = self.delta_chunk
        # per token, per v-head: in-chunk score/attn matrices ~ 4*C*dk +
        # 2*C*dv (kk^T, T-solve amortized, attn@v), state ops ~ 6*dk*dv
        delta = nv * (4 * c * dk + 2 * c * dv + 6 * dk * dv)
        return proj + conv + delta

    # ------------------------------------------------------------ aggregate
    def flops_per_token_fwd(self, seq_len: int) -> float:
        mlp = self._mlp_flops()
        full_layer = self._attn_proj_flops() + self._attn_score_flops(seq_len) + mlp
        if self.full_attention_interval and self.linear_num_value_heads:
            n_full = self.num_layers // self.full_attention_interval
            n_lin = self.num_layers - n_full
            lin_layer = self._linear_attn_flops() + mlp
            body = n_full * full_layer + n_lin * lin_layer
        else:
            body = self.num_layers * full_layer
        lm_head = 2 * self.hidden_size * self.vocab_size
        return body + lm_head

    def batch_flops(self, total_tokens: int, seq_len: int, include_backward: bool = True) -> float:
        fwd = total_tokens * self.flops_per_token_fwd(seq_len)
        return fwd * 3.0 if include_backward else fwd

    @classmethod
    def from_config(cls, cfg) -> "FlopsCounter":
        """Build from any model config exposing llama-family field names.
        Composite (VLM/omni) configs contribute their LM via ``cfg.text``;
        tower FLOPs are fed separately (``vit_flops_fwd``)."""
        if hasattr(cfg, "text") and hasattr(cfg.text, "hidden_size"):
            cfg = cfg.text
        g = lambda n, d=0: getattr(cfg, n, d)
        head_dim = g("head_dim") or (g("hidden_size") // max(1, g("num_attention_heads", 1)))
        return cls(
            hidden_size=g("hidden_size"),
            intermediate_size=g("intermediate_size"),
            num_layers=g("num_hidden_layers"),
            num_heads=g("num_attention_heads"),
            num_kv_heads=g("num_key_value_heads") or g("num_attention_heads"),
            head_dim=head_dim,
            vocab_size=g("vocab_size"),
            num_experts=g("num_experts", 0) or g("n_routed_experts", 0),
            num_experts_per_tok=g("num_experts_per_tok", 0),
            moe_intermediate_size=g("moe_intermediate_size", 0),
            num_shared_experts=g("n_shared_experts", 0),
            shared_expert_intermediate_size=g("shared_expert_intermediate_size", 0),
            tie_word_embeddings=g("tie_word_embeddings", False),
            q_lora_rank=g("q_lora_rank", 0),
            kv_lora_rank=g("kv_lora_rank", 0),
            qk_nope_head_dim=g("qk_nope_head_dim", 0),
            qk_rope_head_dim=g("qk_rope_head_dim", 0),
            v_head_dim=g("v_head_dim", 0),
            linear_num_value_heads=g("linear_num_value_heads", 0),
            linear_num_key_heads=g("linear_num_key_heads", 0),
            linear_key_head_dim=g("linear_key_head_dim", 0),
            linear_value_head_dim=g("linear_value_head_dim", 0),
            linear_conv_kernel_dim=g("linear_conv_kernel_dim", 4),
            full_attention_interval=(
                g("full_attention_interval", 0) if g("linear_num_value_heads", 0) else 0
            ),
            attn_output_gate=g("attn_output_gate", False),
        )


def vit_flops_fwd(vision_cfg, n_patches: int, window_seq: Optional[int] = None) -> float:
    """Forward FLOPs of a ViT tower on ``n_patches`` patches (reference
    ``count_flops.py`` ViT terms for the qwen-vl families).

    window_seq: attention span per patch (window attention); defaults to
    n_patches (full attention among all patches — an upper bound when
    multiple images are packed)."""
    g = lambda n, d=0: getattr(vision_cfg, n, d)
    h = g("hidden_size")
    inter = g("intermediate_size") or 4 * h
    layers = g("depth", 0) or g("num_hidden_layers", 0)
    span = window_seq if window_seq else n_patches
    per_patch = 2 * h * 4 * h                 # qkv + o projections
    per_patch += 2 * 2 * h * span             # scores + context
    per_patch += 2 * 3 * h * inter if g("gated_mlp", True) else 2 * 2 * h * inter
    body = layers * per_patch * n_patches
    # patch embed + merger
    in_dim = g("in_channels", 3) * g("temporal_patch_size", 1) * g("patch_size", 14) ** 2
    embed = 2 * in_dim * h * n_patches
    merge = g("merge_unit", 4)
    out_h = g("out_hidden_size", h)
    merger = 2 * (h * merge) * out_h * (n_patches // max(merge, 1))
    return body + embed + merger


def dit_flops_fwd(cfg, n_tokens: int) -> float:
    """Forward FLOPs of a DiT on ``n_tokens`` latent tokens per sample."""
    g = lambda n, d=0: getattr(cfg, n, d)
    h = g("hidden_size")
    inter = g("intermediate_size") or 4 * h
    layers = g("num_hidden_layers", 0) or g("depth", 0)
    per_tok = 2 * h * 4 * h + 2 * 2 * h * n_tokens + 2 * 2 * h * inter
    per_tok += 2 * h * 6 * h  # adaLN modulation
    return layers * per_tok * n_tokens
