"""MoE router monitoring: expert-load capture + imbalance callback.

Reference: ``veomni/utils/moe_monitor.py:83-267`` (MoERouterMonitor expert-
load heatmaps via router forward hooks) and ``moe_router_replay.py``
(capture/replay routing decisions).

TPU design: inside jit there are no hooks, so the monitor does an *eager
replay* — a python-loop forward over layer slices with a capture list that
``_moe_mlp`` appends its top-k choices to. Run it occasionally on a probe
batch (it costs one un-jitted forward).
"""

from __future__ import annotations

import contextlib
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from veomni_tpu.models import transformer
from veomni_tpu.trainer.callbacks import Callback
from veomni_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@contextlib.contextmanager
def capture_routing():
    captured: List[jax.Array] = []
    transformer.ROUTER_CAPTURE = captured
    try:
        yield captured
    finally:
        transformer.ROUTER_CAPTURE = None


def capture_router_stats(model, params, batch) -> Dict[str, np.ndarray]:
    """Eager replay forward -> per-layer expert load fractions [L, E]."""
    cfg = model.config
    with capture_routing():
        # python-loop forward (no scan -> one capture entry per MoE layer)
        compute = jax.tree.map(lambda p: p.astype(cfg.dtype), params)
        hidden = compute["embed_tokens"][batch["input_ids"]]
        if cfg.embed_scale:
            hidden = hidden * jnp.asarray(cfg.embed_scale, cfg.dtype)
        rope_dim = (
            cfg.qk_rope_head_dim if cfg.use_mla
            else int(cfg.head_dim * cfg.partial_rotary_factor)
        )
        cos, sin = transformer.ops.rotary_tables(
            batch["position_ids"], rope_dim, cfg.rope_theta, cfg.rope_scaling
        )
        cos, sin = cos.astype(cfg.dtype), sin.astype(cfg.dtype)
        L = cfg.num_hidden_layers
        k_dense = cfg.first_k_dense_replace if cfg.is_moe else 0
        trees = ([("dense_layers", k_dense, False)] if k_dense else []) + [
            ("layers", L - k_dense, cfg.is_moe)
        ]
        caps: List[jax.Array] = transformer.ROUTER_CAPTURE
        offset = 0
        for name, count, is_moe in trees:
            tree = compute[name]
            for i in range(count):
                lp = jax.tree.map(lambda t: t[i], tree)
                # non-carry call: 2-tuple always (DSA layers compute their
                # own selection; "shared" reuse is a train-path optimization)
                hidden, _ = transformer._decoder_layer(
                    hidden, lp, cfg=cfg, cos=cos, sin=sin,
                    segment_ids=batch.get("segment_ids"),
                    window=cfg.window_for_layer(offset + i) or None,
                    is_moe_segment=is_moe,
                )[:2]
            offset += count
    loads = []
    for topk in caps:
        counts = np.bincount(
            np.asarray(topk).reshape(-1), minlength=cfg.num_experts
        ).astype(np.float64)
        loads.append(counts / max(counts.sum(), 1))
    return {"expert_load": np.stack(loads) if loads else np.zeros((0, cfg.num_experts))}


def publish_router_stats(load: "np.ndarray", registry=None) -> None:
    """Per-layer router health -> registry gauges (``moe.layer{i}.*``):

    * ``entropy``   — routing entropy in nats (ln E = perfectly balanced);
    * ``max_load``  — the hottest expert's load fraction;
    * ``drop_frac`` — load mass above the per-expert fair share, i.e. the
      fraction a capacity-factor-1.0 dispatcher would drop. This impl
      dispatches dropless, so it measures imbalance *pressure*, not actual
      token loss.
    """
    from veomni_tpu.observability.metrics import get_registry

    reg = registry or get_registry()
    for li, row in enumerate(np.asarray(load, np.float64)):
        nz = row[row > 0]
        reg.gauge(f"moe.layer{li}.entropy").set(
            float(-(nz * np.log(nz)).sum()) if len(nz) else 0.0
        )
        reg.gauge(f"moe.layer{li}.max_load").set(float(row.max()))
        reg.gauge(f"moe.layer{li}.drop_frac").set(
            float(np.clip(row - 1.0 / len(row), 0.0, None).sum())
        )


class MoERouterMonitorCallback(Callback):
    """Periodically replays routing on the current batch, publishes
    per-layer gauges (entropy / max-load / drop-fraction) through the
    observability registry, and logs the min/max imbalance summary."""

    def __init__(self, every_steps: int = 100):
        self.every = every_steps

    def on_step_end(self, trainer, state):
        if not getattr(trainer.model.config, "is_moe", False):
            return
        if state.global_step % self.every:
            return
        batch = {
            k: jnp.asarray(v[0]) for k, v in trainer.current_batch.items()
        }  # first micro-batch
        stats = capture_router_stats(trainer.model, trainer.train_state.params, batch)
        load = stats["expert_load"]
        if len(load):
            publish_router_stats(load)
            logger.info_rank0(
                "moe router load: min=%.3f max=%.3f (ideal %.3f) worst layer %d",
                load.min(), load.max(), 1.0 / load.shape[1], int(load.max(1).argmax()),
            )
