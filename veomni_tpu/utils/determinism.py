"""Determinism utilities.

Reference: ``veomni/ops/batch_invariant_ops/`` (Triton batch-invariant
matmul/norm swapped in per micro-step, ``trainer/base.py:737,750``) and
``enable_full_determinism`` (``utils/helper.py:425-463``: cublas workspace,
deterministic algorithms).

On TPU these are no-op shims by design: XLA:TPU compiles fixed reduction
orders for fixed shapes, so the same program on the same inputs is bitwise
reproducible, and batch invariance holds whenever the compiled shape is the
same (our static-shape pipeline guarantees that). The context manager is
kept so reference-style call sites port cleanly.
"""

from __future__ import annotations

import contextlib

from veomni_tpu.utils.helper import set_seed


@contextlib.contextmanager
def set_batch_invariant_mode(enabled: bool = True):
    """No-op on TPU (XLA static-shape programs are batch-invariant)."""
    yield


def enable_full_determinism(seed: int):
    """Seed all RNG streams; XLA handles the rest (see module docstring)."""
    return set_seed(seed)
