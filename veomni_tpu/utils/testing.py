"""Test/dry-run helpers."""

from __future__ import annotations


def force_cpu_devices(n: int = 8) -> None:
    """Run on N virtual CPU devices (call before any JAX backend use).

    The axon TPU plugin overrides JAX_PLATFORMS via jax.config at import, so
    env vars alone don't stick — we must update the config directly.

    Also raises the XLA:CPU collective-rendezvous stuck/terminate timeouts:
    N virtual devices time-share a few (often 1) physical cores, so a slow
    participant can exceed the default 40s and SIGABRT the process mid-step
    (observed: CollectivePermute AwaitAndLogIfStuck at seq 32k — the flags
    only apply at first backend init, hence here).
    """
    import os

    import jax

    flags = os.environ.get("XLA_FLAGS", "")
    for f in (
        "--xla_cpu_collective_call_warn_stuck_timeout_seconds=300",
        "--xla_cpu_collective_call_terminate_timeout_seconds=1800",
        "--xla_cpu_collective_timeout_seconds=1800",
    ):
        if f.split("=")[0] not in flags:
            flags += " " + f
    os.environ["XLA_FLAGS"] = flags.strip()
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", n)
