"""Test/dry-run helpers."""

from __future__ import annotations


def force_cpu_devices(n: int = 8) -> None:
    """Run on N virtual CPU devices (call before any JAX backend use).

    The axon TPU plugin overrides JAX_PLATFORMS via jax.config at import, so
    env vars alone don't stick — we must update the config directly.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", n)
