"""Test/dry-run helpers."""

from __future__ import annotations


def force_cpu_devices(n: int = 8) -> None:
    """Run on N virtual CPU devices (call before any JAX backend use).

    The axon TPU plugin overrides JAX_PLATFORMS via jax.config at import, so
    env vars alone don't stick — we must update the config directly.

    Also raises the XLA:CPU collective-rendezvous stuck/terminate timeouts:
    N virtual devices time-share a few (often 1) physical cores, so a slow
    participant can exceed the default 40s and SIGABRT the process mid-step
    (observed: CollectivePermute AwaitAndLogIfStuck at seq 32k — the flags
    only apply at first backend init, hence here).
    """
    from veomni_tpu.utils.jax_compat import (
        apply_cpu_collective_timeout_flags,
        set_virtual_cpu_devices,
    )

    apply_cpu_collective_timeout_flags(warn_s=300, terminate_s=1800)
    set_virtual_cpu_devices(n)
