"""Rank-aware logging.

TPU-native counterpart of the reference's ``veomni/utils/logging.py`` (rank0
filtering, warn-once). On a single-controller JAX deployment "rank" means
``jax.process_index()``; we read it lazily so the logger works before
``jax.distributed.initialize``.
"""

from __future__ import annotations

import functools
import logging
import os
import sys
import threading

_FORMAT = "[%(asctime)s] [%(levelname)s] [%(name)s:%(lineno)d] %(message)s"
_lock = threading.Lock()
_configured = False


def _process_index() -> int:
    """Rank for *_rank0 gating WITHOUT forcing backend init: jax.process_index
    would claim the accelerator (on the axon TPU that can block for many
    minutes behind another claimant) — a log call must never be the thing
    that initializes the backend. Pre-init we trust the launcher env."""
    try:
        from jax._src import xla_bridge

        if xla_bridge._backends:  # already initialized: authoritative
            import jax

            return jax.process_index()
    except Exception:
        pass
    return int(
        os.environ.get(
            "VEOMNI_PROCESS_ID", os.environ.get("JAX_PROCESS_INDEX", "0")
        )
    )


def _configure_root() -> None:
    global _configured
    with _lock:
        if _configured:
            return
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT, datefmt="%Y-%m-%d %H:%M:%S"))
        root = logging.getLogger("veomni_tpu")
        root.addHandler(handler)
        root.setLevel(os.environ.get("VEOMNI_LOG_LEVEL", "INFO").upper())
        root.propagate = False
        _configured = True


class _RankLogger(logging.LoggerAdapter):
    """Adds ``*_rank0`` / ``*_once`` variants like the reference logger."""

    def info_rank0(self, msg, *args, **kwargs):
        if _process_index() == 0:
            self.info(msg, *args, **kwargs)

    def warning_rank0(self, msg, *args, **kwargs):
        if _process_index() == 0:
            self.warning(msg, *args, **kwargs)

    @functools.lru_cache(maxsize=None)
    def _seen(self, msg: str) -> bool:  # lru_cache as the dedupe set
        return True

    def warning_once(self, msg, *args, **kwargs):
        key = msg % args if args else msg
        if key not in getattr(self, "_once_seen", set()):
            if not hasattr(self, "_once_seen"):
                self._once_seen = set()
            self._once_seen.add(key)
            self.warning(msg, *args, **kwargs)

    def info_once(self, msg, *args, **kwargs):
        key = msg % args if args else msg
        if not hasattr(self, "_once_seen"):
            self._once_seen = set()
        if key not in self._once_seen:
            self._once_seen.add(key)
            self.info(msg, *args, **kwargs)


def get_logger(name: str = "veomni_tpu") -> _RankLogger:
    _configure_root()
    if not name.startswith("veomni_tpu"):
        name = f"veomni_tpu.{name}"
    return _RankLogger(logging.getLogger(name), {})
