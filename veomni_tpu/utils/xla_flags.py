"""XLA performance flags (the TPU analogue of the reference's async-Ulysses
comm/compute overlap, ``distributed/sequence_parallel/async_ulysses.py``:
on TPU, overlap is the compiler's job — the latency-hiding scheduler
reorders collectives behind compute when these flags are on).

Must run BEFORE the first JAX backend initialization; entrypoints
(tasks/*, bench.py) call ``apply_performance_flags()`` first thing.
Disable with ``VEOMNI_XLA_PERF_FLAGS=0``.
"""

from __future__ import annotations

import os

_PERF_FLAGS = (
    # overlap ICI collectives (Ulysses a2a, FSDP all-gather/reduce-scatter)
    # with compute instead of scheduling them synchronously
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    # allow collectives to combine into fewer, larger transfers
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
)


def apply_performance_flags() -> bool:
    """Append the TPU perf flags to XLA_FLAGS (idempotent). Returns whether
    the flags are active."""
    if os.environ.get("VEOMNI_XLA_PERF_FLAGS", "1") in ("0", "false"):
        return False
    import jax

    if jax._src.xla_bridge._backends:  # backend already up: flags won't apply
        return False
    current = os.environ.get("XLA_FLAGS", "")
    present = {tok.split("=")[0] for tok in current.split()}
    added = [f for f in _PERF_FLAGS if f.split("=")[0] not in present]
    if added:
        os.environ["XLA_FLAGS"] = (current + " " + " ".join(added)).strip()
    return True
