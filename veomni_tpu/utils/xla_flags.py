"""XLA performance flags (the TPU analogue of the reference's async-Ulysses
comm/compute overlap, ``distributed/sequence_parallel/async_ulysses.py``:
on TPU, overlap is the compiler's job — the latency-hiding scheduler
reorders collectives behind compute when these flags are on).

Must run BEFORE the first JAX backend initialization; entrypoints
(tasks/*, bench.py) call ``apply_performance_flags()`` first thing.
Disable with ``VEOMNI_XLA_PERF_FLAGS=0``.
"""

from __future__ import annotations

import os

_PERF_FLAGS = (
    # overlap ICI collectives (Ulysses a2a, FSDP all-gather/reduce-scatter)
    # with compute instead of scheduling them synchronously
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    # allow collectives to combine into fewer, larger transfers
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
)


def enable_compilation_cache() -> str | None:
    """Point JAX's persistent compilation cache at a repo-local directory so
    repeat runs (notably the driver's round-end ``bench.py``) pay zero
    compile time. First TPU compiles through the relay take 20-40s each and
    have hit the 900s bench watchdog twice; the cache is the mitigation.
    Disable with ``VEOMNI_COMPILATION_CACHE=0``."""
    if os.environ.get("VEOMNI_COMPILATION_CACHE", "1") in ("0", "false"):
        return None
    cache_dir = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), ".jax_cache"),
    )
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache everything, even fast compiles: the relay's fixed per-compile
        # round-trip dominates small programs too
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        return None
    return cache_dir


def _axon_plugin_registered() -> bool | None:
    """Whether the axon relay PJRT plugin is registered (pre-init check —
    reading ``jax.devices()`` here would trigger the very parse abort we are
    avoiding). Returns None when the probe itself fails (e.g. a JAX-internal
    rename of ``_backend_factories``): callers must treat that as UNKNOWN
    and fail closed — assuming "no plugin" on a probe error would re-enable
    the perf flags on the very platform whose XLA build aborts on them."""
    try:
        import jax  # noqa: F401
        from jax._src import xla_bridge

        return "axon" in xla_bridge._backend_factories
    except Exception:
        return None


def apply_performance_flags() -> bool:
    """Append the TPU perf flags to XLA_FLAGS (idempotent) and enable the
    persistent compilation cache. Returns whether the flags are active."""
    # the cache has its own kill switch (VEOMNI_COMPILATION_CACHE) and must
    # stay on even when the perf flags are disabled for debugging
    enable_compilation_cache()
    if os.environ.get("VEOMNI_XLA_PERF_FLAGS", "1") in ("0", "false"):
        return False
    import jax

    if jax._src.xla_bridge._backends:  # backend already up: flags won't apply
        return False
    probe = _axon_plugin_registered()
    if probe is not False and os.environ.get("VEOMNI_XLA_PERF_FLAGS") != "force":
        # The axon relay's plugin FATALS at XLA_FLAGS parse time on flags its
        # XLA build doesn't know (parse_flags_from_env.cc "Unknown flags"
        # abort, observed r5 with all three --xla_tpu_* scheduler flags).
        # Its remote-compile terminal also overrides client XLA_FLAGS with
        # its own compile env, so client-side flags would not reach the real
        # compile anyway. Skip them when the plugin is present — AND when the
        # probe errored (probe None: fail closed, a JAX-internal rename must
        # not re-trigger the parse abort); VEOMNI_XLA_PERF_FLAGS=force
        # re-enables either way.
        return False
    current = os.environ.get("XLA_FLAGS", "")
    present = {tok.split("=")[0] for tok in current.split()}
    added = [f for f in _PERF_FLAGS if f.split("=")[0] not in present]
    if added:
        os.environ["XLA_FLAGS"] = (current + " " + " ".join(added)).strip()
    return True


def strip_tpu_flags() -> None:
    """Remove the ``--xla_tpu_*`` perf flags from XLA_FLAGS. The CPU backend
    ABORTS the process on unknown flags (parse_flags_from_env), so a run
    that applied the TPU flags and then switches to ``train.platform: cpu``
    (virtual-mesh simulation) must strip them before first backend init."""
    current = os.environ.get("XLA_FLAGS", "")
    if not current:
        return
    kept = [t for t in current.split() if not t.startswith("--xla_tpu_")]
    os.environ["XLA_FLAGS"] = " ".join(kept)
