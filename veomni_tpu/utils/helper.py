"""Training observability: EnvironMeter (MFU, tokens/sec) + misc helpers.

Reference: ``veomni/utils/helper.py:158-308`` (EnvironMeter) — per-step
achieved-vs-promised FLOPs -> MFU, tokens/sec, consumed tokens, memory stats.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import jax

from veomni_tpu.utils.count_flops import FlopsCounter
from veomni_tpu.utils.device import get_device_peak_flops
from veomni_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class EnvironMeter:
    """Accumulates per-step tokens/FLOPs and derives MFU + throughput.

    Unlike the reference (which all-reduces across ranks), a JAX single-
    controller program sees global batch stats directly; multi-process setups
    pass ``global_ntokens`` already summed (the data pipeline knows the global
    batch composition).
    """

    flops_counter: Optional[FlopsCounter] = None
    world_size: int = 1
    empty_cache_steps: int = 0
    consumed_tokens: int = 0
    _step_tokens: int = 0
    _step_token_seq: float = 0.0
    _step_extra_flops: float = 0.0
    _t_start: float = field(default_factory=time.perf_counter)

    def add(self, ntokens: int, seq_len: int, extra_flops: float = 0.0) -> None:
        """extra_flops: promised FORWARD flops outside the LM formula (ViT /
        audio towers, DiT) for this batch; backward-scaled with the rest.

        Attention FLOPs are linear in seq_len per token, so accumulating
        ``ntokens * seq_len`` makes the token-weighted mean seq-len EXACT for
        mixed-length accumulation windows (a max would over-credit MFU the
        moment dynamic batching mixes pack lengths)."""
        self._step_tokens += int(ntokens)
        self._step_token_seq += float(ntokens) * float(seq_len)
        self._step_extra_flops += float(extra_flops)

    def step(self) -> Dict[str, float]:
        now = time.perf_counter()
        dt = max(now - self._t_start, 1e-9)
        tokens = self._step_tokens
        self.consumed_tokens += tokens
        metrics: Dict[str, float] = {
            "tokens_per_sec": tokens / dt,
            "tokens_per_sec_per_chip": tokens / dt / max(1, self.world_size),
            "step_time_s": dt,
            "consumed_tokens": float(self.consumed_tokens),
        }
        if self.flops_counter is not None and (tokens or self._step_extra_flops):
            eff_seq = self._step_token_seq / tokens if tokens else 0.0
            achieved = self.flops_counter.batch_flops(tokens, eff_seq or tokens)
            achieved += 3.0 * self._step_extra_flops
            peak = get_device_peak_flops() * max(1, self.world_size)
            metrics["tflops"] = achieved / dt / 1e12
            metrics["mfu"] = 100.0 * achieved / dt / peak
        self._step_tokens = 0
        self._step_token_seq = 0.0
        self._step_extra_flops = 0.0
        self._t_start = time.perf_counter()
        return metrics

    def state_dict(self) -> Dict[str, Any]:
        # include tokens added but not yet folded by step(): with the
        # log-step rollup cadence a mid-window checkpoint must not
        # undercount trained tokens
        return {"consumed_tokens": self.consumed_tokens + self._step_tokens}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.consumed_tokens = int(state.get("consumed_tokens", 0))


def dump_thread_stacks() -> str:
    """Formatted stack of every live Python thread (the first thing anyone
    needs from a hung multi-host run: WHERE each thread is blocked)."""
    import sys
    import threading
    import traceback

    names = {t.ident: t.name for t in threading.enumerate()}
    parts = []
    for tid, frame in sys._current_frames().items():
        parts.append(f"--- thread {names.get(tid, '?')} (ident {tid}) ---")
        parts.append("".join(traceback.format_stack(frame)).rstrip())
    return "\n".join(parts)


class Watchdog:
    """Stall detector on a daemon thread (promoted from ``bench.py:_watchdog``
    so the trainer supervisor and the bench share one implementation).

    Arms at :meth:`start`; :meth:`pet` resets the deadline (call once per unit
    of expected progress — a train step, a bench phase). If ``timeout_s``
    elapses with no pet, the dog dumps every thread's stack via
    :func:`dump_thread_stacks`, writes a flight-recorder post-mortem
    (``postmortem-<rank>.json`` — the stack dump alone loses the event
    history; the path lands in :attr:`last_postmortem_path`), invokes
    ``on_stall(stack_dump)`` once per stall, and — unless ``exit_code`` is
    None — hard-exits the process
    (``os._exit``; a wedged backend can't be timeout-killed politely, see
    BENCH_NOTES.md). With ``exit_code=None`` the run is left alive: the stall
    may be a bounded hiccup (slow shared fs) the retry layer absorbs, and the
    dump is the observability artifact either way. Re-arms after firing, so a
    long stall produces periodic dumps rather than one.

    Threading contract (lock-discipline audit, docs/static-analysis.md):
    no lock-guarded state, so no ``# guarded-by:`` annotations. Arming and
    petting ride two ``threading.Event`` objects; ``stall_count`` /
    ``last_dump`` / ``last_postmortem_path`` are written only by the
    watchdog thread and read by observers AFTER a stall is signalled
    (bench reads them from ``on_stall``, which the watchdog thread itself
    invokes) — single-writer, causally-ordered reads.
    """

    # the post-mortem write gets its own deadline: when the stall IS a hung
    # filesystem, blocking on the dump would wedge the watchdog thread
    # before on_stall/exit_code ever run
    DUMP_DEADLINE_S = 15.0

    def __init__(self, timeout_s: float, *, on_stall=None, exit_code=None,
                 description: str = ""):
        import threading

        self.timeout_s = float(timeout_s)
        self.on_stall = on_stall
        self.exit_code = exit_code
        self.description = description
        self.stall_count = 0
        self.last_dump: str = ""
        self.last_postmortem_path: str = ""
        self._pet_event = threading.Event()
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Watchdog":
        import threading

        if self.timeout_s > 0 and self._thread is None:
            self._thread = threading.Thread(
                target=self._watch, name="veomni-watchdog", daemon=True
            )
            self._thread.start()
        return self

    def pet(self) -> None:
        self._pet_event.set()

    def stop(self) -> None:
        self._done.set()
        self._pet_event.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _watch(self) -> None:
        import os as _os
        import threading

        while not self._done.is_set():
            self._pet_event.clear()
            if self._pet_event.wait(self.timeout_s):
                continue  # progress (or stop) before the deadline
            if self._done.is_set():
                return
            self.stall_count += 1
            self.last_dump = dump_thread_stacks()
            logger.error(
                "watchdog: no progress in %.3gs%s; thread stacks:\n%s",
                self.timeout_s,
                f" ({self.description})" if self.description else "",
                self.last_dump,
            )
            # the stack dump says WHERE each thread is; the flight recorder
            # says WHAT the run was doing in the seconds before. Dump BEFORE
            # on_stall so the callback (bench's stall JSON) can reference
            # the artifact path — which means THIS stall must be put on the
            # ring here, not by on_stall, or the artifact it triggers is the
            # one dump with no record of it. Never fatal — dump() is
            # exception-proof — and never unbounded: if the stall IS a hung
            # shared fs, the dump's own writes into it would otherwise wedge
            # THIS thread before on_stall/exit_code run, hanging the driver
            # the watchdog exists to unhang. So the file I/O happens in a
            # side thread joined with a deadline.
            try:
                from veomni_tpu.observability.flight_recorder import (
                    dump_postmortem,
                    record,
                )

                record("watchdog.stall", cid=str(self.stall_count),
                       timeout_s=self.timeout_s,
                       where=self.description or "")
                path_box: list = []
                dumper = threading.Thread(
                    target=lambda: path_box.append(dump_postmortem(
                        f"watchdog:{self.description or 'stall'}",
                        extra={"stall_count": self.stall_count,
                               "timeout_s": self.timeout_s},
                    )),
                    name="veomni-watchdog-dump", daemon=True,
                )
                dumper.start()
                dumper.join(timeout=self.DUMP_DEADLINE_S)
                self.last_postmortem_path = (
                    (path_box[0] or "") if path_box else ""
                )
                if dumper.is_alive():
                    logger.error(
                        "watchdog: post-mortem dump still blocked after "
                        "%.3gs (hung filesystem?) — continuing without it",
                        self.DUMP_DEADLINE_S,
                    )
            except Exception as e:
                # e.g. Thread.start() under thread exhaustion — exactly a
                # pathological stall state; say the dump was attempted
                logger.error("watchdog: post-mortem dump not started: %s", e)
            if self.on_stall is not None:
                try:
                    self.on_stall(self.last_dump)
                except Exception:
                    pass
            if self.exit_code is not None:
                _os._exit(self.exit_code)


def host_floats(metrics: Dict[str, Any]) -> Dict[str, float]:
    """Keep only host-scalar metric values (drop device futures: fetching
    one would block an async loop). Shared by WandbCallback and the serving
    engine's metric surface."""
    return {k: v for k, v in metrics.items() if isinstance(v, (int, float))}


def set_seed(seed: int) -> "jax.Array":
    """Returns the root PRNG key; also seeds numpy/python for data pipeline."""
    import random

    import numpy as np

    random.seed(seed)
    np.random.seed(seed % (2**32))
    return jax.random.PRNGKey(seed)


def enable_full_determinism(seed: int) -> "jax.Array":
    """XLA:TPU is deterministic given fixed seeds and shapes; this is the thin
    shim the reference's cublas/cudnn knobs reduce to on TPU
    (reference ``utils/helper.py:425-463``)."""
    return set_seed(seed)


def pretty_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024:
            return f"{n:.2f}{unit}"
        n /= 1024
    return f"{n:.2f}PB"


def host_rss_bytes() -> float:
    """Current resident-set size of this process in bytes.

    Reads ``/proc/self/statm`` (Linux — a LIVE value that falls when memory
    is released) and falls back to ``resource.getrusage`` peak RSS
    elsewhere (kilobytes on Linux, bytes on macOS). The single home for
    this platform-sensitive read: ``live_memory_stats`` and
    ``observability/devmem.py`` both consume it."""
    try:
        import os

        with open("/proc/self/statm") as f:
            pages = float(f.read().split()[1])
        return pages * float(os.sysconf("SC_PAGE_SIZE"))
    except Exception:
        pass
    try:
        import resource
        import sys

        rss = float(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)
        return rss if sys.platform == "darwin" else rss * 1024.0
    except Exception:
        return 0.0


def live_memory_stats() -> Dict[str, float]:
    """Per-device live buffer bytes (cf. torch.cuda.memory_allocated), plus
    an always-available host RSS reading.

    XLA:CPU's ``memory_stats()`` returns nothing, which used to leave the
    ``mem.*`` gauge family entirely absent under ``JAX_PLATFORMS=cpu`` —
    tier-1 never exercised the path. ``host_rss_bytes`` (host memory, not
    HBM) keeps the family live on every backend."""
    stats = {}
    for i, d in enumerate(jax.local_devices()):
        try:
            ms = d.memory_stats()
            if ms:
                stats[f"device{i}_bytes_in_use"] = float(ms.get("bytes_in_use", 0))
                if "peak_bytes_in_use" in ms:
                    stats[f"device{i}_peak_bytes_in_use"] = float(
                        ms["peak_bytes_in_use"]
                    )
        except Exception:
            pass
    rss = host_rss_bytes()
    if rss:
        stats["host_rss_bytes"] = rss
    return stats
