"""HLO overlap-evidence census: is comm/compute overlap real, not hoped-for?

The reference hand-overlaps the Ulysses a2a with attention GEMMs
(``veomni/distributed/sequence_parallel/async_ulysses.py``); on TPU the
chunked pipeline (``parallel/async_ulysses.py``) builds the overlap into the
program *structure* and GSPMD's latency-hiding scheduler turns each
collective into an async start/done pair spanning compute. This module
makes that claim checkable (and regression-testable) from the emitted HLO,
two ways:

1. :func:`analyze_scheduled_dump` — parse an ``--xla_dump_to`` *scheduled*
   HLO dump (TPU: the latency-hiding scheduler pass) and report every async
   collective ``*-start``/``*-done`` pair with the number of real compute
   ops the scheduler placed inside the window. Nonzero gaps = the compiler
   is hiding that collective behind compute.

2. :func:`overlap_report` — backend-neutral *dependency* census on any HLO
   text (e.g. ``jit(f).lower(...).compile().as_text()`` on the CPU backend,
   where collectives lower synchronously and no start/done pairs exist): a
   collective/compute pair is *overlappable* iff neither transitively
   depends on the other inside the same computation — the exact precondition
   a latency-hiding scheduler needs to run them concurrently. The chunked
   Ulysses pipeline exists to create such pairs (chunk *i*'s a2a is
   independent of chunk *i-1*'s attention dots); the tier-1 gate in
   ``tests/test_async_ulysses.py`` fails if the chunked program ever stops
   exposing at least as many of them as the monolithic one.

``scripts/overlap_evidence.py`` is the CLI wrapper that also measures the
async-trainer-loop fetch-amortization win.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

COMPUTE_OPS = ("fusion", "dot", "convolution", "custom-call")
#: collectives the TPU latency-hiding scheduler turns into async pairs; the
#: Ulysses paths emit all-to-all, the ring-CP path collective-permute
OVERLAP_COLLECTIVES = ("all-to-all", "collective-permute")
ALL_COLLECTIVES = (
    "all-gather", "all-reduce", "reduce-scatter", "all-to-all",
    "collective-permute",
)


# --------------------------------------------------------------------------
# 1. scheduled-dump census (TPU async start/done pairs)
# --------------------------------------------------------------------------
@dataclass(frozen=True)
class AsyncPair:
    """One async collective start/done pair in a *scheduled* HLO module."""

    name: str
    window_lines: int      # schedule distance between start and done
    compute_inside: int    # real compute ops scheduled inside the window

    @property
    def overlapped(self) -> bool:
        return self.compute_inside > 0


def analyze_scheduled_dump(dump_dir: str) -> List[AsyncPair]:
    """Parse scheduled HLO files from an ``--xla_dump_to`` directory: for
    each async collective start/done pair, count compute ops scheduled
    between them. Empty off-TPU (XLA:CPU lowers collectives synchronously —
    use :func:`overlap_report` there)."""
    pairs: List[AsyncPair] = []
    for fname in sorted(os.listdir(dump_dir)):
        if "after_scheduling" not in fname and "latency" not in fname:
            continue
        if not fname.endswith(".txt"):
            continue
        with open(os.path.join(dump_dir, fname)) as f:
            lines = f.readlines()
        open_starts: Dict[str, int] = {}
        for i, line in enumerate(lines):
            m = re.search(r"%(\S*?(all-gather|all-reduce|reduce-scatter|"
                          r"all-to-all|collective-permute)\S*start\S*) =", line)
            if m:
                open_starts[m.group(1).rstrip(",")] = i
                continue
            m = re.search(r"(all-gather|all-reduce|reduce-scatter|all-to-all|"
                          r"collective-permute)\S*done", line)
            if m and open_starts:
                # attribute to the most recent unmatched start of that type
                key = next(
                    (k for k in reversed(list(open_starts))
                     if m.group(1) in k), None,
                )
                if key is None:
                    continue
                start_i = open_starts.pop(key)
                gap_ops = sum(
                    1 for ln in lines[start_i + 1: i]
                    if any(f" {op}(" in ln or f"= {op}" in ln
                           for op in COMPUTE_OPS)
                )
                pairs.append(AsyncPair(key.split(".")[0], i - start_i, gap_ops))
    return pairs


def collective_census(hlo_text: str) -> Dict[str, int]:
    """Count GSPMD-inserted collectives by op in one HLO module's text."""
    census: Dict[str, int] = {}
    for op in ALL_COLLECTIVES:
        census[op] = len(re.findall(rf"= \S* {op}\(|{op}\.", hlo_text))
    return census


# --------------------------------------------------------------------------
# 2. dependency census (backend-neutral overlappable pairs)
# --------------------------------------------------------------------------
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*.*?\b([a-z][a-z0-9\-]*)\("
)
_OPERAND_RE = re.compile(r"%([\w.\-]+)")


def hlo_computations(hlo_text: str) -> Iterator[Tuple[str, List[str]]]:
    """Yield ``(computation_name, instruction_lines)`` per HLO computation
    block (text format: an unindented header ending in ``{``, instructions
    indented, closed by ``}`` at column 0)."""
    name = None
    body: List[str] = []
    for line in hlo_text.splitlines():
        if name is None:
            if line and not line[0].isspace() and line.rstrip().endswith("{"):
                m = re.match(r"(?:ENTRY\s+)?%?([\w.\-]+)", line)
                name = m.group(1) if m else "<anon>"
                body = []
            continue
        if line.startswith("}"):
            yield name, body
            name = None
            continue
        if " = " in line:
            body.append(line)


@dataclass
class OverlapReport:
    """Dependency-census result over one HLO module."""

    collectives: int = 0        # collectives of the tracked kinds
    overlappable: int = 0       # ...with >= 1 independent compute op
    pairs: int = 0              # total independent (collective, compute) pairs
    per_computation: Dict[str, Tuple[int, int, int]] = field(default_factory=dict)

    def describe(self) -> str:
        lines = [
            f"collectives={self.collectives} overlappable={self.overlappable} "
            f"independent collective/compute pairs={self.pairs}"
        ]
        for comp, (n_c, n_o, n_p) in sorted(self.per_computation.items()):
            lines.append(f"  {comp:50s} collectives={n_c} overlappable={n_o} "
                         f"pairs={n_p}")
        return "\n".join(lines)


def _parse_computation(body: List[str]):
    """-> (ops: name->opcode, deps: name->[operand names in this comp])."""
    ops: Dict[str, str] = {}
    deps: Dict[str, List[str]] = {}
    for line in body:
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name, opcode = m.group(1), m.group(2)
        rest = line[m.end():]
        ops[name] = opcode
        # %refs in the rest of the line: operands + control-predecessors
        # (both are scheduling dependencies); refs to other computations
        # (to_apply/calls) simply won't resolve in `ops` and drop out
        deps[name] = [o for o in _OPERAND_RE.findall(rest) if o != name]
    deps = {n: [o for o in ds if o in ops] for n, ds in deps.items()}
    return ops, deps


def _reach(start: str, edges: Dict[str, List[str]]) -> set:
    seen = set()
    stack = [start]
    while stack:
        n = stack.pop()
        for o in edges.get(n, ()):
            if o not in seen:
                seen.add(o)
                stack.append(o)
    return seen


def overlap_report(
    hlo_text: str,
    collective_ops: Sequence[str] = OVERLAP_COLLECTIVES,
    compute_ops: Sequence[str] = COMPUTE_OPS,
) -> OverlapReport:
    """Count collective/compute instruction pairs with **no dependency in
    either direction** inside the same computation — the pairs a
    latency-hiding scheduler is free to overlap. Works on any HLO text
    (optimized CPU modules included), no scheduling pass required."""
    rep = OverlapReport()
    for comp_name, body in hlo_computations(hlo_text):
        ops, deps = _parse_computation(body)
        colls = [n for n, op in ops.items()
                 if any(op.startswith(c) for c in collective_ops)]
        if not colls:
            continue
        users: Dict[str, List[str]] = {}
        for n, ds in deps.items():
            for o in ds:
                users.setdefault(o, []).append(n)
        computes = [n for n, op in ops.items() if op in compute_ops]
        n_over = n_pairs = 0
        for c in colls:
            ancestors = _reach(c, deps)
            descendants = _reach(c, users)
            indep = [d for d in computes
                     if d not in ancestors and d not in descendants]
            n_pairs += len(indep)
            n_over += bool(indep)
        rep.collectives += len(colls)
        rep.overlappable += n_over
        rep.pairs += n_pairs
        rep.per_computation[comp_name] = (len(colls), n_over, n_pairs)
    return rep


def compiled_hlo_text(jitted_fn, *args, **kwargs) -> str:
    """Optimized HLO text of a jitted callable on the current backend
    (`lower().compile()`, no execution)."""
    compiled = jitted_fn.lower(*args, **kwargs).compile()
    texts = compiled.as_text()
    if isinstance(texts, (list, tuple)):
        return "\n".join(texts)
    return texts
