"""HLO overlap-evidence census: is comm/compute overlap real, not hoped-for?

The reference hand-overlaps the Ulysses a2a with attention GEMMs
(``veomni/distributed/sequence_parallel/async_ulysses.py``); on TPU the
chunked pipeline (``parallel/async_ulysses.py``) builds the overlap into the
program *structure* and GSPMD's latency-hiding scheduler turns each
collective into an async start/done pair spanning compute. This module
makes that claim checkable (and regression-testable) from the emitted HLO,
two ways:

1. :func:`analyze_scheduled_dump` — parse an ``--xla_dump_to`` *scheduled*
   HLO dump (TPU: the latency-hiding scheduler pass) and report every async
   collective ``*-start``/``*-done`` pair with the number of real compute
   ops the scheduler placed inside the window. Nonzero gaps = the compiler
   is hiding that collective behind compute.

2. :func:`overlap_report` — backend-neutral *dependency* census on any HLO
   text (e.g. ``jit(f).lower(...).compile().as_text()`` on the CPU backend,
   where collectives lower synchronously and no start/done pairs exist): a
   collective/compute pair is *overlappable* iff neither transitively
   depends on the other inside the same computation — the exact precondition
   a latency-hiding scheduler needs to run them concurrently. The chunked
   Ulysses pipeline exists to create such pairs (chunk *i*'s a2a is
   independent of chunk *i-1*'s attention dots); the tier-1 gate in
   ``tests/test_async_ulysses.py`` fails if the chunked program ever stops
   exposing at least as many of them as the monolithic one.

``scripts/overlap_evidence.py`` is the CLI wrapper that also measures the
async-trainer-loop fetch-amortization win.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

COMPUTE_OPS = ("fusion", "dot", "convolution", "custom-call")
#: collectives the TPU latency-hiding scheduler turns into async pairs; the
#: Ulysses paths emit all-to-all, the ring-CP path collective-permute
OVERLAP_COLLECTIVES = ("all-to-all", "collective-permute")
ALL_COLLECTIVES = (
    "all-gather", "all-reduce", "reduce-scatter", "all-to-all",
    "collective-permute",
)


# --------------------------------------------------------------------------
# 1. scheduled-dump census (TPU async start/done pairs)
# --------------------------------------------------------------------------
@dataclass(frozen=True)
class AsyncPair:
    """One async collective start/done pair in a *scheduled* HLO module."""

    name: str
    window_lines: int      # schedule distance between start and done
    compute_inside: int    # real compute ops scheduled inside the window

    @property
    def overlapped(self) -> bool:
        return self.compute_inside > 0


def analyze_scheduled_dump(dump_dir: str) -> List[AsyncPair]:
    """Parse scheduled HLO files from an ``--xla_dump_to`` directory: for
    each async collective start/done pair, count compute ops scheduled
    between them. Empty off-TPU (XLA:CPU lowers collectives synchronously —
    use :func:`overlap_report` there)."""
    pairs: List[AsyncPair] = []
    for fname in sorted(os.listdir(dump_dir)):
        if "after_scheduling" not in fname and "latency" not in fname:
            continue
        if not fname.endswith(".txt"):
            continue
        with open(os.path.join(dump_dir, fname)) as f:
            lines = f.readlines()
        open_starts: Dict[str, int] = {}
        for i, line in enumerate(lines):
            m = re.search(r"%(\S*?(all-gather|all-reduce|reduce-scatter|"
                          r"all-to-all|collective-permute)\S*start\S*) =", line)
            if m:
                open_starts[m.group(1).rstrip(",")] = i
                continue
            m = re.search(r"(all-gather|all-reduce|reduce-scatter|all-to-all|"
                          r"collective-permute)\S*done", line)
            if m and open_starts:
                # attribute to the most recent unmatched start of that type
                key = next(
                    (k for k in reversed(list(open_starts))
                     if m.group(1) in k), None,
                )
                if key is None:
                    continue
                start_i = open_starts.pop(key)
                gap_ops = sum(
                    1 for ln in lines[start_i + 1: i]
                    if any(f" {op}(" in ln or f"= {op}" in ln
                           for op in COMPUTE_OPS)
                )
                pairs.append(AsyncPair(key.split(".")[0], i - start_i, gap_ops))
    return pairs


def collective_census(hlo_text: str) -> Dict[str, int]:
    """Executed collectives by op per step in HLO text. Delegates to
    :func:`collective_bytes_census` (trip-count-weighted, `-done`-deduped)
    so the two censuses can never disagree on what a collective is."""
    return {
        op: int(rec["count"])
        for op, rec in collective_bytes_census(hlo_text).items()
    }


# HLO element-type -> bytes per element (the types XLA actually emits in
# optimized modules; tokens and opaque types carry no payload and drop out)
_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "f8e4m3": 1, "f8e4m3fn": 1, "f8e4m3b11fnuz": 1, "f8e5m2": 1,
    "f8e4m3fnuz": 1, "f8e5m2fnuz": 1, "f8e8m0fnu": 1, "f8e3m4": 1,
    "s32": 4, "u32": 4, "f32": 4, "c64": 8,
    "s64": 8, "u64": 8, "f64": 8, "c128": 16,
}

_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")

# one collective instruction: `<shape(s)> <op>(` — optimized CPU modules
# emit the synchronous form, scheduled TPU modules async `-start`/`-done`
# pairs. Async collectives are counted at the `-done` half and the `-start`
# is skipped: a start's result tuple carries input aliases and u32 context
# words whose split differs per kind (all-gather's output is its LARGEST
# leaf, reduce-scatter's its smallest), while the done's result is exactly
# the output payload for EVERY kind — including XLA's combiner-fused
# variadic all-reduce, whose done is the plain ``(out...)`` tuple. The
# shape group admits one level of tuple nesting for those variadic forms.
_COLLECTIVE_INSTR_RE = re.compile(
    r"=\s*(\((?:[^()]|\([^()]*\))*\)|\S+)\s+(" + "|".join(ALL_COLLECTIVES)
    + r")(-done)?\("
)


def _shape_leaf_bytes(shape_text: str) -> List[float]:
    """Per-leaf payload bytes of an HLO shape string:
    ``f32[4,128]{1,0}`` -> [2048], ``(bf16[64]{0}, u32[2])`` -> [128, 8]."""
    leaves: List[float] = []
    for dtype, dims in _SHAPE_RE.findall(shape_text):
        size = _DTYPE_BYTES.get(dtype)
        if size is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        leaves.append(float(size * n))
    return leaves


def _flat_bytes_census(text: str) -> Dict[str, Dict[str, float]]:
    """Unweighted per-kind census over a block of instruction text."""
    out: Dict[str, Dict[str, float]] = {
        op: {"count": 0, "bytes": 0.0} for op in ALL_COLLECTIVES
    }
    for m in _COLLECTIVE_INSTR_RE.finditer(text):
        shape_text, op = m.group(1), m.group(2)
        # `-start` never matches (after the op name comes `-start(`, which
        # the `(-done)?\(` tail rejects), so each async pair is counted
        # exactly once, at its `-done` — whose result is the pure output
        # payload. Sync forms match with group(3) empty.
        out[op]["count"] += 1
        out[op]["bytes"] += sum(_shape_leaf_bytes(shape_text))
    return out


# called-computation references on an instruction line (attribute forms
# only — matching bare %refs would confuse instruction operands with
# computation names) + the while loop's statically-known trip count, which
# XLA stamps into the instruction's backend_config for scan-lowered loops
_COMP_REF_RE = re.compile(r"(to_apply|calls|condition|body)=%([\w.\-]+)")
_COMP_LIST_RE = re.compile(
    r"(?:branch_computations|called_computations)=\{([^}]*)\}"
)
# 2-branch PRED conditionals print as true_computation=/false_computation=
# (the index form uses branch_computations); both are one-of-branches
_COMP_TF_RE = re.compile(r"(?:true|false)_computation=%([\w.\-]+)")
_WHILE_TRIP_RE = re.compile(r'"known_trip_count"\s*:\s*\{\s*"n"\s*:\s*"(\d+)"')


def _split_modules(hlo_text: str) -> List[str]:
    """Split concatenated HLO text into per-module chunks on ``HloModule``
    headers. ``compiled.as_text()`` returns a LIST of module texts on some
    jax versions and the joiners concatenate them — each module has its own
    ENTRY and identically-named computations, so any name-keyed parse must
    happen per module or later modules silently shadow earlier ones."""
    chunks: List[str] = []
    cur: List[str] = []
    for line in hlo_text.splitlines():
        if line.startswith("HloModule") and cur:
            chunks.append("\n".join(cur))
            cur = []
        cur.append(line)
    if cur:
        chunks.append("\n".join(cur))
    return chunks


def _parse_module(hlo_text: str):
    """-> (entry_name | None, {computation_name: [instruction lines]}) for
    ONE module's text (names are unique within a module; use
    :func:`_split_modules` first on concatenated text)."""
    comps: Dict[str, List[str]] = {}
    entry = None
    name = None
    body: List[str] = []
    for line in hlo_text.splitlines():
        if name is None:
            if line and not line[0].isspace() and line.rstrip().endswith("{"):
                m = re.match(r"(?:ENTRY\s+)?%?([\w.\-]+)", line)
                name = m.group(1) if m else "<anon>"
                if line.lstrip().startswith("ENTRY"):
                    entry = name
                body = []
            continue
        if line.startswith("}"):
            comps[name] = body
            name = None
            continue
        if " = " in line:
            body.append(line)
    return entry, comps


def collective_bytes_census(hlo_text: str) -> Dict[str, Dict[str, float]]:
    """Per-collective-kind ``{"count": n, "bytes": b}`` over one HLO
    module's text — **per executed step**, from each collective
    instruction's RESULT shape, weighted by loop trip counts.

    This is the byte-level refinement of :func:`collective_census` the live
    comm observatory (``observability/comm.py``) publishes as
    ``comm.{site}.{bucket}.*`` gauges: on an SPMD-partitioned module the
    shapes are per-device, so the bytes are per-device too — the same units
    as the cost census's FLOPs/bytes-accessed. The number is the payload a
    collective's result materializes, not the wire traffic of a particular
    algorithm (a ring all-reduce moves ~2x(n-1)/n of it); it feeds an
    order-of-magnitude comm-time *estimate* against
    ``utils/device.py::get_device_peak_interconnect_bandwidth``, not an SLA.

    Trip-count weighting: a ``lax.scan`` lowers to a while loop whose body
    is ONE computation in the module text, executed ``n`` times — and every
    model here scans its stacked layers, so an unweighted census would
    under-count in-layer collectives (Ulysses all-to-alls, TP all-reduces)
    L-fold, the same blind spot the cost census corrects for FLOPs. The
    census walks the computation call graph (``to_apply``/``calls``/
    ``condition``/``body``/branch lists) and multiplies a while body's
    contribution by the ``known_trip_count`` XLA stamps into the
    instruction's ``backend_config``; loops without a static trip count
    contribute once (uncorrected, matching the cost census's while_loop
    policy). ``count`` is therefore executed collectives per step, not
    static instructions.

    Shape accounting: synchronous collectives count their result payload
    directly (a tuple result is a genuine variadic payload and sums its
    leaves); async pairs count ONCE, at the ``-done`` half, whose result
    is exactly the output payload for every kind — a ``-start``'s result
    tuple mixes input aliases and u32 context words whose layout differs
    per kind (all-gather's output is its largest leaf, reduce-scatter's
    its smallest), so parsing starts would break sync/async consistency.
    """
    chunks = _split_modules(hlo_text)
    if len(chunks) > 1:  # concatenated as_text() list: sum per module
        total = _flat_bytes_census("")
        for chunk in chunks:
            for op, rec in collective_bytes_census(chunk).items():
                total[op]["count"] += rec["count"]
                total[op]["bytes"] += rec["bytes"]
        return total

    entry, comps = _parse_module(hlo_text)
    if entry is None or entry not in comps:
        return _flat_bytes_census(hlo_text)  # fragment: no module structure

    memo: Dict[str, Dict[str, Dict[str, float]]] = {}

    def _add(acc, sub, mult):
        for op, rec in sub.items():
            acc[op]["count"] += rec["count"] * mult
            acc[op]["bytes"] += rec["bytes"] * mult

    def _total(comp: str, stack: frozenset) -> Dict[str, Dict[str, float]]:
        if comp in memo:
            return memo[comp]
        if comp in stack:  # cycles don't exist in valid HLO; fail safe
            return _flat_bytes_census("")
        body = comps.get(comp, [])
        acc = _flat_bytes_census("\n".join(body))
        for line in body:
            trip = 1
            if " while(" in line:
                tm = _WHILE_TRIP_RE.search(line)
                if tm:
                    trip = int(tm.group(1))
            for attr, ref in _COMP_REF_RE.findall(line):
                if ref not in comps:
                    continue
                # the trip count applies to the loop BODY; the condition
                # runs n+1 times but is collective-free in practice — one
                # visit keeps it from inflating a census it can't feed
                _add(acc, _total(ref, stack | {comp}),
                     trip if attr == "body" else 1)
            branch_sets = [
                [r.strip().lstrip("%") for r in lst.split(",")]
                for lst in _COMP_LIST_RE.findall(line)
            ]
            tf = _COMP_TF_RE.findall(line)
            if tf:  # PRED-form conditional: one branch pair
                branch_sets.append(tf)
            for branches in branch_sets:
                branches = [b for b in branches if b in comps]
                if not branches:
                    continue
                # a conditional executes exactly ONE branch per visit:
                # summing all branches would overstate comm up to k-fold,
                # so take the heaviest branch as the per-step upper bound
                heaviest = max(
                    (_total(b, stack | {comp}) for b in branches),
                    key=lambda c: sum(v["bytes"] for v in c.values()),
                )
                _add(acc, heaviest, 1)
        memo[comp] = acc
        return acc

    return _total(entry, frozenset())


# --------------------------------------------------------------------------
# 2. dependency census (backend-neutral overlappable pairs)
# --------------------------------------------------------------------------
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*.*?\b([a-z][a-z0-9\-]*)\("
)
_OPERAND_RE = re.compile(r"%([\w.\-]+)")


def hlo_computations(hlo_text: str) -> Iterator[Tuple[str, List[str]]]:
    """Yield ``(computation_name, instruction_lines)`` per HLO computation
    block (text format: an unindented header ending in ``{``, instructions
    indented, closed by ``}`` at column 0). One parser serves both censuses
    (:func:`_parse_module` additionally reports the ENTRY computation);
    concatenated multi-module text yields every module's computations."""
    for chunk in _split_modules(hlo_text):
        _entry, comps = _parse_module(chunk)
        yield from comps.items()


@dataclass
class OverlapReport:
    """Dependency-census result over one HLO module."""

    collectives: int = 0        # collectives of the tracked kinds
    overlappable: int = 0       # ...with >= 1 independent compute op
    pairs: int = 0              # total independent (collective, compute) pairs
    per_computation: Dict[str, Tuple[int, int, int]] = field(default_factory=dict)

    def describe(self) -> str:
        lines = [
            f"collectives={self.collectives} overlappable={self.overlappable} "
            f"independent collective/compute pairs={self.pairs}"
        ]
        for comp, (n_c, n_o, n_p) in sorted(self.per_computation.items()):
            lines.append(f"  {comp:50s} collectives={n_c} overlappable={n_o} "
                         f"pairs={n_p}")
        return "\n".join(lines)


def _parse_computation(body: List[str]):
    """-> (ops: name->opcode, deps: name->[operand names in this comp])."""
    ops: Dict[str, str] = {}
    deps: Dict[str, List[str]] = {}
    for line in body:
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name, opcode = m.group(1), m.group(2)
        rest = line[m.end():]
        ops[name] = opcode
        # %refs in the rest of the line: operands + control-predecessors
        # (both are scheduling dependencies); refs to other computations
        # (to_apply/calls) simply won't resolve in `ops` and drop out
        deps[name] = [o for o in _OPERAND_RE.findall(rest) if o != name]
    deps = {n: [o for o in ds if o in ops] for n, ds in deps.items()}
    return ops, deps


def _reach(start: str, edges: Dict[str, List[str]]) -> set:
    seen = set()
    stack = [start]
    while stack:
        n = stack.pop()
        for o in edges.get(n, ()):
            if o not in seen:
                seen.add(o)
                stack.append(o)
    return seen


def overlap_report(
    hlo_text: str,
    collective_ops: Sequence[str] = OVERLAP_COLLECTIVES,
    compute_ops: Sequence[str] = COMPUTE_OPS,
) -> OverlapReport:
    """Count collective/compute instruction pairs with **no dependency in
    either direction** inside the same computation — the pairs a
    latency-hiding scheduler is free to overlap. Works on any HLO text
    (optimized CPU modules included), no scheduling pass required."""
    rep = OverlapReport()
    for comp_name, body in hlo_computations(hlo_text):
        ops, deps = _parse_computation(body)
        # a `*-done` op is the tail of an already-counted async collective
        # (scheduled TPU modules), not a second collective
        colls = [n for n, op in ops.items()
                 if any(op.startswith(c) for c in collective_ops)
                 and not op.endswith("-done")]
        if not colls:
            continue
        users: Dict[str, List[str]] = {}
        for n, ds in deps.items():
            for o in ds:
                users.setdefault(o, []).append(n)
        computes = [n for n, op in ops.items() if op in compute_ops]
        n_over = n_pairs = 0
        for c in colls:
            ancestors = _reach(c, deps)
            descendants = _reach(c, users)
            indep = [d for d in computes
                     if d not in ancestors and d not in descendants]
            n_pairs += len(indep)
            n_over += bool(indep)
        rep.collectives += len(colls)
        rep.overlappable += n_over
        rep.pairs += n_pairs
        rep.per_computation[comp_name] = (len(colls), n_over, n_pairs)
    return rep


def compiled_hlo_text(jitted_fn, *args, **kwargs) -> str:
    """Optimized HLO text of a jitted callable on the current backend
    (`lower().compile()`, no execution)."""
    compiled = jitted_fn.lower(*args, **kwargs).compile()
    texts = compiled.as_text()
    if isinstance(texts, (list, tuple)):
        return "\n".join(texts)
    return texts
