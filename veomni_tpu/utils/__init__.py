from veomni_tpu.utils.logging import get_logger
from veomni_tpu.utils.registry import Registry

__all__ = ["get_logger", "Registry"]
