"""Generic string-keyed registry (reference: ``veomni/utils/registry.py``).

Used for datasets, dataloaders, transforms, model families, kernels, etc.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, Optional


class Registry:
    def __init__(self, name: str):
        self.name = name
        self._store: Dict[str, Any] = {}

    def register(self, key: str, obj: Optional[Any] = None, *, override: bool = False):
        """Register ``obj`` under ``key``; usable as a decorator when obj is None."""

        def _do(o):
            if key in self._store and not override:
                raise KeyError(f"{self.name}: duplicate key {key!r}")
            self._store[key] = o
            return o

        if obj is None:
            return _do
        return _do(obj)

    def get(self, key: str) -> Any:
        if key not in self._store:
            raise KeyError(
                f"{self.name}: unknown key {key!r}; available: {sorted(self._store)}"
            )
        return self._store[key]

    def __contains__(self, key: str) -> bool:
        return key in self._store

    def __iter__(self) -> Iterator[str]:
        return iter(self._store)

    def keys(self):
        return self._store.keys()

    def items(self):
        return self._store.items()
