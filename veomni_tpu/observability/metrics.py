"""Process-wide, thread-safe metrics registry.

Three instrument kinds, all safe to touch from any thread (prefetch worker,
checkpoint commit thread, exporter HTTP thread, train loop):

* :class:`Counter`   — monotonically increasing float.
* :class:`Gauge`     — last-write-wins float.
* :class:`Histogram` — bounded reservoir (exact count/sum/min/max; p50/p95
  from a deterministic reservoir sample so memory stays O(max_samples) no
  matter how many steps a run observes).

The registry is *rank-aware* without ever forcing a backend init: rank is
resolved through the same lazy path the logger uses (a metrics call must
never be the thing that claims a TPU chip — see ``utils/logging.py``).

Two egress paths, both pull-free for the hot loop:

* ``attach_jsonl(path)`` — a rank-local JSONL sink; every ``export()`` call
  (the trainer's sync cadence) appends one line.
* ``add_export_hook(fn)`` — pluggable consumers (``WandbCallback``,
  ``LoggingCallback``) receive the merged ``(step, payload)`` instead of
  reaching into ``state.metrics`` directly.

The Prometheus text rendering lives in ``observability/exporter.py``.
"""

from __future__ import annotations

import json
import random
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional

from veomni_tpu.utils.logging import _process_index, get_logger

logger = get_logger(__name__)

#: latency-style bucket bounds (seconds) for native-histogram rendering
LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

#: families the exporter renders as NATIVE Prometheus histograms
#: (`<name>_hist_bucket{le=...}`). The registry auto-attaches these bounds
#: at creation so the bucket counts are EXACT counters maintained at
#: observe() time — a reservoir-scaled estimate would not be monotone
#: non-decreasing across scrapes, and PromQL `rate()` reads any decrease
#: as a counter reset (spurious p99 spikes on exactly the SLO queries the
#: native render exists to serve).
SLO_BUCKET_BOUNDS: Dict[str, tuple] = {
    "serve.ttft_s": LATENCY_BUCKETS,
    "serve.tpot_s": LATENCY_BUCKETS,
    # queue wait is the third serving-SLO family: under the PR 15 QoS
    # layer it is the signal shedding/deadline decisions act on, so p99
    # queries over it must work in PromQL like the other two
    "serve.queue_wait_s": LATENCY_BUCKETS,
}


class Counter:
    """Monotonic counter. ``inc`` of a negative amount is rejected."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str, lock: threading.RLock):
        self.name = name
        self._value = 0.0  # guarded-by: _lock
        self._lock = lock

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative increment {amount}")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {"value": self._value}


class Gauge:
    """Last-write-wins value (queue depth, utilization, live memory)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str, lock: threading.RLock):
        self.name = name
        self._value = 0.0  # guarded-by: _lock
        self._lock = lock

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {"value": self._value}


class Histogram:
    """Bounded-reservoir duration/size distribution.

    count/sum/min/max are exact over every observation; percentiles come
    from an Algorithm-R reservoir (deterministically seeded per name, so a
    fixed observation sequence yields fixed percentiles — tests and bit-
    exact replay drills stay reproducible)."""

    __slots__ = ("name", "_lock", "_samples", "_max_samples", "_count",
                 "_sum", "_min", "_max", "_rng", "_bounds", "_bins")

    def __init__(self, name: str, lock: threading.RLock,
                 max_samples: int = 512,
                 bucket_bounds: Optional[tuple] = None):
        if max_samples < 1:
            raise ValueError("max_samples must be >= 1")
        self.name = name
        self._lock = lock
        self._samples: List[float] = []  # guarded-by: _lock
        self._max_samples = max_samples
        self._count = 0  # guarded-by: _lock
        self._sum = 0.0  # guarded-by: _lock
        self._min = float("inf")  # guarded-by: _lock
        self._max = float("-inf")  # guarded-by: _lock
        # crc32, not hash(): str hash is salted per process, which would
        # break the cross-restart reproducibility promised above
        self._rng = random.Random(0xC0FFEE ^ zlib.crc32(name.encode()))
        # optional EXACT bucket accounting (SLO_BUCKET_BOUNDS families):
        # one bisect + int bump per observe; bins[i] counts values in
        # (bounds[i-1], bounds[i]], bins[-1] the overflow past every bound
        self._bounds = (
            tuple(sorted(float(b) for b in bucket_bounds))
            if bucket_bounds else None
        )
        self._bins: List[int] = (  # guarded-by: _lock
            [0] * (len(self._bounds) + 1) if self._bounds else []
        )

    def observe(self, value: float) -> None:
        import bisect

        value = float(value)
        with self._lock:
            self._count += 1
            self._sum += value
            if value < self._min:
                self._min = value
            if value > self._max:
                self._max = value
            if self._bounds is not None:
                self._bins[bisect.bisect_left(self._bounds, value)] += 1
            if len(self._samples) < self._max_samples:
                self._samples.append(value)
            else:
                j = self._rng.randrange(self._count)
                if j < self._max_samples:
                    self._samples[j] = value

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def percentile(self, q: float) -> float:
        """Nearest-rank percentile over the reservoir, q in [0, 100]."""
        with self._lock:
            if not self._samples:
                return 0.0
            ordered = sorted(self._samples)
            idx = min(len(ordered) - 1, max(0, round(q / 100.0 * (len(ordered) - 1))))
            return ordered[idx]

    def cumulative_buckets(self, bounds) -> List[tuple]:
        """Prometheus-native cumulative bucket counts ``[(le, count), ...,
        ("+Inf", total)]`` over ascending ``bounds``.

        When ``bounds`` are the histogram's attached bucket bounds (every
        ``SLO_BUCKET_BOUNDS`` family), counts come from EXACT per-bin
        counters maintained at observe() time: monotone non-decreasing
        across scrapes at any observation count, as PromQL's ``rate()``
        over ``_bucket`` series requires. For ad-hoc bounds the reservoir
        fraction at or under each bound is scaled to the exact total — an
        estimate (same approximation as the p50/p95 summary), monotone
        within one call but NOT across scrapes once the reservoir churns;
        don't feed it to rate()."""
        import bisect

        want = tuple(sorted(float(b) for b in bounds))
        with self._lock:
            if self._bounds is not None and want == self._bounds:
                running, out = 0, []
                for le, n in zip(self._bounds, self._bins):
                    running += n
                    out.append((le, running))
                out.append(("+Inf", int(self._count)))
                return out
            ordered = sorted(self._samples)
            total = self._count
        out = []
        n_res = len(ordered)
        for le in want:
            if n_res:
                frac = bisect.bisect_right(ordered, float(le)) / n_res
            else:
                frac = 0.0
            out.append((float(le), int(round(frac * total))))
        # cumulative counts must be monotone even under scaling round-off
        for i in range(1, len(out)):
            if out[i][1] < out[i - 1][1]:
                out[i] = (out[i][0], out[i - 1][1])
        out.append(("+Inf", int(total)))
        return out

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            if not self._count:
                return {"count": 0.0, "sum": 0.0}
            return {
                "count": float(self._count),
                "sum": self._sum,
                "min": self._min,
                "max": self._max,
                "mean": self._sum / self._count,
                "p50": self.percentile(50),
                "p95": self.percentile(95),
            }


class MetricsRegistry:
    """Name -> instrument map with JSONL + hook egress.

    Instrument creation is get-or-create (two subsystems asking for the same
    counter share it); asking for an existing name as a different kind is a
    programming error and raises."""

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: Dict[str, Any] = {}  # guarded-by: _lock
        self._hooks: List[Callable[[int, Dict[str, float]], None]] = []  # guarded-by: _lock
        self._jsonl_path: Optional[str] = None  # guarded-by: _lock
        self._last_export: Optional[tuple] = None  # guarded-by: _lock

    # ------------------------------------------------------------ instruments
    def _get_or_create(self, name: str, kind, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = kind(name, self._lock, **kwargs)
                self._metrics[name] = m
            elif not isinstance(m, kind):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {kind.__name__}"
                )
            return m

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, Gauge)

    def histogram(self, name: str, max_samples: int = 512) -> Histogram:
        # SLO families get exact native-bucket counters attached at birth
        # (see SLO_BUCKET_BOUNDS); everyone else stays reservoir-only
        return self._get_or_create(
            name, Histogram, max_samples=max_samples,
            bucket_bounds=SLO_BUCKET_BOUNDS.get(name),
        )

    def get(self, name: str):
        with self._lock:
            return self._metrics.get(name)

    def histogram_sum(self, name: str) -> float:
        """Cumulative sum of a histogram, 0.0 if it doesn't exist yet (the
        goodput tracker deltas span histograms that may not have fired)."""
        with self._lock:
            m = self._metrics.get(name)
        return m.sum if isinstance(m, Histogram) else 0.0

    def items_snapshot(self) -> List[tuple]:
        """Stable-ordered (name, instrument) pairs for renderers."""
        with self._lock:
            return sorted(self._metrics.items())

    # ---------------------------------------------------------------- egress
    def export_scalars(self) -> Dict[str, float]:
        """Flatten every instrument to plain floats (histograms expand to
        ``name.p50`` / ``.p95`` / ``.max`` / ``.mean`` / ``.count``)."""
        out: Dict[str, float] = {}
        for name, m in self.items_snapshot():
            if isinstance(m, Histogram):
                snap = m.snapshot()
                for k in ("p50", "p95", "max", "mean", "count"):
                    if k in snap:
                        out[f"{name}.{k}"] = snap[k]
            else:
                out[name] = m.value
        return out

    def set_gauges(self, prefix: str, values: Dict[str, Any]) -> None:
        """Publish a dict of host floats as ``{prefix}.{key}`` gauges
        (non-numeric values are skipped — device futures must never be
        fetched here)."""
        for k, v in values.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                self.gauge(f"{prefix}.{k}").set(v)

    def add_export_hook(self, fn: Callable[[int, Dict[str, float]], None]) -> None:
        with self._lock:
            if fn not in self._hooks:
                self._hooks.append(fn)

    def remove_export_hook(self, fn) -> None:
        with self._lock:
            if fn in self._hooks:
                self._hooks.remove(fn)

    def attach_jsonl(self, path: str) -> None:
        """Rank-local JSONL sink: every ``export()`` appends one line to
        ``path``. Re-attaching switches files (a resumed run appends to the
        new run's sink)."""
        with self._lock:
            self._jsonl_path = path

    def export(self, step: int, payload: Optional[Dict[str, Any]] = None
               ) -> Dict[str, float]:
        """Merge registry scalars with ``payload`` (step metrics already on
        host), write the JSONL line, fire export hooks, return the merged
        dict. Hook/sink failures are logged, never raised — observability
        must not kill a training step."""
        merged = self.export_scalars()
        if payload:
            merged.update({
                k: v for k, v in payload.items()
                if isinstance(v, (int, float)) and not isinstance(v, bool)
            })
        with self._lock:
            self._last_export = (step, merged)
            path, hooks = self._jsonl_path, list(self._hooks)
        if path:
            try:
                with open(path, "a") as f:
                    f.write(json.dumps({
                        "ts": time.time(), "step": step,
                        "rank": _process_index(), **merged,
                    }) + "\n")
            except OSError as e:
                logger.warning("metrics JSONL write failed: %s", e)
        for fn in hooks:
            try:
                fn(step, merged)
            except Exception as e:
                logger.warning("metrics export hook %r failed: %s", fn, e)
        return merged

    def last_export(self, step: Optional[int] = None
                    ) -> Optional[Dict[str, float]]:
        """The most recent ``export()`` payload; with ``step`` given, only
        if it matches (consumers use this to detect a fresh publish)."""
        with self._lock:
            last = self._last_export
        if last is None:
            return None
        if step is not None and last[0] != step:
            return None
        return last[1]

    def rank(self) -> int:
        """Lazy rank (never the thing that initializes the backend)."""
        return _process_index()

    def reset(self) -> None:
        """Drop every instrument + sink (tests)."""
        with self._lock:
            self._metrics.clear()
            self._hooks.clear()
            self._jsonl_path = None
            self._last_export = None


class _FencedInstrument:
    """Write-dropping proxy over one instrument, owned by a labelled view.

    Writes (``inc``/``set``/``observe``) forward to the real instrument
    until the owning :class:`LabelledRegistry` is ``revoke()``-d, then
    become no-ops — the fence an abandoned zombie pump thread hits when it
    finally returns from a wedged step and tries to bump its replica's
    labelled counters. Everything else (``value``, ``snapshot``, native
    bucket introspection, ...) delegates to the real instrument, so read
    paths and the exporter see the one true series.
    """

    __slots__ = ("_inst", "_owner")

    def __init__(self, inst, owner: "LabelledRegistry"):
        self._inst = inst
        self._owner = owner

    def inc(self, amount: float = 1.0) -> None:
        if not self._owner._revoked:
            self._inst.inc(amount)

    def set(self, value: float) -> None:
        if not self._owner._revoked:
            self._inst.set(value)

    def observe(self, value: float) -> None:
        if not self._owner._revoked:
            self._inst.observe(value)

    def __getattr__(self, attr):
        return getattr(self._inst, attr)


class LabelledRegistry:
    """Per-instance relabeling view over a shared :class:`MetricsRegistry`.

    N in-process serving engines used to clobber each other's process-wide
    ``serve.*`` instruments — every replica's pump wrote the SAME
    ``serve.queue_depth`` gauge, so ``/metrics`` showed whichever replica
    scribbled last. Each engine now emits through a view carrying an
    instance label; the label is inserted after the family prefix at
    creation time (``serve.queue_depth`` -> ``serve.r0.queue_depth``) so
    per-replica series coexist in the one registry the exporter renders.

    Two deliberate properties:

    * **call sites keep literal names** — graftlint's metric scanner and
      the doc-drift gate key off the literal strings at ``.counter(...)``/
      ``.gauge(...)``/``.histogram(...)`` call sites, and those strings are
      the BASE family names; the view relabels underneath, so the scanner
      sanity pins and the docs table keep meaning what they say.
    * **the empty label is the identity** — a single unlabelled engine
      produces byte-identical metric names to every release before this
      one, so existing scrape configs and dashboards keep working.

    Dot-free names (``recompiles``) stay shared across instances: they are
    process-wide by design, not per-replica families.

    **Revocation (the zombie-write fence).** The scale-out router abandons
    a pump thread that blows its ``replica_stall_s`` deadline — but that
    thread may still be inside XLA and will eventually return and keep
    writing this view's labelled instruments. ``revoke()`` flips the view
    into a write-dropping state: every instrument a LABELLED view hands
    out is a :class:`_FencedInstrument` proxy whose ``inc``/``set``/
    ``observe`` no-op once the owning view is revoked, so a late zombie
    write can never double-count the respawned replica's window (the
    successor engine gets a FRESH view for the same label). Reads
    delegate to the real instrument, and the unlabelled identity view
    hands out bare instruments — the single-engine path is untouched.
    """

    def __init__(self, base: MetricsRegistry, label: str = ""):
        # never stack views — relabeling a labelled view re-targets its base
        while isinstance(base, LabelledRegistry):
            base = base.base
        self.base = base
        self.label = str(label)
        self._revoked = False
        self._proxies: Dict[str, "_FencedInstrument"] = {}

    def revoke(self) -> None:
        """Drop every FUTURE write through this view (reads keep working).
        Idempotent; used by the router's generation fence when a wedged
        replica's pump thread is abandoned."""
        self._revoked = True

    @property
    def revoked(self) -> bool:
        return self._revoked

    def _fence(self, inst):
        """Wrap ``inst`` in this view's write fence (cached per concrete
        name so callers that cache the instrument and callers that re-look
        it up behave identically)."""
        if not self.label:
            return inst  # identity view: no fleet above it, no fence
        proxy = self._proxies.get(inst.name)
        if proxy is None or proxy._inst is not inst:
            proxy = _FencedInstrument(inst, self)
            self._proxies[inst.name] = proxy
        return proxy

    def scoped(self, name: str) -> str:
        """The concrete instrument name this view creates for ``name``."""
        if not self.label or "." not in name:
            return name
        head, rest = name.split(".", 1)
        return f"{head}.{self.label}.{rest}"

    # Same instrument surface as MetricsRegistry — callers (engine, tracer,
    # recompile detector) cannot tell the difference.
    def counter(self, name: str) -> Counter:
        return self._fence(self.base.counter(self.scoped(name)))

    def gauge(self, name: str) -> Gauge:
        return self._fence(self.base.gauge(self.scoped(name)))

    def histogram(self, name: str, max_samples: int = 512) -> Histogram:
        # SLO bucket bounds are keyed by BASE family name: a labelled
        # serve.r0.ttft_s must carry the same exact native buckets as
        # serve.ttft_s or per-replica PromQL p99s silently degrade to
        # reservoir estimates
        return self._fence(self.base._get_or_create(
            self.scoped(name), Histogram, max_samples=max_samples,
            bucket_bounds=SLO_BUCKET_BOUNDS.get(name),
        ))

    def get(self, name: str):
        return self.base.get(self.scoped(name))

    def histogram_sum(self, name: str) -> float:
        return self.base.histogram_sum(self.scoped(name))

    def set_gauges(self, prefix: str, values: Dict[str, Any]) -> None:
        for k, v in values.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                self.gauge(f"{prefix}.{k}").set(v)

    def items_snapshot(self) -> List[tuple]:
        return self.base.items_snapshot()

    def rank(self) -> int:
        return self.base.rank()


_GLOBAL: Optional[MetricsRegistry] = None
_GLOBAL_LOCK = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-wide registry every subsystem emits into."""
    global _GLOBAL
    if _GLOBAL is None:
        with _GLOBAL_LOCK:
            if _GLOBAL is None:
                _GLOBAL = MetricsRegistry()
    return _GLOBAL


def set_registry(registry: Optional[MetricsRegistry]) -> Optional[MetricsRegistry]:
    """Swap the global registry (tests); returns the previous one."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        prev, _GLOBAL = _GLOBAL, registry
    return prev
