"""Unified observability: one layer every hot subsystem emits into.

The paper's framework lives or dies on utilization, and the first question
about any slow step is *where the time went* — data, dispatch, checkpoint,
host callbacks, or a recompile. This package answers it without attaching a
full profiler:

* ``metrics``  — process-wide, thread-safe :class:`MetricsRegistry`
                 (counters, gauges, bounded-reservoir histograms with
                 p50/p95/max), rank-aware, with a rank-local JSONL sink and
                 pluggable export hooks.
* ``spans``    — near-zero-overhead host-side span tracing
                 (``with span("data.wait"): ...``) feeding duration
                 histograms, mirrored into ``jax.profiler.TraceAnnotation``
                 when a device trace is active, and dumpable as chrome-trace
                 JSON (``scripts/merge_chrome_trace.py`` consumes it).
* ``goodput``  — per-window wall-time decomposition (data-wait /
                 host-callback / dispatch / checkpoint / other), a goodput
                 percentage, live device-memory gauges, and a recompile
                 detector over the ``TRACE_COUNTS`` machinery.
* ``exporter`` — optional stdlib-only HTTP daemon serving ``/metrics``
                 (Prometheus text), ``/healthz`` (resilience supervisor
                 state), ``/debug/flight`` (flight-recorder tail) and
                 ``/debug/requests`` (in-flight request timelines), shared
                 by the trainer and ``serving.InferenceEngine``.
* ``flight_recorder`` — always-on bounded ring of structured events from
                 every hot subsystem, dumped to ``postmortem-<rank>.json``
                 on watchdog fire / supervisor abort / uncaught exception /
                 SIGTERM (``scripts/postmortem.py`` merges ranks).
* ``request_trace`` — per-request lifecycle timelines through the serving
                 engine (queue-wait / TPOT histograms, per-slot chrome
                 trace).
* ``cost``     — compiled-program cost census: every instrumented jit site
                 records XLA ``cost_analysis``/``memory_analysis`` + compile
                 wall-time per bucket, feeding continuous per-window MFU /
                 bandwidth-utilization gauges and ``/debug/cost``.
* ``devmem``   — live HBM accounting: ``jax.live_arrays()`` buffer census,
                 high-watermark tracking with a CPU fallback, KV-pool
                 capacity stats, and the OOM post-mortem payload
                 (``/debug/memory``).
* ``numerics`` — numerics & training-health observatory: the instrumented
                 sibling train step's per-param-group grad/param RMS,
                 absmax, non-finite counts, update/weight ratio and
                 overflow-margin bits (scan-stacked layers as per-layer
                 vectors), a bounded health-history ring, and the
                 non-finite provenance doc the resilience supervisor's
                 anomaly re-run produces (``/debug/numerics``, the
                 ``numerics.nonfinite`` flight event, the anomaly
                 post-mortem).
* ``comm``     — live collective census riding the cost census's compile:
                 per-program bytes by collective kind, predicted comm time
                 against the ICI peak, overlappable-vs-serialized pair
                 counts, the ``comm``-bound roofline extension and the
                 window ``comm_est_frac``.
* ``fleet``    — cross-rank view: per-sync-window step-time skew exchange
                 (straggler warnings + ``fleet.straggler`` flight events),
                 host-side per-rank heartbeat files for out-of-process
                 wedge diagnosis, and ``/debug/fleet``
                 (``scripts/fleet.py`` merges ranks offline).

``callback.ObservabilityCallback`` (imported lazily by the trainer — it
depends on ``trainer.callbacks``) ties them together in the train loop.
See ``docs/observability.md``.
"""

from veomni_tpu.observability.comm import (
    CommCensus,
    CommCost,
    get_comm_census,
)
from veomni_tpu.observability.cost import (
    CostCensus,
    CostWindow,
    ProgramCost,
    get_cost_census,
    instrument_jit,
)
from veomni_tpu.observability.devmem import (
    attach_oom_extra,
    buffer_census,
    is_resource_exhausted,
    kv_capacity_stats,
    oom_report,
    publish_memory_gauges,
)
from veomni_tpu.observability.exporter import MetricsExporter, render_prometheus
from veomni_tpu.observability.fleet import (
    FleetMonitor,
    get_active_monitor,
    heartbeat_ages,
    read_heartbeats,
    write_heartbeat,
)
from veomni_tpu.observability.flight_recorder import (
    FlightRecorder,
    configure_flight_recorder,
    dump_postmortem,
    get_flight_recorder,
    record,
)
from veomni_tpu.observability.goodput import (
    GoodputTracker,
    RecompileDetector,
    update_memory_gauges,
)
from veomni_tpu.observability.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
    set_registry,
)
from veomni_tpu.observability.numerics import (
    NumericsMonitor,
    NumericsSpec,
    attach_numerics_extra,
    debug_numerics_doc,
    tree_health,
)
from veomni_tpu.observability.request_trace import RequestTimeline, RequestTracer
from veomni_tpu.observability.spans import (
    disable_spans,
    dump_chrome_trace,
    enable_spans,
    span,
    spans_enabled,
)

__all__ = [
    "CommCensus",
    "CommCost",
    "CostCensus",
    "CostWindow",
    "Counter",
    "FleetMonitor",
    "FlightRecorder",
    "Gauge",
    "ProgramCost",
    "GoodputTracker",
    "Histogram",
    "MetricsExporter",
    "MetricsRegistry",
    "NumericsMonitor",
    "NumericsSpec",
    "RecompileDetector",
    "RequestTimeline",
    "RequestTracer",
    "attach_numerics_extra",
    "attach_oom_extra",
    "buffer_census",
    "configure_flight_recorder",
    "debug_numerics_doc",
    "disable_spans",
    "dump_chrome_trace",
    "dump_postmortem",
    "enable_spans",
    "get_active_monitor",
    "get_comm_census",
    "get_cost_census",
    "get_flight_recorder",
    "get_registry",
    "heartbeat_ages",
    "instrument_jit",
    "is_resource_exhausted",
    "kv_capacity_stats",
    "oom_report",
    "publish_memory_gauges",
    "read_heartbeats",
    "record",
    "render_prometheus",
    "set_registry",
    "span",
    "spans_enabled",
    "tree_health",
    "update_memory_gauges",
    "write_heartbeat",
]
