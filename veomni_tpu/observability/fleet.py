"""Cross-rank fleet view: straggler detection, heartbeats, skew telemetry.

Every metric the first three observability tiers emit is rank-local; a
wedged or slow rank is invisible from any other rank's `/metrics` (five
straight bench rounds of a wedged TPU relay produced 0 tok/s and *no
artifact saying which rank stopped* — BENCH_r01–r05). This module closes
the gap three ways:

1. **Skew exchange** (:class:`FleetMonitor`): once per sync window the
   trainer contributes its window step-time stats — a handful of floats —
   to one tiny all-gather across processes (default transport:
   ``jax.experimental.multihost_utils.process_allgather``, i.e. one jitted
   all-gather; injectable for tests and drills). The gathered table feeds
   ``fleet.step_time_skew_s`` / ``fleet.slowest_rank`` /
   ``fleet.step_time_median_s`` / ``fleet.step_time_max_s`` gauges, a
   ``fleet.straggler`` flight-recorder event, and a loud rank-0 warning
   when any rank's window mean exceeds the other ranks' median by
   ``train.observability_straggler_factor`` (the suspect is excluded from
   its own baseline — see :func:`compute_skew`). Off below 2 processes and
   via ``train.observability_fleet=0`` — zero cost when off.

2. **Host-side heartbeats**: each rank atomically rewrites
   ``heartbeat-<rank>.json`` (wall time, global step, window step time,
   phase) in the output dir every sync window. A *wedged* rank — the relay
   failure mode, where no in-band exchange can run — is diagnosable from
   OUTSIDE the process: its heartbeat age keeps growing while its
   neighbors' stay fresh. ``scripts/fleet.py`` and the bench's stall JSON
   read these. The "rank" may also be a string — the serving router's
   per-replica pump workers beat as ``heartbeat-<rid>.json`` (phase
   ``serve_pump``), so a replica wedged inside ``engine.step()`` is
   nameable from outside the process exactly like a wedged trainer rank.

3. **``/debug/fleet``** (exporter): the local rank's last exchanged skew
   table, every heartbeat visible in the heartbeat dir (on a shared
   filesystem that is the whole fleet), and the comm census snapshot —
   one scrape answers "which rank is slow and what is it waiting on".

``scripts/fleet.py`` merges per-rank metrics JSONL / heartbeats /
post-mortems onto one cluster timeline offline.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from veomni_tpu.observability.metrics import MetricsRegistry, get_registry
from veomni_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# rank is numeric for trainer processes; serving router pump workers beat
# under their replica id (e.g. heartbeat-r0.json)
HEARTBEAT_RE = re.compile(r"^heartbeat-([A-Za-z0-9_.\-]+)\.json$")


def _rank_value(rank: Any) -> Any:
    """Numeric ranks stay ints (trainer semantics: sorting, slowest-rank
    gauges); anything else is a string identity."""
    s = str(rank)
    return int(s) if s.lstrip("-").isdigit() else s


def _rank_sort_key(rank: Any):
    """Ints first in numeric order, then string ranks lexically — a mixed
    trainer + serving heartbeat dir must not TypeError a sort."""
    return (1, rank) if isinstance(rank, str) else (0, rank)

#: heartbeat older than this many seconds reads as stale in
#: :func:`heartbeat_ages` (callers may pass their own threshold — the bench
#: stall JSON uses its watchdog timeout)
DEFAULT_STALE_S = 120.0


# ----------------------------------------------------------------- heartbeats
def heartbeat_path(dirpath: str, rank: Any) -> str:
    return os.path.join(dirpath, f"heartbeat-{rank}.json")


def write_heartbeat(dirpath: str, *, rank: Optional[Any] = None,
                    global_step: int = 0, step_time_s: float = 0.0,
                    phase: str = "train",
                    extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
    """Atomically rewrite this rank's heartbeat file. Never raises (a full
    or hung filesystem must not cost a training step); returns the path or
    None on failure."""
    if not dirpath:
        return None
    if rank is None:
        from veomni_tpu.utils.logging import _process_index

        rank = _process_index()
    doc = {
        "schema": 1,
        "rank": _rank_value(rank),
        "pid": os.getpid(),
        "wall_time_s": time.time(),
        "global_step": int(global_step),
        "step_time_s": float(step_time_s),
        "phase": phase,
    }
    if extra:
        doc.update(extra)
    path = heartbeat_path(dirpath, rank)
    try:
        os.makedirs(dirpath, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path
    except OSError as e:
        logger.debug("heartbeat write failed: %s", e)
        return None


def read_heartbeats(dirpath: str) -> List[Dict[str, Any]]:
    """Every parseable ``heartbeat-<rank>.json`` under ``dirpath``, sorted
    by rank. Unreadable/torn files are skipped (a heartbeat is rewritten in
    place; a reader can race the rename on non-atomic filesystems)."""
    out: List[Dict[str, Any]] = []
    try:
        names = os.listdir(dirpath)
    except OSError:
        return out
    for name in sorted(names):
        m = HEARTBEAT_RE.match(name)
        if not m:
            continue
        try:
            with open(os.path.join(dirpath, name)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        doc.setdefault("rank", _rank_value(m.group(1)))
        out.append(doc)
    out.sort(key=lambda d: _rank_sort_key(d.get("rank", 0)))
    return out


def heartbeat_ages(dirpath: str, now: Optional[float] = None,
                   stale_after_s: float = DEFAULT_STALE_S
                   ) -> List[Dict[str, Any]]:
    """Per-rank heartbeat freshness: ``{rank, age_s, stale, global_step,
    step_time_s, phase}`` rows — the table the bench stall JSON and
    ``/debug/fleet`` embed so a wedged rank is *named*, not inferred."""
    now = time.time() if now is None else now
    rows = []
    for doc in read_heartbeats(dirpath):
        age = max(0.0, now - float(doc.get("wall_time_s", 0.0)))
        rows.append({
            "rank": doc.get("rank", -1),
            "age_s": age,
            "stale": age > stale_after_s,
            "global_step": doc.get("global_step", 0),
            "step_time_s": doc.get("step_time_s", 0.0),
            "phase": doc.get("phase", ""),
        })
    return rows


# -------------------------------------------------------------- skew exchange
def _default_exchange(local: np.ndarray) -> np.ndarray:
    """One tiny jitted all-gather of the local stats row across processes
    -> ``[world, k]`` (identical on every rank)."""
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(local))


def compute_skew(table: np.ndarray) -> Dict[str, float]:
    """Pure skew math over a gathered ``[world, >=2]`` stats table whose
    columns are ``(rank, mean_step_s, ...)``: median/max window step time,
    skew (max - median), and the slowest rank. Unit-testable without any
    exchange.

    The median EXCLUDES the slowest rank: with it included, a straggler
    inflates its own detection baseline — on a 2-rank fleet the trigger
    ``max > f * median(all)`` is mathematically unsatisfiable for any
    ``f >= 2`` (median = (a+b)/2 ⇒ b > a+b is impossible), and any small
    even fleet is skewed the same way. Excluding the suspect, the 2-rank
    baseline is simply the healthy rank's time."""
    ranks = table[:, 0].astype(int)
    means = table[:, 1].astype(float)
    slowest = int(np.argmax(means))
    others = np.delete(means, slowest)
    median = float(np.median(others)) if others.size else float(means[slowest])
    mx = float(means[slowest])
    return {
        "step_time_median_s": median,
        "step_time_max_s": mx,
        "step_time_skew_s": max(0.0, mx - median),
        "slowest_rank": int(ranks[slowest]),
        "slowest_mean_s": mx,
    }


class FleetMonitor:
    """Per-sync-window straggler detection + heartbeat emission.

    ``observe_window(global_step, mean_step_s, ...)`` is the single entry
    point (ObservabilityCallback calls it on the trainer's existing sync
    cadence — zero added device syncs): it writes the heartbeat, and, when
    the exchange is live (>= ``min_ranks`` processes and not disabled),
    gathers every rank's ``(rank, mean, max, step)`` row, publishes the
    ``fleet.*`` gauges, and raises the straggler alarm when a rank's window
    mean exceeds ``straggler_factor`` x the fleet median.

    The transport is injectable (``exchange_fn``): tests and single-process
    drills substitute a fake fleet; production uses the jitted all-gather.
    Failure policy: fleet telemetry must never kill a training step, but a
    rank that silently stops calling the gather would WEDGE its peers'
    next exchange (they block in the collective waiting for it) — so a
    failed exchange is RETRIED next window (collectives match in launch
    order, so our next call completes a peer's outstanding round and the
    fleet self-heals from a transient) and only
    :data:`MAX_CONSECUTIVE_EXCHANGE_FAILURES` straight failures disable it,
    with a loud warning that peers on the same knob must ride their
    collective timeout out of the final round."""

    #: straight exchange failures tolerated before this rank stops calling
    #: the all-gather (peers block until their collective timeout on the
    #: last round, then surface a distributed error — loud, not silent)
    MAX_CONSECUTIVE_EXCHANGE_FAILURES = 3

    def __init__(self, *, registry: Optional[MetricsRegistry] = None,
                 world_size: Optional[int] = None,
                 rank: Optional[int] = None,
                 straggler_factor: float = 2.0,
                 heartbeat_dir: str = "",
                 exchange_fn: Optional[
                     Callable[[np.ndarray], np.ndarray]] = None,
                 min_ranks: int = 2):
        if world_size is None or rank is None:
            import jax

            world_size = jax.process_count() if world_size is None else world_size
            rank = jax.process_index() if rank is None else rank
        self.registry = registry or get_registry()
        self.world_size = int(world_size)
        self.rank = int(rank)
        self.straggler_factor = float(straggler_factor)
        self.heartbeat_dir = heartbeat_dir
        self.min_ranks = int(min_ranks)
        self._exchange = exchange_fn or _default_exchange
        self._exchange_disabled = self.world_size < self.min_ranks
        self._exchange_failures = 0  # consecutive; reset on success
        self._window_interval_s = 0.0  # observed sync cadence (debug_doc)
        self._last_window_t: Optional[float] = None
        self._lock = threading.Lock()
        self._last: Optional[Dict[str, Any]] = None  # guarded-by: _lock
        self.straggler_count = 0
        set_active_monitor(self)

    @property
    def exchange_enabled(self) -> bool:
        return not self._exchange_disabled

    def observe_window(self, global_step: int, mean_step_s: float,
                       max_step_s: Optional[float] = None,
                       steps: int = 0,
                       exchange: bool = True) -> Optional[Dict[str, Any]]:
        """One sync window's contribution. Returns the skew dict when the
        exchange ran, else None (heartbeat is written either way).

        ``exchange=False`` writes the heartbeat but skips the skew gather —
        the caller's warmup absorption (ObservabilityCallback skips the
        FIRST sync window, which contains the step-1 compile: cross-host
        compile-wall skew — one rank's cold cache vs another's warm one —
        is not a straggler, the same reason the recompile detector arms
        after step 1). Every rank must pass the same value per window: the
        gather is a collective."""
        now = time.monotonic()
        if self._last_window_t is not None:
            self._window_interval_s = now - self._last_window_t
        self._last_window_t = now
        write_heartbeat(
            self.heartbeat_dir, rank=self.rank, global_step=global_step,
            step_time_s=mean_step_s,
            extra={"window_steps": int(steps)} if steps else None,
        )
        if not exchange or self._exchange_disabled:
            return None
        # everything rank-locally fallible happens BEFORE the collective:
        # the gather must be the only thing inside the try, so a failure is
        # (almost always) the transport itself — symmetric across ranks —
        # rather than a one-rank divergence
        local = np.asarray([
            float(self.rank), float(mean_step_s),
            float(max_step_s if max_step_s is not None else mean_step_s),
            float(global_step),
        ], dtype=np.float64)
        try:
            table = np.asarray(self._exchange(local), dtype=np.float64)
            table = table.reshape(-1, local.shape[0])
            self._exchange_failures = 0
        except Exception as e:
            # do NOT stop calling on the first failure: a rank that goes
            # silent wedges its peers' next gather. Retrying next window
            # pairs with a peer's outstanding round (collectives match in
            # launch order), so a transient self-heals; only a persistent
            # failure earns the disable.
            self._exchange_failures += 1
            if self._exchange_failures >= self.MAX_CONSECUTIVE_EXCHANGE_FAILURES:
                self._exchange_disabled = True
                logger.warning(
                    "fleet skew exchange disabled on rank %d after %d "
                    "consecutive failures (%s: %s) — per-rank heartbeats "
                    "keep flowing; peers still exchanging will block their "
                    "next window until the collective timeout surfaces a "
                    "distributed error",
                    self.rank, self._exchange_failures,
                    type(e).__name__, e,
                )
            else:
                logger.warning(
                    "fleet skew exchange failed on rank %d (%s: %s) — "
                    "retrying next sync window (%d/%d before disable)",
                    self.rank, type(e).__name__, e,
                    self._exchange_failures,
                    self.MAX_CONSECUTIVE_EXCHANGE_FAILURES,
                )
            return None
        skew = compute_skew(table)
        reg = self.registry
        reg.gauge("fleet.step_time_skew_s").set(skew["step_time_skew_s"])
        reg.gauge("fleet.step_time_median_s").set(skew["step_time_median_s"])
        reg.gauge("fleet.step_time_max_s").set(skew["step_time_max_s"])
        reg.gauge("fleet.slowest_rank").set(skew["slowest_rank"])
        straggling = (
            skew["step_time_median_s"] > 0.0
            and skew["step_time_max_s"]
            > self.straggler_factor * skew["step_time_median_s"]
        )
        if straggling:
            self.straggler_count += 1
            reg.counter("fleet.stragglers").inc()
            ratio = skew["step_time_max_s"] / skew["step_time_median_s"]
            from veomni_tpu.observability.flight_recorder import record

            record("fleet.straggler", cid=str(skew["slowest_rank"]),
                   step=int(global_step), ratio=round(ratio, 3),
                   median_s=skew["step_time_median_s"],
                   max_s=skew["step_time_max_s"])
            logger.warning_rank0(
                "STRAGGLER: rank %d is %.2fx the other ranks' median step "
                "time (%.4gs vs %.4gs median) at step %d — check that "
                "rank's heartbeat/postmortem (scripts/fleet.py) before it "
                "wedges the next collective",
                skew["slowest_rank"], ratio, skew["step_time_max_s"],
                skew["step_time_median_s"], int(global_step),
            )
        doc = {
            **skew,
            "straggling": straggling,
            "global_step": int(global_step),
            "table": [
                {"rank": int(r[0]), "mean_step_s": float(r[1]),
                 "max_step_s": float(r[2]), "global_step": int(r[3])}
                for r in table
            ],
        }
        with self._lock:
            self._last = doc
        return skew

    def debug_doc(self) -> Dict[str, Any]:
        """``/debug/fleet`` body: local identity + last skew table +
        heartbeat freshness + the comm census snapshot."""
        with self._lock:
            last = dict(self._last) if self._last else None
        doc: Dict[str, Any] = {
            "enabled": True,
            "rank": self.rank,
            "world_size": self.world_size,
            "exchange_enabled": self.exchange_enabled,
            "straggler_factor": self.straggler_factor,
            "stragglers": self.straggler_count,
            "last_window": last,
            # staleness scaled to the observed sync cadence: on a run that
            # syncs every ~250s, a fixed 120s threshold would mark every
            # HEALTHY rank stale between windows and the flag could never
            # name the one wedged rank
            "heartbeats": heartbeat_ages(
                self.heartbeat_dir,
                stale_after_s=max(DEFAULT_STALE_S,
                                  3.0 * self._window_interval_s),
            ) if self.heartbeat_dir else [],
        }
        try:
            from veomni_tpu.observability.comm import get_comm_census

            doc["comm_census"] = get_comm_census().snapshot()
        except Exception:
            pass
        return doc


_ACTIVE: Optional[FleetMonitor] = None  # guarded-by: _ACTIVE_LOCK
_ACTIVE_LOCK = threading.Lock()


def set_active_monitor(monitor: Optional[FleetMonitor]) -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = monitor


def get_active_monitor() -> Optional[FleetMonitor]:
    with _ACTIVE_LOCK:
        return _ACTIVE


def debug_fleet_doc() -> Dict[str, Any]:
    """Default ``/debug/fleet`` body when no explicit ``fleet_fn`` is wired:
    the active monitor's view, or a disabled stub that still carries the
    comm census (serving processes have collectives too)."""
    mon = get_active_monitor()
    if mon is not None:
        return mon.debug_doc()
    doc: Dict[str, Any] = {"enabled": False, "heartbeats": []}
    try:
        from veomni_tpu.observability.comm import get_comm_census

        doc["comm_census"] = get_comm_census().snapshot()
    except Exception:
        pass
    return doc
