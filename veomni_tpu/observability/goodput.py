"""Goodput accounting: where did the wall time go?

``GoodputTracker`` decomposes a window of wall time into named categories
by delta-ing the cumulative sums of the span histograms the trainer feeds
(``trainer/base.py`` wraps its loop phases in spans):

* ``data_wait``  — blocked on the input pipeline (``data.wait``)
* ``host``       — callback hooks: meters, logging, eval (``host.callbacks``,
                   minus the checkpoint time nested inside them)
* ``dispatch``   — handing work to the device: jitted step dispatch + H2D
                   batch shipping (``step.dispatch``, ``data.ship``)
* ``checkpoint`` — save/restore/wait (``ckpt.save``, ``ckpt.wait``,
                   ``ckpt.restore``)
* ``other``      — the residual; on the async loop this is dominated by the
                   sync-step device fetch, i.e. time the device was the
                   bottleneck — which is exactly where a training run
                   *wants* to spend its time.

``goodput_pct`` is therefore ``100 * (dispatch + other)`` fractions: the
share of wall time not attributable to a known host-side stall. The TPUv4
pjit paper's goodput accounting and T3's step-time tracking (PAPERS.md)
motivate making this a first-class per-window metric rather than a
profiler-session artifact.

Also here: live device-memory gauges and the recompile detector that
extends the decode/serving ``TRACE_COUNTS`` discipline to the train step.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Mapping, Optional, Sequence, Tuple

from veomni_tpu.observability.metrics import MetricsRegistry, get_registry
from veomni_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# category -> span names whose histogram sums it aggregates
CATEGORY_SPANS: Dict[str, Tuple[str, ...]] = {
    "data_wait": ("data.wait",),
    "host": ("host.callbacks",),
    "dispatch": ("step.dispatch", "data.ship"),
    "checkpoint": ("ckpt.save", "ckpt.wait", "ckpt.restore"),
}
# checkpoint saves run inside the on_step_end callback hook, so their time
# is nested inside the host category's span and must be subtracted once
_NESTED_IN_HOST = "checkpoint"


class GoodputTracker:
    """Window-delta decomposition over the span histograms.

    ``begin_window()`` snapshots the cumulative span sums; ``end_window()``
    returns the fractions for the elapsed window (and starts the next one).
    Fractions always sum to ~1.0: the residual is ``other``, and if measured
    categories exceed the wall (overlapping spans on several threads) the
    set is renormalized."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 categories: Optional[Dict[str, Tuple[str, ...]]] = None):
        self.registry = registry or get_registry()
        self.categories = dict(categories or CATEGORY_SPANS)
        self._t0: Optional[float] = None
        self._base: Dict[str, float] = {}

    def _sums(self) -> Dict[str, float]:
        return {
            cat: sum(self.registry.histogram_sum(f"span.{n}") for n in names)
            for cat, names in self.categories.items()
        }

    def begin_window(self) -> None:
        self._t0 = time.perf_counter()
        self._base = self._sums()

    def end_window(self) -> Dict[str, float]:
        """Close the window -> metric dict; re-arms for the next window."""
        if self._t0 is None:
            self.begin_window()
            return {}
        now = time.perf_counter()
        wall = max(now - self._t0, 1e-9)
        cur = self._sums()
        deltas = {c: max(0.0, cur[c] - self._base.get(c, 0.0)) for c in cur}
        if _NESTED_IN_HOST in deltas and "host" in deltas:
            deltas["host"] = max(0.0, deltas["host"] - deltas[_NESTED_IN_HOST])
        fracs = {c: d / wall for c, d in deltas.items()}
        known = sum(fracs.values())
        if known > 1.0:
            fracs = {c: f / known for c, f in fracs.items()}
            known = 1.0
        fracs["other"] = 1.0 - known
        out = {f"{c}_frac": f for c, f in fracs.items()}
        out["goodput_pct"] = 100.0 * (fracs["dispatch"] + fracs["other"])
        out["window_wall_s"] = wall
        self._t0, self._base = now, cur
        return out


def update_memory_gauges(registry: Optional[MetricsRegistry] = None) -> None:
    """Publish the ``mem.*`` gauges. Since the device cost & capacity
    observatory (observability/devmem.py) this is more than a
    ``memory_stats()`` passthrough: per-device bytes where the backend
    reports them, plus host RSS, the live-buffer total and a
    process-lifetime high watermark — live on every backend, so tier-1
    exercises the whole path under ``JAX_PLATFORMS=cpu``."""
    from veomni_tpu.observability.devmem import publish_memory_gauges

    publish_memory_gauges(registry or get_registry())


class RecompileDetector:
    """Watches trace-count dicts (``train/train_step.py::TRACE_COUNTS``,
    ``models/decode.py::TRACE_COUNTS``) and raises a loud rank-0 warning —
    with the offending shapes — when XLA re-traces after the warmup
    compiles were absorbed by :meth:`arm`.

    A recompile storm (every step re-tracing, e.g. dynamic batching without
    shape bucketing) silently multiplies step time; the detector turns it
    into one unmissable log line + a ``recompiles`` counter instead of a
    mystery utilization cliff."""

    def __init__(self, count_sources: Sequence[Tuple],
                 shape_source: Optional[Mapping[str, Any]] = None,
                 registry: Optional[MetricsRegistry] = None,
                 storm_threshold: int = 3):
        """``count_sources``: ``(label, mapping)`` or ``(label, mapping,
        keys)`` tuples; ``keys`` restricts which entries of a live
        TRACE_COUNTS dict are watched (the trainer watches only
        ``train_step`` — a first eval jit or a new decode bucket is a fresh
        program, not a recompile)."""
        self.count_sources = [
            (s[0], s[1], tuple(s[2]) if len(s) > 2 and s[2] else None)
            for s in count_sources
        ]
        self.shape_source = shape_source
        self.registry = registry or get_registry()
        self.storm_threshold = storm_threshold
        self._base: Dict[str, int] = {}
        self._armed = False
        self.total_recompiles = 0

    def _totals(self) -> Dict[str, int]:
        return {
            label: sum(
                v for k, v in counts.items() if keys is None or k in keys
            )
            for label, counts, keys in self.count_sources
        }

    def arm(self) -> None:
        """Snapshot current counts as the expected-compile baseline (call
        after the first step, once warmup traces have happened)."""
        self._base = self._totals()
        self._armed = True

    def check(self) -> int:
        """New traces since the last arm/check; warns (rank 0) if any."""
        if not self._armed:
            self.arm()
            return 0
        cur = self._totals()
        new = {
            label: cur[label] - self._base.get(label, 0)
            for label in cur
            if cur[label] > self._base.get(label, 0)
        }
        self._base = cur
        n = sum(new.values())
        if not n:
            return 0
        self.total_recompiles += n
        self.registry.counter("recompiles").inc(n)
        shapes = dict(self.shape_source) if self.shape_source else {}
        storm = self.total_recompiles >= self.storm_threshold
        logger.warning_rank0(
            "RECOMPILE%s: %d new XLA trace(s) (%s), %d total since warmup; "
            "last traced shapes: %s — recompiles at steady state usually "
            "mean unstable batch shapes (bucket them) or a jit signature "
            "drift (weak types, uncommitted scalars)",
            " STORM" if storm else "",
            n, ", ".join(f"{k}+{v}" for k, v in sorted(new.items())),
            self.total_recompiles, shapes,
        )
        return n
