"""Live device-memory accounting: buffer census, watermarks, OOM forensics.

The ``mem.*`` gauges used to be a thin ``memory_stats()`` passthrough that
silently no-opped on CPU — tier-1 never exercised them, and an OOM left
nothing but the allocator's error string. This module makes HBM accounting
a first-class, always-live surface:

* :func:`buffer_census` — every ``jax.live_arrays()`` buffer aggregated by
  (shape, dtype) with a top-K largest table: *which* arrays are holding
  HBM right now (params vs optimizer moments vs KV pool vs leaked batch).
* :func:`publish_memory_gauges` — the ``mem.*`` family, refreshed each
  sync step: per-device ``bytes_in_use`` where the backend reports it,
  a ``host_rss_bytes`` RSS reading that is live on every backend (so the
  gauge path is testable under ``JAX_PLATFORMS=cpu``), the live-buffer
  total, and a process-lifetime high watermark (backend peak counter when
  available, else the max observed live-buffer total — the CPU fallback
  that lets tier-1 drill the whole path).
* :func:`kv_capacity_stats` — the serving engine's block pool translated
  into operator units: pool bytes, bytes per block, and how many
  max-length sequences fit (total and right now).
* :func:`is_resource_exhausted` / :func:`oom_report` — the OOM post-mortem
  hook: when a ``RESOURCE_EXHAUSTED`` escapes the train loop or the
  serving pump, the flight-recorder dump gains the buffer census and the
  compiled-program cost census — the two tables that answer "what was in
  HBM and which program asked for more".

Import hygiene: nothing here touches a backend at import time; every jax
call happens inside a function (see ``tests/test_import_hygiene.py``).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from veomni_tpu.observability.metrics import MetricsRegistry, get_registry
# single home for the platform-sensitive RSS read (current RSS on Linux,
# peak-RSS fallback elsewhere) — re-exported here because every consumer of
# this module's censuses wants it next to them
from veomni_tpu.utils.helper import host_rss_bytes  # noqa: F401
from veomni_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# process-lifetime high watermark of live device bytes (CPU fallback path:
# the backend's own peak_bytes_in_use is preferred when it exists)
_WATERMARK_LOCK = threading.Lock()
_WATERMARK = {"bytes": 0.0}


def _resident_nbytes(x) -> float:
    """Bytes this PROCESS actually holds for one jax array: the sum of its
    addressable shards. ``x.nbytes`` is the GLOBAL logical size — on a
    multihost run it would overcount a sharded array host-count-fold, and
    on any run it undercounts replication (a replicated array holds one
    full copy per local device)."""
    try:
        shards = x.addressable_shards
    except Exception:
        return float(getattr(x, "nbytes", 0) or 0)
    total = 0.0
    for s in shards:
        try:
            total += float(s.data.nbytes)
        except Exception:
            pass
    return total if total else float(getattr(x, "nbytes", 0) or 0)


def buffer_census(top_k: int = 10) -> Dict[str, Any]:
    """Aggregate every live jax buffer by (shape, dtype).

    Returns ``{total_bytes, num_arrays, by_dtype: {dtype: {count, bytes}},
    top: [{shape, dtype, count, bytes}, ...]}`` with ``top`` sorted by
    aggregate bytes descending, truncated to ``top_k``. Bytes are
    process-RESIDENT (addressable shards, replication counted per copy);
    shapes shown are the global logical shapes. Deleted/donated arrays are
    skipped (their buffers are gone)."""
    import jax

    groups: Dict[tuple, Dict[str, Any]] = {}
    by_dtype: Dict[str, Dict[str, float]] = {}
    total = 0.0
    n = 0
    for x in jax.live_arrays():
        try:
            if getattr(x, "is_deleted", lambda: False)():
                continue
            shape = tuple(x.shape)
            dtype = str(x.dtype)
            nbytes = _resident_nbytes(x)
        except Exception:
            continue
        n += 1
        total += nbytes
        g = groups.setdefault((shape, dtype), {
            "shape": list(shape), "dtype": dtype, "count": 0, "bytes": 0.0,
        })
        g["count"] += 1
        g["bytes"] += nbytes
        d = by_dtype.setdefault(dtype, {"count": 0, "bytes": 0.0})
        d["count"] += 1
        d["bytes"] += nbytes
    top = sorted(groups.values(), key=lambda g: -g["bytes"])[:max(0, top_k)]
    return {
        "total_bytes": total,
        "num_arrays": n,
        "by_dtype": by_dtype,
        "top": top,
    }


def live_buffer_bytes() -> float:
    """Sum of live jax array bytes — the CPU-portable 'bytes in use'."""
    return buffer_census(top_k=0)["total_bytes"]


def reset_watermark() -> None:
    """Tests only: forget the process-lifetime high watermark."""
    with _WATERMARK_LOCK:
        _WATERMARK["bytes"] = 0.0


def publish_memory_gauges(registry: Optional[MetricsRegistry] = None
                          ) -> Dict[str, float]:
    """Refresh the ``mem.*`` family; returns what was published.

    Per-device ``memory_stats()`` readings (``device{i}_bytes_in_use``,
    ``device{i}_peak_bytes_in_use``) where the backend has them, plus the
    always-live fallbacks: ``host_rss_bytes``, ``live_buffer_bytes`` and
    the high watermark (backend peak preferred; else max observed live
    total, so the watermark path runs on CPU too)."""
    from veomni_tpu.utils.helper import live_memory_stats

    reg = registry or get_registry()
    stats = dict(live_memory_stats())  # includes host_rss_bytes (helper)
    live = live_buffer_bytes()
    stats["live_buffer_bytes"] = live
    # both candidates are whole-process totals: summing the per-device
    # readings keeps them unit-compatible (a per-device peak compared
    # against a summed current would under-report the watermark N-fold)
    peak_total = sum(
        v for k, v in stats.items() if k.endswith("peak_bytes_in_use")
    )
    in_use_total = sum(
        v for k, v in stats.items()
        if k.startswith("device") and k.endswith("_bytes_in_use")
        and "peak" not in k
    )
    current = in_use_total if in_use_total else live
    with _WATERMARK_LOCK:
        _WATERMARK["bytes"] = max(_WATERMARK["bytes"], current, peak_total)
        stats["high_watermark_bytes"] = _WATERMARK["bytes"]
    reg.set_gauges("mem", stats)
    return stats


def kv_capacity_stats(blocks, k_pool=None, v_pool=None,
                      max_model_len: int = 0) -> Dict[str, float]:
    """Block-pool capacity in operator units.

    ``blocks`` is a :class:`~veomni_tpu.serving.kv_block_manager.
    KVBlockManager``; ``k_pool``/``v_pool`` (optional device arrays OR
    quantized :class:`~veomni_tpu.ops.quantization.QuantizedKV` pools) size
    the byte figures through their ``nbytes`` — a quantized pool reports
    its ACTUAL footprint (int8 payload + f32 scale sidecar), so every
    derived gauge (``serve.kv_pool_bytes``, ``serve.kv_block_bytes``) shows
    the real capacity win, never f32 math. ``max_concurrent_seqs`` is the
    estimated ceiling on simultaneously-resident sequences, assuming each
    grows to ``max_model_len`` — the capacity-planning number ("how many
    users fit in HBM"); ``free_concurrent_seqs`` is the same estimate over
    the currently free (+ evictable cached) blocks. For sizing a pool to a
    byte budget BEFORE allocating it, use
    :func:`veomni_tpu.ops.quantization.kv_block_nbytes`."""
    pool_bytes = 0.0
    for p in (k_pool, v_pool):
        if p is not None:
            try:
                pool_bytes += float(p.nbytes)
            except Exception:
                pass
    usable = max(1, blocks.num_blocks - 1)  # block 0 is the null block
    block_bytes = pool_bytes / blocks.num_blocks if pool_bytes else 0.0
    per_seq = blocks.blocks_for(max_model_len) if max_model_len else 1
    return {
        "pool_bytes": pool_bytes,
        "block_bytes": block_bytes,
        "block_size": float(blocks.block_size),
        "num_blocks": float(blocks.num_blocks),
        "blocks_free": float(blocks.num_free),
        "blocks_per_max_len_seq": float(per_seq),
        "max_concurrent_seqs": float(usable // per_seq),
        "free_concurrent_seqs": float(blocks.num_free // per_seq),
    }


def debug_memory_doc(memory_fn=None, top_k: int = 10) -> Dict[str, Any]:
    """``/debug/memory`` body: buffer census + watermark (+ the caller's
    pool-capacity document when wired — the serving engine passes
    :func:`kv_capacity_stats`)."""
    doc: Dict[str, Any] = {"buffer_census": buffer_census(top_k=top_k)}
    doc["host_rss_bytes"] = host_rss_bytes()
    with _WATERMARK_LOCK:
        doc["high_watermark_bytes"] = _WATERMARK["bytes"]
    if memory_fn is not None:
        try:
            doc["pool"] = dict(memory_fn())
        except Exception as e:  # a broken scrape must not 500 the census
            doc["pool"] = {"error": str(e)}
    return doc


# ------------------------------------------------------------ OOM forensics
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "out of memory", "Out of memory",
                "OOM when allocating")


def is_resource_exhausted(exc: BaseException) -> bool:
    """Does this exception look like a device allocator failure? Checks the
    message (XlaRuntimeError carries the grpc-style status name) so fault-
    injected drills and real allocator errors take the same path."""
    msg = str(exc)
    return any(m in msg for m in _OOM_MARKERS)


def attach_oom_extra(exc: BaseException,
                     extra: Dict[str, Any]) -> Dict[str, Any]:
    """Merge :func:`oom_report` into a post-mortem ``extra`` dict when the
    exception looks like a device allocator failure; otherwise a no-op.
    The ONE implementation both dump sites (trainer ``train()`` and the
    ``scripts/serve.py`` pump) share, so their artifacts can't diverge.
    Exception-proof: forensics must never mask the original failure."""
    try:
        if is_resource_exhausted(exc):
            extra.update(oom_report())
    except Exception as forensic_err:
        extra["oom_report_error"] = str(forensic_err)
    return extra


def oom_report(top_k: int = 10) -> Dict[str, Any]:
    """The post-mortem payload for an OOM: the buffer census (what held the
    memory) and the cost census (what each compiled program needs on top).
    Exception-proof — forensics must never mask the original failure."""
    out: Dict[str, Any] = {}
    try:
        out["buffer_census"] = buffer_census(top_k=top_k)
    except Exception as e:
        out["buffer_census"] = {"error": str(e)}
    try:
        from veomni_tpu.observability.cost import get_cost_census

        out["cost_census"] = get_cost_census().snapshot()
    except Exception as e:
        out["cost_census"] = {"error": str(e)}
    try:
        out["host_rss_bytes"] = host_rss_bytes()
    except Exception:
        pass
    return out
