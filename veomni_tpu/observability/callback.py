"""ObservabilityCallback: the train loop's wiring into the registry.

Imported lazily by ``BaseTrainer._init_callbacks`` (this module depends on
``trainer.callbacks``; everything else in ``observability`` is trainer-
agnostic). Placed right after ``EnvironMeterCallback`` so the published
payload already contains the meter's throughput/MFU rollup, and before
``LoggingCallback``/``WandbCallback`` so their export-hook consumption sees
this step's publish.

Per sync step (the loop's existing host<->device sync cadence — zero added
syncs): closes the goodput window, refreshes memory gauges, publishes
``train.*`` gauges, and fires ``registry.export`` (JSONL sink + hooks).
Every step: checks the recompile detector (a host-side dict compare).
"""

from __future__ import annotations

import os

from veomni_tpu.observability.cost import CostWindow
from veomni_tpu.observability.exporter import MetricsExporter, resolve_port
from veomni_tpu.observability.goodput import (
    GoodputTracker,
    RecompileDetector,
    update_memory_gauges,
)
from veomni_tpu.observability.metrics import get_registry
from veomni_tpu.observability.spans import (
    dump_chrome_trace,
    enable_spans,
)
from veomni_tpu.trainer.callbacks import Callback
from veomni_tpu.utils.helper import host_floats
from veomni_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class ObservabilityCallback(Callback):
    def __init__(self):
        self.registry = None
        self.tracker = None
        self.detector = None
        self.exporter = None
        self.cost_window = None
        self.fleet = None
        self._chrome_trace_path = ""
        self._armed = False
        # per-sync-window host step timing (feeds the fleet skew exchange):
        # one perf_counter read per step, no device syncs
        self._win_t0 = 0.0
        self._win_last = 0.0
        self._win_steps = 0
        self._win_max_step_s = 0.0
        self._fleet_warm = False

    def on_train_begin(self, trainer, state):
        t = trainer.args.train
        self.registry = get_registry()
        if t.observability_spans:
            enable_spans()
        # (the flight recorder's ring size + dump dir are wired in train()'s
        # prologue, BEFORE any callback can raise — not here)
        if t.observability_jsonl:
            path = os.path.join(
                t.output_dir, f"metrics_rank{self.registry.rank()}.jsonl"
            )
            self.registry.attach_jsonl(path)
        self._chrome_trace_path = t.observability_chrome_trace
        self.tracker = GoodputTracker(self.registry)
        from veomni_tpu.train import train_step as train_step_mod

        # watch ONLY the train step: a first eval jit or a decode bucket
        # compile is a fresh program, not a steady-state retrace
        self.detector = RecompileDetector(
            [("train_step", train_step_mod.TRACE_COUNTS, ("train_step",))],
            shape_source=train_step_mod.LAST_TRACE_SHAPES,
            registry=self.registry,
        )
        # fleet tier (observability/fleet.py): heartbeats always (a wedged
        # rank must be diagnosable from outside), skew exchange only with
        # >= 2 processes; train.observability_fleet=0 turns it all off
        if t.observability_fleet:
            from veomni_tpu.observability.fleet import FleetMonitor

            self.fleet = FleetMonitor(
                registry=self.registry,
                straggler_factor=t.observability_straggler_factor,
                heartbeat_dir=t.output_dir,
            )
        port = resolve_port(t.observability_port)
        if port is not None:
            sup = getattr(trainer, "_supervisor", None)
            health_fn = sup.health if sup is not None else None
            self.exporter = MetricsExporter(
                port=port, registry=self.registry, health_fn=health_fn,
                fleet_fn=self.fleet.debug_doc if self.fleet else None,
            )
            self.exporter.start()
        self.tracker.begin_window()
        # compiled-program cost census window (observability/cost.py): the
        # same sync cadence turns census FLOPs/bytes × step counts into the
        # continuous train.mfu_pct / train.bandwidth_util_pct gauges
        self.cost_window = CostWindow()
        self.cost_window.begin()
        self._armed = False
        import time as _time

        self._win_t0 = self._win_last = _time.perf_counter()
        self._win_steps = 0
        self._win_max_step_s = 0.0
        self._fleet_warm = False  # window 1 = compile warmup, no exchange

    def on_step_end(self, trainer, state):
        import time as _time

        now = _time.perf_counter()
        self._win_steps += 1
        self._win_max_step_s = max(self._win_max_step_s, now - self._win_last)
        self._win_last = now
        if not self._armed:
            # absorb the warmup compile of step 1; everything after is a
            # recompile worth shouting about
            self.detector.arm()
            self._armed = True
        else:
            self.detector.check()
        if not state.synced:
            return
        state.metrics.update(self.tracker.end_window())
        state.metrics.update(self.cost_window.end())
        state.metrics["recompiles"] = float(self.detector.total_recompiles)
        update_memory_gauges(self.registry)
        if self.fleet is not None and self._win_steps:
            # heartbeat + skew exchange on the loop's existing sync cadence
            # (the host just blocked on the device fetch anyway). The FIRST
            # window carries step-1's compile wall — cross-host compile
            # skew (cold vs warm persistent cache) is not a straggler, so
            # it heartbeats but skips the exchange, mirroring the recompile
            # detector's warmup arm. Deterministic per window on every
            # rank: the exchange is a collective.
            self.fleet.observe_window(
                state.global_step,
                (now - self._win_t0) / self._win_steps,
                max_step_s=self._win_max_step_s,
                steps=self._win_steps,
                exchange=self._fleet_warm,
            )
            self._fleet_warm = True
        self._win_t0 = self._win_last = _time.perf_counter()
        self._win_steps = 0
        self._win_max_step_s = 0.0
        payload = host_floats(state.metrics)
        self.registry.set_gauges("train", payload)
        self.registry.export(state.global_step, payload)

    def on_train_end(self, trainer, state):
        if self.registry is None:  # train() without on_train_begin (tests)
            return
        if self.tracker is not None:
            state.metrics.update(self.tracker.end_window())
        if self.cost_window is not None:
            state.metrics.update(self.cost_window.end())
        payload = host_floats(state.metrics)
        self.registry.set_gauges("train", payload)
        self.registry.export(state.global_step, payload)
        if self._chrome_trace_path:
            n = dump_chrome_trace(self._chrome_trace_path)
            logger.info_rank0(
                "wrote %d host span events to %s", n, self._chrome_trace_path
            )
        self.close()

    def close(self):
        """Exception-safe teardown (BaseTrainer calls every callback's
        ``close`` in its finally block): the exporter thread must not
        outlive a crashed run."""
        if self.exporter is not None:
            self.exporter.stop()
            self.exporter = None
        if self.fleet is not None:
            from veomni_tpu.observability.fleet import (
                get_active_monitor,
                set_active_monitor,
            )

            # only un-register our own monitor: a second trainer in the
            # same process may already have installed its own
            if get_active_monitor() is self.fleet:
                set_active_monitor(None)
            self.fleet = None
