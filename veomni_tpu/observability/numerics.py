"""Numerics & training-health observatory: per-layer-group telemetry with
non-finite provenance.

The four earlier observability tiers made the *system* transparent (spans,
flight events, cost census, fleet skew); the training *math* stayed a black
box — the supervisor sees one device-side ``step_ok`` bit and can only
skip/rollback/abort without knowing which layer first went non-finite. At
pjit/TPU scale, loss spikes and silent divergence dominate long-run failures
(the TPUv4 pjit paper, PAPERS.md), and veScale's debuggability-first SPMD
argument is exactly the case for making anomaly *attribution* a framework
layer rather than a notebook exercise.

Two-program design, so the steady-state hot path is untouched:

* :func:`tree_health` runs INSIDE the jitted instrumented sibling step
  (``train/train_step.py::build_train_step(numerics_spec=...)``) and
  summarizes, per stable param-tree group (scan-stacked subtrees — any path
  component ending in ``layers`` — keep their leading layer dim, so stats
  are per-layer vectors): grad RMS / absmax / non-finite counts, param RMS
  and non-finite counts, update/weight ratio, and dtype overflow-margin
  bits. Group cardinality is capped with deterministic coarsening (drop
  trailing path components, then merge the sorted tail into ``...rest``).
* :class:`NumericsMonitor` is the host side: it fetches the health tree on
  the trainer's ``train.observability_numerics_interval`` cadence, keeps a
  bounded history ring, publishes worst-layer ``numerics.*`` gauges, and —
  when the resilience supervisor flags an anomalous step — turns a re-run
  of the *same already-fetched batch* through the instrumented step into a
  provenance document: the first non-finite group (param beats grad beats
  update, since a rotten param is upstream of everything), the offending
  layer for stacked groups, and the recent health history. The doc lands in
  the flight recorder (``numerics.nonfinite``), the anomaly post-mortem
  (:func:`attach_numerics_extra`) and ``/debug/numerics``
  (:func:`debug_numerics_doc`).

Everything in :func:`tree_health` and below it must stay trace-pure — the
graftlint trace-purity walk pins it as a jit-reachable root
(``analysis/purity.py::SANITY_TRACED``).
"""

from __future__ import annotations

import math
import threading
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
# bare-name imports: these run at TRACE time inside the jitted sibling step
# on static pytree structure only — binding them as plain names keeps the
# static-analysis tracedness taint (anything assigned from a jax.* call)
# away from the pure-python group bookkeeping they feed
from jax.tree_util import tree_leaves, tree_leaves_with_path

from veomni_tpu.observability.flight_recorder import record as flight_record
from veomni_tpu.observability.metrics import get_registry
from veomni_tpu.utils.logging import get_logger

logger = get_logger(__name__)

#: merged overflow bucket when the cardinality cap still can't hold every
#: coarsened group name (deterministic: the sorted tail lands here)
REST_GROUP = "...rest"

#: provenance priority: a non-finite PARAM is upstream of every grad, and a
#: non-finite grad is upstream of the update it produces
PROVENANCE_KINDS = ("param", "grad", "update")


@dataclass(frozen=True)
class NumericsSpec:
    """Static (trace-time) configuration of the health summary."""

    max_groups: int = 64
    eps: float = 1e-12


# --------------------------------------------------------------- group naming
def _path_str(path) -> str:
    """KeyPath -> dotted string (same rendering as
    ``parallel_plan.param_path_str``, duplicated to keep this module
    importable without the parallel layer)."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return ".".join(parts)


def _is_stacked(path) -> bool:
    """Scan-stacked subtree detection: the repo-wide convention is per-layer
    tensors stacked on a leading dim under a ``layers``-suffixed key
    (``layers``, ``dense_layers``, a tower's ``vision.layers``, ...)."""
    for k in path:
        name = str(getattr(k, "key", getattr(k, "idx", k)))
        if name.endswith("layers"):
            return True
    return False


def build_groups(paths: Sequence[Any],
                 max_groups: int = 64) -> List[Tuple[str, List[int]]]:
    """Deterministic (name, member-leaf-indices) groups for a flattened
    param tree, cardinality-capped.

    Starts at full leaf-path granularity; while over the cap, coarsens by
    dropping the trailing path component (``layers.q_proj`` stays a group,
    a 200-leaf MoE tree collapses toward its subtree roots); if depth-1
    granularity still exceeds the cap, the sorted tail merges into
    :data:`REST_GROUP`. Pure string work — safe at trace time."""
    max_groups = max(1, max_groups)
    names = [_path_str(p) for p in paths]
    depth = max((n.count(".") + 1 for n in names), default=1)
    while depth > 1 and len(set(names)) > max_groups:
        depth -= 1
        names = [".".join(n.split(".")[:depth]) for n in names]
    distinct = sorted(set(names))
    if len(distinct) > max_groups:
        # keep is empty at max_groups=1: everything lands in ...rest, so
        # the cap holds EXACTLY (keep + the rest bucket <= max_groups)
        keep = set(distinct[: max_groups - 1])
        names = [n if n in keep else REST_GROUP for n in names]
    groups: Dict[str, List[int]] = {}
    for i, n in enumerate(names):
        groups.setdefault(n, []).append(i)
    return sorted(groups.items())


# ---------------------------------------------------------- device-side stats
def _leaf_stats(x, stacked: bool):
    """(sumsq, count, absmax, nonfinite) for one leaf, reduced over every
    axis but the leading layer dim when ``stacked`` (per-layer vectors)."""
    x = x.astype(jnp.float32)
    axes = tuple(range(1, x.ndim)) if stacked and x.ndim >= 1 else None
    n = 1.0
    shape = x.shape[1:] if axes is not None else x.shape
    for d in shape:
        n *= d
    finite = jnp.isfinite(x)
    safe = jnp.where(finite, x, 0.0)
    return (
        jnp.sum(safe * safe, axis=axes),
        jnp.full((x.shape[0],) if axes is not None else (), n, jnp.float32),
        jnp.max(jnp.abs(safe), axis=axes, initial=0.0),
        jnp.sum((~finite).astype(jnp.float32), axis=axes),
    )


def _dtype_max(dtypes):
    """Smallest finite max across the group's float member dtypes (the
    first dtype to overflow is the margin that matters); f32's if none.
    Pure python scalars — no host cast on a traced value."""
    import ml_dtypes  # bf16/fp8 finfo (numpy's rejects them)
    import numpy as np

    best = None
    for dt in dtypes:
        try:
            m = float(ml_dtypes.finfo(dt).max)
        except ValueError:
            try:
                m = float(np.finfo(dt).max)
            except ValueError:  # int leaf (frozen lookup tables)
                continue
        if best is None or m < best:
            best = m
    return best if best is not None else 3.4028235e38  # f32 max


def tree_health(params, grads, updates, *, max_groups: int = 64,
                eps: float = 1e-12) -> Dict[str, Dict[str, jnp.ndarray]]:
    """Per-group training-health summary, computed on device inside the
    instrumented train step.

    Returns ``{group: {stat: array}}`` where stacked groups carry per-layer
    vectors and flat groups scalars:

    * ``grad_rms`` / ``grad_absmax`` / ``grad_nonfinite`` — over the
      token-normalized, mask-applied, PRE-clip gradients (the clip would
      hide exactly the blow-up magnitude this tier exists to see);
    * ``param_rms`` / ``param_nonfinite``;
    * ``update_ratio`` (update RMS over param RMS — the classic
      learning-health dial) and ``update_nonfinite``;
    * ``overflow_margin_bits`` — ``log2(dtype_max / grad_absmax)``: how many
      magnitude doublings remain before the group's narrowest float dtype
      overflows (a divergence early-warning that moves *before* the NaN).

    RMS/absmax are computed over the finite elements only (non-finite mass
    is reported separately in the ``*_nonfinite`` counts — a single inf must
    not erase the magnitude trend that led to it). Group structure is a
    pure function of the param-tree paths, so the summary traces once per
    program and never retraces steady-state.
    """
    flat = tree_leaves_with_path(params)
    paths = [p for p, _ in flat]
    p_leaves = [x for _, x in flat]
    g_leaves = tree_leaves(grads)
    u_leaves = tree_leaves(updates)
    out: Dict[str, Dict[str, jnp.ndarray]] = {}
    for name, members in build_groups(paths, max_groups):
        # a group mixing stacked and flat members (or mixed layer counts —
        # the ...rest bucket can) degrades to fully-reduced scalars
        stacked = all(_is_stacked(paths[i]) for i in members)
        if stacked:
            lens = {p_leaves[i].shape[0] if p_leaves[i].ndim else 1
                    for i in members}
            stacked = len(lens) == 1
        acc = {}
        for kind, leaves in (("grad", g_leaves), ("param", p_leaves),
                             ("update", u_leaves)):
            sumsq = cnt = absmax = nonfin = None
            for i in members:
                s, c, a, nf = _leaf_stats(leaves[i], stacked)
                sumsq = s if sumsq is None else sumsq + s
                cnt = c if cnt is None else cnt + c
                absmax = a if absmax is None else jnp.maximum(absmax, a)
                nonfin = nf if nonfin is None else nonfin + nf
            acc[kind] = (sumsq, cnt, absmax, nonfin)
        g_sumsq, g_cnt, g_absmax, g_nonfin = acc["grad"]
        p_sumsq, p_cnt, _p_absmax, p_nonfin = acc["param"]
        u_sumsq, u_cnt, _u_absmax, u_nonfin = acc["update"]
        grad_rms = jnp.sqrt(g_sumsq / jnp.maximum(g_cnt, 1.0))
        param_rms = jnp.sqrt(p_sumsq / jnp.maximum(p_cnt, 1.0))
        update_rms = jnp.sqrt(u_sumsq / jnp.maximum(u_cnt, 1.0))
        dmax = _dtype_max([p_leaves[i].dtype for i in members])
        out[name] = {
            "grad_rms": grad_rms,
            "grad_absmax": g_absmax,
            "grad_nonfinite": g_nonfin,
            "param_rms": param_rms,
            "param_nonfinite": p_nonfin,
            "update_ratio": update_rms / (param_rms + eps),
            "update_nonfinite": u_nonfin,
            "overflow_margin_bits": (
                math.log2(dmax) - jnp.log2(jnp.maximum(g_absmax, eps))
            ),
        }
    return out


# ------------------------------------------------------------------ host side
#: per-stat worst-layer reduction the gauges publish for stacked groups
_GAUGE_REDUCE = {
    "grad_rms": max, "grad_absmax": max, "grad_nonfinite": max,
    "param_rms": max, "param_nonfinite": max, "update_ratio": max,
    "update_nonfinite": max,
    # margin: the layer CLOSEST to overflow is the one that matters
    "overflow_margin_bits": min,
}


class NumericsMonitor:
    """Host-side consumer of :func:`tree_health` outputs.

    ``observe`` (the interval cadence) fetches, rings, and publishes
    worst-layer ``numerics.*`` gauges; ``diagnose`` (the supervisor's
    anomaly re-run) additionally builds the non-finite provenance document.
    Thread-safe: the exporter's ``/debug/numerics`` scrapes from its own
    thread."""

    def __init__(self, history: int = 32, registry=None):
        self._lock = threading.Lock()
        self._history: deque = deque(maxlen=max(1, history))  # guarded-by: _lock
        self._registry = registry
        self.last_provenance: Optional[Dict[str, Any]] = None  # guarded-by: _lock
        self.observed_steps = 0  # guarded-by: _lock

    def _reg(self):
        return self._registry or get_registry()

    @staticmethod
    def _to_doc(health) -> Dict[str, Dict[str, Any]]:
        """Device health tree -> plain floats/lists. ONE batched
        ``device_get`` for the whole tree — per-stat fetches would be
        ~groups x stats blocking round trips on every numerics step."""
        import numpy as np

        host = jax.device_get(health)
        doc = {}
        for group, stats in host.items():
            doc[group] = {
                k: (float(v) if np.ndim(v) == 0
                    else np.asarray(v, dtype=np.float64).tolist())
                for k, v in stats.items()
            }
        return doc

    # ------------------------------------------------------------- observation
    def observe(self, step: int, health) -> Dict[str, Dict[str, Any]]:
        """Fetch one interval summary: ring it + publish gauges."""
        doc = self._to_doc(health)
        with self._lock:
            self._history.append({"step": int(step), "groups": doc})
            self.observed_steps += 1
        reg = self._reg()
        for group, stats in doc.items():
            for stat, val in stats.items():
                if isinstance(val, list):
                    val = _GAUGE_REDUCE.get(stat, max)(val) if val else 0.0
                reg.gauge(f"numerics.{group}.{stat}").set(val)
        reg.gauge("numerics.last_step").set(float(step))
        return doc

    @staticmethod
    def first_nonfinite(doc: Dict[str, Dict[str, Any]]
                        ) -> Optional[Dict[str, Any]]:
        """First offending (kind, group[, layer]) in deterministic order:
        param-kind first (upstream of everything), then grad, then update;
        groups in sorted-name order; stacked groups name the first bad
        layer."""
        for kind in PROVENANCE_KINDS:
            for group in sorted(doc):
                nf = doc[group].get(f"{kind}_nonfinite", 0.0)
                vals = nf if isinstance(nf, list) else [nf]
                total = sum(vals)
                if total > 0:
                    out = {"group": group, "kind": kind,
                           "nonfinite_count": float(total)}
                    if isinstance(nf, list):
                        out["layer"] = next(
                            i for i, v in enumerate(vals) if v > 0
                        )
                    return out
        return None

    # --------------------------------------------------------------- diagnosis
    def diagnose(self, step: int, health,
                 injected: bool = False) -> Dict[str, Any]:
        """Build (and retain) the provenance document for an anomalous step
        the supervisor re-ran through the instrumented step."""
        doc = self._to_doc(health)
        first = self.first_nonfinite(doc)
        with self._lock:
            history = list(self._history)
        prov: Dict[str, Any] = {
            "step": int(step),
            "injected": bool(injected),
            "first_nonfinite": first,
            "groups": doc,
            "history": history,
        }
        with self._lock:
            self.last_provenance = prov
        reg = self._reg()
        reg.counter("numerics.diagnoses").inc()
        if first is not None:
            reg.counter("numerics.nonfinite_steps").inc()
            flight_record(
                "numerics.nonfinite", cid=str(step),
                group=first["group"], tensor_kind=first["kind"],
                layer=first.get("layer"),
                count=first["nonfinite_count"],
            )
            logger.warning_rank0(
                "NUMERICS: step %d first non-finite tensor is %s group %r%s "
                "(%d non-finite elements) — provenance retained for the "
                "post-mortem and /debug/numerics",
                step, first["kind"], first["group"],
                f" layer {first['layer']}" if "layer" in first else "",
                int(first["nonfinite_count"]),
            )
        else:
            flight_record("numerics.clean_diagnosis", cid=str(step),
                          injected=injected)
            logger.warning_rank0(
                "NUMERICS: anomalous step %d re-ran clean — no non-finite "
                "tensor in grads/params/updates (host-injected drill, or a "
                "transient the re-run did not reproduce)", step,
            )
        return prov

    # ------------------------------------------------------------------ egress
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            history = list(self._history)
            prov = self.last_provenance
            observed = self.observed_steps
        return {
            "enabled": True,
            "observed_steps": observed,
            "latest": history[-1] if history else None,
            "history": history,
            "provenance": prov,
        }


_ACTIVE: Optional[NumericsMonitor] = None  # guarded-by: _ACTIVE_LOCK
_ACTIVE_LOCK = threading.Lock()


def set_active_monitor(monitor: Optional[NumericsMonitor]
                       ) -> Optional[NumericsMonitor]:
    """Install/uninstall the process's live monitor (the trainer's loop owns
    one per run); returns the previous one."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        prev, _ACTIVE = _ACTIVE, monitor
    return prev


def get_active_monitor() -> Optional[NumericsMonitor]:
    with _ACTIVE_LOCK:
        return _ACTIVE


def debug_numerics_doc() -> Dict[str, Any]:
    """``/debug/numerics`` body: the live monitor's snapshot, or a disabled
    stub naming the knob that turns the tier on."""
    mon = get_active_monitor()
    if mon is None:
        return {"enabled": False,
                "hint": "set train.observability_numerics_interval > 0"}
    return mon.snapshot()


def attach_numerics_extra(extra: Dict[str, Any]) -> None:
    """Fold the provenance/history into a post-mortem ``extra`` payload
    (trainer ``_postmortem_extra``). No-op when the tier is off; must never
    raise — forensics can't mask the original failure."""
    mon = get_active_monitor()
    if mon is None:
        return
    snap = mon.snapshot()
    if snap.get("provenance") or snap.get("latest"):
        extra["numerics"] = {
            "provenance": snap.get("provenance"),
            "history": snap.get("history"),
        }


# ------------------------------------------------------------- chaos drilling
def poison_param_group(params, pattern: str = ""):
    """Overwrite ONE element of the first float param leaf whose dotted path
    contains ``pattern`` (sorted-path order; empty pattern = first float
    leaf) with NaN. The ``step.params`` fault drill: unlike the host-side
    ``step.loss`` observation poison, this plants a REAL non-finite value
    the provenance machinery must find and name.

    Returns ``(poisoned_params, dotted_path)``; ``(params, "")`` when no
    leaf matches."""
    flat = jax.tree_util.tree_leaves_with_path(params)
    target = None
    for path, leaf in sorted(flat, key=lambda kv: _path_str(kv[0])):
        name = _path_str(path)
        if pattern and pattern not in name:
            continue
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            target = name
            break
    if target is None:
        return params, ""

    def _poison(path, leaf):
        if _path_str(path) != target:
            return leaf
        return leaf.at[(0,) * leaf.ndim].set(jnp.nan)

    return jax.tree_util.tree_map_with_path(_poison, params), target
