"""Always-on flight recorder: the last N structured events before a death.

PR 4's metrics/spans make a *live* run attributable; this module covers the
*dead* one. Every hot subsystem appends tiny structured events — trainer
step lifecycle, scheduler admit/preempt, checkpoint commit/quarantine,
supervisor verdicts, retry attempts, fault-injection hits — into one
process-wide bounded ring, and when the run dies (watchdog fire, supervisor
abort, uncaught exception escaping ``train()``, SIGTERM) the ring is dumped
to ``postmortem-<rank>.json`` together with a metrics snapshot, the span
ring tail and every thread's stack: a self-contained artifact answering
"what did the scheduler/checkpointer/data path do in the seconds before?".

Design constraints, in order:

1. **Always on, alloc-light.** :func:`record` with the recorder enabled is
   one tuple + one bounded ``deque.append`` under a lock — no I/O, no clock
   beyond ``perf_counter_ns`` (the same timebase the span tracer uses, so a
   post-mortem's events and spans line up). Disabled (ring size 0) it is a
   single attribute check.
2. **Bounded.** The ring evicts oldest-first; evictions are counted
   (``dropped`` in the dump) so a truncated history is never mistaken for a
   quiet one.
3. **Dump must never make things worse.** :meth:`dump` is exception-proof
   and serializes concurrent triggers (a watchdog thread and a crashing main
   thread may both fire); payload values that aren't JSON-serializable are
   stringified rather than aborting the artifact.

``scripts/postmortem.py`` merges rank-local dumps into one fleet timeline
(each dump carries a wall-clock / perf-counter anchor pair, so monotonic
event timestamps from different processes map onto one wall axis).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from veomni_tpu.utils.logging import _process_index, get_logger

logger = get_logger(__name__)

DEFAULT_MAX_EVENTS = 4096

# span ring entries mirrored into a dump (the full 100k span ring would
# dwarf the artifact; the tail is what the last seconds look like)
_SPAN_TAIL = 2000


class FlightRecorder:
    """Thread-safe bounded ring of ``(ts_ns, kind, cid, payload)`` events."""

    def __init__(self, max_events: int = DEFAULT_MAX_EVENTS):
        self._lock = threading.Lock()
        self._dump_lock = threading.Lock()
        self._events: deque = deque(maxlen=max(0, max_events) or None)  # guarded-by: _lock
        # _enabled is deliberately NOT lock-guarded: record()'s fast path
        # reads it as a latch (one attribute check when disabled) and a
        # torn read merely records/skips one borderline event
        self._enabled = max_events > 0
        self._dropped = 0  # guarded-by: _lock
        self.dump_dir = ""
        self.last_dump_path = ""

    # -------------------------------------------------------------- configure
    def configure(self, max_events: Optional[int] = None,
                  dump_dir: Optional[str] = None,
                  fresh: bool = False) -> None:
        """Resize the ring (0 disables recording AND clears it — a run that
        asked for no event history must not dump a previous same-process
        run's events as its own; existing events are kept up to the new
        bound otherwise) and/or set the default dump directory.

        ``fresh=True`` clears the ring first: a new run's startup (the
        trainer prologue) must not inherit a previous same-process run's
        events — a crash-at-startup dump would attribute them to the new
        run."""
        with self._lock:
            if fresh:
                self._events.clear()
                self._dropped = 0
            if max_events is not None:
                if max_events > 0:
                    if self._events.maxlen != max_events:
                        before = len(self._events)
                        self._events = deque(self._events, maxlen=max_events)
                        # shrinking evicts the oldest entries: count them,
                        # same invariant as a full-ring append
                        self._dropped += before - len(self._events)
                    self._enabled = True
                else:
                    self._enabled = False
                    self._events.clear()
                    self._dropped = 0
            if dump_dir is not None:
                self.dump_dir = dump_dir

    @property
    def enabled(self) -> bool:
        return self._enabled

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._dropped = 0

    # ----------------------------------------------------------------- record
    def record(self, kind: str, cid: str = "", **payload: Any) -> None:
        """Append one event. The record is the only allocation: a 4-tuple
        (plus the payload dict when keyword fields are given)."""
        if not self._enabled:
            return
        ev = (time.perf_counter_ns(), kind, cid, payload or None)
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self._dropped += 1
            self._events.append(ev)

    # ------------------------------------------------------------------ egress
    def events(self, limit: int = 0) -> List[tuple]:
        """Most recent ``limit`` raw event tuples (0 = all), oldest first."""
        with self._lock:
            evs = list(self._events)
        return evs[-limit:] if limit > 0 else evs

    def snapshot(self, limit: int = 200) -> Dict[str, Any]:
        """JSON-ready view for ``/debug/flight``."""
        with self._lock:
            evs = list(self._events)
            dropped = self._dropped  # one locked pass: count matches events
        if limit > 0:
            evs = evs[-limit:]
        return {
            "rank": _process_index(),
            "enabled": self._enabled,
            "dropped": dropped,
            "anchor": _anchor(),
            "events": [_event_doc(ev) for ev in evs],
        }

    # a dump wedged on a dead filesystem (the watchdog abandons its side-
    # thread dumper after its deadline, still inside _dump) must not hold
    # _dump_lock against every LATER dump — the SIGTERM path dumps on the
    # main thread before the final checkpoint, and blocking there forever
    # trades a missing artifact for a hard-killed, non-resumable process
    DUMP_LOCK_TIMEOUT_S = 20.0
    # how many superseded postmortem-<rank>.json artifacts to keep as
    # .1/.2/... next to the canonical (= latest) one
    KEEP_PREVIOUS = 2

    def dump(self, reason: str, path: Optional[str] = None,
             extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
        """Write the post-mortem artifact; returns its path (None on
        failure — dumping is best-effort by contract, a broken disk must not
        mask the original failure)."""
        if not self._dump_lock.acquire(timeout=self.DUMP_LOCK_TIMEOUT_S):
            logger.error(
                "flight-recorder dump (%s) skipped: another dump has held "
                "the lock for %.3gs (hung filesystem?)",
                reason, self.DUMP_LOCK_TIMEOUT_S,
            )
            return None
        try:
            return self._dump(reason, path, extra)
        except Exception as e:  # never make a dying run die harder
            logger.error("flight-recorder dump failed: %s", e)
            return None
        finally:
            self._dump_lock.release()

    def _dump(self, reason: str, path: Optional[str],
              extra: Optional[Dict[str, Any]]) -> str:
        rank = _process_index()
        if path is None:
            path = os.path.join(self.dump_dir or ".", f"postmortem-{rank}.json")
        from veomni_tpu.observability.metrics import get_registry
        from veomni_tpu.observability.spans import live_span_events
        from veomni_tpu.utils.helper import dump_thread_stacks

        with self._lock:
            evs = list(self._events)
            dropped = self._dropped  # one locked pass: count matches events
        doc: Dict[str, Any] = {
            "schema": 1,
            "reason": reason,
            "rank": rank,
            "anchor": _anchor(),
            "dropped": dropped,
            "events": [_event_doc(ev) for ev in evs],
            "metrics": get_registry().export_scalars(),
            "spans": [
                {"name": n, "ts_ns": t0, "dur_ns": d, "tid": tid}
                for n, t0, d, tid in live_span_events(_SPAN_TAIL)
            ],
            "thread_stacks": dump_thread_stacks(),
        }
        if extra:
            for k, v in extra.items():
                if k in doc:  # 'events'/'rank'/'anchor'/... are the artifact
                    logger.warning(
                        "post-mortem extra key %r collides with the dump "
                        "schema; dropped", k,
                    )
                    continue
                doc[k] = v
        # the dump dir may be declared-but-not-created (bench's lazy per-pid
        # default): a missing parent must not cost the artifact
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            # default=str: a payload that smuggled in a non-JSON value must
            # not abort the whole artifact
            json.dump(doc, f, default=str)
        # rotate instead of overwrite: a transient stall's dump at step 1000
        # must survive the SIGTERM dump hours later (the ring has long since
        # rotated past the first incident). Canonical name = latest;
        # .1/.2 = the two before it. Rotation happens only AFTER the new
        # artifact is safely on disk — a full-disk write failure above must
        # not have already demoted a valid canonical artifact.
        if os.path.exists(path):
            for k in range(self.KEEP_PREVIOUS, 1, -1):
                older = f"{path}.{k - 1}"
                if os.path.exists(older):
                    os.replace(older, f"{path}.{k}")
            if self.KEEP_PREVIOUS > 0:
                os.replace(path, f"{path}.1")
        os.replace(tmp, path)
        self.last_dump_path = path
        # a graceful SIGTERM preemption (exit 0, bit-exact resume) is not a
        # failure: ERROR there rings operator alerts on every scheduled stop
        log = logger.warning if reason == "sigterm" else logger.error
        log(
            "flight recorder: wrote post-mortem (%s, %d events, %d dropped) "
            "-> %s", reason, len(doc["events"]), dropped, path,
        )
        return path


def _anchor() -> Dict[str, float]:
    """Paired wall-clock / perf-counter reading: lets a merger map this
    process's monotonic event timestamps onto a shared wall axis."""
    return {"wall_time_s": time.time(), "perf_ns": time.perf_counter_ns()}


def _event_doc(ev: tuple) -> Dict[str, Any]:
    ts_ns, kind, cid, payload = ev
    doc: Dict[str, Any] = {"ts_ns": ts_ns, "kind": kind}
    if cid:
        doc["cid"] = cid
    if payload:
        doc["payload"] = payload
    return doc


_RECORDER = FlightRecorder()


def get_flight_recorder() -> FlightRecorder:
    """The process-wide recorder every subsystem emits into."""
    return _RECORDER


def record(kind: str, cid: str = "", **payload: Any) -> None:
    """Module-level shorthand for ``get_flight_recorder().record(...)``."""
    _RECORDER.record(kind, cid, **payload)


def configure_flight_recorder(max_events: Optional[int] = None,
                              dump_dir: Optional[str] = None,
                              fresh: bool = False) -> None:
    _RECORDER.configure(max_events=max_events, dump_dir=dump_dir, fresh=fresh)


def dump_postmortem(reason: str, path: Optional[str] = None,
                    extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
    """Dump the global recorder (watchdog fire, supervisor abort, uncaught
    exception, SIGTERM all route here). Never raises."""
    return _RECORDER.dump(reason, path=path, extra=extra)
