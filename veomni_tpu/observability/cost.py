"""Compiled-program cost census: what did XLA actually build, per jit site?

PRs 4 and 6 made the *host* attributable (spans, goodput, flight recorder);
this module does the same for the *device*. Every instrumented jit site —
the train/eval steps, the decode prompt buckets, the serving engine's
paged decode/prefill buckets — routes its compiles through
:func:`instrument_jit`, which owns the ahead-of-time ``lower()`` /
``compile()`` pair and records, per compiled program:

* XLA ``cost_analysis()``   — FLOPs and bytes accessed (per device: the
  analysis runs on the SPMD-partitioned module, so a 4-way-sharded step
  reports global/4 — exactly the number MFU-per-chip wants), corrected
  for XLA's count-loop-bodies-once blind spot via the traced jaxpr's
  static ``lax.scan`` trip counts (see the correction block below);
* XLA ``memory_analysis()`` — temp / argument / output / generated-code
  bytes of the optimized executable (how much HBM the *program* needs on
  top of the live buffers);
* compile wall time and invocation counts.

That census turns MFU from an offline ``bench.py`` number into a
continuous per-sync-window gauge: :class:`CostWindow` deltas the census
call counts over the trainer's existing sync cadence and divides achieved
FLOPs/bytes by the window wall and the device peaks
(``utils/device.py::get_device_peak_flops`` /
``get_device_peak_bandwidth``) — the accounting the TPUv4 pjit paper
treats as a first-class training signal (PAPERS.md). Each program also
gets a roofline-style verdict: arithmetic intensity (flops / bytes)
against the machine balance says whether the program is compute- or
bandwidth-bound — i.e. where a kernel PR should even look.

Failure policy: the census must never cost a training step. The AOT path
preserves jit semantics (same lowering, same donation, same shardings);
any surprise — an aval/sharding drift the key missed, a backend without
the analysis APIs — logs one warning, permanently falls back to the plain
jit call for that site, and the run continues census-blind but correct.
``VEOMNI_COST_CENSUS=0`` disables instrumentation entirely.

Registry families (``docs/observability.md``): per program
``cost.{site}.{bucket}.flops`` / ``.bytes_accessed`` / ``.temp_bytes`` /
``.argument_bytes`` / ``.output_bytes`` / ``.compile_s`` gauges and a
``.calls`` counter, plus the aggregate ``cost.programs`` counter and
``cost.compile_s`` histogram. ``/debug/cost`` (exporter) serves the full
census plus a scrape-to-scrape live MFU window.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from veomni_tpu.observability.metrics import MetricsRegistry, get_registry
from veomni_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def census_enabled() -> bool:
    """``VEOMNI_COST_CENSUS=0`` turns :func:`instrument_jit` into identity."""
    return os.environ.get("VEOMNI_COST_CENSUS", "1") not in ("0", "")


def scan_correction_enabled() -> bool:
    """``VEOMNI_COST_CENSUS_SCAN_CORRECT=0`` keeps the raw XLA numbers."""
    return os.environ.get(
        "VEOMNI_COST_CENSUS_SCAN_CORRECT", "1"
    ) not in ("0", "")


# ------------------------------------------------- scan-trip-count correction
#
# XLA's HloCostAnalysis counts a while-loop BODY exactly once, regardless of
# trip count (verified empirically: a 4-iteration lax.scan of a matmul
# reports one matmul's FLOPs). Every model in this repo scans over stacked
# layers — and the train step additionally scans over grad-accum micro
# batches — so the raw census would under-report a 28-layer model ~28x and
# the MFU gauge would be decorative. The correction walks the traced jaxpr:
# for each ``scan`` equation the true cost is ``n x T(body)`` where T
# recurses into nested scans, and bodies are measured with a LOWERED-only
# cost analysis (no XLA compile — tracing cost only, paid once per program
# bucket at census time):
#
#   T(j) = M(j) + sum_scans( n_i * T(body_i) - M(body_i) )
#
# (the ``- M(body_i)`` term removes the one copy XLA already counted).
# ``while_loop``/``cond`` have no static trip count and stay uncorrected.
# Body avals are GLOBAL shapes while the compiled module is per-device, so
# the extra divides by the program's device count — exact for evenly
# partitioned work, the same assumption every MFU formula makes.

_MAX_CORRECTION_BODIES = 64  # runaway-nesting guard; beyond it, keep raw


def _measure_jaxpr(closed) -> Tuple[float, float]:
    """(flops, bytes) of a closed jaxpr via lowered-only cost analysis."""
    import jax

    avals = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in closed.in_avals]
    from jax._src import core as jcore

    d = jax.jit(jcore.jaxpr_as_fun(closed)).lower(*avals).cost_analysis() or {}
    return (float(d.get("flops", 0.0) or 0.0),
            float(d.get("bytes accessed", 0.0) or 0.0))


def _iter_sub_jaxprs(eqn):
    from jax._src import core as jcore

    for v in eqn.params.values():
        if isinstance(v, jcore.ClosedJaxpr):
            yield v
        elif isinstance(getattr(v, "jaxpr", None), jcore.Jaxpr):
            yield v  # e.g. a pjit param already closed


def _contains_scan(jaxpr) -> bool:
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "scan":
            return True
        for sub in _iter_sub_jaxprs(eqn):
            if _contains_scan(sub.jaxpr):
                return True
    return False


def _loop_extras(jaxpr, budget: List[int]) -> Tuple[float, float]:
    """ONE walk over a jaxpr's equations collecting the scan undercount:
    ``n*T(body) - M(body)`` per scan (the ``-M`` removes the copy XLA
    already counted), plus the extras of any scan-containing sub-jaxpr
    (pjit/remat/...) whose body is inlined once."""
    ef = eb = 0.0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "scan":
            body = eqn.params["jaxpr"]
            n = int(eqn.params["length"])
            tf, tb, bmf, bmb = _true_cost(body, budget)
            ef += n * tf - bmf
            eb += n * tb - bmb
        else:
            for sub in _iter_sub_jaxprs(eqn):
                if _contains_scan(sub.jaxpr):
                    tf, tb, smf, smb = _true_cost(sub, budget)
                    ef += tf - smf
                    eb += tb - smb
    return ef, eb


def _true_cost(closed, budget: List[int]) -> Tuple[float, float, float, float]:
    """Recursive (T_flops, T_bytes, M_flops, M_bytes) for a closed jaxpr."""
    budget[0] -= 1
    if budget[0] < 0:
        raise RuntimeError(
            f"scan correction exceeded {_MAX_CORRECTION_BODIES} bodies"
        )
    mf, mb = _measure_jaxpr(closed)
    ef, eb = _loop_extras(closed.jaxpr, budget)
    return mf + ef, mb + eb, mf, mb


def scan_extras(closed) -> Tuple[float, float]:
    """Extra (flops, bytes) the compiled module's analysis missed because
    scan bodies are counted once. Global-shape units."""
    return _loop_extras(closed.jaxpr, [_MAX_CORRECTION_BODIES])


def apply_scan_correction(traced, fields: Dict[str, float],
                          num_devices: int) -> Dict[str, float]:
    """Fold the scan-trip-count extras into an ``analyze_compiled`` dict;
    the raw XLA readings survive as ``xla_flops_raw``/``xla_bytes_raw``.
    Fail-open: any surprise keeps the raw numbers."""
    if not scan_correction_enabled():
        return fields
    try:
        closed = traced.jaxpr
        if not _contains_scan(closed.jaxpr):
            return fields
        ef, eb = scan_extras(closed)
        if ef or eb:
            fields["xla_flops_raw"] = fields["flops"]
            fields["xla_bytes_raw"] = fields["bytes_accessed"]
            fields["flops"] += ef / max(1, num_devices)
            fields["bytes_accessed"] += eb / max(1, num_devices)
    except Exception as e:
        logger.debug("scan correction skipped: %s", e)
    return fields


@dataclass
class ProgramCost:
    """One compiled program's census record (per (site, bucket))."""

    site: str
    bucket: str
    flops: float = 0.0            # per device, scan-trip-count corrected
    bytes_accessed: float = 0.0   # per device, scan-trip-count corrected
    xla_flops_raw: float = 0.0    # as HloCostAnalysis reported (bodies once)
    xla_bytes_raw: float = 0.0
    temp_bytes: float = 0.0
    argument_bytes: float = 0.0
    output_bytes: float = 0.0
    generated_code_bytes: float = 0.0
    comm_bytes: float = 0.0       # per device, collective result payloads
                                  # (observability/comm.py rides the same
                                  # compile; full breakdown lives there)
    compile_time_s: float = 0.0
    num_devices: int = 1
    calls: int = 0                # invocations (all compiles of this bucket)
    traces: int = 0               # distinct compiles recorded here
    _call_counter: Any = field(default=None, repr=False)
    _stamp: int = field(default=0, repr=False)  # recency, see latest()

    @property
    def intensity(self) -> float:
        """Arithmetic intensity in FLOPs/byte (0 when bytes unknown)."""
        return self.flops / self.bytes_accessed if self.bytes_accessed else 0.0

    def bound(self) -> str:
        """Roofline verdict: ``compute`` | ``bandwidth`` | ``comm`` |
        ``unknown`` (no analysis / no backend yet). Compute and HBM stay the
        classic intensity-vs-machine-balance comparison; ``comm`` wins when
        the program's estimated collective time (comm census bytes over the
        ICI peak) exceeds both device-local times — i.e. a kernel PR should
        look at overlap/sharding, not the MXU."""
        if not self.flops or not self.bytes_accessed:
            return "unknown"
        try:
            from veomni_tpu.utils.device import (
                get_device_peak_bandwidth,
                get_device_peak_flops,
            )

            t_compute = self.flops / get_device_peak_flops()
            t_mem = self.bytes_accessed / get_device_peak_bandwidth()
        except Exception:
            return "unknown"
        if self.comm_bytes:
            try:
                from veomni_tpu.utils.device import (
                    get_device_peak_interconnect_bandwidth,
                )

                t_comm = (
                    self.comm_bytes / get_device_peak_interconnect_bandwidth()
                )
                if t_comm > t_compute and t_comm > t_mem:
                    return "comm"
            except Exception:
                pass
        return "compute" if t_compute >= t_mem else "bandwidth"

    def to_doc(self) -> Dict[str, Any]:
        return {
            "site": self.site,
            "bucket": self.bucket,
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "xla_flops_raw": self.xla_flops_raw,
            "xla_bytes_raw": self.xla_bytes_raw,
            "temp_bytes": self.temp_bytes,
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "generated_code_bytes": self.generated_code_bytes,
            "comm_bytes": self.comm_bytes,
            "compile_time_s": self.compile_time_s,
            "num_devices": self.num_devices,
            "calls": self.calls,
            "traces": self.traces,
            "intensity_flops_per_byte": self.intensity,
            "bound": self.bound(),
        }


def _first_dict(analysis) -> Dict[str, float]:
    """``Compiled.cost_analysis()`` returns a dict on some jax versions and
    a one-element list of dicts on others; normalize."""
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else {}
    return analysis or {}


def analyze_compiled(compiled) -> Dict[str, float]:
    """Best-effort extraction of the census fields from a ``Compiled``
    stage. Missing/unimplemented analyses (some backends return ``None``)
    yield zeros rather than raising."""
    out = {
        "flops": 0.0, "bytes_accessed": 0.0, "temp_bytes": 0.0,
        "argument_bytes": 0.0, "output_bytes": 0.0,
        "generated_code_bytes": 0.0,
    }
    try:
        ca = _first_dict(compiled.cost_analysis())
        out["flops"] = max(0.0, float(ca.get("flops", 0.0) or 0.0))
        out["bytes_accessed"] = max(
            0.0, float(ca.get("bytes accessed", 0.0) or 0.0)
        )
    except Exception as e:
        logger.debug("cost_analysis unavailable: %s", e)
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            out["temp_bytes"] = float(
                getattr(ma, "temp_size_in_bytes", 0) or 0
            )
            out["argument_bytes"] = float(
                getattr(ma, "argument_size_in_bytes", 0) or 0
            )
            out["output_bytes"] = float(
                getattr(ma, "output_size_in_bytes", 0) or 0
            )
            out["generated_code_bytes"] = float(
                getattr(ma, "generated_code_size_in_bytes", 0) or 0
            )
    except Exception as e:
        logger.debug("memory_analysis unavailable: %s", e)
    return out


class CostCensus:
    """Thread-safe (site, bucket) -> :class:`ProgramCost` map.

    ``record`` happens once per compile (cold path: it also publishes the
    ``cost.*`` registry families); ``note_call`` is the hot-path accounting
    — one dict lookup plus a counter increment."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self._lock = threading.Lock()
        self._programs: Dict[Tuple[str, str], ProgramCost] = {}
        self._registry = registry
        self._stamp = 0  # bumped per record(); recency for latest()

    def _reg(self) -> MetricsRegistry:
        return self._registry or get_registry()

    # ----------------------------------------------------------------- record
    def record(self, site: str, bucket: str, *, compile_time_s: float = 0.0,
               num_devices: int = 1, **fields: float) -> ProgramCost:
        """Register one compiled program. Re-recording an existing bucket
        (e.g. the same shape re-lowered with different shardings) keeps the
        call count, accumulates compile time, and overwrites the analysis
        with the newest program's."""
        reg = self._reg()
        with self._lock:
            rec = self._programs.get((site, bucket))
            fresh = rec is None
            if fresh:
                rec = ProgramCost(site=site, bucket=bucket)
                self._programs[(site, bucket)] = rec
            for k, v in fields.items():
                if hasattr(rec, k):
                    setattr(rec, k, float(v))
            rec.compile_time_s += float(compile_time_s)
            rec.num_devices = max(1, int(num_devices))
            rec.traces += 1
            self._stamp += 1
            rec._stamp = self._stamp  # recency survives in-place re-records
            if rec._call_counter is None:
                rec._call_counter = reg.counter(
                    f"cost.{site}.{bucket}.calls"
                )
        # registry publication outside the census lock (the registry has its
        # own); gauge names carry the bucket so /metrics shows the full
        # per-program census, bounded by the pow2 bucket discipline
        prefix = f"cost.{site}.{bucket}"
        reg.gauge(f"{prefix}.flops").set(rec.flops)
        reg.gauge(f"{prefix}.bytes_accessed").set(rec.bytes_accessed)
        reg.gauge(f"{prefix}.temp_bytes").set(rec.temp_bytes)
        reg.gauge(f"{prefix}.argument_bytes").set(rec.argument_bytes)
        reg.gauge(f"{prefix}.output_bytes").set(rec.output_bytes)
        reg.gauge(f"{prefix}.compile_s").set(rec.compile_time_s)
        if fresh:  # distinct programs only, per the documented meaning
            reg.counter("cost.programs").inc()
        reg.histogram("cost.compile_s").observe(compile_time_s)
        logger.info_rank0(
            "cost census: %s/%s compiled in %.3gs — %.3g GFLOPs, %.3g MB "
            "accessed, %.3g MB temp (%s-bound)",
            site, bucket, compile_time_s, rec.flops / 1e9,
            rec.bytes_accessed / 1e6, rec.temp_bytes / 1e6, rec.bound(),
        )
        return rec

    def note_call(self, site: str, bucket: str) -> None:
        with self._lock:
            rec = self._programs.get((site, bucket))
            if rec is None:
                return
            rec.calls += 1
            counter = rec._call_counter
        if counter is not None:
            counter.inc()

    # ---------------------------------------------------------------- queries
    def get(self, site: str, bucket: str) -> Optional[ProgramCost]:
        with self._lock:
            return self._programs.get((site, bucket))

    def latest(self, site: str) -> Optional[ProgramCost]:
        """The most recently *recorded* program for a site. Recency is a
        per-record() stamp, not dict insertion order: a sweep that revisits
        an earlier bucket re-records it in place, and mfu_sweep-style
        callers need THAT record, not the last-inserted one."""
        with self._lock:
            out = None
            for (s, _b), rec in self._programs.items():
                if s == site and (out is None or rec._stamp > out._stamp):
                    out = rec
            return out

    def programs(self, site: Optional[str] = None) -> List[ProgramCost]:
        with self._lock:
            return [
                rec for (s, _b), rec in self._programs.items()
                if site is None or s == site
            ]

    def call_counts(self) -> Dict[Tuple[str, str], int]:
        """Per-program invocation counts (the :class:`CostWindow` baseline)."""
        with self._lock:
            return {k: rec.calls for k, rec in self._programs.items()}

    def snapshot(self) -> Dict[str, Any]:
        """JSON-ready census for ``/debug/cost`` and post-mortems."""
        progs = [rec.to_doc() for rec in self.programs()]
        return {
            "programs": progs,
            "totals": {
                "programs": len(progs),
                "compile_time_s": sum(p["compile_time_s"] for p in progs),
                "calls": sum(p["calls"] for p in progs),
            },
        }

    def reset(self) -> None:
        with self._lock:
            self._programs.clear()


_GLOBAL: Optional[CostCensus] = None
_GLOBAL_LOCK = threading.Lock()


def get_cost_census() -> CostCensus:
    """The process-wide census every instrumented jit site records into."""
    global _GLOBAL
    if _GLOBAL is None:
        with _GLOBAL_LOCK:
            if _GLOBAL is None:
                _GLOBAL = CostCensus()
    return _GLOBAL


# --------------------------------------------------------------- window MFU
#: jit sites the MFU window ignores by default: diagnostic programs whose
#: occasional invocations would otherwise inflate the achieved-FLOPs sum of
#: the window they land in (the numerics observatory's instrumented sibling
#: step re-runs a batch the train-step site already counted)
DIAGNOSTIC_SITES: Tuple[str, ...] = ("numerics_step",)


class CostWindow:
    """Census-delta MFU/bandwidth over a wall-clock window.

    ``begin()`` snapshots the per-program call counts; ``end()`` multiplies
    each program's new invocations by its census FLOPs/bytes and divides by
    the elapsed wall and the per-device peaks — the continuous analogue of
    ``bench.py``'s offline ``flops / dt / peak``. Census FLOPs are already
    per device (partitioned module), so no world-size factor appears.
    ``exclude_sites`` (default :data:`DIAGNOSTIC_SITES`) keeps diagnostic
    programs out of the utilization math; an explicit ``sites`` allowlist
    wins over the exclusion."""

    def __init__(self, census: Optional[CostCensus] = None,
                 sites: Optional[Tuple[str, ...]] = None,
                 exclude_sites: Optional[Tuple[str, ...]] = DIAGNOSTIC_SITES):
        self.census = census or get_cost_census()
        self.sites = tuple(sites) if sites else None
        self.exclude_sites = tuple(exclude_sites) if exclude_sites else ()
        self._t0: Optional[float] = None
        self._base: Dict[Tuple[str, str], int] = {}

    def begin(self) -> None:
        self._t0 = time.perf_counter()
        self._base = self.census.call_counts()

    def end(self) -> Dict[str, float]:
        """Close the window -> metric dict; re-arms for the next window."""
        if self._t0 is None:
            self.begin()
            return {}
        now = time.perf_counter()
        wall = max(now - self._t0, 1e-9)
        cur = self.census.call_counts()
        flops = bytes_acc = comm_bytes = 0.0
        ran = 0
        for key, calls in cur.items():
            if self.sites is not None and key[0] not in self.sites:
                continue
            if self.sites is None and key[0] in self.exclude_sites:
                continue
            delta = calls - self._base.get(key, 0)
            if delta <= 0:
                continue
            ran += delta
            rec = self.census.get(*key)
            if rec is not None:
                flops += delta * rec.flops
                bytes_acc += delta * rec.bytes_accessed
                comm_bytes += delta * rec.comm_bytes
        if not ran:
            # no instrumented program ran: re-arm and make no utilization
            # statement (the degenerate train-end window must not overwrite
            # the last real sync window's gauges with zeros)
            self._t0, self._base = now, cur
            return {}
        try:
            from veomni_tpu.utils.device import (
                get_device_peak_bandwidth,
                get_device_peak_flops,
                get_device_peak_interconnect_bandwidth,
            )

            peak_flops = get_device_peak_flops()
            peak_bw = get_device_peak_bandwidth()
            peak_ici = get_device_peak_interconnect_bandwidth()
        except Exception:  # no backend yet: report achieved, not utilization
            peak_flops = peak_bw = peak_ici = float("inf")
        out = {
            "mfu_pct": 100.0 * flops / wall / peak_flops,
            "bandwidth_util_pct": 100.0 * bytes_acc / wall / peak_bw,
            "census_tflops_s": flops / wall / 1e12,
            "census_window_s": wall,
            # estimated share of window wall the programs' collectives would
            # take UNHIDDEN (comm census bytes / peak ICI): an exposure
            # *estimate* reported alongside the goodput split — it overlaps
            # the dispatch/other fractions and is deliberately not part of
            # their sum-to-1 set (observability/comm.py)
            "comm_est_frac": min(1.0, comm_bytes / peak_ici / wall)
            if peak_ici != float("inf") else 0.0,
        }
        self._t0, self._base = now, cur
        return out


_DEBUG_WINDOW: Optional[CostWindow] = None
_DEBUG_LOCK = threading.Lock()


def debug_cost_doc() -> Dict[str, Any]:
    """``/debug/cost`` body: the full census plus a scrape-to-scrape live
    MFU window (the first scrape arms it and reports an empty window)."""
    global _DEBUG_WINDOW
    census = get_cost_census()
    with _DEBUG_LOCK:
        if _DEBUG_WINDOW is None:
            _DEBUG_WINDOW = CostWindow(census)
        live = _DEBUG_WINDOW.end()
    doc = census.snapshot()
    doc["live"] = live
    return doc


# ----------------------------------------------------------- jit instrument
def _leaf_key(x) -> Tuple:
    """Jit-signature component for one dynamic argument leaf: shape/dtype/
    weak-type plus the committed sharding (two calls that jit would compile
    separately must never share a census entry). Kept allocation-light —
    this runs per leaf per call on the serving decode hot path (the param
    trees are layer-stacked, so "per leaf" is tens, not thousands); an
    unhashable sharding surfaces as a TypeError at the cache lookup and
    disables the census for the site (fail open, never fail slow)."""
    shape = getattr(x, "shape", None)
    if shape is None:  # python scalar: jit keys on type, not value
        return ("py", type(x).__name__)
    return (shape, getattr(x, "dtype", None),
            bool(getattr(x, "weak_type", False)),
            getattr(x, "sharding", None))


def _num_devices(leaves) -> int:
    n = 1
    for x in leaves:
        ds = getattr(getattr(x, "sharding", None), "device_set", None)
        if ds:
            n = max(n, len(ds))
    return n


class InstrumentedJit:
    """A jit callable whose compiles flow through the cost census.

    Owns an AOT cache keyed on the same signature jit keys on (dynamic
    avals + shardings + static values): a key miss runs
    ``fn.lower(*args).compile()`` — ONE compile, timed, analyzed, recorded
    — and every hit calls the cached executable directly. Attribute access
    (``.lower``, ``.trace``) falls through to the wrapped jit function, so
    HLO-census tooling (``utils/overlap_evidence.py``) keeps working.

    Any failure in the census path disables it for this site permanently
    and falls back to the plain jit call — census loss is acceptable,
    a broken step is not."""

    def __init__(self, site: str, fn: Callable, *,
                 static_argnums: Tuple[int, ...] = (),
                 bucket_fn: Optional[Callable[[tuple], str]] = None,
                 census: Optional[CostCensus] = None):
        self._site = site
        self._fn = fn
        self._static = tuple(static_argnums)
        self._bucket_fn = bucket_fn
        self._census_ref = census
        self._compiled: Dict[Tuple, Tuple[Any, Tuple[str, str]]] = {}
        self._disabled = False
        self._lock = threading.Lock()

    @property
    def _census(self) -> CostCensus:
        return self._census_ref or get_cost_census()

    def __getattr__(self, name):  # .lower/.trace/.clear_cache/...
        if name.startswith("_"):  # never recurse through our own slots
            raise AttributeError(name)
        return getattr(self._fn, name)

    def _disable(self, why: str, exc: Exception) -> None:
        self._disabled = True
        logger.warning_rank0(
            "cost census disabled for jit site %r (%s: %s: %s) — falling "
            "back to the plain jit path; the run continues census-blind",
            self._site, why, type(exc).__name__, exc,
        )

    def _key(self, args) -> Tuple:
        import jax

        static_vals = tuple(
            (i, args[i]) for i in self._static if i < len(args)
        )
        dyn = tuple(
            a for i, a in enumerate(args) if i not in self._static
        )
        leaves, treedef = jax.tree_util.tree_flatten(dyn)
        return (treedef, tuple(_leaf_key(x) for x in leaves), static_vals)

    def _bucket(self, args) -> str:
        if self._bucket_fn is not None:
            try:
                return str(self._bucket_fn(args))
            except Exception:
                pass
        return f"prog{len(self._compiled)}"

    def __call__(self, *args, **kwargs):
        if self._disabled or kwargs:
            # kwargs never appear at the instrumented call sites; if a new
            # caller passes them, jit semantics win over the census
            return self._fn(*args, **kwargs)
        try:
            key = self._key(args)
            entry = self._compiled.get(key)  # TypeError: unhashable leaf
        except Exception as e:
            self._disable("signature", e)
            return self._fn(*args)
        if entry is None:
            with self._lock:
                entry = self._compiled.get(key)
                if entry is None:
                    import jax

                    try:
                        t0 = time.perf_counter()
                        traced = None
                        try:
                            # trace -> lower -> compile keeps the jaxpr in
                            # hand for the scan-trip-count correction
                            traced = self._fn.trace(*args)
                            lowered = traced.lower()
                        except AttributeError:  # older jax: no .trace
                            lowered = self._fn.lower(*args)
                        compiled = lowered.compile()
                        dt = time.perf_counter() - t0
                    except Exception as e:
                        self._disable("lower/compile", e)
                        return self._fn(*args)
                    bucket = self._bucket(args)
                    dyn_leaves = jax.tree_util.tree_leaves(tuple(
                        a for i, a in enumerate(args)
                        if i not in self._static
                    ))
                    ndev = _num_devices(dyn_leaves)
                    fields = analyze_compiled(compiled)
                    if traced is not None:
                        fields = apply_scan_correction(traced, fields, ndev)
                    # comm observatory (observability/comm.py): parse the
                    # ALREADY-compiled program's HLO for the collective
                    # census — zero extra compiles, fail-open, and the
                    # comm_bytes field rides into this ProgramCost so the
                    # roofline verdict can say "comm"-bound
                    try:
                        from veomni_tpu.observability.comm import (
                            maybe_comm_census,
                        )

                        fields.update(maybe_comm_census(
                            self._site, bucket, compiled, ndev
                        ))
                    except Exception as e:
                        logger.debug("comm census unavailable: %s", e)
                    self._census.record(
                        self._site, bucket,
                        compile_time_s=dt,
                        num_devices=ndev,
                        **fields,
                    )
                    entry = (compiled, (self._site, bucket))
                    self._compiled[key] = entry
        compiled, site_bucket = entry
        self._census.note_call(*site_bucket)
        dyn = tuple(a for i, a in enumerate(args) if i not in self._static)
        try:
            return compiled(*dyn)
        except TypeError as e:
            # aval/pytree mismatch the key missed (jit would have silently
            # recompiled): fall back for good rather than guess
            self._disable("compiled call", e)
            return self._fn(*args)


def instrument_jit(site: str, fn: Callable, *,
                   static_argnums: Tuple[int, ...] = (),
                   bucket_fn: Optional[Callable[[tuple], str]] = None,
                   census: Optional[CostCensus] = None) -> Callable:
    """Wrap a jitted callable so its compiles land in the cost census.
    Identity when ``VEOMNI_COST_CENSUS=0``."""
    if not census_enabled():
        return fn
    return InstrumentedJit(
        site, fn, static_argnums=static_argnums, bucket_fn=bucket_fn,
        census=census,
    )
