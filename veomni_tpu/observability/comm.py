"""Live collective census: what does each compiled program *communicate*?

Fourth observability tier. PR 10's cost census made the device's compute
and memory visible per compiled program; communication stayed dark — the
collective/overlap analysis existed only as an offline artifact script
(``scripts/overlap_evidence.py``), goodput lumped exposed comm time into
``dispatch+other``, and GSPMD decides the collectives behind our backs
(T3, PAPERS.md: overlap must be *tracked*, not assumed). This module
promotes the PR 1 HLO census (``utils/overlap_evidence.py``) to a live
per-program record, piggybacking on the cost census's owned AOT
``lower()``/``compile()`` pair — the HLO text of the already-compiled
program is parsed once per compile, no extra compiles, zero hot-path cost.

Per (site, bucket) — the same keys as the cost census:

* **bytes by collective kind** (all-reduce / all-gather / reduce-scatter /
  all-to-all / collective-permute), from each collective instruction's
  result shape in the optimized (SPMD-partitioned, hence per-device) HLO;
* **predicted comm time** — total collective bytes over
  ``utils/device.py::get_device_peak_interconnect_bandwidth`` (an
  order-of-magnitude link-budget estimate, not measured goodput);
* **overlappable vs serialized** collective counts from the PR 1
  dependency census (:func:`overlap_report`): collectives with at least
  one independent compute partner are *overlappable* — the latency-hiding
  scheduler can hide them; the rest are *serialized* and their predicted
  time is exposed step time.

The record rides into the cost census too (``ProgramCost.comm_bytes``), so
the roofline verdict extends to ``comm``-bound and the per-window
``CostWindow`` reports ``comm_est_frac`` — the estimated share of window
wall the program's collectives would take unhidden. ``VEOMNI_COMM_CENSUS=0``
disables the analysis (the cost census keeps running).

Registry families (``docs/observability.md``):
``comm.{site}.{bucket}.bytes_{kind}`` / ``.comm_bytes`` /
``.comm_time_est_s`` / ``.collectives`` / ``.overlappable`` /
``.serialized`` / ``.pairs`` gauges, plus the aggregate ``comm.programs``
counter. ``/debug/fleet`` (exporter) carries the census snapshot next to
the per-rank skew view (``observability/fleet.py``).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from veomni_tpu.observability.metrics import MetricsRegistry, get_registry
from veomni_tpu.utils.logging import get_logger
# stdlib-only module, safe at import time; the SAME tuple drives the byte
# census and the per-kind gauge loop — a hand copy could drift and leave a
# new kind's bytes inside comm_bytes with no per-kind gauge ever published
from veomni_tpu.utils.overlap_evidence import ALL_COLLECTIVES as COMM_KINDS

logger = get_logger(__name__)


def comm_census_enabled() -> bool:
    """``VEOMNI_COMM_CENSUS=0`` keeps compiles comm-census-free (the cost
    census itself stays governed by ``VEOMNI_COST_CENSUS``)."""
    return os.environ.get("VEOMNI_COMM_CENSUS", "1") not in ("0", "")


def _gauge_kind(kind: str) -> str:
    return kind.replace("-", "_")


@dataclass
class CommCost:
    """One compiled program's communication record (per (site, bucket))."""

    site: str
    bucket: str
    bytes_by_kind: Dict[str, float] = field(default_factory=dict)
    counts_by_kind: Dict[str, int] = field(default_factory=dict)
    comm_bytes: float = 0.0       # per device (SPMD-partitioned module)
    comm_time_est_s: float = 0.0  # comm_bytes / peak ICI (estimate)
    collectives: int = 0          # tracked-kind collective instructions
    overlappable: int = 0         # ...with >= 1 independent compute partner
    serialized: int = 0           # ...with none (exposed comm)
    pairs: int = 0                # independent (collective, compute) pairs
    num_devices: int = 1

    def to_doc(self) -> Dict[str, Any]:
        return {
            "site": self.site,
            "bucket": self.bucket,
            "bytes_by_kind": dict(self.bytes_by_kind),
            "counts_by_kind": dict(self.counts_by_kind),
            "comm_bytes": self.comm_bytes,
            "comm_time_est_s": self.comm_time_est_s,
            "collectives": self.collectives,
            "overlappable": self.overlappable,
            "serialized": self.serialized,
            "pairs": self.pairs,
            "num_devices": self.num_devices,
        }


def analyze_hlo_comm(hlo_text: str) -> Dict[str, Any]:
    """Byte + dependency census over one HLO module's text (pure parsing,
    backend-free). Returns the :class:`CommCost` field dict."""
    from veomni_tpu.utils.overlap_evidence import (
        ALL_COLLECTIVES,
        collective_bytes_census,
        overlap_report,
    )

    bc = collective_bytes_census(hlo_text)
    bytes_by_kind = {k: v["bytes"] for k, v in bc.items()}
    counts_by_kind = {k: int(v["count"]) for k, v in bc.items()}
    total = sum(bytes_by_kind.values())
    # dependency census over ALL tracked kinds (the offline script's default
    # was the async-lowering subset; for exposure accounting every kind
    # GSPMD inserted matters)
    rep = overlap_report(hlo_text, collective_ops=ALL_COLLECTIVES)
    return {
        "bytes_by_kind": bytes_by_kind,
        "counts_by_kind": counts_by_kind,
        "comm_bytes": total,
        "collectives": rep.collectives,
        "overlappable": rep.overlappable,
        "serialized": max(0, rep.collectives - rep.overlappable),
        "pairs": rep.pairs,
    }


class CommCensus:
    """Thread-safe (site, bucket) -> :class:`CommCost` map; records happen
    once per compile (cold path) and publish the ``comm.*`` gauges."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self._lock = threading.Lock()
        self._programs: Dict[Tuple[str, str], CommCost] = {}
        self._registry = registry

    def _reg(self) -> MetricsRegistry:
        return self._registry or get_registry()

    def record(self, site: str, bucket: str, *, num_devices: int = 1,
               **fields: Any) -> CommCost:
        reg = self._reg()
        with self._lock:
            rec = self._programs.get((site, bucket))
            fresh = rec is None
            if fresh:
                rec = CommCost(site=site, bucket=bucket)
                self._programs[(site, bucket)] = rec
            for k, v in fields.items():
                if hasattr(rec, k):
                    setattr(rec, k, v)
            rec.num_devices = max(1, int(num_devices))
            try:
                from veomni_tpu.utils.device import (
                    get_device_peak_interconnect_bandwidth,
                )

                rec.comm_time_est_s = (
                    rec.comm_bytes / get_device_peak_interconnect_bandwidth()
                )
            except Exception:  # no backend yet: bytes stand alone
                rec.comm_time_est_s = 0.0
        # registry publication outside the census lock (same discipline as
        # the cost census); bucket-carrying names stay bounded by the pow2
        # bucket discipline of the instrumented sites. Names spell the
        # "comm." family literally so the doc-drift gate's call-site scan
        # (tests/test_flight_recorder.py) sees it.
        for kind in COMM_KINDS:
            reg.gauge(
                f"comm.{site}.{bucket}.bytes_{_gauge_kind(kind)}"
            ).set(rec.bytes_by_kind.get(kind, 0.0))
        reg.gauge(f"comm.{site}.{bucket}.comm_bytes").set(rec.comm_bytes)
        reg.gauge(f"comm.{site}.{bucket}.comm_time_est_s").set(
            rec.comm_time_est_s
        )
        reg.gauge(f"comm.{site}.{bucket}.collectives").set(rec.collectives)
        reg.gauge(f"comm.{site}.{bucket}.overlappable").set(rec.overlappable)
        reg.gauge(f"comm.{site}.{bucket}.serialized").set(rec.serialized)
        reg.gauge(f"comm.{site}.{bucket}.pairs").set(rec.pairs)
        if fresh:
            reg.counter("comm.programs").inc()
        if rec.collectives:
            logger.info_rank0(
                "comm census: %s/%s — %d collectives (%d overlappable, "
                "%d serialized), %.3g MB/device, est %.3g ms at peak ICI",
                site, bucket, rec.collectives, rec.overlappable,
                rec.serialized, rec.comm_bytes / 1e6,
                rec.comm_time_est_s * 1e3,
            )
        return rec

    def get(self, site: str, bucket: str) -> Optional[CommCost]:
        with self._lock:
            return self._programs.get((site, bucket))

    def programs(self, site: Optional[str] = None) -> List[CommCost]:
        with self._lock:
            return [
                rec for (s, _b), rec in self._programs.items()
                if site is None or s == site
            ]

    def snapshot(self) -> Dict[str, Any]:
        progs = [rec.to_doc() for rec in self.programs()]
        return {
            "programs": progs,
            "totals": {
                "programs": len(progs),
                "comm_bytes": sum(p["comm_bytes"] for p in progs),
                "serialized": sum(p["serialized"] for p in progs),
            },
        }

    def reset(self) -> None:
        with self._lock:
            self._programs.clear()


_GLOBAL: Optional[CommCensus] = None
_GLOBAL_LOCK = threading.Lock()


def get_comm_census() -> CommCensus:
    """The process-wide comm census the instrumented jit sites record into."""
    global _GLOBAL
    if _GLOBAL is None:
        with _GLOBAL_LOCK:
            if _GLOBAL is None:
                _GLOBAL = CommCensus()
    return _GLOBAL


def _compiled_text(compiled) -> str:
    texts = compiled.as_text()
    if isinstance(texts, (list, tuple)):
        return "\n".join(texts)
    return texts or ""


def maybe_comm_census(site: str, bucket: str, compiled,
                      num_devices: int) -> Dict[str, float]:
    """Comm-census hook for ``cost.InstrumentedJit``'s compile branch: parse
    the already-compiled program's HLO (no extra compile), record the
    :class:`CommCost`, and return the fields the cost census folds into its
    own :class:`ProgramCost` (``comm_bytes`` — the roofline/window input).
    Fail-open: any surprise returns ``{}`` and the compile proceeds
    comm-census-blind."""
    if not comm_census_enabled():
        return {}
    try:
        text = _compiled_text(compiled)
        if not text:
            return {}
        fields = analyze_hlo_comm(text)
        rec = get_comm_census().record(
            site, bucket, num_devices=num_devices, **fields
        )
        return {"comm_bytes": rec.comm_bytes}
    except Exception as e:
        logger.debug("comm census skipped for %s/%s: %s", site, bucket, e)
        return {}
