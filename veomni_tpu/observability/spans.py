"""Host-side span tracing: ``with span("data.wait"): ...``.

Design constraints, in order:

1. **Disabled ≈ free.** ``span()`` with tracing off returns one shared
   no-op context manager — no allocation, no clock read, no dict lookup.
   The trainer can leave call sites in the hot loop unconditionally.
2. **Always feeds histograms when enabled.** Every span exit observes its
   duration into the registry histogram ``span.<name>`` — that is what the
   goodput decomposition deltas, so spans are useful with zero extra
   machinery running.
3. **Mirrors into the device profiler only when one is active.** When
   ``jax.profiler`` has a trace running (``set_profiler_active(True)``, set
   by ``ProfileCallback``), each span also opens a
   ``jax.profiler.TraceAnnotation`` so host phases line up with device ops
   in the heavyweight trace — but the heavyweight path is never *required*.
4. **Self-serve chrome traces.** Completed spans land in a bounded ring
   buffer; :func:`dump_chrome_trace` writes chrome-trace JSON (gzip by
   extension) that ``scripts/merge_chrome_trace.py`` merges across hosts —
   a stall timeline without ever starting the profiler.
"""

from __future__ import annotations

import gzip
import json
import threading
import time
from collections import deque
from typing import Optional

from veomni_tpu.observability.metrics import get_registry
from veomni_tpu.utils.logging import _process_index, get_logger

logger = get_logger(__name__)

_enabled = False
_profiler_active = False
_epoch_ns: Optional[int] = None
# (name, t0_ns, dur_ns, tid) ring
_events: deque = deque(maxlen=100_000)  # guarded-by: _ring_lock
# ring evictions: a chrome trace missing its head is truncated,
_dropped = 0  # guarded-by: _ring_lock
# not short — say so once (rank 0) + count forever
_warned_dropped = False  # guarded-by: _ring_lock
# serializes the full-ring check + append + drop accounting: spans exit on
# several threads (prefetch worker, commit thread), and an unlocked
# check-then-act would undercount evictions right at the full boundary
_ring_lock = threading.Lock()
_tid_lock = threading.Lock()
# thread ident -> small stable int
_tids: dict = {}  # guarded-by: _tid_lock


class _NullSpan:
    """Shared do-nothing context manager for the disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


def _tid() -> int:
    ident = threading.get_ident()
    t = _tids.get(ident)
    if t is None:
        with _tid_lock:
            t = _tids.setdefault(ident, len(_tids))
    return t


class _Span:
    __slots__ = ("name", "_t0", "_annot")

    def __init__(self, name: str):
        self.name = name
        self._annot = None

    def __enter__(self):
        if _profiler_active:
            import jax.profiler

            self._annot = jax.profiler.TraceAnnotation(self.name)
            self._annot.__enter__()
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        dur_ns = time.perf_counter_ns() - self._t0
        if self._annot is not None:
            self._annot.__exit__(*exc)
            self._annot = None
        get_registry().histogram(f"span.{self.name}").observe(dur_ns * 1e-9)
        ev = (self.name, self._t0, dur_ns, _tid())
        global _dropped, _warned_dropped
        evicted = warn = False
        cap = 0
        with _ring_lock:
            cap = _events.maxlen
            if len(_events) == cap:
                # once full (steady state on a long run) EVERY exit evicts:
                # only the bookkeeping ints live under the lock — registry
                # lookup and logging happen after release so concurrent
                # span exits don't serialize behind I/O
                _dropped += 1
                evicted = True
                if not _warned_dropped:
                    _warned_dropped = True
                    warn = True
            _events.append(ev)
        if evicted:
            _note_dropped(1, warn, cap)
        return False


def _note_dropped(n: int, warn: bool, cap: int) -> None:
    """``n`` events were just evicted (full-ring append, or a shrink via
    ``enable_spans``); the caller already bumped ``_dropped``, claimed the
    one-time warning, and read the ring capacity ``cap`` under
    ``_ring_lock``. This mirrors the loss into the ``span.dropped`` counter
    and warns ONCE (rank 0) — without this a truncated chrome trace reads
    as a short run, not a long one missing its head. Deliberately called
    OUTSIDE the ring lock (registry + logging I/O must not serialize
    concurrent span exits), which is why the capacity is passed in instead
    of read from the guarded ring here."""
    get_registry().counter("span.dropped").inc(n)
    if warn:
        logger.warning_rank0(
            "span ring buffer full (%d events): oldest spans are being "
            "dropped — a chrome-trace dump will be missing its HEAD, not its "
            "tail. Raise enable_spans(max_events=...) or dump earlier; "
            "`span.dropped` counts the loss from here on.",
            cap,
        )


def span(name: str):
    """Time a host phase. Returns the shared no-op when tracing is off."""
    return _Span(name) if _enabled else _NULL


def dropped_events() -> int:
    """Span-ring evictions so far (mirrors the ``span.dropped`` counter)."""
    with _ring_lock:
        return _dropped


def chrome_epoch_ns() -> Optional[int]:
    """The ts=0 anchor of span chrome traces (None until first enable).
    Other chrome exporters (request_trace) offset against this so their
    tracks line up with the span tracks in one viewer."""
    return _epoch_ns


def live_span_events(limit: int = 0):
    """Most recent ``limit`` raw span tuples ``(name, t0_ns, dur_ns, tid)``
    (0 = all). The flight recorder embeds this tail in post-mortems so host
    phases and recorder events share one timebase."""
    with _ring_lock:  # a concurrent span exit mutates the deque mid-list()
        evs = list(_events)
    return evs[-limit:] if limit > 0 else evs


def spans_enabled() -> bool:
    return _enabled


def enable_spans(max_events: int = 100_000) -> None:
    """Turn tracing on; resizes the event ring if ``max_events`` changed.
    The chrome-trace epoch is pinned on first enable so ts offsets stay
    comparable across enable/disable cycles in one process."""
    global _enabled, _epoch_ns, _events, _dropped, _warned_dropped
    if _epoch_ns is None:
        _epoch_ns = time.perf_counter_ns()
    warn = False
    evicted = 0
    with _ring_lock:
        if _events.maxlen != max_events:
            before = len(_events)
            _events = deque(_events, maxlen=max_events)
            # shrinking evicts the oldest entries: count them, same
            # invariant as a full-ring append
            evicted = before - len(_events)
            if evicted:
                _dropped += evicted
                if not _warned_dropped:
                    _warned_dropped = True
                    warn = True
    if evicted:
        _note_dropped(evicted, warn, max_events)
    _enabled = True


def disable_spans() -> None:
    global _enabled
    _enabled = False


def set_profiler_active(active: bool) -> None:
    """ProfileCallback toggles this around start/stop_trace so spans mirror
    into ``jax.profiler.TraceAnnotation`` exactly while a trace runs."""
    global _profiler_active
    _profiler_active = bool(active)


def clear_events() -> None:
    global _dropped, _warned_dropped
    with _ring_lock:
        _events.clear()
        _dropped = 0
        _warned_dropped = False


def dump_chrome_trace(path: str) -> int:
    """Write the span ring buffer as chrome-trace JSON ("X" complete
    events, µs timebase; pid = process rank so multi-host merges group
    naturally). Returns the number of span events written."""
    epoch = _epoch_ns if _epoch_ns is not None else time.perf_counter_ns()
    rank = _process_index()
    with _ring_lock:  # a concurrent span exit mutates the deque mid-list()
        events = list(_events)
        dropped = _dropped  # same locked pass: count matches the snapshot
    trace = [{
        "name": "process_name", "ph": "M", "pid": rank, "tid": 0,
        # dropped rides along so a viewer of a truncated trace can see HOW
        # truncated (satellite of the one-time warning above)
        "args": {"name": f"veomni host spans (rank {rank})",
                 "dropped_events": dropped},
    }]
    with _tid_lock:  # a thread registering its first span mutates the dict
        tids = sorted(_tids.items(), key=lambda kv: kv[1])
    for ident, t in tids:
        trace.append({
            "name": "thread_name", "ph": "M", "pid": rank, "tid": t,
            "args": {"name": f"thread-{ident}"},
        })
    for name, t0_ns, dur_ns, tid in events:
        trace.append({
            "name": name, "cat": "host", "ph": "X", "pid": rank, "tid": tid,
            "ts": (t0_ns - epoch) / 1e3, "dur": dur_ns / 1e3,
        })
    payload = {"traceEvents": trace, "displayTimeUnit": "ms"}
    if path.endswith(".gz"):
        with gzip.open(path, "wt") as f:
            json.dump(payload, f)
    else:
        with open(path, "w") as f:
            json.dump(payload, f)
    return len(events)
