"""Stdlib-only Prometheus exporter + health and debug endpoints.

One daemon thread, zero dependencies: ``/metrics`` renders the registry in
Prometheus text exposition format 0.0.4; ``/healthz`` serves a JSON health
document (the trainer wires it to the resilience supervisor's state — a
scraper or k8s probe sees rollbacks/aborts without log scraping);
``/debug/flight`` returns the flight recorder's recent events (``?n=``
bounds the tail); ``/debug/requests`` the serving engine's in-flight
request timelines (``requests_fn``); ``/debug/memory`` the live buffer
census + HBM watermark (plus the KV pool capacity document when
``memory_fn`` is wired — ``scripts/serve.py`` passes the engine's
``kv_capacity``); ``/debug/cost`` the compiled-program cost census
with a scrape-to-scrape live MFU window; ``/debug/numerics`` the
numerics observatory's latest per-group training-health summary,
history ring and non-finite provenance (the process's active
``NumericsMonitor``; a disabled stub names the knob); and
``/debug/fleet`` the
cross-rank view (per-rank step-time skew table, heartbeat freshness,
collective census — ``fleet_fn`` or the process's active
``FleetMonitor``); and ``/debug/router`` the scale-out router's replica
census (per-replica state/queue/assignment, retired replicas, weights
version — ``router_fn``, wired by ``scripts/serve.py --replicas N``; an
unwired deployment reports an empty document). Usable by both the trainer
(``train.observability_port`` / ``VEOMNI_METRICS_PORT``) and
``serving.InferenceEngine`` (``scripts/serve.py``).
"""

from __future__ import annotations

import json
import os
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from veomni_tpu.observability.metrics import (
    SLO_BUCKET_BOUNDS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
)
from veomni_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(name: str) -> str:
    return "veomni_" + _NAME_RE.sub("_", name)


#: families additionally rendered as NATIVE Prometheus histograms
#: (`<name>_hist_bucket{le=...}`): the serving latency SLOs need
#: `histogram_quantile(0.99, rate(..._hist_bucket[5m]))` to work in
#: PromQL — the summary's fixed p50/p95 quantiles can't answer a p99
#: query. Covers TTFT, TPOT and queue wait (the third serving-SLO family:
#: queue wait is the signal the QoS layer's shedding/deadline decisions
#: act on). Rendered under a `_hist` sibling name because one metric name
#: cannot be both TYPE summary and TYPE histogram. The bounds table lives
#: in metrics.py (SLO_BUCKET_BOUNDS) so the registry attaches EXACT
#: per-bucket counters at observe() time — rate() over these series needs
#: monotone counters, which a reservoir estimate cannot promise across
#: scrapes. The bounds themselves (LATENCY_BUCKETS) live in metrics.py.
NATIVE_HISTOGRAM_FAMILIES = SLO_BUCKET_BOUNDS


def render_prometheus(registry: Optional[MetricsRegistry] = None) -> str:
    """Registry -> Prometheus text format. Counters/gauges map directly;
    histograms render as summaries (quantile labels + _sum/_count) plus a
    ``_max`` gauge (p100 is the stall-hunting number quantiles smear); the
    families in :data:`NATIVE_HISTOGRAM_FAMILIES` additionally render as
    native cumulative-bucket histograms for PromQL quantile queries."""
    reg = registry or get_registry()
    rank = str(reg.rank())
    lines = []
    for name, m in reg.items_snapshot():
        pname = _prom_name(name)
        if isinstance(m, Counter):
            lines.append(f"# TYPE {pname} counter")
            lines.append(f'{pname}{{rank="{rank}"}} {m.value}')
        elif isinstance(m, Gauge):
            lines.append(f"# TYPE {pname} gauge")
            lines.append(f'{pname}{{rank="{rank}"}} {m.value}')
        elif isinstance(m, Histogram):
            snap = m.snapshot()
            lines.append(f"# TYPE {pname} summary")
            for q, key in (("0.5", "p50"), ("0.95", "p95")):
                if key in snap:
                    lines.append(
                        f'{pname}{{rank="{rank}",quantile="{q}"}} {snap[key]}'
                    )
            lines.append(f'{pname}_sum{{rank="{rank}"}} {snap["sum"]}')
            lines.append(f'{pname}_count{{rank="{rank}"}} {int(snap["count"])}')
            if "max" in snap:
                lines.append(f"# TYPE {pname}_max gauge")
                lines.append(f'{pname}_max{{rank="{rank}"}} {snap["max"]}')
            bounds = NATIVE_HISTOGRAM_FAMILIES.get(name)
            if bounds is not None:
                hname = f"{pname}_hist"
                lines.append(f"# TYPE {hname} histogram")
                for le, count in m.cumulative_buckets(bounds):
                    le_txt = le if le == "+Inf" else repr(float(le))
                    lines.append(
                        f'{hname}_bucket{{rank="{rank}",le="{le_txt}"}} '
                        f"{count}"
                    )
                lines.append(f'{hname}_sum{{rank="{rank}"}} {snap["sum"]}')
                lines.append(
                    f'{hname}_count{{rank="{rank}"}} {int(snap["count"])}'
                )
    return "\n".join(lines) + "\n"


class MetricsExporter:
    """Daemon-thread HTTP server for ``/metrics`` and ``/healthz``.

    ``port=0`` binds an ephemeral port (tests); :meth:`start` returns the
    actual port. ``health_fn`` returns a JSON-serializable dict; a falsy
    ``"healthy"`` key turns the response into a 503 so load balancers and
    probes need no body parsing. The 503 must be *recoverable*: callers
    wire live state, not a latched flag — the serving router's
    ``health()`` (scripts/serve.py) flips unhealthy while its live
    replica count sits under ``min_live`` and back to 200 once respawned
    replicas clear probation, so a probe watching this endpoint sees the
    self-healing cycle, not a tombstone."""

    def __init__(self, port: int = 0, host: str = "0.0.0.0",
                 registry: Optional[MetricsRegistry] = None,
                 health_fn: Optional[Callable[[], Dict]] = None,
                 requests_fn: Optional[Callable[[], Dict]] = None,
                 memory_fn: Optional[Callable[[], Dict]] = None,
                 fleet_fn: Optional[Callable[[], Dict]] = None,
                 router_fn: Optional[Callable[[], Dict]] = None):
        self.requested_port = port
        self.host = host
        self.registry = registry  # None -> resolve the global lazily
        self.health_fn = health_fn
        # serving wires RequestTracer.snapshot here; the trainer leaves it
        # None and /debug/requests reports an empty document
        self.requests_fn = requests_fn
        # serving wires InferenceEngine.kv_capacity here; /debug/memory
        # serves the buffer census either way
        self.memory_fn = memory_fn
        # the trainer wires FleetMonitor.debug_doc; unwired, /debug/fleet
        # falls back to the process's active monitor (fleet.debug_fleet_doc)
        self.fleet_fn = fleet_fn
        # scale-out serving wires Router.debug_doc; unwired, /debug/router
        # reports an empty replica census (single-engine deployment)
        self.router_fn = router_fn
        self.port: Optional[int] = None
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        if self._server is not None:
            return self.port
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # no per-scrape stderr spam
                pass

            def _send(self, code: int, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                try:
                    route, _, query = self.path.partition("?")
                    if route == "/metrics":
                        body = render_prometheus(exporter.registry).encode()
                        self._send(200, body,
                                   "text/plain; version=0.0.4; charset=utf-8")
                    elif route == "/healthz":
                        doc = {"healthy": True}
                        if exporter.health_fn is not None:
                            doc = dict(exporter.health_fn())
                        code = 200 if doc.get("healthy", True) else 503
                        self._send(code, json.dumps(doc).encode(),
                                   "application/json")
                    elif route == "/debug/flight":
                        from veomni_tpu.observability.flight_recorder import (
                            get_flight_recorder,
                        )

                        limit = 200
                        for part in query.split("&"):
                            if part.startswith("n="):
                                try:  # a typo'd ?n= must not read as a 500
                                    # 0 = the whole ring, same convention as
                                    # FlightRecorder.events(limit=0)
                                    limit = max(0, int(part[2:]))
                                except ValueError:
                                    pass
                        doc = get_flight_recorder().snapshot(limit)
                        self._send(200, json.dumps(doc, default=str).encode(),
                                   "application/json")
                    elif route == "/debug/requests":
                        doc = {"inflight": [], "finished": []}
                        if exporter.requests_fn is not None:
                            doc = dict(exporter.requests_fn())
                        self._send(200, json.dumps(doc, default=str).encode(),
                                   "application/json")
                    elif route == "/debug/memory":
                        from veomni_tpu.observability.devmem import (
                            debug_memory_doc,
                        )

                        top_k = 10
                        for part in query.split("&"):
                            if part.startswith("k="):
                                try:  # a typo'd ?k= must not read as a 500
                                    top_k = max(0, int(part[2:]))
                                except ValueError:
                                    pass
                        doc = debug_memory_doc(exporter.memory_fn,
                                               top_k=top_k)
                        self._send(200, json.dumps(doc, default=str).encode(),
                                   "application/json")
                    elif route == "/debug/cost":
                        from veomni_tpu.observability.cost import (
                            debug_cost_doc,
                        )

                        doc = debug_cost_doc()
                        self._send(200, json.dumps(doc, default=str).encode(),
                                   "application/json")
                    elif route == "/debug/numerics":
                        from veomni_tpu.observability.numerics import (
                            debug_numerics_doc,
                        )

                        doc = debug_numerics_doc()
                        self._send(200, json.dumps(doc, default=str).encode(),
                                   "application/json")
                    elif route == "/debug/fleet":
                        if exporter.fleet_fn is not None:
                            doc = dict(exporter.fleet_fn())
                        else:
                            from veomni_tpu.observability.fleet import (
                                debug_fleet_doc,
                            )

                            doc = debug_fleet_doc()
                        self._send(200, json.dumps(doc, default=str).encode(),
                                   "application/json")
                    elif route == "/debug/router":
                        doc = {"replicas": [], "retired": []}
                        if exporter.router_fn is not None:
                            doc = dict(exporter.router_fn())
                        self._send(200, json.dumps(doc, default=str).encode(),
                                   "application/json")
                    else:
                        self._send(404, b"not found", "text/plain")
                except Exception as e:  # a broken scrape must not kill us
                    try:
                        self._send(500, str(e).encode(), "text/plain")
                    except Exception:
                        pass

        self._server = ThreadingHTTPServer((self.host, self.requested_port),
                                           Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="veomni-metrics-http",
            daemon=True,
        )
        self._thread.start()
        logger.info_rank0(
            "metrics exporter serving /metrics and /healthz on %s:%d",
            self.host, self.port,
        )
        return self.port

    def stop(self) -> None:
        server, self._server = self._server, None
        if server is not None:
            server.shutdown()
            server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


def resolve_port(config_port: int = 0) -> Optional[int]:
    """Effective exporter port: ``VEOMNI_METRICS_PORT`` overrides the config
    knob. ``0``/unset disables; negative means "ephemeral" (tests)."""
    raw = os.environ.get("VEOMNI_METRICS_PORT", "").strip()
    port = int(raw) if raw else config_port
    if port == 0:
        return None
    return max(port, 0)  # negative -> 0 -> ephemeral bind


def maybe_start_from_env(registry: Optional[MetricsRegistry] = None,
                         health_fn: Optional[Callable[[], Dict]] = None,
                         config_port: int = 0,
                         requests_fn: Optional[Callable[[], Dict]] = None,
                         memory_fn: Optional[Callable[[], Dict]] = None,
                         fleet_fn: Optional[Callable[[], Dict]] = None,
                         router_fn: Optional[Callable[[], Dict]] = None,
                         ) -> Optional[MetricsExporter]:
    """Start an exporter iff configured; returns it (caller owns stop())."""
    port = resolve_port(config_port)
    if port is None:
        return None
    exp = MetricsExporter(port=port, registry=registry, health_fn=health_fn,
                          requests_fn=requests_fn, memory_fn=memory_fn,
                          fleet_fn=fleet_fn, router_fn=router_fn)
    exp.start()
    return exp
