"""Per-request lifecycle tracing for the serving engine.

Aggregate TTFT (PR 4) cannot answer *"why was request X slow — queue wait,
preemption, or slow decode?"*. This module threads a timeline through
``serving/api.py`` → ``scheduler.py`` → ``engine.py``: every request records
its stage transitions (queued → admitted → prefill-done → first-token →
preempted×N → finished) with host timestamps, and the tracer derives the two
per-request latency distributions the SLO-scheduling roadmap item regresses
against:

* ``serve.queue_wait_s`` — each waiting segment (initial admission wait AND
  every post-preemption re-admission wait) observed into one histogram;
* ``serve.tpot_s``       — time-per-output-token over the decode phase
  (first token → finish, minus any re-admission waits inside that window so
  a preemption's requeue time is not double-counted as slow decode,
  ÷ tokens-1), observed once per finished request;
* ``serve.preemptions_per_request`` — preemption count per finished request.

Each transition also lands in the flight recorder (``serve.*`` events,
correlation id = request id), so a serving post-mortem carries request
histories, and :meth:`RequestTracer.dump_chrome_trace` exports a chrome
trace with **one track per decode slot** (plus a ``waiting`` track): a
request's hops across preemptions are visible in the same viewer as the
host spans from ``observability/spans.py`` (same µs timebase, pid = rank).

The tracer is pure host bookkeeping and thread-safe: the engine's pump loop
writes while the exporter's HTTP thread snapshots for ``/debug/requests``.
"""

from __future__ import annotations

import gzip
import json
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from veomni_tpu.observability.flight_recorder import record as _flight_record
from veomni_tpu.observability.metrics import MetricsRegistry, get_registry
from veomni_tpu.utils.logging import _process_index

@dataclass
class RequestTimeline:
    """Host-side lifecycle history of one request."""

    request_id: str
    # (t_s, stage, detail) — t_s is perf_counter seconds (tracer-relative
    # offsets come from the owning tracer's epoch)
    marks: List[Tuple[float, str, Dict[str, Any]]] = field(default_factory=list)
    # closed (slot, t0, t1) residencies + the currently-open one
    slot_segments: List[Tuple[int, float, float]] = field(default_factory=list)
    _open_slot: Optional[Tuple[int, float]] = None
    _wait_since: Optional[float] = None
    queue_wait_s: float = 0.0
    wait_segments: List[Tuple[float, float]] = field(default_factory=list)
    preemptions: int = 0
    first_token_t: Optional[float] = None
    finished_t: Optional[float] = None
    finish_reason: str = ""
    tokens: int = 0
    tpot_s: Optional[float] = None
    cached_tokens: int = 0  # prompt positions served from the prefix cache
    # decode-phase accounting by PER-TICK emitted counts: with speculative
    # decoding a verify step lands several tokens in one tick, so "ticks
    # since first token" and "tokens since first token" are different
    # numbers — tpot_s must divide by the latter. decode_tokens accumulates
    # every decode tick's real emitted count (the first token, emitted by
    # prefill, is excluded: tpot is a decode-phase figure).
    decode_tokens: int = 0
    spec_accepted_tokens: int = 0  # of those, accepted speculative drafts

    def mark(self, stage: str, t: Optional[float] = None,
             **detail: Any) -> float:
        t = time.perf_counter() if t is None else t
        self.marks.append((t, stage, detail))
        return t

    def copy_for_read(self) -> "RequestTimeline":
        """Shallow copy with the mutable lists copied — taken under the
        tracer lock so callers can format ``to_doc`` AFTER releasing it
        (formatting ~260 dicts under the lock would stall the decode pump
        on every ``/debug/requests`` scrape)."""
        import copy

        tl = copy.copy(self)
        tl.marks = list(self.marks)
        tl.slot_segments = list(self.slot_segments)
        tl.wait_segments = list(self.wait_segments)
        return tl

    @property
    def stages(self) -> List[str]:
        return [s for _, s, _ in self.marks]

    def to_doc(self, epoch: float = 0.0,
               now: Optional[float] = None) -> Dict[str, Any]:
        """JSON-ready view (``/debug/requests``, post-mortems).

        ``now`` (perf_counter seconds) folds a live request's *open* state
        into the doc: a still-waiting request reports its wait so far, a
        decoding one the slot it occupies — otherwise "why is request X
        slow right now?" reads as ``queue_wait_s: 0.0`` the whole time it
        queues."""
        queue_wait = self.queue_wait_s
        if now is not None and self._wait_since is not None:
            queue_wait += max(now - self._wait_since, 0.0)
        doc: Dict[str, Any] = {
            "request_id": self.request_id,
            "timeline": [
                {"t_s": round(t - epoch, 6), "stage": s, **d}
                for t, s, d in self.marks
            ],
            "queue_wait_s": queue_wait,
            "preemptions": self.preemptions,
            "tokens": self.tokens,
            "cached_tokens": self.cached_tokens,
            "spec_accepted_tokens": self.spec_accepted_tokens,
        }
        if self._wait_since is not None and now is not None:
            doc["waiting"] = True
        if self._open_slot is not None:
            doc["in_slot"] = self._open_slot[0]
        if self.finished_t is not None and self.marks:
            doc["e2e_s"] = round(self.finished_t - self.marks[0][0], 6)
        if self.tpot_s is not None:
            doc["tpot_s"] = self.tpot_s
        if self.finish_reason:
            doc["finish_reason"] = self.finish_reason
        return doc


class RequestTracer:
    """Collects :class:`RequestTimeline` objects and feeds the per-request
    histograms + flight recorder. One instance per engine."""

    def __init__(self, num_slots: int,
                 registry: Optional[MetricsRegistry] = None,
                 max_finished: int = 256):
        self.num_slots = num_slots
        self.registry = registry or get_registry()
        self._h_wait = self.registry.histogram("serve.queue_wait_s")
        self._h_tpot = self.registry.histogram("serve.tpot_s")
        self._h_preempt = self.registry.histogram(
            "serve.preemptions_per_request"
        )
        self._lock = threading.Lock()
        self._inflight: Dict[str, RequestTimeline] = {}  # guarded-by: _lock
        # finished timelines kept for chrome export / debugging, bounded so a
        # long-running pump never accumulates one timeline per request served
        self._finished: deque = deque(maxlen=max_finished)  # guarded-by: _lock
        self.epoch = time.perf_counter()

    # ------------------------------------------------------------ transitions
    def on_queued(self, request_id: str) -> None:
        tl = RequestTimeline(request_id=request_id)
        t = tl.mark("queued")
        tl._wait_since = t
        with self._lock:
            self._inflight[request_id] = tl
        _flight_record("serve.queued", cid=request_id)

    def on_rejected(self, request_id: str) -> None:
        """A request load-shed at submit (bounded queue / tenant cap): it
        was never queued, so its timeline is a single terminal ``rejected``
        mark straight into the finished ring — ``/debug/requests`` shows
        WHO was turned away during an overload window, with timestamps."""
        tl = RequestTimeline(request_id=request_id)
        t = tl.mark("rejected")
        tl.finished_t = t
        tl.finish_reason = "rejected"
        with self._lock:
            self._finished.append(tl)
        _flight_record("serve.rejected", cid=request_id)

    def on_admitted(self, request_id: str, slot: int) -> None:
        with self._lock:
            tl = self._inflight.get(request_id)
            if tl is None:
                return
            t = tl.mark("admitted", slot=slot)
            if tl._wait_since is not None:
                wait = t - tl._wait_since
                tl.queue_wait_s += wait
                tl.wait_segments.append((tl._wait_since, t))
                tl._wait_since = None
                self._h_wait.observe(wait)
            tl._open_slot = (slot, t)
        _flight_record("serve.admitted", cid=request_id, slot=slot)

    def on_prefill_done(self, request_id: str,
                        cached_tokens: int = 0) -> None:
        """``cached_tokens`` = prompt positions served from the prefix cache
        on this admission (lands on the timeline mark AND the rollup doc, so
        ``/debug/requests`` answers "did request X hit the cache?")."""
        with self._lock:
            tl = self._inflight.get(request_id)
            if tl is not None:
                tl.mark("prefill_done", cached_tokens=cached_tokens)
                tl.cached_tokens = cached_tokens

    def on_first_token(self, request_id: str) -> None:
        with self._lock:
            tl = self._inflight.get(request_id)
            if tl is not None:
                tl.first_token_t = tl.mark("first_token")
        _flight_record("serve.first_token", cid=request_id)

    def on_decode_tokens(self, request_id: str, n: int,
                         spec_accepted: int = 0) -> None:
        """Record one decode tick's REAL emitted-token count (the engine
        calls this once per sequence per decode tick, before the tokens are
        emitted). ``serve.tpot_s`` previously divided the decode wall by
        ``tokens - 1`` — correct only while every decode tick emits exactly
        one token; a speculative verify step lands up to k+1, so the
        tracer now accumulates the per-tick counts and divides by those.
        Multi-token ticks additionally land a ``verify_emit`` timeline mark
        (tokens + accepted-draft count) so ``/debug/requests`` shows WHERE
        a request's speculative wins happened; one-token ticks only bump
        the counters (a mark per generated token would bloat every
        timeline)."""
        with self._lock:
            tl = self._inflight.get(request_id)
            if tl is None:
                return
            tl.decode_tokens += n
            tl.spec_accepted_tokens += spec_accepted
            if n > 1:
                tl.mark("verify_emit", tokens=n, spec_accepted=spec_accepted)

    def on_preempted(self, request_id: str) -> None:
        with self._lock:
            tl = self._inflight.get(request_id)
            if tl is None:
                return
            t = tl.mark("preempted")
            tl.preemptions += 1
            if tl._open_slot is not None:
                slot, t0 = tl._open_slot
                tl.slot_segments.append((slot, t0, t))
                tl._open_slot = None
            tl._wait_since = t  # requeued: the next admit closes this wait
        _flight_record("serve.preempted", cid=request_id)

    def on_finished(self, request_id: str, reason: str,
                    tokens: int) -> Optional[RequestTimeline]:
        """Close the timeline; returns it so the engine's finish path needs
        no second lookup (``get()`` would scan the finished deque)."""
        with self._lock:
            tl = self._inflight.pop(request_id, None)
            if tl is None:
                return None
            t = tl.mark("finished", reason=reason, tokens=tokens)
            tl.finished_t = t
            tl.finish_reason = reason
            tl.tokens = tokens
            if tl._open_slot is not None:
                slot, t0 = tl._open_slot
                tl.slot_segments.append((slot, t0, t))
                tl._open_slot = None
            if tl._wait_since is not None:
                # finished while requeued (cancel/abort): close the wait so
                # queue_wait_s covers it and the segment is exported
                wait = t - tl._wait_since
                tl.queue_wait_s += wait
                tl.wait_segments.append((tl._wait_since, t))
                tl._wait_since = None
                self._h_wait.observe(wait)
            if tl.first_token_t is not None and tokens > 1:
                # decode wall time MINUS re-admission waits inside it: a
                # preempted request's 10s requeue wait is queue_wait_s, and
                # counting it here too would read as slow decode — the exact
                # confusion this decomposition exists to remove
                decode_wall = t - tl.first_token_t
                for w0, w1 in tl.wait_segments:
                    decode_wall -= max(
                        0.0, min(w1, t) - max(w0, tl.first_token_t)
                    )
                # divide by the RECORDED decode-phase token count (per-tick
                # emitted counts, multi-token verify ticks included) — the
                # old ``tokens - 1`` denominator assumed one token per
                # decode tick and is kept only as the fallback for engines
                # that never report tick counts
                denom = tl.decode_tokens if tl.decode_tokens > 0 else (
                    tokens - 1
                )
                tl.tpot_s = max(decode_wall, 0.0) / denom
                self._h_tpot.observe(tl.tpot_s)
            self._h_preempt.observe(float(tl.preemptions))
            self._finished.append(tl)
        _flight_record("serve.finished", cid=request_id, reason=reason,
                       tokens=tokens, preemptions=tl.preemptions)
        return tl

    # ---------------------------------------------------------------- queries
    def get(self, request_id: str) -> Optional[RequestTimeline]:
        with self._lock:
            tl = self._inflight.get(request_id)
            if tl is not None:
                return tl
            for done in self._finished:
                if done.request_id == request_id:
                    return done
        return None

    def finished(self) -> List[RequestTimeline]:
        with self._lock:
            return list(self._finished)

    def snapshot(self) -> Dict[str, Any]:
        """JSON-ready view for ``/debug/requests``: in-flight timelines plus
        the bounded recently-finished tail."""
        now = time.perf_counter()
        with self._lock:
            live = [tl.copy_for_read() for tl in self._inflight.values()]
            done_tls = list(self._finished)  # immutable once finished
        inflight = [tl.to_doc(self.epoch, now=now) for tl in live]
        done = [tl.to_doc(self.epoch) for tl in done_tls]
        return {
            "rank": _process_index(),
            "num_slots": self.num_slots,
            "inflight": inflight,
            "finished": done,
        }

    # ----------------------------------------------------------------- export
    def dump_chrome_trace(self, path: str) -> int:
        """Chrome-trace JSON (gzip by extension): one track per decode slot,
        one ``waiting`` track, pid = rank — loadable alongside
        ``spans.dump_chrome_trace`` output in the same viewer. Returns the
        number of "X" events written."""
        rank = _process_index()
        # share the span tracer's ts=0 anchor when it has one: a request's
        # slot-residency segment must land ON the serve.prefill/serve.decode
        # host spans covering it, not seconds away (the tracer's own epoch
        # is pinned at engine construction, the spans' at first enable)
        from veomni_tpu.observability.spans import chrome_epoch_ns

        span_epoch = chrome_epoch_ns()
        epoch = span_epoch / 1e9 if span_epoch is not None else self.epoch
        now = time.perf_counter()
        # segment lists + open state must be copied in ONE locked pass: a
        # preemption between a lock-free list() and reading _open_slot would
        # export the same residency twice (once closed, once extended to
        # "now"). Live requests close their open slot/wait segments at "now"
        # for the export, else an in-flight request's current residency (and
        # a 30s-and-counting wait) is simply absent from the trace.
        snaps: List[Tuple[RequestTimeline, List[Tuple[int, float, float]],
                          List[Tuple[float, float]]]] = []
        with self._lock:
            for tl in list(self._finished) + list(self._inflight.values()):
                slot_segs = list(tl.slot_segments)
                wait_segs = list(tl.wait_segments)
                if tl._open_slot is not None:
                    slot, t0 = tl._open_slot
                    slot_segs.append((slot, t0, now))
                if tl._wait_since is not None:
                    wait_segs.append((tl._wait_since, now))
                snaps.append((tl, slot_segs, wait_segs))
        wait_tid = self.num_slots
        trace: List[Dict[str, Any]] = [{
            "name": "process_name", "ph": "M", "pid": rank, "tid": 0,
            "args": {"name": f"veomni serve requests (rank {rank})"},
        }]
        for s in range(self.num_slots):
            trace.append({"name": "thread_name", "ph": "M", "pid": rank,
                          "tid": s, "args": {"name": f"slot-{s}"}})
        trace.append({"name": "thread_name", "ph": "M", "pid": rank,
                      "tid": wait_tid, "args": {"name": "waiting"}})
        n = 0
        for tl, slot_segs, wait_segs in snaps:
            for slot, t0, t1 in slot_segs:
                trace.append({
                    "name": tl.request_id, "cat": "serve", "ph": "X",
                    "pid": rank, "tid": slot,
                    "ts": (t0 - epoch) * 1e6,
                    "dur": max(t1 - t0, 0.0) * 1e6,
                    "args": {"preemptions": tl.preemptions,
                             "tokens": tl.tokens},
                })
                n += 1
            for t0, t1 in wait_segs:
                trace.append({
                    "name": tl.request_id, "cat": "serve.wait", "ph": "X",
                    "pid": rank, "tid": wait_tid,
                    "ts": (t0 - epoch) * 1e6,
                    "dur": max(t1 - t0, 0.0) * 1e6,
                })
                n += 1
            if tl.first_token_t is not None:
                trace.append({
                    "name": f"{tl.request_id}:first_token", "ph": "i",
                    "pid": rank, "tid": wait_tid, "s": "t",
                    "ts": (tl.first_token_t - epoch) * 1e6,
                })
        payload = {"traceEvents": trace, "displayTimeUnit": "ms"}
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "wt") as f:
            json.dump(payload, f)
        return n
