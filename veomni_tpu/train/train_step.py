"""The jitted training step: grad accumulation, clipping, optimizer update.

Reference hot loop: ``veomni/trainer/base.py:715-826`` (forward_backward per
micro-batch with deferred FSDP reshard, then clip + optimizer step). TPU
design: the *entire* optimizer step — a ``lax.scan`` over micro-batches
accumulating token-sum gradients, global-norm clip, optax update — is one jit
program. GSPMD schedules the FSDP all-gathers/reduce-scatters; the deferral
and prefetch tricks of the reference are compiler-owned here
(SURVEY.md §7.1 "grad accumulation" row).

Loss/grad normalization follows the reference's ``mean_global_loss``: token
sums are accumulated across micro-batches (and implicitly across dp/sp via
GSPMD's replicated reduction of the scalar loss), and divided by the global
valid-token count once — so packing imbalance never skews gradients.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from veomni_tpu.observability.numerics import tree_health
from veomni_tpu.parallel.parallel_plan import ParallelPlan
from veomni_tpu.parallel.parallel_state import ParallelState
from veomni_tpu.utils.env import env_bool
from veomni_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# Trace-time counters, same discipline as ``models/decode.py::TRACE_COUNTS``:
# the jitted step body increments at TRACE time only, so a steady-state run
# holds the count flat and any later bump is a recompile. The observability
# recompile detector (``observability/goodput.py``) watches these and logs
# the offending shapes from LAST_TRACE_SHAPES. ``numerics_step`` is the
# instrumented sibling program (numerics observatory): the trace-count gate
# bounds the tier to exactly ONE extra compiled program per batch shape.
TRACE_COUNTS: Dict[str, int] = {
    "train_step": 0, "eval_step": 0, "numerics_step": 0,
}
LAST_TRACE_SHAPES: Dict[str, Any] = {}


def _batch_bucket(batch: Dict[str, Any]) -> str:
    """Cost-census bucket label for a step batch: the accum/batch/seq shape
    of the first array leaf (every retrace-relevant shape in a packed text
    batch). Falls back to a leaf count for exotic batch schemas."""
    for v in batch.values():
        shape = getattr(v, "shape", None)
        if shape:
            return "x".join(str(int(d)) for d in shape)
    return f"leaves{len(batch)}"


@flax.struct.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jax.Array  # int32 scalar


def build_train_state(params, optimizer: optax.GradientTransformation) -> TrainState:
    return TrainState(params=params, opt_state=optimizer.init(params), step=jnp.int32(0))


def resolve_state_shardings(
    abstract_state: TrainState, plan: ParallelPlan, pstate: ParallelState
) -> TrainState:
    """Shard the whole TrainState by the plan: optimizer moments inherit the
    param sharding via their path suffix (reference: FSDP2 shards optimizer
    state implicitly because DTensor params flow into optimizer.init)."""

    def _one(path, leaf):
        from veomni_tpu.parallel.parallel_plan import param_path_str

        spec = plan.spec_for(param_path_str(path), leaf.shape, pstate)
        return NamedSharding(pstate.mesh, spec)

    return jax.tree_util.tree_map_with_path(_one, abstract_state)


def build_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    pstate: ParallelState,
    *,
    state_shardings: Optional[TrainState] = None,
    batch_shardings: Optional[Any] = None,
    max_grad_norm: float = 1.0,
    grad_mask: Optional[Any] = None,
    skip_nonfinite: bool = False,
    numerics_spec: Optional[Any] = None,
) -> Callable:
    """Returns jitted ``train_step(state, batch) -> (state, metrics)``.

    ``loss_fn(params, micro_batch) -> (token_sum_loss, metrics_dict)`` where
    metrics include 'ntokens'. ``batch`` leaves have a leading micro-batch
    (grad-accum) dim A: [A, B, S].

    ``grad_mask``: optional 0/1 pytree matching params — frozen modules'
    grads are zeroed BEFORE the global-norm clip, so they neither shrink the
    trainable params' clip budget nor pollute the grad_norm metric
    (reference freeze semantics exclude params from optimization entirely).

    Metrics always include ``step_ok`` — a device-side finite-loss/finite-
    grad flag the resilience supervisor fetches with the loop's existing
    in-flight drain (no extra host syncs). With ``skip_nonfinite`` the
    update itself is gated on that flag ON DEVICE: a blown-up step leaves
    params/opt_state untouched (the ``where`` select is exact, so finite
    steps are bitwise-identical to the ungated program).

    ``numerics_spec`` (an ``observability.numerics.NumericsSpec``) builds
    the INSTRUMENTED SIBLING step of the numerics observatory instead: same
    update math, but the step additionally returns a third output — the
    per-param-group training-health tree from ``numerics.tree_health``
    (grad/param RMS, absmax, non-finite counts, update/weight ratio,
    overflow-margin bits; scan-stacked subtrees as per-layer vectors). The
    sibling registers its compiles under its own ``numerics_step`` cost-
    census site (so occasional numerics steps never pollute the train-step
    MFU window) and its own ``TRACE_COUNTS`` key (so the trace-count gates
    can prove the tier costs exactly one extra compiled program). It never
    donates its inputs: the supervisor's anomaly diagnosis re-runs the same
    already-fetched batch and DISCARDS the returned state.
    """
    site = "train_step" if numerics_spec is None else "numerics_step"

    def grads_one_micro(params, micro):
        (loss_sum, metrics), grads = jax.value_and_grad(
            loss_fn, has_aux=True, allow_int=True
        )(params, micro)
        # non-differentiable leaves (frozen int lookup tables, e.g. the
        # deepseek_v4 hash-router tid2eid) produce float0 grads; zero-fill
        # so the f32 accumulation tree stays uniform (build_optimizer routes
        # these leaves to set_to_zero)
        grads = jax.tree.map(
            lambda g, p: jnp.zeros(p.shape, jnp.float32)
            if g.dtype == jax.dtypes.float0 else g,
            grads, params,
        )
        extras = {
            k: v.astype(jnp.float32)
            for k, v in metrics.items()
            if k not in ("ntokens",) and jnp.ndim(v) <= 1
        }
        return grads, loss_sum, metrics["ntokens"], extras

    def step_fn(state: TrainState, batch: Dict[str, jax.Array]):
        TRACE_COUNTS[site] += 1  # trace-time only
        LAST_TRACE_SHAPES[site] = {
            k: tuple(v.shape) for k, v in batch.items()
        }
        params = state.params

        def accum(carry, micro):
            g_acc, loss_acc, tok_acc = carry
            g, l, n, ex = grads_one_micro(params, micro)
            g_acc = jax.tree.map(jnp.add, g_acc, g)
            return (g_acc, loss_acc + l, tok_acc + n), ex

        zero_grads = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
        (grads, loss_sum, ntokens), extras_stacked = jax.lax.scan(
            accum, (zero_grads, jnp.float32(0.0), jnp.int32(0)), batch
        )
        # scalar extras average over micro-steps; vector extras (per-channel
        # sums) accumulate
        extras = {
            k: (x.sum(0) if x.ndim > 1 else x.mean(0))
            for k, x in extras_stacked.items()
        }
        denom = jnp.maximum(ntokens, 1).astype(jnp.float32)
        grads = jax.tree.map(lambda g: g / denom, grads)
        if grad_mask is not None:
            grads = jax.tree.map(lambda g, m: g * m, grads, grad_mask)
        grad_norm = optax.global_norm(grads)
        # numerics observatory reads the token-normalized, mask-applied,
        # PRE-clip gradients: the clip would hide exactly the blow-up
        # magnitude the health summary exists to see
        health_grads = grads
        if max_grad_norm:
            scale = jnp.minimum(1.0, max_grad_norm / (grad_norm + 1e-6))
            grads = jax.tree.map(lambda g: g * scale, grads)
        updates, new_opt = optimizer.update(grads, state.opt_state, params)
        new_params = optax.apply_updates(params, updates)
        health = None
        if numerics_spec is not None:
            health = tree_health(
                params, health_grads, updates,
                max_groups=numerics_spec.max_groups, eps=numerics_spec.eps,
            )
        # grad_norm is NaN/Inf whenever ANY grad leaf is (sqrt-of-sum-of-
        # squares propagates), so loss+grad_norm finiteness covers the tree
        step_ok = jnp.isfinite(loss_sum) & jnp.isfinite(grad_norm)
        if skip_nonfinite:
            new_params = jax.tree.map(
                lambda n, o: jnp.where(step_ok, n, o), new_params, params
            )
            new_opt = jax.tree.map(
                lambda n, o: jnp.where(step_ok, n, o), new_opt, state.opt_state
            )
        new_state = TrainState(params=new_params, opt_state=new_opt, step=state.step + 1)
        metrics = {
            "loss": loss_sum / denom,
            "grad_norm": grad_norm,
            "ntokens": ntokens,
            "step_ok": step_ok,
            # auxiliary scalar metrics from the loss fn (e.g. dpo_acc),
            # averaged over micro-steps
            **extras,
        }
        if numerics_spec is not None:
            return new_state, metrics, health
        return new_state, metrics

    # the numerics sibling never donates: the supervisor's anomaly diagnosis
    # calls it and keeps the CALLER's state (the returned one is discarded)
    donate = (
        (0,) if env_bool("VEOMNI_DONATE_STATE") and numerics_spec is None
        else ()
    )
    if state_shardings is not None:
        # metrics must be explicitly replicated: fully-replicated globals are
        # host-fetchable on every process (multihost float(metrics[...]))
        replicated = NamedSharding(pstate.mesh, P())
        out_shardings = (
            (state_shardings, replicated) if numerics_spec is None
            else (state_shardings, replicated, replicated)
        )
        jitted = jax.jit(
            step_fn,
            in_shardings=(state_shardings, batch_shardings),
            out_shardings=out_shardings,
            donate_argnums=donate,
        )
    else:
        jitted = jax.jit(step_fn, donate_argnums=donate)
    # cost census (observability/cost.py): the jit site's compiles flow
    # through an AOT lower/compile pair that records XLA cost_analysis /
    # memory_analysis / compile wall-time per batch-shape bucket — the
    # attribution substrate behind the train.mfu_pct window gauge. The comm
    # observatory (observability/comm.py) rides the same compile: the
    # partitioned program's HLO is parsed once for the per-kind collective
    # byte census + overlappable/serialized pair counts behind the
    # comm.train_step.* gauges and the comm_est_frac window metric — no
    # extra compiles, so the trace-count gates stay green. Identity
    # under VEOMNI_COST_CENSUS=0; any census failure falls back to the
    # plain jit call permanently.
    from veomni_tpu.observability.cost import instrument_jit

    return instrument_jit(
        site, jitted, bucket_fn=lambda args: _batch_bucket(args[1])
    )


def build_eval_step(loss_fn: Callable, state_shardings=None, batch_shardings=None):
    def eval_fn(params, batch):
        TRACE_COUNTS["eval_step"] += 1  # trace-time only
        LAST_TRACE_SHAPES["eval_step"] = {
            k: tuple(v.shape) for k, v in batch.items()
        }
        loss_sum, metrics = loss_fn(params, batch)
        return {"loss": loss_sum / jnp.maximum(metrics["ntokens"], 1), **metrics}

    # NOT census-instrumented: the trainer's evaluate() builds (and
    # instruments) its own eval jit — a second 'eval_step' site here would
    # collide with it in the census on the same batch-shape buckets
    if state_shardings is not None:
        return jax.jit(
            eval_fn, in_shardings=(state_shardings.params, batch_shardings)
        )
    return jax.jit(eval_fn)
