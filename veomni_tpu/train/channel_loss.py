"""Per-data-source ("channel") loss accounting.

Reference: ``veomni/trainer/callbacks/channel_loss_callback.py`` (1517 LoC —
per-source loss/token tracking, checkpointable). TPU design: the collator
stamps each token with its sample's channel id; the loss fn returns
per-channel (sum, count) vectors that flow through the train step's extras
and are accumulated/averaged by ChannelLossCallback.
"""

from __future__ import annotations

from typing import Callable, Dict, List

import jax
import jax.numpy as jnp

from veomni_tpu.models import transformer
from veomni_tpu.ops.cross_entropy import fused_linear_cross_entropy_per_token
from veomni_tpu.trainer.callbacks import Callback
from veomni_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def _find_merged_hidden(model_type: str):
    """The merged-hidden preamble of this VL/omni family (probe, not a
    table — a new family supports channel loss the moment its module grows
    the preamble). MoE variants share the dense module. Returns the bound
    preamble fn ``(params, cfg, batch) -> (lm, hidden, moe_aux, dropped)``
    or None."""
    import importlib

    candidates = [model_type]
    if model_type.endswith("_moe"):
        candidates.append(model_type[: -len("_moe")])
    for name in candidates:
        try:
            mod = importlib.import_module(f"veomni_tpu.models.{name}")
        except ImportError:
            continue
        for attr in ("_vision_merged_hidden", "_omni_merged_hidden"):
            if hasattr(mod, attr):
                return getattr(mod, attr)
    return None


def supports_channel_loss(model) -> bool:
    """Text trees and any VL/omni family exposing the merged-hidden
    preamble."""
    return (
        "embed_tokens" in model.abstract()
        or _find_merged_hidden(getattr(model.config, "model_type", "")) is not None
    )


def _hidden_fn(cfg):
    """(params, batch) -> (head params, text cfg, hidden, moe_aux) for text
    AND VL/omni-family models (the per-channel CE only needs the pre-head
    hidden states; each family exposes its merged-hidden preamble)."""
    preamble = _find_merged_hidden(getattr(cfg, "model_type", ""))
    if preamble is not None:
        def fn(params, batch):
            lm, hidden, moe_aux, _ = preamble(params, cfg, batch)
            return lm, cfg.text, hidden, moe_aux

        return fn

    def fn(params, batch):
        hidden, moe_aux, _ = transformer.forward_hidden(
            params, cfg, batch["input_ids"], batch["position_ids"],
            batch.get("segment_ids"),
        )
        return params, cfg, hidden, moe_aux

    return fn


def make_channel_loss_fn(model, num_channels: int) -> Callable:
    """Wrap the model loss to additionally emit per-channel sums.
    batch needs 'channel_ids' [B,S] (-1 on ignored/pad tokens). Works for
    text, VL, and omni-thinker families — any model exposing a
    merged-hidden preamble (reference channel_loss_callback.py tracks every
    trainer; seed-omni generation-head composites remain out of scope)."""
    hidden_fn = _hidden_fn(model.config)

    def loss_fn(params, batch):
        head_params, cfg, hidden, moe_aux = hidden_fn(params, batch)
        b, s, h = hidden.shape
        kernel = transformer.lm_head_kernel(head_params, cfg).astype(cfg.dtype)
        nll = fused_linear_cross_entropy_per_token(
            hidden.reshape(b * s, h), kernel, batch["labels"].reshape(b * s),
            logit_softcap=cfg.final_logit_softcap or None,
        )
        valid = (batch["labels"].reshape(-1) != -100)
        loss_sum = nll.sum()
        ntokens = valid.sum()
        ch = batch["channel_ids"].reshape(-1)
        ch_safe = jnp.where(valid & (ch >= 0), ch, num_channels)
        ch_loss = jax.ops.segment_sum(nll, ch_safe, num_segments=num_channels + 1)[:-1]
        ch_tokens = jax.ops.segment_sum(
            valid.astype(jnp.float32), ch_safe, num_segments=num_channels + 1
        )[:-1]
        total = loss_sum
        if cfg.is_moe and cfg.router_aux_loss_coef:
            total = total + cfg.router_aux_loss_coef * moe_aux * ntokens
        return total, {
            "ntokens": ntokens,
            "channel_loss_sums": ch_loss,
            "channel_token_counts": ch_tokens,
        }

    return loss_fn


class ChannelLossCallback(Callback):
    """Accumulates per-channel token-mean loss and logs it periodically
    (checkpointable via state in extra_state)."""

    def __init__(self, channel_names: List[str], log_steps: int = 50):
        self.names = list(channel_names)
        self.log_steps = log_steps
        self._sums = [0.0] * len(self.names)
        self._counts = [0.0] * len(self.names)
        self._acc_sums = None   # device-side (lazy) running sums
        self._acc_counts = None

    def _fold(self):
        """Fetch the device accumulators into the host totals (one sync)."""
        if self._acc_sums is None:
            return
        import numpy as np

        sums = np.asarray(self._acc_sums)
        counts = np.asarray(self._acc_counts)
        for i in range(len(self.names)):
            self._sums[i] += float(sums[i])
            self._counts[i] += float(counts[i])
        self._acc_sums = self._acc_counts = None

    def on_step_end(self, trainer, state):
        sums = state.metrics.pop("channel_loss_sums", None)
        counts = state.metrics.pop("channel_token_counts", None)
        if sums is None:
            return
        # gate window accumulation on step_ok: a non-finite step's sums are
        # NaN-adjacent garbage, and one NaN added here poisons the running
        # channel averages for the rest of the run (the device already
        # refused the param update — the accounting must refuse too). The
        # flag is a host scalar on sync steps (and python False when the
        # supervisor saw a host-injected step.loss drill); between log
        # steps it is a device future, masked lazily so the loop stays
        # async — no extra host syncs.
        ok = state.metrics.get("step_ok")
        if isinstance(ok, (bool, int, float)):
            if not ok:
                return  # drop the anomalous step's contribution
        elif ok is not None:  # device future: mask lazily
            sums = jnp.where(ok, sums, jnp.zeros((), sums.dtype))
            counts = jnp.where(ok, counts, jnp.zeros((), counts.dtype))
        # add without materializing: between log steps these are device
        # futures and fetching them would block the async loop
        self._acc_sums = sums if self._acc_sums is None else self._acc_sums + sums
        self._acc_counts = (
            counts if self._acc_counts is None else self._acc_counts + counts
        )
        if state.global_step % self.log_steps == 0:
            self._fold()
            parts = [
                f"{n}={self._sums[i] / max(self._counts[i], 1):.4f}"
                f"({int(self._counts[i])}tok)"
                for i, n in enumerate(self.names)
            ]
            logger.info_rank0("channel_loss | %s", " | ".join(parts))

    def state_dict(self):
        self._fold()
        return {"sums": self._sums, "counts": self._counts}

    def load_state_dict(self, state):
        self._sums = list(state.get("sums", self._sums))
        self._counts = list(state.get("counts", self._counts))
