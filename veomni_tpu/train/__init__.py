from veomni_tpu.train.train_step import TrainState, build_train_step, build_train_state

__all__ = ["TrainState", "build_train_step", "build_train_state"]
