"""Cosmos discrete image tokenizer (FSQ + Haar-wavelet patching).

Reference: ``veomni/models/seed_omni/decoder/cosmos/modeling_cosmos.py``
(NVIDIA Cosmos-Tokenizer DI: Haar DWT patcher -> VQGAN-style conv encoder ->
FSQ quantizer with an implicit codebook -> decoder -> inverse Haar).
Distinctives vs the other registered decoders:

* **FSQ** (arXiv:2309.15505): no learned codebook and no commit loss — each
  latent channel is bounded with a shifted tanh and rounded (straight-
  through) onto a small grid of ``levels``; the code index is the mixed-
  radix number of the per-channel digits, so the codebook is implicit and
  the quantizer is parameter-free up to optional in/out projections;
* **wavelet patching**: ``patch_size`` 4 = two orthonormal Haar DWT rounds
  (grouped separable convs; rescaled /2 per round) before the conv stack,
  with the exact inverse transform (dilated transposed correlation) after
  the decoder — bit-exact roundtrip, tested;
* downsample count decouples from the channel ladder
  (``log2(spatial_compression) - log2(patch_size)`` of the levels).

TPU-first: NHWC depthwise ``lax.conv_general_dilated`` for the DWT/IDWT
(2-tap filters map onto cheap fused convs), functional param tree, and the
movqgan conv primitives for the res/attn blocks.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from veomni_tpu.models.movqgan import (
    _attn_block,
    _attn_params,
    _conv,
    _conv_init,
    _group_norm,
    _norm_params,
    _res_block,
    _res_params,
    _swish,
)

Params = Dict[str, Any]

_HAAR = np.asarray([1.0, 1.0], np.float32) / np.sqrt(2.0)


@dataclass
class CosmosConfig:
    """``CosmosConfig`` surface (defaults = Cosmos-Tokenizer-DI16x16)."""

    channels: int = 128
    channels_mult: Tuple[int, ...] = (2, 4, 4)
    num_res_blocks: int = 2
    attn_resolutions: Tuple[int, ...] = (32,)
    in_channels: int = 3
    out_channels: int = 3
    resolution: int = 1024
    patch_size: int = 4
    patch_method: str = "haar"      # "haar" | "rearrange"
    spatial_compression: int = 16
    z_channels: int = 256
    embedding_dim: int = 6
    levels: Tuple[int, ...] = (8, 8, 8, 5, 5, 5)
    num_groups: int = 32
    initializer_range: float = 0.02

    def __post_init__(self):
        self.channels_mult = tuple(self.channels_mult)
        self.attn_resolutions = tuple(self.attn_resolutions)
        self.levels = tuple(self.levels)

    @property
    def num_downsamples(self) -> int:
        return int(np.log2(self.spatial_compression)) - int(np.log2(self.patch_size))

    @property
    def token_grid(self) -> int:
        return self.resolution // self.spatial_compression

    @property
    def tokens_per_image(self) -> int:
        return self.token_grid ** 2

    @property
    def codebook_size(self) -> int:
        return int(np.prod(self.levels))


# ---------------------------------------------------------------------------
# Haar wavelet patching (reference Patcher/UnPatcher, NHWC depthwise convs)
# ---------------------------------------------------------------------------

def _depthwise(x, filt_1d, axis: int, stride: int, pad):
    """Grouped 1-D correlation along a spatial axis of NHWC x."""
    c = x.shape[-1]
    if axis == 1:   # H
        k = jnp.asarray(filt_1d, x.dtype).reshape(-1, 1, 1, 1)
        window = (stride, 1)
        padding = (pad, (0, 0))
    else:           # W
        k = jnp.asarray(filt_1d, x.dtype).reshape(1, -1, 1, 1)
        window = (1, stride)
        padding = ((0, 0), pad)
    k = jnp.tile(k, (1, 1, 1, c))
    return jax.lax.conv_general_dilated(
        x, k, window, padding, feature_group_count=c,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _depthwise_t(x, filt_1d, axis: int, torch_pad: int):
    """Grouped stride-2 transposed convolution along one axis (the exact
    inverse-DWT op: correlate the 2x-dilated input with the FLIPPED filter,
    padding n-1-p per side — matches torch ``conv_transpose2d``)."""
    c = x.shape[-1]
    n = len(filt_1d)
    flipped = jnp.asarray(filt_1d, x.dtype)[::-1]
    p = n - 1 - torch_pad
    if axis == 1:
        k = flipped.reshape(-1, 1, 1, 1)
        dil = (2, 1)
        padding = ((p, p), (0, 0))
    else:
        k = flipped.reshape(1, -1, 1, 1)
        dil = (1, 2)
        padding = ((0, 0), (p, p))
    k = jnp.tile(k, (1, 1, 1, c))
    return jax.lax.conv_general_dilated(
        x, k, (1, 1), padding, lhs_dilation=dil, feature_group_count=c,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _dwt(x):
    """One orthonormal-Haar DWT round: NHWC [N,H,W,C] ->
    [N,H/2,W/2,4C] with subband-major channels [ll|lh|hl|hh], rescaled /2."""
    h = _HAAR
    n = len(h)
    hl = h[::-1]
    hh = h * ((-1.0) ** np.arange(n))
    # reflect pad (n-2, n-1) on H and W like torch F.pad mode="reflect"
    x = jnp.pad(x, ((0, 0), (n - 2, n - 1), (n - 2, n - 1), (0, 0)), "reflect")
    xl = _depthwise(x, hl, axis=2, stride=2, pad=(0, 0))
    xh = _depthwise(x, hh, axis=2, stride=2, pad=(0, 0))
    xll = _depthwise(xl, hl, axis=1, stride=2, pad=(0, 0))
    xlh = _depthwise(xl, hh, axis=1, stride=2, pad=(0, 0))
    xhl = _depthwise(xh, hl, axis=1, stride=2, pad=(0, 0))
    xhh = _depthwise(xh, hh, axis=1, stride=2, pad=(0, 0))
    return jnp.concatenate([xll, xlh, xhl, xhh], axis=-1) / 2.0


def _idwt(x):
    """Inverse of one DWT round (rescale *2)."""
    h = _HAAR
    n = len(h)
    hl = h[::-1]
    hh = h * ((-1.0) ** np.arange(n))
    xll, xlh, xhl, xhh = jnp.split(x, 4, axis=-1)
    yl = _depthwise_t(xll, hl, axis=1, torch_pad=n - 2) \
        + _depthwise_t(xlh, hh, axis=1, torch_pad=n - 2)
    yh = _depthwise_t(xhl, hl, axis=1, torch_pad=n - 2) \
        + _depthwise_t(xhh, hh, axis=1, torch_pad=n - 2)
    y = _depthwise_t(yl, hl, axis=2, torch_pad=n - 2) \
        + _depthwise_t(yh, hh, axis=2, torch_pad=n - 2)
    return y * 2.0


def patchify(x, cfg: CosmosConfig):
    if cfg.patch_method == "rearrange":
        n, h, w, c = x.shape
        p = cfg.patch_size
        x = x.reshape(n, h // p, p, w // p, p, c)
        return x.transpose(0, 1, 3, 5, 2, 4).reshape(n, h // p, w // p, c * p * p)
    for _ in range(int(np.log2(cfg.patch_size))):
        x = _dwt(x)
    return x


def unpatchify(x, cfg: CosmosConfig):
    if cfg.patch_method == "rearrange":
        n, h, w, cpp = x.shape
        p = cfg.patch_size
        c = cpp // (p * p)
        x = x.reshape(n, h, w, c, p, p)
        return x.transpose(0, 1, 4, 2, 5, 3).reshape(n, h * p, w * p, c)
    for _ in range(int(np.log2(cfg.patch_size))):
        x = _idwt(x)
    return x


# ---------------------------------------------------------------------------
# FSQ (parameter-free; implicit codebook)
# ---------------------------------------------------------------------------

def fsq_quantize(z, levels: Tuple[int, ...], eps: float = 1e-3):
    """z [..., d] -> (zhat in [-1,1] straight-through, indices [...])."""
    lv = jnp.asarray(levels, jnp.float32)
    half_l = (lv - 1.0) * (1.0 + eps) / 2.0
    offset = jnp.where(jnp.asarray(levels) % 2 == 0, 0.5, 0.0)
    shift = jnp.arctanh(offset / half_l)
    zf = z.astype(jnp.float32)
    bounded = jnp.tanh(zf + shift) * half_l - offset
    q = jnp.round(bounded)
    q = bounded + jax.lax.stop_gradient(q - bounded)  # round_ste
    half_w = jnp.asarray([l // 2 for l in levels], jnp.float32)
    zhat = q / half_w
    basis = np.cumprod([1] + list(levels[:-1])).astype(np.int32)
    digits = (jax.lax.stop_gradient(q) + half_w).astype(jnp.int32)
    indices = (digits * basis).sum(-1)
    return zhat.astype(z.dtype), indices


def fsq_indices_to_codes(indices, levels: Tuple[int, ...]):
    basis = np.cumprod([1] + list(levels[:-1])).astype(np.int32)
    lv = np.asarray(levels, np.int32)
    digits = (indices[..., None] // basis) % lv
    half_w = jnp.asarray([l // 2 for l in levels], jnp.float32)
    return (digits.astype(jnp.float32) - half_w) / half_w


# ---------------------------------------------------------------------------
# params / encode / decode
# ---------------------------------------------------------------------------

def init_params(rng: jax.Array, cfg: CosmosConfig) -> Params:
    s = cfg.initializer_range
    keys = iter(jax.random.split(rng, 512))
    levels_n = len(cfg.channels_mult)
    p_in = cfg.in_channels * cfg.patch_size ** 2

    enc: Params = {
        "conv_in_w": _conv_init(next(keys), 3, 3, p_in, cfg.channels, s),
        "conv_in_b": jnp.zeros((cfg.channels,), jnp.float32),
        "down": [],
    }
    in_mult = (1,) + cfg.channels_mult
    res = cfg.resolution // cfg.patch_size
    for i in range(levels_n):
        cin = cfg.channels * in_mult[i]
        cout = cfg.channels * cfg.channels_mult[i]
        level: Params = {"res": [], "attn": []}
        for _ in range(cfg.num_res_blocks):
            level["res"].append(_res_params(keys, cin, cout, s))
            cin = cout
            if res in cfg.attn_resolutions:
                level["attn"].append(_attn_params(keys, cin, s))
        if i < cfg.num_downsamples:
            level["down_w"] = _conv_init(next(keys), 3, 3, cin, cin, s)
            level["down_b"] = jnp.zeros((cin,), jnp.float32)
            res //= 2
        enc["down"].append(level)
    top = cfg.channels * cfg.channels_mult[-1]
    enc["mid_res1"] = _res_params(keys, top, top, s)
    enc["mid_attn"] = _attn_params(keys, top, s)
    enc["mid_res2"] = _res_params(keys, top, top, s)
    enc["norm_out"] = _norm_params(top, False)
    enc["conv_out_w"] = _conv_init(next(keys), 3, 3, top, cfg.z_channels, s)
    enc["conv_out_b"] = jnp.zeros((cfg.z_channels,), jnp.float32)

    p_out = cfg.out_channels * cfg.patch_size ** 2
    dec: Params = {
        "conv_in_w": _conv_init(next(keys), 3, 3, cfg.z_channels, top, s),
        "conv_in_b": jnp.zeros((top,), jnp.float32),
        "mid_res1": _res_params(keys, top, top, s),
        "mid_attn": _attn_params(keys, top, s),
        "mid_res2": _res_params(keys, top, top, s),
        "up": [],
    }
    cin = top
    for j, i in enumerate(reversed(range(levels_n))):
        cout = cfg.channels * cfg.channels_mult[i]
        level = {"res": [], "attn": []}
        for _ in range(cfg.num_res_blocks + 1):
            level["res"].append(_res_params(keys, cin, cout, s))
            cin = cout
            if res in cfg.attn_resolutions:
                level["attn"].append(_attn_params(keys, cin, s))
        if i >= levels_n - cfg.num_downsamples:
            level["up_w"] = _conv_init(next(keys), 3, 3, cin, cin, s)
            level["up_b"] = jnp.zeros((cin,), jnp.float32)
            res *= 2
        dec["up"].append(level)
    dec["norm_out"] = _norm_params(cin, False)
    dec["conv_out_w"] = _conv_init(next(keys), 3, 3, cin, p_out, s)
    dec["conv_out_b"] = jnp.zeros((p_out,), jnp.float32)

    e = cfg.embedding_dim
    return {
        "encoder": enc,
        "decoder": dec,
        "quant_conv_w": _conv_init(next(keys), 1, 1, cfg.z_channels, e, s),
        "quant_conv_b": jnp.zeros((e,), jnp.float32),
        "post_quant_conv_w": _conv_init(next(keys), 1, 1, e, cfg.z_channels, s),
        "post_quant_conv_b": jnp.zeros((cfg.z_channels,), jnp.float32),
    }


def encode(params: Params, cfg: CosmosConfig, pixels: jax.Array):
    """pixels [N,H,W,3] -> (zhat [N,h,w,e] straight-through, indices [N,h,w],
    per-image quant loss [N] — zeros: FSQ needs no commit loss)."""
    g = cfg.num_groups
    p = params["encoder"]
    h = patchify(pixels, cfg)
    h = _conv(h, p["conv_in_w"], p["conv_in_b"])
    for level in p["down"]:
        attn_iter = iter(level["attn"])
        for rp in level["res"]:
            h = _res_block(h, rp, g)
            if level["attn"]:
                h = _attn_block(h, next(attn_iter), g)
        if "down_w" in level:
            h = _conv(
                jnp.pad(h, ((0, 0), (0, 1), (0, 1), (0, 0))),
                level["down_w"], level["down_b"], stride=2, padding="VALID",
            )
    h = _res_block(h, p["mid_res1"], g)
    h = _attn_block(h, p["mid_attn"], g)
    h = _res_block(h, p["mid_res2"], g)
    h = _swish(_group_norm(h, p["norm_out"]["gn_w"], p["norm_out"]["gn_b"], g))
    z = _conv(h, p["conv_out_w"], p["conv_out_b"])
    z = _conv(z, params["quant_conv_w"], params["quant_conv_b"])
    zhat, idx = fsq_quantize(z, cfg.levels)
    return zhat, idx, jnp.zeros((pixels.shape[0],), jnp.float32)


def decode(params: Params, cfg: CosmosConfig, zhat: jax.Array) -> jax.Array:
    g = cfg.num_groups
    z = _conv(zhat, params["post_quant_conv_w"], params["post_quant_conv_b"])
    p = params["decoder"]
    h = _conv(z, p["conv_in_w"], p["conv_in_b"])
    h = _res_block(h, p["mid_res1"], g)
    h = _attn_block(h, p["mid_attn"], g)
    h = _res_block(h, p["mid_res2"], g)
    for level in p["up"]:
        attn_iter = iter(level["attn"])
        for rp in level["res"]:
            h = _res_block(h, rp, g)
            if level["attn"]:
                h = _attn_block(h, next(attn_iter), g)
        if "up_w" in level:
            n, hh, ww, c = h.shape
            h = jax.image.resize(h, (n, hh * 2, ww * 2, c), "nearest")
            h = _conv(h, level["up_w"], level["up_b"])
    h = _swish(_group_norm(h, p["norm_out"]["gn_w"], p["norm_out"]["gn_b"], g))
    h = _conv(h, p["conv_out_w"], p["conv_out_b"])
    return unpatchify(h, cfg)


def decode_code(params: Params, cfg: CosmosConfig, indices: jax.Array) -> jax.Array:
    """indices [N, T] or [N, h, w] -> pixels."""
    if indices.ndim == 2:
        grid = cfg.token_grid
        indices = indices.reshape(indices.shape[0], grid, grid)
    return decode(params, cfg, fsq_indices_to_codes(indices, cfg.levels))
