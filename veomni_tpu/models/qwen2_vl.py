"""Qwen2-VL: the real architecture — full-attention ViT, mrope, merger.

Reference: ``veomni/models/transformers/qwen2_vl/`` (2.8k LoC generated
modeling; upstream contract = HF ``Qwen2VLForConditionalGeneration``).
Differences from Qwen2.5-VL (which shares our collator row contract):

* vision blocks use **LayerNorm** (with bias) and a **quick-GELU fc1/fc2
  MLP** (``mlp_ratio``×) instead of RMSNorm + biased-SwiGLU;
* **no window attention**: every layer attends globally *within a frame*
  (HF builds ``cu_seqlens`` per (h·w) frame — our packed-segment contract
  reproduces that with one segment id per frame);
* patches stay in processor (merge-block) order — no window permutation,
  so merged 2×2 groups are contiguous and no inverse gather is needed;
* video mrope t-positions are plain frame indices (no ``tokens_per_second``
  scaling — that arrived with Qwen2.5-VL).

TPU-first design mirrors qwen2_5_vl.py: one statically padded packed patch
sequence per micro-batch, host-precomputed (h, w) rope positions + frame
segment ids, pure gathers + dense math inside jit.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from veomni_tpu import ops
from veomni_tpu.models import transformer
from veomni_tpu.models.config import TransformerConfig
from veomni_tpu.models.qwen2_5_vl import (
    _is_visual_key,
    _per_image_pos_hw,
    _text_key_map,
    merge_vision_features,
)
from veomni_tpu.models.qwen2_5_vl import (
    mrope_position_ids as _mrope_q25,
)


@dataclass
class Qwen2VisionConfig:
    """HF ``Qwen2VLVisionConfig`` surface (defaults = 7B checkpoint)."""

    depth: int = 32
    embed_dim: int = 1280
    hidden_size: int = 3584          # LM width (merger output)
    hidden_act: str = "quick_gelu"
    mlp_ratio: int = 4
    num_heads: int = 16
    in_channels: int = 3
    patch_size: int = 14
    spatial_merge_size: int = 2
    temporal_patch_size: int = 2
    initializer_range: float = 0.02
    # qwen2_vl has no time scaling; fixed 1.0 makes the shared qwen2_5_vl
    # mrope walker produce plain frame indices for video grids
    tokens_per_second: float = 1.0

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads

    @property
    def intermediate_size(self) -> int:
        return self.embed_dim * self.mlp_ratio

    @property
    def patch_dim(self) -> int:
        return self.in_channels * self.temporal_patch_size * self.patch_size ** 2

    @property
    def merge_unit(self) -> int:
        return self.spatial_merge_size ** 2

    @property
    def out_hidden_size(self) -> int:  # trainer/collator shared surface
        return self.hidden_size


@dataclass
class Qwen2VLConfig:
    text: TransformerConfig = field(default_factory=TransformerConfig)
    vision: Qwen2VisionConfig = field(default_factory=Qwen2VisionConfig)
    image_token_id: int = 151655
    video_token_id: int = 151656
    vision_start_token_id: int = 151652
    freeze_vision: bool = False
    model_type: str = "qwen2_vl"

    def __post_init__(self):
        if isinstance(self.text, dict):
            self.text = TransformerConfig(**self.text)
        if isinstance(self.vision, dict):
            self.vision = Qwen2VisionConfig(**self.vision)

    def __getattr__(self, name):  # FlopsCounter / trainer surface
        return getattr(object.__getattribute__(self, "text"), name)


# ---------------------------------------------------------------------------
# params
# ---------------------------------------------------------------------------

def init_vision_params(rng: jax.Array, cfg: Qwen2VisionConfig, dtype=jnp.float32):
    s = cfg.initializer_range
    d, i, L = cfg.embed_dim, cfg.intermediate_size, cfg.depth
    merge_dim = d * cfg.merge_unit
    keys = iter(jax.random.split(rng, 12))

    def init(key, shape):
        return (jax.random.normal(key, shape, jnp.float32) * s).astype(dtype)

    return {
        "patch_embed": init(next(keys), (cfg.patch_dim, d)),
        "blocks": {
            "norm1_w": jnp.ones((L, d), dtype),
            "norm1_b": jnp.zeros((L, d), dtype),
            "norm2_w": jnp.ones((L, d), dtype),
            "norm2_b": jnp.zeros((L, d), dtype),
            "qkv_w": init(next(keys), (L, d, 3 * d)),
            "qkv_b": jnp.zeros((L, 3 * d), dtype),
            "proj_w": init(next(keys), (L, d, d)),
            "proj_b": jnp.zeros((L, d), dtype),
            "fc1_w": init(next(keys), (L, d, i)),
            "fc1_b": jnp.zeros((L, i), dtype),
            "fc2_w": init(next(keys), (L, i, d)),
            "fc2_b": jnp.zeros((L, d), dtype),
        },
        "merger": {
            "ln_q_w": jnp.ones((d,), dtype),
            "ln_q_b": jnp.zeros((d,), dtype),
            "fc1_w": init(next(keys), (merge_dim, merge_dim)),
            "fc1_b": jnp.zeros((merge_dim,), dtype),
            "fc2_w": init(next(keys), (merge_dim, cfg.hidden_size)),
            "fc2_b": jnp.zeros((cfg.hidden_size,), dtype),
        },
    }


def init_params(rng: jax.Array, cfg: Qwen2VLConfig) -> Dict[str, Any]:
    r1, r2 = jax.random.split(rng)
    return {
        "language_model": transformer.init_params(r1, cfg.text),
        "vision_tower": init_vision_params(r2, cfg.vision, dtype=cfg.text.param_dtype),
    }


def abstract_params(cfg: Qwen2VLConfig):
    return jax.eval_shape(lambda: init_params(jax.random.PRNGKey(0), cfg))


# ---------------------------------------------------------------------------
# host-side index plan
# ---------------------------------------------------------------------------

def vision_metadata(
    grid_thw: Sequence[Tuple[int, int, int]],
    cfg: Qwen2VisionConfig,
    n_pad_patches: int,
) -> Dict[str, np.ndarray]:
    """Static index plan for a packed patch sequence in processor order:
    ``pos_hw`` [N, 2] rope positions, ``seg`` [N] per-frame attention
    segments (0 = padding; HF ``cu_seqlens = repeat_interleave(h*w, t)``),
    ``merged_mask`` [N / merge_unit]."""
    pos_list, segs = [], []
    seg_id = 0
    n = 0
    for (t, h, w) in grid_thw:
        pos_list.append(_per_image_pos_hw(t, h, w, cfg.spatial_merge_size))
        for _ in range(t):
            seg_id += 1
            segs.append(np.full(h * w, seg_id, np.int32))
        n += t * h * w
    if n > n_pad_patches:
        raise ValueError(
            f"{n} patches exceed the static budget {n_pad_patches}; raise "
            "data.max_patches or drop images upstream"
        )
    unit = cfg.merge_unit
    m_pad = n_pad_patches // unit

    def pad_to(x, size, fill=0):
        out = np.full((size,) + x.shape[1:], fill, x.dtype)
        out[: len(x)] = x
        return out

    return {
        "pos_hw": pad_to(
            np.concatenate(pos_list).astype(np.int32) if pos_list
            else np.zeros((0, 2), np.int32), n_pad_patches),
        "seg": pad_to(
            np.concatenate(segs) if segs else np.zeros((0,), np.int32),
            n_pad_patches),
        "merged_mask": pad_to(np.ones(n // unit, bool), m_pad, fill=False),
    }


def mrope_position_ids(
    input_ids: np.ndarray,
    grid_thw: Sequence[Tuple[int, int, int]],
    cfg: Qwen2VLConfig,
    video: Optional[Sequence[bool]] = None,
) -> np.ndarray:
    """HF ``get_rope_index`` (modeling_qwen2_vl.py:925): identical walk to
    qwen2_5_vl except t-indices are plain frame numbers for EVERY grid
    (qwen2_vl predates ``second_per_grid_ts``; its image/video branches are
    the same ``arange(t)``) — delegated with interval pinned to 1."""
    del video  # no image/video distinction in the qwen2_vl walk
    return _mrope_q25(
        input_ids, grid_thw, cfg,
        second_per_grid_ts=[1.0] * len(grid_thw), video=[True] * len(grid_thw),
    )


# ---------------------------------------------------------------------------
# vision tower forward
# ---------------------------------------------------------------------------

def _layer_norm(x, w, b, eps=1e-6):
    dt = x.dtype
    x = x.astype(jnp.float32)
    mean = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    x = (x - mean) * jax.lax.rsqrt(var + eps)
    return (x * w + b).astype(dt)


def _quick_gelu(x):
    return x * jax.nn.sigmoid(1.702 * x)


def _vision_block(x, lp, cfg: Qwen2VisionConfig, cos, sin, seg):
    n, d = x.shape
    hd = cfg.head_dim
    y = _layer_norm(x, lp["norm1_w"], lp["norm1_b"])
    qkv = jnp.dot(y, lp["qkv_w"]) + lp["qkv_b"]
    q, k, v = jnp.split(qkv.reshape(1, n, 3 * cfg.num_heads, hd), 3, axis=2)
    q, k = ops.apply_rotary(q, k, cos, sin)
    attn = ops.attention(q, k, v, segment_ids=seg, causal=False)
    x = x + jnp.dot(attn.reshape(n, d), lp["proj_w"]) + lp["proj_b"]
    y = _layer_norm(x, lp["norm2_w"], lp["norm2_b"])
    x = x + jnp.dot(_quick_gelu(jnp.dot(y, lp["fc1_w"]) + lp["fc1_b"]),
                    lp["fc2_w"]) + lp["fc2_b"]
    return x


def vision_forward(
    params, cfg: Qwen2VisionConfig, pixel_values, pos_hw, seg,
    dtype=jnp.bfloat16,
):
    """pixel_values [N, patch_dim] (processor order, padded); returns merged
    features [N / merge_unit, hidden_size] in image order. Scoped to sp=1
    like the qwen2_5_vl tower (per-module heterogeneous SP)."""
    from veomni_tpu.parallel.parallel_state import (
        get_parallel_state_or_none, use_parallel_state,
    )

    ps = get_parallel_state_or_none()
    if ps is not None and ps.sp_enabled:
        with use_parallel_state(ps.without_sp()):
            return vision_forward(params, cfg, pixel_values, pos_hw, seg, dtype=dtype)
    p = jax.tree.map(lambda t: t.astype(dtype), params)
    x = jnp.dot(pixel_values.astype(dtype), p["patch_embed"])  # [N, D]

    # 2D rope: head_dim/2 split across (h, w) — HF Qwen2VisionRotaryEmbedding
    hd = cfg.head_dim
    inv_freq = 1.0 / (10000.0 ** (jnp.arange(0, hd // 2, 2, jnp.float32) / (hd // 2)))
    fh = pos_hw[:, 0:1].astype(jnp.float32) * inv_freq
    fw = pos_hw[:, 1:2].astype(jnp.float32) * inv_freq
    freqs = jnp.concatenate([fh, fw], -1)
    emb = jnp.concatenate([freqs, freqs], -1)[None]
    cos, sin = jnp.cos(emb), jnp.sin(emb)

    body = partial(_vision_block, cfg=cfg, cos=cos, sin=sin, seg=seg[None])
    x, _ = jax.lax.scan(
        lambda c, lp: (jax.checkpoint(body)(c, lp), None), x, p["blocks"]
    )

    # 2x2 merger (merge-block groups are contiguous in processor order)
    mg = p["merger"]
    y = _layer_norm(x, mg["ln_q_w"], mg["ln_q_b"])
    y = y.reshape(x.shape[0] // cfg.merge_unit, cfg.merge_unit * cfg.embed_dim)
    y = jax.nn.gelu(jnp.dot(y, mg["fc1_w"]) + mg["fc1_b"])
    return jnp.dot(y, mg["fc2_w"]) + mg["fc2_b"]


# ---------------------------------------------------------------------------
# loss
# ---------------------------------------------------------------------------

def _vision_merged_hidden(params, cfg: Qwen2VLConfig, batch):
    """Vision tower + placeholder merge + text transformer; returns
    (lm params, hidden [B,S,H], moe_aux, moe_dropped)."""
    tcfg = cfg.text
    vp = params["vision_tower"]
    if cfg.freeze_vision:
        vp = jax.lax.stop_gradient(vp)
    row_tokens = 0
    if batch["pixel_values"].ndim == 3:
        from veomni_tpu.models.qwen2_5_vl import flatten_per_row_vision

        packed, row_tokens = flatten_per_row_vision(batch, cfg.vision.merge_unit)
        batch = {**batch, **packed}
    feats = vision_forward(
        vp, cfg.vision, batch["pixel_values"], batch["vis_pos_hw"],
        batch["vis_seg"], dtype=tcfg.dtype,
    )
    lm = params["language_model"]
    embeds = lm["embed_tokens"].astype(tcfg.dtype)[batch["input_ids"]]
    embeds = merge_vision_features(
        embeds, batch["input_ids"], feats, batch["vis_merged_mask"],
        cfg.image_token_id, cfg.video_token_id, row_tokens=row_tokens,
    )
    hidden, moe_aux, moe_dropped = transformer.forward_hidden(
        lm, tcfg, batch["input_ids"], batch["position_ids"],
        batch.get("segment_ids"), inputs_embeds=embeds,
    )
    return lm, hidden, moe_aux, moe_dropped


def loss_fn(params, cfg: Qwen2VLConfig, batch) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """batch: input_ids/labels/segment_ids [B,S]; position_ids [B,3,S]
    (mrope); pixel_values [N, patch_dim]; vis_pos_hw [N,2]; vis_seg [N];
    vis_merged_mask [M]."""
    lm, hidden, moe_aux, moe_dropped = _vision_merged_hidden(params, cfg, batch)
    return transformer.head_loss(
        lm, cfg.text, hidden, batch["labels"], moe_aux, moe_dropped
    )


# ---------------------------------------------------------------------------
# HF checkpoint io
# ---------------------------------------------------------------------------

_VIS_BLOCK_MAP = [
    ("norm1_w", "norm1.weight", False),
    ("norm1_b", "norm1.bias", False),
    ("norm2_w", "norm2.weight", False),
    ("norm2_b", "norm2.bias", False),
    ("qkv_w", "attn.qkv.weight", True),
    ("qkv_b", "attn.qkv.bias", False),
    ("proj_w", "attn.proj.weight", True),
    ("proj_b", "attn.proj.bias", False),
    ("fc1_w", "mlp.fc1.weight", True),
    ("fc1_b", "mlp.fc1.bias", False),
    ("fc2_w", "mlp.fc2.weight", True),
    ("fc2_b", "mlp.fc2.bias", False),
]


def hf_to_params(model_dir: str, cfg: Qwen2VLConfig, target_shardings=None):
    """Load an HF Qwen2-VL checkpoint (visual.* + model.* text tree) into our
    composite pytree; text stays on hf_io's streamed shard-aligned path."""
    from veomni_tpu.models import hf_io

    pd = cfg.text.param_dtype
    ts_lm = target_shardings["language_model"] if target_shardings else None
    ts_vis = target_shardings["vision_tower"] if target_shardings else None

    language_model = hf_io.hf_to_params(
        model_dir, cfg.text, target_shardings=ts_lm, key_map=_text_key_map
    )

    lazy = hf_io.LazyHFTensors(model_dir)
    vis_alias = {}
    for k in lazy.keys():
        if _is_visual_key(k):
            vis_alias[k[k.index("visual.") + len("visual."):]] = k

    def read(name: str) -> np.ndarray:
        return np.asarray(lazy.read(vis_alias[name]))

    def place(path_in_vis, arr):
        arr = jnp.asarray(np.ascontiguousarray(arr), pd)
        if ts_vis is None:
            return arr
        sh = ts_vis
        for p in path_in_vis:
            sh = sh[p]
        return jax.device_put(arr, sh)

    vcfg = cfg.vision
    blocks: Dict[str, Any] = {}
    for ours, suffix, transpose in _VIS_BLOCK_MAP:
        stacked = np.stack([
            read(f"blocks.{i}.{suffix}").T if transpose
            else read(f"blocks.{i}.{suffix}")
            for i in range(vcfg.depth)
        ])
        blocks[ours] = place(("blocks", ours), stacked)
    vision_tower = {
        "patch_embed": place(
            ("patch_embed",),
            read("patch_embed.proj.weight").reshape(vcfg.embed_dim, -1).T,
        ),
        "blocks": blocks,
        "merger": {
            "ln_q_w": place(("merger", "ln_q_w"), read("merger.ln_q.weight")),
            "ln_q_b": place(("merger", "ln_q_b"), read("merger.ln_q.bias")),
            "fc1_w": place(("merger", "fc1_w"), read("merger.mlp.0.weight").T),
            "fc1_b": place(("merger", "fc1_b"), read("merger.mlp.0.bias")),
            "fc2_w": place(("merger", "fc2_w"), read("merger.mlp.2.weight").T),
            "fc2_b": place(("merger", "fc2_b"), read("merger.mlp.2.bias")),
        },
    }
    return {"language_model": language_model, "vision_tower": vision_tower}


def params_to_hf(params, cfg: Qwen2VLConfig) -> Dict[str, np.ndarray]:
    from veomni_tpu.models import hf_io

    out: Dict[str, np.ndarray] = {}
    text = hf_io.params_to_hf(params["language_model"], cfg.text)
    for k, v in text.items():
        if k == "lm_head.weight":
            out[k] = v
        else:
            out[k.replace("model.", "model.language_model.", 1)] = v
    vt = hf_io.gather_to_host(params["vision_tower"])
    vcfg = cfg.vision
    pfx = "model.visual"
    out[f"{pfx}.patch_embed.proj.weight"] = vt["patch_embed"].T.reshape(
        vcfg.embed_dim, vcfg.in_channels, vcfg.temporal_patch_size,
        vcfg.patch_size, vcfg.patch_size,
    )
    for ours, suffix, transpose in _VIS_BLOCK_MAP:
        for i in range(vcfg.depth):
            x = vt["blocks"][ours][i]
            out[f"{pfx}.blocks.{i}.{suffix}"] = x.T if transpose else x
    out[f"{pfx}.merger.ln_q.weight"] = vt["merger"]["ln_q_w"]
    out[f"{pfx}.merger.ln_q.bias"] = vt["merger"]["ln_q_b"]
    out[f"{pfx}.merger.mlp.0.weight"] = vt["merger"]["fc1_w"].T
    out[f"{pfx}.merger.mlp.0.bias"] = vt["merger"]["fc1_b"]
    out[f"{pfx}.merger.mlp.2.weight"] = vt["merger"]["fc2_w"].T
    out[f"{pfx}.merger.mlp.2.bias"] = vt["merger"]["fc2_b"]
    return out


def save_hf_checkpoint(params, cfg: Qwen2VLConfig, out_dir: str) -> None:
    import json
    import os

    from safetensors.flax import save_file

    tensors = params_to_hf(params, cfg)  # collective gather
    if jax.process_index() != 0:
        return
    os.makedirs(out_dir, exist_ok=True)
    save_file({k: jnp.asarray(v) for k, v in tensors.items()},
              os.path.join(out_dir, "model.safetensors"))
    hf_cfg = {
        "model_type": "qwen2_vl",
        "architectures": ["Qwen2VLForConditionalGeneration"],
        "image_token_id": cfg.image_token_id,
        "video_token_id": cfg.video_token_id,
        "vision_start_token_id": cfg.vision_start_token_id,
        "text_config": {**cfg.text.to_hf_config(), "model_type": "qwen2_vl_text"},
        "vision_config": {
            "model_type": "qwen2_vl",
            "depth": cfg.vision.depth,
            "embed_dim": cfg.vision.embed_dim,
            "hidden_size": cfg.vision.hidden_size,
            "hidden_act": cfg.vision.hidden_act,
            "mlp_ratio": cfg.vision.mlp_ratio,
            "num_heads": cfg.vision.num_heads,
            "in_channels": cfg.vision.in_channels,
            "patch_size": cfg.vision.patch_size,
            "spatial_merge_size": cfg.vision.spatial_merge_size,
            "temporal_patch_size": cfg.vision.temporal_patch_size,
        },
    }
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump(hf_cfg, f, indent=2)


def config_from_hf(hf: Dict[str, Any], **overrides) -> Qwen2VLConfig:
    """Build from an HF Qwen2VLConfig dict (config.json)."""
    text_hf = dict(hf.get("text_config") or {})
    for key in ("vocab_size", "hidden_size", "intermediate_size",
                "num_hidden_layers", "num_attention_heads",
                "num_key_value_heads", "rope_theta", "rms_norm_eps",
                "tie_word_embeddings", "rope_scaling", "max_position_embeddings"):
        if key not in text_hf and key in hf:
            text_hf[key] = hf[key]
    text = TransformerConfig.from_hf_config(
        {**text_hf, "model_type": "qwen2"}, **overrides
    )
    vis_hf = dict(hf.get("vision_config") or {})
    vis_fields = {f for f in Qwen2VisionConfig.__dataclass_fields__}
    vision = Qwen2VisionConfig(**{k: v for k, v in vis_hf.items() if k in vis_fields})
    return Qwen2VLConfig(
        text=text,
        vision=vision,
        image_token_id=hf.get("image_token_id", 151655),
        video_token_id=hf.get("video_token_id", 151656),
        vision_start_token_id=hf.get("vision_start_token_id", 151652),
    )
