"""Qwen3-Next / Qwen3.5 hybrid GatedDeltaNet family.

Reference capability: ``veomni/models/transformers/qwen3_5/`` (8,146 LoC
generated modeling: hybrid linear-attention + full-attention decoder) with
``ops/kernels/gated_delta_rule/`` Triton kernels. Architecture (public
Qwen3Next): a periodic layer pattern — ``full_attention_interval - 1``
GatedDeltaNet linear-attention layers followed by one gated full-attention
layer — each with a (MoE or dense) MLP, shared expert + sigmoid gate.

TPU-first design:

* **Super-layer scan**: the layer pattern is periodic, so params are stacked
  as [G, P, ...] (G groups x P linear layers) and [G, ...] (one full-attn
  layer per group) and the forward is ONE ``lax.scan`` over G with an inner
  scan over P — two compiled layer bodies total regardless of depth.
* **Chunkwise gated delta rule in pure XLA**: the sequential delta-rule
  recurrence is reformulated chunkwise (chunk 64): the in-chunk UT transform
  is a batched unit-triangular solve (``jax.scipy.linalg.solve_triangular``
  — MXU-friendly, differentiable), and only the O(S/64) inter-chunk state
  scan is sequential. Numerics in f32 like the reference kernels.
* Depthwise causal conv1d = ``lax.conv_general_dilated`` with
  ``feature_group_count`` and left-only padding.

Semantics match ``transformers`` Qwen3Next (torch fallback path:
``torch_chunk_gated_delta_rule``) and are parity-tested against it.

Packed multi-segment rows are fully reset-aware: ``segment_ids`` mask the
full-attention layers (ops.attention facade), reset the delta-rule
recurrence at document boundaries (see ``chunk_gated_delta_rule``), and
zero conv taps crossing boundaries — matching the reference's varlen
``ops/kernels/gated_delta_rule`` handling. Sequence parallelism applies to
the full-attention layers via the ops.attention facade; linear layers
compute on the gathered sequence (GSPMD handles the sharded scan).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from veomni_tpu import ops
from veomni_tpu.models import transformer as core
from veomni_tpu.models.config import TransformerConfig

Params = Dict[str, Any]


# --------------------------------------------------------------------------
# Chunkwise gated delta rule
# --------------------------------------------------------------------------
def _l2norm(x, eps=1e-6):
    return x * jax.lax.rsqrt((x * x).sum(-1, keepdims=True) + eps)


def chunk_gated_delta_rule(q, k, v, g, beta, chunk: int = 64, segment_ids=None):
    """q/k [B,S,H,Dk] (pre-l2norm'd, head-repeated), v [B,S,H,Dv],
    g [B,S,H] log-decay (f32), beta [B,S,H]. Returns [B,S,H,Dv] (f32).

    Chunkwise form of: S_t = S_{t-1}*exp(g_t) + k_t (beta_t (v_t - k_t^T
    S_{t-1}exp(g_t)))^T; o_t = q_t S_t. In-chunk inversion via triangular
    solve instead of the reference's row-by-row forward substitution.

    ``segment_ids`` [B,S] (packed documents; 0 = padding) resets the
    recurrence at document boundaries, matching the reference's varlen
    handling (``ops/kernels/gated_delta_rule`` cu_seqlens path) without
    re-chunking per document: because documents are contiguous, every
    cross-document interaction is killed by masks —

    * in-chunk pair masks (tril AND same-segment) on the decay matrix, the
      UT-transform Gram matrix, and the intra-chunk attention: the
      triangular solve becomes block-diagonal per document, so ``v_prime``/
      ``k_cumdecay`` rows never mix documents;
    * a continuation mask (position's segment == segment at the end of the
      previous chunk) gates every read of the carried state S — only the
      document that was active at the previous chunk boundary may see it;
    * the state update keeps S only if no boundary occurred in the chunk and
      accumulates only positions belonging to the chunk-final document.
    """
    b, s, h, dk = q.shape
    dv = v.shape[-1]
    q, k, v = (x.transpose(0, 2, 1, 3).astype(jnp.float32) for x in (q, k, v))
    g = g.transpose(0, 2, 1).astype(jnp.float32)       # [B,H,S]
    beta = beta.transpose(0, 2, 1).astype(jnp.float32)  # [B,H,S]
    seg = (
        jnp.ones((b, s), jnp.int32)
        if segment_ids is None
        else segment_ids.astype(jnp.int32)
    )

    pad = (-s) % chunk
    if pad:
        q, k, v = (jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0))) for x in (q, k, v))
        g = jnp.pad(g, ((0, 0), (0, 0), (0, pad)))
        beta = jnp.pad(beta, ((0, 0), (0, 0), (0, pad)))
        seg = jnp.pad(seg, ((0, 0), (0, pad)))
    n = (s + pad) // chunk
    c = chunk

    q = q.reshape(b, h, n, c, dk) * (dk ** -0.5)
    k = k.reshape(b, h, n, c, dk)
    v = v.reshape(b, h, n, c, dv)
    g = g.reshape(b, h, n, c).cumsum(-1)               # in-chunk cumulative decay
    beta = beta.reshape(b, h, n, c)
    seg = seg.reshape(b, 1, n, c)                      # broadcast over heads

    k_beta = k * beta[..., None]
    v_beta = v * beta[..., None]
    # pair mask: j <= i AND same document (documents are contiguous, so the
    # in-chunk cumsum g_i - g_j spans only same-document decay when i,j are
    # in the same document)
    tril = jnp.tril(jnp.ones((c, c), bool))
    same = seg[..., :, None] == seg[..., None, :]      # [B,1,n,c,c]
    mask = tril & same
    # decay[i,j] = exp(g_i - g_j) for valid pairs. Mask the exponent BEFORE
    # exp: upper-triangle g_i - g_j is large-positive, and
    # where(mask, exp(big), 0) backprops 0 * inf = NaN through the exp.
    decay = jnp.exp(jnp.where(mask, g[..., :, None] - g[..., None, :], -1e30))

    # UT transform: T = (I + strict_tril(k_beta K^T * decay))^{-1}
    kk = jnp.einsum("bhnic,bhnjc->bhnij", k_beta, k) * decay
    kk = jnp.where(jnp.tril(jnp.ones((c, c), bool), -1) & same, kk, 0.0)
    eye = jnp.eye(c, dtype=jnp.float32)
    T = jax.scipy.linalg.solve_triangular(
        eye + kk, jnp.broadcast_to(eye, kk.shape), lower=True, unit_diagonal=True
    )
    v_prime = jnp.einsum("bhnij,bhnjd->bhnid", T, v_beta)
    k_cumdecay = jnp.einsum(
        "bhnij,bhnjd->bhnid", T, k_beta * jnp.exp(g)[..., None]
    )

    def chunk_step(carry, xs):
        S, seg_prev_last = carry                       # S [B,H,dk,dv]; [B,1]
        q_i, k_i, v_i, g_i, kcd_i, seg_i = xs          # seg_i [B,1,c]
        cont = (seg_i == seg_prev_last[..., None]).astype(jnp.float32)
        attn = jnp.einsum("bhic,bhjc->bhij", q_i, k_i)
        mask_i = tril & (seg_i[..., :, None] == seg_i[..., None, :])
        dec_i = jnp.exp(
            jnp.where(mask_i, g_i[..., :, None] - g_i[..., None, :], -1e30)
        )
        attn = jnp.where(mask_i, attn, 0.0) * dec_i
        v_new = v_i - jnp.einsum(
            "bhik,bhkd->bhid", kcd_i * cont[..., None], S
        )
        out_i = (
            jnp.einsum("bhik,bhkd->bhid", q_i * jnp.exp(g_i)[..., None], S)
            * cont[..., None]
            + jnp.einsum("bhij,bhjd->bhid", attn, v_new)
        )
        seg_last = seg_i[..., -1]                      # [B,1]
        keep = (seg_last == seg_prev_last).astype(jnp.float32)
        accum = (seg_i == seg_last[..., None]).astype(jnp.float32)
        g_last = g_i[..., -1]
        S = S * jnp.exp(g_last)[..., None, None] * keep[..., None, None] \
            + jnp.einsum(
                "bhik,bhid->bhkd",
                k_i * jnp.exp(g_last[..., None] - g_i)[..., None]
                * accum[..., None],
                v_new,
            )
        return (S, seg_last), out_i

    xs = tuple(
        jnp.moveaxis(x, 2, 0) for x in (q, k, v_prime, g, k_cumdecay, seg)
    )  # each [n, B, H, ...]
    S0 = jnp.zeros((b, h, dk, dv), jnp.float32)
    _, out = jax.lax.scan(chunk_step, (S0, seg[:, :, 0, 0]), xs)
    out = jnp.moveaxis(out, 0, 2).reshape(b, h, n * c, dv)[:, :, :s]
    return out.transpose(0, 2, 1, 3)  # [B,S,H,Dv]


def _causal_conv1d(x, weight, segment_ids=None):
    """Depthwise causal conv: x [B,S,C], weight [C,K] -> [B,S,C] (silu'd).

    Written as K shifted multiply-adds rather than ``lax.conv``: the kernel
    is tiny (K=4), elementwise ops fuse into the surrounding projections, and
    XLA:CPU's oneDNN grouped-conv path computes in reduced precision (breaks
    the HF-parity oracle).

    With ``segment_ids`` [B,S], taps reaching across a packed-document
    boundary are zeroed (each document sees the same left-zero-padded window
    it would see unpacked)."""
    s = x.shape[1]
    k = weight.shape[-1]
    xp = jnp.pad(x, ((0, 0), (k - 1, 0), (0, 0)))
    if segment_ids is None:
        return jax.nn.silu(
            sum(weight[None, None, :, i] * xp[:, i:i + s, :] for i in range(k))
        )
    # pad with -1 so out-of-range taps never match a real segment id
    segp = jnp.pad(segment_ids, ((0, 0), (k - 1, 0)), constant_values=-1)
    out = sum(
        weight[None, None, :, i]
        * xp[:, i:i + s, :]
        * (segp[:, i:i + s] == segment_ids)[..., None]
        for i in range(k)
    )
    return jax.nn.silu(out)


def _gated_delta_net(x, lp, cfg: TransformerConfig, segment_ids=None):
    """One GatedDeltaNet mixer (HF Qwen3NextGatedDeltaNet.forward)."""
    b, s, _ = x.shape
    nk, nv = cfg.linear_num_key_heads, cfg.linear_num_value_heads
    dk, dv = cfg.linear_key_head_dim, cfg.linear_value_head_dim
    rep = nv // nk
    key_dim, value_dim = nk * dk, nv * dv

    qkvz = jnp.dot(x, lp["in_proj_qkvz"])  # [B,S, 2*key_dim + 2*value_dim]
    ba = jnp.dot(x, lp["in_proj_ba"])      # [B,S, 2*nv]
    # per-k-head interleaved layout (HF fix_query_key_value_ordering)
    qkvz = qkvz.reshape(b, s, nk, 2 * dk + 2 * rep * dv)
    qg = qkvz[..., :dk]
    kg = qkvz[..., dk:2 * dk]
    vg = qkvz[..., 2 * dk:2 * dk + rep * dv].reshape(b, s, nv, dv)
    z = qkvz[..., 2 * dk + rep * dv:].reshape(b, s, nv, dv)
    ba = ba.reshape(b, s, nk, 2 * rep)
    b_ = ba[..., :rep].reshape(b, s, nv)
    a = ba[..., rep:].reshape(b, s, nv)

    # conv over flattened (q, k, v)
    mixed = jnp.concatenate(
        [qg.reshape(b, s, key_dim), kg.reshape(b, s, key_dim),
         vg.reshape(b, s, value_dim)], axis=-1
    )
    mixed = _causal_conv1d(mixed, lp["conv_weight"], segment_ids)
    q = mixed[..., :key_dim].reshape(b, s, nk, dk)
    k = mixed[..., key_dim:2 * key_dim].reshape(b, s, nk, dk)
    v = mixed[..., 2 * key_dim:].reshape(b, s, nv, dv)

    beta = jax.nn.sigmoid(b_.astype(jnp.float32))
    g = -jnp.exp(lp["A_log"].astype(jnp.float32)) * jax.nn.softplus(
        a.astype(jnp.float32) + lp["dt_bias"].astype(jnp.float32)
    )
    q = _l2norm(q.astype(jnp.float32))
    k = _l2norm(k.astype(jnp.float32))
    if rep > 1:
        q = jnp.repeat(q, rep, axis=2)
        k = jnp.repeat(k, rep, axis=2)

    out = chunk_gated_delta_rule(
        q, k, v, g, beta, segment_ids=segment_ids
    )  # [B,S,nv,dv] f32

    # gated RMSNorm (norm before gate), f32 silu gate
    var = (out * out).mean(-1, keepdims=True)
    out = out * jax.lax.rsqrt(var + cfg.rms_norm_eps)
    out = (lp["norm"] * out.astype(cfg.dtype)).astype(cfg.dtype)
    out = out * jax.nn.silu(z.astype(jnp.float32)).astype(cfg.dtype)
    return jnp.dot(out.reshape(b, s, value_dim), lp["out_proj"])


def _gated_full_attention(x, lp, cfg: TransformerConfig, cos, sin, segment_ids):
    """Full-attention mixer with per-head output gate (HF Qwen3NextAttention)."""
    b, s, _ = x.shape
    nh, nkv, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    qg = jnp.dot(x, lp["q_proj"]).reshape(b, s, nh, 2 * hd)
    q, gate = qg[..., :hd], qg[..., hd:]
    k = jnp.dot(x, lp["k_proj"]).reshape(b, s, nkv, hd)
    v = jnp.dot(x, lp["v_proj"]).reshape(b, s, nkv, hd)
    q = core._norm(q, lp["q_norm"], cfg)
    k = core._norm(k, lp["k_norm"], cfg)
    rot = cos.shape[-1]
    q_r, k_r = ops.apply_rotary(q[..., :rot], k[..., :rot], cos, sin)
    q = jnp.concatenate([q_r, q[..., rot:]], axis=-1)
    k = jnp.concatenate([k_r, k[..., rot:]], axis=-1)
    attn = ops.attention(
        q, k, v, segment_ids=segment_ids, causal=True, softmax_scale=hd ** -0.5
    )
    attn = attn * jax.nn.sigmoid(gate)
    return jnp.dot(attn.reshape(b, s, nh * hd), lp["o_proj"])


def _mlp(x, lp, cfg: TransformerConfig):
    """Dense or MoE MLP reusing the core helpers (incl. EP dispatch)."""
    b, s, h = x.shape
    if cfg.is_moe:
        from veomni_tpu.parallel.parallel_state import get_parallel_state_or_none

        ps = get_parallel_state_or_none()
        if ps is not None and ps.ep_enabled:
            from veomni_tpu.parallel.moe import ep_moe_mlp

            return ep_moe_mlp(x, lp, cfg, ps)
        out, aux = core._moe_mlp(x.reshape(b * s, h), lp, cfg)
        return out.reshape(b, s, h), aux, jnp.float32(0.0)
    gate = jnp.dot(x, lp["gate_proj"])
    up = jnp.dot(x, lp["up_proj"])
    out = jnp.dot(core.gated_act(gate, up, cfg), lp["down_proj"])
    return out, jnp.float32(0.0), jnp.float32(0.0)


def _sublayer(hidden, lp, mixer, *, cfg):
    constrain = core._activation_constraint()
    hidden = constrain(hidden)
    x = core._norm(hidden, lp["input_layernorm"], cfg)
    hidden = hidden + mixer(x, lp)
    hidden = constrain(hidden)
    x = core._norm(hidden, lp["post_attention_layernorm"], cfg)
    out, aux, dropped = _mlp(x, lp, cfg)
    return constrain(hidden + out), aux, dropped


# --------------------------------------------------------------------------
# Params
# --------------------------------------------------------------------------
def _mixer_linear_params(keys, cfg, L, pd):
    h, s = cfg.hidden_size, cfg.initializer_range
    nk, nv = cfg.linear_num_key_heads, cfg.linear_num_value_heads
    dk, dv = cfg.linear_key_head_dim, cfg.linear_value_head_dim
    key_dim, value_dim = nk * dk, nv * dv
    conv_dim = 2 * key_dim + value_dim
    return {
        "input_layernorm": jnp.ones((L, h), pd),
        "post_attention_layernorm": jnp.ones((L, h), pd),
        "in_proj_qkvz": core._dense_init(
            next(keys), (L, h, 2 * key_dim + 2 * value_dim), pd, s
        ),
        "in_proj_ba": core._dense_init(next(keys), (L, h, 2 * nv), pd, s),
        "conv_weight": core._dense_init(
            next(keys), (L, conv_dim, cfg.linear_conv_kernel_dim), pd, s
        ),
        "dt_bias": jnp.ones((L, nv), pd),
        "A_log": jnp.zeros((L, nv), pd),
        "norm": jnp.ones((L, dv), pd),
        "out_proj": core._dense_init(next(keys), (L, value_dim, h), pd, s),
    }


def _mixer_full_params(keys, cfg, L, pd):
    h, s = cfg.hidden_size, cfg.initializer_range
    nh, nkv, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    return {
        "input_layernorm": jnp.ones((L, h), pd),
        "post_attention_layernorm": jnp.ones((L, h), pd),
        "q_proj": core._dense_init(next(keys), (L, h, nh * hd * 2), pd, s),
        "k_proj": core._dense_init(next(keys), (L, h, nkv * hd), pd, s),
        "v_proj": core._dense_init(next(keys), (L, h, nkv * hd), pd, s),
        "o_proj": core._dense_init(next(keys), (L, nh * hd, h), pd, s),
        "q_norm": jnp.ones((L, hd), pd),
        "k_norm": jnp.ones((L, hd), pd),
    }


def _group_shape(cfg) -> Tuple[int, int]:
    interval = cfg.full_attention_interval
    L = cfg.num_hidden_layers
    if L % interval:
        raise ValueError(
            f"qwen3_next requires num_hidden_layers ({L}) divisible by "
            f"full_attention_interval ({interval})"
        )
    return L // interval, interval - 1  # (groups, linear layers per group)


def init_params(rng: jax.Array, cfg: TransformerConfig) -> Params:
    G, P = _group_shape(cfg)
    pd = cfg.param_dtype
    keys = iter(jax.random.split(rng, 64))
    mlp = partial(
        core._moe_params if cfg.is_moe else core._dense_mlp_params, keys, cfg
    )

    def reshape_gp(tree, lead):
        return jax.tree.map(lambda t: t.reshape(lead + t.shape[1:]), tree)

    params: Params = {
        "embed_tokens": core._dense_init(
            next(keys), (cfg.vocab_size, cfg.hidden_size), pd, cfg.initializer_range
        ),
        "norm": jnp.ones((cfg.hidden_size,), pd),
        "linear_layers": reshape_gp(
            {**_mixer_linear_params(keys, cfg, G * P, pd), **mlp(G * P, pd)},
            (G, P),
        ),
        "full_layers": {**_mixer_full_params(keys, cfg, G, pd), **mlp(G, pd)},
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = core._dense_init(
            next(keys), (cfg.hidden_size, cfg.vocab_size), pd, cfg.initializer_range
        )
    return params


def abstract_params(cfg: TransformerConfig) -> Params:
    return jax.eval_shape(lambda: init_params(jax.random.PRNGKey(0), cfg))


# --------------------------------------------------------------------------
# Forward / loss
# --------------------------------------------------------------------------
def forward_hidden(params, cfg, input_ids, position_ids, segment_ids=None,
                   inputs_embeds=None):
    compute = jax.tree.map(lambda p: p.astype(cfg.dtype), params)
    hidden = (
        inputs_embeds.astype(cfg.dtype)
        if inputs_embeds is not None
        else compute["embed_tokens"][input_ids]
    )
    rot_dim = int(cfg.head_dim * cfg.partial_rotary_factor)
    cos, sin = ops.rotary_tables(
        position_ids, rot_dim, cfg.rope_theta, rope_scaling=cfg.rope_scaling
    )
    cos, sin = cos.astype(cfg.dtype), sin.astype(cfg.dtype)

    def super_layer(hidden, group):
        lin, full = group

        def lin_body(h_, lp):
            h_, aux, drop = _sublayer(
                h_, lp,
                lambda x, lp_: _gated_delta_net(x, lp_, cfg, segment_ids),
                cfg=cfg,
            )
            return h_, (aux, drop)

        def full_body(h_, lp):
            h_, aux, drop = _sublayer(
                h_, lp,
                lambda x, lp_: _gated_full_attention(x, lp_, cfg, cos, sin, segment_ids),
                cfg=cfg,
            )
            return h_, (aux, drop)

        if cfg.remat:
            lin_body = jax.checkpoint(lin_body, policy=core._remat_policy(cfg))
            full_body = jax.checkpoint(full_body, policy=core._remat_policy(cfg))
        hidden, (auxes, drops) = jax.lax.scan(lin_body, hidden, lin)
        hidden, (aux_f, drop_f) = full_body(hidden, full)
        return hidden, (auxes.sum() + aux_f, drops.sum() + drop_f)

    hidden, (auxes, drops) = jax.lax.scan(
        super_layer, hidden, (compute["linear_layers"], compute["full_layers"])
    )
    hidden = core._norm(hidden, compute["norm"], cfg)
    return hidden, auxes.sum(), drops.sum() / max(cfg.num_hidden_layers, 1)


def loss_fn(params, cfg, batch):
    hidden, aux, dropped = forward_hidden(
        params, cfg, batch["input_ids"], batch["position_ids"],
        batch.get("segment_ids"),
    )
    return core.head_loss(params, cfg, hidden, batch["labels"], aux, dropped)


def forward_logits(params, cfg, input_ids, position_ids, segment_ids=None):
    hidden, _, _ = forward_hidden(params, cfg, input_ids, position_ids, segment_ids)
    kernel = core.lm_head_kernel(params, cfg).astype(cfg.dtype)
    return jnp.dot(hidden, kernel, preferred_element_type=jnp.float32)


# --------------------------------------------------------------------------
# HF checkpoint io
# --------------------------------------------------------------------------
def _hf_layer_maps(cfg):
    """(our_key, hf_suffix, transpose) for each mixer kind + the MLP."""
    lin = [
        ("input_layernorm", "input_layernorm.weight", False),
        ("post_attention_layernorm", "post_attention_layernorm.weight", False),
        ("in_proj_qkvz", "linear_attn.in_proj_qkvz.weight", True),
        ("in_proj_ba", "linear_attn.in_proj_ba.weight", True),
        ("dt_bias", "linear_attn.dt_bias", False),
        ("A_log", "linear_attn.A_log", False),
        ("norm", "linear_attn.norm.weight", False),
        ("out_proj", "linear_attn.out_proj.weight", True),
    ]
    full = [
        ("input_layernorm", "input_layernorm.weight", False),
        ("post_attention_layernorm", "post_attention_layernorm.weight", False),
        ("q_proj", "self_attn.q_proj.weight", True),
        ("k_proj", "self_attn.k_proj.weight", True),
        ("v_proj", "self_attn.v_proj.weight", True),
        ("o_proj", "self_attn.o_proj.weight", True),
        ("q_norm", "self_attn.q_norm.weight", False),
        ("k_norm", "self_attn.k_norm.weight", False),
    ]
    if cfg.is_moe:
        mlp = [
            ("router", "mlp.gate.weight", True),
            ("shared_experts.gate_proj", "mlp.shared_expert.gate_proj.weight", True),
            ("shared_experts.up_proj", "mlp.shared_expert.up_proj.weight", True),
            ("shared_experts.down_proj", "mlp.shared_expert.down_proj.weight", True),
            ("shared_expert_gate", "mlp.shared_expert_gate.weight", True),
        ]
    else:
        mlp = [
            ("gate_proj", "mlp.gate_proj.weight", True),
            ("up_proj", "mlp.up_proj.weight", True),
            ("down_proj", "mlp.down_proj.weight", True),
        ]
    return lin, full, mlp


def hf_to_params(model_dir: str, cfg: TransformerConfig, target_shardings=None):
    """Load an HF Qwen3Next checkpoint into the [G, P]-stacked layout.

    Streamed + shard-aligned like ``hf_io.hf_to_params``: with
    ``target_shardings`` every stacked tensor is built via
    ``jax.make_array_from_callback`` whose callback reads only the (layer,
    expert, feature) slices the local shards need from the mmap'd
    safetensors — peak host RAM O(one shard slice), EP processes read only
    their expert slice (reference ``module_utils.py:348,530,867``)."""
    import itertools

    import numpy as np

    from veomni_tpu.models.hf_io import LazyHFTensors
    from veomni_tpu.parallel.parallel_plan import param_path_str

    G, P = _group_shape(cfg)
    interval = cfg.full_attention_interval
    lin_map, full_map, mlp_map = _hf_layer_maps(cfg)
    lazy = LazyHFTensors(model_dir)
    pd = cfg.param_dtype
    pd_np = np.dtype(jnp.zeros((), pd).dtype)

    shardings: Dict[str, Any] = {}
    if target_shardings is not None:
        jax.tree_util.tree_map_with_path(
            lambda p, s: shardings.__setitem__(param_path_str(p), s),
            target_shardings,
        )

    def place(dotted, shape, read_block):
        sh = shardings.get(dotted)
        if shardings and sh is None:
            raise KeyError(f"param {dotted!r} missing from target_shardings")
        if sh is not None:
            return jax.make_array_from_callback(
                tuple(shape), sh,
                lambda idx: np.ascontiguousarray(read_block(idx)).astype(pd_np),
            )
        full = read_block(tuple(slice(None) for _ in shape))
        return jnp.asarray(np.ascontiguousarray(full), pd)

    def lead_positions(lead_slices, lead):
        """Cartesian product of the selected leading (group/per-group)
        positions -> flat layer-list indices + output block shape."""
        ranges = [range(*sl.indices(n)) for sl, n in zip(lead_slices, lead)]
        return list(itertools.product(*ranges)), [len(r) for r in ranges]

    def stacked(dotted, names, lead, transpose, extract=None):
        one = lazy.shape(names[0])
        one_ours = tuple(reversed(one)) if transpose else one
        if extract is not None:
            one_ours = extract.shape(one_ours)
        for real in names:
            lazy.mark_consumed(real)

        def read(idx):
            lead_sl, rest = idx[: len(lead)], tuple(idx[len(lead):])
            pos, block = lead_positions(lead_sl, lead)
            parts = []
            for coords in pos:
                flat = 0
                for c, n in zip(coords, lead):
                    flat = flat * n + c
                if extract is not None:
                    part = extract.extract(
                        lazy.read_slice(names[flat], tuple(slice(None) for _ in one))
                    )[rest]
                elif transpose:
                    part = lazy.read_slice(names[flat], tuple(reversed(rest))).T
                else:
                    part = lazy.read_slice(names[flat], rest)
                parts.append(part)
            return np.stack(parts).reshape(tuple(block) + parts[0].shape)

        return place(dotted, tuple(lead) + tuple(one_ours), read)

    class _ConvSqueeze:
        """HF conv1d [C, 1, K] -> [C, K]."""

        @staticmethod
        def shape(s):
            return (s[0], s[2])

        @staticmethod
        def extract(t):
            return t[:, 0, :]

    def experts_stacked(dotted, idxs, lead, name):
        names = [
            [f"model.layers.{i}.mlp.experts.{e}.{name}.weight"
             for e in range(cfg.num_experts)]
            for i in idxs
        ]
        for row in names:
            for real in row:
                lazy.mark_consumed(real)
        o_dim, i_dim = lazy.shape(names[0][0])

        def read(idx):
            lead_sl, esl = idx[: len(lead)], idx[len(lead)]
            isl, osl = idx[len(lead) + 1], idx[len(lead) + 2]
            pos, block = lead_positions(lead_sl, lead)
            parts = []
            for coords in pos:
                flat = 0
                for c, n in zip(coords, lead):
                    flat = flat * n + c
                row = [
                    lazy.read_slice(names[flat][e], (osl, isl)).T
                    for e in range(*esl.indices(cfg.num_experts))
                ]
                parts.append(np.stack(row))
            return np.stack(parts).reshape(
                tuple(block) + parts[0].shape
            )

        return place(
            dotted, tuple(lead) + (cfg.num_experts, i_dim, o_dim), read
        )

    lin_idxs = [i for i in range(cfg.num_hidden_layers) if (i + 1) % interval]
    full_idxs = [i for i in range(cfg.num_hidden_layers) if not (i + 1) % interval]

    def build_tree(prefix, idxs, maps, lead):
        out: Params = {}
        for ours, suffix, tr in maps:
            names = [f"model.layers.{i}.{suffix}" for i in idxs]
            node = out
            parts = ours.split(".")
            for p_ in parts[:-1]:
                node = node.setdefault(p_, {})
            node[parts[-1]] = stacked(
                f"{prefix}.{ours}", names, lead, tr
            )
        return out

    params: Params = {
        "embed_tokens": place(
            "embed_tokens",
            lazy.shape("model.embed_tokens.weight"),
            lambda idx: lazy.read_slice("model.embed_tokens.weight", idx),
        ),
        "norm": place(
            "norm", lazy.shape("model.norm.weight"),
            lambda idx: lazy.read_slice("model.norm.weight", idx),
        ),
        "linear_layers": build_tree("linear_layers", lin_idxs, lin_map + mlp_map, (G, P)),
        "full_layers": build_tree("full_layers", full_idxs, full_map + mlp_map, (G,)),
    }
    lazy.mark_consumed("model.embed_tokens.weight")
    lazy.mark_consumed("model.norm.weight")
    params["linear_layers"]["conv_weight"] = stacked(
        "linear_layers.conv_weight",
        [f"model.layers.{i}.linear_attn.conv1d.weight" for i in lin_idxs],
        (G, P), False, extract=_ConvSqueeze,
    )
    if cfg.is_moe:
        for tree, idxs, lead, prefix in (
            (params["linear_layers"], lin_idxs, (G, P), "linear_layers"),
            (params["full_layers"], full_idxs, (G,), "full_layers"),
        ):
            tree["experts"] = {
                name: experts_stacked(f"{prefix}.experts.{name}", idxs, lead, name)
                for name in ("gate_proj", "up_proj", "down_proj")
            }
    if not cfg.tie_word_embeddings:
        hf_shape = lazy.shape("lm_head.weight")
        params["lm_head"] = place(
            "lm_head", tuple(reversed(hf_shape)),
            lambda idx: lazy.read_slice(
                "lm_head.weight", tuple(reversed(idx))).T,
        )
        lazy.mark_consumed("lm_head.weight")
    return params


def save_hf_checkpoint(params, cfg: TransformerConfig, out_dir: str) -> None:
    """Export to HF Qwen3Next layout (inverse of hf_to_params)."""
    import os

    import numpy as np
    from safetensors.numpy import save_file

    from veomni_tpu.models.hf_io import gather_to_host

    host = gather_to_host(params)
    if jax.process_index() != 0:
        return
    os.makedirs(out_dir, exist_ok=True)
    G, P = _group_shape(cfg)
    interval = cfg.full_attention_interval
    lin_map, full_map, mlp_map = _hf_layer_maps(cfg)
    flat: Dict[str, Any] = {
        "model.embed_tokens.weight": np.asarray(host["embed_tokens"]),
        "model.norm.weight": np.asarray(host["norm"]),
    }
    if not cfg.tie_word_embeddings:
        flat["lm_head.weight"] = np.asarray(host["lm_head"]).T

    def unstack(tree, idxs, maps, lead_ndim):
        for ours, suffix, tr in maps:
            node = tree
            for p_ in ours.split("."):
                node = node[p_]
            t = np.asarray(node)
            t = t.reshape((-1,) + t.shape[lead_ndim:])
            for pos, i in enumerate(idxs):
                flat[f"model.layers.{i}.{suffix}"] = t[pos].T if tr else t[pos]

    lin_idxs = [i for i in range(cfg.num_hidden_layers) if (i + 1) % interval]
    full_idxs = [i for i in range(cfg.num_hidden_layers) if not (i + 1) % interval]
    unstack(host["linear_layers"], lin_idxs, lin_map + mlp_map, 2)
    unstack(host["full_layers"], full_idxs, full_map + mlp_map, 1)
    conv = np.asarray(host["linear_layers"]["conv_weight"])
    conv = conv.reshape((-1,) + conv.shape[2:])
    for pos, i in enumerate(lin_idxs):
        flat[f"model.layers.{i}.linear_attn.conv1d.weight"] = conv[pos][:, None, :]
    if cfg.is_moe:
        for tree, idxs, lead in (
            (host["linear_layers"], lin_idxs, 2),
            (host["full_layers"], full_idxs, 1),
        ):
            for name in ("gate_proj", "up_proj", "down_proj"):
                t = np.asarray(tree["experts"][name])
                t = t.reshape((-1,) + t.shape[lead:])
                for pos, i in enumerate(idxs):
                    for e in range(cfg.num_experts):
                        flat[f"model.layers.{i}.mlp.experts.{e}.{name}.weight"] = (
                            t[pos, e].T
                        )
    save_file({k: np.ascontiguousarray(v) for k, v in flat.items()},
              os.path.join(out_dir, "model.safetensors"))
    import json

    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump(cfg.to_hf_config(), f, indent=2)


def parallel_plan(cfg):
    from veomni_tpu.parallel.parallel_plan import ParallelPlan

    rules: Dict[str, tuple] = {}
    if cfg.is_moe:
        rules[r"(linear|full)_layers\.experts\..*"] = ("ep", "ep_fsdp", None)
        rules[r"(linear|full)_layers\.router$"] = ()
    return ParallelPlan(
        rules=rules,
        stacked_layer_prefixes=(("linear_layers", 2), ("full_layers", 1)),
    )
