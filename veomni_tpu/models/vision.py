"""Native ViT vision encoder (functional, scan-over-layers).

Reference capability: the vision towers inside
``veomni/models/transformers/qwen2_vl`` / ``qwen3_vl`` (patch embed ->
transformer blocks with full attention -> spatial merger projecting into the
LLM embedding space). TPU-first simplifications:

* fixed patch grid per image (config.image_size / patch_size), so every
  image contributes a *static* number of tokens — XLA-friendly, and it also
  subsumes the reference's ``dummy_forward`` deadlock prevention
  (``qwen3_vl/generated/...:1312``): every rank runs the vision tower on its
  (possibly all-padding) image slots each step, keeping collectives aligned
  by construction.
* full (non-causal) attention via the shared ``ops.attention`` facade;
  per-layer params stacked for ``lax.scan`` like the text core.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from veomni_tpu import ops


@dataclass
class ViTConfig:
    image_size: int = 224
    patch_size: int = 14
    num_channels: int = 3
    hidden_size: int = 256
    intermediate_size: int = 1024
    num_hidden_layers: int = 4
    num_attention_heads: int = 4
    layer_norm_eps: float = 1e-6
    spatial_merge_size: int = 2  # 2x2 patch merge before projection
    out_hidden_size: int = 1024  # LLM hidden size (projector output)
    initializer_range: float = 0.02

    @property
    def grid(self) -> int:
        return self.image_size // self.patch_size

    @property
    def tokens_per_image(self) -> int:
        return (self.grid // self.spatial_merge_size) ** 2

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


def _layer_norm(x, weight, bias, eps):
    dtype = x.dtype
    x = x.astype(jnp.float32)
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    x = (x - mu) * jax.lax.rsqrt(var + eps)
    return (x * weight + bias).astype(dtype)


def init_vit_params(rng: jax.Array, cfg: ViTConfig, dtype=jnp.float32) -> Dict[str, Any]:
    s = cfg.initializer_range
    h, inter = cfg.hidden_size, cfg.intermediate_size
    L = cfg.num_hidden_layers
    keys = iter(jax.random.split(rng, 16))
    patch_dim = cfg.num_channels * cfg.patch_size ** 2
    merge_dim = h * cfg.spatial_merge_size ** 2

    def init(key, shape):
        return (jax.random.normal(key, shape, jnp.float32) * s).astype(dtype)

    return {
        "patch_embed": init(next(keys), (patch_dim, h)),
        "pos_embed": init(next(keys), (cfg.grid ** 2, h)),
        "layers": {
            "ln1_w": jnp.ones((L, h), dtype), "ln1_b": jnp.zeros((L, h), dtype),
            "qkv": init(next(keys), (L, h, 3 * h)),
            "qkv_bias": jnp.zeros((L, 3 * h), dtype),
            "proj": init(next(keys), (L, h, h)),
            "ln2_w": jnp.ones((L, h), dtype), "ln2_b": jnp.zeros((L, h), dtype),
            "fc1": init(next(keys), (L, h, inter)),
            "fc1_b": jnp.zeros((L, inter), dtype),
            "fc2": init(next(keys), (L, inter, h)),
            "fc2_b": jnp.zeros((L, h), dtype),
        },
        "merger": {
            "ln_w": jnp.ones((h,), dtype), "ln_b": jnp.zeros((h,), dtype),
            "fc1": init(next(keys), (merge_dim, merge_dim)),
            "fc1_b": jnp.zeros((merge_dim,), dtype),
            "fc2": init(next(keys), (merge_dim, cfg.out_hidden_size)),
            "fc2_b": jnp.zeros((cfg.out_hidden_size,), dtype),
        },
    }


def _vit_layer(x, lp, cfg: ViTConfig):
    n, t, h = x.shape
    y = _layer_norm(x, lp["ln1_w"], lp["ln1_b"], cfg.layer_norm_eps)
    qkv = jnp.dot(y, lp["qkv"]) + lp["qkv_bias"]
    q, k, v = jnp.split(qkv.reshape(n, t, 3 * cfg.num_attention_heads, cfg.head_dim), 3, axis=2)
    attn = ops.attention(q, k, v, causal=False)
    x = x + jnp.dot(attn.reshape(n, t, h), lp["proj"])
    y = _layer_norm(x, lp["ln2_w"], lp["ln2_b"], cfg.layer_norm_eps)
    y = jax.nn.gelu(jnp.dot(y, lp["fc1"]) + lp["fc1_b"])
    return x + jnp.dot(y, lp["fc2"]) + lp["fc2_b"], None


def vit_forward(params, cfg: ViTConfig, pixel_patches: jax.Array) -> jax.Array:
    """pixel_patches [N_img, grid*grid, patch_dim] -> [N_img, tokens_per_image,
    out_hidden_size].

    Runs under a no-SP scoped ParallelState (per-module heterogeneous SP,
    reference ``use_parallel_state`` scoping + sp_gather_seqs,
    sequence_parallel/data.py:149-298): image-slot tensors are replicated
    along the sequence axes, so the tower computes at sp=1 while the
    surrounding LM keeps its ulysses/cp layout."""
    from veomni_tpu.parallel.parallel_state import (
        get_parallel_state_or_none, use_parallel_state,
    )

    ps = get_parallel_state_or_none()
    if ps is not None and ps.sp_enabled:
        with use_parallel_state(ps.without_sp()):
            return vit_forward(params, cfg, pixel_patches)
    x = jnp.dot(pixel_patches.astype(params["patch_embed"].dtype), params["patch_embed"])
    x = x + params["pos_embed"]

    layer = partial(_vit_layer, cfg=cfg)
    x, _ = jax.lax.scan(lambda c, lp: layer(c, lp), x, params["layers"])

    # spatial 2x2 merge: [N, g, g, h] -> [N, g/m * g/m, m*m*h]
    n = x.shape[0]
    g, m = cfg.grid, cfg.spatial_merge_size
    h = cfg.hidden_size
    x = x.reshape(n, g // m, m, g // m, m, h).transpose(0, 1, 3, 2, 4, 5)
    x = x.reshape(n, (g // m) ** 2, m * m * h)
    mg = params["merger"]
    x = _layer_norm(x, jnp.tile(mg["ln_w"], m * m), jnp.tile(mg["ln_b"], m * m),
                    cfg.layer_norm_eps)
    x = jax.nn.gelu(jnp.dot(x, mg["fc1"]) + mg["fc1_b"])
    return jnp.dot(x, mg["fc2"]) + mg["fc2_b"]


def images_to_patches(images: jax.Array, cfg: ViTConfig) -> jax.Array:
    """[N, H, W, C] uint8/float -> [N, grid*grid, patch_dim] normalized."""
    n, hh, ww, c = images.shape
    p = cfg.patch_size
    g = cfg.grid
    x = images.astype(jnp.float32) / 255.0 if images.dtype == jnp.uint8 else images.astype(jnp.float32)
    x = (x - 0.5) / 0.5
    x = x.reshape(n, g, p, g, p, c).transpose(0, 1, 3, 2, 4, 5).reshape(n, g * g, p * p * c)
    return x
