"""Omni composite: any-modality encoders + foundation LM + generation decoders.

Reference: ``veomni/models/seed_omni/modeling_seed_omni.py:63-423``
(SeedOmniModel = N encoders (vision/audio) + foundation LM + N decoders,
per-module configs, trainable-only toggles) and qwen2_5_omni/qwen3_omni_moe.

TPU design: like the VLM, every modality occupies *static slots* —
``pixel_patches [B, max_images, P, D]`` and ``audio_features
[B, max_audio, frames, mels]`` — and encoder outputs are scattered into the
token stream at modality-placeholder positions. Freezing is functional
(stop_gradient per module).

Image GENERATION (reference ``seed_omni/decoder/movqgan``, the lm_encode /
lm_head contract at ``decoder/base.py:63-98``): output images are VQ-encoded
by a MoVQGAN tokenizer into codebook indices; their codebook embeddings are
projected into the LM stream at ``image_gen_token_id`` slots, and a
generation head (linear-GELU-linear onto the codebook vocabulary) is trained
next-token over LM hidden states via the same fused chunked CE as the text
head — static shapes, no dynamic gathers (non-gen positions carry IGNORE
labels exactly like padded text).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from veomni_tpu.models import transformer
from veomni_tpu.models.config import TransformerConfig
from veomni_tpu.models.vision import ViTConfig, _vit_layer, init_vit_params, vit_forward
from veomni_tpu.models.vlm import merge_image_features


@dataclass
class AudioEncoderConfig:
    n_mels: int = 80
    max_frames: int = 100          # input frames per audio slot
    subsample: int = 4             # conv time-subsampling factor
    hidden_size: int = 256
    intermediate_size: int = 1024
    num_hidden_layers: int = 4
    num_attention_heads: int = 4
    layer_norm_eps: float = 1e-6
    out_hidden_size: int = 1024
    initializer_range: float = 0.02

    @property
    def tokens_per_audio(self) -> int:
        return self.max_frames // self.subsample


@dataclass
class ImageGenConfig:
    """Image-generation decoder attachment (reference
    ``seed_omni/decoder/movqgan/configuration_movqgan.py`` + GenerationHead).

    ``freeze_tokenizer`` mirrors ``set_projector_trainable_only``: the VQ
    autoencoder stays frozen while the aligner + generation head train;
    ``freeze_codebook=False`` additionally trains the codebook embedding."""

    movq: "MoVQGANConfig" = None
    gen_loss_weight: float = 1.0
    freeze_tokenizer: bool = True
    freeze_codebook: bool = True

    def __post_init__(self):
        from veomni_tpu.models.movqgan import MoVQGANConfig

        if self.movq is None:
            self.movq = MoVQGANConfig()
        elif isinstance(self.movq, dict):
            self.movq = MoVQGANConfig(**self.movq)

    @property
    def tokens_per_image(self) -> int:
        return self.movq.tokens_per_image


@dataclass
class OmniConfig:
    text: TransformerConfig = field(default_factory=TransformerConfig)
    vision: Optional[ViTConfig] = None
    audio: Optional[AudioEncoderConfig] = None
    image_gen: Optional[ImageGenConfig] = None
    image_token_id: int = 151655
    audio_token_id: int = 151646
    image_gen_token_id: int = 151859
    freeze_vision: bool = False
    freeze_audio: bool = False
    freeze_text: bool = False
    max_images: int = 2
    max_audio: int = 2
    max_gen_images: int = 1
    model_type: str = "seed_omni"

    def __post_init__(self):
        if isinstance(self.text, dict):
            self.text = TransformerConfig(**self.text)
        if isinstance(self.vision, dict):
            self.vision = ViTConfig(**self.vision)
        if isinstance(self.audio, dict):
            self.audio = AudioEncoderConfig(**self.audio)
        if isinstance(self.image_gen, dict):
            self.image_gen = ImageGenConfig(**self.image_gen)
        for enc in (self.vision, self.audio):
            if enc is not None:
                enc.out_hidden_size = self.text.hidden_size

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "text"), name)


def init_audio_params(rng: jax.Array, cfg: AudioEncoderConfig, dtype=jnp.float32):
    s = cfg.initializer_range
    h = cfg.hidden_size
    keys = iter(jax.random.split(rng, 8))

    def init(shape):
        return (jax.random.normal(next(keys), shape, jnp.float32) * s).astype(dtype)

    L = cfg.num_hidden_layers
    inter = cfg.intermediate_size
    return {
        # frame stacking "conv": subsample frames by stacking then projecting
        "subsample_proj": init((cfg.n_mels * cfg.subsample, h)),
        "pos_embed": init((cfg.tokens_per_audio, h)),
        "layers": {
            "ln1_w": jnp.ones((L, h), dtype), "ln1_b": jnp.zeros((L, h), dtype),
            "qkv": init((L, h, 3 * h)), "qkv_bias": jnp.zeros((L, 3 * h), dtype),
            "proj": init((L, h, h)),
            "ln2_w": jnp.ones((L, h), dtype), "ln2_b": jnp.zeros((L, h), dtype),
            "fc1": init((L, h, inter)), "fc1_b": jnp.zeros((L, inter), dtype),
            "fc2": init((L, inter, h)), "fc2_b": jnp.zeros((L, h), dtype),
        },
        "out_proj": init((h, cfg.out_hidden_size)),
    }


def audio_forward(params, cfg: AudioEncoderConfig, features: jax.Array) -> jax.Array:
    """features [N, max_frames, n_mels] -> [N, tokens_per_audio, out_hidden].

    Scoped to sp=1 like vit_forward (per-module heterogeneous SP): audio
    slots are replicated along the sequence axes."""
    from veomni_tpu.parallel.parallel_state import (
        get_parallel_state_or_none, use_parallel_state,
    )

    ps = get_parallel_state_or_none()
    if ps is not None and ps.sp_enabled:
        with use_parallel_state(ps.without_sp()):
            return audio_forward(params, cfg, features)
    n, frames, mels = features.shape
    t = cfg.tokens_per_audio
    x = features.astype(params["subsample_proj"].dtype)
    x = x[:, : t * cfg.subsample].reshape(n, t, cfg.subsample * mels)
    x = jnp.dot(x, params["subsample_proj"]) + params["pos_embed"]

    # reuse the generic full-attention encoder block (vision._vit_layer works
    # on any [N, T, H] with the same param names)
    vit_like = ViTConfig(
        hidden_size=cfg.hidden_size, intermediate_size=cfg.intermediate_size,
        num_attention_heads=cfg.num_attention_heads,
        layer_norm_eps=cfg.layer_norm_eps,
    )
    body = partial(_vit_layer, cfg=vit_like)
    x, _ = jax.lax.scan(lambda c, lp: body(c, lp), x, params["layers"])
    return jnp.dot(x, params["out_proj"])


def init_omni_params(rng: jax.Array, cfg: OmniConfig) -> Dict[str, Any]:
    r1, r2, r3 = jax.random.split(rng, 3)
    params: Dict[str, Any] = {
        "language_model": transformer.init_params(r1, cfg.text),
    }
    if cfg.vision is not None:
        params["vision_tower"] = init_vit_params(r2, cfg.vision, cfg.text.param_dtype)
    if cfg.audio is not None:
        params["audio_tower"] = init_audio_params(r3, cfg.audio, cfg.text.param_dtype)
    return params


def abstract_omni_params(cfg: OmniConfig):
    return jax.eval_shape(lambda: init_omni_params(jax.random.PRNGKey(0), cfg))


def omni_loss_fn(params, cfg: OmniConfig, batch) -> Tuple[jax.Array, Dict]:
    tcfg = cfg.text
    lm_params = params["language_model"]
    if cfg.freeze_text:
        lm_params = jax.lax.stop_gradient(lm_params)
    lm = jax.tree.map(lambda p: p.astype(tcfg.dtype), lm_params)
    input_ids = batch["input_ids"]
    embeds = lm["embed_tokens"][input_ids]
    if tcfg.embed_scale:  # forward_hidden skips this for inputs_embeds
        embeds = embeds * jnp.asarray(tcfg.embed_scale, tcfg.dtype)

    if cfg.vision is not None and "pixel_patches" in batch:
        vp = params["vision_tower"]
        if cfg.freeze_vision:
            vp = jax.lax.stop_gradient(vp)
        patches = batch["pixel_patches"]
        bi, mi = patches.shape[:2]
        feats = vit_forward(vp, cfg.vision, patches.reshape(bi * mi, *patches.shape[2:]))
        feats = feats.reshape(bi, mi, *feats.shape[1:])
        embeds = merge_image_features(
            embeds, input_ids, feats, batch["image_mask"], cfg.image_token_id
        )
    if cfg.audio is not None and "audio_features" in batch:
        ap = params["audio_tower"]
        if cfg.freeze_audio:
            ap = jax.lax.stop_gradient(ap)
        af = batch["audio_features"]
        bi, ma = af.shape[:2]
        feats = audio_forward(ap, cfg.audio, af.reshape(bi * ma, *af.shape[2:]))
        feats = feats.reshape(bi, ma, *feats.shape[1:])
        embeds = merge_image_features(  # same ordered-slot merge, audio token
            embeds, input_ids, feats, batch["audio_mask"], cfg.audio_token_id
        )

    hidden, moe_aux, moe_dropped = transformer.forward_hidden(
        lm_params, tcfg, input_ids, batch["position_ids"],
        batch.get("segment_ids"), inputs_embeds=embeds,
    )
    return transformer.head_loss(
        lm_params, tcfg, hidden, batch["labels"], moe_aux, moe_dropped
    )
