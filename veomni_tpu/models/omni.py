"""Omni composite: any-modality encoders + foundation LM + generation decoders.

Reference: ``veomni/models/seed_omni/modeling_seed_omni.py:63-423``
(SeedOmniModel = N encoders (vision/audio) + foundation LM + N decoders,
per-module configs, trainable-only toggles) and qwen2_5_omni/qwen3_omni_moe.

TPU design: like the VLM, every modality occupies *static slots* —
``pixel_patches [B, max_images, P, D]`` and ``audio_features
[B, max_audio, frames, mels]`` — and encoder outputs are scattered into the
token stream at modality-placeholder positions. Freezing is functional
(stop_gradient per module).

Image GENERATION (reference ``seed_omni/decoder/movqgan``, the lm_encode /
lm_head contract at ``decoder/base.py:63-98``): output images are VQ-encoded
by a MoVQGAN tokenizer into codebook indices; their codebook embeddings are
projected into the LM stream at ``image_gen_token_id`` slots, and a
generation head (linear-GELU-linear onto the codebook vocabulary) is trained
next-token over LM hidden states via the same fused chunked CE as the text
head — static shapes, no dynamic gathers (non-gen positions carry IGNORE
labels exactly like padded text).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from veomni_tpu.models import transformer
from veomni_tpu.models.config import TransformerConfig
from veomni_tpu.models.vision import ViTConfig, _vit_layer, init_vit_params, vit_forward
from veomni_tpu.models.vlm import merge_image_features


@dataclass
class AudioEncoderConfig:
    n_mels: int = 80
    max_frames: int = 100          # input frames per audio slot
    subsample: int = 4             # conv time-subsampling factor
    hidden_size: int = 256
    intermediate_size: int = 1024
    num_hidden_layers: int = 4
    num_attention_heads: int = 4
    layer_norm_eps: float = 1e-6
    out_hidden_size: int = 1024
    initializer_range: float = 0.02

    @property
    def tokens_per_audio(self) -> int:
        return self.max_frames // self.subsample


@dataclass
class ImageGenConfig:
    """Image-generation decoder attachment (reference seed_omni decoder
    contract, ``decoder/base.py:71-90`` + GenerationHead): any decoder from
    ``GEN_DECODER_REGISTRY`` (movqgan, janus_vq, ...) selected by
    ``decoder_type``.

    ``freeze_tokenizer`` mirrors ``set_projector_trainable_only``: the VQ
    autoencoder stays frozen while the aligner + generation head train;
    ``freeze_codebook=False`` additionally trains the codebook embedding."""

    decoder_type: str = "movqgan"
    movq: Any = None               # decoder config (name kept from the
    # original movq-only attachment; holds whichever decoder_type's config)
    gen_loss_weight: float = 1.0
    freeze_tokenizer: bool = True
    freeze_codebook: bool = True

    def __post_init__(self):
        dec = self.gen_decoder
        if self.movq is None:
            self.movq = dec.config_cls()
        elif isinstance(self.movq, dict):
            self.movq = dec.config_cls(**self.movq)
        if not self.freeze_tokenizer and not dec.trainable_tokenizer:
            raise ValueError(
                f"decoder {dec.name!r} has no trainable quantization "
                "objective (implicit FSQ codebook) — freeze_tokenizer=False "
                "would be a silent no-op; train it offline via its "
                "reconstruction objective instead"
            )

    @property
    def gen_decoder(self):
        from veomni_tpu.models.gen_decoders import get_gen_decoder

        return get_gen_decoder(self.decoder_type)

    @property
    def tokens_per_image(self) -> int:
        return self.gen_decoder.tokens_per_image(self.movq)

    @property
    def image_size(self) -> int:
        return self.gen_decoder.image_size(self.movq)


@dataclass
class OmniConfig:
    text: TransformerConfig = field(default_factory=TransformerConfig)
    vision: Optional[ViTConfig] = None
    audio: Optional[AudioEncoderConfig] = None
    image_gen: Optional[ImageGenConfig] = None
    image_token_id: int = 151655
    audio_token_id: int = 151646
    image_gen_token_id: int = 151859
    freeze_vision: bool = False
    freeze_audio: bool = False
    freeze_text: bool = False
    max_images: int = 2
    max_audio: int = 2
    max_gen_images: int = 1
    model_type: str = "seed_omni"

    def __post_init__(self):
        if isinstance(self.text, dict):
            self.text = TransformerConfig(**self.text)
        if isinstance(self.vision, dict):
            self.vision = ViTConfig(**self.vision)
        if isinstance(self.audio, dict):
            self.audio = AudioEncoderConfig(**self.audio)
        if isinstance(self.image_gen, dict):
            self.image_gen = ImageGenConfig(**self.image_gen)
        for enc in (self.vision, self.audio):
            if enc is not None:
                enc.out_hidden_size = self.text.hidden_size

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "text"), name)


def init_audio_params(rng: jax.Array, cfg: AudioEncoderConfig, dtype=jnp.float32):
    s = cfg.initializer_range
    h = cfg.hidden_size
    keys = iter(jax.random.split(rng, 8))

    def init(shape):
        return (jax.random.normal(next(keys), shape, jnp.float32) * s).astype(dtype)

    L = cfg.num_hidden_layers
    inter = cfg.intermediate_size
    return {
        # frame stacking "conv": subsample frames by stacking then projecting
        "subsample_proj": init((cfg.n_mels * cfg.subsample, h)),
        "pos_embed": init((cfg.tokens_per_audio, h)),
        "layers": {
            "ln1_w": jnp.ones((L, h), dtype), "ln1_b": jnp.zeros((L, h), dtype),
            "qkv": init((L, h, 3 * h)), "qkv_bias": jnp.zeros((L, 3 * h), dtype),
            "proj": init((L, h, h)),
            "ln2_w": jnp.ones((L, h), dtype), "ln2_b": jnp.zeros((L, h), dtype),
            "fc1": init((L, h, inter)), "fc1_b": jnp.zeros((L, inter), dtype),
            "fc2": init((L, inter, h)), "fc2_b": jnp.zeros((L, h), dtype),
        },
        "out_proj": init((h, cfg.out_hidden_size)),
    }


def audio_forward(params, cfg: AudioEncoderConfig, features: jax.Array) -> jax.Array:
    """features [N, max_frames, n_mels] -> [N, tokens_per_audio, out_hidden].

    Scoped to sp=1 like vit_forward (per-module heterogeneous SP): audio
    slots are replicated along the sequence axes."""
    from veomni_tpu.parallel.parallel_state import (
        get_parallel_state_or_none, use_parallel_state,
    )

    ps = get_parallel_state_or_none()
    if ps is not None and ps.sp_enabled:
        with use_parallel_state(ps.without_sp()):
            return audio_forward(params, cfg, features)
    n, frames, mels = features.shape
    t = cfg.tokens_per_audio
    x = features.astype(params["subsample_proj"].dtype)
    x = x[:, : t * cfg.subsample].reshape(n, t, cfg.subsample * mels)
    x = jnp.dot(x, params["subsample_proj"]) + params["pos_embed"]

    # reuse the generic full-attention encoder block (vision._vit_layer works
    # on any [N, T, H] with the same param names)
    vit_like = ViTConfig(
        hidden_size=cfg.hidden_size, intermediate_size=cfg.intermediate_size,
        num_attention_heads=cfg.num_attention_heads,
        layer_norm_eps=cfg.layer_norm_eps,
    )
    body = partial(_vit_layer, cfg=vit_like)
    x, _ = jax.lax.scan(lambda c, lp: body(c, lp), x, params["layers"])
    return jnp.dot(x, params["out_proj"])


def build_gen_labels(input_ids, codes, gen_mask, gen_token_id, tokens_per_image,
                     segment_ids=None):
    """Next-token codebook labels [B,S] for autoregressive image generation
    (shared by the seed_omni and janus composites).

    ``codes`` [B, max_gen * T] holds each slot image's VQ indices in slot
    order; position p gets the code at p+1 (IGNORE off gen slots / across
    packed-segment boundaries)."""
    from veomni_tpu.data.data_collator import IGNORE_INDEX

    bi = input_ids.shape[0]
    mg = codes.shape[1] // tokens_per_image
    is_gen = input_ids == gen_token_id
    ordinal = jnp.cumsum(is_gen.astype(jnp.int32), axis=1) - 1
    img_i_raw = ordinal // tokens_per_image
    img_i = jnp.clip(img_i_raw, 0, mg - 1)
    tok_i = jnp.clip(ordinal % tokens_per_image, 0, tokens_per_image - 1)
    code_at = jnp.take_along_axis(codes, img_i * tokens_per_image + tok_i, axis=1)
    valid = (
        is_gen
        & (img_i_raw < mg)
        & jnp.take_along_axis(gen_mask, img_i, axis=1)
    )
    code_at = jnp.where(valid, code_at, IGNORE_INDEX)
    gen_labels = jnp.concatenate(
        [code_at[:, 1:], jnp.full((bi, 1), IGNORE_INDEX, code_at.dtype)], axis=1
    )
    if segment_ids is not None:  # no cross-segment prediction under packing
        same = jnp.concatenate(
            [segment_ids[:, 1:] == segment_ids[:, :-1], jnp.zeros((bi, 1), bool)],
            axis=1,
        )
        gen_labels = jnp.where(same, gen_labels, IGNORE_INDEX)
    return gen_labels


def init_image_gen_params(rng: jax.Array, cfg: OmniConfig) -> Dict[str, Any]:
    """Registered VQ decoder + gen_aligner (codebook -> LM stream,
    Linear-GELU-Linear like reference ``seed_omni/projector.py:20-33``) +
    generation head (Linear-GELU-Linear onto the codebook vocab,
    ``GenerationHead`` at ``decoder/movqgan/modeling_movqgan.py:40-52``)."""
    icfg = cfg.image_gen
    dec = icfg.gen_decoder
    h = cfg.text.hidden_size
    e = dec.embed_dim(icfg.movq)
    v = dec.codebook_size(icfg.movq)
    s = icfg.movq.initializer_range
    r1, r2, r3, r4, r5 = jax.random.split(rng, 5)

    def init(key, shape):
        return jax.random.normal(key, shape, jnp.float32) * s

    return {
        "movq": dec.init_params(r1, icfg.movq),
        "aligner": {
            "fc1": init(r2, (e, h)), "fc1_b": jnp.zeros((h,), jnp.float32),
            "fc2": init(r3, (h, h)), "fc2_b": jnp.zeros((h,), jnp.float32),
        },
        "gen_head": {
            "fc1": init(r4, (h, h)), "fc1_b": jnp.zeros((h,), jnp.float32),
            "fc2": init(r5, (h, v)), "fc2_b": jnp.zeros((v,), jnp.float32),
        },
    }


def apply_aligner(al, x):
    """gen_aligner MLP (codebook embedding -> LM stream); shared by the
    training loss and autoregressive generation so the two can't diverge."""
    h = jax.nn.gelu(jnp.dot(x, al["fc1"]) + al["fc1_b"])
    return jnp.dot(h, al["fc2"]) + al["fc2_b"]


def gen_head_hidden(gh, h):
    """First half of the generation head (pre-vocab projection + GELU);
    the fused CE folds the final projection, generation materializes it."""
    return jax.nn.gelu(jnp.dot(h, gh["fc1"]) + gh["fc1_b"])


def gen_head_logits(gh, h):
    return jnp.dot(gen_head_hidden(gh, h), gh["fc2"]) + gh["fc2_b"]


def gen_head_ce(hidden, gh, gen_labels):
    """Generation-head (Linear-GELU-Linear onto the codebook vocab) loss via
    the fused chunked CE; the head bias folds in as a ones column so the
    [T, codebook] logits never materialize. Shared by seed_omni and janus."""
    from veomni_tpu.ops.cross_entropy import fused_linear_cross_entropy

    b, s, h = hidden.shape
    g = gen_head_hidden(gh, hidden.reshape(b * s, h))
    g1 = jnp.concatenate([g, jnp.ones((b * s, 1), g.dtype)], axis=1)
    k1 = jnp.concatenate([gh["fc2"], gh["fc2_b"][None, :]], axis=0)
    return fused_linear_cross_entropy(g1, k1, gen_labels.reshape(-1))


def init_omni_params(rng: jax.Array, cfg: OmniConfig) -> Dict[str, Any]:
    r1, r2, r3, r4 = jax.random.split(rng, 4)
    params: Dict[str, Any] = {
        "language_model": transformer.init_params(r1, cfg.text),
    }
    if cfg.vision is not None:
        params["vision_tower"] = init_vit_params(r2, cfg.vision, cfg.text.param_dtype)
    if cfg.audio is not None:
        params["audio_tower"] = init_audio_params(r3, cfg.audio, cfg.text.param_dtype)
    if cfg.image_gen is not None:
        params["image_gen"] = init_image_gen_params(r4, cfg)
    return params


def abstract_omni_params(cfg: OmniConfig):
    return jax.eval_shape(lambda: init_omni_params(jax.random.PRNGKey(0), cfg))


def generate_image(params, cfg: OmniConfig, prompt_ids, rng,
                   temperature: float = 1.0):
    """Autoregressive image generation — the ``lm_generate`` half of the
    seed_omni decoder contract (reference ``decoder/base.py:87-90`` +
    ``MoVQGANDecoder.lm_embed/lm_generate``).

    prompt_ids [B, P] -> (pixels [B, H, W, C], codes [B, T]). Each step runs
    the full prefix through the LM (teacher-forced stack, no KV cache — the
    decode loop is ``lax.scan`` over T with a statically padded sequence, so
    it jits once; T = tokens_per_image is 16-1024, and this path is a
    correctness/parity surface, not the serving path)."""
    icfg = cfg.image_gen
    dec = icfg.gen_decoder
    tcfg = cfg.text
    t_gen = icfg.tokens_per_image
    b, p_len = prompt_ids.shape
    s = p_len + t_gen
    lm = params["language_model"]
    gp = params["image_gen"]
    al = jax.tree.map(lambda t: t.astype(tcfg.dtype), gp["aligner"])
    gh = jax.tree.map(lambda t: t.astype(tcfg.dtype), gp["gen_head"])
    embed = lm["embed_tokens"].astype(tcfg.dtype)

    def code_embed(codes):
        cb = dec.code_embeds(gp["movq"], icfg.movq, codes)
        return apply_aligner(al, cb.astype(tcfg.dtype))

    positions = jnp.broadcast_to(jnp.arange(s), (b, s)).astype(jnp.int32)
    segments = jnp.ones((b, s), jnp.int32)
    ids_full = jnp.concatenate(
        [prompt_ids.astype(jnp.int32),
         jnp.full((b, t_gen), cfg.image_gen_token_id, jnp.int32)], axis=1
    )

    def step(carry, i):
        codes, key = carry
        # embeddings: prompt tokens + already-generated code embeddings at
        # the gen slots (future slots hold the placeholder embedding, masked
        # off by causality)
        gen_embeds = code_embed(codes)                     # [B, T, H]
        base = embed[ids_full]
        if tcfg.embed_scale:  # match the training path's prompt scaling
            base = base * jnp.asarray(tcfg.embed_scale, tcfg.dtype)
        slot = jnp.arange(t_gen)[None, :, None]
        gen_part = jnp.where(slot < i, gen_embeds, base[:, p_len:])
        embeds = jnp.concatenate([base[:, :p_len], gen_part], axis=1)
        hidden, _, _ = transformer.forward_hidden(
            lm, tcfg, ids_full, positions, segments, inputs_embeds=embeds
        )
        h_pred = hidden[:, p_len + i - 1]                  # predicts slot i
        logits = gen_head_logits(gh, h_pred).astype(jnp.float32)
        key, sub = jax.random.split(key)
        nxt = jax.random.categorical(sub, logits / jnp.maximum(temperature, 1e-6))
        codes = codes.at[:, i].set(nxt.astype(jnp.int32))
        return (codes, key), None

    codes0 = jnp.zeros((b, t_gen), jnp.int32)
    (codes, _), _ = jax.lax.scan(step, (codes0, rng), jnp.arange(t_gen))
    pixels = dec.decode(gp["movq"], icfg.movq, codes)
    return pixels, codes


def omni_loss_fn(params, cfg: OmniConfig, batch) -> Tuple[jax.Array, Dict]:
    tcfg = cfg.text
    lm_params = params["language_model"]
    if cfg.freeze_text:
        lm_params = jax.lax.stop_gradient(lm_params)
    lm = jax.tree.map(lambda p: p.astype(tcfg.dtype), lm_params)
    input_ids = batch["input_ids"]
    embeds = lm["embed_tokens"][input_ids]
    if tcfg.embed_scale:  # forward_hidden skips this for inputs_embeds
        embeds = embeds * jnp.asarray(tcfg.embed_scale, tcfg.dtype)

    if cfg.vision is not None and "pixel_patches" in batch:
        vp = params["vision_tower"]
        if cfg.freeze_vision:
            vp = jax.lax.stop_gradient(vp)
        patches = batch["pixel_patches"]
        bi, mi = patches.shape[:2]
        feats = vit_forward(vp, cfg.vision, patches.reshape(bi * mi, *patches.shape[2:]))
        feats = feats.reshape(bi, mi, *feats.shape[1:])
        embeds = merge_image_features(
            embeds, input_ids, feats, batch["image_mask"], cfg.image_token_id
        )
    if cfg.audio is not None and "audio_features" in batch:
        ap = params["audio_tower"]
        if cfg.freeze_audio:
            ap = jax.lax.stop_gradient(ap)
        af = batch["audio_features"]
        bi, ma = af.shape[:2]
        feats = audio_forward(ap, cfg.audio, af.reshape(bi * ma, *af.shape[2:]))
        feats = feats.reshape(bi, ma, *feats.shape[1:])
        embeds = merge_image_features(  # same ordered-slot merge, audio token
            embeds, input_ids, feats, batch["audio_mask"], cfg.audio_token_id
        )

    # ---- image generation: VQ-tokenize target images, inject aligned
    # codebook embeddings at image_gen_token_id slots, build next-token
    # codebook labels (reference MoVQGANDecoder.lm_encode/lm_head contract,
    # ``seed_omni/decoder/movqgan/modeling_movqgan.py:97-151``)
    gen_labels = None
    vq_loss = None
    if cfg.image_gen is not None and "gen_pixels" in batch:
        icfg = cfg.image_gen
        dec = icfg.gen_decoder
        gp = params["image_gen"]
        enc_p = gp["movq"]
        if icfg.freeze_tokenizer:
            enc_p = jax.lax.stop_gradient(enc_p)
        px = batch["gen_pixels"]                     # [B, max_gen, H, W, C]
        bi, mg = px.shape[:2]
        gen_mask = batch["gen_image_mask"]
        codes, vq_per = dec.encode_codes(
            enc_p, icfg.movq, px.reshape(bi * mg, *px.shape[2:])
        )
        if not icfg.freeze_tokenizer:
            # mask zero-filled dummy slots out of the VQ/commit objective
            m = gen_mask.reshape(-1).astype(jnp.float32)
            vq_loss = (vq_per * m).sum() / jnp.maximum(m.sum(), 1.0)
        t_gen = icfg.tokens_per_image
        idx = codes.reshape(bi, mg, t_gen)           # codebook index per slot
        # the LM-side code embedding trains iff freeze_codebook is off
        # (reference set_projector_trainable_only); FSQ decoders (cosmos)
        # have an implicit codebook — nothing to freeze
        emb_p = dict(gp["movq"])
        if icfg.freeze_codebook and "codebook" in emb_p:
            emb_p["codebook"] = jax.lax.stop_gradient(emb_p["codebook"])
        cb = dec.code_embeds(emb_p, icfg.movq, idx)  # [B, mg, T, e] f32
        al = jax.tree.map(lambda p: p.astype(tcfg.dtype), gp["aligner"])
        feats = apply_aligner(al, cb.astype(tcfg.dtype))  # [B, mg, T, H]
        embeds = merge_image_features(
            embeds, input_ids, feats, gen_mask, cfg.image_gen_token_id
        )
        gen_labels = build_gen_labels(
            input_ids, idx.reshape(bi, mg * t_gen), gen_mask,
            cfg.image_gen_token_id, t_gen, batch.get("segment_ids"),
        )

    hidden, moe_aux, moe_dropped = transformer.forward_hidden(
        lm_params, tcfg, input_ids, batch["position_ids"],
        batch.get("segment_ids"), inputs_embeds=embeds,
    )
    total, metrics = transformer.head_loss(
        lm_params, tcfg, hidden, batch["labels"], moe_aux, moe_dropped
    )
    if gen_labels is not None:
        gh = jax.tree.map(lambda p: p.astype(tcfg.dtype), params["image_gen"]["gen_head"])
        gen_sum, gen_n = gen_head_ce(hidden, gh, gen_labels)
        total = total + cfg.image_gen.gen_loss_weight * gen_sum
        # gen tokens join the token-sum normalization space (train_step
        # divides by ntokens after the dp/sp psum)
        metrics["ntokens"] = metrics["ntokens"] + gen_n
        metrics["gen_loss_sum"] = gen_sum
        metrics["gen_ntokens"] = gen_n
        if vq_loss is not None:  # sum-space like the router aux loss
            total = total + vq_loss * gen_n
            metrics["vq_loss"] = vq_loss
    return total, metrics
