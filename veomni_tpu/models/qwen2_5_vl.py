"""Qwen2.5-VL: the real architecture — window-attention ViT, mrope, merger.

Reference: ``veomni/models/transformers/qwen2_5vl/`` (3.5k LoC generated
modeling; upstream contract = HF ``Qwen2_5_VLForConditionalGeneration``).
Architecture (verified against the installed transformers source):

* vision tower: Conv3D patch embed (temporal 2 x 14 x 14 — a pure linear on
  flattened patches), 2D-rope over (h, w) patch positions, RMSNorm blocks
  with **window attention** (112px windows; ``fullatt_block_indexes`` layers
  attend globally per image), biased-SwiGLU MLP, then a 2x2 spatial merger
  MLP projecting into the LLM width.
* LM: qwen2-dialect decoder with **mrope** — 3 rope streams (t/h/w) mixed
  per frequency section (``rope_scaling.mrope_section``).

TPU-first design: every dynamic-shape construct of the torch code
(``get_window_index`` python loops, varlen cu_seqlens attention, dynamic
feature scatter) becomes a *host-precomputed index plan* over a statically
padded patch sequence:

* the collator packs all images of the batch into ONE padded patch sequence
  **already in window order** and emits segment ids for window- and
  full-attention layers (our packed-attention masking contract), rope (h, w)
  positions, and the merged-token inverse permutation;
* inside jit the tower is pure gathers + dense math; padding patches live in
  segment 0 and their features are never scattered into the text stream.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from veomni_tpu import ops
from veomni_tpu.models import transformer
from veomni_tpu.models.config import TransformerConfig


@dataclass
class Qwen25VisionConfig:
    """HF ``Qwen2_5_VLVisionConfig`` surface (defaults = 7B checkpoint)."""

    depth: int = 32
    hidden_size: int = 1280
    intermediate_size: int = 3420
    num_heads: int = 16
    in_channels: int = 3
    patch_size: int = 14
    temporal_patch_size: int = 2
    spatial_merge_size: int = 2
    window_size: int = 112
    fullatt_block_indexes: Tuple[int, ...] = (7, 15, 23, 31)
    out_hidden_size: int = 3584
    hidden_act: str = "silu"
    tokens_per_second: float = 2.0
    initializer_range: float = 0.02

    def __post_init__(self):
        self.fullatt_block_indexes = tuple(self.fullatt_block_indexes)

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def patch_dim(self) -> int:
        return self.in_channels * self.temporal_patch_size * self.patch_size ** 2

    @property
    def merge_unit(self) -> int:
        return self.spatial_merge_size ** 2


@dataclass
class Qwen25VLConfig:
    text: TransformerConfig = field(default_factory=TransformerConfig)
    vision: Qwen25VisionConfig = field(default_factory=Qwen25VisionConfig)
    image_token_id: int = 151655
    video_token_id: int = 151656
    vision_start_token_id: int = 151652
    freeze_vision: bool = False
    model_type: str = "qwen2_5_vl"

    def __post_init__(self):
        if isinstance(self.text, dict):
            self.text = TransformerConfig(**self.text)
        if isinstance(self.vision, dict):
            self.vision = Qwen25VisionConfig(**self.vision)

    def __getattr__(self, name):  # FlopsCounter / trainer surface
        return getattr(object.__getattribute__(self, "text"), name)


# ---------------------------------------------------------------------------
# params
# ---------------------------------------------------------------------------

def init_vision_params(rng: jax.Array, cfg: Qwen25VisionConfig, dtype=jnp.float32):
    s = cfg.initializer_range
    d, i, L = cfg.hidden_size, cfg.intermediate_size, cfg.depth
    merge_dim = d * cfg.merge_unit
    keys = iter(jax.random.split(rng, 12))

    def init(key, shape):
        return (jax.random.normal(key, shape, jnp.float32) * s).astype(dtype)

    return {
        "patch_embed": init(next(keys), (cfg.patch_dim, d)),
        "blocks": {
            "norm1": jnp.ones((L, d), dtype),
            "norm2": jnp.ones((L, d), dtype),
            "qkv_w": init(next(keys), (L, d, 3 * d)),
            "qkv_b": jnp.zeros((L, 3 * d), dtype),
            "proj_w": init(next(keys), (L, d, d)),
            "proj_b": jnp.zeros((L, d), dtype),
            "gate_w": init(next(keys), (L, d, i)),
            "gate_b": jnp.zeros((L, i), dtype),
            "up_w": init(next(keys), (L, d, i)),
            "up_b": jnp.zeros((L, i), dtype),
            "down_w": init(next(keys), (L, i, d)),
            "down_b": jnp.zeros((L, d), dtype),
        },
        "merger": {
            "ln_q": jnp.ones((d,), dtype),
            "fc1_w": init(next(keys), (merge_dim, merge_dim)),
            "fc1_b": jnp.zeros((merge_dim,), dtype),
            "fc2_w": init(next(keys), (merge_dim, cfg.out_hidden_size)),
            "fc2_b": jnp.zeros((cfg.out_hidden_size,), dtype),
        },
    }


def init_params(rng: jax.Array, cfg: Qwen25VLConfig) -> Dict[str, Any]:
    r1, r2 = jax.random.split(rng)
    return {
        "language_model": transformer.init_params(r1, cfg.text),
        "vision_tower": init_vision_params(r2, cfg.vision, dtype=cfg.text.param_dtype),
    }


def abstract_params(cfg: Qwen25VLConfig):
    return jax.eval_shape(lambda: init_params(jax.random.PRNGKey(0), cfg))


# ---------------------------------------------------------------------------
# host-side index plan (numpy; runs in the collator)
# ---------------------------------------------------------------------------

def _per_image_pos_hw(t: int, h: int, w: int, m: int) -> np.ndarray:
    """(h, w) rope position per patch in the processor's merge-block patch
    order (HF ``rot_pos_emb``: (h/m, w/m, m, m) flattening)."""
    hpos = np.arange(h)[:, None].repeat(w, 1)
    wpos = np.arange(w)[None, :].repeat(h, 0)

    def order(x):
        return x.reshape(h // m, m, w // m, m).transpose(0, 2, 1, 3).reshape(-1)

    per_t = np.stack([order(hpos), order(wpos)], -1)  # [h*w, 2]
    return np.tile(per_t, (t, 1))


def _per_image_window_plan(t: int, h: int, w: int, cfg: Qwen25VisionConfig):
    """Port of HF ``get_window_index`` for one image: returns
    (window_index [t*lh*lw] merged-token permutation, window_sizes list of
    merged-token counts per window)."""
    m = cfg.spatial_merge_size
    lh, lw = h // m, w // m
    vit_ws = cfg.window_size // m // cfg.patch_size
    index = np.arange(t * lh * lw).reshape(t, lh, lw)
    pad_h = (-lh) % vit_ws
    pad_w = (-lw) % vit_ws
    nwh, nww = (lh + pad_h) // vit_ws, (lw + pad_w) // vit_ws
    padded = np.full((t, lh + pad_h, lw + pad_w), -100)
    padded[:, :lh, :lw] = index
    padded = padded.reshape(t, nwh, vit_ws, nww, vit_ws).transpose(0, 1, 3, 2, 4)
    padded = padded.reshape(t, nwh * nww, vit_ws, vit_ws)
    sizes = (padded != -100).sum((2, 3)).reshape(-1)
    flat = padded.reshape(-1)
    window_index = flat[flat != -100]
    return window_index, [int(s) for s in sizes if s > 0]


def vision_metadata(
    grid_thw: Sequence[Tuple[int, int, int]],
    cfg: Qwen25VisionConfig,
    n_pad_patches: int,
) -> Dict[str, np.ndarray]:
    """Build the static index plan for a batch's packed image patches.

    Returns arrays sized for ``n_pad_patches`` patches (and
    ``n_pad_patches // merge_unit`` merged tokens):

    - ``patch_gather`` [N]: window-ordering gather over the *original*
      (processor-order) packed patch sequence — the collator applies this to
      pixel_values before feeding the model;
    - ``pos_hw`` [N, 2]: rope positions, window-ordered;
    - ``seg_window`` / ``seg_full`` [N]: attention segment ids (0 = padding)
      for windowed and global layers;
    - ``reverse`` [M]: merged-token inverse permutation (window order ->
      image order);
    - ``merged_mask`` [M]: valid merged tokens.
    """
    unit = cfg.merge_unit
    pos_list, gather, segw, segf = [], [], [], []
    reverse_parts = []
    merged_offset = 0  # merged tokens emitted so far (image order)
    win_seg = 0
    for img_id, (t, h, w) in enumerate(grid_thw):
        n_merged = t * (h // cfg.spatial_merge_size) * (w // cfg.spatial_merge_size)
        widx, wsizes = _per_image_window_plan(t, h, w, cfg)
        # patch-level gather: merged token widx[j] -> its `unit` patches
        pg = (widx[:, None] * unit + np.arange(unit)[None, :]).reshape(-1)
        gather.append(pg + merged_offset * unit)
        pos = _per_image_pos_hw(t, h, w, cfg.spatial_merge_size)
        pos_list.append(pos[pg])
        segf.append(np.full(n_merged * unit, img_id + 1, np.int32))
        for sz in wsizes:
            win_seg += 1
            segw.append(np.full(sz * unit, win_seg, np.int32))
        reverse_parts.append(np.argsort(widx) + merged_offset)
        merged_offset += n_merged

    n = merged_offset * unit
    if n > n_pad_patches:
        raise ValueError(
            f"{n} patches exceed the static budget {n_pad_patches}; raise "
            "data.max_patches or drop images upstream"
        )
    m_pad = n_pad_patches // unit

    def pad_to(x, size, fill=0):
        out = np.full((size,) + x.shape[1:], fill, x.dtype)
        out[: len(x)] = x
        return out

    pg = np.concatenate(gather) if gather else np.zeros((0,), np.int64)
    return {
        "patch_gather": pad_to(pg.astype(np.int32), n_pad_patches,
                               fill=max(n, 1) - 1),
        "pos_hw": pad_to(
            np.concatenate(pos_list).astype(np.int32) if pos_list
            else np.zeros((0, 2), np.int32), n_pad_patches),
        "seg_window": pad_to(
            np.concatenate(segw) if segw else np.zeros((0,), np.int32),
            n_pad_patches),
        "seg_full": pad_to(
            np.concatenate(segf) if segf else np.zeros((0,), np.int32),
            n_pad_patches),
        "reverse": pad_to(
            np.concatenate(reverse_parts).astype(np.int32) if reverse_parts
            else np.zeros((0,), np.int32), m_pad, fill=max(m_pad, 1) - 1),
        "merged_mask": pad_to(np.ones(merged_offset, bool), m_pad, fill=False),
    }


def mrope_position_ids(
    input_ids: np.ndarray,
    grid_thw: Sequence[Tuple[int, int, int]],
    cfg: "Qwen25VLConfig",
    second_per_grid_ts: Optional[Sequence[float]] = None,
    video: Optional[Sequence[bool]] = None,
) -> np.ndarray:
    """Numpy port of HF ``get_rope_index`` (modeling_qwen2_5_vl.py:956):
    input_ids [B, S] -> position_ids [B, 3, S] (t/h/w streams). Vision spans
    get 3D grid positions; text spans continue 1D from the running max."""
    b, s = input_ids.shape
    out = np.zeros((b, 3, s), np.int64)
    vis_iter = iter(
        list(zip(grid_thw, video or [False] * len(grid_thw),
                 second_per_grid_ts or [1.0] * len(grid_thw)))
    )
    m = cfg.vision.spatial_merge_size
    for row in range(b):
        ids = input_ids[row]
        pos_chunks: List[np.ndarray] = []
        is_vis = (ids == cfg.image_token_id) | (ids == cfg.video_token_id)
        p = 0
        st = 0
        while p < s:
            if not is_vis[p]:
                p += 1
                continue
            # each grid consumes exactly its merged-token count, so adjacent
            # images stay distinct (HF walks placeholder-by-placeholder)
            (t, h, w), is_video, spg = next(vis_iter)
            lt, lh, lw = t, h // m, w // m
            st_idx = (pos_chunks[-1].max() + 1) if pos_chunks else 0
            text_len = p - st
            if text_len:
                pos_chunks.append(
                    np.broadcast_to(np.arange(text_len), (3, text_len)) + st_idx
                )
                st_idx = pos_chunks[-1].max() + 1
            interval = spg * cfg.vision.tokens_per_second if is_video else 0.0
            t_idx = (np.arange(lt)[:, None] * interval).astype(np.int64)
            t_idx = t_idx.repeat(lh * lw, 1).reshape(-1)
            h_idx = np.tile(np.arange(lh)[None, :, None], (lt, 1, lw)).reshape(-1)
            w_idx = np.tile(np.arange(lw)[None, None, :], (lt, lh, 1)).reshape(-1)
            pos_chunks.append(np.stack([t_idx, h_idx, w_idx]) + st_idx)
            p += lt * lh * lw
            st = p
        if st < s:
            st_idx = (pos_chunks[-1].max() + 1) if pos_chunks else 0
            text_len = s - st
            pos_chunks.append(
                np.broadcast_to(np.arange(text_len), (3, text_len)) + st_idx
            )
        out[row] = np.concatenate(pos_chunks, axis=1)
    return out


# ---------------------------------------------------------------------------
# vision tower forward
# ---------------------------------------------------------------------------

def _rms_norm(x, w, eps=1e-6):
    dt = x.dtype
    x = x.astype(jnp.float32)
    x = x * jax.lax.rsqrt((x * x).mean(-1, keepdims=True) + eps)
    return (x * w).astype(dt)


def _vision_block(x, lp, cfg: Qwen25VisionConfig, cos, sin, seg):
    n, d = x.shape
    hd = cfg.head_dim
    y = _rms_norm(x, lp["norm1"])
    qkv = jnp.dot(y, lp["qkv_w"]) + lp["qkv_b"]
    q, k, v = jnp.split(qkv.reshape(1, n, 3 * cfg.num_heads, hd), 3, axis=2)
    q, k = ops.apply_rotary(q, k, cos, sin)
    attn = ops.attention(q, k, v, segment_ids=seg, causal=False)
    x = x + jnp.dot(attn.reshape(n, d), lp["proj_w"]) + lp["proj_b"]
    y = _rms_norm(x, lp["norm2"])
    gate = jnp.dot(y, lp["gate_w"]) + lp["gate_b"]
    up = jnp.dot(y, lp["up_w"]) + lp["up_b"]
    x = x + jnp.dot(jax.nn.silu(gate) * up, lp["down_w"]) + lp["down_b"]
    return x


def vision_forward(
    params, cfg: Qwen25VisionConfig, pixel_values, pos_hw,
    seg_window, seg_full, reverse, dtype=jnp.bfloat16,
):
    """pixel_values [N, patch_dim] (window-ordered, padded); returns merged
    features [N / merge_unit, out_hidden_size] in image order.

    Runs under a no-SP scoped ParallelState: the packed patch sequence is
    replicated, not sequence-sharded, so the tower computes at sp=1 while
    the LM around it keeps full SP (per-module heterogeneous SP)."""
    from veomni_tpu.parallel.parallel_state import (
        get_parallel_state_or_none, use_parallel_state,
    )

    ps = get_parallel_state_or_none()
    if ps is not None and ps.sp_enabled:
        with use_parallel_state(ps.without_sp()):
            return vision_forward(
                params, cfg, pixel_values, pos_hw, seg_window, seg_full,
                reverse, dtype=dtype,
            )
    p = jax.tree.map(lambda t: t.astype(dtype), params)
    x = jnp.dot(pixel_values.astype(dtype), p["patch_embed"])  # [N, D]

    # 2D rope: head_dim/2 split across (h, w) — HF Qwen2_5_VisionRotaryEmbedding
    hd = cfg.head_dim
    inv_freq = 1.0 / (10000.0 ** (jnp.arange(0, hd // 2, 2, jnp.float32) / (hd // 2)))
    fh = pos_hw[:, 0:1].astype(jnp.float32) * inv_freq  # [N, hd/4]
    fw = pos_hw[:, 1:2].astype(jnp.float32) * inv_freq
    freqs = jnp.concatenate([fh, fw], -1)               # [N, hd/2]
    emb = jnp.concatenate([freqs, freqs], -1)[None]     # [1, N, hd]
    cos, sin = jnp.cos(emb), jnp.sin(emb)

    # group consecutive layers by window/full attention and scan each run
    runs: List[List[int]] = []  # [start, count, is_full]
    for li in range(cfg.depth):
        is_full = li in cfg.fullatt_block_indexes
        if runs and runs[-1][2] == is_full:
            runs[-1][1] += 1
        else:
            runs.append([li, 1, is_full])
    segw = seg_window[None]
    segf = seg_full[None]
    for start, count, is_full in runs:
        sub = jax.tree.map(lambda t: t[start:start + count], p["blocks"])
        body = partial(
            _vision_block, cfg=cfg, cos=cos, sin=sin,
            seg=segf if is_full else segw,
        )
        x, _ = jax.lax.scan(
            lambda c, lp: (jax.checkpoint(body)(c, lp), None), x, sub
        )

    # 2x2 merger (window-ordered groups are contiguous by construction)
    mg = p["merger"]
    y = _rms_norm(x, mg["ln_q"])
    y = y.reshape(x.shape[0] // cfg.merge_unit, cfg.merge_unit * cfg.hidden_size)
    y = jax.nn.gelu(jnp.dot(y, mg["fc1_w"]) + mg["fc1_b"])
    y = jnp.dot(y, mg["fc2_w"]) + mg["fc2_b"]
    return y[reverse]  # back to image order


# ---------------------------------------------------------------------------
# loss
# ---------------------------------------------------------------------------

def gather_packed_features(input_ids, feats, merged_mask,
                           image_token_id, video_token_id,
                           row_tokens: int = 0):
    """Align packed per-token features [M, H] (image order) with placeholder
    tokens: returns (gathered [B*S, H], valid [B*S]) — the shared scatter
    core for the VLM/omni composites.

    ``row_tokens=0`` (packed mode): feats cover the whole batch in reading
    order, global cumsum ordinal. ``row_tokens=R`` (per-row budget mode):
    feats are row-major [B*R, H], each row's placeholders index its own R
    merged slots — elementwise per row, so dp batch sharding stays local
    (the multihost data path)."""
    m = feats.shape[0]
    is_vis = (input_ids == image_token_id) | (input_ids == video_token_id)
    flat = is_vis.reshape(-1)
    if row_tokens:
        b = input_ids.shape[0]
        ordinal = (jnp.cumsum(is_vis.astype(jnp.int32), axis=1) - 1)
        in_budget = is_vis & (ordinal < row_tokens)
        idx = (
            jnp.arange(b)[:, None] * row_tokens
            + jnp.clip(ordinal, 0, row_tokens - 1)
        ).reshape(-1)
        valid = in_budget.reshape(-1) & merged_mask[idx]
        return feats[idx], valid
    ordinal = jnp.cumsum(flat.astype(jnp.int32)) - 1
    idx = jnp.clip(ordinal, 0, m - 1)
    valid = flat & (ordinal < m) & merged_mask[idx]
    return feats[idx], valid


def merge_vision_features(embeds, input_ids, feats, merged_mask,
                          image_token_id, video_token_id,
                          row_tokens: int = 0):
    """Scatter packed vision features (image order) into placeholder tokens
    (reading order over the whole batch in packed mode; per-row in budget
    mode — see gather_packed_features)."""
    b, s, h = embeds.shape
    gathered, valid = gather_packed_features(
        input_ids, feats, merged_mask, image_token_id, video_token_id,
        row_tokens=row_tokens,
    )
    out = jnp.where(valid[:, None], gathered.astype(embeds.dtype),
                    embeds.reshape(b * s, h))
    return out.reshape(b, s, h)


def flatten_per_row_vision(batch, unit: int) -> Tuple[Dict[str, jax.Array], int]:
    """Per-row-budget vision arrays [B, Pr, ...] -> the packed layout the
    vision tower consumes, with per-row segment/index offsets so rows stay
    mutually masked after concatenation. Returns (packed arrays, merged
    tokens per row). Elementwise per row => dp batch sharding stays local
    (the multihost VLM data path; reference per-rank slicing,
    ``data/data_collator.py:317-431``)."""
    pv = batch["pixel_values"]
    b, pr, d = pv.shape
    mr = pr // unit
    row = jnp.arange(b, dtype=jnp.int32)[:, None]
    out = {"pixel_values": pv.reshape(b * pr, d)}
    for key in ("vis_seg_window", "vis_seg_full", "vis_seg"):
        if key in batch:
            seg = batch[key]
            # +1 headroom: row seg ids are 1..Pr, so stride Pr+1 cannot collide
            out[key] = jnp.where(seg > 0, seg + row * (pr + 1), 0).reshape(-1)
    if "vis_pos_hw" in batch:
        out["vis_pos_hw"] = batch["vis_pos_hw"].reshape(b * pr, 2)
    if "vis_reverse" in batch:
        out["vis_reverse"] = (batch["vis_reverse"] + row * mr).reshape(-1)
    if "vis_merged_mask" in batch:
        out["vis_merged_mask"] = batch["vis_merged_mask"].reshape(-1)
    if "vis_pos_interp_idx" in batch:
        # [B, 4, Pr] -> [4, B*Pr]; indices address the shared pos-embed
        # table, so no per-row offset
        out["vis_pos_interp_idx"] = (
            batch["vis_pos_interp_idx"].transpose(1, 0, 2).reshape(4, b * pr)
        )
        out["vis_pos_interp_w"] = (
            batch["vis_pos_interp_w"].transpose(1, 0, 2).reshape(4, b * pr)
        )
    return out, mr


def _vision_merged_hidden(params, cfg: Qwen25VLConfig, batch):
    """Shared preamble: vision tower + placeholder merge + text transformer.
    Returns (lm params, hidden [B,S,H], moe_aux, moe_dropped)."""
    tcfg = cfg.text
    vp = params["vision_tower"]
    if cfg.freeze_vision:
        vp = jax.lax.stop_gradient(vp)
    row_tokens = 0
    if batch["pixel_values"].ndim == 3:
        packed, row_tokens = flatten_per_row_vision(batch, cfg.vision.merge_unit)
        batch = {**batch, **packed}
    feats = vision_forward(
        vp, cfg.vision, batch["pixel_values"], batch["vis_pos_hw"],
        batch["vis_seg_window"], batch["vis_seg_full"], batch["vis_reverse"],
        dtype=tcfg.dtype,
    )
    lm = params["language_model"]
    embeds = lm["embed_tokens"].astype(tcfg.dtype)[batch["input_ids"]]
    embeds = merge_vision_features(
        embeds, batch["input_ids"], feats, batch["vis_merged_mask"],
        cfg.image_token_id, cfg.video_token_id, row_tokens=row_tokens,
    )
    hidden, moe_aux, moe_dropped = transformer.forward_hidden(
        lm, tcfg, batch["input_ids"], batch["position_ids"],
        batch.get("segment_ids"), inputs_embeds=embeds,
    )
    return lm, hidden, moe_aux, moe_dropped


def loss_fn(params, cfg: Qwen25VLConfig, batch) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """batch: input_ids/labels/segment_ids [B,S]; position_ids [B,3,S]
    (mrope); pixel_values [N, patch_dim] window-ordered; vis_pos_hw [N,2];
    vis_seg_window / vis_seg_full [N]; vis_reverse [M]; vis_merged_mask [M].
    Per-row budget mode: the vision arrays carry a leading batch dim instead
    ([B, Pr, ...]) and are flattened here with per-row offsets."""
    lm, hidden, moe_aux, moe_dropped = _vision_merged_hidden(params, cfg, batch)
    return transformer.head_loss(
        lm, cfg.text, hidden, batch["labels"], moe_aux, moe_dropped
    )


def sequence_logprob_sums(params, cfg: Qwen25VLConfig, batch) -> jax.Array:
    """Per-row sum of label log-probs [B] through the full VLM (the
    multimodal DPO/RL logit gather; text counterpart
    ``transformer.sequence_logprob_sums``)."""
    from veomni_tpu.ops import fused_linear_cross_entropy

    lm, hidden, _, _ = _vision_merged_hidden(params, cfg, batch)
    kernel = transformer.lm_head_kernel(lm, cfg.text).astype(cfg.text.dtype)

    def row_nll(h_row, l_row):
        loss_sum, _ = fused_linear_cross_entropy(h_row, kernel, l_row)
        return loss_sum

    return -jax.vmap(row_nll)(hidden, batch["labels"])


# ---------------------------------------------------------------------------
# HF checkpoint io
# ---------------------------------------------------------------------------

_VIS_BLOCK_MAP = [
    # (ours, hf suffix, transpose)
    ("norm1", "norm1.weight", False),
    ("norm2", "norm2.weight", False),
    ("qkv_w", "attn.qkv.weight", True),
    ("qkv_b", "attn.qkv.bias", False),
    ("proj_w", "attn.proj.weight", True),
    ("proj_b", "attn.proj.bias", False),
    ("gate_w", "mlp.gate_proj.weight", True),
    ("gate_b", "mlp.gate_proj.bias", False),
    ("up_w", "mlp.up_proj.weight", True),
    ("up_b", "mlp.up_proj.bias", False),
    ("down_w", "mlp.down_proj.weight", True),
    ("down_b", "mlp.down_proj.bias", False),
]


def _is_visual_key(k: str) -> bool:
    return ".visual." in k or k.startswith("visual.")


def _text_key_map(k: str) -> Optional[str]:
    if _is_visual_key(k):
        return None
    return k.replace("model.language_model.", "model.").replace(
        "language_model.model.", "model."
    )


def hf_to_params(model_dir: str, cfg: Qwen25VLConfig, target_shardings=None):
    """Load an HF Qwen2.5-VL checkpoint (visual.* + model.language_model.* /
    model.* text tree) into our composite pytree. The text subtree (the
    dominant share of a 7B/72B checkpoint) stays on hf_io's streamed
    shard-aligned path; vision tensors stream one at a time."""
    from veomni_tpu.models import hf_io

    pd = cfg.text.param_dtype
    ts_lm = target_shardings["language_model"] if target_shardings else None
    ts_vis = target_shardings["vision_tower"] if target_shardings else None

    language_model = hf_io.hf_to_params(
        model_dir, cfg.text, target_shardings=ts_lm, key_map=_text_key_map
    )

    lazy = hf_io.LazyHFTensors(model_dir)
    vis_alias = {}
    for k in lazy.keys():
        if _is_visual_key(k):
            vis_alias[k[k.index("visual.") + len("visual."):]] = k

    def read(name: str) -> np.ndarray:
        return np.asarray(lazy.read(vis_alias[name]))

    def place(path_in_vis, arr):
        arr = jnp.asarray(np.ascontiguousarray(arr), pd)
        if ts_vis is None:
            return arr
        sh = ts_vis
        for p in path_in_vis:
            sh = sh[p]
        return jax.device_put(arr, sh)

    vcfg = cfg.vision
    blocks: Dict[str, Any] = {}
    for ours, suffix, transpose in _VIS_BLOCK_MAP:
        stacked = np.stack([
            read(f"blocks.{i}.{suffix}").T if transpose
            else read(f"blocks.{i}.{suffix}")
            for i in range(vcfg.depth)
        ])
        blocks[ours] = place(("blocks", ours), stacked)
    vision_tower = {
        "patch_embed": place(
            ("patch_embed",),
            read("patch_embed.proj.weight").reshape(vcfg.hidden_size, -1).T,
        ),
        "blocks": blocks,
        "merger": {
            "ln_q": place(("merger", "ln_q"), read("merger.ln_q.weight")),
            "fc1_w": place(("merger", "fc1_w"), read("merger.mlp.0.weight").T),
            "fc1_b": place(("merger", "fc1_b"), read("merger.mlp.0.bias")),
            "fc2_w": place(("merger", "fc2_w"), read("merger.mlp.2.weight").T),
            "fc2_b": place(("merger", "fc2_b"), read("merger.mlp.2.bias")),
        },
    }
    return {"language_model": language_model, "vision_tower": vision_tower}


def params_to_hf(params, cfg: Qwen25VLConfig) -> Dict[str, np.ndarray]:
    from veomni_tpu.models import hf_io

    out: Dict[str, np.ndarray] = {}
    text = hf_io.params_to_hf(params["language_model"], cfg.text)
    for k, v in text.items():
        if k == "lm_head.weight":
            out[k] = v
        else:
            out[k.replace("model.", "model.language_model.", 1)] = v
    vt = hf_io.gather_to_host(params["vision_tower"])
    vcfg = cfg.vision
    pfx = "model.visual"
    out[f"{pfx}.patch_embed.proj.weight"] = vt["patch_embed"].T.reshape(
        vcfg.hidden_size, vcfg.in_channels, vcfg.temporal_patch_size,
        vcfg.patch_size, vcfg.patch_size,
    )
    for ours, suffix, transpose in _VIS_BLOCK_MAP:
        for i in range(vcfg.depth):
            x = vt["blocks"][ours][i]
            out[f"{pfx}.blocks.{i}.{suffix}"] = x.T if transpose else x
    out[f"{pfx}.merger.ln_q.weight"] = vt["merger"]["ln_q"]
    out[f"{pfx}.merger.mlp.0.weight"] = vt["merger"]["fc1_w"].T
    out[f"{pfx}.merger.mlp.0.bias"] = vt["merger"]["fc1_b"]
    out[f"{pfx}.merger.mlp.2.weight"] = vt["merger"]["fc2_w"].T
    out[f"{pfx}.merger.mlp.2.bias"] = vt["merger"]["fc2_b"]
    return out


def save_hf_checkpoint(params, cfg: Qwen25VLConfig, out_dir: str) -> None:
    import json
    import os

    from safetensors.flax import save_file

    tensors = params_to_hf(params, cfg)  # collective gather
    if jax.process_index() != 0:
        return
    os.makedirs(out_dir, exist_ok=True)
    save_file({k: jnp.asarray(v) for k, v in tensors.items()},
              os.path.join(out_dir, "model.safetensors"))
    hf_cfg = {
        "model_type": "qwen2_5_vl",
        "architectures": ["Qwen2_5_VLForConditionalGeneration"],
        "image_token_id": cfg.image_token_id,
        "video_token_id": cfg.video_token_id,
        "vision_start_token_id": cfg.vision_start_token_id,
        "text_config": {**cfg.text.to_hf_config(), "model_type": "qwen2_5_vl_text"},
        "vision_config": {
            "model_type": "qwen2_5_vl",
            "depth": cfg.vision.depth,
            "hidden_size": cfg.vision.hidden_size,
            "intermediate_size": cfg.vision.intermediate_size,
            "num_heads": cfg.vision.num_heads,
            "in_channels": cfg.vision.in_channels,
            "patch_size": cfg.vision.patch_size,
            "temporal_patch_size": cfg.vision.temporal_patch_size,
            "spatial_merge_size": cfg.vision.spatial_merge_size,
            "window_size": cfg.vision.window_size,
            "fullatt_block_indexes": list(cfg.vision.fullatt_block_indexes),
            "out_hidden_size": cfg.vision.out_hidden_size,
            "tokens_per_second": cfg.vision.tokens_per_second,
            "hidden_act": cfg.vision.hidden_act,
        },
    }
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump(hf_cfg, f, indent=2)


def config_from_hf(hf: Dict[str, Any], **overrides) -> Qwen25VLConfig:
    """Build from an HF Qwen2_5_VLConfig dict (config.json)."""
    text_hf = dict(hf.get("text_config") or {})
    for key in ("vocab_size", "hidden_size", "intermediate_size",
                "num_hidden_layers", "num_attention_heads",
                "num_key_value_heads", "rope_theta", "rms_norm_eps",
                "tie_word_embeddings", "rope_scaling", "max_position_embeddings"):
        if key not in text_hf and key in hf:
            text_hf[key] = hf[key]
    text = TransformerConfig.from_hf_config(
        {**text_hf, "model_type": "qwen2"}, **overrides
    )
    vis_hf = dict(hf.get("vision_config") or {})
    vis_fields = {f for f in Qwen25VisionConfig.__dataclass_fields__}
    vision = Qwen25VisionConfig(**{k: v for k, v in vis_hf.items() if k in vis_fields})
    return Qwen25VLConfig(
        text=text,
        vision=vision,
        image_token_id=hf.get("image_token_id", 151655),
        video_token_id=hf.get("video_token_id", 151656),
        vision_start_token_id=hf.get("vision_start_token_id", 151652),
    )
