"""LTX-2 audio-video DiT (the trainable transformer core).

Reference: ``veomni/models/diffusers/ltx2_3/`` — ``ltx_core/model/transformer/
{model,transformer,attention,rope,adaln}.py`` (LTXModel / BasicAVTransformerBlock)
wrapped by ``ltx_transformer/modeling_ltx2_3_transformer.py``. Defining
features re-derived here:

* **dual streams**: video tokens and audio tokens run symmetric per-block
  pipelines — adaLN-zero self-attention (per-TOKEN timestep modulation: the
  6·dim coefficients come from a PixArt-style adaln-single evaluated per
  token, so conditioning frames can carry different sigmas), ungated text
  cross-attention over rms-normed queries, gated **audio↔video cross
  attention** (both directions read the pre-exchange snapshot; q/k carry
  temporal-axis rope so alignment is time-relative), then adaLN-zero FFs;
* **LTX SPLIT rope**: fractional positions ``pos/max_pos`` mapped to
  [-1, 1], multiplied by a log-spaced ``θ^linspace·π/2`` frequency ladder,
  distributed ACROSS heads (each head sees a different frequency slice,
  front-padded with identity rotation), applied as a half-split rotation;
* PixArt adaln-single stacks (per modality + 3 extra for the A/V cross
  scale/shift/gate), 2-row scale-shift output head per stream.

Scope: the transformer (what trains); the video/audio VAEs + vocoder are
frozen inference tooling — training consumes cached latents, matching the
reference trainer contract and our wan/qwen_image/flux DiT pattern.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from veomni_tpu import ops
from veomni_tpu.models.diffusion_common import (
    ln_noaffine as _ln_noaffine,
    timestep_embedding as _ts_embed,
)

Params = Dict[str, Any]


@dataclass
class LTX2Config:
    """``LTXVideoTransformerModelConfig`` surface (defaults ~ LTX-2 13B)."""

    num_attention_heads: int = 32
    attention_head_dim: int = 128
    in_channels: int = 128
    out_channels: int = 128
    num_layers: int = 48
    cross_attention_dim: int = 4096
    caption_channels: int = 4096
    with_audio: bool = True
    audio_num_attention_heads: int = 32
    audio_attention_head_dim: int = 64
    audio_in_channels: int = 128
    audio_out_channels: int = 128
    rope_theta: float = 10000.0
    # pixel-space extents for the fractional rope axes (f, h, w) / (t,)
    positional_embedding_max_pos: Tuple[int, ...] = (20, 2048, 2048)
    audio_positional_embedding_max_pos: Tuple[int, ...] = (20,)
    # latent-token -> pixel-coordinate strides ((sec/frame, px, px) analogue)
    video_pos_scale: Tuple[float, float, float] = (1.0, 32.0, 32.0)
    audio_pos_scale: Tuple[float, ...] = (1.0,)
    timestep_scale_multiplier: float = 1000.0
    norm_eps: float = 1e-6
    initializer_range: float = 0.02
    # static latent grid (f, h, w) and audio token count for the rope plan
    video_shape: Tuple[int, int, int] = ()
    audio_len: int = 0
    model_type: str = "ltx2"
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True

    def __post_init__(self):
        for f_ in ("positional_embedding_max_pos", "audio_positional_embedding_max_pos",
                   "video_pos_scale", "audio_pos_scale", "video_shape"):
            setattr(self, f_, tuple(getattr(self, f_)))
        for f_ in ("dtype", "param_dtype"):
            v = getattr(self, f_)
            if isinstance(v, str):
                setattr(self, f_, getattr(jnp, v))

    @property
    def inner_dim(self) -> int:
        return self.num_attention_heads * self.attention_head_dim

    @property
    def audio_inner_dim(self) -> int:
        return self.audio_num_attention_heads * self.audio_attention_head_dim


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------

def _attn_params(keys, q_dim, ctx_dim, inner, pd, s):
    def init(shape):
        return (jax.random.normal(next(keys), shape, jnp.float32) * s).astype(pd)

    return {
        "q_norm": jnp.ones((inner,), pd),
        "k_norm": jnp.ones((inner,), pd),
        "to_q": init((q_dim, inner)), "to_q_b": jnp.zeros((inner,), pd),
        "to_k": init((ctx_dim, inner)), "to_k_b": jnp.zeros((inner,), pd),
        "to_v": init((ctx_dim, inner)), "to_v_b": jnp.zeros((inner,), pd),
        "to_out": init((inner, q_dim)), "to_out_b": jnp.zeros((q_dim,), pd),
    }


def _ff_params(keys, dim, pd, s):
    def init(shape):
        return (jax.random.normal(next(keys), shape, jnp.float32) * s).astype(pd)

    return {
        "fc1": init((dim, 4 * dim)), "fc1_b": jnp.zeros((4 * dim,), pd),
        "fc2": init((4 * dim, dim)), "fc2_b": jnp.zeros((dim,), pd),
    }


def _adaln_single_params(keys, dim, coeff, pd, s):
    def init(shape):
        return (jax.random.normal(next(keys), shape, jnp.float32) * s).astype(pd)

    return {
        "emb_fc1": init((256, dim)), "emb_fc1_b": jnp.zeros((dim,), pd),
        "emb_fc2": init((dim, dim)), "emb_fc2_b": jnp.zeros((dim,), pd),
        "linear": init((dim, coeff * dim)), "linear_b": jnp.zeros((coeff * dim,), pd),
    }


def init_params(rng: jax.Array, cfg: LTX2Config) -> Params:
    pd, s = cfg.param_dtype, cfg.initializer_range
    d, da = cfg.inner_dim, cfg.audio_inner_dim
    L = cfg.num_layers
    keys = iter(jax.random.split(rng, 256))

    def init(shape):
        return (jax.random.normal(next(keys), shape, jnp.float32) * s).astype(pd)

    def stack(fn):
        per = [fn() for _ in range(L)]
        return jax.tree.map(lambda *xs: jnp.stack(xs), *per)

    blocks: Params = {
        "attn1": stack(lambda: _attn_params(keys, d, d, d, pd, s)),
        "attn2": stack(lambda: _attn_params(keys, d, d, d, pd, s)),
        "ff": stack(lambda: _ff_params(keys, d, pd, s)),
        "scale_shift_table": jnp.zeros((L, 6, d), pd),
    }
    p: Params = {
        "patchify_proj": init((cfg.in_channels, d)),
        "patchify_proj_b": jnp.zeros((d,), pd),
        "caption_projection": {
            "linear_1": init((cfg.caption_channels, d)),
            "linear_1_b": jnp.zeros((d,), pd),
            "linear_2": init((d, d)), "linear_2_b": jnp.zeros((d,), pd),
        },
        "adaln_single": _adaln_single_params(keys, d, 6, pd, s),
        "scale_shift_table": jnp.zeros((2, d), pd),
        "proj_out": init((d, cfg.out_channels)),
        "proj_out_b": jnp.zeros((cfg.out_channels,), pd),
    }
    if cfg.with_audio:
        blocks.update(
            audio_attn1=stack(lambda: _attn_params(keys, da, da, da, pd, s)),
            audio_attn2=stack(lambda: _attn_params(keys, da, da, da, pd, s)),
            audio_ff=stack(lambda: _ff_params(keys, da, pd, s)),
            audio_scale_shift_table=jnp.zeros((L, 6, da), pd),
            # q: video, kv: audio — audio-sized heads/inner dim (reference)
            audio_to_video_attn=stack(lambda: _attn_params(keys, d, da, da, pd, s)),
            video_to_audio_attn=stack(lambda: _attn_params(keys, da, d, da, pd, s)),
            scale_shift_table_a2v_ca_video=jnp.zeros((L, 5, d), pd),
            scale_shift_table_a2v_ca_audio=jnp.zeros((L, 5, da), pd),
        )
        p.update(
            audio_patchify_proj=init((cfg.audio_in_channels, da)),
            audio_patchify_proj_b=jnp.zeros((da,), pd),
            audio_caption_projection={
                "linear_1": init((cfg.caption_channels, da)),
                "linear_1_b": jnp.zeros((da,), pd),
                "linear_2": init((da, da)), "linear_2_b": jnp.zeros((da,), pd),
            },
            audio_adaln_single=_adaln_single_params(keys, da, 6, pd, s),
            av_ca_video_scale_shift_adaln_single=_adaln_single_params(keys, d, 4, pd, s),
            av_ca_audio_scale_shift_adaln_single=_adaln_single_params(keys, da, 4, pd, s),
            av_ca_a2v_gate_adaln_single=_adaln_single_params(keys, d, 1, pd, s),
            av_ca_v2a_gate_adaln_single=_adaln_single_params(keys, da, 1, pd, s),
            audio_scale_shift_table=jnp.zeros((2, da), pd),
            audio_proj_out=init((da, cfg.audio_out_channels)),
            audio_proj_out_b=jnp.zeros((cfg.audio_out_channels,), pd),
        )
    p["blocks"] = blocks
    return p


def abstract_params(cfg: LTX2Config) -> Params:
    return jax.eval_shape(lambda: init_params(jax.random.PRNGKey(0), cfg))


# ---------------------------------------------------------------------------
# rope (LTX SPLIT: per-head frequency slices, fractional [-1,1] positions)
# ---------------------------------------------------------------------------

def ltx_rope(positions: np.ndarray, max_pos, inner_dim: int, heads: int,
             theta: float):
    """positions [T, n_axes] (pixel coords) -> (cos, sin) [1, heads, T,
    head_dim/2] per-head SPLIT tables (reference ``rope.py:precompute_freqs_cis``)."""
    n_axes = positions.shape[1]
    n_freq = inner_dim // (2 * n_axes)
    ladder = theta ** np.linspace(0.0, 1.0, n_freq) * (np.pi / 2)   # [F]
    frac = np.stack([positions[:, i] / max_pos[i] for i in range(n_axes)], -1)
    freqs = (ladder[None, None, :] * (frac[..., None] * 2.0 - 1.0))  # [T,A,F]
    freqs = freqs.transpose(0, 2, 1).reshape(positions.shape[0], -1)  # [T,F*A]? -> match ref
    # reference: (indices * frac).transpose(-1,-2).flatten -> [T, A*F] with
    # axis-major ordering after transpose: freqs[t] = concat_f [f over axes]
    pad = inner_dim // 2 - freqs.shape[-1]
    cos = np.cos(freqs)
    sin = np.sin(freqs)
    if pad:
        cos = np.concatenate([np.ones((cos.shape[0], pad)), cos], -1)
        sin = np.concatenate([np.zeros((sin.shape[0], pad)), sin], -1)
    t = cos.shape[0]
    cos = cos.reshape(1, t, heads, -1).transpose(0, 2, 1, 3)
    sin = sin.reshape(1, t, heads, -1).transpose(0, 2, 1, 3)
    return jnp.asarray(cos, jnp.float32), jnp.asarray(sin, jnp.float32)


def _apply_split_rope(x, cos, sin):
    """x [B, H, T, hd]; cos/sin [1, H, T, hd/2]: half-split rotation."""
    d = x.shape[-1] // 2
    x1, x2 = x[..., :d], x[..., d:]
    out1 = x1 * cos - sin * x2
    out2 = x2 * cos + sin * x1
    return jnp.concatenate([out1, out2], -1).astype(x.dtype)


def _video_positions(cfg: LTX2Config, shape) -> np.ndarray:
    f, h, w = shape
    ff, hh, ww = np.meshgrid(np.arange(f), np.arange(h), np.arange(w),
                             indexing="ij")
    grid = np.stack([ff, hh, ww], -1).reshape(-1, 3).astype(np.float64)
    scale = np.asarray(cfg.video_pos_scale)
    return (grid + 0.5) * scale  # middle-indices grid


def _audio_positions(cfg: LTX2Config, n: int) -> np.ndarray:
    return ((np.arange(n, dtype=np.float64) + 0.5) * cfg.audio_pos_scale[0])[:, None]


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _rms(x, w, eps):
    xf = x.astype(jnp.float32)
    y = xf * jax.lax.rsqrt((xf * xf).mean(-1, keepdims=True) + eps)
    return (y * (w if w is not None else 1.0)).astype(x.dtype)


def _adaln_single(lp, timestep, coeff):
    """timestep [B] -> (coeffs [B, 1, coeff*dim] f32, embedded [B, dim])."""
    e = _ts_embed(timestep, 256).astype(lp["emb_fc1"].dtype)
    e = jnp.dot(e, lp["emb_fc1"]) + lp["emb_fc1_b"]
    e = jnp.dot(jax.nn.silu(e), lp["emb_fc2"]) + lp["emb_fc2_b"]
    out = jnp.dot(jax.nn.silu(e), lp["linear"]) + lp["linear_b"]
    return out.astype(jnp.float32)[:, None, :], e


def _ada(sst, ts_coeffs, idx0, n, dim):
    """rows [idx0, idx0+n) of the block sst + per-token coeffs -> n tensors
    [B, 1, dim] (timestep is per-sample here; the reference supports
    per-token sigma — broadcasting keeps the same contract)."""
    b = ts_coeffs.shape[0]
    co = ts_coeffs.reshape(b, 1, -1, dim)
    return [
        (sst[i][None, None] + co[:, :, i]).astype(jnp.float32)
        for i in range(idx0, idx0 + n)
    ]


def _attention(lp, x, ctx, heads, eps, pe=None, k_pe=None, seg_q=None, seg_k=None):
    b, tq, _ = x.shape
    inner = lp["to_q"].shape[-1]
    hd = inner // heads
    q = _rms(jnp.dot(x, lp["to_q"]) + lp["to_q_b"], lp["q_norm"], eps)
    k = _rms(jnp.dot(ctx, lp["to_k"]) + lp["to_k_b"], lp["k_norm"], eps)
    v = jnp.dot(ctx, lp["to_v"]) + lp["to_v_b"]
    tk = ctx.shape[1]
    q = q.reshape(b, tq, heads, hd).transpose(0, 2, 1, 3)
    k = k.reshape(b, tk, heads, hd).transpose(0, 2, 1, 3)
    if pe is not None:
        q = _apply_split_rope(q, *pe)
        k = _apply_split_rope(k, *(k_pe if k_pe is not None else pe))
    q = q.transpose(0, 2, 1, 3)
    k = k.transpose(0, 2, 1, 3)
    o = ops.attention(
        q, k, v.reshape(b, tk, heads, hd),
        segment_ids=None, causal=False,
    ) if seg_q is None else _masked_attn(q, k, v.reshape(b, tk, heads, hd),
                                         seg_q, seg_k)
    o = o.reshape(b, tq, inner)
    return jnp.dot(o, lp["to_out"]) + lp["to_out_b"]


def _masked_attn(q, k, v, seg_q, seg_k):
    from veomni_tpu.ops.attention import _attention_dense

    bias = jnp.where(
        (seg_q[:, :, None] > 0) & (seg_k[:, None, :] > 0), 0.0, -jnp.inf
    ).astype(jnp.float32)
    return _attention_dense(q, k, v, causal=False, bias=bias)


def _block(carry, lp, cfg: LTX2Config, vts, ats, v_ss_ts, a_ss_ts,
           v_gate_ts, a_gate_ts, v_ctx, a_ctx, ctx_mask, v_pe, a_pe,
           v_cross_pe, a_cross_pe):
    eps = cfg.norm_eps
    d, da = cfg.inner_dim, cfg.audio_inner_dim
    if cfg.with_audio:
        vx, ax = carry
    else:
        vx, ax = carry, None

    # video self-attention (adaLN-zero) + text cross
    sh, sc, gate = _ada(lp["scale_shift_table"], vts, 0, 3, d)
    vn = (_rms(vx, None, eps).astype(jnp.float32) * (1 + sc) + sh).astype(vx.dtype)
    vx = vx + _attention(lp["attn1"], vn, vn, cfg.num_attention_heads, eps,
                         pe=v_pe) * gate.astype(vx.dtype)
    vx = vx + _attention(lp["attn2"], _rms(vx, None, eps), v_ctx,
                         cfg.num_attention_heads, eps,
                         seg_q=None if ctx_mask is None else jnp.ones(vx.shape[:2], jnp.int32),
                         seg_k=ctx_mask)

    if cfg.with_audio:
        sh, sc, gate = _ada(lp["audio_scale_shift_table"], ats, 0, 3, da)
        an = (_rms(ax, None, eps).astype(jnp.float32) * (1 + sc) + sh).astype(ax.dtype)
        ax = ax + _attention(lp["audio_attn1"], an, an,
                             cfg.audio_num_attention_heads, eps,
                             pe=a_pe) * gate.astype(ax.dtype)
        ax = ax + _attention(lp["audio_attn2"], _rms(ax, None, eps), a_ctx,
                             cfg.audio_num_attention_heads, eps,
                             seg_q=None if ctx_mask is None else jnp.ones(ax.shape[:2], jnp.int32),
                             seg_k=ctx_mask)

        # audio <-> video cross attention over the pre-exchange snapshot.
        # NOTE row order: the A/V cross tables unpack (scale, shift) — the
        # reference's get_av_ca_ada_values names row0 scale — while the MSA/FF
        # tables unpack (shift, scale, gate) per the PixArt convention
        # (reference transformer.py:196-228 vs 204-216). Both match upstream.
        vx_pre, ax_pre = vx, ax
        v_sc, v_sh = _ada(lp["scale_shift_table_a2v_ca_video"][:4], v_ss_ts, 0, 2, d)
        (v_gate,) = _ada(lp["scale_shift_table_a2v_ca_video"][4:], v_gate_ts, 0, 1, d)
        a_sc, a_sh = _ada(lp["scale_shift_table_a2v_ca_audio"][:4], a_ss_ts, 0, 2, da)
        vq = (_rms(vx_pre, None, eps).astype(jnp.float32) * (1 + v_sc) + v_sh).astype(vx.dtype)
        akv = (_rms(ax_pre, None, eps).astype(jnp.float32) * (1 + a_sc) + a_sh).astype(ax.dtype)
        vx = vx + _attention(
            lp["audio_to_video_attn"], vq, akv, cfg.audio_num_attention_heads,
            eps, pe=v_cross_pe, k_pe=a_cross_pe,
        ) * v_gate.astype(vx.dtype)

        a_sc2, a_sh2 = _ada(lp["scale_shift_table_a2v_ca_audio"][:4], a_ss_ts, 2, 2, da)
        (a_gate,) = _ada(lp["scale_shift_table_a2v_ca_audio"][4:], a_gate_ts, 0, 1, da)
        v_sc2, v_sh2 = _ada(lp["scale_shift_table_a2v_ca_video"][:4], v_ss_ts, 2, 2, d)
        aq = (_rms(ax_pre, None, eps).astype(jnp.float32) * (1 + a_sc2) + a_sh2).astype(ax.dtype)
        vkv = (_rms(vx_pre, None, eps).astype(jnp.float32) * (1 + v_sc2) + v_sh2).astype(vx.dtype)
        ax = ax + _attention(
            lp["video_to_audio_attn"], aq, vkv, cfg.audio_num_attention_heads,
            eps, pe=a_cross_pe, k_pe=v_cross_pe,
        ) * a_gate.astype(ax.dtype)

    # FFs (adaLN-zero)
    sh, sc, gate = _ada(lp["scale_shift_table"], vts, 3, 3, d)
    vn = (_rms(vx, None, eps).astype(jnp.float32) * (1 + sc) + sh).astype(vx.dtype)
    y = jax.nn.gelu(jnp.dot(vn, lp["ff"]["fc1"]) + lp["ff"]["fc1_b"], approximate=True)
    vx = vx + (jnp.dot(y, lp["ff"]["fc2"]) + lp["ff"]["fc2_b"]) * gate.astype(vx.dtype)
    if cfg.with_audio:
        sh, sc, gate = _ada(lp["audio_scale_shift_table"], ats, 3, 3, da)
        an = (_rms(ax, None, eps).astype(jnp.float32) * (1 + sc) + sh).astype(ax.dtype)
        y = jax.nn.gelu(jnp.dot(an, lp["audio_ff"]["fc1"]) + lp["audio_ff"]["fc1_b"],
                        approximate=True)
        ax = ax + (jnp.dot(y, lp["audio_ff"]["fc2"]) + lp["audio_ff"]["fc2_b"]) \
            * gate.astype(ax.dtype)
        return vx, ax
    return vx


def _caption_proj(lp, ctx):
    y = jax.nn.gelu(jnp.dot(ctx, lp["linear_1"]) + lp["linear_1_b"], approximate=True)
    return jnp.dot(y, lp["linear_2"]) + lp["linear_2_b"]


def ltx2_forward(params, cfg: LTX2Config, video_latents, timestep, text_states,
                 audio_latents=None, text_mask=None,
                 video_shape: Tuple[int, int, int] = None):
    """video_latents [B, N_v, in_channels] (N_v = f*h*w of ``video_shape``);
    timestep [B] (flow sigma in [0,1]); text_states [B, Lt, caption_channels];
    audio_latents [B, N_a, audio_in_channels] -> (video_pred, audio_pred)."""
    p = jax.tree.map(lambda t: t.astype(cfg.dtype), params)
    b, nv, _ = video_latents.shape
    video_shape = video_shape or cfg.video_shape
    if int(np.prod(video_shape)) != nv:
        raise ValueError(f"video_shape {video_shape} != {nv} tokens")
    ts = timestep * cfg.timestep_scale_multiplier

    vx = jnp.dot(video_latents.astype(cfg.dtype), p["patchify_proj"]) + p["patchify_proj_b"]
    v_ctx = _caption_proj(p["caption_projection"], text_states.astype(cfg.dtype))
    vts, v_emb = _adaln_single(p["adaln_single"], ts, 6)

    vpos = _video_positions(cfg, video_shape)
    v_pe = ltx_rope(vpos, cfg.positional_embedding_max_pos, cfg.inner_dim,
                    cfg.num_attention_heads, cfg.rope_theta)

    ax = a_ctx = ats = a_emb = a_pe = None
    v_ss = a_ss = v_gate = a_gate = v_cross_pe = a_cross_pe = None
    if cfg.with_audio:
        if audio_latents is None:
            raise ValueError("with_audio config needs audio_latents")
        na = audio_latents.shape[1]
        ax = jnp.dot(audio_latents.astype(cfg.dtype), p["audio_patchify_proj"]) \
            + p["audio_patchify_proj_b"]
        a_ctx = _caption_proj(p["audio_caption_projection"], text_states.astype(cfg.dtype))
        ats, a_emb = _adaln_single(p["audio_adaln_single"], ts, 6)
        apos = _audio_positions(cfg, na)
        a_pe = ltx_rope(apos, cfg.audio_positional_embedding_max_pos,
                        cfg.audio_inner_dim, cfg.audio_num_attention_heads,
                        cfg.rope_theta)
        v_ss, _ = _adaln_single(p["av_ca_video_scale_shift_adaln_single"], ts, 4)
        a_ss, _ = _adaln_single(p["av_ca_audio_scale_shift_adaln_single"], ts, 4)
        v_gate, _ = _adaln_single(p["av_ca_a2v_gate_adaln_single"], ts, 1)
        a_gate, _ = _adaln_single(p["av_ca_v2a_gate_adaln_single"], ts, 1)
        # A/V cross rope: shared TEMPORAL axis (frame seconds on both sides)
        cross_max = (max(cfg.positional_embedding_max_pos[0],
                         cfg.audio_positional_embedding_max_pos[0]),)
        v_cross_pe = ltx_rope(vpos[:, :1], cross_max, cfg.audio_inner_dim,
                              cfg.audio_num_attention_heads, cfg.rope_theta)
        a_cross_pe = ltx_rope(apos[:, :1], cross_max, cfg.audio_inner_dim,
                              cfg.audio_num_attention_heads, cfg.rope_theta)

    ctx_mask = None if text_mask is None else text_mask.astype(jnp.int32)
    body = partial(
        _block, cfg=cfg, vts=vts, ats=ats, v_ss_ts=v_ss, a_ss_ts=a_ss,
        v_gate_ts=v_gate, a_gate_ts=a_gate, v_ctx=v_ctx, a_ctx=a_ctx,
        ctx_mask=ctx_mask, v_pe=v_pe, a_pe=a_pe, v_cross_pe=v_cross_pe,
        a_cross_pe=a_cross_pe,
    )
    if cfg.remat:
        body = jax.checkpoint(body)
    carry = (vx, ax) if cfg.with_audio else vx
    carry, _ = jax.lax.scan(lambda c, lp: (body(c, lp), None), carry, p["blocks"])
    if cfg.with_audio:
        vx, ax = carry
    else:
        vx = carry

    def head(x, sst, emb, proj, proj_b, dim):
        mod = sst[None, None] + emb.astype(jnp.float32)[:, None, None, :]
        shift, scale = mod[:, :, 0], mod[:, :, 1]
        x = (_ln_noaffine(x, cfg.norm_eps) * (1 + scale) + shift).astype(x.dtype)
        return jnp.dot(x, proj) + proj_b

    v_out = head(vx, p["scale_shift_table"].astype(jnp.float32), v_emb,
                 p["proj_out"], p["proj_out_b"], cfg.inner_dim)
    if not cfg.with_audio:
        return v_out, None
    a_out = head(ax, p["audio_scale_shift_table"].astype(jnp.float32), a_emb,
                 p["audio_proj_out"], p["audio_proj_out_b"], cfg.audio_inner_dim)
    return v_out, a_out


def loss_fn(params, cfg: LTX2Config, batch) -> Tuple[jax.Array, Dict]:
    """batch: latents [B,Nv,C] (noisy video), timestep [B] (0..1000 scale as
    shipped by WanCollator — rescaled internally), text_states, text_mask,
    target [B,Nv,C]; optional audio_latents/audio_target [B,Na,Ca]."""
    ts = batch["timestep"] / cfg.timestep_scale_multiplier
    v_pred, a_pred = ltx2_forward(
        params, cfg, batch["latents"], ts, batch["text_states"],
        audio_latents=batch.get("audio_latents"),
        text_mask=batch.get("text_mask"),
        video_shape=cfg.video_shape or None,
    )
    err = (v_pred.astype(jnp.float32) - batch["target"].astype(jnp.float32)) ** 2
    loss = err.reshape(err.shape[0], -1).mean(axis=1)
    if a_pred is not None and "audio_target" in batch:
        aerr = (a_pred.astype(jnp.float32)
                - batch["audio_target"].astype(jnp.float32)) ** 2
        loss = loss + aerr.reshape(aerr.shape[0], -1).mean(axis=1)
    loss = loss.mean()
    n = jnp.int32(err.shape[0])
    return loss * n, {"loss": loss, "ntokens": n, "mse_loss": loss}


# ---------------------------------------------------------------------------
# checkpoint io (reference LTXModel module names)
# ---------------------------------------------------------------------------

_ATTN_MAP = [
    ("q_norm", "q_norm.weight", False), ("k_norm", "k_norm.weight", False),
    ("to_q", "to_q.weight", True), ("to_q_b", "to_q.bias", False),
    ("to_k", "to_k.weight", True), ("to_k_b", "to_k.bias", False),
    ("to_v", "to_v.weight", True), ("to_v_b", "to_v.bias", False),
    ("to_out", "to_out.0.weight", True), ("to_out_b", "to_out.0.bias", False),
]
_FF_MAP = [
    ("fc1", "net.0.proj.weight", True), ("fc1_b", "net.0.proj.bias", False),
    ("fc2", "net.2.weight", True), ("fc2_b", "net.2.bias", False),
]
_ADALN_MAP = [
    ("emb_fc1", "emb.timestep_embedder.linear_1.weight", True),
    ("emb_fc1_b", "emb.timestep_embedder.linear_1.bias", False),
    ("emb_fc2", "emb.timestep_embedder.linear_2.weight", True),
    ("emb_fc2_b", "emb.timestep_embedder.linear_2.bias", False),
    ("linear", "linear.weight", True), ("linear_b", "linear.bias", False),
]
_CAP_MAP = [
    ("linear_1", "linear_1.weight", True), ("linear_1_b", "linear_1.bias", False),
    ("linear_2", "linear_2.weight", True), ("linear_2_b", "linear_2.bias", False),
]

_BLOCK_SUBMODULES = [
    ("attn1", "attn1", _ATTN_MAP), ("attn2", "attn2", _ATTN_MAP),
    ("ff", "ff", _FF_MAP),
    ("audio_attn1", "audio_attn1", _ATTN_MAP),
    ("audio_attn2", "audio_attn2", _ATTN_MAP),
    ("audio_ff", "audio_ff", _FF_MAP),
    ("audio_to_video_attn", "audio_to_video_attn", _ATTN_MAP),
    ("video_to_audio_attn", "video_to_audio_attn", _ATTN_MAP),
]
_BLOCK_TABLES = [
    ("scale_shift_table", "scale_shift_table"),
    ("audio_scale_shift_table", "audio_scale_shift_table"),
    ("scale_shift_table_a2v_ca_video", "scale_shift_table_a2v_ca_video"),
    ("scale_shift_table_a2v_ca_audio", "scale_shift_table_a2v_ca_audio"),
]
_TOP_SINGLE = [
    ("patchify_proj", "patchify_proj.weight", True),
    ("patchify_proj_b", "patchify_proj.bias", False),
    ("scale_shift_table", "scale_shift_table", False),
    ("proj_out", "proj_out.weight", True), ("proj_out_b", "proj_out.bias", False),
    ("audio_patchify_proj", "audio_patchify_proj.weight", True),
    ("audio_patchify_proj_b", "audio_patchify_proj.bias", False),
    ("audio_scale_shift_table", "audio_scale_shift_table", False),
    ("audio_proj_out", "audio_proj_out.weight", True),
    ("audio_proj_out_b", "audio_proj_out.bias", False),
]
_TOP_MODULES = [
    ("caption_projection", "caption_projection", _CAP_MAP),
    ("audio_caption_projection", "audio_caption_projection", _CAP_MAP),
    ("adaln_single", "adaln_single", _ADALN_MAP),
    ("audio_adaln_single", "audio_adaln_single", _ADALN_MAP),
    ("av_ca_video_scale_shift_adaln_single",
     "av_ca_video_scale_shift_adaln_single", _ADALN_MAP),
    ("av_ca_audio_scale_shift_adaln_single",
     "av_ca_audio_scale_shift_adaln_single", _ADALN_MAP),
    ("av_ca_a2v_gate_adaln_single", "av_ca_a2v_gate_adaln_single", _ADALN_MAP),
    ("av_ca_v2a_gate_adaln_single", "av_ca_v2a_gate_adaln_single", _ADALN_MAP),
]


def params_to_hf(params, cfg: LTX2Config) -> Dict[str, np.ndarray]:
    from veomni_tpu.models import hf_io

    host = hf_io.gather_to_host(params)
    out: Dict[str, np.ndarray] = {}
    for ours, hf, tr in _TOP_SINGLE:
        if ours in host:
            x = host[ours]
            out[hf] = np.ascontiguousarray(x.T) if tr else x
    for ours, hf, mapping in _TOP_MODULES:
        if ours in host:
            for o2, h2, tr in mapping:
                x = host[ours][o2]
                out[f"{hf}.{h2}"] = np.ascontiguousarray(x.T) if tr else x
    for i in range(cfg.num_layers):
        pfx = f"transformer_blocks.{i}"
        for ours, hf, mapping in _BLOCK_SUBMODULES:
            if ours not in host["blocks"]:
                continue
            for o2, h2, tr in mapping:
                x = host["blocks"][ours][o2][i]
                out[f"{pfx}.{hf}.{h2}"] = np.ascontiguousarray(x.T) if tr else x
        for ours, hf in _BLOCK_TABLES:
            if ours in host["blocks"]:
                out[f"{pfx}.{hf}"] = host["blocks"][ours][i]
    return out


def hf_to_params(model_dir: str, cfg: LTX2Config, target_shardings=None):
    from veomni_tpu.models import hf_io

    lazy = hf_io.LazyHFTensors(model_dir)
    pd = cfg.param_dtype

    def read(name):
        return np.asarray(lazy.read(name))

    def get(name, tr):
        a = read(name)
        return jnp.asarray(np.ascontiguousarray(a.T) if tr else a, pd)

    params: Params = {}
    for ours, hf, tr in _TOP_SINGLE:
        if hf in lazy:
            params[ours] = get(hf, tr)
    for ours, hf, mapping in _TOP_MODULES:
        if f"{hf}.{mapping[0][1]}" in lazy:
            params[ours] = {o2: get(f"{hf}.{h2}", tr) for o2, h2, tr in mapping}
    blocks: Params = {}
    for ours, hf, mapping in _BLOCK_SUBMODULES:
        if f"transformer_blocks.0.{hf}.{mapping[0][1]}" not in lazy:
            continue
        sub = {}
        for o2, h2, tr in mapping:
            sub[o2] = jnp.asarray(np.stack([
                np.ascontiguousarray(read(f"transformer_blocks.{i}.{hf}.{h2}").T)
                if tr else read(f"transformer_blocks.{i}.{hf}.{h2}")
                for i in range(cfg.num_layers)
            ]), pd)
        blocks[ours] = sub
    for ours, hf in _BLOCK_TABLES:
        if f"transformer_blocks.0.{hf}" in lazy:
            blocks[ours] = jnp.asarray(np.stack([
                read(f"transformer_blocks.{i}.{hf}")
                for i in range(cfg.num_layers)
            ]), pd)
    params["blocks"] = blocks
    return params


def save_hf_checkpoint(params, cfg: LTX2Config, out_dir: str) -> None:
    import json
    import os

    from safetensors.numpy import save_file

    tensors = params_to_hf(params, cfg)
    if jax.process_index() != 0:
        return
    os.makedirs(out_dir, exist_ok=True)
    save_file({k: np.ascontiguousarray(v) for k, v in tensors.items()},
              os.path.join(out_dir, "model.safetensors"))
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump({
            "model_type": "ltx2",
            "architectures": ["LTXVideoTransformerModel"],
            "num_attention_heads": cfg.num_attention_heads,
            "attention_head_dim": cfg.attention_head_dim,
            "in_channels": cfg.in_channels,
            "out_channels": cfg.out_channels,
            "num_layers": cfg.num_layers,
            "cross_attention_dim": cfg.cross_attention_dim,
            "caption_channels": cfg.caption_channels,
            "with_audio": cfg.with_audio,
            "audio_num_attention_heads": cfg.audio_num_attention_heads,
            "audio_attention_head_dim": cfg.audio_attention_head_dim,
            "audio_in_channels": cfg.audio_in_channels,
            "audio_out_channels": cfg.audio_out_channels,
            "positional_embedding_max_pos": list(cfg.positional_embedding_max_pos),
            "audio_positional_embedding_max_pos":
                list(cfg.audio_positional_embedding_max_pos),
            "video_pos_scale": list(cfg.video_pos_scale),
            "audio_pos_scale": list(cfg.audio_pos_scale),
            "video_shape": list(cfg.video_shape),
            "audio_len": cfg.audio_len,
        }, f, indent=2)


def config_from_hf(hf: Dict[str, Any], **overrides) -> LTX2Config:
    fields = set(LTX2Config.__dataclass_fields__)
    kw = {k: v for k, v in hf.items() if k in fields}
    kw.update(overrides)
    kw["model_type"] = "ltx2"
    return LTX2Config(**kw)
