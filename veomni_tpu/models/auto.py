"""Model registry + build entry points.

Reference: ``veomni/models/auto.py:41-280`` (build_foundation_model /
build_tokenizer) and ``models/loader.py:49-291`` (registries keyed by
model_type). A *family* bundles the functional pieces the trainer needs:
config class, init/apply/loss, the declarative ParallelPlan, and HF
checkpoint converters.
"""

from __future__ import annotations

import os

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

import jax

from veomni_tpu.models import hf_io, transformer
from veomni_tpu.models.config import TransformerConfig
from veomni_tpu.parallel.parallel_plan import ParallelPlan
from veomni_tpu.utils.logging import get_logger
from veomni_tpu.utils.registry import Registry

logger = get_logger(__name__)

MODEL_REGISTRY = Registry("models")


@dataclass
class ModelFamily:
    """The per-model_type recipe (cf. reference MODELING_REGISTRY entries)."""

    model_type: str
    config_cls: type = TransformerConfig
    init_params: Callable = transformer.init_params
    abstract_params: Callable = transformer.abstract_params
    loss_fn: Callable = transformer.loss_fn
    forward_logits: Callable = transformer.forward_logits
    hf_to_params: Callable = hf_io.hf_to_params
    save_hf_checkpoint: Callable = hf_io.save_hf_checkpoint
    parallel_plan_fn: Optional[Callable] = None

    def get_parallel_plan(self, cfg) -> ParallelPlan:
        """Model-declared sharding (reference get_parallel_plan,
        e.g. ``models/transformers/qwen3_moe/parallel_plan.py:6-16``)."""
        if self.parallel_plan_fn is not None:
            return self.parallel_plan_fn(cfg)
        rules: Dict[str, tuple] = {}
        if getattr(cfg, "is_moe", False):
            # experts [L, E, in, out]: expert dim over ep, features over fsdp
            rules[r"layers\.experts\..*"] = ("ep", "ep_fsdp", None)
            rules[r"layers\.router$"] = ()
        return ParallelPlan(rules=rules)


for _mt in (
    "llama", "qwen2", "qwen3", "qwen3_moe",
    "gemma3", "gemma3_text",
    "deepseek_v2", "deepseek_v3",
    "gpt_oss", "seed_oss", "glm_moe", "glm4_moe", "glm_moe_dsa",
):
    MODEL_REGISTRY.register(_mt, ModelFamily(model_type=_mt))


def _register_qwen3_next():
    from veomni_tpu.models import qwen3_next as q3n

    MODEL_REGISTRY.register(
        "qwen3_next",
        ModelFamily(
            model_type="qwen3_next",
            init_params=q3n.init_params,
            abstract_params=q3n.abstract_params,
            loss_fn=q3n.loss_fn,
            forward_logits=q3n.forward_logits,
            hf_to_params=q3n.hf_to_params,
            save_hf_checkpoint=q3n.save_hf_checkpoint,
            parallel_plan_fn=q3n.parallel_plan,
        ),
    )


_register_qwen3_next()


def _register_deepseek_v4():
    from veomni_tpu.models import deepseek_v4 as dsv4

    MODEL_REGISTRY.register(
        "deepseek_v4",
        ModelFamily(
            model_type="deepseek_v4",
            config_cls=dsv4.DeepseekV4Config,
            init_params=dsv4.init_params,
            abstract_params=dsv4.abstract_params,
            loss_fn=dsv4.loss_fn,
            forward_logits=dsv4.forward_logits,
            hf_to_params=dsv4.hf_to_params,
            save_hf_checkpoint=dsv4.save_hf_checkpoint,
        ),
    )


_register_deepseek_v4()


def _register_vlm_families():
    from veomni_tpu.models import vlm as vlm_mod
    from veomni_tpu.models.vlm import VLMConfig

    def _save_native(params, cfg, out_dir):
        """Native flat-safetensors save for composite models (HF-layout VLM
        export is a follow-up; the language_model subtree additionally gets a
        standard HF export)."""
        import os

        from safetensors.flax import save_file

        from veomni_tpu.parallel.parallel_plan import param_path_str

        host = hf_io.gather_to_host(params)  # collective in multiprocess
        if jax.process_index() == 0:
            os.makedirs(out_dir, exist_ok=True)
            flat = {}
            jax.tree_util.tree_map_with_path(
                lambda p, x: flat.__setitem__(param_path_str(p), x), host
            )
            save_file(flat, f"{out_dir}/model.safetensors")
        # reuse the gathered host copy: gather_to_host inside is a no-op on
        # numpy leaves, so the LM isn't allgathered a second time
        hf_io.save_hf_checkpoint(
            host["language_model"], cfg.text, f"{out_dir}/language_model"
        )

    # generic fixed-slot VLM composite (any ViT + any registered LM) — the
    # didactic/testing baseline; real checkpoint families have their own archs
    MODEL_REGISTRY.register(
        "slot_vlm",
        ModelFamily(
            model_type="slot_vlm",
            config_cls=VLMConfig,
            init_params=vlm_mod.init_vlm_params,
            abstract_params=vlm_mod.abstract_vlm_params,
            loss_fn=vlm_mod.vlm_loss_fn,
            forward_logits=None,
            hf_to_params=None,
            save_hf_checkpoint=_save_native,
        ),
    )

    # qwen2_vl is the real architecture (full-attn LayerNorm ViT, per-frame
    # segments, quick-GELU MLP, mrope)
    from veomni_tpu.models import qwen2_vl as q2vl

    MODEL_REGISTRY.register(
        "qwen2_vl",
        ModelFamily(
            model_type="qwen2_vl",
            config_cls=q2vl.Qwen2VLConfig,
            init_params=q2vl.init_params,
            abstract_params=q2vl.abstract_params,
            loss_fn=q2vl.loss_fn,
            forward_logits=None,
            hf_to_params=q2vl.hf_to_params,
            save_hf_checkpoint=q2vl.save_hf_checkpoint,
        ),
    )

    # qwen3_vl is the real architecture (deepstack ViT + interleaved mrope);
    # qwen3_vl_moe = same tower + qwen3_moe text (fused-chunked experts)
    from veomni_tpu.models import qwen3_vl as q3vl

    for mt in ("qwen3_vl", "qwen3_vl_moe"):
        MODEL_REGISTRY.register(
            mt,
            ModelFamily(
                model_type=mt,
                config_cls=q3vl.Qwen3VLConfig,
                init_params=q3vl.init_params,
                abstract_params=q3vl.abstract_params,
                loss_fn=q3vl.loss_fn,
                forward_logits=None,
                hf_to_params=q3vl.hf_to_params,
                save_hf_checkpoint=q3vl.save_hf_checkpoint,
            ),
        )

    # qwen2_5_vl is the real architecture (window-attn ViT + mrope + merger)
    from veomni_tpu.models import qwen2_5_vl as q25

    MODEL_REGISTRY.register(
        "qwen2_5_vl",
        ModelFamily(
            model_type="qwen2_5_vl",
            config_cls=q25.Qwen25VLConfig,
            init_params=q25.init_params,
            abstract_params=q25.abstract_params,
            loss_fn=q25.loss_fn,
            forward_logits=None,
            hf_to_params=q25.hf_to_params,
            save_hf_checkpoint=q25.save_hf_checkpoint,
        ),
    )

    # janus: unified understanding (SigLIP ViT) + generation (llamagen VQ)
    from veomni_tpu.models import janus as janus_mod

    def _janus_plan(_cfg):
        from veomni_tpu.parallel.parallel_plan import ParallelPlan

        # replicate the (frozen) VQ tokenizer: GSPMD-partitioned conv
        # kernels deadlock XLA:CPU's rendezvous and gain nothing on TPU
        return ParallelPlan(rules={r"(^|\.)gen_vision\.": ()})

    MODEL_REGISTRY.register(
        "janus",
        ModelFamily(
            model_type="janus",
            config_cls=janus_mod.JanusConfig,
            init_params=janus_mod.init_params,
            abstract_params=janus_mod.abstract_params,
            loss_fn=janus_mod.loss_fn,
            forward_logits=None,
            hf_to_params=janus_mod.hf_to_params,
            save_hf_checkpoint=janus_mod.save_hf_checkpoint,
            parallel_plan_fn=_janus_plan,
        ),
    )

    # qwen2_5_omni thinker: real audio tower + qwen2_5_vl vision/LM
    from veomni_tpu.models import qwen2_5_omni as q25o

    MODEL_REGISTRY.register(
        "qwen2_5_omni",
        ModelFamily(
            model_type="qwen2_5_omni",
            config_cls=q25o.Qwen25OmniConfig,
            init_params=q25o.init_params,
            abstract_params=q25o.abstract_params,
            loss_fn=q25o.loss_fn,
            forward_logits=None,
            hf_to_params=q25o.hf_to_params,
            save_hf_checkpoint=q25o.save_hf_checkpoint,
            parallel_plan_fn=q25o.parallel_plan,
        ),
    )

    # qwen3_omni_moe thinker: AuT audio + qwen3_vl vision + MoE LM
    from veomni_tpu.models import qwen3_omni_moe as q3o

    MODEL_REGISTRY.register(
        "qwen3_omni_moe",
        ModelFamily(
            model_type="qwen3_omni_moe",
            config_cls=q3o.Qwen3OmniMoeConfig,
            init_params=q3o.init_params,
            abstract_params=q3o.abstract_params,
            loss_fn=q3o.loss_fn,
            forward_logits=None,
            hf_to_params=q3o.hf_to_params,
            save_hf_checkpoint=q3o.save_hf_checkpoint,
            parallel_plan_fn=q3o.parallel_plan,
        ),
    )


def _register_diffusion_families():
    from veomni_tpu.models import (
        flux as flux_mod,
        ltx2 as ltx2_mod,
        qwen_image as qi_mod,
        wan as wan_mod,
    )

    for mt, mod, cfg_cls in (
        ("wan_t2v", wan_mod, wan_mod.WanConfig),
        ("qwen_image", qi_mod, qi_mod.QwenImageConfig),
        ("flux", flux_mod, flux_mod.FluxConfig),
        ("ltx2", ltx2_mod, ltx2_mod.LTX2Config),
    ):
        MODEL_REGISTRY.register(
            mt,
            ModelFamily(
                model_type=mt,
                config_cls=cfg_cls,
                init_params=mod.init_params,
                abstract_params=mod.abstract_params,
                loss_fn=mod.loss_fn,
                forward_logits=None,
                hf_to_params=mod.hf_to_params,
                save_hf_checkpoint=mod.save_hf_checkpoint,
            ),
        )


_register_vlm_families()
_register_diffusion_families()

VLM_MODEL_TYPES = ("slot_vlm", "qwen2_vl", "qwen2_5_vl", "qwen3_vl", "qwen3_vl_moe")


def build_config(model_type: str = "", **overrides):
    """Construct the right config class for a model_type (VLM vs text).

    For VLM types, top-level non-VLM keys (dtype, remat, ...) flow into the
    nested text config so the same override surface works for both.
    """
    overrides.pop("model_type", None)
    if model_type == "janus":
        from veomni_tpu.models.janus import JanusConfig

        kw = {
            k: overrides.pop(k)
            for k in ("vision", "gen_vision", "aligner_depth",
                      "gen_aligner_depth", "gen_head_embed", "image_token_id",
                      "image_gen_token_id", "gen_loss_weight", "freeze_vision",
                      "freeze_gen_vision", "max_images", "max_gen_images")
            if k in overrides
        }
        text = dict(overrides.pop("text", {}) or {})
        text.update(overrides)
        text.setdefault("model_type", "llama")
        return JanusConfig(text=text, **kw)
    if model_type == "deepseek_v4":
        from veomni_tpu.models.deepseek_v4 import DeepseekV4Config

        return DeepseekV4Config(**overrides)
    if model_type in ("qwen2_vl", "qwen2_5_vl", "qwen3_vl", "qwen3_vl_moe"):
        if model_type == "qwen2_vl":
            from veomni_tpu.models.qwen2_vl import Qwen2VLConfig as vl_cfg

            text_mt = "qwen2"
        elif model_type == "qwen2_5_vl":
            from veomni_tpu.models.qwen2_5_vl import Qwen25VLConfig as vl_cfg

            text_mt = "qwen2"
        else:
            from veomni_tpu.models.qwen3_vl import Qwen3VLConfig as vl_cfg

            text_mt = "qwen3_moe" if model_type == "qwen3_vl_moe" else "qwen3"
        kw = {
            k: overrides.pop(k)
            for k in ("vision", "image_token_id", "video_token_id",
                      "vision_start_token_id", "freeze_vision")
            if k in overrides
        }
        text = dict(overrides.pop("text", {}) or {})
        text.update(overrides)
        text.setdefault("model_type", text_mt)
        if model_type.startswith("qwen3_vl") and text.get("rope_scaling"):
            # qwen3-vl mrope is interleaved — keep both config paths
            # (build_config and config_from_hf) on the same rope layout
            rs = dict(text["rope_scaling"])
            rs.setdefault("mrope_interleaved", True)
            text["rope_scaling"] = rs
        if model_type == "qwen3_vl_moe":
            text.setdefault("expert_layout", "fused_chunked")
            kw["model_type"] = model_type
        return vl_cfg(text=text, **kw)
    if model_type == "qwen2_5_omni":
        from veomni_tpu.models.qwen2_5_omni import Qwen25OmniConfig

        kw = {
            k: overrides.pop(k)
            for k in ("vision", "audio", "image_token_id", "video_token_id",
                      "audio_token_id", "vision_start_token_id",
                      "audio_start_token_id", "audio_end_token_id",
                      "position_id_per_seconds", "freeze_vision",
                      "freeze_audio")
            if k in overrides
        }
        text = dict(overrides.pop("text", {}) or {})
        text.update(overrides)
        text.setdefault("model_type", "qwen2")
        return Qwen25OmniConfig(text=text, **kw)
    if model_type == "qwen3_omni_moe":
        from veomni_tpu.models.qwen3_omni_moe import Qwen3OmniMoeConfig

        kw = {
            k: overrides.pop(k)
            for k in ("vision", "audio", "image_token_id", "video_token_id",
                      "audio_token_id", "vision_start_token_id",
                      "audio_start_token_id", "position_id_per_seconds",
                      "freeze_vision", "freeze_audio")
            if k in overrides
        }
        text = dict(overrides.pop("text", {}) or {})
        text.update(overrides)
        text.setdefault("model_type", "qwen3_moe")
        if text.get("rope_scaling"):
            rs = dict(text["rope_scaling"])
            rs.setdefault("mrope_interleaved", True)
            text["rope_scaling"] = rs
        return Qwen3OmniMoeConfig(text=text, **kw)
    if model_type in VLM_MODEL_TYPES:
        from veomni_tpu.models.vlm import VLMConfig

        vlm_kw = {
            k: overrides.pop(k)
            for k in ("vision", "image_token_id", "freeze_vision")
            if k in overrides
        }
        text = dict(overrides.pop("text", {}) or {})
        text.update(overrides)
        return VLMConfig(model_type=model_type, text=text, **vlm_kw)
    return TransformerConfig(model_type=model_type or "llama", **overrides)


@dataclass
class FoundationModel:
    """What build_foundation_model returns: config + family + (lazy) params."""

    config: TransformerConfig
    family: ModelFamily
    params: Optional[Any] = None

    def init(self, rng: jax.Array):
        self.params = self.family.init_params(rng, self.config)
        return self.params

    def abstract(self):
        return self.family.abstract_params(self.config)

    def loss_fn(self, params, batch):
        return self.family.loss_fn(params, self.config, batch)

    def get_parallel_plan(self) -> ParallelPlan:
        return self.family.get_parallel_plan(self.config)

    def load_hf(self, model_dir: str, target_shardings=None):
        if self.family.hf_to_params is None:
            raise NotImplementedError(
                f"HF checkpoint import not wired for {self.family.model_type}; "
                "load the native safetensors export instead"
            )
        self.params = self.family.hf_to_params(model_dir, self.config, target_shardings)
        return self.params

    def save_hf(self, out_dir: str, params=None):
        self.family.save_hf_checkpoint(
            params if params is not None else self.params, self.config, out_dir
        )


def build_foundation_model(
    config_path: Optional[str] = None,
    *,
    config: Optional[TransformerConfig] = None,
    weights_path: Optional[str] = None,
    ops_implementation: Optional[Dict[str, str]] = None,
    **config_overrides,
) -> FoundationModel:
    """Reference ``build_foundation_model`` (models/auto.py:110): resolve
    config -> bind ops -> construct (weights load deferred to the
    parallelized build so tensors land shard-aligned)."""
    from veomni_tpu.ops.kernel_registry import apply_ops_config

    if config is None:
        if config_path is None:
            raise ValueError("need config_path or config")
        import json as _json
        import os as _os

        with open(_os.path.join(config_path, "config.json")) as f:
            hf_dict = _json.load(f)
        if hf_dict.get("model_type") == "deepseek_v4":
            from veomni_tpu.models.deepseek_v4 import config_from_hf as dsv4_from_hf

            config = dsv4_from_hf(hf_dict, **config_overrides)
        elif hf_dict.get("model_type") == "qwen2_vl":
            from veomni_tpu.models.qwen2_vl import config_from_hf as q2vl_from_hf

            config = q2vl_from_hf(hf_dict, **config_overrides)
        elif hf_dict.get("model_type") == "qwen2_5_vl":
            from veomni_tpu.models.qwen2_5_vl import config_from_hf

            config = config_from_hf(hf_dict, **config_overrides)
        elif hf_dict.get("model_type") in ("qwen3_vl", "qwen3_vl_moe"):
            from veomni_tpu.models.qwen3_vl import config_from_hf as q3vl_from_hf

            config = q3vl_from_hf(hf_dict, **config_overrides)
        elif hf_dict.get("model_type") == "janus":
            from veomni_tpu.models.janus import config_from_hf as janus_from_hf

            config = janus_from_hf(hf_dict, **config_overrides)
        elif hf_dict.get("model_type") in ("qwen2_5_omni", "qwen2_5_omni_thinker"):
            from veomni_tpu.models.qwen2_5_omni import config_from_hf as omni_from_hf

            config = omni_from_hf(hf_dict, **config_overrides)
        elif hf_dict.get("model_type") in ("qwen3_omni_moe", "qwen3_omni_moe_thinker"):
            from veomni_tpu.models.qwen3_omni_moe import config_from_hf as q3o_from_hf

            config = q3o_from_hf(hf_dict, **config_overrides)
        elif (hf_dict.get("model_type") == "wan_t2v"
              or hf_dict.get("_class_name") == "WanTransformer3DModel"):
            from veomni_tpu.models.wan import config_from_hf as wan_from_hf

            config = wan_from_hf(hf_dict, **config_overrides)
        elif (hf_dict.get("model_type") == "qwen_image"
              or hf_dict.get("_class_name") == "QwenImageTransformer2DModel"):
            from veomni_tpu.models.qwen_image import config_from_hf as qi_from_hf

            config = qi_from_hf(hf_dict, **config_overrides)
        elif (hf_dict.get("model_type") == "flux"
              or hf_dict.get("_class_name") == "FluxTransformer2DModel"):
            from veomni_tpu.models.flux import config_from_hf as flux_from_hf

            config = flux_from_hf(hf_dict, **config_overrides)
        elif (hf_dict.get("model_type") == "ltx2"
              or hf_dict.get("_class_name") == "LTXVideoTransformerModel"):
            from veomni_tpu.models.ltx2 import config_from_hf as ltx2_from_hf

            config = ltx2_from_hf(hf_dict, **config_overrides)
        else:
            config = TransformerConfig.from_hf_config(hf_dict, **config_overrides)
    if config.model_type not in MODEL_REGISTRY:
        logger.warning_rank0(
            "model_type %r not registered; using llama-family core", config.model_type
        )
    family = (
        MODEL_REGISTRY.get(config.model_type)
        if config.model_type in MODEL_REGISTRY
        else ModelFamily(model_type=config.model_type)
    )
    apply_ops_config(ops_implementation)
    model = FoundationModel(config=config, family=family)
    if weights_path:
        model.load_hf(weights_path)
    return model


def build_tokenizer(path: str):
    """HF tokenizer passthrough (reference models/auto.py:41).

    Local checkpoint dirs live on shared filesystems whose reads fail
    transiently — those retry with the same bounded deterministic backoff as
    the other I/O edges (resilience/retry.py). Hub-id loads do NOT retry:
    transformers raises plain OSError for PERMANENT errors too (unknown
    model id, gated repo), and retrying those burns round-trips while
    masking the real message."""
    from transformers import AutoTokenizer

    if os.path.isdir(path):
        from veomni_tpu.resilience.retry import retry_call

        return retry_call(
            AutoTokenizer.from_pretrained, path, trust_remote_code=True,
            description=f"tokenizer load {path}",
        )
    return AutoTokenizer.from_pretrained(path, trust_remote_code=True)
