from veomni_tpu.models.auto import (
    MODEL_REGISTRY,
    FoundationModel,
    ModelFamily,
    build_foundation_model,
    build_tokenizer,
)
from veomni_tpu.models.config import TransformerConfig

__all__ = [
    "MODEL_REGISTRY",
    "FoundationModel",
    "ModelFamily",
    "TransformerConfig",
    "build_foundation_model",
    "build_tokenizer",
]
