"""DiT: diffusion transformer for latent image/video generation.

Reference capability: ``veomni/models/diffusers/`` (wan_t2v, qwen_image,
ltx2_3 DiT models trained by DiTTrainer with the FlowMatch scheduler).
TPU-first design mirrors the text core: stacked adaLN-zero blocks scanned
with ``lax.scan``, full (non-causal) attention through the shared
``ops.attention`` facade, conditioning = timestep sinusoidal embedding +
(pre-computed) text/condition embedding.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from veomni_tpu import ops


@dataclass
class DiTConfig:
    model_type: str = "dit"
    latent_size: int = 32      # latent grid (H == W)
    latent_channels: int = 4
    patch_size: int = 2
    hidden_size: int = 384
    num_hidden_layers: int = 8
    num_attention_heads: int = 6
    mlp_ratio: float = 4.0
    cond_dim: int = 512        # pre-computed condition embedding dim
    initializer_range: float = 0.02
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True

    def __post_init__(self):
        if isinstance(self.dtype, str):
            self.dtype = getattr(jnp, self.dtype)
        if isinstance(self.param_dtype, str):
            self.param_dtype = getattr(jnp, self.param_dtype)

    @property
    def tokens(self) -> int:
        return (self.latent_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.latent_channels * self.patch_size ** 2

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


def timestep_embedding(t: jax.Array, dim: int, max_period: float = 10000.0):
    """Sinusoidal [B] -> [B, dim]."""
    half = dim // 2
    freqs = jnp.exp(-math.log(max_period) * jnp.arange(half, dtype=jnp.float32) / half)
    ang = t.astype(jnp.float32)[:, None] * freqs[None]
    return jnp.concatenate([jnp.cos(ang), jnp.sin(ang)], axis=-1)


def init_dit_params(rng: jax.Array, cfg: DiTConfig) -> Dict[str, Any]:
    pd = cfg.param_dtype
    s = cfg.initializer_range
    h = cfg.hidden_size
    inter = int(h * cfg.mlp_ratio)
    L = cfg.num_hidden_layers
    keys = iter(jax.random.split(rng, 32))

    def init(shape, scale=s):
        return (jax.random.normal(next(keys), shape, jnp.float32) * scale).astype(pd)

    return {
        "patch_embed": init((cfg.patch_dim, h)),
        "pos_embed": init((cfg.tokens, h)),
        "t_embed": {"fc1": init((256, h)), "fc2": init((h, h))},
        "cond_embed": init((cfg.cond_dim, h)),
        "layers": {
            # adaLN-zero: 6 modulation vectors per block from the cond signal
            "mod": jnp.zeros((L, h, 6 * h), pd),
            "mod_bias": jnp.zeros((L, 6 * h), pd),
            "qkv": init((L, h, 3 * h)),
            "proj": init((L, h, h)),
            "fc1": init((L, h, inter)),
            "fc2": init((L, inter, h)),
        },
        "final_mod": jnp.zeros((h, 2 * h), pd),
        "final_mod_bias": jnp.zeros((2 * h,), pd),
        "final_proj": jnp.zeros((h, cfg.patch_dim), pd),  # zero-init output
    }


def abstract_dit_params(cfg: DiTConfig):
    return jax.eval_shape(lambda: init_dit_params(jax.random.PRNGKey(0), cfg))


def _modulate(x, shift, scale):
    return x * (1 + scale[:, None, :]) + shift[:, None, :]


def _ln(x):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + 1e-6)


def _dit_block(x, c, lp, cfg: DiTConfig):
    """x [B,T,H]; c [B,H] conditioning."""
    b, t, h = x.shape
    mod = jnp.dot(jax.nn.silu(c), lp["mod"]) + lp["mod_bias"]  # [B, 6H]
    sa_shift, sa_scale, sa_gate, mlp_shift, mlp_scale, mlp_gate = jnp.split(mod, 6, -1)

    y = _modulate(_ln(x), sa_shift, sa_scale)
    qkv = jnp.dot(y, lp["qkv"]).reshape(b, t, 3 * cfg.num_attention_heads, cfg.head_dim)
    q, k, v = jnp.split(qkv, 3, axis=2)
    attn = ops.attention(q, k, v, causal=False).reshape(b, t, h)
    x = x + sa_gate[:, None, :] * jnp.dot(attn, lp["proj"])

    y = _modulate(_ln(x), mlp_shift, mlp_scale)
    y = jnp.dot(jax.nn.gelu(jnp.dot(y, lp["fc1"]), approximate=True), lp["fc2"])
    return x + mlp_gate[:, None, :] * y, None


def patchify(latents: jax.Array, cfg: DiTConfig) -> jax.Array:
    """[B, G, G, C] -> [B, T, patch_dim]."""
    b = latents.shape[0]
    g, p, c = cfg.latent_size, cfg.patch_size, cfg.latent_channels
    n = g // p
    x = latents.reshape(b, n, p, n, p, c).transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, n * n, p * p * c)


def unpatchify(x: jax.Array, cfg: DiTConfig) -> jax.Array:
    b = x.shape[0]
    g, p, c = cfg.latent_size, cfg.patch_size, cfg.latent_channels
    n = g // p
    x = x.reshape(b, n, n, p, p, c).transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, g, g, c)


def dit_forward(params, cfg: DiTConfig, noisy_latents, t, cond) -> jax.Array:
    """noisy_latents [B,G,G,C]; t [B]; cond [B, cond_dim] -> velocity field."""
    compute = jax.tree.map(lambda p: p.astype(cfg.dtype), params)
    x = jnp.dot(patchify(noisy_latents.astype(cfg.dtype), cfg), compute["patch_embed"])
    x = x + compute["pos_embed"]

    temb = timestep_embedding(t * 1000.0, 256).astype(cfg.dtype)
    c = jnp.dot(jax.nn.silu(jnp.dot(temb, compute["t_embed"]["fc1"])),
                compute["t_embed"]["fc2"])
    c = c + jnp.dot(cond.astype(cfg.dtype), compute["cond_embed"])

    body = partial(_dit_block, cfg=cfg)
    if cfg.remat:
        body = jax.checkpoint(body, policy=jax.checkpoint_policies.nothing_saveable)
    x, _ = jax.lax.scan(lambda carry, lp: body(carry, c, lp), x, compute["layers"])

    mod = jnp.dot(jax.nn.silu(c), compute["final_mod"]) + compute["final_mod_bias"]
    shift, scale = jnp.split(mod, 2, -1)
    x = _modulate(_ln(x), shift, scale)
    out = jnp.dot(x, compute["final_proj"])
    return unpatchify(out.astype(jnp.float32), cfg)


def dit_loss_fn(params, cfg: DiTConfig, batch) -> Tuple[jax.Array, Dict]:
    """FlowMatch MSE: batch {latents, noise, t, cond} (noise/t sampled by the
    collator so the jit step stays rng-free)."""
    x0 = batch["latents"].astype(jnp.float32)
    noise = batch["noise"].astype(jnp.float32)
    t = batch["t"]
    x_t = (1.0 - t[:, None, None, None]) * x0 + t[:, None, None, None] * noise
    target = noise - x0
    pred = dit_forward(params, cfg, x_t, t, batch["cond"])
    per_sample = ((pred - target) ** 2).mean(axis=(1, 2, 3))
    return per_sample.sum(), {"ntokens": jnp.int32(x0.shape[0])}
