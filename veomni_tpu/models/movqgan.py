"""MoVQGAN: VQ image tokenizer/decoder for the image-generation path.

Reference capability: ``veomni/models/transformers/movqgan/modeling_movqgan.py``
(MOVQEncoder/MOVQDecoder/VectorQuantizer, ~650 LoC torch) and its decoder
wrapper ``veomni/models/seed_omni/decoder/movqgan/`` (lm_encode / lm_head /
lm_embed / lm_generate contract). Public architecture: ai-forever/MoVQGAN —
a VQGAN whose decoder normalization is spatially conditioned on the
quantized code (SpatialNorm), n_embed-way codebook over
``resolution / 2^(levels-1)`` square token grids.

TPU-first design: pure functional, NHWC layout (``lax.conv_general_dilated``
maps onto the MXU as implicit GEMMs), static shapes throughout, f32 codebook
math with straight-through gradients. No torch module graph — params are a
nested dict, every block is a plain function, and the whole
encode→quantize→decode pipeline jits as one program.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, Any]

_DN = ("NHWC", "HWIO", "NHWC")


@dataclass
class MoVQGANConfig:
    resolution: int = 256
    in_channels: int = 3
    out_ch: int = 3
    ch: int = 128
    ch_mult: Tuple[int, ...] = (1, 2, 2, 4)
    num_res_blocks: int = 2
    attn_resolutions: Tuple[int, ...] = (32,)
    z_channels: int = 4
    embed_dim: int = 4
    n_embed: int = 16384
    beta: float = 0.25              # commitment weight
    num_groups: int = 32            # GroupNorm groups (clamped to channels)
    initializer_range: float = 0.02

    def __post_init__(self):
        if isinstance(self.ch_mult, list):
            self.ch_mult = tuple(self.ch_mult)
        if isinstance(self.attn_resolutions, list):
            self.attn_resolutions = tuple(self.attn_resolutions)

    @property
    def token_grid(self) -> int:
        return self.resolution // (2 ** (len(self.ch_mult) - 1))

    @property
    def tokens_per_image(self) -> int:
        return self.token_grid ** 2


# --------------------------------------------------------------------------
# primitives
# --------------------------------------------------------------------------
def _conv(x, w, b=None, stride=1, padding="SAME"):
    out = jax.lax.conv_general_dilated(
        x, w, (stride, stride), padding, dimension_numbers=_DN
    )
    return out if b is None else out + b


def _group_norm(x, gamma, beta, groups, eps=1e-6):
    n, h, w, c = x.shape
    g = min(groups, c)
    while c % g:
        g -= 1
    xg = x.reshape(n, h, w, g, c // g).astype(jnp.float32)
    mean = xg.mean((1, 2, 4), keepdims=True)
    var = xg.var((1, 2, 4), keepdims=True)
    xg = (xg - mean) * jax.lax.rsqrt(var + eps)
    return (xg.reshape(n, h, w, c) * gamma + beta).astype(x.dtype)


def _swish(x):
    return x * jax.nn.sigmoid(x)


def _spatial_norm(f, zq, p, groups):
    """MoVQ signature op: GroupNorm(f) modulated by conv projections of the
    quantized code upsampled to f's resolution."""
    zq = jax.image.resize(zq, (*f.shape[:3], zq.shape[-1]), "nearest")
    normed = _group_norm(f, p["gn_w"], p["gn_b"], groups)
    return normed * _conv(zq, p["conv_y_w"], p["conv_y_b"]) + _conv(
        zq, p["conv_b_w"], p["conv_b_b"]
    )


def _attn_block(x, p, groups, zq=None):
    h_ = (
        _spatial_norm(x, zq, p["norm"], groups)
        if zq is not None
        else _group_norm(x, p["norm"]["gn_w"], p["norm"]["gn_b"], groups)
    )
    n, h, w, c = x.shape
    q = _conv(h_, p["q_w"], p["q_b"]).reshape(n, h * w, c)
    k = _conv(h_, p["k_w"], p["k_b"]).reshape(n, h * w, c)
    v = _conv(h_, p["v_w"], p["v_b"]).reshape(n, h * w, c)
    attn = jax.nn.softmax(
        jnp.einsum("nqc,nkc->nqk", q, k).astype(jnp.float32) * (c ** -0.5), axis=-1
    ).astype(x.dtype)
    out = jnp.einsum("nqk,nkc->nqc", attn, v).reshape(n, h, w, c)
    return x + _conv(out, p["proj_w"], p["proj_b"])


def _res_block(x, p, groups, zq=None):
    def norm(y, key):
        return (
            _spatial_norm(y, zq, p[key], groups)
            if zq is not None
            else _group_norm(y, p[key]["gn_w"], p[key]["gn_b"], groups)
        )

    h = _conv(_swish(norm(x, "norm1")), p["conv1_w"], p["conv1_b"])
    h = _conv(_swish(norm(h, "norm2")), p["conv2_w"], p["conv2_b"])
    if "shortcut_w" in p:
        x = _conv(x, p["shortcut_w"], p["shortcut_b"])
    return x + h


# --------------------------------------------------------------------------
# init
# --------------------------------------------------------------------------
def _conv_init(key, kh, kw, cin, cout, scale):
    return jax.random.normal(key, (kh, kw, cin, cout), jnp.float32) * scale


def _norm_params(c, spatial, zq_ch=None):
    p = {"gn_w": jnp.ones((c,), jnp.float32), "gn_b": jnp.zeros((c,), jnp.float32)}
    if spatial:
        p["conv_y_w"] = jnp.zeros((1, 1, zq_ch, c), jnp.float32) + 1.0 / max(zq_ch, 1)
        p["conv_y_b"] = jnp.ones((c,), jnp.float32)
        p["conv_b_w"] = jnp.zeros((1, 1, zq_ch, c), jnp.float32)
        p["conv_b_b"] = jnp.zeros((c,), jnp.float32)
    return p


def _res_params(keys, cin, cout, scale, spatial=False, zq_ch=None):
    p = {
        "norm1": _norm_params(cin, spatial, zq_ch),
        "conv1_w": _conv_init(next(keys), 3, 3, cin, cout, scale),
        "conv1_b": jnp.zeros((cout,), jnp.float32),
        "norm2": _norm_params(cout, spatial, zq_ch),
        "conv2_w": _conv_init(next(keys), 3, 3, cout, cout, scale),
        "conv2_b": jnp.zeros((cout,), jnp.float32),
    }
    if cin != cout:
        p["shortcut_w"] = _conv_init(next(keys), 1, 1, cin, cout, scale)
        p["shortcut_b"] = jnp.zeros((cout,), jnp.float32)
    return p


def _attn_params(keys, c, scale, spatial=False, zq_ch=None):
    p = {"norm": _norm_params(c, spatial, zq_ch)}
    for name in ("q", "k", "v", "proj"):
        p[f"{name}_w"] = _conv_init(next(keys), 1, 1, c, c, scale)
        p[f"{name}_b"] = jnp.zeros((c,), jnp.float32)
    return p


def init_params(rng: jax.Array, cfg: MoVQGANConfig) -> Params:
    s = cfg.initializer_range
    keys = iter(jax.random.split(rng, 512))
    levels = len(cfg.ch_mult)
    chs = [cfg.ch * m for m in cfg.ch_mult]

    # ---- encoder
    enc: Params = {
        "conv_in_w": _conv_init(next(keys), 3, 3, cfg.in_channels, chs[0], s),
        "conv_in_b": jnp.zeros((chs[0],), jnp.float32),
        "down": [],
    }
    res = cfg.resolution
    cin = chs[0]
    for i in range(levels):
        level: Params = {"res": [], "attn": []}
        for _ in range(cfg.num_res_blocks):
            level["res"].append(_res_params(keys, cin, chs[i], s))
            cin = chs[i]
            if res in cfg.attn_resolutions:
                level["attn"].append(_attn_params(keys, cin, s))
        if i != levels - 1:
            level["down_w"] = _conv_init(next(keys), 3, 3, cin, cin, s)
            level["down_b"] = jnp.zeros((cin,), jnp.float32)
            res //= 2
        enc["down"].append(level)
    enc["mid_res1"] = _res_params(keys, cin, cin, s)
    enc["mid_attn"] = _attn_params(keys, cin, s)
    enc["mid_res2"] = _res_params(keys, cin, cin, s)
    enc["norm_out"] = _norm_params(cin, False)
    enc["conv_out_w"] = _conv_init(next(keys), 3, 3, cin, cfg.z_channels, s)
    enc["conv_out_b"] = jnp.zeros((cfg.z_channels,), jnp.float32)

    zq = cfg.embed_dim
    # ---- decoder (spatially-normed on zq); conv_in consumes the
    # post_quant_conv output, which has z_channels channels (reference
    # modeling_movqgan.py:413,594 — embed_dim->z_channels then conv_in)
    dec: Params = {
        "conv_in_w": _conv_init(next(keys), 3, 3, cfg.z_channels, cin, s),
        "conv_in_b": jnp.zeros((cin,), jnp.float32),
        "mid_res1": _res_params(keys, cin, cin, s, True, zq),
        "mid_attn": _attn_params(keys, cin, s, True, zq),
        "mid_res2": _res_params(keys, cin, cin, s, True, zq),
        "up": [],
    }
    for i in reversed(range(levels)):
        level = {"res": [], "attn": []}
        for _ in range(cfg.num_res_blocks + 1):
            level["res"].append(_res_params(keys, cin, chs[i], s, True, zq))
            cin = chs[i]
            if res in cfg.attn_resolutions:
                level["attn"].append(_attn_params(keys, cin, s, True, zq))
        if i != 0:
            level["up_w"] = _conv_init(next(keys), 3, 3, cin, cin, s)
            level["up_b"] = jnp.zeros((cin,), jnp.float32)
            res *= 2
        dec["up"].append(level)
    dec["norm_out"] = _norm_params(cin, True, zq)
    dec["conv_out_w"] = _conv_init(next(keys), 3, 3, cin, cfg.out_ch, s)
    dec["conv_out_b"] = jnp.zeros((cfg.out_ch,), jnp.float32)

    return {
        "encoder": enc,
        "decoder": dec,
        "codebook": jax.random.normal(
            next(keys), (cfg.n_embed, cfg.embed_dim), jnp.float32
        ) * (1.0 / cfg.n_embed ** 0.5),
        "quant_conv_w": _conv_init(next(keys), 1, 1, cfg.z_channels, cfg.embed_dim, s),
        "quant_conv_b": jnp.zeros((cfg.embed_dim,), jnp.float32),
        "post_quant_conv_w": _conv_init(
            next(keys), 1, 1, cfg.embed_dim, cfg.z_channels, s
        ),
        "post_quant_conv_b": jnp.zeros((cfg.z_channels,), jnp.float32),
    }


def abstract_params(cfg: MoVQGANConfig) -> Params:
    return jax.eval_shape(lambda: init_params(jax.random.PRNGKey(0), cfg))


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------
def _encoder(p, cfg, x):
    g = cfg.num_groups
    h = _conv(x, p["conv_in_w"], p["conv_in_b"])
    for i, level in enumerate(p["down"]):
        attn_iter = iter(level["attn"])
        for rp in level["res"]:
            h = _res_block(h, rp, g)
            if level["attn"]:
                h = _attn_block(h, next(attn_iter), g)
        if "down_w" in level:
            h = _conv(
                jnp.pad(h, ((0, 0), (0, 1), (0, 1), (0, 0))),
                level["down_w"], level["down_b"], stride=2, padding="VALID",
            )
    h = _res_block(h, p["mid_res1"], g)
    h = _attn_block(h, p["mid_attn"], g)
    h = _res_block(h, p["mid_res2"], g)
    h = _swish(_group_norm(h, p["norm_out"]["gn_w"], p["norm_out"]["gn_b"], g))
    return _conv(h, p["conv_out_w"], p["conv_out_b"])


def _decoder(p, cfg, z, zq):
    g = cfg.num_groups
    h = _conv(z, p["conv_in_w"], p["conv_in_b"])
    h = _res_block(h, p["mid_res1"], g, zq)
    h = _attn_block(h, p["mid_attn"], g, zq)
    h = _res_block(h, p["mid_res2"], g, zq)
    for level in p["up"]:
        attn_iter = iter(level["attn"])
        for rp in level["res"]:
            h = _res_block(h, rp, g, zq)
            if level["attn"]:
                h = _attn_block(h, next(attn_iter), g, zq)
        if "up_w" in level:
            n, hh, ww, c = h.shape
            h = jax.image.resize(h, (n, hh * 2, ww * 2, c), "nearest")
            h = _conv(h, level["up_w"], level["up_b"])
    h = _swish(_spatial_norm(h, zq, p["norm_out"], g))
    return _conv(h, p["conv_out_w"], p["conv_out_b"])


def quantize(codebook: jax.Array, z: jax.Array, beta: float):
    """z [N,h,w,e] -> (z_q straight-through, indices [N,h,w], vq_loss [N]).

    The VQ/commit loss is PER IMAGE so callers with padded image slots can
    mask before reducing (``omni_loss_fn``); ``autoencode_loss`` takes the
    mean."""
    zf = z.astype(jnp.float32)
    cb = codebook.astype(jnp.float32)
    d = (
        (zf * zf).sum(-1, keepdims=True)
        - 2.0 * jnp.einsum("nhwe,ke->nhwk", zf, cb)
        + (cb * cb).sum(-1)[None, None, None, :]
    )
    idx = jnp.argmin(d, axis=-1)
    e = cb[idx]
    vq_loss = ((jax.lax.stop_gradient(zf) - e) ** 2).mean((1, 2, 3)) + beta * (
        (zf - jax.lax.stop_gradient(e)) ** 2
    ).mean((1, 2, 3))
    z_q = zf + jax.lax.stop_gradient(e - zf)  # straight-through
    return z_q.astype(z.dtype), idx, vq_loss


def encode(params: Params, cfg: MoVQGANConfig, pixels: jax.Array):
    """pixels [N,H,W,C] in [-1,1] -> (z_q [N,h,w,e], indices [N,h,w], vq_loss)."""
    z = _encoder(params["encoder"], cfg, pixels)
    z = _conv(z, params["quant_conv_w"], params["quant_conv_b"])
    return quantize(params["codebook"], z, cfg.beta)


def decode(params: Params, cfg: MoVQGANConfig, z_q: jax.Array) -> jax.Array:
    z = _conv(z_q, params["post_quant_conv_w"], params["post_quant_conv_b"])
    return _decoder(params["decoder"], cfg, z, z_q)


def decode_code(params: Params, cfg: MoVQGANConfig, indices: jax.Array) -> jax.Array:
    """indices [N, T] or [N, h, w] -> pixels [N,H,W,C]."""
    if indices.ndim == 2:
        grid = cfg.token_grid
        indices = indices.reshape(indices.shape[0], grid, grid)
    z_q = params["codebook"].astype(jnp.float32)[indices]
    return decode(params, cfg, z_q)


# --------------------------------------------------------------------------
# HF checkpoint import (ai-forever/MoVQGAN torch layout — reference module
# tree at ``veomni/models/transformers/movqgan/modeling_movqgan.py:216,413``)
# --------------------------------------------------------------------------
def hf_to_params(model_dir: str, cfg: MoVQGANConfig) -> Params:
    """Map a torch MoVQGAN state dict onto the functional param tree.

    Torch convs are OIHW; ours are HWIO. Decoder norms are SpatialNorms
    (``norm_layer`` + ``conv_y``/``conv_b``); ``add_conv`` checkpoints are
    rejected (we don't carry the extra 3x3 on zq)."""
    import numpy as np

    from veomni_tpu.models.hf_io import LazyHFTensors

    src = LazyHFTensors(model_dir)
    if any(".norm1.conv.weight" in k for k in src.keys()):
        raise NotImplementedError("MoVQGAN add_conv checkpoints not supported")

    def t(name):
        return np.asarray(src.read(name))

    def conv(name):
        return jnp.asarray(t(f"{name}.weight").transpose(2, 3, 1, 0))

    def bias(name):
        return jnp.asarray(t(f"{name}.bias"))

    def norm(prefix, spatial):
        if spatial:
            return {
                "gn_w": jnp.asarray(t(f"{prefix}.norm_layer.weight")),
                "gn_b": jnp.asarray(t(f"{prefix}.norm_layer.bias")),
                "conv_y_w": conv(f"{prefix}.conv_y"),
                "conv_y_b": bias(f"{prefix}.conv_y"),
                "conv_b_w": conv(f"{prefix}.conv_b"),
                "conv_b_b": bias(f"{prefix}.conv_b"),
            }
        return {
            "gn_w": jnp.asarray(t(f"{prefix}.weight")),
            "gn_b": jnp.asarray(t(f"{prefix}.bias")),
        }

    def res_block(prefix, cin, cout, spatial):
        p = {
            "norm1": norm(f"{prefix}.norm1", spatial),
            "conv1_w": conv(f"{prefix}.conv1"),
            "conv1_b": bias(f"{prefix}.conv1"),
            "norm2": norm(f"{prefix}.norm2", spatial),
            "conv2_w": conv(f"{prefix}.conv2"),
            "conv2_b": bias(f"{prefix}.conv2"),
        }
        if cin != cout:
            p["shortcut_w"] = conv(f"{prefix}.nin_shortcut")
            p["shortcut_b"] = bias(f"{prefix}.nin_shortcut")
        return p

    def attn_block(prefix, spatial):
        p = {"norm": norm(f"{prefix}.norm", spatial)}
        for mine, theirs in (("q", "q"), ("k", "k"), ("v", "v"), ("proj", "proj_out")):
            p[f"{mine}_w"] = conv(f"{prefix}.{theirs}")
            p[f"{mine}_b"] = bias(f"{prefix}.{theirs}")
        return p

    levels = len(cfg.ch_mult)
    chs = [cfg.ch * m for m in cfg.ch_mult]

    enc: Params = {
        "conv_in_w": conv("encoder.conv_in"),
        "conv_in_b": bias("encoder.conv_in"),
        "down": [],
    }
    res = cfg.resolution
    cin = chs[0]
    for i in range(levels):
        level: Params = {"res": [], "attn": []}
        for j in range(cfg.num_res_blocks):
            level["res"].append(res_block(f"encoder.down.{i}.block.{j}", cin, chs[i], False))
            cin = chs[i]
            if res in cfg.attn_resolutions:
                level["attn"].append(attn_block(f"encoder.down.{i}.attn.{j}", False))
        if i != levels - 1:
            level["down_w"] = conv(f"encoder.down.{i}.downsample.conv")
            level["down_b"] = bias(f"encoder.down.{i}.downsample.conv")
            res //= 2
        enc["down"].append(level)
    enc["mid_res1"] = res_block("encoder.mid.block_1", cin, cin, False)
    enc["mid_attn"] = attn_block("encoder.mid.attn_1", False)
    enc["mid_res2"] = res_block("encoder.mid.block_2", cin, cin, False)
    enc["norm_out"] = norm("encoder.norm_out", False)
    enc["conv_out_w"] = conv("encoder.conv_out")
    enc["conv_out_b"] = bias("encoder.conv_out")

    dec: Params = {
        "conv_in_w": conv("decoder.conv_in"),
        "conv_in_b": bias("decoder.conv_in"),
        "mid_res1": res_block("decoder.mid.block_1", cin, cin, True),
        "mid_attn": attn_block("decoder.mid.attn_1", True),
        "mid_res2": res_block("decoder.mid.block_2", cin, cin, True),
        "up": [],
    }
    # torch ``up`` is prepended (up[i] = resolution level i); our list runs
    # deepest-first, so our up[j] reads torch up[levels-1-j]
    for i in reversed(range(levels)):
        level = {"res": [], "attn": []}
        for j in range(cfg.num_res_blocks + 1):
            level["res"].append(res_block(f"decoder.up.{i}.block.{j}", cin, chs[i], True))
            cin = chs[i]
            if res in cfg.attn_resolutions:
                level["attn"].append(attn_block(f"decoder.up.{i}.attn.{j}", True))
        if i != 0:
            level["up_w"] = conv(f"decoder.up.{i}.upsample.conv")
            level["up_b"] = bias(f"decoder.up.{i}.upsample.conv")
            res *= 2
        dec["up"].append(level)
    dec["norm_out"] = norm("decoder.norm_out", True)
    dec["conv_out_w"] = conv("decoder.conv_out")
    dec["conv_out_b"] = bias("decoder.conv_out")

    return {
        "encoder": enc,
        "decoder": dec,
        "codebook": jnp.asarray(t("quantize.embedding.weight")),
        "quant_conv_w": conv("quant_conv"),
        "quant_conv_b": bias("quant_conv"),
        "post_quant_conv_w": conv("post_quant_conv"),
        "post_quant_conv_b": bias("post_quant_conv"),
    }


def autoencode_loss(params: Params, cfg: MoVQGANConfig, pixels: jax.Array):
    """Tokenizer training objective: reconstruction MSE + VQ/commit loss
    (reference MoVQGANDecoder.forward)."""
    z_q, idx, vq_per = encode(params, cfg, pixels)
    vq_loss = vq_per.mean()
    rec = decode(params, cfg, z_q)
    rec_loss = ((rec.astype(jnp.float32) - pixels.astype(jnp.float32)) ** 2).mean()
    return rec_loss + vq_loss, {
        "rec_loss": rec_loss, "vq_loss": vq_loss, "indices": idx, "rec": rec
    }
