"""Janus(-Pro): unified understanding + generation composite.

Reference: ``veomni/models/transformers/janus/modeling_janus.py:1183-1320``
(Janus = timm/SigLIP ViT understanding tower + MlpProjector aligner + llama
LM + llamagen VQ-GAN generation tokenizer + gen_embed/gen_aligner/gen_head).
The two image pathways are decoupled: understanding images enter as ViT
features at input-image placeholder tokens; generated images are VQ-encoded
into codebook ids whose *separate* ``gen_embed`` table (not the VQ codebook)
feeds the LM stream, and a generation head predicts the next code.

TPU-first: fixed image slots (``pixel_values [B, max_images, H, W, C]`` /
``gen_pixels [B, max_gen, H, W, C]``) with ordered-slot merges, the shared
``build_gen_labels``/``gen_head_ce`` machinery from the omni composite, and
the MoVQGAN functional conv primitives for the (plain-GroupNorm) llamagen
VQ — the whole loss jits as one program with static shapes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from veomni_tpu import ops
from veomni_tpu.models import transformer
from veomni_tpu.models.config import TransformerConfig
from veomni_tpu.models.movqgan import (
    _attn_block,
    _attn_params,
    _conv,
    _conv_init,
    _group_norm,
    _norm_params,
    _res_block,
    _res_params,
    _swish,
)
from veomni_tpu.models.omni import build_gen_labels, gen_head_ce
from veomni_tpu.models.vlm import merge_image_features

Params = Dict[str, Any]


@dataclass
class JanusVisionConfig:
    """timm/SigLIP ViT surface (reference ``JanusVisionConfig`` defaults =
    SigLIP-L/16-384 with select_layer truncation already applied)."""

    width: int = 1024
    layers: int = 24
    heads: int = 16
    patch_size: int = 16
    image_size: int = 384
    mlp_ratio: float = 4.0
    class_token: bool = False
    qkv_bias: bool = True
    init_values: float = 0.0      # 0 = no LayerScale
    layer_norm_eps: float = 1e-6
    initializer_range: float = 0.02

    @property
    def grid(self) -> int:
        return self.image_size // self.patch_size

    @property
    def tokens_per_image(self) -> int:
        return self.grid ** 2

    @property
    def patch_dim(self) -> int:
        return 3 * self.patch_size ** 2

    @property
    def mlp_dim(self) -> int:
        return int(self.width * self.mlp_ratio)


@dataclass
class JanusGenVisionConfig:
    """llamagen VQ-16 surface (reference ``JanusGenVisionConfig``)."""

    codebook_size: int = 16384
    codebook_embed_dim: int = 8
    codebook_l2_norm: bool = True
    commit_loss_beta: float = 0.25
    ch: int = 128
    encoder_ch_mult: Tuple[int, ...] = (1, 1, 2, 2, 4)
    decoder_ch_mult: Tuple[int, ...] = (1, 1, 2, 2, 4)
    num_res_blocks: int = 2
    z_channels: int = 256
    image_size: int = 384
    num_groups: int = 32
    initializer_range: float = 0.02

    def __post_init__(self):
        self.encoder_ch_mult = tuple(self.encoder_ch_mult)
        self.decoder_ch_mult = tuple(self.decoder_ch_mult)

    @property
    def token_grid(self) -> int:
        return self.image_size // (2 ** (len(self.encoder_ch_mult) - 1))

    @property
    def tokens_per_image(self) -> int:
        return self.token_grid ** 2


@dataclass
class JanusConfig:
    text: TransformerConfig = field(default_factory=TransformerConfig)
    vision: JanusVisionConfig = field(default_factory=JanusVisionConfig)
    gen_vision: JanusGenVisionConfig = field(default_factory=JanusGenVisionConfig)
    aligner_depth: int = 2
    gen_aligner_depth: int = 2
    gen_head_embed: int = 2048
    image_token_id: int = 100581
    image_gen_token_id: int = 100594
    gen_loss_weight: float = 1.0
    freeze_vision: bool = False
    freeze_gen_vision: bool = True   # VQ tokenizer stays frozen (reference)
    max_images: int = 1
    max_gen_images: int = 1
    model_type: str = "janus"

    def __post_init__(self):
        if isinstance(self.text, dict):
            self.text = TransformerConfig(**self.text)
        if isinstance(self.vision, dict):
            self.vision = JanusVisionConfig(**self.vision)
        if isinstance(self.gen_vision, dict):
            self.gen_vision = JanusGenVisionConfig(**self.gen_vision)

    def __getattr__(self, name):  # trainer surface
        return getattr(object.__getattribute__(self, "text"), name)


# ---------------------------------------------------------------------------
# understanding tower (timm ViT)
# ---------------------------------------------------------------------------

def init_vision_params(rng: jax.Array, cfg: JanusVisionConfig, dtype=jnp.float32):
    s = cfg.initializer_range
    d, L, m = cfg.width, cfg.layers, cfg.mlp_dim
    keys = iter(jax.random.split(rng, 8))

    def init(shape):
        return (jax.random.normal(next(keys), shape, jnp.float32) * s).astype(dtype)

    n_tok = cfg.tokens_per_image + (1 if cfg.class_token else 0)
    p: Params = {
        "patch_embed": init((cfg.patch_dim, d)),
        "patch_embed_b": jnp.zeros((d,), dtype),
        "pos_embed": init((n_tok, d)),
        "blocks": {
            "norm1_w": jnp.ones((L, d), dtype), "norm1_b": jnp.zeros((L, d), dtype),
            "qkv_w": init((L, d, 3 * d)),
            "proj_w": init((L, d, d)), "proj_b": jnp.zeros((L, d), dtype),
            "norm2_w": jnp.ones((L, d), dtype), "norm2_b": jnp.zeros((L, d), dtype),
            "fc1_w": init((L, d, m)), "fc1_b": jnp.zeros((L, m), dtype),
            "fc2_w": init((L, m, d)), "fc2_b": jnp.zeros((L, d), dtype),
        },
        "norm_w": jnp.ones((d,), dtype),
        "norm_b": jnp.zeros((d,), dtype),
    }
    if cfg.qkv_bias:
        p["blocks"]["qkv_b"] = jnp.zeros((L, 3 * d), dtype)
    if cfg.init_values:
        p["blocks"]["ls1"] = jnp.full((L, d), cfg.init_values, dtype)
        p["blocks"]["ls2"] = jnp.full((L, d), cfg.init_values, dtype)
    if cfg.class_token:
        p["cls_token"] = jnp.zeros((1, d), dtype)
    return p


def _ln(x, w, b, eps):
    xf = x.astype(jnp.float32)
    xf = (xf - xf.mean(-1, keepdims=True)) * jax.lax.rsqrt(
        xf.var(-1, keepdims=True) + eps
    )
    return (xf * w + b).astype(x.dtype)


def _janus_vit_block(x, lp, cfg: JanusVisionConfig):
    n, t, d = x.shape
    hd = d // cfg.heads
    y = _ln(x, lp["norm1_w"], lp["norm1_b"], cfg.layer_norm_eps)
    qkv = jnp.dot(y, lp["qkv_w"])
    if "qkv_b" in lp:
        qkv = qkv + lp["qkv_b"]
    q, k, v = jnp.split(qkv.reshape(n, t, 3 * cfg.heads, hd), 3, axis=2)
    attn = ops.attention(q, k, v, causal=False).reshape(n, t, d)
    attn = jnp.dot(attn, lp["proj_w"]) + lp["proj_b"]
    if "ls1" in lp:
        attn = attn * lp["ls1"]
    x = x + attn
    y = _ln(x, lp["norm2_w"], lp["norm2_b"], cfg.layer_norm_eps)
    y = jax.nn.gelu(jnp.dot(y, lp["fc1_w"]) + lp["fc1_b"])
    y = jnp.dot(y, lp["fc2_w"]) + lp["fc2_b"]
    if "ls2" in lp:
        y = y * lp["ls2"]
    return x + y


def vision_forward(params, cfg: JanusVisionConfig, pixels: jax.Array) -> jax.Array:
    """pixels [N, H, W, 3] -> patch features [N, tokens_per_image, width]
    (cls token dropped — reference select_feature='patch'). Runs at sp=1
    like the other towers."""
    from veomni_tpu.parallel.parallel_state import (
        get_parallel_state_or_none, use_parallel_state,
    )

    ps = get_parallel_state_or_none()
    if ps is not None and ps.sp_enabled:
        with use_parallel_state(ps.without_sp()):
            return vision_forward(params, cfg, pixels)
    n = pixels.shape[0]
    p_sz, g = cfg.patch_size, cfg.grid
    x = pixels.reshape(n, g, p_sz, g, p_sz, 3).transpose(0, 1, 3, 2, 4, 5)
    x = x.reshape(n, g * g, cfg.patch_dim).astype(params["patch_embed"].dtype)
    x = jnp.dot(x, params["patch_embed"]) + params["patch_embed_b"]
    if "cls_token" in params:
        x = jnp.concatenate(
            [jnp.broadcast_to(params["cls_token"], (n, 1, x.shape[-1])), x], axis=1
        )
    x = x + params["pos_embed"]
    body = partial(_janus_vit_block, cfg=cfg)
    x, _ = jax.lax.scan(
        lambda c, lp: (jax.checkpoint(body)(c, lp), None), x, params["blocks"]
    )
    x = _ln(x, params["norm_w"], params["norm_b"], cfg.layer_norm_eps)
    return x[:, 1:] if "cls_token" in params else x


# ---------------------------------------------------------------------------
# generation tokenizer (llamagen VQ: plain-GroupNorm VQ-GAN, l2 codebook)
# ---------------------------------------------------------------------------

def init_gen_vision_params(rng: jax.Array, cfg: JanusGenVisionConfig) -> Params:
    s = cfg.initializer_range
    keys = iter(jax.random.split(rng, 512))
    levels = len(cfg.encoder_ch_mult)

    enc: Params = {
        "conv_in_w": _conv_init(next(keys), 3, 3, 3, cfg.ch, s),
        "conv_in_b": jnp.zeros((cfg.ch,), jnp.float32),
        "down": [],
    }
    in_mult = (1,) + cfg.encoder_ch_mult
    for i in range(levels):
        cin = cfg.ch * in_mult[i]
        cout = cfg.ch * cfg.encoder_ch_mult[i]
        level: Params = {"res": [], "attn": []}
        for _ in range(cfg.num_res_blocks):
            level["res"].append(_res_params(keys, cin, cout, s))
            cin = cout
            if i == levels - 1:  # llamagen: attention only at the deepest level
                level["attn"].append(_attn_params(keys, cin, s))
        if i != levels - 1:
            level["down_w"] = _conv_init(next(keys), 3, 3, cin, cin, s)
            level["down_b"] = jnp.zeros((cin,), jnp.float32)
        enc["down"].append(level)
    cin = cfg.ch * cfg.encoder_ch_mult[-1]
    enc["mid_res1"] = _res_params(keys, cin, cin, s)
    enc["mid_attn"] = _attn_params(keys, cin, s)
    enc["mid_res2"] = _res_params(keys, cin, cin, s)
    enc["norm_out"] = _norm_params(cin, False)
    enc["conv_out_w"] = _conv_init(next(keys), 3, 3, cin, cfg.z_channels, s)
    enc["conv_out_b"] = jnp.zeros((cfg.z_channels,), jnp.float32)

    dec: Params = {
        "conv_in_w": _conv_init(next(keys), 3, 3, cfg.z_channels, cin, s),
        "conv_in_b": jnp.zeros((cin,), jnp.float32),
        "mid_res1": _res_params(keys, cin, cin, s),
        "mid_attn": _attn_params(keys, cin, s),
        "mid_res2": _res_params(keys, cin, cin, s),
        "up": [],
    }
    for j, i in enumerate(reversed(range(levels))):
        cout = cfg.ch * cfg.decoder_ch_mult[i]
        level = {"res": [], "attn": []}
        for _ in range(cfg.num_res_blocks + 1):
            level["res"].append(_res_params(keys, cin, cout, s))
            cin = cout
            if i == levels - 1:
                level["attn"].append(_attn_params(keys, cin, s))
        if i != 0:
            level["up_w"] = _conv_init(next(keys), 3, 3, cin, cin, s)
            level["up_b"] = jnp.zeros((cin,), jnp.float32)
        dec["up"].append(level)
    dec["norm_out"] = _norm_params(cin, False)
    dec["conv_out_w"] = _conv_init(next(keys), 3, 3, cin, 3, s)
    dec["conv_out_b"] = jnp.zeros((3,), jnp.float32)

    e = cfg.codebook_embed_dim
    return {
        "encoder": enc,
        "decoder": dec,
        "codebook": jax.random.uniform(
            next(keys), (cfg.codebook_size, e), jnp.float32,
            -1.0 / cfg.codebook_size, 1.0 / cfg.codebook_size,
        ),
        "quant_conv_w": _conv_init(next(keys), 1, 1, cfg.z_channels, e, s),
        "quant_conv_b": jnp.zeros((e,), jnp.float32),
        "post_quant_conv_w": _conv_init(next(keys), 1, 1, e, cfg.z_channels, s),
        "post_quant_conv_b": jnp.zeros((cfg.z_channels,), jnp.float32),
    }


def _l2norm(x, eps=1e-12):
    return x * jax.lax.rsqrt(jnp.maximum((x * x).sum(-1, keepdims=True), eps))


def gen_vision_encode(params: Params, cfg: JanusGenVisionConfig, pixels: jax.Array):
    """pixels [N,H,W,3] -> (z_q [N,h,w,e] straight-through, indices [N,h,w],
    per-image vq loss [N]). llamagen quantizer: l2-normalized z AND codebook."""
    g = cfg.num_groups
    p = params["encoder"]
    h = _conv(pixels, p["conv_in_w"], p["conv_in_b"])
    for level in p["down"]:
        attn_iter = iter(level["attn"])
        for rp in level["res"]:
            h = _res_block(h, rp, g)
            if level["attn"]:
                h = _attn_block(h, next(attn_iter), g)
        if "down_w" in level:
            h = _conv(
                jnp.pad(h, ((0, 0), (0, 1), (0, 1), (0, 0))),
                level["down_w"], level["down_b"], stride=2, padding="VALID",
            )
    h = _res_block(h, p["mid_res1"], g)
    h = _attn_block(h, p["mid_attn"], g)
    h = _res_block(h, p["mid_res2"], g)
    h = _swish(_group_norm(h, p["norm_out"]["gn_w"], p["norm_out"]["gn_b"], g))
    z = _conv(h, p["conv_out_w"], p["conv_out_b"])
    z = _conv(z, params["quant_conv_w"], params["quant_conv_b"])

    zf = z.astype(jnp.float32)
    cb = params["codebook"].astype(jnp.float32)
    if cfg.codebook_l2_norm:
        zf = _l2norm(zf)
        cb = _l2norm(cb)
    d = (
        (zf * zf).sum(-1, keepdims=True)
        - 2.0 * jnp.einsum("nhwe,ke->nhwk", zf, cb)
        + (cb * cb).sum(-1)[None, None, None, :]
    )
    idx = jnp.argmin(d, axis=-1)
    e = cb[idx]
    vq = ((jax.lax.stop_gradient(zf) - e) ** 2).mean((1, 2, 3)) + \
        cfg.commit_loss_beta * ((zf - jax.lax.stop_gradient(e)) ** 2).mean((1, 2, 3))
    z_q = zf + jax.lax.stop_gradient(e - zf)
    return z_q.astype(z.dtype), idx, vq


def gen_vision_decode(params: Params, cfg: JanusGenVisionConfig, z_q: jax.Array):
    g = cfg.num_groups
    z = _conv(z_q, params["post_quant_conv_w"], params["post_quant_conv_b"])
    p = params["decoder"]
    h = _conv(z, p["conv_in_w"], p["conv_in_b"])
    h = _res_block(h, p["mid_res1"], g)
    h = _attn_block(h, p["mid_attn"], g)
    h = _res_block(h, p["mid_res2"], g)
    for level in p["up"]:
        attn_iter = iter(level["attn"])
        for rp in level["res"]:
            h = _res_block(h, rp, g)
            if level["attn"]:
                h = _attn_block(h, next(attn_iter), g)
        if "up_w" in level:
            n, hh, ww, c = h.shape
            h = jax.image.resize(h, (n, hh * 2, ww * 2, c), "nearest")
            h = _conv(h, level["up_w"], level["up_b"])
    h = _swish(_group_norm(h, p["norm_out"]["gn_w"], p["norm_out"]["gn_b"], g))
    return _conv(h, p["conv_out_w"], p["conv_out_b"])


def decode_code(params: Params, cfg: JanusGenVisionConfig, indices: jax.Array):
    """indices [N, T] or [N, h, w] -> pixels (codebook lookup is l2-normed
    like the reference get_codebook_entry)."""
    if indices.ndim == 2:
        grid = cfg.token_grid
        indices = indices.reshape(indices.shape[0], grid, grid)
    cb = params["codebook"].astype(jnp.float32)
    if cfg.codebook_l2_norm:
        cb = _l2norm(cb)
    return gen_vision_decode(params, cfg, cb[indices])


# ---------------------------------------------------------------------------
# composite params / loss
# ---------------------------------------------------------------------------

def _mlp_proj_params(keys, in_dim, n_embed, depth, s, dtype):
    def init(shape):
        return (jax.random.normal(next(keys), shape, jnp.float32) * s).astype(dtype)

    layers = [{"w": init((in_dim, n_embed)), "b": jnp.zeros((n_embed,), dtype)}]
    for _ in range(1, depth):
        layers.append({"w": init((n_embed, n_embed)), "b": jnp.zeros((n_embed,), dtype)})
    return layers


def _mlp_proj(x, layers):
    x = jnp.dot(x, layers[0]["w"].astype(x.dtype)) + layers[0]["b"].astype(x.dtype)
    for lp in layers[1:]:
        x = jax.nn.gelu(x)
        x = jnp.dot(x, lp["w"].astype(x.dtype)) + lp["b"].astype(x.dtype)
    return x


def init_params(rng: jax.Array, cfg: JanusConfig) -> Params:
    r1, r2, r3, r4, r5, r6, r7 = jax.random.split(rng, 7)
    pd = cfg.text.param_dtype
    s = cfg.text.initializer_range
    h = cfg.text.hidden_size
    e = cfg.gen_vision.codebook_embed_dim
    keys_a = iter(jax.random.split(r4, 8))
    keys_g = iter(jax.random.split(r5, 8))

    def init(key, shape):
        return (jax.random.normal(key, shape, jnp.float32) * s).astype(pd)

    return {
        "language_model": transformer.init_params(r1, cfg.text),
        "vision_tower": init_vision_params(r2, cfg.vision, pd),
        "gen_vision": init_gen_vision_params(r3, cfg.gen_vision),
        "aligner": _mlp_proj_params(keys_a, cfg.vision.width, h,
                                    cfg.aligner_depth, s, pd),
        "gen_aligner": _mlp_proj_params(keys_g, e, h, cfg.gen_aligner_depth, s, pd),
        "gen_embed": init(r6, (cfg.gen_vision.codebook_size, e)),
        "gen_head": {
            "fc1": init(jax.random.split(r7)[0], (h, cfg.gen_head_embed)),
            "fc1_b": jnp.zeros((cfg.gen_head_embed,), pd),
            "fc2": init(jax.random.split(r7)[1],
                        (cfg.gen_head_embed, cfg.gen_vision.codebook_size)),
            "fc2_b": jnp.zeros((cfg.gen_vision.codebook_size,), pd),
        },
    }


def abstract_params(cfg: JanusConfig):
    return jax.eval_shape(lambda: init_params(jax.random.PRNGKey(0), cfg))


def loss_fn(params, cfg: JanusConfig, batch) -> Tuple[jax.Array, Dict]:
    """batch: input_ids/labels/position_ids/segment_ids [B,S];
    pixel_values [B, max_images, H, W, 3] + image_mask [B, max_images]
    (understanding); gen_pixels [B, max_gen, h, w, 3] + gen_image_mask
    (generation targets, [-1, 1])."""
    tcfg = cfg.text
    lm = params["language_model"]
    input_ids = batch["input_ids"]
    embeds = lm["embed_tokens"].astype(tcfg.dtype)[input_ids]

    if "pixel_values" in batch:
        vp = params["vision_tower"]
        if cfg.freeze_vision:
            vp = jax.lax.stop_gradient(vp)
        px = batch["pixel_values"]
        bi, mi = px.shape[:2]
        feats = vision_forward(
            jax.tree.map(lambda t: t.astype(tcfg.dtype), vp), cfg.vision,
            px.reshape(bi * mi, *px.shape[2:]),
        )
        feats = _mlp_proj(feats, params["aligner"])
        feats = feats.reshape(bi, mi, *feats.shape[1:])
        embeds = merge_image_features(
            embeds, input_ids, feats, batch["image_mask"], cfg.image_token_id
        )

    gen_labels = None
    if "gen_pixels" in batch:
        gvp = params["gen_vision"]
        if cfg.freeze_gen_vision:
            gvp = jax.lax.stop_gradient(gvp)
        gp = batch["gen_pixels"]
        bi, mg = gp.shape[:2]
        t_gen = cfg.gen_vision.tokens_per_image
        _, idx, _ = gen_vision_encode(gvp, cfg.gen_vision,
                                      gp.reshape(bi * mg, *gp.shape[2:]))
        idx = idx.reshape(bi, mg, t_gen)
        # the LM-side code embedding is its own table (NOT the VQ codebook)
        cb_embeds = params["gen_embed"].astype(tcfg.dtype)[idx]
        feats = _mlp_proj(cb_embeds, params["gen_aligner"])
        gen_mask = batch["gen_image_mask"]
        embeds = merge_image_features(
            embeds, input_ids, feats, gen_mask, cfg.image_gen_token_id
        )
        gen_labels = build_gen_labels(
            input_ids, idx.reshape(bi, mg * t_gen), gen_mask,
            cfg.image_gen_token_id, t_gen, batch.get("segment_ids"),
        )

    hidden, moe_aux, moe_dropped = transformer.forward_hidden(
        lm, tcfg, input_ids, batch["position_ids"],
        batch.get("segment_ids"), inputs_embeds=embeds,
    )
    total, metrics = transformer.head_loss(
        lm, tcfg, hidden, batch["labels"], moe_aux, moe_dropped
    )
    if gen_labels is not None:
        gh = jax.tree.map(lambda p: p.astype(tcfg.dtype), params["gen_head"])
        gen_sum, gen_n = gen_head_ce(hidden, gh, gen_labels)
        total = total + cfg.gen_loss_weight * gen_sum
        metrics["ntokens"] = metrics["ntokens"] + gen_n
        metrics["gen_loss_sum"] = gen_sum
        metrics["gen_ntokens"] = gen_n
    return total, metrics


# ---------------------------------------------------------------------------
# HF checkpoint io (deepseek-ai/Janus layout via the reference module tree)
# ---------------------------------------------------------------------------

_VIT_BLOCK_MAP = [
    ("norm1_w", "norm1.weight", False), ("norm1_b", "norm1.bias", False),
    ("qkv_w", "attn.qkv.weight", True), ("qkv_b", "attn.qkv.bias", False),
    ("proj_w", "attn.proj.weight", True), ("proj_b", "attn.proj.bias", False),
    ("norm2_w", "norm2.weight", False), ("norm2_b", "norm2.bias", False),
    ("fc1_w", "mlp.fc1.weight", True), ("fc1_b", "mlp.fc1.bias", False),
    ("fc2_w", "mlp.fc2.weight", True), ("fc2_b", "mlp.fc2.bias", False),
    ("ls1", "ls1.gamma", False), ("ls2", "ls2.gamma", False),
]


def _vq_tree_maps(cfg: JanusGenVisionConfig):
    """[(our dotted path, hf name, kind)] for the whole VQ tree; kind in
    conv|tensor. Mirrors init_gen_vision_params' structural loops."""
    out = []
    levels = len(cfg.encoder_ch_mult)

    def norm(ours, hf):
        out.append((f"{ours}.gn_w", f"{hf}.weight", "tensor"))
        out.append((f"{ours}.gn_b", f"{hf}.bias", "tensor"))

    def conv(ours, hf):
        out.append((f"{ours}_w", f"{hf}.weight", "conv"))
        out.append((f"{ours}_b", f"{hf}.bias", "tensor"))

    def res(ours, hf, cin, cout):
        norm(f"{ours}.norm1", f"{hf}.norm1")
        conv(f"{ours}.conv1", f"{hf}.conv1")
        norm(f"{ours}.norm2", f"{hf}.norm2")
        conv(f"{ours}.conv2", f"{hf}.conv2")
        if cin != cout:
            conv(f"{ours}.shortcut", f"{hf}.nin_shortcut")

    def attn(ours, hf):
        norm(f"{ours}.norm", f"{hf}.norm")
        for mine, theirs in (("q", "q"), ("k", "k"), ("v", "v"), ("proj", "proj_out")):
            conv(f"{ours}.{mine}", f"{hf}.{theirs}")

    # encoder
    conv("encoder.conv_in", "gen_vision_model.encoder.conv_in")
    in_mult = (1,) + cfg.encoder_ch_mult
    for i in range(levels):
        cin = cfg.ch * in_mult[i]
        cout = cfg.ch * cfg.encoder_ch_mult[i]
        for j in range(cfg.num_res_blocks):
            res(f"encoder.down.{i}.res.{j}",
                f"gen_vision_model.encoder.conv_blocks.{i}.res.{j}", cin, cout)
            cin = cout
            if i == levels - 1:
                attn(f"encoder.down.{i}.attn.{j}",
                     f"gen_vision_model.encoder.conv_blocks.{i}.attn.{j}")
        if i != levels - 1:
            conv(f"encoder.down.{i}.down",
                 f"gen_vision_model.encoder.conv_blocks.{i}.downsample.conv")
    top = cfg.ch * cfg.encoder_ch_mult[-1]
    res("encoder.mid_res1", "gen_vision_model.encoder.mid.0", top, top)
    attn("encoder.mid_attn", "gen_vision_model.encoder.mid.1")
    res("encoder.mid_res2", "gen_vision_model.encoder.mid.2", top, top)
    norm("encoder.norm_out", "gen_vision_model.encoder.norm_out")
    conv("encoder.conv_out", "gen_vision_model.encoder.conv_out")

    # decoder (our up[j] reads reference conv_blocks[j]; both run deep->shallow)
    conv("decoder.conv_in", "gen_vision_model.decoder.conv_in")
    res("decoder.mid_res1", "gen_vision_model.decoder.mid.0", top, top)
    attn("decoder.mid_attn", "gen_vision_model.decoder.mid.1")
    res("decoder.mid_res2", "gen_vision_model.decoder.mid.2", top, top)
    cin = top
    for j, i in enumerate(reversed(range(levels))):
        cout = cfg.ch * cfg.decoder_ch_mult[i]
        for k in range(cfg.num_res_blocks + 1):
            res(f"decoder.up.{j}.res.{k}",
                f"gen_vision_model.decoder.conv_blocks.{j}.res.{k}", cin, cout)
            cin = cout
            if i == levels - 1:
                attn(f"decoder.up.{j}.attn.{k}",
                     f"gen_vision_model.decoder.conv_blocks.{j}.attn.{k}")
        if i != 0:
            conv(f"decoder.up.{j}.up",
                 f"gen_vision_model.decoder.conv_blocks.{j}.upsample.conv")
    norm("decoder.norm_out", "gen_vision_model.decoder.norm_out")
    conv("decoder.conv_out", "gen_vision_model.decoder.conv_out")

    out.append(("codebook", "gen_vision_model.quantize.embedding.weight", "tensor"))
    conv("quant_conv", "gen_vision_model.quant_conv")
    conv("post_quant_conv", "gen_vision_model.post_quant_conv")
    return out


def _vq_get(tree, dotted):
    cur = tree
    for part in dotted.split("."):
        # our res params use "shortcut_w"/"conv1_w" flat names inside dicts
        if isinstance(cur, list):
            cur = cur[int(part)]
        elif part in cur:
            cur = cur[part]
        else:
            return None
    return cur


def _vq_set(tree, dotted, value):
    parts = dotted.split(".")
    cur = tree
    for part in parts[:-1]:
        cur = cur[int(part)] if isinstance(cur, list) else cur[part]
    if isinstance(cur, list):
        cur[int(parts[-1])] = value
    else:
        cur[parts[-1]] = value


def hf_to_params(model_dir: str, cfg: JanusConfig, target_shardings=None):
    from veomni_tpu.models import hf_io
    from veomni_tpu.models.qwen2_5_vl import _text_key_map

    pd = cfg.text.param_dtype
    def text_key_map(k):
        if not k.startswith(("language_model.", "model.", "lm_head")):
            return None
        return _text_key_map(k.replace("language_model.lm_head.", "lm_head.", 1))

    language_model = hf_io.hf_to_params(
        model_dir, cfg.text,
        target_shardings=target_shardings["language_model"] if target_shardings else None,
        key_map=text_key_map,
    )
    lazy = hf_io.LazyHFTensors(model_dir)

    def read(name):
        return np.asarray(lazy.read(name))

    def t2(name):
        return jnp.asarray(np.ascontiguousarray(read(name).T), pd)

    def t0(name, dtype=pd):
        return jnp.asarray(read(name), dtype)

    vcfg = cfg.vision
    pfx = "vision_model.vision_tower"
    blocks: Params = {}
    for ours, suffix, tr in _VIT_BLOCK_MAP:
        if f"{pfx}.blocks.0.{suffix}" not in lazy and ours in ("ls1", "ls2", "qkv_b"):
            continue
        blocks[ours] = jnp.asarray(np.stack([
            read(f"{pfx}.blocks.{i}.{suffix}").T if tr
            else read(f"{pfx}.blocks.{i}.{suffix}")
            for i in range(vcfg.layers)
        ]), pd)
    vision_tower: Params = {
        "patch_embed": jnp.asarray(np.ascontiguousarray(
            read(f"{pfx}.patch_embed.proj.weight")
            .transpose(2, 3, 1, 0).reshape(-1, vcfg.width)), pd),
        "patch_embed_b": t0(f"{pfx}.patch_embed.proj.bias"),
        "pos_embed": t0(f"{pfx}.pos_embed")[0],
        "blocks": blocks,
        "norm_w": t0(f"{pfx}.norm.weight"),
        "norm_b": t0(f"{pfx}.norm.bias"),
    }
    if f"{pfx}.cls_token" in lazy:
        vision_tower["cls_token"] = t0(f"{pfx}.cls_token")[0]

    gen_vision = init_gen_vision_params(jax.random.PRNGKey(0), cfg.gen_vision)
    for ours, hf, kind in _vq_tree_maps(cfg.gen_vision):
        arr = read(hf)
        if kind == "conv":
            arr = np.ascontiguousarray(arr.transpose(2, 3, 1, 0))
        _vq_set(gen_vision, ours, jnp.asarray(arr, jnp.float32))

    def proj(prefix, depth):
        layers = []
        idxs = [0] + [2 * i for i in range(1, depth)]
        for li in idxs:
            layers.append({"w": t2(f"{prefix}.layers.{li}.weight"),
                           "b": t0(f"{prefix}.layers.{li}.bias")})
        return layers

    return {
        "language_model": language_model,
        "vision_tower": vision_tower,
        "gen_vision": gen_vision,
        "aligner": proj("aligner", cfg.aligner_depth),
        "gen_aligner": proj("gen_aligner", cfg.gen_aligner_depth),
        "gen_embed": t0("gen_embed.weight"),
        "gen_head": {
            "fc1": t2("gen_head.output_mlp_projector.weight"),
            "fc1_b": t0("gen_head.output_mlp_projector.bias"),
            "fc2": t2("gen_head.vision_head.weight"),
            "fc2_b": t0("gen_head.vision_head.bias"),
        },
    }


def params_to_hf(params, cfg: JanusConfig) -> Dict[str, np.ndarray]:
    from veomni_tpu.models import hf_io

    host = hf_io.gather_to_host(params)
    out: Dict[str, np.ndarray] = {}
    text = hf_io.params_to_hf(host["language_model"], cfg.text)
    for k, v in text.items():
        if k == "lm_head.weight":
            out["language_model.lm_head.weight"] = v
        else:
            out[f"language_model.{k}"] = v

    vcfg = cfg.vision
    pfx = "vision_model.vision_tower"
    vt = host["vision_tower"]
    out[f"{pfx}.patch_embed.proj.weight"] = np.ascontiguousarray(
        vt["patch_embed"].reshape(vcfg.patch_size, vcfg.patch_size, 3, vcfg.width)
        .transpose(3, 2, 0, 1)
    )
    out[f"{pfx}.patch_embed.proj.bias"] = vt["patch_embed_b"]
    out[f"{pfx}.pos_embed"] = vt["pos_embed"][None]
    out[f"{pfx}.norm.weight"] = vt["norm_w"]
    out[f"{pfx}.norm.bias"] = vt["norm_b"]
    if "cls_token" in vt:
        out[f"{pfx}.cls_token"] = vt["cls_token"][None]
    for ours, suffix, tr in _VIT_BLOCK_MAP:
        if ours not in vt["blocks"]:
            continue
        for i in range(vcfg.layers):
            x = vt["blocks"][ours][i]
            out[f"{pfx}.blocks.{i}.{suffix}"] = np.ascontiguousarray(
                x.T if tr else x
            )

    for ours, hf, kind in _vq_tree_maps(cfg.gen_vision):
        arr = np.asarray(_vq_get(host["gen_vision"], ours))
        if kind == "conv":
            arr = np.ascontiguousarray(arr.transpose(3, 2, 0, 1))
        out[hf] = arr

    for name, depth in (("aligner", cfg.aligner_depth),
                        ("gen_aligner", cfg.gen_aligner_depth)):
        idxs = [0] + [2 * i for i in range(1, depth)]
        for layer, li in zip(host[name], idxs):
            out[f"{name}.layers.{li}.weight"] = np.ascontiguousarray(layer["w"].T)
            out[f"{name}.layers.{li}.bias"] = layer["b"]
    out["gen_embed.weight"] = host["gen_embed"]
    out["gen_head.output_mlp_projector.weight"] = np.ascontiguousarray(
        host["gen_head"]["fc1"].T)
    out["gen_head.output_mlp_projector.bias"] = host["gen_head"]["fc1_b"]
    out["gen_head.vision_head.weight"] = np.ascontiguousarray(
        host["gen_head"]["fc2"].T)
    out["gen_head.vision_head.bias"] = host["gen_head"]["fc2_b"]
    return out


def save_hf_checkpoint(params, cfg: JanusConfig, out_dir: str) -> None:
    import json
    import os

    from safetensors.numpy import save_file

    tensors = params_to_hf(params, cfg)
    if jax.process_index() != 0:
        return
    os.makedirs(out_dir, exist_ok=True)
    save_file({k: np.ascontiguousarray(v) for k, v in tensors.items()},
              os.path.join(out_dir, "model.safetensors"))
    gv = cfg.gen_vision
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump({
            "model_type": "janus",
            "architectures": ["Janus"],
            "language_config": {**cfg.text.to_hf_config(), "model_type": "llama"},
            "vision_config": {
                "width": cfg.vision.width, "layers": cfg.vision.layers,
                "heads": cfg.vision.heads, "patch_size": cfg.vision.patch_size,
                "image_size": cfg.vision.image_size,
                "mlp_ratio": cfg.vision.mlp_ratio,
                "class_token": cfg.vision.class_token,
            },
            "gen_vision_config": {
                "codebook_size": gv.codebook_size,
                "codebook_embed_dim": gv.codebook_embed_dim,
                "codebook_l2_norm": gv.codebook_l2_norm,
                "encoder_ch_mult": list(gv.encoder_ch_mult),
                "decoder_ch_mult": list(gv.decoder_ch_mult),
                "z_channels": gv.z_channels,
                "image_size": gv.image_size,
                "ch": gv.ch,
                "num_res_blocks": gv.num_res_blocks,
            },
            "aligner_depth": cfg.aligner_depth,
            "gen_aligner_depth": cfg.gen_aligner_depth,
            "gen_head_embed": cfg.gen_head_embed,
            "image_token_id": cfg.image_token_id,
            "image_gen_token_id": cfg.image_gen_token_id,
        }, f, indent=2)


def config_from_hf(hf: Dict[str, Any], **overrides) -> JanusConfig:
    text = TransformerConfig.from_hf_config(
        {**(hf.get("language_config") or {}), "model_type": "llama"}
    )
    vis_fields = set(JanusVisionConfig.__dataclass_fields__)
    gen_fields = set(JanusGenVisionConfig.__dataclass_fields__)
    kw: Dict[str, Any] = {
        "text": text,
        "vision": JanusVisionConfig(**{
            k: v for k, v in (hf.get("vision_config") or {}).items()
            if k in vis_fields
        }),
        "gen_vision": JanusGenVisionConfig(**{
            k: v for k, v in (hf.get("gen_vision_config") or {}).items()
            if k in gen_fields
        }),
    }
    for key in ("aligner_depth", "gen_aligner_depth", "gen_head_embed",
                "image_token_id", "image_gen_token_id"):
        if key in hf:
            kw[key] = hf[key]
    kw.update(overrides)
    return JanusConfig(**kw)
