"""Wan 2.x T2V video DiT (real architecture).

Reference: ``veomni/models/diffusers/wan_t2v/`` (wraps diffusers
``WanTransformer3DModel`` and patches its forward for Ulysses SP —
``modeling_wan_transformer.py:278-366`` documents the exact model flow this
module re-implements TPU-first):

* 3D patch embedding (Conv3d ``patch_size``=(1,2,2) — a linear over
  flattened patches) into ``heads * head_dim``;
* 3-axis rotary embedding over (frame, height, width) patch positions with
  the head_dim split ``[d - 2*(d//3), d//3, d//3]`` and pairwise
  (complex-multiplication) rotation;
* condition embedder: sinusoidal timesteps -> 2-layer SiLU MLP (``temb``) +
  a SiLU projection to 6 adaLN streams; text states through a 2-layer
  gelu-tanh projection into the model width;
* blocks: affine-free LayerNorm + 6-way adaLN (per-block
  ``scale_shift_table`` added to the projected timestep), self-attention
  with qk RMSNorm across heads, cross-attention to the text states
  (optionally LayerNorm'd input), gelu-tanh FFN;
* output head: affine-free LayerNorm with a global 2-way scale/shift
  table, linear projection, unpatchify.

Training objective (reference ``WanTransformer3DModel.forward``): MSE
against a precomputed flow-matching target, per-sample mean then batch mean.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from veomni_tpu import ops
from veomni_tpu.models.diffusion_common import (
    ln_noaffine as _ln_noaffine,
    rms_norm as _rms,
    timestep_embedding as _ts_embed,
    tree_get as _get,
    tree_set as _set,
)


@dataclass
class WanConfig:
    """``WanTransformer3DModelConfig`` surface (defaults = Wan2.1-T2V-14B)."""

    patch_size: Tuple[int, int, int] = (1, 2, 2)
    num_attention_heads: int = 40
    attention_head_dim: int = 128
    in_channels: int = 16
    out_channels: int = 16
    text_dim: int = 4096
    freq_dim: int = 256
    ffn_dim: int = 13824
    num_layers: int = 40
    cross_attn_norm: bool = True
    eps: float = 1e-6
    rope_max_seq_len: int = 1024
    rope_theta: float = 10000.0
    initializer_range: float = 0.02
    model_type: str = "wan_t2v"
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True

    def __post_init__(self):
        self.patch_size = tuple(self.patch_size)
        for f in ("dtype", "param_dtype"):
            v = getattr(self, f)
            if isinstance(v, str):
                setattr(self, f, getattr(jnp, v))

    @property
    def inner_dim(self) -> int:
        return self.num_attention_heads * self.attention_head_dim

    @property
    def patch_dim(self) -> int:
        return self.in_channels * int(np.prod(self.patch_size))


def init_params(rng: jax.Array, cfg: WanConfig) -> Dict[str, Any]:
    s = cfg.initializer_range
    d, fd, L = cfg.inner_dim, cfg.ffn_dim, cfg.num_layers
    keys = iter(jax.random.split(rng, 24))
    pd = cfg.param_dtype

    def init(key, shape, scale=s):
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(pd)

    def attn(key, kv_dim):
        ks = jax.random.split(key, 4)
        return {
            "q_w": init(ks[0], (L, d, d)), "q_b": jnp.zeros((L, d), pd),
            "k_w": init(ks[1], (L, kv_dim, d)), "k_b": jnp.zeros((L, d), pd),
            "v_w": init(ks[2], (L, kv_dim, d)), "v_b": jnp.zeros((L, d), pd),
            "o_w": init(ks[3], (L, d, d)), "o_b": jnp.zeros((L, d), pd),
            "norm_q": jnp.ones((L, d), pd),
            "norm_k": jnp.ones((L, d), pd),
        }

    return {
        "patch_embedding_w": init(next(keys), (cfg.patch_dim, d)),
        "patch_embedding_b": jnp.zeros((d,), pd),
        "time_embedder": {
            "fc1_w": init(next(keys), (cfg.freq_dim, d)),
            "fc1_b": jnp.zeros((d,), pd),
            "fc2_w": init(next(keys), (d, d)),
            "fc2_b": jnp.zeros((d,), pd),
        },
        "time_proj_w": init(next(keys), (d, 6 * d)),
        "time_proj_b": jnp.zeros((6 * d,), pd),
        "text_embedder": {
            "fc1_w": init(next(keys), (cfg.text_dim, d)),
            "fc1_b": jnp.zeros((d,), pd),
            "fc2_w": init(next(keys), (d, d)),
            "fc2_b": jnp.zeros((d,), pd),
        },
        "blocks": {
            "attn1": attn(next(keys), d),
            "attn2": attn(next(keys), d),
            "norm2_w": jnp.ones((L, d), pd),
            "norm2_b": jnp.zeros((L, d), pd),
            "ffn_fc1_w": init(next(keys), (L, d, fd)),
            "ffn_fc1_b": jnp.zeros((L, fd), pd),
            "ffn_fc2_w": init(next(keys), (L, fd, d)),
            "ffn_fc2_b": jnp.zeros((L, d), pd),
            "scale_shift_table": init(next(keys), (L, 6, d), scale=d ** -0.5),
        },
        "scale_shift_table": init(next(keys), (2, d), scale=d ** -0.5),
        "proj_out_w": init(next(keys), (d, cfg.patch_dim // cfg.in_channels * cfg.out_channels)),
        "proj_out_b": jnp.zeros(
            (cfg.patch_dim // cfg.in_channels * cfg.out_channels,), pd),
    }


def abstract_params(cfg: WanConfig):
    return jax.eval_shape(lambda: init_params(jax.random.PRNGKey(0), cfg))


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def rope_3d(cfg: WanConfig, f: int, h: int, w: int):
    """(cos, sin) [1, f*h*w, head_dim] — pairwise-interleaved layout; the
    head_dim splits [d-2*(d//3), d//3, d//3] over (frame, height, width)."""
    d = cfg.attention_head_dim
    dh = dw = 2 * (d // 6)  # per-axis rotary dims (pairs)
    dt = d - dh - dw

    def axis(n, dim):
        inv = 1.0 / (cfg.rope_theta ** (np.arange(0, dim, 2) / dim))
        ang = np.arange(n)[:, None] * inv[None, :]
        return np.repeat(ang, 2, axis=1)  # pairwise layout

    at = axis(f, dt)[:, None, None, :]
    ah = axis(h, dh)[None, :, None, :]
    aw = axis(w, dw)[None, None, :, :]
    ang = np.concatenate([
        np.broadcast_to(at, (f, h, w, dt)),
        np.broadcast_to(ah, (f, h, w, dh)),
        np.broadcast_to(aw, (f, h, w, dw)),
    ], axis=-1).reshape(1, f * h * w, d)
    return jnp.cos(ang).astype(jnp.float32), jnp.sin(ang).astype(jnp.float32)


def _attention(x, ctx, lp, cfg: WanConfig, cos=None, sin=None):
    """x [B,N,D]; ctx [B,M,D] (self-attn when ctx is x)."""
    b, n, d = x.shape
    nh, hd = cfg.num_attention_heads, cfg.attention_head_dim
    q = jnp.dot(x, lp["q_w"]) + lp["q_b"]
    k = jnp.dot(ctx, lp["k_w"]) + lp["k_b"]
    v = jnp.dot(ctx, lp["v_w"]) + lp["v_b"]
    q = _rms(q, lp["norm_q"], cfg.eps)
    k = _rms(k, lp["norm_k"], cfg.eps)
    q = q.reshape(b, n, nh, hd)
    k = k.reshape(b, ctx.shape[1], nh, hd)
    v = v.reshape(b, ctx.shape[1], nh, hd)
    if cos is not None:
        q, k = ops.apply_rotary(q, k, cos, sin, interleaved=True)
    o = ops.attention(q, k, v, causal=False)
    return jnp.dot(o.reshape(b, n, d), lp["o_w"]) + lp["o_b"]


def _block(x, lp, cfg: WanConfig, text, temb6, cos, sin):
    # temb6 [B, 6, D] f32; per-block table added in f32
    mod = (lp["scale_shift_table"].astype(jnp.float32)[None] + temb6)
    sh_msa, sc_msa, g_msa, sh_c, sc_c, g_c = jnp.split(mod, 6, axis=1)
    xn = (_ln_noaffine(x, cfg.eps) * (1 + sc_msa) + sh_msa).astype(x.dtype)
    attn = _attention(xn, xn, lp["attn1"], cfg, cos, sin)
    x = (x.astype(jnp.float32) + attn.astype(jnp.float32) * g_msa).astype(x.dtype)

    if cfg.cross_attn_norm:
        xn = (_ln_noaffine(x, cfg.eps) * lp["norm2_w"] + lp["norm2_b"]).astype(x.dtype)
    else:
        xn = x
    x = x + _attention(xn, text, lp["attn2"], cfg)

    xn = (_ln_noaffine(x, cfg.eps) * (1 + sc_c) + sh_c).astype(x.dtype)
    y = jnp.dot(xn, lp["ffn_fc1_w"]) + lp["ffn_fc1_b"]
    y = jax.nn.gelu(y, approximate=True)
    y = jnp.dot(y, lp["ffn_fc2_w"]) + lp["ffn_fc2_b"]
    x = (x.astype(jnp.float32) + y.astype(jnp.float32) * g_c).astype(x.dtype)
    return x


def _condition(params, cfg: WanConfig, timestep, text_states):
    p = params
    te = p["time_embedder"]
    ts = _ts_embed(timestep, cfg.freq_dim).astype(cfg.dtype)
    temb = jnp.dot(ts, te["fc1_w"]) + te["fc1_b"]
    temb = jnp.dot(jax.nn.silu(temb), te["fc2_w"]) + te["fc2_b"]  # [B, D]
    proj = jnp.dot(jax.nn.silu(temb), p["time_proj_w"]) + p["time_proj_b"]
    temb6 = proj.reshape(temb.shape[0], 6, -1).astype(jnp.float32)
    tx = p["text_embedder"]
    text = jnp.dot(text_states.astype(cfg.dtype), tx["fc1_w"]) + tx["fc1_b"]
    text = jnp.dot(jax.nn.gelu(text, approximate=True), tx["fc2_w"]) + tx["fc2_b"]
    return temb.astype(jnp.float32), temb6, text


def wan_forward(params, cfg: WanConfig, latents, timestep, text_states):
    """latents [B, C, F, H, W]; timestep [B]; text_states [B, Lt, text_dim]
    -> prediction [B, C, F, H, W]."""
    p = jax.tree.map(lambda t: t.astype(cfg.dtype), params)
    b, c, f, h, w = latents.shape
    pt, ph, pw = cfg.patch_size
    nf, nh_, nw = f // pt, h // ph, w // pw

    x = latents.reshape(b, c, nf, pt, nh_, ph, nw, pw)
    x = x.transpose(0, 2, 4, 6, 1, 3, 5, 7).reshape(b, nf * nh_ * nw, -1)
    x = jnp.dot(x.astype(cfg.dtype), p["patch_embedding_w"]) + p["patch_embedding_b"]

    cos, sin = rope_3d(cfg, nf, nh_, nw)
    temb, temb6, text = _condition(p, cfg, timestep, text_states)

    body = partial(_block, cfg=cfg, text=text, temb6=temb6, cos=cos, sin=sin)
    if cfg.remat:
        body = jax.checkpoint(body)
    x, _ = jax.lax.scan(lambda carry, lp: (body(carry, lp), None), x, p["blocks"])

    # output head: global 2-way scale/shift
    tab = p["scale_shift_table"].astype(jnp.float32)[None] + temb[:, None, :]
    shift, scale = tab[:, 0:1], tab[:, 1:2]
    x = (_ln_noaffine(x, cfg.eps) * (1 + scale) + shift).astype(x.dtype)
    x = jnp.dot(x, p["proj_out_w"]) + p["proj_out_b"]

    # unpatchify
    x = x.reshape(b, nf, nh_, nw, pt, ph, pw, cfg.out_channels)
    x = x.transpose(0, 7, 1, 4, 2, 5, 3, 6).reshape(b, cfg.out_channels, f, h, w)
    return x


def loss_fn(params, cfg: WanConfig, batch) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """batch: latents (noisy) [B,C,F,H,W], timestep [B], text_states
    [B,Lt,text_dim], target [B,C,F,H,W] (flow-match velocity). MSE per
    sample then batch mean (reference WanTransformer3DModel.forward)."""
    pred = wan_forward(
        params, cfg, batch["latents"], batch["timestep"], batch["text_states"]
    )
    err = (pred.astype(jnp.float32) - batch["target"].astype(jnp.float32)) ** 2
    per_sample = err.reshape(err.shape[0], -1).mean(axis=1)
    loss = per_sample.mean()
    n = jnp.int32(err.shape[0])
    return loss * n, {"loss": loss, "ntokens": n, "mse_loss": loss}


# ---------------------------------------------------------------------------
# HF (diffusers-format) checkpoint io
# ---------------------------------------------------------------------------

_ATTN_MAP = [
    ("q_w", "to_q.weight", True), ("q_b", "to_q.bias", False),
    ("k_w", "to_k.weight", True), ("k_b", "to_k.bias", False),
    ("v_w", "to_v.weight", True), ("v_b", "to_v.bias", False),
    ("o_w", "to_out.0.weight", True), ("o_b", "to_out.0.bias", False),
    ("norm_q", "norm_q.weight", False), ("norm_k", "norm_k.weight", False),
]

_BLOCK_MAP = [
    ("norm2_w", "norm2.weight", False),
    ("norm2_b", "norm2.bias", False),
    ("ffn_fc1_w", "ffn.net.0.proj.weight", True),
    ("ffn_fc1_b", "ffn.net.0.proj.bias", False),
    ("ffn_fc2_w", "ffn.net.2.weight", True),
    ("ffn_fc2_b", "ffn.net.2.bias", False),
    ("scale_shift_table", "scale_shift_table", "squeeze"),
]

_TOP_MAP = [
    ("time_embedder.fc1_w", "condition_embedder.time_embedder.linear_1.weight", True),
    ("time_embedder.fc1_b", "condition_embedder.time_embedder.linear_1.bias", False),
    ("time_embedder.fc2_w", "condition_embedder.time_embedder.linear_2.weight", True),
    ("time_embedder.fc2_b", "condition_embedder.time_embedder.linear_2.bias", False),
    ("time_proj_w", "condition_embedder.time_proj.weight", True),
    ("time_proj_b", "condition_embedder.time_proj.bias", False),
    ("text_embedder.fc1_w", "condition_embedder.text_embedder.linear_1.weight", True),
    ("text_embedder.fc1_b", "condition_embedder.text_embedder.linear_1.bias", False),
    ("text_embedder.fc2_w", "condition_embedder.text_embedder.linear_2.weight", True),
    ("text_embedder.fc2_b", "condition_embedder.text_embedder.linear_2.bias", False),
    ("proj_out_w", "proj_out.weight", True),
    ("proj_out_b", "proj_out.bias", False),
]


def hf_to_params(model_dir: str, cfg: WanConfig, target_shardings=None):
    """Load a diffusers-format Wan checkpoint (safetensors in model_dir)."""
    from veomni_tpu.models import hf_io

    lazy = hf_io.LazyHFTensors(model_dir)
    pd = cfg.param_dtype

    def read(name):
        return np.asarray(lazy.read(name))

    def place(path, arr):
        arr = jnp.asarray(np.ascontiguousarray(arr), pd)
        if target_shardings is None:
            return arr
        return jax.device_put(arr, _get(target_shardings, path))

    params: Dict[str, Any] = {
        "patch_embedding_w": place(
            "patch_embedding_w",
            read("patch_embedding.weight").reshape(cfg.inner_dim, -1).T,
        ),
        "patch_embedding_b": place("patch_embedding_b", read("patch_embedding.bias")),
        "scale_shift_table": place(
            "scale_shift_table", read("scale_shift_table").reshape(2, -1)
        ),
    }
    for ours, hf, transpose in _TOP_MAP:
        arr = read(hf)
        _set(params, ours, place(ours, arr.T if transpose else arr))

    blocks: Dict[str, Any] = {"attn1": {}, "attn2": {}}
    L = cfg.num_layers

    def stack(tmpl, transform):
        return np.stack([transform(read(tmpl.format(i=i))) for i in range(L)])

    for which in ("attn1", "attn2"):
        for ours, hf, transpose in _ATTN_MAP:
            blocks[which][ours] = place(
                f"blocks.{which}.{ours}",
                stack(f"blocks.{{i}}.{which}.{hf}",
                      (lambda a: a.T) if transpose else (lambda a: a)),
            )
    for ours, hf, mode in _BLOCK_MAP:
        if mode == "squeeze":
            tr = lambda a: a.reshape(6, -1)
        elif mode:
            tr = lambda a: a.T
        else:
            tr = lambda a: a
        blocks[ours] = place(f"blocks.{ours}", stack(f"blocks.{{i}}.{hf}", tr))
    params["blocks"] = blocks
    return params


def params_to_hf(params, cfg: WanConfig) -> Dict[str, np.ndarray]:
    from veomni_tpu.models import hf_io

    host = hf_io.gather_to_host(params)
    out: Dict[str, np.ndarray] = {}
    pt, ph, pw = cfg.patch_size
    out["patch_embedding.weight"] = host["patch_embedding_w"].T.reshape(
        cfg.inner_dim, cfg.in_channels, pt, ph, pw
    )
    out["patch_embedding.bias"] = host["patch_embedding_b"]
    out["scale_shift_table"] = host["scale_shift_table"].reshape(1, 2, -1)
    for ours, hf, transpose in _TOP_MAP:
        arr = _get(host, ours)
        out[hf] = arr.T if transpose else arr
    for i in range(cfg.num_layers):
        for which in ("attn1", "attn2"):
            for ours, hf, transpose in _ATTN_MAP:
                arr = host["blocks"][which][ours][i]
                out[f"blocks.{i}.{which}.{hf}"] = arr.T if transpose else arr
        for ours, hf, mode in _BLOCK_MAP:
            arr = host["blocks"][ours][i]
            if mode == "squeeze":
                arr = arr.reshape(1, 6, -1)
            elif mode:
                arr = arr.T
            out[f"blocks.{i}.{hf}"] = arr
    return out


def save_hf_checkpoint(params, cfg: WanConfig, out_dir: str) -> None:
    import json
    import os

    from safetensors.flax import save_file

    tensors = params_to_hf(params, cfg)
    if jax.process_index() != 0:
        return
    os.makedirs(out_dir, exist_ok=True)
    save_file({k: jnp.asarray(v) for k, v in tensors.items()},
              os.path.join(out_dir, "diffusion_pytorch_model.safetensors"))
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump({
            "_class_name": "WanTransformer3DModel",
            "model_type": "wan_t2v",
            "patch_size": list(cfg.patch_size),
            "num_attention_heads": cfg.num_attention_heads,
            "attention_head_dim": cfg.attention_head_dim,
            "in_channels": cfg.in_channels,
            "out_channels": cfg.out_channels,
            "text_dim": cfg.text_dim,
            "freq_dim": cfg.freq_dim,
            "ffn_dim": cfg.ffn_dim,
            "num_layers": cfg.num_layers,
            "cross_attn_norm": cfg.cross_attn_norm,
            "eps": cfg.eps,
            "rope_max_seq_len": cfg.rope_max_seq_len,
        }, f, indent=2)


def config_from_hf(hf: Dict[str, Any], **overrides) -> WanConfig:
    fields = set(WanConfig.__dataclass_fields__)
    kw = {k: v for k, v in hf.items() if k in fields}
    kw.update(overrides)
    kw["model_type"] = "wan_t2v"
    return WanConfig(**kw)
