"""Qwen-Image MMDiT (real architecture).

Reference: ``veomni/models/diffusers/qwen_image/`` (wraps diffusers
``QwenImageTransformer2DModel`` with an SP-patched forward —
``modeling_qwen_image_transformer.py:166-312`` documents the model flow this
module re-implements TPU-first):

* ``img_in``: linear over pre-patchified latents (in_channels = C * p * p);
  ``txt_norm`` (RMSNorm) + ``txt_in`` linear over the text-encoder states;
* ``time_text_embed``: sinusoidal timesteps -> SiLU MLP -> ``temb``;
* dual-stream (MMDiT / flux-style) blocks: per-stream 6-way modulation
  (SiLU + linear on ``temb``), affine-free LayerNorms, **joint attention**
  over the concatenated [text, image] streams (per-head q/k RMSNorm on both
  streams, 3-axis rope on image tokens and a trailing 1-D range on text
  tokens), per-stream output projections and 4x gelu-tanh MLPs;
* output head: adaLN-continuous (SiLU + linear -> scale/shift over an
  affine-free LayerNorm) + linear to the patch dim.

Objective: flow-matching MSE on the image stream (same contract as wan.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from veomni_tpu import ops
from veomni_tpu.models.diffusion_common import (
    ln_noaffine as _ln_noaffine,
    rms_norm as _rms,
    timestep_embedding as _ts_embed,
    tree_get as _get,
    tree_set as _set,
)


@dataclass
class QwenImageConfig:
    """``QwenImageTransformer2DModelConfig`` surface (defaults = 20B)."""

    patch_size: int = 2
    in_channels: int = 64          # pre-patchified: latent C * p * p
    out_channels: int = 16
    num_layers: int = 60
    attention_head_dim: int = 128
    num_attention_heads: int = 24
    joint_attention_dim: int = 3584
    axes_dims_rope: Tuple[int, int, int] = (16, 56, 56)
    # static latent grid (frame, h, w) for the rope plan; () = infer a
    # square single-frame grid from the token count
    img_shape: Tuple[int, int, int] = ()
    rope_theta: float = 10000.0
    eps: float = 1e-6
    initializer_range: float = 0.02
    model_type: str = "qwen_image"
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True

    def __post_init__(self):
        self.axes_dims_rope = tuple(self.axes_dims_rope)
        self.img_shape = tuple(self.img_shape)
        for f in ("dtype", "param_dtype"):
            v = getattr(self, f)
            if isinstance(v, str):
                setattr(self, f, getattr(jnp, v))

    @property
    def inner_dim(self) -> int:
        return self.num_attention_heads * self.attention_head_dim

    @property
    def proj_dim(self) -> int:
        return self.patch_size ** 2 * self.out_channels


def init_params(rng: jax.Array, cfg: QwenImageConfig) -> Dict[str, Any]:
    s = cfg.initializer_range
    d, L = cfg.inner_dim, cfg.num_layers
    keys = iter(jax.random.split(rng, 32))
    pd = cfg.param_dtype

    def init(key, shape):
        return (jax.random.normal(key, shape, jnp.float32) * s).astype(pd)

    def stream_attn(key, prefix_dim):
        ks = jax.random.split(key, 4)
        return {
            "q_w": init(ks[0], (L, prefix_dim, d)), "q_b": jnp.zeros((L, d), pd),
            "k_w": init(ks[1], (L, prefix_dim, d)), "k_b": jnp.zeros((L, d), pd),
            "v_w": init(ks[2], (L, prefix_dim, d)), "v_b": jnp.zeros((L, d), pd),
            "o_w": init(ks[3], (L, d, d)), "o_b": jnp.zeros((L, d), pd),
            "norm_q": jnp.ones((L, cfg.attention_head_dim), pd),
            "norm_k": jnp.ones((L, cfg.attention_head_dim), pd),
        }

    def mlp(key):
        k1, k2 = jax.random.split(key)
        return {
            "fc1_w": init(k1, (L, d, 4 * d)), "fc1_b": jnp.zeros((L, 4 * d), pd),
            "fc2_w": init(k2, (L, 4 * d, d)), "fc2_b": jnp.zeros((L, d), pd),
        }

    return {
        "img_in_w": init(next(keys), (cfg.in_channels, d)),
        "img_in_b": jnp.zeros((d,), pd),
        "txt_norm": jnp.ones((cfg.joint_attention_dim,), pd),
        "txt_in_w": init(next(keys), (cfg.joint_attention_dim, d)),
        "txt_in_b": jnp.zeros((d,), pd),
        "time_embedder": {
            "fc1_w": init(next(keys), (256, d)), "fc1_b": jnp.zeros((d,), pd),
            "fc2_w": init(next(keys), (d, d)), "fc2_b": jnp.zeros((d,), pd),
        },
        "blocks": {
            "img_mod_w": init(next(keys), (L, d, 6 * d)),
            "img_mod_b": jnp.zeros((L, 6 * d), pd),
            "txt_mod_w": init(next(keys), (L, d, 6 * d)),
            "txt_mod_b": jnp.zeros((L, 6 * d), pd),
            "img_attn": stream_attn(next(keys), d),
            "txt_attn": stream_attn(next(keys), d),
            "img_mlp": mlp(next(keys)),
            "txt_mlp": mlp(next(keys)),
        },
        "norm_out_w": init(next(keys), (d, 2 * d)),
        "norm_out_b": jnp.zeros((2 * d,), pd),
        "proj_out_w": init(next(keys), (d, cfg.proj_dim)),
        "proj_out_b": jnp.zeros((cfg.proj_dim,), pd),
    }


def abstract_params(cfg: QwenImageConfig):
    return jax.eval_shape(lambda: init_params(jax.random.PRNGKey(0), cfg))


# ---------------------------------------------------------------------------
# rope plan
# ---------------------------------------------------------------------------

def rope_plan(cfg: QwenImageConfig, img_shape: Tuple[int, int, int], txt_len: int):
    """(cos, sin) [1, txt_len + f*h*w, head_dim] in joint [text, image]
    order — diffusers ``QwenEmbedRope`` with ``scale_rope=True``: image
    row/col positions are centered around zero (rows span
    ``[-(h - h//2), h//2)``), frames start at 0, and text tokens carry a
    1-D range starting at ``max(h//2, w//2)`` on every axis."""
    f, h, w = img_shape
    dims = cfg.axes_dims_rope

    def axis_ang(pos, dim):
        inv = 1.0 / (cfg.rope_theta ** (np.arange(0, dim, 2) / dim))
        return np.repeat(pos[:, None] * inv[None, :], 2, axis=1)

    fpos = np.arange(f)
    hpos = np.arange(h) - (h - h // 2)
    wpos = np.arange(w) - (w - w // 2)
    ff, hh, ww = np.meshgrid(fpos, hpos, wpos, indexing="ij")
    img_ang = np.concatenate([
        axis_ang(ff.reshape(-1), dims[0]),
        axis_ang(hh.reshape(-1), dims[1]),
        axis_ang(ww.reshape(-1), dims[2]),
    ], axis=1)
    start = max(h // 2, w // 2)
    tpos = np.arange(start, start + txt_len)
    txt_ang = np.concatenate([axis_ang(tpos, dim) for dim in dims], axis=1)
    ang = np.concatenate([txt_ang, img_ang], axis=0)[None]
    return jnp.cos(ang).astype(jnp.float32), jnp.sin(ang).astype(jnp.float32)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _qkv(x, ap, cfg: QwenImageConfig):
    b, n, _ = x.shape
    nh, hd = cfg.num_attention_heads, cfg.attention_head_dim
    q = (jnp.dot(x, ap["q_w"]) + ap["q_b"]).reshape(b, n, nh, hd)
    k = (jnp.dot(x, ap["k_w"]) + ap["k_b"]).reshape(b, n, nh, hd)
    v = (jnp.dot(x, ap["v_w"]) + ap["v_b"]).reshape(b, n, nh, hd)
    q = _rms(q, ap["norm_q"], cfg.eps)
    k = _rms(k, ap["norm_k"], cfg.eps)
    return q, k, v


def _mod6(temb, w, b):
    """SiLU + linear -> [B, 1, 6D] f32 -> six [B,1,D] streams."""
    m = jnp.dot(jax.nn.silu(temb), w) + b
    return jnp.split(m.astype(jnp.float32)[:, None, :], 6, axis=-1)


def _block(carry, lp, cfg: QwenImageConfig, temb, cos, sin, txt_seg, img_seg):
    img, txt = carry
    sh1_i, sc1_i, g1_i, sh2_i, sc2_i, g2_i = _mod6(temb, lp["img_mod_w"], lp["img_mod_b"])
    sh1_t, sc1_t, g1_t, sh2_t, sc2_t, g2_t = _mod6(temb, lp["txt_mod_w"], lp["txt_mod_b"])

    img_n = (_ln_noaffine(img, cfg.eps) * (1 + sc1_i) + sh1_i).astype(img.dtype)
    txt_n = (_ln_noaffine(txt, cfg.eps) * (1 + sc1_t) + sh1_t).astype(txt.dtype)

    qi, ki, vi = _qkv(img_n, lp["img_attn"], cfg)
    qt, kt, vt = _qkv(txt_n, lp["txt_attn"], cfg)
    # joint order [text, image]
    q = jnp.concatenate([qt, qi], axis=1)
    k = jnp.concatenate([kt, ki], axis=1)
    v = jnp.concatenate([vt, vi], axis=1)
    q, k = ops.apply_rotary(q, k, cos, sin, interleaved=True)
    seg = jnp.concatenate([txt_seg, img_seg], axis=1)
    o = ops.attention(q, k, v, segment_ids=seg, causal=False)
    nt = txt.shape[1]
    b = img.shape[0]
    ot = o[:, :nt].reshape(b, nt, -1)
    oi = o[:, nt:].reshape(b, img.shape[1], -1)
    oi = jnp.dot(oi, lp["img_attn"]["o_w"]) + lp["img_attn"]["o_b"]
    ot = jnp.dot(ot, lp["txt_attn"]["o_w"]) + lp["txt_attn"]["o_b"]
    img = (img.astype(jnp.float32) + oi.astype(jnp.float32) * g1_i).astype(img.dtype)
    txt = (txt.astype(jnp.float32) + ot.astype(jnp.float32) * g1_t).astype(txt.dtype)

    def stream_mlp(x, mp, sh, sc, g):
        xn = (_ln_noaffine(x, cfg.eps) * (1 + sc) + sh).astype(x.dtype)
        y = jnp.dot(xn, mp["fc1_w"]) + mp["fc1_b"]
        y = jax.nn.gelu(y, approximate=True)
        y = jnp.dot(y, mp["fc2_w"]) + mp["fc2_b"]
        return (x.astype(jnp.float32) + y.astype(jnp.float32) * g).astype(x.dtype)

    img = stream_mlp(img, lp["img_mlp"], sh2_i, sc2_i, g2_i)
    txt = stream_mlp(txt, lp["txt_mlp"], sh2_t, sc2_t, g2_t)
    return img, txt


def qwen_image_forward(params, cfg: QwenImageConfig, latents, timestep,
                       text_states, text_mask=None,
                       img_shape: Tuple[int, int, int] = None):
    """latents [B, N_img, in_channels] (pre-patchified, N_img = f*h*w of
    ``img_shape``); timestep [B]; text_states [B, Lt, joint_dim];
    text_mask [B, Lt] (1 = real token) -> prediction [B, N_img, proj_dim]."""
    p = jax.tree.map(lambda t: t.astype(cfg.dtype), params)
    b, n_img, _ = latents.shape
    lt = text_states.shape[1]
    if img_shape is None:
        side = int(round(n_img ** 0.5))
        if side * side != n_img:
            raise ValueError(
                f"{n_img} image tokens is not a square grid; set "
                "cfg.img_shape=(f, h, w) explicitly"
            )
        img_shape = (1, side, side)
    elif int(np.prod(img_shape)) != n_img:
        raise ValueError(f"img_shape {img_shape} != {n_img} image tokens")

    img = jnp.dot(latents.astype(cfg.dtype), p["img_in_w"]) + p["img_in_b"]
    txt = _rms(text_states.astype(cfg.dtype), p["txt_norm"], cfg.eps)
    txt = jnp.dot(txt, p["txt_in_w"]) + p["txt_in_b"]

    te = p["time_embedder"]
    temb = _ts_embed(timestep, 256).astype(cfg.dtype)
    temb = jnp.dot(temb, te["fc1_w"]) + te["fc1_b"]
    temb = jnp.dot(jax.nn.silu(temb), te["fc2_w"]) + te["fc2_b"]  # [B, D]

    cos, sin = rope_plan(cfg, img_shape, lt)
    img_seg = jnp.ones((b, n_img), jnp.int32)
    txt_seg = (
        text_mask.astype(jnp.int32) if text_mask is not None
        else jnp.ones((b, lt), jnp.int32)
    )

    body = partial(_block, cfg=cfg, temb=temb, cos=cos, sin=sin,
                   txt_seg=txt_seg, img_seg=img_seg)
    if cfg.remat:
        body = jax.checkpoint(body)
    (img, txt), _ = jax.lax.scan(
        lambda c, lp: (body(c, lp), None), (img, txt), p["blocks"]
    )

    # adaLN-continuous output head
    mod = jnp.dot(jax.nn.silu(temb), p["norm_out_w"]) + p["norm_out_b"]
    scale, shift = jnp.split(mod.astype(jnp.float32)[:, None, :], 2, axis=-1)
    img = (_ln_noaffine(img, cfg.eps) * (1 + scale) + shift).astype(img.dtype)
    return jnp.dot(img, p["proj_out_w"]) + p["proj_out_b"]


def loss_fn(params, cfg: QwenImageConfig, batch) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """batch: latents [B,N,in_channels] (noisy), timestep [B], text_states
    [B,Lt,joint_dim], text_mask [B,Lt], target [B,N,proj_dim]."""
    pred = qwen_image_forward(
        params, cfg, batch["latents"], batch["timestep"],
        batch["text_states"], batch.get("text_mask"),
        img_shape=cfg.img_shape or None,
    )
    err = (pred.astype(jnp.float32) - batch["target"].astype(jnp.float32)) ** 2
    per_sample = err.reshape(err.shape[0], -1).mean(axis=1)
    loss = per_sample.mean()
    n = jnp.int32(err.shape[0])
    return loss * n, {"loss": loss, "ntokens": n, "mse_loss": loss}


# ---------------------------------------------------------------------------
# diffusers-format checkpoint io
# ---------------------------------------------------------------------------

_STREAM_ATTN_MAP = {
    "img_attn": [
        ("q_w", "attn.to_q.weight", True), ("q_b", "attn.to_q.bias", False),
        ("k_w", "attn.to_k.weight", True), ("k_b", "attn.to_k.bias", False),
        ("v_w", "attn.to_v.weight", True), ("v_b", "attn.to_v.bias", False),
        ("o_w", "attn.to_out.0.weight", True), ("o_b", "attn.to_out.0.bias", False),
        ("norm_q", "attn.norm_q.weight", False),
        ("norm_k", "attn.norm_k.weight", False),
    ],
    "txt_attn": [
        ("q_w", "attn.add_q_proj.weight", True), ("q_b", "attn.add_q_proj.bias", False),
        ("k_w", "attn.add_k_proj.weight", True), ("k_b", "attn.add_k_proj.bias", False),
        ("v_w", "attn.add_v_proj.weight", True), ("v_b", "attn.add_v_proj.bias", False),
        ("o_w", "attn.to_add_out.weight", True), ("o_b", "attn.to_add_out.bias", False),
        ("norm_q", "attn.norm_added_q.weight", False),
        ("norm_k", "attn.norm_added_k.weight", False),
    ],
}

_BLOCK_MAP = [
    ("img_mod_w", "img_mod.1.weight", True), ("img_mod_b", "img_mod.1.bias", False),
    ("txt_mod_w", "txt_mod.1.weight", True), ("txt_mod_b", "txt_mod.1.bias", False),
    ("img_mlp.fc1_w", "img_mlp.net.0.proj.weight", True),
    ("img_mlp.fc1_b", "img_mlp.net.0.proj.bias", False),
    ("img_mlp.fc2_w", "img_mlp.net.2.weight", True),
    ("img_mlp.fc2_b", "img_mlp.net.2.bias", False),
    ("txt_mlp.fc1_w", "txt_mlp.net.0.proj.weight", True),
    ("txt_mlp.fc1_b", "txt_mlp.net.0.proj.bias", False),
    ("txt_mlp.fc2_w", "txt_mlp.net.2.weight", True),
    ("txt_mlp.fc2_b", "txt_mlp.net.2.bias", False),
]

_TOP_MAP = [
    ("img_in_w", "img_in.weight", True), ("img_in_b", "img_in.bias", False),
    ("txt_norm", "txt_norm.weight", False),
    ("txt_in_w", "txt_in.weight", True), ("txt_in_b", "txt_in.bias", False),
    ("time_embedder.fc1_w",
     "time_text_embed.timestep_embedder.linear_1.weight", True),
    ("time_embedder.fc1_b",
     "time_text_embed.timestep_embedder.linear_1.bias", False),
    ("time_embedder.fc2_w",
     "time_text_embed.timestep_embedder.linear_2.weight", True),
    ("time_embedder.fc2_b",
     "time_text_embed.timestep_embedder.linear_2.bias", False),
    ("norm_out_w", "norm_out.linear.weight", True),
    ("norm_out_b", "norm_out.linear.bias", False),
    ("proj_out_w", "proj_out.weight", True),
    ("proj_out_b", "proj_out.bias", False),
]


def hf_to_params(model_dir: str, cfg: QwenImageConfig, target_shardings=None):
    from veomni_tpu.models import hf_io

    lazy = hf_io.LazyHFTensors(model_dir)
    pd = cfg.param_dtype

    def read(name):
        return np.asarray(lazy.read(name))

    def place(path, arr):
        arr = jnp.asarray(np.ascontiguousarray(arr), pd)
        if target_shardings is None:
            return arr
        return jax.device_put(arr, _get(target_shardings, path))

    params: Dict[str, Any] = {}
    for ours, hf, transpose in _TOP_MAP:
        arr = read(hf)
        _set(params, ours, place(ours, arr.T if transpose else arr))

    L = cfg.num_layers

    def stack(tmpl, transform):
        return np.stack([
            transform(read(tmpl.format(i=i))) for i in range(L)
        ])

    blocks: Dict[str, Any] = {}
    for which, mapping in _STREAM_ATTN_MAP.items():
        sub = {}
        for ours, hf, transpose in mapping:
            sub[ours] = place(
                f"blocks.{which}.{ours}",
                stack(f"transformer_blocks.{{i}}.{hf}",
                      (lambda a: a.T) if transpose else (lambda a: a)),
            )
        blocks[which] = sub
    for ours, hf, transpose in _BLOCK_MAP:
        _set(blocks, ours, place(
            f"blocks.{ours}",
            stack(f"transformer_blocks.{{i}}.{hf}",
                  (lambda a: a.T) if transpose else (lambda a: a)),
        ))
    params["blocks"] = blocks
    return params


def params_to_hf(params, cfg: QwenImageConfig) -> Dict[str, np.ndarray]:
    from veomni_tpu.models import hf_io

    host = hf_io.gather_to_host(params)
    out: Dict[str, np.ndarray] = {}
    for ours, hf, transpose in _TOP_MAP:
        arr = _get(host, ours)
        out[hf] = arr.T if transpose else arr
    for i in range(cfg.num_layers):
        for which, mapping in _STREAM_ATTN_MAP.items():
            for ours, hf, transpose in mapping:
                arr = host["blocks"][which][ours][i]
                out[f"transformer_blocks.{i}.{hf}"] = arr.T if transpose else arr
        for ours, hf, transpose in _BLOCK_MAP:
            arr = _get(host["blocks"], ours)[i]
            out[f"transformer_blocks.{i}.{hf}"] = arr.T if transpose else arr
    return out


def save_hf_checkpoint(params, cfg: QwenImageConfig, out_dir: str) -> None:
    import json
    import os

    from safetensors.flax import save_file

    tensors = params_to_hf(params, cfg)
    if jax.process_index() != 0:
        return
    os.makedirs(out_dir, exist_ok=True)
    save_file({k: jnp.asarray(v) for k, v in tensors.items()},
              os.path.join(out_dir, "diffusion_pytorch_model.safetensors"))
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump({
            "_class_name": "QwenImageTransformer2DModel",
            "model_type": "qwen_image",
            "patch_size": cfg.patch_size,
            "in_channels": cfg.in_channels,
            "out_channels": cfg.out_channels,
            "num_layers": cfg.num_layers,
            "attention_head_dim": cfg.attention_head_dim,
            "num_attention_heads": cfg.num_attention_heads,
            "joint_attention_dim": cfg.joint_attention_dim,
            "axes_dims_rope": list(cfg.axes_dims_rope),
            # non-diffusers extra: keep the trained latent grid so a reload
            # doesn't regress to square inference
            "img_shape": list(cfg.img_shape),
        }, f, indent=2)


def config_from_hf(hf: Dict[str, Any], **overrides) -> QwenImageConfig:
    fields = set(QwenImageConfig.__dataclass_fields__)
    kw = {k: v for k, v in hf.items() if k in fields}
    kw.update(overrides)
    kw["model_type"] = "qwen_image"
    return QwenImageConfig(**kw)
