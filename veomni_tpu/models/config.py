"""Model configuration: one dataclass covering the llama-family dialects.

Reference: per-model HF configs under ``veomni/models/transformers/<name>/``.
We keep HF *checkpoint/config* compatibility (``from_hf_config`` consumes an
HF config.json dict) while owning the modeling code (SURVEY.md §7.1: no
patchgen — native model zoo).

Dialect switches:
  llama:      defaults
  qwen2:      attention_bias=True (qkv bias)
  qwen3:      qk_norm=True, head_dim explicit
  qwen3_moe:  qk_norm=True + MoE fields (num_experts, top_k, norm_topk_prob)
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import jax.numpy as jnp


@dataclass
class TransformerConfig:
    model_type: str = "llama"
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    head_dim: int = 0  # 0 -> hidden // heads
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    rope_scaling: Optional[Dict[str, Any]] = None  # HF rope_scaling dict
    max_position_embeddings: int = 4096
    tie_word_embeddings: bool = False
    attention_bias: bool = False
    qk_norm: bool = False
    sliding_window: Optional[int] = None
    # MoE (num_experts == 0 -> dense MLP)
    num_experts: int = 0
    num_experts_per_tok: int = 0
    moe_intermediate_size: int = 0
    norm_topk_prob: bool = True
    router_aux_loss_coef: float = 0.001
    # EP dispatch capacity factor; <= 0 means dropless (see parallel/moe.py)
    moe_capacity_factor: float = 0.0
    # numerics
    dtype: Any = jnp.bfloat16       # activation/compute dtype
    param_dtype: Any = jnp.float32  # master param dtype
    remat: bool = True              # jax.checkpoint each decoder layer
    initializer_range: float = 0.02

    def __post_init__(self):
        if not self.head_dim:
            self.head_dim = self.hidden_size // self.num_attention_heads
        if isinstance(self.dtype, str):
            self.dtype = getattr(jnp, self.dtype)
        if isinstance(self.param_dtype, str):
            self.param_dtype = getattr(jnp, self.param_dtype)

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    @property
    def q_dim(self) -> int:
        return self.num_attention_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.num_key_value_heads * self.head_dim

    # ------------------------------------------------------------------ HF io
    _HF_FIELDS = (
        "vocab_size hidden_size intermediate_size num_hidden_layers "
        "num_attention_heads num_key_value_heads rms_norm_eps rope_theta "
        "max_position_embeddings tie_word_embeddings sliding_window "
        "num_experts_per_tok moe_intermediate_size norm_topk_prob "
        "router_aux_loss_coef initializer_range"
    ).split()

    @classmethod
    def from_hf_config(cls, hf: Dict[str, Any], **overrides) -> "TransformerConfig":
        mt = hf.get("model_type", "llama")
        kw: Dict[str, Any] = {"model_type": mt}
        for name in cls._HF_FIELDS:
            if name in hf and hf[name] is not None:
                kw[name] = hf[name]
        if hf.get("head_dim"):
            kw["head_dim"] = hf["head_dim"]
        if hf.get("rope_scaling"):
            kw["rope_scaling"] = dict(hf["rope_scaling"])
        if mt in ("qwen2",):
            kw["attention_bias"] = True
        if mt in ("qwen3", "qwen3_moe"):
            kw["qk_norm"] = True
        if "attention_bias" in hf:
            kw["attention_bias"] = hf["attention_bias"]
        if mt == "qwen3_moe":
            kw["num_experts"] = hf.get("num_experts", 0)
        elif "num_local_experts" in hf:
            kw["num_experts"] = hf["num_local_experts"]
        if not hf.get("use_sliding_window", mt == "gemma3"):
            kw["sliding_window"] = None
        kw.update(overrides)
        return cls(**kw)

    @classmethod
    def from_pretrained(cls, path: str, **overrides) -> "TransformerConfig":
        with open(os.path.join(path, "config.json")) as f:
            return cls.from_hf_config(json.load(f), **overrides)

    def to_hf_config(self) -> Dict[str, Any]:
        hf = {"model_type": self.model_type, "head_dim": self.head_dim,
              "attention_bias": self.attention_bias}
        if self.rope_scaling:
            hf["rope_scaling"] = self.rope_scaling
        for name in self._HF_FIELDS:
            hf[name] = getattr(self, name)
        if self.is_moe:
            hf["num_experts"] = self.num_experts
        return hf
