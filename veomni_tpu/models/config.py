"""Model configuration: one dataclass covering the llama-family dialects.

Reference: per-model HF configs under ``veomni/models/transformers/<name>/``.
We keep HF *checkpoint/config* compatibility (``from_hf_config`` consumes an
HF config.json dict) while owning the modeling code (SURVEY.md §7.1: no
patchgen — native model zoo).

Dialect switches:
  llama:      defaults
  qwen2:      attention_bias=True (qkv bias)
  qwen3:      qk_norm=True, head_dim explicit
  qwen3_moe:  qk_norm=True + MoE fields (num_experts, top_k, norm_topk_prob)
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import jax.numpy as jnp


@dataclass
class TransformerConfig:
    model_type: str = "llama"
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    head_dim: int = 0  # 0 -> hidden // heads
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    rope_scaling: Optional[Dict[str, Any]] = None  # HF rope_scaling dict
    max_position_embeddings: int = 4096
    tie_word_embeddings: bool = False
    attention_bias: bool = False
    o_bias: bool = False  # bias on o_proj too (gpt_oss; qwen2 has qkv only)
    # rope covers only the first head_dim*factor dims (glm4_moe: 0.5)
    partial_rotary_factor: float = 1.0
    mlp_bias: bool = False
    qk_norm: bool = False
    sliding_window: Optional[int] = None
    # per-layer attention pattern: list of "sliding_attention"/"full_attention"
    # (gemma3 / gpt_oss alternating local-global); None -> uniform
    layer_types: Optional[List[str]] = None
    rope_local_base_freq: float = 0.0  # gemma3: separate theta for sliding layers
    # activation / norms / scaling dialects
    hidden_act: str = "silu"            # silu | gelu_pytorch_tanh | gelu
    norm_zero_centered: bool = False    # gemma family: weight is (1 + w)
    sandwich_norms: bool = False        # gemma3 post-attn/pre+post-ffw norms
    embed_scale: float = 0.0            # gemma: sqrt(hidden); 0 = off
    final_logit_softcap: float = 0.0
    query_pre_attn_scalar: float = 0.0  # gemma3: softmax scale = qpas^-0.5
    attention_sinks: bool = False       # gpt_oss learned per-head sink logit
    router_bias: bool = False           # gpt_oss router linear has a bias
    # MLA (deepseek_v3): kv/q low-rank compression + rope/nope head split
    rope_interleave: bool = False  # deepseek pairwise rope layout
    q_lora_rank: int = 0
    kv_lora_rank: int = 0
    qk_nope_head_dim: int = 0
    qk_rope_head_dim: int = 0
    v_head_dim: int = 0
    # DSA lightning indexer (glm_moe_dsa / DeepSeek-V3.2 sparse attention):
    # per-token top-k KV selection scored by a lightweight side network
    # (reference ``glm_moe_dsa/generated/...:123`` GlmMoeDsaIndexer)
    index_n_heads: int = 0
    index_head_dim: int = 0
    index_topk: int = 0            # 0 -> DSA off
    indexer_types: Any = ()        # per-layer "full" | "shared" (reuse prev)
    # MoE (num_experts == 0 -> dense MLP)
    num_experts: int = 0
    num_experts_per_tok: int = 0
    moe_intermediate_size: int = 0
    norm_topk_prob: bool = True
    router_aux_loss_coef: float = 0.001
    # deepseek routing dialect
    scoring_func: str = "softmax"       # softmax | sigmoid (w/ correction bias)
    routed_scaling_factor: float = 1.0
    n_group: int = 0                    # group-limited routing (noaux-tc)
    topk_group: int = 0
    n_shared_experts: int = 0
    # qwen2-moe / qwen3_next style shared expert: explicit intermediate size
    # (overrides moe_intermediate_size * n_shared_experts) + sigmoid gate
    shared_expert_intermediate_size: int = 0
    shared_expert_gated: bool = False
    first_k_dense_replace: int = 0      # leading dense layers (deepseek)
    # qwen3_next hybrid GatedDeltaNet (reference models/transformers/qwen3_5/,
    # ops/kernels/gated_delta_rule/): periodic linear-attention layers with a
    # full-attention layer every `full_attention_interval` layers
    linear_num_value_heads: int = 0     # 0 -> no linear-attention layers
    linear_num_key_heads: int = 0
    linear_key_head_dim: int = 0
    linear_value_head_dim: int = 0
    linear_conv_kernel_dim: int = 4
    full_attention_interval: int = 4
    attn_output_gate: bool = False      # full-attn layers: out *= sigmoid(gate)
    # EP dispatch capacity factor; <= 0 means dropless (see parallel/moe.py)
    moe_capacity_factor: float = 0.0
    # HF checkpoint expert-tensor layout: "" = auto by model_type
    # (gpt_oss -> fused_interleaved, else per_expert); "fused_chunked" is the
    # qwen3_vl_moe layout (gate_up_proj [E, H, 2I] with gate then up halves)
    expert_layout: str = ""
    # numerics
    dtype: Any = jnp.bfloat16       # activation/compute dtype
    param_dtype: Any = jnp.float32  # master param dtype
    remat: bool = True              # jax.checkpoint each decoder layer
    # remat policy: "nothing" (full recompute), "dots" (save matmul outputs),
    # "offload" (save dots to host memory — the TPU analogue of the
    # reference's CPU activation offload, distributed/offloading.py:74)
    remat_policy: str = "nothing"
    # ChunkMBS analogue (reference distributed/chunk_mbs.py:145): sequence
    # chunk length for the per-layer MLP compute. The [B, S, intermediate]
    # activation — the largest per-layer tensor at long context — is bounded
    # to [B, chunk_mbs, intermediate] by a lax.map over sequence chunks
    # (fwd AND the remat'd bwd recompute). 0 disables.
    chunk_mbs: int = 0
    # Ulysses SP a2a/compute overlap (parallel/async_ulysses.py): head-chunk
    # count for the chunked async pipeline. 0 = defer to the kernel-registry
    # pin / VEOMNI_ULYSSES_ASYNC env; 1 = force monolithic; >= 2 = pipeline
    # with that many chunks (clamped to the head layout's feasible maximum).
    ulysses_async_chunks: int = 0
    initializer_range: float = 0.02

    def __post_init__(self):
        if not self.head_dim:
            self.head_dim = self.hidden_size // self.num_attention_heads
        if isinstance(self.dtype, str):
            self.dtype = getattr(jnp, self.dtype)
        if isinstance(self.param_dtype, str):
            self.param_dtype = getattr(jnp, self.param_dtype)

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    @property
    def use_mla(self) -> bool:
        return self.kv_lora_rank > 0

    @property
    def use_dsa(self) -> bool:
        return self.index_topk > 0

    @property
    def q_dim(self) -> int:
        return self.num_attention_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.num_key_value_heads * self.head_dim

    @property
    def qk_head_dim(self) -> int:
        """MLA query/key head dim (nope + rope parts)."""
        return self.qk_nope_head_dim + self.qk_rope_head_dim

    def window_for_layer(self, i: int) -> int:
        """Per-layer sliding window (0 = full attention)."""
        if self.layer_types is not None:
            sliding = self.layer_types[i] == "sliding_attention"
        else:
            sliding = self.sliding_window is not None
        return int(self.sliding_window or 0) if sliding else 0


    # ------------------------------------------------------------------ HF io
    _HF_FIELDS = (
        "vocab_size hidden_size intermediate_size num_hidden_layers "
        "num_attention_heads num_key_value_heads rms_norm_eps rope_theta "
        "max_position_embeddings tie_word_embeddings sliding_window "
        "num_experts_per_tok moe_intermediate_size norm_topk_prob "
        "router_aux_loss_coef initializer_range layer_types hidden_act "
        "rope_local_base_freq q_lora_rank kv_lora_rank qk_nope_head_dim "
        "qk_rope_head_dim v_head_dim routed_scaling_factor n_group "
        "topk_group n_shared_experts first_k_dense_replace scoring_func "
        "mlp_bias attention_bias partial_rotary_factor"
    ).split()

    @classmethod
    def from_hf_config(cls, hf: Dict[str, Any], **overrides) -> "TransformerConfig":
        mt = hf.get("model_type", "llama")
        if isinstance(hf.get("text_config"), dict):
            # multimodal wrappers (gemma3, *-vl) nest the LM dialect
            hf = {**hf, **hf["text_config"]}
            mt = hf.get("model_type", mt)
        kw: Dict[str, Any] = {"model_type": mt}
        for name in cls._HF_FIELDS:
            if name in hf and hf[name] is not None:
                kw[name] = hf[name]
        if hf.get("head_dim"):
            kw["head_dim"] = hf["head_dim"]
        if hf.get("rope_scaling"):
            kw["rope_scaling"] = dict(hf["rope_scaling"])
        if hf.get("hidden_activation"):  # gemma naming
            kw["hidden_act"] = hf["hidden_activation"]
        if mt in ("qwen2",):
            kw["attention_bias"] = True
        if mt in ("qwen3", "qwen3_moe"):
            kw["qk_norm"] = True
        if "attention_bias" in hf:
            kw["attention_bias"] = hf["attention_bias"]
        # expert count: our exports use "num_experts"; HF dialects vary
        for key in ("num_experts", "n_routed_experts", "num_local_experts"):
            if hf.get(key):
                kw["num_experts"] = hf[key]
                break
        if mt in ("gemma3", "gemma3_text"):
            kw.update(
                model_type="gemma3",
                qk_norm=True,
                norm_zero_centered=True,
                sandwich_norms=True,
                embed_scale=hf["hidden_size"] ** 0.5,
                query_pre_attn_scalar=hf.get("query_pre_attn_scalar", 256),
                tie_word_embeddings=hf.get("tie_word_embeddings", True),
            )
            if hf.get("final_logit_softcapping"):
                kw["final_logit_softcap"] = hf["final_logit_softcapping"]
        if mt == "gpt_oss":
            kw.update(attention_sinks=True, attention_bias=True, o_bias=True,
                      mlp_bias=True, hidden_act="gpt_oss_glu", router_bias=True,
                      num_experts=hf.get("num_local_experts", 0))
        if mt in ("deepseek_v3", "deepseek_v2"):
            # v3 routes on sigmoid scores + correction bias (noaux-tc); v2
            # uses plain softmax scores with greedy / max-per-group topk
            kw["scoring_func"] = hf.get(
                "scoring_func", "softmax" if mt == "deepseek_v2" else "sigmoid"
            )
            kw["norm_topk_prob"] = hf.get("norm_topk_prob", True)
            # deepseek trains bias-update (noaux-tc), not an aux loss term
            kw["router_aux_loss_coef"] = hf.get("aux_loss_alpha", 0.0)
            kw["rope_interleave"] = hf.get("rope_interleave", True)
        if mt == "seed_oss":
            kw["attention_bias"] = hf.get("attention_bias", True)
            kw["o_bias"] = hf.get("attention_out_bias", False)
        if mt in ("glm4_moe", "glm_moe"):
            kw.update(
                model_type="glm4_moe",
                qk_norm=hf.get("use_qk_norm", False),
                scoring_func="sigmoid",       # Glm4MoeTopkRouter: sigmoid + bias
                router_aux_loss_coef=0.0,     # bias-update balancing, no aux term
                norm_topk_prob=hf.get("norm_topk_prob", True),
            )
        if mt == "glm_moe_dsa":
            # MLA (deepseek-v3.2 lineage) + DSA indexer + glm4_moe routing
            kw.update(
                expert_layout="fused_chunked",
                scoring_func="sigmoid",
                router_aux_loss_coef=hf.get(
                    "router_aux_loss_coef", hf.get("aux_loss_alpha", 0.0)
                ),
                norm_topk_prob=hf.get("norm_topk_prob", True),
                rope_interleave=hf.get("rope_interleave", True),
                index_n_heads=hf.get("index_n_heads", 0),
                index_head_dim=hf.get("index_head_dim", 0),
                index_topk=hf.get("index_topk", 0),
                indexer_types=tuple(hf.get("indexer_types") or ()),
            )
            mlt = hf.get("mlp_layer_types")
            if mlt and "first_k_dense_replace" not in hf:
                k_dense = 0
                while k_dense < len(mlt) and mlt[k_dense] == "dense":
                    k_dense += 1
                if any(t == "dense" for t in mlt[k_dense:]):
                    raise ValueError(
                        "glm_moe_dsa mlp_layer_types with non-prefix dense "
                        "layers is unsupported (first_k_dense layout only)"
                    )
                kw["first_k_dense_replace"] = k_dense
        if mt in ("qwen3_next", "qwen3_5", "qwen3_5_moe"):
            # hybrid GatedDeltaNet (models/qwen3_next.py); layer pattern comes
            # from full_attention_interval, not HF layer_types
            kw.pop("layer_types", None)
            kw.update(
                model_type="qwen3_next",
                # Qwen3NextRMSNorm is zero-centered ((1 + w), zeros init);
                # the GATED delta-net norm is standard and handled separately
                norm_zero_centered=True,
                linear_num_value_heads=hf.get("linear_num_value_heads", 0),
                linear_num_key_heads=hf.get("linear_num_key_heads", 0),
                linear_key_head_dim=hf.get("linear_key_head_dim", 0),
                linear_value_head_dim=hf.get("linear_value_head_dim", 0),
                linear_conv_kernel_dim=hf.get("linear_conv_kernel_dim", 4),
                full_attention_interval=hf.get("full_attention_interval", 4) or 4,
                attn_output_gate=True,
                partial_rotary_factor=hf.get("partial_rotary_factor", 0.25),
                shared_expert_intermediate_size=hf.get(
                    "shared_expert_intermediate_size", 0
                ),
                shared_expert_gated=bool(
                    hf.get("shared_expert_intermediate_size", 0)
                ),
                router_aux_loss_coef=hf.get("router_aux_loss_coef", 0.0)
                if hf.get("output_router_logits") else 0.0,
            )
        if not hf.get("use_sliding_window", True) and mt.startswith("qwen"):
            kw["sliding_window"] = None
        kw.update(overrides)
        return cls(**kw)

    @classmethod
    def from_pretrained(cls, path: str, **overrides) -> "TransformerConfig":
        with open(os.path.join(path, "config.json")) as f:
            return cls.from_hf_config(json.load(f), **overrides)

    # inverse of the expert-count probing in from_hf_config: the key HF
    # transformers expects for each MoE dialect (extra keys are tolerated by
    # HF, but the canonical one must be present for the count to round-trip)
    _HF_EXPERT_KEY = {
        "deepseek_v2": "n_routed_experts",
        "deepseek_v3": "n_routed_experts",
        "gpt_oss": "num_local_experts",
        "mixtral": "num_local_experts",
    }
    # internal dialect activation names -> HF spellings
    _HF_ACT_SPELLING = {"gpt_oss_glu": "silu"}

    def to_hf_config(self) -> Dict[str, Any]:
        hf = {"model_type": self.model_type, "head_dim": self.head_dim,
              "attention_bias": self.attention_bias}
        if self.rope_scaling:
            hf["rope_scaling"] = self.rope_scaling
        for name in self._HF_FIELDS:
            hf[name] = getattr(self, name)
        hf["hidden_act"] = self._HF_ACT_SPELLING.get(self.hidden_act, self.hidden_act)
        if self.is_moe:
            hf[self._HF_EXPERT_KEY.get(self.model_type, "num_experts")] = self.num_experts
        if self.model_type in ("gemma3", "gemma3_text"):
            hf["hidden_activation"] = hf.pop("hidden_act")
            hf["query_pre_attn_scalar"] = self.query_pre_attn_scalar
            if self.final_logit_softcap:
                hf["final_logit_softcapping"] = self.final_logit_softcap
        if self.model_type in ("deepseek_v2", "deepseek_v3"):
            hf["aux_loss_alpha"] = hf.pop("router_aux_loss_coef")
        if self.use_dsa:
            hf.update(
                index_n_heads=self.index_n_heads,
                index_head_dim=self.index_head_dim,
                index_topk=self.index_topk,
                indexer_types=list(self.indexer_types),
                rope_interleave=self.rope_interleave,
            )
        if self.model_type == "qwen3_next":
            hf.update(
                linear_num_value_heads=self.linear_num_value_heads,
                linear_num_key_heads=self.linear_num_key_heads,
                linear_key_head_dim=self.linear_key_head_dim,
                linear_value_head_dim=self.linear_value_head_dim,
                linear_conv_kernel_dim=self.linear_conv_kernel_dim,
                full_attention_interval=self.full_attention_interval,
                shared_expert_intermediate_size=self.shared_expert_intermediate_size,
                partial_rotary_factor=self.partial_rotary_factor,
            )
        return hf
