"""Composite VLM: ViT encoder + text transformer with image-token merging.

Reference: ``veomni/models/transformers/qwen2_vl`` / ``qwen2_5vl`` /
``qwen3_vl`` generated modeling (vision tower -> feature merge at
image-placeholder token positions -> LLM) and the SeedOmni composition
pattern (``models/seed_omni/modeling_seed_omni.py:63-423``: N encoders +
foundation LM).

TPU design: the batch carries a *static* image-slot layout —
``images [A?, B, max_images, H, W, C]`` + ``image_mask [B, max_images]`` —
and every image slot runs through the ViT each step (padding slots produce
garbage features that are never scattered). Feature injection is a
vectorized scatter over positions where ``input_ids == image_token_id``,
taken in order; this replaces the reference's dynamic-length
``dummy_forward`` machinery with shape-uniform compute.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from veomni_tpu import ops
from veomni_tpu.models import transformer
from veomni_tpu.models.config import TransformerConfig
from veomni_tpu.models.vision import ViTConfig, init_vit_params, vit_forward


@dataclass
class VLMConfig:
    text: TransformerConfig = field(default_factory=TransformerConfig)
    vision: ViTConfig = field(default_factory=ViTConfig)
    image_token_id: int = 151655  # qwen-vl convention
    freeze_vision: bool = False
    max_images: int = 4  # image slots per sample (static shape contract)
    model_type: str = "slot_vlm"

    def __post_init__(self):
        if isinstance(self.text, dict):
            self.text = TransformerConfig(**self.text)
        if isinstance(self.vision, dict):
            self.vision = ViTConfig(**self.vision)
        self.vision.out_hidden_size = self.text.hidden_size

    # surface used by FlopsCounter / trainers
    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "text"), name)


def init_vlm_params(rng: jax.Array, cfg: VLMConfig) -> Dict[str, Any]:
    r1, r2 = jax.random.split(rng)
    return {
        "language_model": transformer.init_params(r1, cfg.text),
        "vision_tower": init_vit_params(r2, cfg.vision, dtype=cfg.text.param_dtype),
    }


def abstract_vlm_params(cfg: VLMConfig):
    return jax.eval_shape(lambda: init_vlm_params(jax.random.PRNGKey(0), cfg))


def merge_image_features(embeds, input_ids, feats, image_mask, image_token_id):
    """Scatter image features into embedding positions.

    embeds [B,S,H]; feats [B, max_images, T_img, H]; image_mask [B, max_images].
    The n-th placeholder *block* of ``T_img`` consecutive image tokens in a
    row receives the n-th valid image's features.
    """
    b, s, h = embeds.shape
    t_img = feats.shape[2]
    max_images = feats.shape[1]
    is_img = (input_ids == image_token_id)  # [B,S]
    # ordinal of each image token within its row (0-based)
    ordinal = jnp.cumsum(is_img.astype(jnp.int32), axis=1) - 1
    img_idx_raw = ordinal // t_img
    img_idx = jnp.clip(img_idx_raw, 0, max_images - 1)
    tok_idx = jnp.clip(ordinal % t_img, 0, t_img - 1)
    gathered = jnp.take_along_axis(
        feats.reshape(b, -1, h),
        (img_idx * t_img + tok_idx)[..., None], axis=1,
    )  # [B,S,H]
    # placeholder blocks beyond the slot count keep their text embedding
    # (never silently reuse another image's features)
    valid = (
        is_img
        & (img_idx_raw < max_images)
        & jnp.take_along_axis(image_mask, img_idx, axis=1)
    )
    return jnp.where(valid[..., None], gathered.astype(embeds.dtype), embeds)


def vlm_loss_fn(
    params: Dict[str, Any],
    cfg: VLMConfig,
    batch: Dict[str, jax.Array],
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """batch: text keys as usual + pixel_patches [B, max_images, P, D_patch],
    image_mask [B, max_images]."""
    tcfg = cfg.text
    vision_params = params["vision_tower"]
    if cfg.freeze_vision:
        vision_params = jax.lax.stop_gradient(vision_params)
    lm = jax.tree.map(lambda p: p.astype(tcfg.dtype), params["language_model"])

    input_ids = batch["input_ids"]
    embeds = lm["embed_tokens"][input_ids]
    if tcfg.embed_scale:  # forward_hidden skips this for inputs_embeds
        embeds = embeds * jnp.asarray(tcfg.embed_scale, tcfg.dtype)

    patches = batch["pixel_patches"]
    bi, mi = patches.shape[:2]
    feats = vit_forward(vision_params, cfg.vision, patches.reshape(bi * mi, *patches.shape[2:]))
    feats = feats.reshape(bi, mi, *feats.shape[1:])
    embeds = merge_image_features(
        embeds, input_ids, feats, batch["image_mask"], cfg.image_token_id
    )

    hidden, moe_aux, moe_dropped = transformer.forward_hidden(
        params["language_model"], tcfg, input_ids, batch["position_ids"],
        batch.get("segment_ids"), inputs_embeds=embeds,
    )
    return transformer.head_loss(
        params["language_model"], tcfg, hidden, batch["labels"], moe_aux, moe_dropped
    )
